"""WAL crash elastic recovery — the batch thread dies and the system
recovers without losing a single acknowledged entry.

Scenario shapes follow the reference's coordination_SUITE
``segment_writer_or_wal_crash_follower/_leader`` and the ra_log_wal_SUITE
restart cases: kill the WAL under load, supervisor restarts it, writers
resend above last_written (ra_log.erl:778-793), servers ride it out in
await_condition(wal_down) instead of dying (ra_server.erl:538-554)."""
import os
import time

import pytest

import ra_tpu
from ra_tpu import LocalRouter, RaNode, RaSystem
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import Entry, RaftState, ServerConfig, ServerId, \
    UserCommand, WalUpEvent, WrittenEvent
from ra_tpu.log.wal import WalDown

from nemesis import await_leader

# Wal.kill() makes the batch thread die by an uncaught exception on
# purpose — that IS the scenario under test
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def mk_cfg(sid, sids, machine=None):
    return ServerConfig(server_id=sid, uid=f"uid_{sid.name}",
                        cluster_name="walcrash",
                        initial_members=tuple(sids),
                        machine=machine or counter(),
                        election_timeout_ms=80, tick_interval_ms=50)


def mk_log(system, uid="u1"):
    cfg = ServerConfig(server_id=None, uid=uid, cluster_name="c",
                       initial_members=(), machine=None)
    return system.log_factory(cfg)


def drain(log, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for e in log.take_events():
            if isinstance(e, WrittenEvent):
                log.handle_written(e)
        if log.last_written().index >= log.last_index_term().index:
            return
        time.sleep(0.005)
    raise TimeoutError("log never confirmed")


# ---------------------------------------------------------------------------
# low level: kill + restart + resend
# ---------------------------------------------------------------------------

def test_wal_kill_restart_resends_unconfirmed(tmp_path):
    """Entries appended while the WAL is dead stay in the memtable and are
    resent by wal_restarted(); nothing acknowledged is lost."""
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    log = mk_log(sys_)
    for i in range(1, 51):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    assert log.last_written().index == 50

    sys_.wal.kill()
    assert not sys_.wal.alive
    # appends land in the memtable but cannot reach the WAL
    for i in range(51, 61):
        with pytest.raises(WalDown):
            log.append(Entry(i, 1, UserCommand(i)))
    assert log.last_index_term().index == 60
    assert log.last_written().index == 50

    gen = sys_.wal.generation
    sys_.wal.restart()
    assert sys_.wal.alive
    assert sys_.wal.generation == gen + 1
    log.wal_restarted()
    # the resend makes 51..60 durable and a WalUpEvent surfaces
    events_seen = []
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and \
            log.last_written().index < 60:
        for e in log.take_events():
            events_seen.append(e)
            if isinstance(e, WrittenEvent):
                log.handle_written(e)
        time.sleep(0.005)
    assert log.last_written().index == 60
    assert any(isinstance(e, WalUpEvent) for e in events_seen)
    sys_.close()

    # full restart from disk: every entry present
    sys2 = RaSystem(str(tmp_path), wal_supervise=False)
    log2 = mk_log(sys2)
    assert log2.last_index_term().index == 60
    for i in (1, 50, 51, 60):
        assert log2.fetch(i).command.data == i
    sys2.close()


def test_wal_supervisor_restarts_dead_wal(tmp_path):
    """The system's supervisor notices a dead batch thread, restarts it,
    and runs the resend hook — no manual intervention."""
    sys_ = RaSystem(str(tmp_path))
    log = mk_log(sys_)
    for i in range(1, 21):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.kill()
    # while the supervisor races us, appends may raise WalDown; the
    # memtable keeps them either way
    for i in range(21, 31):
        try:
            log.append(Entry(i, 1, UserCommand(i)))
        except WalDown:
            pass
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if sys_.wal.alive and sys_.wal.generation >= 1:
            break
        time.sleep(0.01)
    assert sys_.wal.alive
    drain(log)
    assert log.last_written().index == 30
    sys_.close()


# ---------------------------------------------------------------------------
# cluster level: leader / follower WAL crash under load
# ---------------------------------------------------------------------------

def _start_cluster(tmp_path, sids, router):
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    return systems, nodes


def _commit_with_retry(leader, value, router, deadline=10.0):
    end = time.monotonic() + deadline
    while time.monotonic() < end:
        try:
            res = ra_tpu.process_command(leader, value, router=router,
                                         timeout=1.0)
            return res
        except TimeoutError:
            continue
    raise TimeoutError(f"command {value} never committed")


@pytest.mark.parametrize("victim", ["leader", "follower"])
def test_wal_crash_under_load_no_committed_loss(tmp_path, victim):
    router = LocalRouter()
    sids = [ServerId(f"w{i}", f"wn{i}") for i in (1, 2, 3)]
    systems, nodes = _start_cluster(tmp_path, sids, router)
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)

    acked = 0
    for v in range(1, 21):
        _commit_with_retry(leader, v, router)
        acked += v

    target = leader if victim == "leader" else \
        next(s for s in sids if s != leader)
    systems[target.node].wal.kill()

    # keep the load on: every command that returns was acknowledged by
    # quorum and must survive everything below
    for v in range(21, 41):
        leader = await_leader(router, sids)
        _commit_with_retry(leader, v, router)
        acked += v
    assert acked == sum(range(1, 41))

    # the victim's server must still be alive (parked or recovered), not
    # torn down: WalDown is an infra fault, not a server crash
    victim_node = nodes[target.node]
    assert target.name in victim_node.shells

    # and the victim's WAL must have been supervised back up
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and \
            not systems[target.node].wal.alive:
        time.sleep(0.01)
    assert systems[target.node].wal.alive

    leader = await_leader(router, sids)
    res = ra_tpu.consistent_query(leader, lambda s: s, router=router)
    assert res.reply == acked

    # cold restart of every node from disk: acknowledged state intact
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()
    router2 = LocalRouter()
    systems2 = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes2 = {s.node: RaNode(s.node, router=router2,
                             log_factory=systems2[s.node].log_factory)
              for s in sids}
    for s in sids:
        systems2[s.node].recover_servers(
            nodes2[s.node], lambda cluster, name: counter())
    leader2 = await_leader(router2, sids)
    res = ra_tpu.consistent_query(leader2, lambda s: s, router=router2)
    assert res.reply == acked
    for n in nodes2.values():
        n.stop()
    for s in systems2.values():
        s.close()


def test_parked_leader_resumes_leadership_after_wal_restart(tmp_path):
    """A leader whose WAL dies parks in await_condition and resumes as
    LEADER (not via re-election) once the supervisor brings the WAL back."""
    router = LocalRouter()
    sid = ServerId("solo", "sw1")
    system = RaSystem(str(tmp_path / "sw1"))
    node = RaNode("sw1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(sid, [sid]))
    ra_tpu.trigger_election(sid, router)
    await_leader(router, [sid])
    ra_tpu.process_command(sid, 5, router=router)

    system.wal.kill()
    # drive a write so the shell hits WalDown and parks
    deadline = time.monotonic() + 5.0
    parked_or_recovered = False
    while time.monotonic() < deadline:
        try:
            ra_tpu.process_command(sid, 7, router=router, timeout=0.5)
            parked_or_recovered = True
            break
        except TimeoutError:
            srv = node.shells[sid.name].server
            if srv.raft_state == RaftState.AWAIT_CONDITION:
                parked_or_recovered = True  # observed the parked state
                break
    assert parked_or_recovered
    # supervisor restarts; the parked command (postponed, not bounced)
    # or a fresh one commits and the server is LEADER again
    _commit_with_retry(sid, 9, router)
    assert node.shells[sid.name].server.raft_state == RaftState.LEADER
    res = ra_tpu.consistent_query(sid, lambda s: s, router=router)
    assert res.reply >= 5 + 9
    node.stop()
    system.close()


def test_wal_rollover_after_poison_under_load_no_committed_loss(tmp_path):
    """The ISSUE 4 cluster-level pin: fsync-EIO + torn writes injected
    on ONE node's WAL under live traffic — the poison/rollover/resend
    ladder (not thread death) absorbs them, every acknowledged command
    survives a full cold restart, and the fsyncgate discipline holds."""
    from ra_tpu.log import faults
    from ra_tpu.log.faults import DiskFaultPlan, DiskFaultSpec

    faults.reset_disk_fault_counters()
    router = LocalRouter()
    sids = [ServerId(f"p{i}", f"pn{i}") for i in (1, 2, 3)]
    systems, nodes = _start_cluster(tmp_path, sids, router)
    try:
        ra_tpu.trigger_election(sids[0], router)
        leader = await_leader(router, sids)
        acked = 0
        for v in range(1, 11):
            _commit_with_retry(leader, v, router)
            acked += v
        # target ONE node's wal dir (path_match) — the blast radius of
        # a single sick disk, while the other nodes stay clean
        victim = sids[0].node
        faults.install_plan(DiskFaultPlan(seed=19, rules=[
            ("wal", DiskFaultSpec(fsync_eio=0.4, short_write=0.2,
                                  limit=6,
                                  path_match=os.path.sep + victim +
                                  os.path.sep))]))
        for v in range(11, 31):
            leader = await_leader(router, sids)
            _commit_with_retry(leader, v, router)
            acked += v
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["faults_injected"] >= 1, ctr
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        leader = await_leader(router, sids)
        res = ra_tpu.consistent_query(leader, lambda s: s, router=router)
        assert res.reply == acked
    finally:
        faults.clear_plan()
        for n in nodes.values():
            n.stop()
        for s in systems.values():
            s.close()
    # cold restart of every node from disk: acknowledged state intact
    router2 = LocalRouter()
    systems2 = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes2 = {s.node: RaNode(s.node, router=router2,
                             log_factory=systems2[s.node].log_factory)
              for s in sids}
    try:
        for s in sids:
            systems2[s.node].recover_servers(
                nodes2[s.node], lambda cluster, name: counter())
        leader2 = await_leader(router2, sids)
        res = ra_tpu.consistent_query(leader2, lambda s: s,
                                      router=router2)
        assert res.reply == acked
    finally:
        for n in nodes2.values():
            n.stop()
        for s in systems2.values():
            s.close()


# -- write strategies (ra_log_wal.erl:66-96) --------------------------------

def _strategy_roundtrip(tmp_path, strategy):
    from ra_tpu.log.wal import Wal, scan_wal_file

    confirms = []
    wal = Wal(str(tmp_path), sync_mode=1, write_strategy=strategy)
    wal.register("u1", lambda uid, lo, hi, term: confirms.append((lo, hi)))
    for i in range(1, 21):
        wal.write("u1", i, 1, f"payload-{i}".encode())
    wal.flush()
    assert confirms and confirms[-1][1] == 20, confirms
    wal.close()
    tables = {}
    import os as _os
    wdir = str(tmp_path / "wal")
    for f in sorted(_os.listdir(wdir)):
        if f.endswith(".wal"):
            scan_wal_file(_os.path.join(wdir, f), tables)
    got = tables.get("u1", {})
    assert sorted(got) == list(range(1, 21)), sorted(got)
    assert got[20][1] == b"payload-20"


def test_wal_strategy_default(tmp_path):
    _strategy_roundtrip(tmp_path, "default")


def test_wal_strategy_o_sync(tmp_path):
    _strategy_roundtrip(tmp_path, "o_sync")


def test_wal_strategy_sync_after_notify(tmp_path):
    _strategy_roundtrip(tmp_path, "sync_after_notify")


def test_wal_strategy_unknown_rejected(tmp_path):
    from ra_tpu.log.wal import Wal
    import pytest as _pytest
    with _pytest.raises(ValueError):
        Wal(str(tmp_path), write_strategy="bogus")


def test_engine_durable_o_sync_strategy(tmp_path):
    """The engine durability bridge runs over every strategy."""
    import numpy as np
    from ra_tpu.engine import open_engine
    from ra_tpu.models import CounterMachine

    eng = open_engine(CounterMachine(), str(tmp_path), 4, 3,
                      sync_mode=1, write_strategy="o_sync",
                      ring_capacity=64, max_step_cmds=4)
    n_new = np.full((4,), 2, np.int32)
    pay = np.ones((4, 4, 1), np.int32)
    for _ in range(8):
        eng.step(n_new, pay)
    for _ in range(8):
        eng.step(np.zeros((4,), np.int32), np.zeros_like(pay))
        eng._dur.drain_all()
        eng._dur.wal.flush()
    assert eng.committed_total() > 0
    eng.close()
