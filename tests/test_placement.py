"""Placement failover pins (ISSUE 17): the replicated PlacementTable
machine (never half-moved, redelivery-idempotent), the engine
supervisor's hysteresis detector (delay is not death), the bounded
commit loop (RA16's runtime twin), the wire listener's re-home claims
(old dedup slots or nothing), and the end-to-end failover soak with
its exactly-once oracle + trace timeline.

ISSUE 19 pins ride at the end: stale-generation probe replies are
discarded, the latency-domain matrix resolves/injects from the local
vantage (and the autotune freeze guard honors that), and the serving
path's placement staleness gate refuses with a typed REHOME hint the
client follows at most once per connection epoch."""
import threading
import time

import numpy as np
import pytest

from harness import SimCluster
from ra_tpu.core.machine import ApplyMeta
from ra_tpu.core.types import ErrorResult
from ra_tpu.placement import (EngineSupervisor, PlacementCache,
                              PlacementError, PlacementTableMachine,
                              owned_ranges, run_failover_soak)
from ra_tpu.transport.rpc import FaultPlan, FaultSpec

# the soak's kill-9 dies loudly in the victim's WAL threads by design
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")

META = ApplyMeta(index=1, term=1)


def _mk(*commands):
    """Apply a command sequence to a fresh table; -> (machine, state)."""
    m = PlacementTableMachine()
    state = m.init({})
    for cmd in commands:
        state, _reply = m.apply(META, cmd, state)
    return m, state


# -- the table machine ----------------------------------------------------

def test_table_register_assign_migrate():
    m, st = _mk(("register_engine", "engA"),
                ("register_engine", "engB"),
                ("assign", "r0", "engA", 0, 8))
    assert st["ranges"]["r0"] == {"engine": "engA", "generation": 1,
                                  "lo": 0, "hi": 8}
    st, reply = m.apply(META, ("migrate", "r0", "engA", "engB", 2), st)
    assert reply == ("placed", "r0", "engB", 2)
    assert owned_ranges(st, "engA") == []
    assert [rid for rid, _ in owned_ranges(st, "engB")] == ["r0"]


def test_migrate_redelivery_is_idempotent():
    """A re-delivered migrate (cumulative-ack redelivery, a retrying
    supervisor) observes the move it already made — same reply, zero
    state churn."""
    m, st = _mk(("register_engine", "engA"),
                ("register_engine", "engB"),
                ("assign", "r0", "engA", 0, 8),
                ("migrate", "r0", "engA", "engB", 2))
    rev = st["rev"]
    st2, reply = m.apply(META, ("migrate", "r0", "engA", "engB", 2), st)
    assert reply == ("placed", "r0", "engB", 2)
    assert st2["rev"] == rev          # no-op: rev did not move
    assert st2["ranges"]["r0"]["engine"] == "engB"


def test_migrate_stale_generation_absorbed():
    """A migrate against a generation that already moved on is answered
    with the standing placement, never applied."""
    m, st = _mk(("register_engine", "engA"),
                ("register_engine", "engB"),
                ("assign", "r0", "engA", 0, 8),
                ("migrate", "r0", "engA", "engB", 2))
    # stale supervisor still thinks engA owns r0 at gen <= 2
    st2, reply = m.apply(META, ("migrate", "r0", "engA", "engC", 2), st)
    assert reply == ("placed", "r0", "engB", 2)
    assert st2["ranges"]["r0"]["engine"] == "engB"


def test_engine_down_generation_gated():
    m, st = _mk(("register_engine", "engA"))
    st2, reply = m.apply(META, ("engine_down", "engA", 99), st)
    assert reply == ("engine", "engA", "up", 1)   # wrong gen: no-op
    assert st2["engines"]["engA"]["status"] == "up"
    st3, reply = m.apply(META, ("engine_down", "engA", 1), st2)
    assert reply == ("engine", "engA", "down", 1)
    assert st3["engines"]["engA"]["status"] == "down"


def test_assign_refuses_cross_engine_reassign():
    """Re-homing an existing range must go through migrate (generation
    gated); a bare re-assign is refused with the standing placement."""
    m, st = _mk(("register_engine", "engA"),
                ("register_engine", "engB"),
                ("assign", "r0", "engA", 0, 8))
    st2, reply = m.apply(META, ("assign", "r0", "engB", 0, 8), st)
    assert reply == ("refused", "r0", "engA", 1)
    assert st2["ranges"]["r0"]["engine"] == "engA"
    # identical re-assign is the idempotent no-op
    _st3, reply = m.apply(META, ("assign", "r0", "engA", 0, 8), st2)
    assert reply == ("placed", "r0", "engA", 1)


def test_placement_cache_is_revision_monotone():
    _m, st = _mk(("register_engine", "engA"),
                 ("assign", "r0", "engA", 0, 8))
    cache = PlacementCache()
    assert cache.refresh(st) is True
    assert cache.lookup("r0") == ("engA", 1)
    assert cache.lane_owner(3) == "engA"
    assert cache.lane_owner(9) is None
    stale = {"rev": st["rev"] - 1, "ranges": {}}
    assert cache.refresh(stale) is False       # lagging follower read
    assert cache.lookup("r0") == ("engA", 1)   # ...never rolls back
    assert cache.stale_against({"rev": st["rev"] + 1}) is True
    cache.invalidate()
    assert cache.lookup("r0") is None


# -- never half-moved under leader kill-9 ---------------------------------

def test_leader_kill9_mid_migration_never_half_moved():
    """Kill-9 the classic leader mid-migration: the table lands pre- or
    post-move (one committed command — there is no half-moved state),
    and re-delivering the migration is idempotent."""
    c = SimCluster(3, machine_factory=lambda: PlacementTableMachine())
    n1, n2, n3 = c.ids
    c.elect(n1)
    for cmd in (("register_engine", "engA"),
                ("register_engine", "engB"),
                ("assign", "r0", "engA", 0, 8)):
        c.command(n1, cmd)
    # the leader accepts the migrate but dies (isolated) before any
    # AppendEntries lands — the uncommitted entry must vanish
    c.isolate(n1)
    c.command(n1, ("migrate", "r0", "engA", "engB", 2))
    assert c.servers[n1].machine_state["ranges"]["r0"]["engine"] == \
        "engA"                       # appended, NOT applied
    c.elect(n2)
    c.heal()
    c.run()
    for sid in c.ids:                # pre-move everywhere: no half state
        ent = c.servers[sid].machine_state["ranges"]["r0"]
        assert (ent["engine"], ent["generation"]) == ("engA", 1)
    # the supervisor re-delivers the same migration to the new leader
    c.command(n2, ("migrate", "r0", "engA", "engB", 2))
    c.run()
    for sid in c.ids:                # post-move everywhere
        ent = c.servers[sid].machine_state["ranges"]["r0"]
        assert (ent["engine"], ent["generation"]) == ("engB", 2)
    rev = c.servers[n2].machine_state["rev"]
    c.command(n2, ("migrate", "r0", "engA", "engB", 2))  # and again
    c.run()
    assert c.servers[n2].machine_state["rev"] == rev     # absorbed
    for sid in c.ids:
        ent = c.servers[sid].machine_state["ranges"]["r0"]
        assert (ent["engine"], ent["generation"]) == ("engB", 2)


# -- the detector: delay is not death -------------------------------------

def _sup(probe, *, fault_plan=None, suspect_after=0.15, down_after=1.0,
         hysteresis=0.5):
    """A supervisor on a fake clock; -> (sup, clock cell)."""
    t = [0.0]
    sup = EngineSupervisor(
        None, None, probes={"eng": probe}, suspect_after=suspect_after,
        down_after=down_after, hysteresis=hysteresis,
        fault_plan=fault_plan, clock=lambda: t[0])
    return sup, t


def test_pure_delay_never_migrates():
    """The ISSUE 17 pin: a FaultPlan that only DELAYS probe replies
    (every delay under down_after) makes the engine look slow — late
    arrivals show up as last-heard age, suspects fire, recoveries
    follow — but never yields a down verdict."""
    plan = FaultPlan(7, by_class={"ping": FaultSpec(
        delay=1.0, delay_ms=(200.0, 900.0))})
    sup, t = _sup(lambda: True, fault_plan=plan)
    downs = []
    for _ in range(400):
        t[0] += 0.05
        downs.extend(sup.tick())
    plan.unregister()
    assert downs == []
    assert sup.counters["downs"] == 0
    assert sup.verdict("eng") != "down"
    assert sup.counters["suspects"] > 0       # the delay WAS visible
    assert sup.counters["recoveries"] > 0     # ...and rode out


def test_hysteresis_absorbs_spike_then_kill_downs():
    """A silence spike shorter than the hysteresis window recovers; a
    kill-9 (permanent silence) escalates to down exactly once."""
    alive = [True]
    sup, t = _sup(lambda: alive[0], suspect_after=0.1, down_after=0.3,
                  hysteresis=0.5)
    alive[0] = False                 # spike: 0.55s of silence
    while t[0] < 0.55:
        t[0] += 0.05
        assert sup.tick() == []      # > down_after but < hysteresis
    alive[0] = True
    t[0] += 0.05
    sup.tick()
    assert sup.verdict("eng") == "up"
    assert sup.counters["recoveries"] == 1
    assert sup.counters["downs"] == 0
    alive[0] = False                 # the real kill-9
    downs = []
    while t[0] < 2.0:
        t[0] += 0.05
        downs.extend(sup.tick())
    assert downs == ["eng"]
    assert sup.verdict("eng") == "down"
    assert sup.counters["downs"] == 1


def test_drop_plan_downs():
    """Dropped probes ARE silence: a drop-everything plan escalates to
    down even though the engine's probe callable still answers."""
    plan = FaultPlan(3, by_class={"ping": FaultSpec(drop=1.0)})
    sup, t = _sup(lambda: True, fault_plan=plan)
    downs = []
    for _ in range(100):
        t[0] += 0.05
        downs.extend(sup.tick())
    plan.unregister()
    assert downs == ["eng"]
    assert sup.counters["downs"] == 1


# -- the bounded commit loop (RA16's runtime twin) ------------------------

def test_commit_loop_gives_up_on_deadline():
    t = [0.0]
    sup = EngineSupervisor(None, None, commit_timeout=0.1,
                           clock=lambda: t[0])

    def attempt():
        t[0] += 0.05
        raise RuntimeError("leader gone")

    with pytest.raises(PlacementError):
        sup._commit(attempt, what="migrate/r0")
    assert sup.counters["giveups"] == 1
    assert sup.counters["migrate_retries"] > 0


def test_commit_loop_retries_returned_error_results():
    """The classic API reports churn by RETURNING ErrorResult, not by
    raising — the commit loop must treat that as a retryable failure."""
    t = [0.0]
    sup = EngineSupervisor(None, None, commit_timeout=0.1,
                           clock=lambda: t[0])
    results = [ErrorResult("not_leader"), ErrorResult("timeout"), "ok"]

    def attempt():
        t[0] += 0.01
        return results.pop(0)

    assert sup._commit(attempt, what="migrate/r0") == "ok"
    assert sup.counters["migrate_retries"] == 2
    assert sup.counters["giveups"] == 0


# -- the re-home claim path -----------------------------------------------

def _stack(lanes, slots=64, port=None):
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.ingress import IngressPlane
    from ra_tpu.wire import DedupCounterMachine, WireListener
    eng = LockstepEngine(DedupCounterMachine(slots=slots), lanes, 3,
                         ring_capacity=128, max_step_cmds=8,
                         donate=False)
    plane = IngressPlane(eng, superstep_k=2, window_s=0.0)
    lst = WireListener(plane, port=port)
    return eng, lst


def test_rehome_claims_are_validated():
    """loopback_rehome claims the OLD dedup slots or nothing: known
    keys, short claims, duplicate (lane, slot) pairs and collisions
    with live sessions are all refused before any state changes."""
    from ra_tpu.wire import LoopbackFleet
    eng, lst = _stack(lanes=1)       # one lane: claims are deterministic
    try:
        fleet = LoopbackFleet(lst, 2, key="k")
        zeros = np.zeros(2, np.int64)
        with pytest.raises(RuntimeError, match="known key"):
            lst.loopback_rehome(2, key="k", slots=fleet.slots,
                                committed=zeros)
        with pytest.raises(ValueError, match="one claimed slot"):
            lst.loopback_rehome(2, key="short",
                                slots=np.array([7], np.int32),
                                committed=zeros)
        with pytest.raises(ValueError, match="duplicate"):
            lst.loopback_rehome(2, key="dup",
                                slots=np.array([5, 5], np.int32),
                                committed=zeros)
        with pytest.raises(ValueError, match="already bound"):
            lst.loopback_rehome(2, key="clash", slots=fleet.slots,
                                committed=zeros)
        # a clean claim above every live slot succeeds and bumps epochs
        before = lst.plane.directory.epoch[fleet.handles].copy()
        conns = lst.loopback_rehome(2, key="fresh",
                                    slots=np.array([10, 11], np.int32),
                                    committed=zeros)
        assert len(conns) == 2
        assert (lst.plane.directory.epoch[fleet.handles] ==
                before).all()        # other sessions untouched
    finally:
        lst.close()
        eng.close()


def test_rehome_refuses_diverged_lane_placement():
    """fleet.rehome adopts a new home only when the deterministic
    directory hash lands every session on the SAME lane there — a
    different lane geometry must refuse, not silently mis-place."""
    from ra_tpu.wire import LoopbackFleet
    eng_a, lst_a = _stack(lanes=2)
    eng_b, lst_b = _stack(lanes=4)
    try:
        # one session whose key is pinned to hash onto DIFFERENT lanes
        # at 2 vs 4 lanes (the splitmix64 placement is seed-stable, so
        # this divergence is deterministic)
        fleet = LoopbackFleet(lst_a, 1, key="div")
        with pytest.raises(RuntimeError, match="diverged"):
            fleet.rehome(lst_b)
    finally:
        lst_a.close()
        eng_a.close()
        lst_b.close()
        eng_b.close()


# -- end to end: the failover soak + the trace timeline -------------------

#: CPU-scaled bar on kill -> first-commit-on-new-home (the TPU bench
#: stamps the real number; this pins "bounded", not "fast")
RECOVERY_BAR_S = 60.0


@pytest.fixture(scope="module")
def failover_run(tmp_path_factory):
    from ra_tpu.blackbox import RECORDER
    row = run_failover_soak(
        0, conns=4, sessions_per_conn=2, lanes=8, waves=4,
        wave_ops=200, kill_wave=2,
        data_dir=str(tmp_path_factory.mktemp("failover")),
        recovery_bar=RECOVERY_BAR_S)
    return row, RECORDER.events()


def test_failover_soak_exactly_once(failover_run):
    row, _events = failover_run
    assert row["failover_lost_acked"] == 0
    assert row["failover_double_applied"] == 0
    assert 0 < row["failover_recovery_s"] <= RECOVERY_BAR_S
    assert row["migrations"] >= 1
    assert row["detector"]["downs"] == 1
    assert row["rehomed_sessions"] == 8


def test_failover_trace_timeline(failover_run):
    """One failover trace joins the cross-tier hops in causal order:
    client refusal at the old home -> table commit on the classic
    cluster -> adoption + re-home on the survivor — and ra_trace
    --explain renders that timeline."""
    import tools.ra_trace as rt
    _row, events = failover_run
    refusals = [e for e in events
                if e[1] == "placement.refuse" and e[2].get("trace")]
    assert refusals, "soak recorded no traced placement.refuse"
    tid = refusals[-1][2]["trace"]
    traces = rt.index_traces([(ts, et, f, "soak")
                              for ts, et, f in events])
    tl = traces[tid]
    first_ts = {}
    for ts, etype, _f, _o in tl["hops"]:
        first_ts.setdefault(etype, ts)
    for hop in ("placement.refuse", "cmd.submit", "cmd.commit",
                "placement.migrate", "placement.adopt",
                "placement.rehome"):
        assert hop in first_ts, f"trace missing {hop} hop"
    assert first_ts["placement.refuse"] <= first_ts["cmd.submit"] \
        <= first_ts["cmd.commit"] <= first_ts["placement.adopt"] \
        <= first_ts["placement.rehome"]
    text = rt.explain(tid, tl)
    for needle in ("placement.refuse", "cmd.commit", "placement.adopt",
                   "placement.rehome"):
        assert needle in text


# -- ISSUE 19: stale probe generations ------------------------------------

def test_stale_probe_generation_discarded():
    """An async probe reply captured under a SUPERSEDED slot generation
    is dropped — a stale incumbent's straggler must not vouch for the
    slot's new incumbent — while current-generation replies count."""
    sup, t = _sup(lambda: None)      # async probe: replies land out of band
    sup.tick()
    t[0] += 0.05
    assert sup.probe_reply("eng", heard_at=t[0], generation=1)
    assert sup.counters["heartbeats"] == 1
    # the slot is re-provisioned while a probe is still in flight
    sup.watch("eng", lambda: None, generation=2)
    t[0] += 0.2                      # > suspect_after: incumbent suspect
    sup.tick()
    assert sup.verdict("eng") == "suspect"
    # the old incumbent's straggler: dropped, suspect streak intact
    assert not sup.probe_reply("eng", heard_at=t[0], generation=1)
    assert sup.counters["stale_probe_drops"] == 1
    assert sup.counters["heartbeats"] == 1
    sup.tick()
    assert sup.verdict("eng") == "suspect"   # not rescued
    # a reply from the CURRENT generation clears the suspicion
    assert sup.probe_reply("eng", heard_at=t[0], generation=2)
    sup.tick()
    assert sup.verdict("eng") == "up"
    assert sup.counters["recoveries"] == 1
    # an unwatched engine's reply is refused outright
    assert not sup.probe_reply("ghost", generation=1)


# -- ISSUE 19: latency domains --------------------------------------------

_GEO_MEMBERS = {"ctl": ["ctl"], "far": ["gf1", "gf2"],
                "eng": ["nA", "nB"]}
_GEO_MATRIX = {("ctl", "far"): {"delay_ms": (80.0, 150.0)}}


def test_domain_matrix_quiet_is_vantage_local():
    """quiet() judges the matrix from THIS plan's vantage: a standing
    control-tier delay cell leaves an engine-tier plan (same topology,
    different ``local``) quiet, and an all-zero matrix injects nothing."""
    ctl = FaultPlan(0, domains={"local": "ctl", "members": _GEO_MEMBERS,
                                "matrix": _GEO_MATRIX})
    eng = FaultPlan(0, domains={"local": "eng", "members": _GEO_MEMBERS,
                                "matrix": _GEO_MATRIX})
    zero = FaultPlan(0, domains={
        "local": "ctl", "members": _GEO_MEMBERS,
        "matrix": {("ctl", "far"): {"delay_ms": 0.0}}})
    try:
        assert not ctl.quiet()       # its frames cross the delayed cell
        assert eng.quiet()           # engines never see that geography
        assert zero.quiet()
    finally:
        for p in (ctl, eng, zero):
            p.unregister()


def test_freeze_guard_is_domain_aware():
    """The autotune freeze guard freezes a host only when a live plan
    can inject from ITS vantage — a standing control-tier matrix must
    not freeze the engine tier's tuners."""
    from ra_tpu.autotune import default_freeze_guard
    base = default_freeze_guard()
    eng = FaultPlan(0, domains={"local": "eng", "members": _GEO_MEMBERS,
                                "matrix": _GEO_MATRIX})
    try:
        assert default_freeze_guard() == base   # quiet plan: no freeze
        ctl = FaultPlan(0, domains={"local": "ctl",
                                    "members": _GEO_MEMBERS,
                                    "matrix": _GEO_MATRIX})
        try:
            assert default_freeze_guard() == \
                "transport_fault_plan_active"
        finally:
            ctl.unregister()
        assert default_freeze_guard() == base
    finally:
        eng.unregister()


def test_domain_matrix_resolution_and_precedence():
    """The matrix keys (src, dst) domain cells: send crosses
    (local, peer-domain), recv the reverse (with the reversed pair as
    the symmetric-RTT fallback), peers outside every domain ride the
    zero default, and explicitly-keyed specs rank ABOVE the matrix."""
    plan = FaultPlan(7, by_peer={"gf2": FaultSpec()},
                     domains={"local": "ctl", "members": _GEO_MEMBERS,
                              "matrix": {("ctl", "far"):
                                         {"delay_ms": (5.0, 5.0)}}})
    try:
        d = plan.decide("gf1", "append", "send")
        assert d.action == "deliver"
        assert abs(d.delay_s - 0.005) < 1e-9
        # recv crosses (far, ctl): no exact cell, so the reversed pair
        # covers the symmetric-RTT common case
        assert plan.decide("gf1", "append", "recv").delay_s > 0.0
        # a peer in no domain rides the (zero) default
        assert plan.decide("stranger", "append", "send").delay_s == 0.0
        # an explicit per-peer spec ranks above the matrix
        assert plan.decide("gf2", "append", "send").delay_s == 0.0
    finally:
        plan.unregister()


def test_domain_delay_streams_replay_deterministically():
    """Matrix delays ride the seeded per-(peer, class, direction)
    streams: two plans with one seed draw identical jitter."""
    def mk():
        return FaultPlan(11, domains={"local": "ctl",
                                      "members": _GEO_MEMBERS,
                                      "matrix": _GEO_MATRIX})
    a, b = mk(), mk()
    try:
        seq_a = [a.decide("gf1", "append", "send").delay_s
                 for _ in range(8)]
        seq_b = [b.decide("gf1", "append", "send").delay_s
                 for _ in range(8)]
        assert seq_a == seq_b
        assert len(set(seq_a)) > 1       # jitter is real, not a constant
        assert all(0.080 <= s <= 0.150 for s in seq_a)
    finally:
        a.unregister()
        b.unregister()


# -- ISSUE 19: the serving-path placement staleness gate ------------------

def test_stale_placement_rows_get_rehome_hint_not_submit():
    """Rows whose lane the bound PlacementCache homes on a FOREIGN
    engine are refused with a typed REHOME hint — never submitted,
    never shed — and an empty view or a foreign RID over the same lane
    numbers fails OPEN (no view is not a foreign view)."""
    from ra_tpu.wire import LoopbackFleet
    eng, lst = _stack(lanes=4)
    try:
        cache = PlacementCache()
        lst.bind_placement(cache, {"engA"}, rids={"r0"})
        fleet = LoopbackFleet(lst, 2, key="stale")
        sess = np.arange(2)
        # empty cache: ops flow
        fleet.new_ops(sess, np.full(2, 3, np.int32))
        fleet.send_queued()
        assert lst.sweep() == 2
        fleet.collect()
        assert (fleet.op_state[:2] == 2).all()       # PLACED
        # committed table state homes every lane on engB: refuse + hint
        cache.refresh({"rev": 5, "ranges": {
            "r0": {"engine": "engB", "generation": 3, "lo": 0,
                   "hi": 4}}})
        swept0 = lst.counters["swept_rows"]
        fleet.new_ops(sess, np.full(2, 3, np.int32))
        fleet.send_queued()
        assert lst.sweep() == 0
        fleet.collect()
        assert lst.counters["swept_rows"] == swept0  # nothing submitted
        assert (fleet.op_state[2:4] == 1).all()      # SENT: no verdict
        assert fleet.tenant_shed.sum() == 0          # ...and no shed
        assert lst.rehome_hints >= 1
        assert fleet.rehome_hints >= 1
        _slot, engine, gen, rev = fleet.rehome_hint
        assert (engine, gen, rev) == ("engB", 3, 5)
        # a FOREIGN rid over the same lane numbers says nothing about
        # this listener's sessions (per-engine lane spaces overlap)
        cache.refresh({"rev": 6, "ranges": {
            "r0": {"engine": "engA", "generation": 4, "lo": 0, "hi": 4},
            "rX": {"engine": "engB", "generation": 9, "lo": 0,
                   "hi": 4}}})
        fresh = LoopbackFleet(lst, 2, key="healed")
        fresh.new_ops(np.arange(2), np.full(2, 3, np.int32))
        fresh.send_queued()
        assert lst.sweep() == 2
        fresh.collect()
        assert (fresh.op_state[:2] == 2).all()
    finally:
        lst.close()
        eng.close()


def _pump_tcp(lsts, cli, done, timeout=30.0):
    deadline = time.monotonic() + timeout
    while not done():
        cli.flush()
        for lst in lsts:
            lst.sweep()
            lst.plane.pump(force=True)
            lst.plane.settle()
        cli.poll()
        assert time.monotonic() < deadline


def test_wire_client_follows_rehome_hint_once_per_epoch():
    """Over real TCP: a stale home refuses with a typed REHOME frame;
    with a resolver the client follows it — redials the pre-claimed new
    home, replays its unacked window against the recovered dedup slots
    — and duplicate hints within one connection epoch follow once."""
    from ra_tpu.wire import WireClient
    eng_a, lst_a = _stack(lanes=4, port=0)
    eng_b, lst_b = _stack(lanes=4, port=0)
    cli = None
    try:
        cache = PlacementCache()
        lst_a.bind_placement(cache, {"engA"}, rids={"r0"})
        cli = WireClient(lst_a.address, key="geo/c1", n_sessions=1,
                         timeout=10.0)
        cli.enqueue(5)
        cli.flush()
        _pump_tcp([lst_a], cli, lambda: cli.acked_count() >= 1)
        # the new home PRE-CLAIMS the session block (the host_rehome
        # verb): old dedup slots verbatim, watermarks at acked counts
        lst_b.claim_sessions("geo/c1", 1,
                             slots=np.asarray(cli.slots, np.int64),
                             committed=cli.watermark.copy())
        cli.rehome_resolver = {"engB": lst_b.address}.get
        # the table moves every lane to engB; the next swept row is
        # refused with the hint and the client follows it to engB
        cache.refresh({"rev": 2, "ranges": {
            "r0": {"engine": "engB", "generation": 2, "lo": 0,
                   "hi": 4}}})
        cli.enqueue(7)
        cli.flush()
        _pump_tcp([lst_a, lst_b], cli,
                  lambda: cli.acked_count() >= 2)
        assert cli.rehome_follows == 1
        assert cli.rehome_hint == ("engB", 2, 2)
        assert cli.address == tuple(lst_b.address)
        assert lst_a.rehome_hints >= 1
        # exactly-once across the move: the acked op stayed on A, only
        # the refused op landed on B
        lanes = np.arange(4)
        sum_a = int(np.asarray(
            eng_a.consistent_read(lanes)["value"]).sum())
        sum_b = int(np.asarray(
            eng_b.consistent_read(lanes)["value"]).sum())
        assert (sum_a, sum_b) == (5, 7)
        # duplicate hints buffered within ONE epoch follow exactly
        # once: the gate is recorded before the redial
        follows = []
        real = cli.rehome_to
        cli.rehome_to = lambda addr, durable=None: \
            follows.append(tuple(addr))
        hint = {"engine": "engB", "generation": 2, "rev": 2}
        cli._maybe_follow_rehome(hint)
        cli._maybe_follow_rehome(hint)
        cli.rehome_to = real
        assert follows == [tuple(lst_b.address)]
        assert cli.rehome_follows == 2
        # without a resolver a hint is surfaced, never acted on
        cli.rehome_resolver = None
        cli._maybe_follow_rehome({"engine": "engC", "generation": 9,
                                  "rev": 9})
        assert cli.rehome_follows == 2
    finally:
        if cli is not None:
            cli.close()
        lst_a.close()
        lst_b.close()
        eng_a.close()
        eng_b.close()


# -- ISSUE 19: the host agent's serving-loop bridge -----------------------

class _FakeNode:
    def __init__(self):
        self.control_ops = {}


class _FakeHost:
    engine_id = "engX"
    lanes = 4
    listener = None

    @staticmethod
    def alive():
        return True


def test_host_agent_bridges_mutating_verbs_onto_serving_loop():
    """host_status answers immediately (the probe path must never wait
    on the serving loop); mutating verbs block until pump() executes
    them ON the loop; placement pushes stay revision-monotone."""
    from ra_tpu.placement.fabric import HostAgent
    node = _FakeNode()
    agent = HostAgent(_FakeHost(), node)
    assert node.control_ops["host_status"]({}) == \
        {"eid": "engX", "alive": True, "generation": 1}
    assert agent.pump() == 0

    def push(rev):
        out = {}
        th = threading.Thread(
            target=lambda: out.update(node.control_ops
                                      ["host_placement"]
                                      ({"state": {"rev": rev,
                                                  "ranges": {}}})))
        th.start()
        deadline = time.monotonic() + 5.0
        while agent.pump() == 0:         # the serving loop's half
            assert time.monotonic() < deadline
            time.sleep(0.001)
        th.join(5.0)
        assert not th.is_alive()
        return out

    assert push(3) == {"rev": 3, "changed": True}
    assert agent.cache.rev == 3
    assert push(1) == {"rev": 3, "changed": False}   # stale: no-op
    # host_stop flips the stop flag without touching the loop
    assert node.control_ops["host_stop"]({}) == "stopping"
    assert agent.stopped.is_set()
