"""History-based linearizability checking — the in-repo stand-in for
the external Jepsen verification the reference relies on
(/root/reference/README.md:33-35: continuous Jepsen runs against the
ra-kv-store).

Concurrent clients drive writes (process_command) and linearizable
reads (consistent_query) against a live 3-node cluster while a nemesis
partitions and heals links; every operation is recorded as an
(invoke, complete) interval and the full history is checked against a
sequential register model with the classic Wing & Gong search
(memoized on (linearized-set, state)).  Timed-out operations are
indeterminate: the checker may place them at any point after their
invocation or drop them entirely.
"""
import threading
import time

import ra_tpu
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerId
from ra_tpu.node import LocalRouter, RaNode


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

def check_register_linearizable(history):
    """history: list of dicts with keys
        op:      "write" | "read"
        value:   written value (write) or observed value (read)
        invoke:  monotonic invocation time
        complete: completion time, or None for indeterminate (timeout)
    Returns True iff some linearization exists.  None-completed ops are
    optional: they may take effect at any point after invoke or never.
    """
    ops = list(enumerate(history))
    n = len(ops)
    seen = set()

    def step(state, h):
        if h["op"] == "write":
            return h["value"], True
        return state, state == h["value"]

    def dfs(done_mask, state):
        if (done_mask, state) in seen:
            return False
        if done_mask == (1 << n) - 1:
            return True
        # an op may be linearized next only if no other UNdone completed
        # op finished before it was invoked (real-time order)
        min_complete = None
        for i, h in ops:
            if done_mask >> i & 1:
                continue
            c = h["complete"]
            if c is not None and (min_complete is None or c < min_complete):
                min_complete = c
        for i, h in ops:
            if done_mask >> i & 1:
                continue
            if min_complete is not None and h["invoke"] > min_complete:
                continue
            new_state, ok = step(state, h)
            if ok and dfs(done_mask | (1 << i), new_state):
                return True
            if h["complete"] is None:
                # indeterminate: also try "never took effect"
                if dfs(done_mask | (1 << i), state):
                    return True
        seen.add((done_mask, state))
        return False

    return dfs(0, 0)


def test_checker_accepts_valid_history():
    h = [
        {"op": "write", "value": 1, "invoke": 0.0, "complete": 1.0},
        {"op": "read", "value": 1, "invoke": 2.0, "complete": 3.0},
        {"op": "write", "value": 2, "invoke": 2.5, "complete": 4.0},
        {"op": "read", "value": 2, "invoke": 5.0, "complete": 6.0},
    ]
    assert check_register_linearizable(h)


def test_checker_rejects_stale_read():
    h = [
        {"op": "write", "value": 1, "invoke": 0.0, "complete": 1.0},
        {"op": "write", "value": 2, "invoke": 2.0, "complete": 3.0},
        # stale: reads the OLD value strictly after write(2) completed
        {"op": "read", "value": 1, "invoke": 4.0, "complete": 5.0},
    ]
    assert not check_register_linearizable(h)


def test_checker_allows_concurrent_overlap():
    h = [
        {"op": "write", "value": 1, "invoke": 0.0, "complete": 5.0},
        {"op": "write", "value": 2, "invoke": 0.0, "complete": 5.0},
        {"op": "read", "value": 1, "invoke": 6.0, "complete": 7.0},
    ]
    assert check_register_linearizable(h)      # w2 then w1 is valid
    h[2]["value"] = 2
    assert check_register_linearizable(h)      # w1 then w2 also valid


def test_checker_handles_indeterminate_write():
    h = [
        {"op": "write", "value": 1, "invoke": 0.0, "complete": 1.0},
        {"op": "write", "value": 2, "invoke": 2.0, "complete": None},
        {"op": "read", "value": 1, "invoke": 3.0, "complete": 4.0},
        {"op": "read", "value": 2, "invoke": 5.0, "complete": 6.0},
    ]
    # both reads explained: the timed-out write landed between them
    assert check_register_linearizable(h)
    # but it cannot UN-happen: 1 read after 2 was observed is stale
    h.append({"op": "read", "value": 1, "invoke": 7.0, "complete": 8.0})
    assert not check_register_linearizable(h)


# ---------------------------------------------------------------------------
# live cluster history collection
# ---------------------------------------------------------------------------

def test_live_cluster_history_is_linearizable():
    router = LocalRouter()
    nodes = [RaNode(f"lz{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"m{i}", f"lz{i}") for i in (1, 2, 3)]
    history: list = []
    hlock = threading.Lock()
    stop = threading.Event()

    def record(op, value, invoke, complete):
        with hlock:
            history.append({"op": op, "value": value,
                            "invoke": invoke, "complete": complete})

    try:
        ra_tpu.start_cluster(
            "lz", lambda: SimpleMachine(lambda c, s: c, 0), sids,
            router=router, election_timeout_ms=150)
        deadline = time.monotonic() + 15
        booted = False
        while time.monotonic() < deadline and not booted:
            t0 = time.monotonic()
            try:
                ra_tpu.process_command(sids[0], 1, router=router,
                                       timeout=2)
                record("write", 1, t0, time.monotonic())
                booted = True
            except Exception:
                # a timed-out attempt may still commit later: it is an
                # indeterminate write, and dropping it would make a
                # correct history check as non-linearizable
                record("write", 1, t0, None)
                time.sleep(0.1)
        assert booted, "cluster never became available"

        def writer(tid):
            v = tid * 1000
            # bounded: the checker's search is exponential in history
            # size; ~40 writes/thread keeps it well inside budget
            for _ in range(40):
                if stop.is_set():
                    break
                v += 1
                t0 = time.monotonic()
                try:
                    ra_tpu.process_command(sids[tid % 3], v,
                                           router=router, timeout=2)
                    record("write", v, t0, time.monotonic())
                except Exception:
                    record("write", v, t0, None)   # indeterminate
                time.sleep(0.02)

        def reader():
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    r = ra_tpu.consistent_query(sids[1], lambda s: s,
                                                router=router, timeout=2)
                    record("read", r.reply, t0, time.monotonic())
                except Exception:
                    pass                            # failed read: no info
                time.sleep(0.03)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in (1, 2)] + [threading.Thread(target=reader)]
        for th in threads:
            th.start()
        # scripted nemesis: minority partition, heal, cross-link cut
        from nemesis import Nemesis
        Nemesis(router, nodes).run([
            ("wait", 0.6),
            ("part", (("lz1", "lz2"), ("lz3",)), 0.6),
            ("wait", 0.6),
            ("part", (("lz1",), ("lz2",)), 0.6),
            ("wait", 0.5),
        ])
        stop.set()
        for th in threads:
            th.join(timeout=10)
        assert len(history) >= 20, len(history)
        determinate = [h for h in history if h["complete"] is not None]
        assert any(h["op"] == "read" for h in determinate)
        assert check_register_linearizable(history), history
    finally:
        stop.set()
        for n in nodes:
            n.stop()
