"""Disaster-recovery membership ops — the ra_2_SUITE
force_start_follower_as_single_member scenarios
(/root/reference/test/ra_2_SUITE.erl:652-737): after permanent majority
loss, the survivor shrinks to a single-member cluster, keeps serving,
survives a restart, and can grow back; plus the minority guard rails
(cluster delete and membership changes cannot commit without quorum).
"""
import os
import time

import pytest

import ra_tpu
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import Membership, ServerConfig, ServerId
from ra_tpu.node import LocalRouter, RaNode
from ra_tpu.system import RaSystem


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def await_(fn, timeout=25):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            r = fn()
            if r is not None:
                return r
        except Exception as e:  # noqa: BLE001 — retried probe
            last = e
        time.sleep(0.1)
    raise TimeoutError(last)


def test_force_shrink_after_majority_loss(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"m{i}", f"fs{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(os.path.join(str(tmp_path), s.node))
               for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    try:
        for s in sids:
            nodes[s.node].start_server(ServerConfig(
                server_id=s, uid=f"uid_{s.name}", cluster_name="fs",
                initial_members=tuple(sids), machine=counter(),
                election_timeout_ms=250, tick_interval_ms=100))
        ra_tpu.trigger_election(sids[0], router)
        leader = await_(lambda: ra_tpu.process_command(
            sids[0], 1, router=router).leader)
        ra_tpu.process_command(leader, 2, router=router)

        # permanent outage of a majority
        survivor = [s for s in sids if s != leader][0] \
            if leader == sids[0] else sids[0]
        for s in sids:
            if s != survivor:
                nodes[s.node].stop()

        # the survivor cannot commit ...
        with pytest.raises(Exception):
            ra_tpu.process_command(survivor, 99, router=router, timeout=1.5)
        # ... until it force-shrinks to a single-member cluster
        ra_tpu.force_shrink_members_to_current_member(survivor, router)
        r = await_(lambda: ra_tpu.process_command(survivor, 10,
                                                  router=router))
        assert r.reply == 13
        mem = ra_tpu.members(survivor, router=router)
        assert [m for m in mem] == [survivor], mem

        # restart the survivor: the forced membership is durable
        nodes[survivor.node].stop()
        systems[survivor.node].close()
        systems[survivor.node] = RaSystem(
            os.path.join(str(tmp_path), survivor.node))
        nodes[survivor.node] = RaNode(
            survivor.node, router=router,
            log_factory=systems[survivor.node].log_factory)
        rec = systems[survivor.node].recover_servers(
            nodes[survivor.node], lambda c, n: counter())
        assert len(rec) == 1
        ra_tpu.trigger_election(survivor, router)
        r = await_(lambda: ra_tpu.process_command(survivor, 5,
                                                  router=router))
        assert r.reply == 18
        assert list(ra_tpu.members(survivor, router=router)) == [survivor]

        # grow back: add a fresh member on a fresh node
        s4 = ServerId("m4", "fs4")
        systems[s4.node] = RaSystem(os.path.join(str(tmp_path), s4.node))
        nodes[s4.node] = RaNode(s4.node, router=router,
                                log_factory=systems[s4.node].log_factory)
        nodes[s4.node].start_server(ServerConfig(
            server_id=s4, uid="uid_m4", cluster_name="fs",
            initial_members=(survivor,), machine=counter(),
            election_timeout_ms=250, tick_interval_ms=100))
        ra_tpu.add_member(survivor, s4, router=router,
                          membership=Membership.PROMOTABLE)
        def caught_up():
            st = ra_tpu.local_query(s4, lambda x: x, router=router).reply
            return st if st == 18 else None
        assert await_(caught_up) == 18
        r = ra_tpu.process_command(survivor, 1, router=router)
        assert r.reply == 19
    finally:
        for n in nodes.values():
            n.stop()
        for s_ in systems.values():
            s_.close()


def test_minority_cannot_delete_cluster_or_change_membership():
    """cluster_cannot_be_deleted_in_minority + add_member_without_quorum:
    without a quorum neither a '$ra_cluster' delete nor a membership
    change can complete — the cluster survives intact."""
    router = LocalRouter()
    nodes = [RaNode(f"mc{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"m{i}", f"mc{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("mc", counter, sids, router=router)
        ra_tpu.trigger_election(sids[0], router)
        leader = await_(lambda: ra_tpu.process_command(
            sids[0], 1, router=router).leader)
        # cut the leader off from both followers
        for s in sids:
            if s != leader:
                router.block(leader.node, s.node)
        with pytest.raises(Exception):
            ra_tpu.delete_cluster(leader, router=router, timeout=1.5)
        s4 = ServerId("m4", "mc1")
        with pytest.raises(Exception):
            ra_tpu.add_member(leader, s4, router=router, timeout=1.5)
        router.heal()
        # the cluster is alive and consistent
        r = await_(lambda: ra_tpu.process_command(leader, 1,
                                                  router=router,
                                                  timeout=5))
        assert r.reply >= 2
    finally:
        for n in nodes:
            n.stop()
