"""Directory registry, system-level server recovery, force-delete, and
the ra_aux accessor surface (reference: ra_directory_SUITE,
ra_system_recover.erl, ra.erl force_delete/restart, ra_aux.erl)."""
import os
import time

import ra_tpu
from ra_tpu import Directory, LocalRouter, RaNode, RaSystem
from ra_tpu.core import aux
from ra_tpu.core.machine import Machine, SimpleMachine
from ra_tpu.core.types import ServerConfig, ServerId

from nemesis import await_leader


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def mk_cfg(sid, sids, machine=None):
    return ServerConfig(server_id=sid, uid=f"uid_{sid.name}",
                        cluster_name="ops", initial_members=tuple(sids),
                        machine=machine or counter(),
                        election_timeout_ms=80, tick_interval_ms=100)


# ---------------------------------------------------------------------------
# directory
# ---------------------------------------------------------------------------

def test_directory_roundtrip_and_persistence(tmp_path):
    d = Directory(str(tmp_path))
    d.register("u1", "m1", "clusterA", {"k": 1})
    d.register("u2", "m2", "clusterA")
    assert d.where_is("m1") == "u1"
    assert d.name_of("u2") == "m2"
    assert d.cluster_of("u1") == "clusterA"
    assert d.config_of("u1") == {"k": 1}
    assert d.is_registered_uid("u1")
    # re-registering a name under a new uid supersedes the old record
    d.register("u3", "m1", "clusterA")
    assert d.where_is("m1") == "u3"
    assert not d.is_registered_uid("u1")
    # survives a reload
    d2 = Directory(str(tmp_path))
    assert d2.where_is("m1") == "u3"
    assert sorted(d2.uids()) == ["u2", "u3"]
    d2.unregister("u2")
    d3 = Directory(str(tmp_path))
    assert d3.where_is("m2") is None


# ---------------------------------------------------------------------------
# system recovery (server_recovery_strategy: registered)
# ---------------------------------------------------------------------------

def test_recover_servers_restarts_registered_cluster(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"r{i}", f"rn{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)
    for v in range(1, 11):
        ra_tpu.process_command(leader, v, router=router)
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()

    # boot fresh systems over the same dirs; recover from the directory
    # alone — no caller-side config needed beyond the machine resolver
    router2 = LocalRouter()
    systems2 = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes2 = {s.node: RaNode(s.node, router=router2,
                             log_factory=systems2[s.node].log_factory)
              for s in sids}
    started = []
    for s in sids:
        started += systems2[s.node].recover_servers(
            nodes2[s.node], lambda cluster, name: counter())
    assert sorted(x.name for x in started) == ["r1", "r2", "r3"]
    leader2 = await_leader(router2, sids)
    res = ra_tpu.consistent_query(leader2, lambda s: s, router=router2)
    assert res.reply == 55
    # resolver returning None skips (machine unknown to this deployment)
    assert systems2[sids[0].node].recover_servers(
        nodes2[sids[0].node], lambda c, n: None) == []
    for n in nodes2.values():
        n.stop()
    for s in systems2.values():
        s.close()


def test_force_delete_server_wipes_data(tmp_path):
    router = LocalRouter()
    sid = ServerId("solo", "sn1")
    system = RaSystem(str(tmp_path / "sn1"))
    node = RaNode("sn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(sid, [sid]))
    ra_tpu.trigger_election(sid, router)
    await_leader(router, [sid])
    ra_tpu.process_command(sid, 1, router=router)
    uid = node.shells[sid.name].server.cfg.uid
    assert os.path.isdir(os.path.join(system.data_dir, uid))
    ra_tpu.force_delete_server(sid, system=system, router=router)
    deadline = time.monotonic() + 5
    while sid.name in node.shells and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sid.name not in node.shells
    assert not os.path.isdir(os.path.join(system.data_dir, uid))
    assert not system.directory.is_registered_uid(uid)
    node.stop()
    system.close()


def test_restart_and_stop_server_api(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"a{i}", f"an{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 9, router=router)
    follower = next(s for s in sids if s != leader)
    ra_tpu.stop_server(follower, router=router)
    assert follower.name not in nodes[follower.node].shells
    # restart = start over the persisted config/log: state recovers
    systems[follower.node].recover_servers(
        nodes[follower.node], lambda c, n: counter())
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        st = ra_tpu.local_query(follower, lambda s: s, router=router)
        if st.reply == 9:
            break
        time.sleep(0.02)
    assert st.reply == 9
    # in-place restart API on a running member
    ra_tpu.restart_server(leader, router=router)
    await_leader(router, sids)
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()


# ---------------------------------------------------------------------------
# ra_aux accessor surface
# ---------------------------------------------------------------------------

class AuxProbe(Machine):
    """Machine whose aux handler reports server internals via the
    sanctioned accessor surface."""

    def init(self, config):
        return 0

    def apply(self, meta, command, state):
        return state + command, state + command, []

    def init_aux(self, name):
        return {"name": name}

    def handle_aux(self, raft_state, kind, msg, aux_state, internal):
        if msg == "probe":
            report = {
                "machine_state": aux.machine_state(internal),
                "leader": aux.leader_id(internal),
                "term": aux.current_term(internal),
                "members": sorted(m.name for m in aux.members(internal)),
                "last": tuple(aux.log_last_index_term(internal)),
                "entry3": aux.log_fetch(3, internal),
                "log_type": aux.log_stats(internal).get("type",
                                                        "memory"),
                "mac_ver": aux.effective_machine_version(internal),
            }
            return aux_state, [], report
        return aux_state, [], None


def test_aux_accessors_via_aux_command():
    router = LocalRouter()
    nodes = [RaNode(f"xn{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"x{i}", f"xn{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("auxq", AuxProbe, sids, router=router)
        leader = await_leader(router, sids)
        for v in (5, 7):
            ra_tpu.process_command(leader, v, router=router)
        rep = ra_tpu.aux_command(leader, "probe", router=router)
        assert rep["machine_state"] == 12
        assert rep["leader"] == leader
        assert rep["members"] == ["x1", "x2", "x3"]
        assert rep["last"][0] >= 3
        assert rep["term"] >= 1
        assert rep["mac_ver"] == 0
        # log_fetch resolves a real committed entry
        assert rep["entry3"] is not None
    finally:
        for n in nodes:
            n.stop()


def test_force_delete_stopped_member_and_no_resurrection(tmp_path):
    """force_delete on an already-stopped member must still wipe its data
    (uid resolved via the system directory), and restart_server must not
    be able to resurrect the deleted identity over an empty log."""
    import pytest

    router = LocalRouter()
    sid = ServerId("gone", "gn1")
    system = RaSystem(str(tmp_path / "gn1"))
    node = RaNode("gn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(sid, [sid]))
    ra_tpu.trigger_election(sid, router)
    await_leader(router, [sid])
    ra_tpu.process_command(sid, 1, router=router)
    uid = node.shells[sid.name].server.cfg.uid
    ra_tpu.stop_server(sid, router=router)            # stopped first
    ra_tpu.force_delete_server(sid, system=system, router=router)
    assert not os.path.isdir(os.path.join(system.data_dir, uid))
    assert not system.directory.is_registered_uid(uid)
    # the node directory forgot it too: no amnesiac resurrection
    with pytest.raises(RuntimeError, match="not_found"):
        ra_tpu.restart_server(sid, router=router)
    # and system recovery skips it (nothing registered anymore)
    assert system.recover_servers(node, lambda c, n: counter()) == []
    node.stop()
    system.close()


def test_force_delete_does_not_pin_wal_files(tmp_path):
    """A force-deleted uid must not keep WAL files alive: after purge, a
    rollover whose file contains the deleted uid's entries can still be
    retired once the surviving servers' entries are flushed."""
    router = LocalRouter()
    a, b = ServerId("wa", "wn1"), ServerId("wb", "wn1")
    system = RaSystem(str(tmp_path / "wn1"))
    node = RaNode("wn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(a, [a]))
    node.start_server(mk_cfg(b, [b]))
    ra_tpu.trigger_election(a, router)
    ra_tpu.trigger_election(b, router)
    await_leader(router, [a])
    await_leader(router, [b])
    ra_tpu.process_command(a, 1, router=router)
    ra_tpu.process_command(b, 2, router=router)
    system.wal.flush()
    ra_tpu.force_delete_server(a, system=system, router=router)
    system.wal.rollover()
    system.wal.flush()
    system.segment_writer.await_idle()
    wal_dir = os.path.join(system.data_dir, "wal")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        files = sorted(os.listdir(wal_dir))
        if len(files) == 1:       # only the fresh post-rollover file
            break
        time.sleep(0.05)
    assert len(files) == 1, f"WAL files pinned by deleted uid: {files}"
    # the survivor still works
    res = ra_tpu.process_command(b, 3, router=router)
    assert res.reply == 5
    node.stop()
    system.close()


def test_queued_flush_job_skips_deleted_uid(tmp_path):
    """A flush job already queued when its uid is force-deleted must skip
    the uid instead of keeping the WAL file forever (purge/rollover
    race).  Driven directly against the segment writer."""
    import pickle

    router = LocalRouter()
    a, b = ServerId("qa", "qn1"), ServerId("qb", "qn1")
    system = RaSystem(str(tmp_path / "qn1"))
    node = RaNode("qn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(a, [a]))
    node.start_server(mk_cfg(b, [b]))
    logb = system._logs["uid_qb"]
    from ra_tpu.core.types import Entry
    logb.write([Entry(1, 1, "x")])
    system.wal.flush()
    for evt in logb.take_events():
        logb.handle_written(evt)
    # force-delete A, then hand the writer a job that still names uid_qa
    # (as a job queued before the delete would)
    ra_tpu.force_delete_server(a, system=system, router=router)
    fake_wal = os.path.join(system.data_dir, "wal", "99999999.wal")
    with open(fake_wal, "wb") as f:
        f.write(b"RTW1")
    system.segment_writer.accept_ranges({"uid_qa": (1, 1),
                                         "uid_qb": (1, 1)}, fake_wal)
    system.segment_writer.await_idle()
    assert not os.path.exists(fake_wal), \
        "deleted uid pinned a queued WAL flush job"
    node.stop()
    system.close()


def test_boot_purges_wal_entries_of_deleted_uids(tmp_path):
    """WAL-recovered entries for *tombstoned* uids (force-deleted before
    their file rotated out) must be purged at boot, or the retirement gate
    never fires again and every recovered WAL file is pinned across all
    future restarts.  Only a tombstone authorises the purge — see the
    companion tests for the conservative paths."""
    router = LocalRouter()
    a, b = ServerId("ba", "bn1"), ServerId("bb", "bn1")
    system = RaSystem(str(tmp_path / "bn1"))
    node = RaNode("bn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(a, [a]))
    node.start_server(mk_cfg(b, [b]))
    ra_tpu.trigger_election(a, router)
    ra_tpu.trigger_election(b, router)
    await_leader(router, [a])
    await_leader(router, [b])
    ra_tpu.process_command(a, 1, router=router)
    ra_tpu.process_command(b, 2, router=router)
    system.wal.flush()
    # delete A's directory record with a tombstone — simulating a
    # force-delete whose purge didn't cover the on-disk WAL (crash after)
    uid_a = "uid_ba"
    system.directory.unregister(uid_a, tombstone=True)
    node.stop()
    system.close()

    router2 = LocalRouter()
    system2 = RaSystem(str(tmp_path / "bn1"))
    node2 = RaNode("bn1", router=router2, log_factory=system2.log_factory)
    # boot purge dropped the orphan uid; once B re-registers, the
    # recovered WAL files retire instead of pinning forever
    assert uid_a not in system2.wal._recovered
    started = system2.recover_servers(node2, lambda c, n: counter())
    assert [s.name for s in started] == ["bb"]
    system2.wal.flush()
    system2.segment_writer.await_idle()
    wal_dir = os.path.join(system2.data_dir, "wal")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        files = sorted(os.listdir(wal_dir))
        if len(files) == 1:
            break
        time.sleep(0.05)
    assert len(files) == 1, f"recovered WAL files pinned: {files}"
    # B's state survived
    ra_tpu.trigger_election(b, router2)
    await_leader(router2, [b])
    res = ra_tpu.consistent_query(b, lambda s: s, router=router2)
    assert res.reply == 2
    node2.stop()
    system2.close()


def test_boot_keeps_wal_entries_of_unknown_uids(tmp_path):
    """A recovered uid that is neither registered nor tombstoned (e.g. a
    data dir written before its directory record landed) keeps its
    fsync-acknowledged WAL data: absence from the registry is not proof
    of deletion (ADVICE r1 medium)."""
    router = LocalRouter()
    a = ServerId("ka", "kn1")
    system = RaSystem(str(tmp_path / "kn1"))
    node = RaNode("kn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(a, [a]))
    ra_tpu.trigger_election(a, router)
    await_leader(router, [a])
    ra_tpu.process_command(a, 5, router=router)
    system.wal.flush()
    # drop the record WITHOUT a tombstone (lost registration, not delete)
    system.directory.unregister("uid_ka")
    node.stop()
    system.close()

    system2 = RaSystem(str(tmp_path / "kn1"))
    assert "uid_ka" in system2.wal._recovered, \
        "unknown uid's WAL data destroyed at boot"
    # and once the server re-registers, its state is recoverable
    router2 = LocalRouter()
    node2 = RaNode("kn1", router=router2, log_factory=system2.log_factory)
    node2.start_server(mk_cfg(a, [a]))
    ra_tpu.trigger_election(a, router2)
    await_leader(router2, [a])
    res = ra_tpu.consistent_query(a, lambda s: s, router=router2)
    assert res.reply == 5
    node2.stop()
    system2.close()


def test_boot_refuses_purge_when_directory_unreadable(tmp_path):
    """A corrupt directory file means the registry (and its tombstones)
    are unknown: the boot purge must not destroy anything on its
    authority."""
    router = LocalRouter()
    a = ServerId("ca", "cn1")
    system = RaSystem(str(tmp_path / "cn1"))
    node = RaNode("cn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(a, [a]))
    ra_tpu.trigger_election(a, router)
    await_leader(router, [a])
    ra_tpu.process_command(a, 7, router=router)
    system.wal.flush()
    node.stop()
    system.close()

    # corrupt the directory file
    dir_path = os.path.join(str(tmp_path / "cn1"), "directory")
    with open(dir_path, "wb") as f:
        f.write(b"\x80garbage-not-a-pickle")

    system2 = RaSystem(str(tmp_path / "cn1"))
    assert system2.directory.load_failed
    assert "uid_ca" in system2.wal._recovered, \
        "WAL data destroyed despite unreadable registry"
    system2.close()


def test_start_server_uid_validation(tmp_path):
    """start_server_uid_validation (ra_2_SUITE): uids name on-disk
    directories — unsafe ones are refused before any state is created."""
    import pytest

    from ra_tpu.core.types import ServerConfig, ServerId
    from ra_tpu.system import RaSystem

    system = RaSystem(str(tmp_path))
    try:
        assert RaSystem.validate_uid("abc_DEF-123=")
        for bad in ("", "a/b", "a b", "a\x00b", "../etc", "a.b"):
            assert not RaSystem.validate_uid(bad), bad
            cfg = ServerConfig(server_id=ServerId("s1", "n1"), uid=bad,
                               cluster_name="c", initial_members=(),
                               machine=None)
            with pytest.raises(ValueError):
                system.log_factory(cfg)
        assert not (tmp_path / "a").exists()
    finally:
        system.close()


def test_mutable_config_survives_disk_recovery(tmp_path):
    """A restart-applied mutable-config change persists in the config
    snapshot and survives a full node-process recovery from disk —
    the reference persists the EFFECTIVE config
    (ra_server_sup_sup.erl:80-103)."""
    import ra_tpu
    from ra_tpu.core.types import ServerId
    from ra_tpu.machines import machine_spec
    from ra_tpu.node import LocalRouter, RaNode
    from ra_tpu.system import RaSystem

    router = LocalRouter()
    system = RaSystem(str(tmp_path))
    node = RaNode("mc1", router=router, system=system)
    sid = ServerId("mcm1", "mc1")
    try:
        ra_tpu.start_cluster("mcc", machine_spec("counter"), [sid],
                             router=router)
        ra_tpu.restart_server(sid, router=router, mutable_config={
            "friendly_name": "kept", "max_pipeline_count": 777})
        cfg = node.shells[sid.name].server.cfg
        assert cfg.friendly_name == "kept"
        assert cfg.max_pipeline_count == 777
    finally:
        node.stop()
        system.close()
    # full process-restart simulation: fresh system + node over the
    # same data dir; the member recovers from the persisted snapshot
    system2 = RaSystem(str(tmp_path))
    node2 = RaNode("mc1", router=LocalRouter(), system=system2)
    try:
        started = system2.recover_servers(node2)
        assert started == [sid]
        cfg2 = node2.shells[sid.name].server.cfg
        assert cfg2.friendly_name == "kept"
        assert cfg2.max_pipeline_count == 777
        # local restart with NO in-memory loss also goes through the
        # disk fallback path when the node directory is empty
        node2.directory.clear()
        ra_tpu.restart_server(sid, router=node2.router)
        assert node2.shells[sid.name].server.cfg.friendly_name == "kept"
    finally:
        node2.stop()
        system2.close()
