"""FIFO machine tests — the capability proof (reference: test/ra_fifo.erl
driven by ra_fifo_SUITE scenarios + ra_machine_int_SUITE effect tests).

Part 1 drives FifoMachine.apply directly (pure data-in/data-out, the
mocked-log style of ra_server_SUITE); part 2 runs it on a live 3-member
cluster through FifoClient, including monitor-driven consumer death and
release-cursor-driven log truncation."""
import time

import pytest

import ra_tpu
from ra_tpu.core.machine import ApplyMeta
from ra_tpu.core.types import Monitor, ReleaseCursor, SendMsg, ServerId
from ra_tpu.models import FifoClient, FifoMachine, Mailbox
from ra_tpu.models.fifo import (
    query_consumer_count,
    query_messages_checked_out,
    query_messages_ready,
)
from ra_tpu.node import LocalRouter, RaNode


# ---------------------------------------------------------------------------
# part 1: pure apply
# ---------------------------------------------------------------------------

class Driver:
    """Applies commands with auto-incrementing raft indexes."""

    def __init__(self, machine=None):
        self.m = machine or FifoMachine()
        self.state = self.m.init({"name": "q"})
        self.idx = 0
        self.effects = []

    def apply(self, cmd):
        self.idx += 1
        st, reply, effs = self.m.apply(ApplyMeta(self.idx, 1), cmd,
                                       self.state)
        self.state = st
        self.effects.extend(effs)
        return reply

    def deliveries(self, pid):
        out = []
        for e in self.effects:
            if isinstance(e, SendMsg) and e.to is pid and \
                    e.msg[0] == "delivery":
                out.extend(e.msg[2])
        return out


def test_enqueue_dedup_and_ordering():
    d = Driver()
    enq = Mailbox("e1")
    d.apply(("enqueue", enq, 1, "a"))
    d.apply(("enqueue", enq, 1, "a"))      # duplicate: dropped
    d.apply(("enqueue", enq, 3, "c"))      # gap: held pending
    assert query_messages_ready(d.state) == 1
    d.apply(("enqueue", enq, 2, "b"))      # fills gap, releases both
    assert query_messages_ready(d.state) == 3
    order = [raw for (_i, _h, raw) in d.state.messages.values()]
    assert order == ["a", "b", "c"]
    # first contact monitors the enqueuer
    assert any(isinstance(e, Monitor) and e.target is enq
               for e in d.effects)


def test_checkout_auto_delivers_and_settle_frees_credit():
    d = Driver()
    con = Mailbox("c1")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("enqueue", None, None, "m2"))
    d.apply(("enqueue", None, None, "m3"))
    d.apply(("checkout", ("auto", 2), ("t", con)))
    got = d.deliveries(con)
    assert [raw for (_id, _h, raw) in got] == ["m1", "m2"]  # credit caps at 2
    assert query_messages_checked_out(d.state) == 2
    assert query_messages_ready(d.state) == 1
    d.apply(("settle", (got[0][0],), ("t", con)))
    got2 = d.deliveries(con)
    assert [raw for (_id, _h, raw) in got2][-1] == "m3"  # freed credit refills
    assert query_messages_ready(d.state) == 0


def test_return_redelivers_with_delivery_count():
    d = Driver()
    con = Mailbox("c1")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("checkout", ("auto", 1), ("t", con)))
    (mid, header, raw) = d.deliveries(con)[0]
    assert header["delivery_count"] == 0
    d.apply(("return", (mid,), ("t", con)))
    redelivered = d.deliveries(con)[-1]
    assert redelivered[2] == "m1"
    assert redelivered[1]["delivery_count"] == 1


def test_returned_messages_keep_fifo_order():
    d = Driver()
    con = Mailbox("c1")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("enqueue", None, None, "m2"))
    d.apply(("checkout", ("auto", 1), ("t", con)))
    (mid, _h, raw) = d.deliveries(con)[0]
    assert raw == "m1"
    d.apply(("return", (mid,), ("t", con)))
    # m1 must come back before m2
    assert d.deliveries(con)[-1][2] == "m1"


def test_discard_and_purge():
    d = Driver()
    con = Mailbox("c1")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("enqueue", None, None, "m2"))
    d.apply(("checkout", ("auto", 1), ("t", con)))
    (mid, _h, _r) = d.deliveries(con)[0]
    d.apply(("discard", (mid,), ("t", con)))
    assert query_messages_checked_out(d.state) == 1  # m2 auto-delivered
    reply = d.apply(("purge",))
    assert reply == ("purge", 0)  # all ready msgs were checked out
    d.apply(("enqueue", None, None, "m3"))
    d.apply(("checkout", "cancel", ("t", con)))
    reply = d.apply(("purge",))
    assert reply[0] == "purge" and reply[1] >= 1


def test_dequeue_modes():
    d = Driver()
    con = Mailbox("c1")
    assert d.apply(("checkout", ("dequeue", "settled"),
                    ("t", con))) == ("dequeue", "empty")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("enqueue", None, None, "m2"))
    kind, (header, raw) = d.apply(("checkout", ("dequeue", "settled"),
                                   ("t", con)))
    assert (kind, raw) == ("dequeue", "m1")
    kind, (msg_id, header, raw) = d.apply(
        ("checkout", ("dequeue", "unsettled"), ("t", con)))
    assert raw == "m2"
    assert query_messages_checked_out(d.state) == 1
    d.apply(("settle", (msg_id,), ("t", con)))
    assert query_messages_checked_out(d.state) == 0


def test_consumer_down_requeues_messages():
    d = Driver()
    c1, c2 = Mailbox("c1"), Mailbox("c2")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("checkout", ("auto", 5), ("t1", c1)))
    assert len(d.deliveries(c1)) == 1
    d.apply(("down", c1, "killed"))
    assert query_consumer_count(d.state) == 0
    assert query_messages_ready(d.state) == 1      # requeued
    d.apply(("checkout", ("auto", 5), ("t2", c2)))
    re = d.deliveries(c2)[0]
    assert re[2] == "m1" and re[1]["delivery_count"] == 1


def test_noconnection_suspects_then_nodeup_restores():
    d = Driver()
    con = Mailbox("c1", node="nodeB")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("checkout", ("auto", 5), ("t", con)))
    d.apply(("settle", (d.deliveries(con)[0][0],), ("t", con)))
    d.apply(("down", con, "noconnection"))
    d.apply(("enqueue", None, None, "m2"))
    # suspected consumer must not receive deliveries
    assert len(d.deliveries(con)) == 1
    assert query_messages_ready(d.state) == 1
    d.apply(("nodeup", "nodeB"))
    assert d.deliveries(con)[-1][2] == "m2"


def test_release_cursor_on_drain_and_interval():
    d = Driver(FifoMachine(shadow_copy_interval=10))
    con = Mailbox("c1")
    d.apply(("enqueue", None, None, "m1"))
    d.apply(("checkout", ("auto", 5), ("t", con)))
    d.apply(("settle", (d.deliveries(con)[0][0],), ("t", con)))
    drains = [e for e in d.effects if isinstance(e, ReleaseCursor)]
    assert drains and drains[-1].index == d.idx   # drained => cursor
    d.effects.clear()
    for i in range(12):
        d.apply(("enqueue", None, None, f"x{i}"))
    assert any(isinstance(e, ReleaseCursor) for e in d.effects)
    # snapshot state must be detached from live state
    snap = [e for e in d.effects if isinstance(e, ReleaseCursor)][-1]
    before = query_messages_ready(snap.machine_state)
    d.apply(("enqueue", None, None, "y"))
    assert query_messages_ready(snap.machine_state) == before


# ---------------------------------------------------------------------------
# part 2: live cluster
# ---------------------------------------------------------------------------

@pytest.fixture
def fabric():
    router = LocalRouter()
    nodes = [RaNode(f"n{i}", router=router) for i in (1, 2, 3)]
    yield router, nodes
    for n in nodes:
        n.stop()


def ids(n=3):
    return [ServerId(f"f{i+1}", f"n{i+1}") for i in range(n)]


from nemesis import await_leader  # noqa: E402  (shared helper)


def test_fifo_end_to_end(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("fifo-e2e", lambda: FifoMachine(), sids,
                         router=router)
    leader = await_leader(router, sids)
    client = FifoClient(sids, router=router, tag="w1")
    client.checkout("auto", credit=50)
    for i in range(30):
        client.enqueue(f"msg-{i}")
    client.flush(timeout=10.0)
    deadline = time.monotonic() + 5.0
    while len(client.deliveries) < 30 and time.monotonic() < deadline:
        client.poll_deliveries()
        time.sleep(0.02)
    assert [raw for (_i, _h, raw) in client.deliveries] == \
        [f"msg-{i}" for i in range(30)]
    client.settle([i for (i, _h, _r) in client.deliveries])
    res = ra_tpu.leader_query(leader, query_messages_checked_out,
                              router=router)
    assert res.reply == 0


def test_fifo_consumer_death_redelivers(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("fifo-death", lambda: FifoMachine(), sids,
                         router=router)
    leader = await_leader(router, sids)
    c1 = FifoClient(sids, router=router, tag="dead")
    c2 = FifoClient(sids, router=router, tag="alive")
    c1.checkout("auto", credit=10)
    for i in range(5):
        c1.enqueue_sync(i)
    deadline = time.monotonic() + 5.0
    while len(c1.deliveries) < 5 and time.monotonic() < deadline:
        c1.poll_deliveries()
        time.sleep(0.02)
    assert len(c1.deliveries) == 5
    # kill consumer 1: the leader's node reports the monitored pid down
    for node in nodes:
        node.process_down(c1.mailbox, "killed")
    c2.checkout("auto", credit=10)
    deadline = time.monotonic() + 5.0
    while len(c2.deliveries) < 5 and time.monotonic() < deadline:
        c2.poll_deliveries()
        time.sleep(0.02)
    assert sorted(r for (_i, _h, r) in c2.deliveries) == [0, 1, 2, 3, 4]
    assert all(h["delivery_count"] == 1 for (_i, h, _r) in c2.deliveries)


def test_fifo_release_cursor_truncates_log(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("fifo-rc",
                         lambda: FifoMachine(shadow_copy_interval=8),
                         sids, router=router)
    leader = await_leader(router, sids)
    client = FifoClient(sids, router=router, tag="rc")
    for i in range(40):
        client.enqueue(i)
    client.flush(timeout=10.0)
    # drain the queue so the cursor lands
    client.checkout("auto", credit=64)
    deadline = time.monotonic() + 5.0
    while len(client.deliveries) < 40 and time.monotonic() < deadline:
        client.poll_deliveries()
        time.sleep(0.02)
    client.settle([i for (i, _h, _r) in client.deliveries])
    deadline = time.monotonic() + 5.0
    node = router.nodes[leader.node]
    while time.monotonic() < deadline:
        log = node.shells[leader.name].server.log
        if log.first_index() > 1:
            break
        time.sleep(0.05)
    assert node.shells[leader.name].server.log.first_index() > 1


def test_fifo_cross_host_pipeline_acks():
    """Three single-node hosts over real TCP.  A client co-hosted with a
    FOLLOWER pipelines enqueues: the follower must relay the batch to the
    leader, applied-notifications must route back across hosts (rnotify),
    and seqno dedup must survive the pickle boundary — resends may commit
    twice on the wire but must apply once."""
    import socket

    from ra_tpu import api
    from ra_tpu.transport.tcp import TcpRouter

    names = ("h1", "h2", "h3")
    ports, socks = {}, []
    for n in names:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports[n] = s.getsockname()[1]
        socks.append(s)
    for s in socks:
        s.close()
    routers, nodes = {}, {}
    for n in names:
        book = {m: ("127.0.0.1", ports[m]) for m in names if m != n}
        routers[n] = TcpRouter(("127.0.0.1", ports[n]), book)
        nodes[n] = RaNode(n, router=routers[n])
    sids = {n: ServerId(f"q_{n}", n) for n in names}
    try:
        for n in names:
            api.start_server("xq", lambda: FifoMachine(), sids[n],
                             list(sids.values()), router=routers[n])
        ra_tpu.trigger_election(sids["h1"], routers["h1"])
        leader_host = None
        deadline = time.monotonic() + 10.0
        while leader_host is None and time.monotonic() < deadline:
            for n in names:
                sh = nodes[n].shells.get(sids[n].name)
                if sh and sh.server.raft_state.value == "leader":
                    leader_host = n
            time.sleep(0.02)
        assert leader_host, "no leader over TCP"
        follower_host = next(n for n in names if n != leader_host)
        client = FifoClient([sids[follower_host]],
                            router=routers[follower_host], tag="xh")
        for i in range(10):
            client.enqueue(i)
        client.flush(timeout=20.0)
        res = ra_tpu.leader_query(sids[leader_host], query_messages_ready,
                                  router=routers[leader_host])
        assert res.reply == 10  # exactly once despite any resends
    finally:
        for n in names:
            nodes[n].stop()
            routers[n].stop()


def test_client_backpressure_soft_limit_and_stop_sending():
    """ra_fifo_client flow control (ra_fifo_client.erl:21, :93-110):
    enqueue answers "slow" once the unapplied window passes soft_limit
    and raises StopSending at max_pending; once a leader applies the
    backlog the window drains and status returns to "ok"."""
    from ra_tpu.core.types import ServerConfig
    from ra_tpu.models import StopSending

    router = LocalRouter()
    sids = [ServerId(f"bp{i}", f"bpn{i}") for i in (1, 2, 3)]
    nodes = {s.node: RaNode(s.node, router=router) for s in sids}
    try:
        # cluster is configured but NOT elected: pipelined enqueues park
        # in the client's pending set, so the window only grows
        for sid in sids:
            nodes[sid.node].start_server(ServerConfig(
                server_id=sid, uid=ra_tpu.new_uid(sid.name),
                cluster_name="bp", initial_members=tuple(sids),
                machine=FifoMachine(),
                election_timeout_ms=10_000, tick_interval_ms=50))
        client = FifoClient(sids, router=router, soft_limit=4,
                            max_pending=8)
        statuses = [client.enqueue(i)[0] for i in range(8)]
        assert statuses[:3] == ["ok"] * 3
        assert statuses[3:] == ["slow"] * 5          # window >= soft_limit
        # an open window carries no blocked stamp and no rejections yet
        assert client.blocked_since is None
        assert client.ingress_rejections == 0
        import time as _time
        t_before = _time.monotonic()
        with pytest.raises(StopSending):
            client.enqueue("overflow")
        # the refusal is observable (ISSUE 10 satellite): the episode's
        # start is stamped ONCE and every refusal counts, so a shed
        # decision can read "blocked since X, N refusals"
        assert client.blocked_since is not None
        assert t_before <= client.blocked_since <= _time.monotonic()
        first_stamp = client.blocked_since
        with pytest.raises(StopSending):
            client.enqueue("overflow-2")
        assert client.blocked_since == first_stamp   # episode start kept
        assert client.ingress_rejections == 2
        # now elect and let the backlog apply: the window drains, dedup
        # keeps the queue exactly-once, and enqueue is "ok" again
        ra_tpu.trigger_election(sids[0], router=router)
        await_leader(router, sids)
        client.flush(timeout=15.0)
        assert client.pending_count() == 0
        assert client.enqueue("after")[0] == "ok"
        assert client.blocked_since is None          # episode ended
        assert client.ingress_rejections == 2        # lifetime counter
        client.flush(timeout=15.0)
        leader = await_leader(router, sids)
        res = ra_tpu.local_query(
            leader, query_messages_ready, router=router)
        assert res.reply == 9                         # 0..7 + "after", no dupes
    finally:
        for n in nodes.values():
            n.stop()
