"""Segment-file depth suite — the ra_log_segment_SUITE scenarios
(/root/reference/test/ra_log_segment_SUITE.erl): header persistence,
write/close/open/write cycles, full-file refusal, missing reads,
overwrite tail invalidation (live AND across reload), large payloads,
invalid/corrupted files, and truncate_from durability.
"""
import os

import pytest

from ra_tpu.log.segment import SegmentFile


def fill(seg, lo, hi, term=1, payload=None):
    for i in range(lo, hi + 1):
        assert seg.append(i, term, payload or f"e{i}".encode())
    seg.flush()


def test_open_close_persists_max_count(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=64, create=True)
    seg.close()
    seg2 = SegmentFile(p, max_count=4096)   # arg ignored on open
    assert seg2.max_count == 64
    seg2.close()


def test_write_close_open_write(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=128, create=True)
    fill(seg, 1, 10)
    seg.close()
    seg2 = SegmentFile(p)
    assert seg2.range() == (1, 10)
    fill(seg2, 11, 20)
    seg2.close()
    seg3 = SegmentFile(p)
    assert seg3.range() == (1, 20)
    for i in (1, 10, 11, 20):
        term, payload = seg3.read(i)
        assert (term, payload) == (1, f"e{i}".encode())
    seg3.close()


def test_full_file_refuses_and_reports(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=8, create=True)
    for i in range(1, 9):
        assert seg.append(i, 1, b"x")
    assert seg.full
    assert not seg.append(9, 1, b"y")       # {error, full}
    seg.flush()
    assert seg.range() == (1, 8)
    seg.close()


def test_overwrite_into_full_pending_segment_fits_in_place(tmp_path):
    """An overwrite landing in a segment whose capacity is consumed by
    PENDING entries frees the superseded tail first and fits in place
    instead of forcing a roll (invalidate-before-capacity-check)."""
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=8, create=True)
    for i in range(1, 9):
        assert seg.append(i, 1, f"e{i}".encode())
    assert seg.full
    assert seg.append(5, 2, b"new5")        # drops pending 5..8, fits
    seg.flush()
    assert seg.range() == (1, 5)
    assert seg.read(5) == (2, b"new5")
    # flushed slots are append-only: once capacity is in the FILE an
    # overwrite still refuses ({error, full} -> roll), and the refusal
    # mutates nothing — the live view must keep agreeing with a reload
    for i in range(6, 9):
        assert seg.append(i, 2, b"x")
    seg.flush()
    assert not seg.append(3, 3, b"y")
    assert seg.range() == (1, 8)
    assert seg.read(6) == (2, b"x")
    seg.close()


def test_try_read_missing(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=16, create=True)
    fill(seg, 5, 8)
    assert seg.read(1) is None
    assert seg.read(9) is None
    assert seg.read(999) is None
    seg.close()


def test_overwrite_invalidates_live_tail(tmp_path):
    """Rewriting a lower index drops every live entry at/above it —
    without waiting for a reload (the overwrite case)."""
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=32, create=True)
    fill(seg, 1, 5)
    assert seg.append(3, 2, b"new3")
    seg.flush()
    assert seg.read(3) == (2, b"new3")
    assert seg.read(4) is None
    assert seg.read(5) is None
    assert seg.range() == (1, 3)
    seg.close()
    # reload reconstructs the same view from slot order
    seg2 = SegmentFile(p)
    assert seg2.range() == (1, 3)
    assert seg2.read(3) == (2, b"new3")
    assert seg2.read(5) is None
    seg2.close()


def test_overwrite_pending_before_flush(tmp_path):
    """An overwrite within the same unflushed batch drops the pending
    stale tail too."""
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=32, create=True)
    for i in range(1, 6):
        seg.append(i, 1, f"e{i}".encode())
    seg.append(2, 3, b"new2")   # invalidates pending 2..5
    seg.flush()
    assert seg.range() == (1, 2)
    assert seg.read(2) == (3, b"new2")
    assert seg.read(3) is None
    seg.close()


def test_write_many_large_payloads(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=600, create=True)
    big = os.urandom(256 * 1024)
    for i in range(1, 501):
        payload = big if i % 100 == 0 else f"v{i}".encode()
        assert seg.append(i, 1, payload)
    seg.flush()
    seg.close()
    seg2 = SegmentFile(p)
    assert seg2.range() == (1, 500)
    assert seg2.read(100)[1] == big
    assert seg2.read(499)[1] == b"v499"
    seg2.close()


def test_open_invalid_magic(tmp_path):
    p = str(tmp_path / "bad.segment")
    with open(p, "wb") as f:
        f.write(b"NOTASEGMENTFILE" + b"\x00" * 100)
    with pytest.raises(ValueError, match="magic"):
        SegmentFile(p)


def test_corrupted_data_region_detected_by_crc(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=16, create=True)
    fill(seg, 1, 8, payload=b"payload-payload")
    seg.close()
    # flip bytes in the data region (past header + slot table)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.seek(size - 10)
        f.write(b"\xff\xff\xff")
    seg2 = SegmentFile(p)
    with pytest.raises(ValueError, match="crc"):
        for i in range(1, 9):
            seg2.read(i)
    seg2.close()


def test_truncate_from_durable_across_reload(tmp_path):
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=32, create=True)
    fill(seg, 1, 10)
    seg.truncate_from(6)
    assert seg.range() == (1, 5)
    seg.close()
    seg2 = SegmentFile(p)
    assert seg2.range() == (1, 5)
    assert seg2.read(6) is None
    assert seg2.read(5) == (1, b"e5")
    # truncated indexes are appendable again
    fill(seg2, 6, 8, term=2)
    seg2.close()
    seg3 = SegmentFile(p)
    assert seg3.range() == (1, 8)
    assert seg3.read(6) == (2, b"e6")
    seg3.close()


# -- segment-writer barrier semantics (ra_log_segment_writer_SUITE) ---------

class _StubLog:
    def __init__(self, fail=False):
        self.fail = fail
        self.flushed = []

    def flush_mem_to_segments(self, hi):
        if self.fail:
            raise OSError("disk gone")
        self.flushed.append(hi)
        return (1, 10, 0)


def _writer(resolve):
    from ra_tpu.log.segment import SegmentWriter
    return SegmentWriter(resolve=resolve, flush_workers=2)


def test_wal_file_deleted_only_after_every_flush(tmp_path):
    """accept_mem_tables: the WAL file is unlinked once every uid's
    range reached segments (the deletion barrier)."""
    wal = tmp_path / "00000001.wal"
    wal.write_bytes(b"x")
    logs = {"u1": _StubLog(), "u2": _StubLog()}
    w = _writer(lambda uid: logs.get(uid))
    w.accept_ranges({"u1": (1, 5), "u2": (1, 9)}, str(wal))
    w.await_idle()
    assert not wal.exists()
    assert logs["u1"].flushed == [5] and logs["u2"].flushed == [9]


def test_wal_file_kept_when_a_flush_fails(tmp_path):
    """A failed per-uid flush keeps the WAL file: its entries remain
    recoverable (accept_mem_tables_with_corrupt_segment shape)."""
    wal = tmp_path / "00000002.wal"
    wal.write_bytes(b"x")
    logs = {"u1": _StubLog(), "u2": _StubLog(fail=True)}
    w = _writer(lambda uid: logs.get(uid))
    w.accept_ranges({"u1": (1, 5), "u2": (1, 9)}, str(wal))
    w.await_idle()
    assert wal.exists()                      # barrier held
    assert logs["u1"].flushed == [5]         # the healthy uid still flushed


def test_wal_file_kept_for_stopped_server(tmp_path):
    """accept_mem_tables_for_down_server: an unresolvable (stopped, not
    deleted) uid pins the file for restart recovery."""
    wal = tmp_path / "00000003.wal"
    wal.write_bytes(b"x")
    w = _writer(lambda uid: None)
    w.accept_ranges({"ghost": (1, 5)}, str(wal))
    w.await_idle()
    assert wal.exists()


def test_deleted_uid_does_not_pin_wal(tmp_path):
    """accept_mem_tables_with_delete_server: a force-deleted uid's
    entries are garbage — the file must not be pinned."""
    wal = tmp_path / "00000004.wal"
    wal.write_bytes(b"x")
    w = _writer(lambda uid: None)
    w.mark_deleted("gone")
    w.accept_ranges({"gone": (1, 5)}, str(wal))
    w.await_idle()
    assert not wal.exists()


def test_flush_skips_entries_below_snapshot_index(tmp_path):
    """skip_entries_lower_than_snapshot_index: a snapshot taken before
    the rollover means only post-snapshot entries reach segments."""
    from test_durable_log import drain, mk_log, mk_system
    from ra_tpu.core.types import Entry, UserCommand

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 61):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    log.update_release_cursor(50, (), 0, {"acc": 50})
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    ov = log.overview()
    assert ov["num_mem_entries"] == 0
    # segment files hold only 51..60
    segs = [f for f in os.listdir(os.path.join(str(tmp_path), "u1"))
            if f.endswith(".segment")]
    lo = 10**9
    for f in segs:
        seg = SegmentFile(os.path.join(str(tmp_path), "u1", f))
        r = seg.range()
        if r:
            lo = min(lo, r[0])
        seg.close()
    assert lo >= 51, lo
    assert log.fetch(55).command.data == 55
    sys_.close()


def test_fd_eviction_reopens_transparently(tmp_path):
    """close_fd (the FLRU eviction) keeps the index; the next read
    reopens the descriptor."""
    p = str(tmp_path / "a.segment")
    seg = SegmentFile(p, max_count=16, create=True)
    fill(seg, 1, 4)
    seg.close_fd()
    assert seg.fd is None
    assert seg.read(3) == (1, b"e3")
    assert seg.fd is not None
    seg.close()
