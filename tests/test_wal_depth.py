"""WAL edge-case depth — the ra_log_wal_SUITE role
(/root/reference/test/ra_log_wal_SUITE.erl, 992 LoC): batching,
rollover triggers, recovery through corruption, out-of-sequence
resends, truncate writes, and multi-writer interleaving.
"""
import os
import threading
import time

import pytest

from ra_tpu.log.wal import DEFAULT_MAX_BATCH, Wal, WalDown, scan_wal_file


class Sink:
    """Confirm collector for one registered writer."""

    def __init__(self):
        self.confirms = []       # (lo, hi, term)
        self.resends = []        # hi (lo=None signals)
        self.event = threading.Event()

    def __call__(self, uid, lo, hi, term):
        if lo is None:
            self.resends.append(hi)
        else:
            self.confirms.append((lo, hi, term))
        self.event.set()

    def wait_hi(self, hi, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if any(c[1] >= hi for c in self.confirms):
                return True
            self.event.wait(0.05)
            self.event.clear()
        return False


def wal_files(tmp_path):
    d = os.path.join(str(tmp_path), "wal")
    return sorted(f for f in os.listdir(d) if f.endswith(".wal"))


def test_batch_confirms_coalesce(tmp_path):
    """Many queued writes confirm as few batches (gen_batch_server
    coalescing, ra_log_wal.erl:753-800)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 501):
        wal.write("u1", i, 1, b"x" * 16)
    wal.flush()
    assert sink.wait_hi(500)
    # confirms arrive [lo..hi] coalesced, in order, gap-free
    covered = 0
    for lo, hi, _t in sink.confirms:
        assert lo == covered + 1
        covered = hi
    assert covered == 500
    assert wal.counters["batches"] < 500  # really batched
    assert wal.counters["writes"] == 500
    wal.close()


def test_out_of_sequence_write_signals_resend(tmp_path):
    """A gapped write is refused with a resend-from signal rather than
    silently accepted (ra_log_wal.erl:457-481)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    wal.write("u1", 1, 1, b"a")
    wal.write("u1", 2, 1, b"b")
    wal.flush()
    wal.write("u1", 9, 1, b"gap")  # skips 3..8
    wal.flush()
    assert sink.resends and sink.resends[0] == 2, sink.resends
    # the gapped entry is NOT on disk
    tables = {}
    wal.close()
    for f in wal_files(tmp_path):
        scan_wal_file(os.path.join(str(tmp_path), "wal", f), tables)
    assert sorted(tables["u1"]) == [1, 2]


def test_overwrite_lower_index_accepted_and_dedupes(tmp_path):
    """Overwriting at a lower index (leader change rewrites the tail)
    is legal; recovery keeps the LAST write and drops the stale higher
    suffix (ra_log_wal recovery semantics :871-955)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 6):
        wal.write("u1", i, 1, f"t1-{i}".encode())
    wal.flush()
    # new term truncates back to 3 and rewrites
    wal.write("u1", 3, 2, b"t2-3", truncate=True)
    wal.write("u1", 4, 2, b"t2-4")
    wal.flush()
    wal.close()
    tables = {}
    for f in wal_files(tmp_path):
        scan_wal_file(os.path.join(str(tmp_path), "wal", f), tables)
    got = tables["u1"]
    assert sorted(got) == [1, 2, 3, 4]  # stale 5 deduped away
    assert got[3] == (2, b"t2-3")
    assert got[4] == (2, b"t2-4")


def test_recovery_stops_at_corrupt_tail(tmp_path):
    """A torn/corrupted record ends recovery at the last good prefix
    (crc check, ra_log_wal.erl:871-955)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 21):
        wal.write("u1", i, 1, f"payload-{i:03d}".encode())
    wal.flush()
    assert sink.wait_hi(20)
    path = os.path.join(str(tmp_path), "wal", wal_files(tmp_path)[-1])
    wal.close()
    # flip bytes near 2/3 of the file: corrupts some record's payload
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size * 2 // 3)
        f.write(b"\xff\xff\xff\xff")
    tables = {}
    try:
        scan_wal_file(path, tables)
        raised = False
    except ValueError:
        raised = True  # crc mismatch (header or payload damage)
    got = sorted(tables.get("u1", {}))
    assert got, "prefix should survive"
    # the good prefix is contiguous and recovery STOPPED at the damage
    # (the record crc covers header fields too, so a flipped wid/idx
    # cannot silently skip an entry and continue)
    assert got == list(range(1, len(got) + 1)), got
    assert len(got) < 20
    # header-covered crc: any 4-byte flip inside a record raises
    assert raised


def test_header_field_corruption_stops_recovery(tmp_path):
    """Flipping a record's writer-id (not its payload) must fail the
    crc and stop recovery — regression for the header-coverage gap."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 11):
        wal.write("u1", i, 1, b"PAYLOAD-%02d" % i)
    wal.flush()
    assert sink.wait_hi(10)
    path = os.path.join(str(tmp_path), "wal", wal_files(tmp_path)[-1])
    wal.close()
    raw = bytearray(open(path, "rb").read())
    # find the 6th entry record (type byte 2 followed by our payload)
    needle = b"PAYLOAD-06"
    at = raw.find(needle)
    assert at > 0
    hdr_at = at - 29  # _ENT.size == 29
    assert raw[hdr_at] == 2
    raw[hdr_at + 1] ^= 0xFF  # flip the wid byte
    open(path, "wb").write(bytes(raw))
    tables = {}
    with pytest.raises(ValueError):
        scan_wal_file(path, tables)
    assert sorted(tables.get("u1", {})) == [1, 2, 3, 4, 5]


def test_rollover_on_size_threshold(tmp_path):
    """Crossing max_size rolls the file over automatically
    (ra_log_wal.erl:593-620)."""
    wal = Wal(str(tmp_path), sync_mode=0, max_size=4096)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 41):
        wal.write("u1", i, 1, b"z" * 256)
    wal.flush()
    assert sink.wait_hi(40)
    assert wal.counters["wal_files"] >= 2
    wal.close()


def test_two_writers_interleaved_ranges(tmp_path):
    """Co-hosted writers share files; per-writer ranges recover
    independently (the fan-in design, ra_log_wal.erl:193-214)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    s1, s2 = Sink(), Sink()
    wal.register("a", s1)
    wal.register("b", s2)
    for i in range(1, 101):
        wal.write("a", i, 1, f"a{i}".encode())
        wal.write("b", i, 5, f"b{i}".encode())
    wal.flush()
    assert s1.wait_hi(100) and s2.wait_hi(100)
    wal.close()
    tables = {}
    for f in wal_files(tmp_path):
        scan_wal_file(os.path.join(str(tmp_path), "wal", f), tables)
    assert sorted(tables["a"]) == list(range(1, 101))
    assert sorted(tables["b"]) == list(range(1, 101))
    assert tables["a"][7] == (1, b"a7")
    assert tables["b"][7] == (5, b"b7")


def test_max_batch_bounds_one_pass(tmp_path):
    """The batch thread never folds more than max_batch queue items
    into one write (ra.hrl:192)."""
    wal = Wal(str(tmp_path), sync_mode=0, max_batch=8)
    sink = Sink()
    wal.register("u1", sink)
    for i in range(1, 65):
        wal.write("u1", i, 1, b"q")
    wal.flush()
    assert sink.wait_hi(64)
    assert wal.counters["batches"] >= 64 // 8
    wal.close()


def test_write_after_close_raises_waldown(tmp_path):
    wal = Wal(str(tmp_path), sync_mode=0)
    wal.register("u1", Sink())
    wal.close()
    with pytest.raises(WalDown):
        wal.write("u1", 1, 1, b"x")
    with pytest.raises(WalDown):
        wal.flush()


def test_empty_payload_and_large_payload(tmp_path):
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    wal.register("u1", sink)
    big = os.urandom(2 * 1024 * 1024)
    wal.write("u1", 1, 1, b"")
    wal.write("u1", 2, 1, big)
    wal.flush()
    assert sink.wait_hi(2)
    wal.close()
    tables = {}
    for f in wal_files(tmp_path):
        scan_wal_file(os.path.join(str(tmp_path), "wal", f), tables)
    assert tables["u1"][1] == (1, b"")
    assert tables["u1"][2][1] == big


def test_default_max_batch_matches_reference():
    assert DEFAULT_MAX_BATCH == 8192  # ra.hrl:192


def test_rtw1_files_remain_readable(tmp_path):
    """Read-compat: files with the v1 magic (payload-only crc) still
    recover — a format bump must not orphan existing data dirs."""
    import struct as _struct
    import zlib

    from ra_tpu.log.wal import _ENT_HDR, _REG, MAGIC_V1

    path = os.path.join(str(tmp_path), "old.wal")
    buf = bytearray(MAGIC_V1)
    uid = b"legacy"
    buf += _REG.pack(1, 1, len(uid)) + uid
    for i in range(1, 6):
        payload = b"old-%d" % i
        buf += _ENT_HDR.pack(2, 1, i, 7, len(payload))
        buf += _struct.pack("<I", zlib.crc32(payload))
        buf += payload
    open(path, "wb").write(bytes(buf))
    tables = {}
    scan_wal_file(path, tables)
    assert sorted(tables["legacy"]) == [1, 2, 3, 4, 5]
    assert tables["legacy"][3] == (7, b"old-3")


def test_same_uid_reregistration_reroutes_confirms(tmp_path):
    """same_uid_different_process: a restarted server re-registers its
    uid; confirms from then on go to the NEW notify identity, and the
    fresh writer's sequence check tolerates the restart (writer_id =
    {UId, pid} in the reference, ra_log_wal.erl:44-51)."""
    wal = Wal(str(tmp_path), sync_mode=0)
    try:
        old, new = Sink(), Sink()
        wal.register("u1", old)
        wal.write("u1", 1, 1, b"a")
        wal.write("u1", 2, 1, b"b")
        assert old.wait_hi(2)
        n_old = len(old.confirms)
        # "process restart": same uid, new incarnation
        wal.register("u1", new)
        # the restarted writer resumes ABOVE its durable tail; a fresh
        # sequence is accepted without a resend signal
        wal.write("u1", 3, 1, b"c")
        assert new.wait_hi(3)
        assert len(old.confirms) == n_old, "stale identity kept confirms"
        assert not new.resends
    finally:
        wal.close()


def test_recover_empty_wal_file(tmp_path):
    """recover_empty: a zero-entry (header-only or 0-byte) WAL file
    recovers to an empty table without complaint."""
    wal = Wal(str(tmp_path), sync_mode=0)
    wal.close()                       # leaves the fresh file, no records
    # plus a truly empty stray file
    open(os.path.join(str(tmp_path), "wal", "99999999.wal"),
         "wb").close()
    wal2 = Wal(str(tmp_path), sync_mode=0)
    try:
        assert wal2.recovered_table("anyuid") == {}
        s = Sink()
        wal2.register("u1", s)
        wal2.write("u1", 1, 1, b"x")
        assert s.wait_hi(1)
    finally:
        wal2.close()


def test_recover_overwrite_in_same_batch(tmp_path):
    """recover_overwrite_in_same_batch: an overwrite landing in the SAME
    fsync batch as the overwritten entries must recover to the final
    values only.  The same-batch property is scheduling-dependent, so
    it is asserted (batches == 1) with retries on fresh directories."""
    for attempt in range(5):
        d = os.path.join(str(tmp_path), f"try{attempt}")
        wal = Wal(d, sync_mode=0)
        s = Sink()
        wal.register("u1", s)
        # queue all writes before the batch thread drains: same batch
        wal.write("u1", 1, 1, b"one")
        wal.write("u1", 2, 1, b"two")
        wal.write("u1", 3, 1, b"three")
        wal.write("u1", 2, 2, b"TWO'")     # overwrite invalidates 3
        wal.write("u1", 3, 2, b"THREE'")
        assert s.wait_hi(3)
        one_batch = wal.counters["batches"] == 1
        wal.close()
        if one_batch:
            str_d = d
            break
    else:
        pytest.skip("scheduler split the writes across batches 5x")
    wal2 = Wal(str_d, sync_mode=0)
    try:
        table = wal2.recovered_table("u1")
        assert {i: (t, bytes(p)) for i, (t, p) in table.items()} == {
            1: (1, b"one"), 2: (2, b"TWO'"), 3: (2, b"THREE'")}
    finally:
        wal2.close()


def test_rollover_on_entry_limit(tmp_path):
    """roll_over_entry_limit: the file rolls once it holds max_entries
    records, independent of byte size."""
    ranges = []

    class Catcher:
        def accept_ranges(self, r, path):
            ranges.append((dict(r), path))

        def retire(self, uids, files):
            pass

        def mark_deleted(self, uid):
            pass

    wal = Wal(str(tmp_path), sync_mode=0, max_entries=10,
              segment_writer=Catcher())
    try:
        s = Sink()
        wal.register("u1", s)
        for i in range(1, 26):     # 25 tiny records, far under max_size
            wal.write("u1", i, 1, b"x")
        assert s.wait_hi(25)
        wal.flush()
        files = wal_files(tmp_path)
        assert len(files) >= 3, files   # >= two rollovers for 25/10
        assert len(ranges) >= 2, ranges
        # the cap is a hard per-file bound, batch granularity included
        for _r, path in ranges:
            tables: dict = {}
            scan_wal_file(path, tables)
            n = sum(len(t) for t in tables.values())
            assert n <= 10, (path, n)
    finally:
        wal.close()


def test_writer_id_cached_once_per_file(tmp_path):
    """Record density: the uid string is framed ONCE per WAL file (a
    registration record mapping wid -> uid); every entry record carries
    only the u32 wid — the reference's per-file writer-id cache
    (ra_log_wal.erl:404-421).  A new file after rollover re-registers."""
    wal = Wal(str(tmp_path), sync_mode=0)
    sink = Sink()
    uid = "dense_uid_marker"
    wal.register(uid, sink)
    for i in range(1, 201):
        wal.write(uid, i, 1, b"p" * 8)
    wal.flush()
    assert sink.wait_hi(200)
    waldir = os.path.join(str(tmp_path), "wal")
    files = sorted(f for f in os.listdir(waldir) if f.endswith(".wal"))
    assert files
    blob = open(os.path.join(waldir, files[-1]), "rb").read()
    assert blob.count(uid.encode()) == 1, \
        "uid must appear exactly once per file (the wid table), " \
        f"found {blob.count(uid.encode())}"
    # rollover: the NEXT file carries its own registration record
    # (flush first — a roll queued with the write in one batch applies
    # after the batch, so the write would land in the OLD file)
    wal.rollover()
    wal.flush()
    wal.write(uid, 201, 1, b"q" * 8)
    wal.flush()
    assert sink.wait_hi(201)
    files2 = sorted(f for f in os.listdir(waldir) if f.endswith(".wal"))
    newest = open(os.path.join(waldir, files2[-1]), "rb").read()
    assert newest.count(uid.encode()) == 1
    # and recovery resolves entries through the table
    tables: dict = {}
    scan_wal_file(os.path.join(waldir, files2[-1]), tables)
    assert 201 in tables[uid]
    wal.close()
