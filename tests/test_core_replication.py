"""Replication/commit conformance tests — AER paths, quorum arithmetic,
overwrite/truncation, await_condition catch-up, reply modes.  Scenario
shapes follow /root/reference/test/ra_server_SUITE.erl (AER edge cases)."""
from harness import SimCluster

from ra_tpu.core.types import (
    AppendEntriesReply,
    AppendEntriesRpc,
    CommandEvent,
    CommandResult,
    Entry,
    ErrorResult,
    ReplyMode,
    UserCommand,
    WrittenEvent,
)


def test_command_commits_and_applies_everywhere():
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    for v in (1, 2, 3):
        c.command(s1, v)
    assert set(c.machine_states().values()) == {6}
    leader = c.servers[s1]
    # noop + 3 commands
    assert leader.commit_index == 4
    assert leader.last_applied == 4


def test_await_consensus_reply():
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    c.command(s1, 10, from_="client1")
    replies = [r for (sid, r) in c.replies if r.to == "client1"]
    assert len(replies) == 1
    res = replies[0].msg
    assert isinstance(res, CommandResult)
    assert res.reply == 10  # SimpleMachine replies with new state
    assert res.leader == s1


def test_after_log_append_reply_is_immediate():
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    srv = c.servers[s1]
    effs = srv.handle(CommandEvent(
        UserCommand(1, reply_mode=ReplyMode.AFTER_LOG_APPEND),
        from_="client2"))
    replies = [e for e in effs if getattr(e, "to", None) == "client2"]
    assert len(replies) == 1
    assert replies[0].msg.reply is None  # acked before consensus


def test_notify_reply_mode():
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    c.command(s1, 7, correlation="corr-1", notify_to="pid9",
              reply_mode=ReplyMode.NOTIFY)
    notes = [n for (sid, n) in c.notifies if n.to == "pid9"]
    assert notes and notes[0].correlations == (("corr-1", 7),)


def test_commander_redirect_when_not_leader():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    c.command(s2, 1, from_="client3")
    errs = [r for (sid, r) in c.replies if r.to == "client3"]
    assert isinstance(errs[0].msg, ErrorResult)
    assert errs[0].msg.reason == "not_leader"
    assert errs[0].msg.leader == s1


def test_leader_own_fsync_counts_toward_quorum():
    """Commit requires majority of {leader last_written, follower matches}
    (ra_server.erl:2977-2993)."""
    c = SimCluster(3, auto_written=False)
    s1 = c.ids[0]
    # manual written mode: elect requires written events for the noop...
    srv = c.servers[s1]
    # drive election by hand: pre_vote + votes
    from ra_tpu.core.types import ElectionTimeout
    c.handle(s1, ElectionTimeout())
    c.run()
    # leader appended noop but nothing is written anywhere yet
    assert srv.raft_state.value == "leader"
    assert srv.commit_index == 0
    # follower 2 confirms write of idx1 (the noop)
    c.handle(s1, AppendEntriesReply(term=srv.current_term, success=True,
                                    next_index=2, last_index=1,
                                    last_term=srv.current_term,
                                    from_=c.ids[1]))
    # still not committed: leader's own write hasn't been confirmed and
    # only 1 of 3 voters matched... but wait: peer match=1, leader lw=0,
    # other peer=0 -> median=0
    assert srv.commit_index == 0
    # now the leader's own WAL confirms
    srv.log.release_written(1, 1, srv.current_term)
    c._drain_log_events(s1)
    assert srv.commit_index == 1


def test_follower_truncates_conflicting_suffix():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    # seed s3 with entries from a divergent term
    srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s2, prev_log_index=0, prev_log_term=0,
        leader_commit=0,
        entries=(Entry(1, 1, UserCommand(100)), Entry(2, 1, UserCommand(200)))))
    assert srv3.log.last_index_term().index == 2
    # now the real leader (term 2) overwrites from index 1
    srv3.handle(AppendEntriesRpc(
        term=2, leader_id=s1, prev_log_index=0, prev_log_term=0,
        leader_commit=0, entries=(Entry(1, 2, UserCommand(7)),)))
    assert srv3.log.last_index_term() == (1, 2)
    assert srv3.log.fetch(2) is None


def test_follower_gap_enters_await_condition_and_recovers():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    # AER with a prev point far beyond the follower's log
    effs = srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s1, prev_log_index=10, prev_log_term=1,
        leader_commit=10, entries=(Entry(11, 1, UserCommand(1)),)))
    assert srv3.raft_state.value == "await_condition"
    # the reply asks the leader to resend from next_index=1
    sent = [e.msg for e in effs if hasattr(e, "msg")
            and isinstance(e.msg, AppendEntriesReply)]
    assert sent and not sent[0].success
    assert sent[0].next_index == 1
    # leader resends from the start: condition satisfied, entries accepted
    entries = tuple(Entry(i, 1, UserCommand(i)) for i in range(1, 12))
    srv3.handle(AppendEntriesRpc(term=1, leader_id=s1, prev_log_index=0,
                                 prev_log_term=0, leader_commit=11,
                                 entries=entries))
    assert srv3.raft_state.value == "follower"
    assert srv3.log.last_index_term().index == 11


def test_stale_aer_rejected():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    srv2 = c.servers[s2]
    term = srv2.current_term
    effs = srv2.handle(AppendEntriesRpc(term=0, leader_id=s2,
                                        prev_log_index=0, prev_log_term=0,
                                        leader_commit=0))
    replies = [e.msg for e in effs if hasattr(e, "msg")
               and isinstance(e.msg, AppendEntriesReply)]
    assert replies and not replies[0].success
    assert replies[0].term == term


def test_minority_leader_cannot_commit():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.isolate(s1)
    c.command(s1, 42)
    leader = c.servers[s1]
    assert leader.machine_state == 0  # not applied
    assert leader.commit_index == 1   # only the noop from before isolation


def test_new_leader_overwrites_uncommitted_minority_entries():
    """The classic Raft §5.4 scenario: entries replicated to a minority by a
    deposed leader are overwritten by the new majority leader."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.isolate(s1)
    c.command(s1, 42)   # uncommitted on s1 only
    assert c.servers[s1].log.last_index_term().index == 2
    # majority side elects s2
    c.elect(s2)
    assert c.servers[s2].raft_state.value == "leader"
    c.command(s2, 7)
    c.heal()
    # old leader rejoins; next tick of the new leader repairs it
    from ra_tpu.core.types import TickEvent
    c.handle(s2, TickEvent())
    c.run()
    assert c.servers[s1].raft_state.value == "follower"
    assert c.servers[s1].machine_state == 7
    states = c.machine_states()
    assert states[s1] == states[s2] == states[s3] == 7


def test_written_event_for_overwritten_term_is_ignored():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s2, prev_log_index=0, prev_log_term=0,
        leader_commit=0, entries=(Entry(1, 1, UserCommand(1)),)))
    srv3.log.take_events()  # discard the pending written confirm
    # overwrite by newer leader before the WAL confirmed
    srv3.handle(AppendEntriesRpc(
        term=2, leader_id=s1, prev_log_index=0, prev_log_term=0,
        leader_commit=0, entries=(Entry(1, 2, UserCommand(9)),)))
    srv3.log.take_events()
    # stale written event for the old term must not advance last_written
    srv3.handle(WrittenEvent(1, 1, 1))
    assert srv3.log.last_written().index == 0


def test_consistent_query_needs_heartbeat_quorum():
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    c.command(s1, 5)
    c.consistent_query(s1, lambda st: st * 10)
    q = [r for (sid, r) in c.replies if r.to == "qclient"]
    assert q and q[0].msg.reply == 50
