"""Codec format tests (ISSUE 18): the golden byte-exact corpus pin
(layout change without a CODEC_VERSION bump fails here), old-format
decode compatibility (legacy fast-tuple / raw-pickle / v1 corpus),
seeded truncation/bit-flip corruption properties for every record type,
and the WAL batch-run record's torn-tail / crc / old-magic recovery
contract.
"""
import pickle
import random
import struct

import pytest

from ra_tpu.codec import (CODEC_VERSION, CodecError, TAG_FALLBACK,
                          TAG_LEGACY_FAST, TAG_USER, decode_command,
                          decode_user_parts, encode_command,
                          encode_fallback, encode_user)
from ra_tpu.core.types import ReplyMode, UserCommand
from ra_tpu.log.faults import IO
from ra_tpu.log.wal import (MAGIC, MAGIC_V2, _CRC, _ENT, _ENT_HDR,
                            _PAY_HDR, _REG, _RUN_ENT, _RUN_HDR,
                            _entry_crc, _parse_wal_bytes)

# ---------------------------------------------------------------------------
# golden corpus — BYTE-EXACT v1 images.  If any of these pins fails,
# the wire/WAL/segment layout moved: bump CODEC_VERSION, keep the old
# decode branch alive, and append (never rewrite) a new corpus — files
# and peers running the old layout must keep decoding forever.
# ---------------------------------------------------------------------------

GOLDEN_V1 = [
    # (name, encode_user args, pinned image)
    ("raw_notify",
     (b"hello", ReplyMode.NOTIFY, (7, 1),
      ("rnotify", ("10.0.0.1", 5000), 3, 9), None, None),
     bytes.fromhex(
         "0201020105000000120048000100010068656c6c6f060207000000000000"
         "00010000000000000004040800000003726e6f746966791c000000040209"
         "0000000331302e302e302e31090000000188130000000000000900000001"
         "0300000000000000090000000109000000000000000000")),
    ("tuple_data",
     (("set", "k", 1), ReplyMode.AWAIT_CONSENSUS, None, None, None,
      None),
     bytes.fromhex(
         "020101001d00000001000100010001000403040000000373657402000000"
         "036b0900000001010000000000000000000000")),
    ("int_corr",
     (1234, ReplyMode.AFTER_LOG_APPEND, 99, None, None, None),
     bytes.fromhex(
         "0201000009000000090001000100010001d2040000000000000163000000"
         "00000000000000")),
    ("noreply_str",
     ("ping", ReplyMode.NOREPLY, None, None, None, None),
     bytes.fromhex("020103000500000001000100010001000370696e6700000000")),
]


def test_codec_version_is_pinned():
    # layout changes REQUIRE a version bump + a new appended corpus;
    # this pin forces the editor through that checklist
    assert CODEC_VERSION == 1


@pytest.mark.parametrize("name,args,image",
                         [(n, a, i) for n, a, i in GOLDEN_V1])
def test_golden_corpus_byte_exact(name, args, image):
    assert encode_user(*args) == image, \
        f"{name}: USER layout changed — bump CODEC_VERSION and append " \
        "a new corpus generation (old images must keep decoding)"


@pytest.mark.parametrize("name,args,image",
                         [(n, a, i) for n, a, i in GOLDEN_V1])
def test_golden_corpus_decodes(name, args, image):
    data, rm, corr, notify, from_, reply_from = args
    got = decode_command(image)
    assert type(got) is UserCommand
    assert got.data == data and got.reply_mode is rm
    assert got.correlation == corr and got.notify_to == notify
    assert got.from_ == from_ and got.reply_from == reply_from
    # the parts decoder (the wire receiver's trace-attaching path)
    # agrees field-for-field
    assert decode_user_parts(image) == (data, rm, corr, notify, from_,
                                        reply_from)


def test_golden_header_fields():
    # spot-pin the header itself: tag, version, reply-mode codes
    tag, ver, rm, flags, dlen = struct.unpack_from(
        "<BBBBI", GOLDEN_V1[0][2], 0)
    assert (tag, ver, rm, flags, dlen) == (TAG_USER, 1, 2, 1, 5)
    for image, code in ((GOLDEN_V1[1][2], 1), (GOLDEN_V1[2][2], 0),
                        (GOLDEN_V1[3][2], 3)):
        assert image[2] == code     # AWAIT=1, AFTER_LOG_APPEND=0, NOREPLY=3


# ---------------------------------------------------------------------------
# round-trips and demotion rules
# ---------------------------------------------------------------------------

def _mk(data, rm=ReplyMode.NOTIFY, corr=None, notify=None, from_=None,
        reply_from=None):
    return UserCommand(data, rm, corr, notify, from_, reply_from)


def test_encode_command_round_trips_every_shape():
    cases = [
        _mk(b"x" * 1000, corr=(123456789, 42)),
        _mk((1, 2, 3, 4), rm=ReplyMode.AWAIT_CONSENSUS),
        _mk(None, rm=ReplyMode.NOREPLY),
        _mk("utf-8 ☃", corr="corr-id"),
        _mk(b"", corr=0, notify=("rnotify", ("h", 1), 0, 0)),
        _mk((("nested", (1, 2)), b"mix", None), from_="m1",
            reply_from="m2"),
        _mk(tuple(range(300))),          # >255 tuple -> field pickle
        _mk(1 << 100),                   # bignum -> field pickle
    ]
    for cmd in cases:
        img = encode_command(cmd)
        got = decode_command(img)
        assert type(got) is UserCommand
        assert (got.data, got.reply_mode, got.correlation,
                got.notify_to, got.from_, got.reply_from) == \
            (cmd.data, cmd.reply_mode, cmd.correlation, cmd.notify_to,
             cmd.from_, cmd.reply_from)


def test_local_handles_never_leave_the_process():
    # callables/futures are process-local: the image carries None
    cmd = _mk(b"d", notify=lambda *_: None, from_=lambda *_: None)
    got = decode_command(encode_command(cmd))
    assert got.notify_to is None and got.from_ is None


def test_non_user_commands_demote_to_tagged_fallback():
    obj = {"op": "membership", "add": ("m4", ("h", 1))}
    img = encode_command(obj)
    assert img[0] == TAG_FALLBACK and img[1] == CODEC_VERSION
    assert decode_command(img) == obj


def test_oversized_section_demotes_whole_record():
    # a correlation too big for its u16 length field cannot fit USER v1
    big = b"c" * 70000
    assert encode_user(big, ReplyMode.NOTIFY, big, None, None,
                       None) is None
    img = encode_command(_mk(b"d", corr=big))
    assert img[0] == TAG_FALLBACK
    assert decode_command(img).correlation == big


# ---------------------------------------------------------------------------
# legacy decode-only branches (the r06 dirs / mixed-version peers)
# ---------------------------------------------------------------------------

def test_legacy_fast_tuple_frames_decode():
    # the pre-codec durable image: 0x01 + pickle of the field tuple —
    # both the 5-field (pre-reply_from) and 6-field generations
    data, rm, corr = ("set", "k", 1), ReplyMode.NOTIFY, (9, 9)
    notify, from_, reply_from = ("rnotify", ("h", 1), 0, 3), "m2", "m1"
    five = bytes([TAG_LEGACY_FAST]) + pickle.dumps(
        (data, rm.value, corr, from_, notify))
    six = bytes([TAG_LEGACY_FAST]) + pickle.dumps(
        (data, rm.value, corr, from_, notify, reply_from))
    got5 = decode_command(five)
    assert (got5.data, got5.reply_mode, got5.correlation, got5.notify_to,
            got5.from_, got5.reply_from) == \
        (data, rm, corr, notify, from_, None)
    got6 = decode_command(six)
    assert got6.reply_from == reply_from


def test_legacy_raw_pickle_images_decode():
    # oldest generation: a bare pickle (first byte >= 0x80)
    cmd = _mk((1, "two"), corr=7)
    img = pickle.dumps(cmd, protocol=pickle.HIGHEST_PROTOCOL)
    assert img[0] >= 0x80
    got = decode_command(img)
    assert got == cmd


def test_newer_version_records_refuse_loudly():
    img = bytearray(GOLDEN_V1[0][2])
    img[1] = CODEC_VERSION + 1
    with pytest.raises(CodecError, match="newer codec"):
        decode_command(bytes(img))
    fb = bytearray(encode_fallback({"x": 1}))
    fb[1] = CODEC_VERSION + 1
    with pytest.raises(CodecError, match="newer codec"):
        decode_command(bytes(fb))


# ---------------------------------------------------------------------------
# seeded corruption properties: decode NEVER raises anything but
# CodecError, for any truncation or single-bit flip, on any record type
# ---------------------------------------------------------------------------

def _corpus_all_types():
    out = [img for _n, _a, img in GOLDEN_V1]
    out.append(encode_command(_mk(b"payload-bytes" * 7,
                                  corr=(1, 2),
                                  notify=("rnotify", ("h", 1), 0, 5))))
    out.append(encode_fallback({"op": "noop", "why": "corruption-test"}))
    out.append(bytes([TAG_LEGACY_FAST]) + pickle.dumps(
        ((1, 2), ReplyMode.NOTIFY.value, None, None, None, None)))
    out.append(pickle.dumps(_mk(b"old"), protocol=pickle.HIGHEST_PROTOCOL))
    return out


def test_truncation_never_crashes_decode():
    rng = random.Random(18)
    for img in _corpus_all_types():
        cuts = {0, 1, 2, len(img) - 1}
        cuts.update(rng.randrange(len(img)) for _ in range(24))
        for cut in sorted(cuts):
            try:
                decode_command(img[:cut])
            except CodecError:
                pass        # the only sanctioned failure mode


def test_bit_flips_never_crash_decode():
    # a flip in a pickle length field asks the decoder for a multi-GB
    # buffer; cap the heap during the fuzz so those fail FAST (the
    # MemoryError wraps into CodecError like any other decode fault)
    # instead of zeroing gigabytes per sample
    import resource
    soft, hard = resource.getrlimit(resource.RLIMIT_DATA)
    rng = random.Random(181)
    try:
        resource.setrlimit(resource.RLIMIT_DATA, (1 << 31, hard))
        for img in _corpus_all_types():
            positions = {0, 1, len(img) - 1}
            positions.update(rng.randrange(len(img)) for _ in range(48))
            for p in sorted(positions):
                flipped = bytearray(img)
                flipped[p] ^= 1 << rng.randrange(8)
                try:
                    decode_command(bytes(flipped))
                except CodecError:
                    pass    # flips may also decode to a DIFFERENT value
                            # (e.g. inside raw data) — that layer's
                            # integrity is the WAL/segment/frame crc's job
    finally:
        resource.setrlimit(resource.RLIMIT_DATA, (soft, hard))


def test_user_length_mismatch_is_codec_error():
    img = GOLDEN_V1[0][2]
    with pytest.raises(CodecError):
        decode_command(img + b"trailing")
    with pytest.raises(CodecError):
        decode_command(img[:-1])
    with pytest.raises(CodecError):
        decode_command(bytes([0x07]) + img[1:])   # unknown tag


# ---------------------------------------------------------------------------
# WAL batch-run records (RTW3 type 3): torn tails, flipped bits, and
# the old-magic (RTW2) read path
# ---------------------------------------------------------------------------

def _pay_record(payloads):
    """One type-4 payload-table append exactly as Wal._write_batch
    packs it: header + chained crc + length table + concatenated
    images."""
    lens = struct.pack("<%dI" % len(payloads),
                       *[len(p) for p in payloads])
    cat = b"".join(payloads)
    hdr = _PAY_HDR.pack(4, len(payloads), len(lens) + len(cat))
    crc = IO.crc32(cat, IO.crc32(lens, IO.crc32(hdr)))
    return hdr + _CRC.pack(crc) + lens + cat


def _run_parts(wid, entries, intern):
    """(type-4 payload-table bytes, type-3 run bytes) for one batch
    run, exactly as Wal._write_batch packs them; ``intern`` is the
    file-scope payload->slot dict shared across one file's runs (new
    images intern in first-seen order)."""
    new = []
    trips = []
    for i, t, p in entries:
        slot = intern.get(p)
        if slot is None:
            slot = intern[p] = len(intern)
            new.append(p)
        trips.append(_RUN_ENT.pack(i, t, slot))
    tab = b"".join(trips)
    hdr = _RUN_HDR.pack(3, wid, len(entries), len(tab))
    rec = hdr + _CRC.pack(IO.crc32(tab, IO.crc32(hdr))) + tab
    return (_pay_record(new) if new else b""), rec


def _run_record(wid, entries, intern=None):
    pay, rec = _run_parts(wid, entries,
                          {} if intern is None else intern)
    return pay + rec


def _reg_record(wid, uid):
    ub = uid.encode()
    return _REG.pack(1, wid, len(ub)) + ub


def test_run_record_parses_and_is_atomic_on_torn_tail():
    run1 = [(1, 1, b"alpha"), (2, 1, b"beta"), (3, 1, b"gamma")]
    run2 = [(4, 2, b"delta"), (5, 2, b"epsilon")]
    intern: dict = {}
    blob = MAGIC + _reg_record(7, "m1") + _run_record(7, run1, intern) \
        + _run_record(7, run2, intern)
    records, err = _parse_wal_bytes(blob)
    assert err is None
    assert records[0] == ("reg", 7, "m1")
    ents = [r for r in records if r[0] == "ent"]
    assert [(i, t, bytes(p)) for _k, _w, i, t, p in ents] == run1 + run2
    # tear run2 at EVERY byte boundary: run1 always survives whole,
    # run2 lands atomically or not at all
    intern = {}
    base = MAGIC + _reg_record(7, "m1") + _run_record(7, run1, intern)
    pay2, rec2 = _run_parts(7, run2, intern)
    r2 = pay2 + rec2
    for cut in range(len(r2)):
        records, err = _parse_wal_bytes(base + r2[:cut])
        ents = [r for r in records if r[0] == "ent"]
        assert len(ents) == len(run1), cut       # never a partial run2
        if cut > 0 and cut != len(pay2):
            assert err is not None               # damage was reported
        # cut == len(pay2) is the ONE clean boundary inside the pair: a
        # complete payload-table append whose run was lost to the tear.
        # Table growth alone adds no entries, so recovery stays exact —
        # the orphaned images are garbage the next rollover drops


def test_run_record_bit_flip_is_caught_by_crc():
    run1 = [(1, 1, b"alpha"), (2, 1, b"beta")]
    prefix = MAGIC + _reg_record(3, "m2")
    rec = _run_record(3, run1)
    rng = random.Random(7)
    hits = 0
    for _ in range(64):
        p = rng.randrange(len(rec))
        flipped = bytearray(rec)
        flipped[p] ^= 1 << rng.randrange(8)
        records, err = _parse_wal_bytes(prefix + bytes(flipped))
        ents = [r for r in records if r[0] == "ent"]
        # a flip may hit the type byte (unknown record -> clean stop) or
        # anywhere else (crc/table mismatch) — NEVER a silently altered
        # entry set of the same length with different bytes
        if ents:
            assert [(i, t, bytes(pl)) for _k, _w, i, t, pl in ents] == \
                [(i, t, p) for i, t, p in run1]
        else:
            hits += 1
    assert hits > 0


def test_payload_interning_writes_each_image_once():
    # the fan-out cut (ISSUE 18): three co-hosted members writing the
    # same replicated burst share ONE payload-table entry per image —
    # the image bytes appear once in the file, each member's run is
    # 20 bytes/entry of slot triplets
    img = b"shared-payload-image-" * 8
    intern: dict = {}
    blob = MAGIC
    for wid in (1, 2, 3):
        blob += _reg_record(wid, f"m{wid}")
        blob += _run_record(wid, [(1, 1, img), (2, 1, img + b"x")],
                            intern)
    assert blob.count(img + b"x") == 1          # interned, not fanned out
    records, err = _parse_wal_bytes(blob)
    assert err is None
    ents = [r for r in records if r[0] == "ent"]
    assert len(ents) == 6
    for _k, _w, idx, _t, p in ents:
        assert bytes(p) == (img if idx == 1 else img + b"x")


def test_run_slot_out_of_range_stops_recovery():
    # a type-3 run referencing a slot the file never interned is
    # damage, not a silent empty payload
    tab = _RUN_ENT.pack(1, 1, 5)                 # slot 5, empty table
    hdr = _RUN_HDR.pack(3, 2, 1, len(tab))
    rec = hdr + _CRC.pack(IO.crc32(tab, IO.crc32(hdr))) + tab
    records, err = _parse_wal_bytes(MAGIC + _reg_record(2, "m1") + rec)
    assert [r for r in records if r[0] == "ent"] == []
    assert err is not None and "slot" in str(err)


def test_old_magic_rtw2_files_still_recover():
    # an r06-era file: RTW2 magic, per-entry type-2 records only
    def ent2(wid, idx, term, payload):
        hdr = _ENT_HDR.pack(2, wid, idx, term, len(payload))
        return _ENT.pack(2, wid, idx, term, len(payload),
                         _entry_crc(hdr, payload)) + payload
    blob = MAGIC_V2 + _reg_record(1, "old-m1") \
        + ent2(1, 10, 3, b"old-payload-a") + ent2(1, 11, 3, b"old-b")
    records, err = _parse_wal_bytes(blob)
    assert err is None
    assert records == [("reg", 1, "old-m1"),
                       ("ent", 1, 10, 3, b"old-payload-a"),
                       ("ent", 1, 11, 3, b"old-b")]


def test_type2_singles_still_parse_under_rtw3():
    # the single-write path (resend/recovery) still emits type-2 records
    # into RTW3 files; both types interleave in one file
    def ent2(wid, idx, term, payload):
        hdr = _ENT_HDR.pack(2, wid, idx, term, len(payload))
        return _ENT.pack(2, wid, idx, term, len(payload),
                         _entry_crc(hdr, payload)) + payload
    blob = MAGIC + _reg_record(2, "m3") + ent2(2, 1, 1, b"single") \
        + _run_record(2, [(2, 1, b"run-a"), (3, 1, b"run-b")]) \
        + ent2(2, 4, 1, b"single-2")
    records, err = _parse_wal_bytes(blob)
    assert err is None
    idxs = [r[2] for r in records if r[0] == "ent"]
    assert idxs == [1, 2, 3, 4]


def test_codec_images_ride_wal_run_records_unmodified():
    # end-to-end byte identity: a codec image stored through a run
    # record comes back the exact bytes that went in (encode once,
    # relay bytes — the ISSUE 18 contract at the WAL layer)
    img = encode_command(_mk(b"e2e", corr=(5, 6)))
    blob = MAGIC + _reg_record(9, "m1") + _run_record(9, [(1, 1, img)])
    records, err = _parse_wal_bytes(blob)
    assert err is None
    stored = bytes(records[-1][4])
    assert stored == img
    assert decode_command(stored).correlation == (5, 6)
