"""Property-based tests (seeded-random, many trials per property).

Mirrors the reference's two PropEr suites:

* ra_props_SUITE.erl:51-60 — replicated **non-associative** arithmetic:
  clusters fed interleaved commands under adversarial scheduling must
  converge to the same machine state on every replica, and that state
  must equal the sequential fold of the leader's committed log.  A
  non-associative, non-commutative operation makes any ordering or
  duplication divergence observable.

* ra_log_props_SUITE.erl — random command sequences against the real
  durable log (writes, overwrites, rollovers, snapshots, restarts)
  checked against a trivial in-memory model after every step.
"""
import random

import pytest

from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import (CommandEvent, ElectionTimeout, Entry,
                               ServerConfig, ServerId, UserCommand)

from harness import SimCluster


# ---------------------------------------------------------------------------
# property 1: replicated non-associative arithmetic convergence
# ---------------------------------------------------------------------------

def apply_op(state, cmd):
    op, n = cmd
    if op == "add":
        return state + n
    if op == "sub":
        return state - n
    if op == "mul":
        return state * n
    # non-associative, non-commutative integer op; keeps values bounded
    return state // n if n else state


OPS = ("add", "sub", "mul", "div")


def random_cmd(rng):
    return (rng.choice(OPS), rng.randint(0, 9))


def _converge(cluster):
    """Heal, establish a single live leader, and push one barrier command
    until it commits on every replica.  Stale minority leaders linger
    after a heal (no idle heartbeats — INTERNALS.md:291-328), so the
    highest-term leader is the real one and the barrier may need a retry
    when a stale leader absorbs (and loses) it while stepping down."""
    cluster.heal()
    for attempt in range(25):
        cluster.run()
        # a parked member postpones unsatisfying AERs and relies on the
        # await_condition timeout for liveness (the shell's state
        # timeout, armed at server.py:752; ra_server_proc.erl:946-1010)
        # — the harness has no clock, so deliver the timeout explicitly
        # or a member whose nack was lost pre-heal never rejoins (seen
        # at soak seeds 50014/50019)
        for sid, srv in cluster.servers.items():
            if srv.raft_state.value == "await_condition":
                cluster.handle(sid, ElectionTimeout())
        cluster.run()
        leaders = [sid for sid, srv in cluster.servers.items()
                   if srv.raft_state.value == "leader"]
        if not leaders:
            cluster.elect(cluster.ids[attempt % len(cluster.ids)])
            continue
        leader = max(leaders,
                     key=lambda s: cluster.servers[s].current_term)
        cluster.command(leader, ("add", 0))
        cluster.run()
        srv = cluster.servers[leader]
        if srv.raft_state.value != "leader":
            continue  # was stale after all; the barrier died with it
        applied = srv.last_applied
        if applied > 0 and all(s.last_applied == applied
                               for s in cluster.servers.values()):
            return leader
    raise AssertionError("cluster did not converge after heal")


def _sequential_fold(server):
    """Fold the *applied* prefix of a server's log — entries past
    last_applied (an ex-leader's never-committed tail) are not state."""
    state = 0
    for entry in server.log.read_range(1, server.last_applied):
        if isinstance(entry.command, UserCommand):
            state = apply_op(state, entry.command.data)
    return state


@pytest.mark.parametrize("seed", range(8))
def test_replicated_nonassoc_arithmetic_converges(seed):
    rng = random.Random(seed)
    n_members = rng.choice((3, 5))
    cluster = SimCluster(
        n_members,
        machine_factory=lambda: SimpleMachine(
            lambda cmd, st: apply_op(st, cmd), 0))
    cluster.elect(cluster.ids[0])
    sent = 0
    for _ in range(250):
        roll = rng.random()
        if roll < 0.55:
            # deliver one pending message at a random member
            ready = [sid for sid in cluster.ids if cluster.queues[sid]]
            if ready:
                sid = rng.choice(ready)
                cluster.handle(sid, cluster.queues[sid].popleft())
        elif roll < 0.75 and sent < 120:
            leader = cluster.leader()
            if leader is not None:
                cluster.handle(
                    leader, CommandEvent(UserCommand(random_cmd(rng)),
                                         from_=None))
                sent += 1
        elif roll < 0.82:
            # spurious election timeout at a random member
            cluster.handle(rng.choice(cluster.ids), ElectionTimeout())
        elif roll < 0.90:
            a, b = rng.sample(cluster.ids, 2)
            cluster.partition(a, b)
        else:
            cluster.heal()
    leader = _converge(cluster)
    states = set(cluster.machine_states().values())
    assert len(states) == 1, f"replicas diverged: {states}"
    expected = _sequential_fold(cluster.servers[leader])
    assert states == {expected}


@pytest.mark.parametrize("seed", range(4))
def test_convergence_through_repeated_isolation(seed):
    """Repeatedly isolate random members (including leaders mid-command
    burst); the survivors keep committing and everyone converges."""
    rng = random.Random(1000 + seed)
    cluster = SimCluster(5, machine_factory=lambda: SimpleMachine(
        lambda cmd, st: apply_op(st, cmd), 0))
    cluster.elect(cluster.ids[0])
    for _round in range(6):
        victim = rng.choice(cluster.ids)
        cluster.isolate(victim)
        # someone on the majority side must (re)take leadership
        majority = [s for s in cluster.ids if s != victim]
        if cluster.leader() in (victim, None):
            cluster.elect(rng.choice(majority))
        leader = cluster.leader()
        if leader is None or leader == victim:
            cluster.elect(rng.choice(majority))
            leader = cluster.leader()
        for _ in range(rng.randint(1, 8)):
            cluster.command(leader, random_cmd(rng))
        cluster.heal()
        cluster.run()
    leader = _converge(cluster)
    states = set(cluster.machine_states().values())
    assert len(states) == 1
    assert states == {_sequential_fold(cluster.servers[leader])}


# ---------------------------------------------------------------------------
# property 2: durable log vs model under random op sequences
# ---------------------------------------------------------------------------

class LogModel:
    """The obviously-correct in-memory twin of DurableLog."""

    def __init__(self):
        self.entries: dict[int, tuple] = {}   # idx -> (term, payload)
        self.first = 1
        self.last = 0
        self.snap = (0, 0)

    def write(self, idx, term, payload):
        for k in [k for k in self.entries if k >= idx]:
            del self.entries[k]
        self.entries[idx] = (term, payload)
        self.last = idx

    def snapshot(self, idx, term):
        for k in [k for k in self.entries if k <= idx]:
            del self.entries[k]
        self.first = idx + 1
        self.snap = (idx, term)
        self.last = max(self.last, idx)


def _mk_log(system, uid):
    cfg = ServerConfig(server_id=ServerId(uid, "n1"), uid=uid,
                       cluster_name="props",
                       initial_members=(ServerId(uid, "n1"),),
                       machine=SimpleMachine(lambda c, s: s, 0))
    return system.log_factory(cfg)


def _settle(system, log):
    """Make everything queued durable and consume written confirms."""
    system.wal.flush()
    system.segment_writer.await_idle()
    for evt in log.take_events():
        log.handle_written(evt)


def _check(log, model):
    assert log.first_index() == model.first
    lit = log.last_index_term()
    assert lit.index == model.last
    if model.last >= model.first:
        expect_term = (model.entries[model.last][0]
                       if model.last in model.entries else model.snap[1])
        assert lit.term == expect_term
    assert tuple(log.snapshot_index_term()) == model.snap
    for idx in range(model.first, model.last + 1):
        ent = log.fetch(idx)
        assert ent is not None, f"missing idx {idx}"
        term, payload = model.entries[idx]
        assert ent.term == term and ent.command == payload, \
            f"mismatch at {idx}: {(ent.term, ent.command)} != " \
            f"{(term, payload)}"
    # reads outside the live range answer None
    assert log.fetch(model.first - 1) is None
    assert log.fetch(model.last + 1) is None


@pytest.mark.parametrize("seed", range(6))
def test_durable_log_random_ops_match_model(tmp_path, seed):
    from ra_tpu import RaSystem

    rng = random.Random(seed)
    data_dir = str(tmp_path / f"props{seed}")
    system = RaSystem(data_dir, segment_max_count=16)
    uid = f"prop_uid_{seed}"
    log = _mk_log(system, uid)
    model = LogModel()
    term = 1
    try:
        for _step in range(60):
            roll = rng.random()
            if roll < 0.45:
                # append a batch at the tail
                n = rng.randint(1, 5)
                entries = []
                for _ in range(n):
                    idx = model.last + 1 if not entries \
                        else entries[-1].index + 1
                    payload = f"s{seed}-{idx}-t{term}"
                    entries.append(Entry(idx, term, payload))
                log.write(entries)
                for e in entries:
                    model.write(e.index, e.term, e.command)
            elif roll < 0.60 and model.last >= model.first:
                # overwrite: a new term rewrites a random suffix
                term += 1
                idx = rng.randint(model.first, model.last)
                payload = f"s{seed}-{idx}-t{term}"
                log.write([Entry(idx, term, payload)])
                model.write(idx, term, payload)
            elif roll < 0.72:
                system.wal.rollover()
                _settle(system, log)
            elif roll < 0.85 and model.last >= model.first:
                # snapshot at a random durable index
                _settle(system, log)
                idx = rng.randint(model.first, model.last)
                snap_term = model.entries[idx][0]
                log.update_release_cursor(idx, (), 0, {"v": idx})
                model.snapshot(idx, snap_term)
            else:
                # restart the whole log stack and recover
                _settle(system, log)
                system.close()
                system = RaSystem(data_dir, segment_max_count=16)
                log = _mk_log(system, uid)
            _settle(system, log)
            _check(log, model)
        # final restart must reproduce the model exactly
        _settle(system, log)
        system.close()
        system = RaSystem(data_dir, segment_max_count=16)
        log = _mk_log(system, uid)
        _check(log, model)
    finally:
        system.close()


def test_stale_retained_wal_file_does_not_rewind_tail(tmp_path):
    """A WAL file can be RETAINED across a rollover because some other
    uid on the node was unresolved at flush time — while this uid's
    entries from that file were flushed to segments and more entries were
    appended after it.  On recovery the stale file's table overlaps the
    segments with agreeing terms; that overlap must NOT be read as an
    overwrite, or acknowledged entries above it are lost."""
    from ra_tpu import RaSystem

    data_dir = str(tmp_path / "retain")
    system = RaSystem(data_dir, segment_max_count=1024)
    logx = _mk_log(system, "uidX")
    logy = _mk_log(system, "uidY")
    logx.write([Entry(i, 1, f"x{i}") for i in range(1, 11)])
    logy.write([Entry(i, 1, f"y{i}") for i in range(1, 6)])
    _settle(system, logx)
    # simulate a stopped server: Y becomes unresolvable, so the WAL file
    # containing its entries must be kept at rollover while X's entries
    # are drained to segments
    with system._lock:
        system._logs.pop("uidY")
    system.wal.rollover()
    _settle(system, logx)
    # X keeps appending; this lands in (and is flushed from) a later file
    logx.write([Entry(i, 1, f"x{i}") for i in range(11, 21)])
    system.wal.rollover()
    _settle(system, logx)
    system.close()

    system2 = RaSystem(data_dir, segment_max_count=1024)
    logx2 = _mk_log(system2, "uidX")
    try:
        assert logx2.last_index_term().index == 20, \
            "stale retained WAL file rewound the durable tail"
        for i in range(1, 21):
            ent = logx2.fetch(i)
            assert ent is not None and ent.command == f"x{i}"
        # and the uid whose entries lived only in the retained file
        # recovers them from it
        logy2 = _mk_log(system2, "uidY")
        assert logy2.last_index_term().index == 5
        assert logy2.fetch(3).command == "y3"
    finally:
        system2.close()


# ---------------------------------------------------------------------------
# property 4: Raft safety under fuzzed interleavings
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed,n_members",
                         [(s, 3) for s in (11, 23, 37, 59, 101, 151)] +
                         [(s, 5) for s in (71, 83, 127, 140855)])
def test_election_safety_and_log_matching_fuzz(seed, n_members):
    """Figure-3 safety properties under a random schedule of message
    deliveries, drops, partitions, election timeouts, and client
    commands:

    * Election Safety — at most one leader is ever observed per term.
    * Leader Append-Only / Log Matching — committed prefixes agree on
      every pair of members at every observation point.
    * Liveness (after quiescence) — healed cluster converges.
    """
    rng = random.Random(seed)
    c = SimCluster(n_members)
    sids = c.ids
    leaders_by_term: dict = {}

    def observe():
        for sid in sids:
            srv = c.servers[sid]
            if srv.raft_state.value == "leader":
                term = srv.current_term
                prev = leaders_by_term.setdefault(term, sid)
                assert prev == sid, \
                    f"two leaders in term {term}: {prev} and {sid}"
        # applied prefixes agree (State Machine Safety at the apply
        # frontier).  NB: commit_index is not a safe observation point —
        # like the reference, a follower optimistically adopts
        # leader_commit before the AER consistency check, so the field
        # can transiently cover an unvalidated stale suffix; what must
        # never diverge is what machines APPLY.
        for i, a in enumerate(sids):
            for b in sids[i + 1:]:
                sa, sb = c.servers[a], c.servers[b]
                upto = min(sa.last_applied, sb.last_applied)
                for idx in (upto, max(1, upto // 2)):
                    if idx < 1:
                        continue
                    ea, eb = sa.log.fetch(idx), sb.log.fetch(idx)
                    if ea is not None and eb is not None:
                        assert ea.term == eb.term, (a, b, idx)

    c.elect(sids[0])
    for step in range(400):
        roll = rng.random()
        if roll < 0.45:
            c.step()                       # deliver one message
        elif roll < 0.55:
            sid = rng.choice(sids)         # drop one queued message
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.65:
            a, b = rng.sample(sids, 2)     # flip one link
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.8:
            sid = rng.choice(sids)         # spurious election/condition
            srv = c.servers[sid]           # timeout
            if srv.raft_state.value in ("follower", "pre_vote",
                                        "candidate", "await_condition"):
                c.handle(sid, ElectionTimeout())
        else:
            lead = c.leader()              # client traffic
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        observe()

    c.heal()
    # drain to quiescence: ticks drive pipeline resends for replies the
    # fuzz dropped (the reference retries on tick too), timeouts resolve
    # half-finished elections
    from ra_tpu.core.types import TickEvent
    for _ in range(40):
        c.run()
        for sid in sids:
            c.handle(sid, TickEvent())
            # a parked await_condition only exits on its timeout (the
            # deterministic harness has no real timers)
            if c.servers[sid].raft_state.value in (
                    "await_condition", "pre_vote", "candidate") and \
                    rng.random() < 0.4:
                c.handle(sid, ElectionTimeout())
        c.run()
        lead = c.leader()
        if lead is not None and not any(c.queues[s] for s in sids):
            states = c.machine_states()
            if len(set(states.values())) == 1:
                break
        if lead is None:
            c.handle(rng.choice(sids), ElectionTimeout())
    observe()
    lead = c.leader()
    assert lead is not None
    # the healed cluster accepts and converges on fresh traffic
    c.command(lead, 1)
    for _ in range(5):
        for sid in sids:
            c.handle(sid, TickEvent())
        c.run()
    states = c.machine_states()
    assert len(set(states.values())) == 1, states


# ---------------------------------------------------------------------------
# property 5: safety fuzz over REAL durable logs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed,n_members", [(101, 3), (137, 3), (42, 3),
                                             (151, 5), (77, 5)])
def test_safety_fuzz_over_durable_logs(tmp_path, seed, n_members):
    """The interleaving safety fuzz with RaSystem-backed DurableLogs
    instead of the in-memory mock: WAL confirms arrive asynchronously
    from a real batch/fsync thread, exercising the written-event
    protocol (clamping, gaps, stale confirms) under adversarial
    schedules.  Same invariants: one leader per term, applied prefixes
    agree, post-heal convergence — plus a final restart proving the
    durable state recovers."""
    import time as _time

    from ra_tpu.core.types import TickEvent
    from ra_tpu.system import RaSystem

    rng = random.Random(seed)
    system = RaSystem(str(tmp_path), wal_sync_mode=0)
    c = SimCluster(n_members, log_factory=system.log_factory)
    sids = c.ids
    leaders_by_term: dict = {}

    def observe():
        for sid in sids:
            srv = c.servers[sid]
            if srv.raft_state.value == "leader":
                prev = leaders_by_term.setdefault(srv.current_term, sid)
                assert prev == sid, (srv.current_term, prev, sid)
        for i, a in enumerate(sids):
            for b in sids[i + 1:]:
                sa, sb = c.servers[a], c.servers[b]
                upto = min(sa.last_applied, sb.last_applied)
                if upto >= 1:
                    ea, eb = sa.log.fetch(upto), sb.log.fetch(upto)
                    if ea is not None and eb is not None:
                        assert ea.term == eb.term, (a, b, upto)

    def pump_confirms():
        # real WAL: confirms land on the batch thread; surface them
        for sid in sids:
            c._drain_log_events(sid)

    c.elect(sids[0])
    for step in range(200):
        roll = rng.random()
        if roll < 0.4:
            c.step()
        elif roll < 0.5:
            sid = rng.choice(sids)
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.6:
            a, b = rng.sample(sids, 2)
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.72:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in (
                    "follower", "pre_vote", "candidate",
                    "await_condition"):
                c.handle(sid, ElectionTimeout())
        elif roll < 0.78:
            system.wal.flush()          # force a confirm boundary
            pump_confirms()
        else:
            lead = c.leader()
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        pump_confirms()
        observe()

    c.heal()
    deadline = _time.monotonic() + 30
    converged = False
    while _time.monotonic() < deadline and not converged:
        c.run()
        system.wal.flush()
        pump_confirms()
        for sid in sids:
            c.handle(sid, TickEvent())
            if c.servers[sid].raft_state.value == "await_condition":
                c.handle(sid, ElectionTimeout())
        c.run()
        lead = c.leader()
        if lead is None:
            c.handle(rng.choice(sids), ElectionTimeout())
            continue
        states = c.machine_states()
        converged = len(set(states.values())) == 1 and \
            all(c.servers[s].last_applied ==
                c.servers[lead].last_applied for s in sids)
    observe()
    assert converged, c.machine_states()
    final_state = c.machine_states()[sids[0]]
    final_applied = c.servers[sids[0]].last_applied
    system.close()

    # durable recovery: reopen the system, rebuild a server over each
    # log, and check the applied prefix survived (commit re-establishes
    # only after an election, so compare against persisted meta)
    system2 = RaSystem(str(tmp_path), wal_sync_mode=0)
    c2 = SimCluster(n_members, log_factory=system2.log_factory,
                    machine_factory=lambda: SimpleMachine(
                        lambda cmd, st: st + cmd, 0))
    c2.elect(c2.ids[0])
    deadline = _time.monotonic() + 30
    ok = False
    while _time.monotonic() < deadline and not ok:
        c2.run()
        system2.wal.flush()
        for sid in c2.ids:
            c2._drain_log_events(sid)
            c2.handle(sid, TickEvent())
        c2.run()
        lead2 = c2.leader()
        ok = lead2 is not None and \
            c2.servers[lead2].last_applied >= final_applied
    assert ok
    lead2 = c2.leader()
    # the recovered log may legitimately run AHEAD of the pre-shutdown
    # applied frontier: entries accepted-but-uncommitted at close sit on
    # a durable quorum and commit after the restart election.  The
    # invariant is prefix consistency: folding the recovered log up to
    # the old frontier reproduces the old state exactly.
    srv2 = c2.servers[lead2]
    assert srv2.last_applied >= final_applied
    prefix = 0
    for e in srv2.log.read_range(1, final_applied):
        if isinstance(e.command, UserCommand):
            prefix += e.command.data
    assert prefix == final_state, (prefix, final_state)
    system2.close()


# ---------------------------------------------------------------------------
# property 6: safety fuzz with snapshots/truncation in the schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed,n_members",
                         [(s, 3) for s in (7, 8, 19, 43, 230,
                                           # candidate-vs-install wedge:
                                           # stale chunks at a higher-term
                                           # candidate must be refused
                                           # with the candidate's term
                                           401146, 401363, 402692)] +
                         [(61, 5), (89, 5)])
def test_safety_fuzz_with_snapshots(seed, n_members,
                                    require_snapshot=True):
    """The interleaving fuzz with snapshot actions mixed in: leaders
    release their cursor at the applied index (truncating the log), so
    laggards must catch up via chunked snapshot installs racing
    partitions, drops, and elections.  Invariants: one leader per term,
    applied prefixes agree wherever both logs still hold the entry, and
    post-heal convergence with identical machine states."""
    from ra_tpu.core.types import ReleaseCursor, TickEvent

    rng = random.Random(seed)
    c = SimCluster(n_members, snapshot_chunk_size=8)
    sids = c.ids
    leaders_by_term: dict = {}

    def observe():
        for sid in sids:
            srv = c.servers[sid]
            if srv.raft_state.value == "leader":
                prev = leaders_by_term.setdefault(srv.current_term, sid)
                assert prev == sid, (srv.current_term, prev, sid)
        for i, a in enumerate(sids):
            for b in sids[i + 1:]:
                sa, sb = c.servers[a], c.servers[b]
                upto = min(sa.last_applied, sb.last_applied)
                if upto >= 1:
                    ea, eb = sa.log.fetch(upto), sb.log.fetch(upto)
                    if ea is not None and eb is not None:
                        assert ea.term == eb.term, (a, b, upto)

    c.elect(sids[0])
    for step in range(350):
        roll = rng.random()
        if roll < 0.4:
            c.step()
        elif roll < 0.5:
            sid = rng.choice(sids)
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.6:
            a, b = rng.sample(sids, 2)
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.7:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in (
                    "follower", "pre_vote", "candidate",
                    "await_condition"):
                c.handle(sid, ElectionTimeout())
        elif roll < 0.78:
            # snapshot: the leader releases its cursor at last_applied
            # (the release_cursor machine-effect path -> log truncation;
            # laggards now need the chunked install)
            lead = c.leader()
            if lead is not None:
                srv = c.servers[lead]
                if srv.last_applied > srv.log.snapshot_index_term().index:
                    c._process_effects(lead, srv.handle_machine_effect(
                        ReleaseCursor(srv.last_applied,
                                      srv.machine_state)))
        else:
            lead = c.leader()
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        observe()

    c.heal()
    from ra_tpu.core.types import PeerStatus
    for _ in range(60):
        c.run()
        for sid in sids:
            srv = c.servers[sid]
            # chunks dropped by the fuzz can wedge a transfer in
            # SENDING_SNAPSHOT; the production retry is wall-clock
            # (SNAPSHOT_SEND_TIMEOUT_S) and sim time never passes, so
            # age the transfer and let the REAL tick-retry path fire
            for p in srv.cluster.values():
                if p.status == PeerStatus.SENDING_SNAPSHOT:
                    p.snapshot_started = 0.0
            c.handle(sid, TickEvent())
            if srv.raft_state.value == "await_condition":
                c.handle(sid, ElectionTimeout())
        c.run()
        lead = c.leader()
        if lead is None:
            c.handle(rng.choice(sids), ElectionTimeout())
            continue
        states = c.machine_states()
        if len(set(states.values())) == 1 and all(
                c.servers[s].last_applied ==
                c.servers[lead].last_applied for s in sids):
            break
    observe()
    lead = c.leader()
    assert lead is not None
    states = c.machine_states()
    assert len(set(states.values())) == 1, states
    # snapshots actually happened — an anti-vacuity guard for the
    # ANCHORED seeds (chosen to exercise the path); exploration soaks
    # pass require_snapshot=False since a random schedule occasionally
    # never crosses the release-cursor threshold (seen at seed 200691)
    if require_snapshot:
        assert any(c.servers[s].log.snapshot_index_term().index > 0
                   for s in sids), "no snapshot taken during fuzz"



class _WedgeEscape:
    """Model the disaster-recovery runbook for a wedged membership state
    (reachable: a join racing a self-removal can commit a config whose
    quorum includes a permanently terminated member — then no change can
    ever commit and even the leader's own removal hangs; found by seed
    140095).  After ``threshold`` healing cycles with zero progress AND
    a verified wedged configuration, the operator force-shrinks the
    live server with the most advanced log to a single-member cluster
    (quorum of one) — ra:force_shrink_members_to_current_member
    (test_force_shrink.py).  The wedge shape is asserted so a future
    liveness regression (a stall WITHOUT quorum hostage to terminated
    members) still fails the fuzz instead of being silently repaired.
    One intervention per run."""

    def __init__(self, c, sids, threshold: int = 250):
        self.c, self.sids, self.threshold = c, sids, threshold
        self.stale, self.last_prog, self.forced = 0, None, False

    def _live(self):
        return [s for s in self.sids
                if self.c.servers[s].raft_state.value not in
                ("stop", "delete_and_terminate")]

    def _config_is_wedged(self) -> bool:
        """True iff some live server's effective config cannot form a
        quorum from LIVE voters (terminated members hold it hostage)."""
        from ra_tpu.core.types import Membership
        live = set(self._live())
        for s in live:
            cluster = self.c.servers[s].cluster
            voters = [pid for pid, p in cluster.items()
                      if p.membership == Membership.VOTER]
            if not voters:
                continue
            alive = [pid for pid in voters if pid in live]
            if len(alive) < len(voters) // 2 + 1:
                return True
        return False

    def tick(self) -> None:
        c, sids = self.c, self.sids
        prog = tuple(sorted(
            (s.name, c.servers[s].last_applied,
             c.servers[s].commit_index) for s in sids))
        self.stale = self.stale + 1 if prog == self.last_prog else 0
        self.last_prog = prog
        if self.stale < self.threshold or self.forced:
            return
        self.forced = True
        assert self._config_is_wedged(), \
            "healing stalled without a wedged config: liveness bug"
        from ra_tpu.core.types import ForceMemberChangeEvent
        live = [s for s in self._live()
                if c.servers[s].raft_state.value != "await_condition"]
        assert live, "operator intervention with no live servers"

        def rank(s):
            srv = c.servers[s]
            t = srv.log.last_index_term()
            return (t.term, t.index, srv.last_applied)

        c.handle(max(live, key=rank), ForceMemberChangeEvent(from_=None))


# ---------------------------------------------------------------------------
# property 7: safety fuzz with membership changes in the schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [5, 29, 47, 97, 147, 189, 220, 348,
                                  140095, 161122])
def test_safety_fuzz_with_membership_changes(seed):
    """Joins and leaves ('$ra_join'/'$ra_leave' -> '$ra_cluster_change'
    appends, effective on append, one change in flight at a time) racing
    partitions, drops, spurious elections, and client traffic.  A pool
    of five servers starts as a three-member cluster; the fuzz joins
    standbys (voter or promotable) and removes members — including
    sitting leaders, which must step down once their own removal
    commits.  Invariants: at most one leader per term, applied prefixes
    agree, and after healing the final committed membership converges on
    one leader, one cluster view, and one machine state."""
    from ra_tpu.core.types import (JoinCommand, LeaveCommand, Membership,
                                   PeerStatus, TickEvent)

    rng = random.Random(seed)
    c = SimCluster(5, initial_count=3)
    sids = c.ids
    leaders_by_term: dict = {}

    def live_leaders():
        return [sid for sid in sids
                if c.servers[sid].raft_state.value == "leader"]

    def observe():
        for sid in live_leaders():
            srv = c.servers[sid]
            prev = leaders_by_term.setdefault(srv.current_term, sid)
            assert prev == sid, (srv.current_term, prev, sid)
        for i, a in enumerate(sids):
            for b in sids[i + 1:]:
                sa, sb = c.servers[a], c.servers[b]
                upto = min(sa.last_applied, sb.last_applied)
                if upto >= 1:
                    ea, eb = sa.log.fetch(upto), sb.log.fetch(upto)
                    if ea is not None and eb is not None:
                        assert ea.term == eb.term, (a, b, upto)

    c.elect(sids[0])
    for step in range(400):
        roll = rng.random()
        if roll < 0.4:
            c.step()
        elif roll < 0.48:
            sid = rng.choice(sids)
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.56:
            a, b = rng.sample(sids, 2)
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.66:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in (
                    "follower", "pre_vote", "candidate",
                    "await_condition"):
                c.handle(sid, ElectionTimeout())
        elif roll < 0.78:
            lead = c.leader()
            if lead is not None:
                srv = c.servers[lead]
                target = rng.choice(sids)
                stopped = c.servers[target].raft_state.value in (
                    "stop", "delete_and_terminate")
                if rng.random() < 0.5 and target not in srv.cluster \
                        and not stopped:
                    # a self-removed server has terminated; only a
                    # supervisor restart (not modeled in the sim) could
                    # revive it, so the fuzz re-joins live servers only
                    ms = rng.choice((Membership.VOTER,
                                     Membership.PROMOTABLE))
                    c.handle(lead, CommandEvent(
                        JoinCommand(target, membership=ms)))
                elif target in srv.cluster and len(srv.cluster) > 1:
                    c.handle(lead, CommandEvent(LeaveCommand(target)))
        else:
            lead = c.leader()
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        observe()

    # heal + converge on the FINAL committed membership
    c.heal()
    final_members = None
    escape = _WedgeEscape(c, sids)
    for _ in range(600):
        escape.tick()
        c.run()
        for sid in sids:
            srv = c.servers[sid]
            for p in srv.cluster.values():
                if p.status == PeerStatus.SENDING_SNAPSHOT:
                    p.snapshot_started = 0.0
            c.handle(sid, TickEvent())
            # randomized stand-ins for election timers: parked members
            # exit their condition and stuck electors retry — but NOT
            # in lockstep, or a hopeless candidate's term churn forever
            # outruns the viable candidate's pre-vote window (real
            # timers are randomized for exactly this reason)
            st = srv.raft_state.value
            # condition timeouts fire fast (each cycle consumes one
            # stale postponed event before re-parking, so a member
            # needs ~backlog-length kicks before it can stand);
            # elector retries stay slow so rival candidacies cannot
            # run in lockstep
            if (st == "await_condition" and rng.random() < 0.9) or \
                    (st in ("pre_vote", "candidate") and
                     rng.random() < 0.3):
                c.handle(sid, ElectionTimeout())
        c.run()
        lds = live_leaders()
        if not lds:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in ("follower", "pre_vote",
                                                   "candidate"):
                c.handle(sid, ElectionTimeout())
            continue
        lead = max(lds, key=lambda s: c.servers[s].current_term)
        srv = c.servers[lead]
        # live members only: a join racing a self-removal can leave a
        # terminated member in the config; real deployments restart it
        # via supervision, which the sim does not model
        members = [pid for pid in srv.cluster
                   if c.servers[pid].raft_state.value not in
                   ("stop", "delete_and_terminate")]
        if lead not in members:
            continue  # leader's own removal still committing
        la = srv.last_applied
        tail = srv.log.last_index_term()
        if la > 0 and all(
                c.servers[m].last_applied == la and
                c.servers[m].log.last_index_term() == tail
                for m in members):
            states = {m: c.servers[m].machine_state for m in members}
            if len(set(states.values())) == 1:
                final_members = members
                break
    observe()
    assert final_members is not None, "membership fuzz did not converge"
    lead = max(live_leaders(), key=lambda s: c.servers[s].current_term)
    # every final LIVE member agrees on the full committed composition
    lead_cluster = set(c.servers[lead].cluster)
    for m in final_members:
        assert set(c.servers[m].cluster) == lead_cluster, \
            (m, set(c.servers[m].cluster), lead_cluster)


# ---------------------------------------------------------------------------
# property 8: combined chaos — membership + snapshots + partitions
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [3, 17, 31, 53, 113, 162, 374, 446,
                                  1967, 2110, 2677, 2738, 181279])
def test_safety_fuzz_membership_and_snapshots(seed):
    """The two hardest schedules combined: cluster changes (effective on
    append, carried in snapshot metas, install-restored on laggards)
    interleaved with release_cursor truncation, partitions, drops, and
    elections.  Laggards may now learn MEMBERSHIP through a chunked
    snapshot install whose meta cluster is newer than anything in their
    log.  Invariants as before, plus final cluster-view agreement."""
    from ra_tpu.core.types import (JoinCommand, LeaveCommand, Membership,
                                   PeerStatus, ReleaseCursor, TickEvent)

    rng = random.Random(seed)
    c = SimCluster(5, initial_count=3, snapshot_chunk_size=8)
    sids = c.ids
    leaders_by_term: dict = {}

    def live_leaders():
        return [sid for sid in sids
                if c.servers[sid].raft_state.value == "leader"]

    def observe():
        for sid in live_leaders():
            srv = c.servers[sid]
            prev = leaders_by_term.setdefault(srv.current_term, sid)
            assert prev == sid, (srv.current_term, prev, sid)
        for i, a in enumerate(sids):
            for b in sids[i + 1:]:
                sa, sb = c.servers[a], c.servers[b]
                upto = min(sa.last_applied, sb.last_applied)
                if upto >= 1:
                    ea, eb = sa.log.fetch(upto), sb.log.fetch(upto)
                    if ea is not None and eb is not None:
                        assert ea.term == eb.term, (a, b, upto)

    c.elect(sids[0])
    for step in range(400):
        roll = rng.random()
        if roll < 0.38:
            c.step()
        elif roll < 0.46:
            sid = rng.choice(sids)
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.54:
            a, b = rng.sample(sids, 2)
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.62:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in (
                    "follower", "pre_vote", "candidate",
                    "await_condition"):
                c.handle(sid, ElectionTimeout())
        elif roll < 0.7:
            lead = c.leader()
            if lead is not None:
                srv = c.servers[lead]
                if srv.last_applied > srv.log.snapshot_index_term().index:
                    c._process_effects(lead, srv.handle_machine_effect(
                        ReleaseCursor(srv.last_applied,
                                      srv.machine_state)))
        elif roll < 0.8:
            lead = c.leader()
            if lead is not None:
                srv = c.servers[lead]
                target = rng.choice(sids)
                stopped = c.servers[target].raft_state.value in (
                    "stop", "delete_and_terminate")
                if rng.random() < 0.5 and target not in srv.cluster \
                        and not stopped:
                    ms = rng.choice((Membership.VOTER,
                                     Membership.PROMOTABLE))
                    c.handle(lead, CommandEvent(
                        JoinCommand(target, membership=ms)))
                elif target in srv.cluster and len(srv.cluster) > 1:
                    c.handle(lead, CommandEvent(LeaveCommand(target)))
        else:
            lead = c.leader()
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        observe()

    c.heal()
    final_members = None
    escape = _WedgeEscape(c, sids)   # same escape hatch, same gate
    for _ in range(600):
        escape.tick()
        c.run()
        for sid in sids:
            srv = c.servers[sid]
            for p in srv.cluster.values():
                if p.status == PeerStatus.SENDING_SNAPSHOT:
                    p.snapshot_started = 0.0
            c.handle(sid, TickEvent())
            # randomized stand-ins for election timers: parked members
            # exit their condition and stuck electors retry — but NOT
            # in lockstep, or a hopeless candidate's term churn forever
            # outruns the viable candidate's pre-vote window (real
            # timers are randomized for exactly this reason)
            st = srv.raft_state.value
            # condition timeouts fire fast (each cycle consumes one
            # stale postponed event before re-parking, so a member
            # needs ~backlog-length kicks before it can stand);
            # elector retries stay slow so rival candidacies cannot
            # run in lockstep
            if (st == "await_condition" and rng.random() < 0.9) or \
                    (st in ("pre_vote", "candidate") and
                     rng.random() < 0.3):
                c.handle(sid, ElectionTimeout())
        c.run()
        lds = live_leaders()
        if not lds:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in ("follower", "pre_vote",
                                                   "candidate"):
                c.handle(sid, ElectionTimeout())
            continue
        lead = max(lds, key=lambda s: c.servers[s].current_term)
        srv = c.servers[lead]
        members = [pid for pid in srv.cluster
                   if c.servers[pid].raft_state.value not in
                   ("stop", "delete_and_terminate")]
        if lead not in members:
            continue
        la = srv.last_applied
        tail = srv.log.last_index_term()
        if la > 0 and all(
                c.servers[m].last_applied == la and
                c.servers[m].log.last_index_term() == tail
                for m in members):
            states = {m: c.servers[m].machine_state for m in members}
            if len(set(states.values())) == 1:
                final_members = members
                break
    observe()
    assert final_members is not None, \
        "membership+snapshot fuzz did not converge"
    lead = max(live_leaders(), key=lambda s: c.servers[s].current_term)
    lead_cluster = set(c.servers[lead].cluster)
    for m in final_members:
        assert set(c.servers[m].cluster) == lead_cluster, \
            (m, set(c.servers[m].cluster), lead_cluster)


# ---------------------------------------------------------------------------
# property 9: safety fuzz with mixed machine versions
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [13, 41, 67, 97, 211])
def test_safety_fuzz_mixed_machine_versions(seed):
    """A rolling upgrade under chaos: three members run the v1 machine,
    two still run v0, with partitions/drops/elections/commands racing
    the noop version-bump protocol (ra_server.erl:2671-2732).

    Invariants: at most one leader per term; an effective version never
    regresses on any member; v0 members never apply past a v1 bump
    (their apply stalls, :2713-2732); and after healing every v1 member
    converges to one state while stalled v0 members hold exactly the
    pre-bump prefix."""
    from ra_tpu.core.types import PeerStatus, TickEvent

    from test_machine_version import mixed_cluster

    rng = random.Random(seed)
    c = mixed_cluster(5, upgraded=(0, 1, 2))
    sids = c.ids
    v1_members = set(sids[:3])
    leaders_by_term: dict = {}
    eff_seen = {sid: 0 for sid in sids}

    def observe():
        for sid in sids:
            srv = c.servers[sid]
            if srv.raft_state.value == "leader":
                prev = leaders_by_term.setdefault(srv.current_term, sid)
                assert prev == sid, (srv.current_term, prev, sid)
            # effective machine version never regresses
            assert srv.effective_machine_version >= eff_seen[sid], sid
            eff_seen[sid] = srv.effective_machine_version
            # a v0 member must never RUN the v1 machine: its state stays
            # a plain int (the v1 state is a ("v1", ...) tuple)
            if sid not in v1_members:
                assert not isinstance(srv.machine_state, tuple), \
                    (sid, srv.machine_state)

    c.elect(sids[0])
    for step in range(350):
        roll = rng.random()
        if roll < 0.42:
            c.step()
        elif roll < 0.52:
            sid = rng.choice(sids)
            if c.queues[sid]:
                c.queues[sid].popleft()
        elif roll < 0.62:
            a, b = rng.sample(sids, 2)
            if (a, b) in c.dropped:
                c.dropped.discard((a, b))
                c.dropped.discard((b, a))
            else:
                c.partition(a, b)
        elif roll < 0.74:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in (
                    "follower", "pre_vote", "candidate",
                    "await_condition"):
                c.handle(sid, ElectionTimeout())
        else:
            lead = c.leader()
            if lead is not None:
                c.handle(lead, CommandEvent(
                    UserCommand(rng.randrange(1, 9))))
        observe()

    c.heal()
    for _ in range(200):
        c.run()
        for sid in sids:
            srv = c.servers[sid]
            for p in srv.cluster.values():
                if p.status == PeerStatus.SENDING_SNAPSHOT:
                    p.snapshot_started = 0.0
            c.handle(sid, TickEvent())
            st = srv.raft_state.value
            if (st == "await_condition" and rng.random() < 0.9) or \
                    (st in ("pre_vote", "candidate") and
                     rng.random() < 0.3):
                c.handle(sid, ElectionTimeout())
        c.run()
        lds = [s for s in sids
               if c.servers[s].raft_state.value == "leader"]
        if not lds:
            sid = rng.choice(sids)
            if c.servers[sid].raft_state.value in ("follower", "pre_vote",
                                                   "candidate"):
                c.handle(sid, ElectionTimeout())
            continue
        lead = max(lds, key=lambda s: c.servers[s].current_term)
        la = c.servers[lead].last_applied
        if la > 0 and all(c.servers[m].last_applied == la
                          for m in v1_members):
            converged = lead
            break
    else:
        raise AssertionError("version fuzz did not converge")
    observe()
    lead = converged   # the max-term leader — c.leader() could return a
    srv_l = c.servers[lead]  # deposed one still unaware of the new term
    # the bump must have committed (every seed exercises it; a silent
    # version-0 ending would make the rest of the test vacuous)
    assert srv_l.effective_machine_version == 1
    # only a v1 member can lead once the bump committed
    assert lead in v1_members
    states = {m: c.servers[m].machine_state for m in v1_members}
    assert len(set(map(str, states.values()))) == 1, states
    # stalled v0 members hold strictly the pre-bump prefix
    bump = next(i for i, v in srv_l.machine_versions if v == 1)
    for sid in [s for s in sids if s not in v1_members]:
        srv = c.servers[sid]
        if srv.effective_machine_version == 1:
            assert srv.last_applied < bump, (sid, srv.last_applied, bump)
            assert not isinstance(srv.machine_state, tuple)
