"""Lockstep lane-engine tests: batched commit/apply correctness, failure +
election behavior, ring backpressure, write-delay (async WAL) mode."""
import numpy as np
import pytest

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import CounterMachine


def mk(n_lanes=8, n_members=3, **kw):
    return LockstepEngine(CounterMachine(), n_lanes, n_members, **kw)


def test_commands_commit_and_apply_all_members():
    e = mk()
    for _ in range(5):
        e.uniform_step(4, payload_value=2)
    e.uniform_step(0)  # let the last confirms settle
    mac = e.machine_states()
    # every lane committed 20 commands of +2 on every member
    assert mac.shape == (8, 3)
    assert (mac == 40).all()
    assert e.committed_per_lane().min() >= 20


def test_commit_requires_majority():
    e = mk(n_lanes=4, n_members=3)
    e.uniform_step(1)
    # kill both followers of lane 0: no quorum beyond what's committed
    e.fail_member(0, 1)
    e.fail_member(0, 2)
    before = e.committed_per_lane()[0]
    for _ in range(3):
        e.uniform_step(1)
    after = e.committed_per_lane()
    assert after[0] == before  # no quorum -> commit index frozen
    assert (after[1:] >= before + 3).all()  # healthy lanes keep committing


def test_one_follower_down_still_commits():
    e = mk(n_lanes=4, n_members=3)
    e.fail_member(2, 1)
    for _ in range(4):
        e.uniform_step(2, payload_value=3)
    e.uniform_step(0)
    mac = e.machine_states()
    # lane 2 still commits via leader+follower2 (majority of 3)
    assert mac[2, 0] == 8 * 3
    assert mac[2, 2] == 8 * 3
    # the dead member applied nothing new
    assert mac[2, 1] < 8 * 3


def test_election_rotates_leader_and_term():
    e = mk(n_lanes=4, n_members=3)
    e.uniform_step(3)
    assert e.overview(1)["leader_slot"] == 0
    e.fail_member(1, 0)  # kill lane 1's leader
    e.trigger_election([1])
    o = e.overview(1)
    assert o["term"] == 2
    assert o["leader_slot"] in (1, 2)
    # lane 1 keeps committing under the new leader
    before = e.committed_per_lane()[1]
    for _ in range(3):
        e.uniform_step(2)
    e.uniform_step(0)
    assert e.committed_per_lane()[1] > before
    # untouched lane is unaffected
    assert e.overview(0)["term"] == 1


def test_write_delay_models_async_wal():
    e = mk(n_lanes=2, write_delay=1)
    e.uniform_step(5)
    # step 1: appended but nothing confirmed -> no commit
    assert e.committed_per_lane().max() == 0
    e.uniform_step(0)
    # step 2: previous tail confirmed -> committed
    assert e.committed_per_lane().min() == 5


def test_ring_backpressure_drops_excess_cleanly():
    # tiny ring: with apply keeping up the ring never overflows, but a
    # burst beyond headroom must be truncated, not corrupt state
    e = mk(n_lanes=2, ring_capacity=32, max_step_cmds=16)
    for _ in range(10):
        e.uniform_step(16)
    e.uniform_step(0)
    mac = e.machine_states()
    commits = e.committed_per_lane()
    # applied value == committed count (each +1): no loss, no duplication
    assert (mac[:, 0] == commits).all()


def test_recovery_past_ring_horizon_installs_snapshot():
    """A member that was down while the ring recycled its unapplied range
    must come back via snapshot-install (copy from leader), not by applying
    recycled slots — distinct payloads catch silent divergence."""
    import jax.numpy as jnp
    e = mk(n_lanes=1, n_members=3, ring_capacity=32, max_step_cmds=8)
    e.fail_member(0, 1)
    for i in range(20):  # 160 entries >> ring 32, varying payloads
        e.step(jnp.full((1,), 8, jnp.int32),
               jnp.full((1, 8, 1), i + 1, jnp.int32))
    e.recover_member(0, 1)
    for _ in range(3):
        e.uniform_step(0)
    mac = e.machine_states()
    assert mac[0, 1] == mac[0, 0] == mac[0, 2], mac


def test_large_lane_count_smoke():
    e = mk(n_lanes=512, n_members=5)
    for _ in range(3):
        e.uniform_step(8)
    e.uniform_step(0)
    assert e.committed_per_lane().min() >= 24
    assert (e.machine_states()[:, 0] == 24).all()


def test_membership_add_promote_remove_quorum():
    """Per-lane membership: a removed voter leaves the quorum
    denominator, a joined nonvoter does not count until promoted, and a
    promoted member does (ra_server.erl:3218-3293 on the lane engine)."""
    import jax.numpy as jnp
    import numpy as np
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    N, P, K = 4, 5, 4
    eng = LockstepEngine(CounterMachine(), N, P, ring_capacity=128,
                         max_step_cmds=K, donate=False)
    n_new = jnp.full((N,), K, jnp.int32)
    payloads = jnp.ones((N, K, 1), jnp.int32)
    zero = jnp.zeros((N,), jnp.int32)
    zpay = jnp.zeros((N, K, 1), jnp.int32)

    def drain():
        for _ in range(3):
            eng.step(zero, zpay)
        eng.block_until_ready()

    eng.step(n_new, payloads)
    drain()
    base = eng.committed_per_lane()[0]
    assert base > 0

    # remove two voters from lane 0: 3 voters remain -> quorum 2 holds
    eng.remove_member(0, 3)
    eng.remove_member(0, 4)
    eng.step(n_new, payloads)
    drain()
    after_remove = eng.committed_per_lane()[0]
    assert after_remove > base

    # fail one of the remaining three: 2 of 3 active -> still commits
    eng.fail_member(0, 2)
    eng.step(n_new, payloads)
    drain()
    after_fail = eng.committed_per_lane()[0]
    assert after_fail > after_remove

    # fail another: 1 of 3 voters active -> lane 0 stalls, others advance
    eng.fail_member(0, 1)
    before_stall = eng.committed_per_lane().copy()
    eng.step(n_new, payloads)
    drain()
    now = eng.committed_per_lane()
    assert now[0] == before_stall[0], "minority lane must not commit"
    assert now[1] > before_stall[1]

    # dead members stay in the quorum denominator until REMOVED (a
    # leader that lost its majority must not commit); removing one dead
    # voter leaves voters {0,1} with only slot 0 alive -> still stalled
    eng.remove_member(0, 2)
    eng.step(n_new, payloads)
    drain()
    assert eng.committed_per_lane()[0] == before_stall[0]
    # a joining NONVOTER must not restore quorum...
    eng.add_member(0, 3, voter=False)
    eng.step(n_new, payloads)
    drain()
    assert eng.committed_per_lane()[0] == before_stall[0]
    # ...but promoting it does: voters {0,1,3}, alive {0,3} = quorum 2
    eng.promote_member(0, 3)
    eng.step(n_new, payloads)
    drain()
    assert eng.committed_per_lane()[0] > before_stall[0]
    # machine state on the joined member matches the leader's replica
    mac = np.asarray(eng.state.mac)
    leader = int(np.asarray(eng.state.leader_slot)[0])
    assert mac[0, 3] == mac[0, leader]


def test_engine_save_restore_roundtrip(tmp_path):
    """Checkpoint/resume for the lane engine: a fresh engine restored
    from a saved snapshot continues committing from the same state."""
    import jax.numpy as jnp
    import numpy as np
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    N, K = 8, 4
    eng = LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                         max_step_cmds=K, donate=False)
    n_new = jnp.full((N,), K, jnp.int32)
    pay = jnp.ones((N, K, 1), jnp.int32)
    for _ in range(5):
        eng.step(n_new, pay)
    eng.block_until_ready()
    committed = eng.committed_total()
    mac_before = np.asarray(eng.state.mac).copy()
    path = str(tmp_path / "lanes.npz")
    eng.save(path)

    eng2 = LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                          max_step_cmds=K, donate=False)
    eng2.restore(path)
    assert eng2.committed_total() == committed
    assert (np.asarray(eng2.state.mac) == mac_before).all()
    # resumed engine keeps committing
    for _ in range(3):
        eng2.step(n_new, pay)
    eng2.block_until_ready()
    assert eng2.committed_total() > committed
    # geometry mismatch is refused
    import pytest
    bad = LockstepEngine(CounterMachine(), N + 1, 3, ring_capacity=64,
                         max_step_cmds=K, donate=False)
    with pytest.raises(ValueError):
        bad.restore(path)


def test_engine_restore_pre_telemetry_checkpoint(tmp_path):
    """An archive written before LaneState grew the telem pytree (the
    PR5-era index-flattened format) restores with zero-filled
    telemetry: a durable dir must never be stranded behind a health-
    counter format bump."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.engine.lockstep import LaneState, LaneTelemetry
    from ra_tpu.models import CounterMachine

    N, K = 8, 4
    eng = LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                         max_step_cmds=K, donate=False)
    n_new = jnp.full((N,), K, jnp.int32)
    pay = jnp.ones((N, K, 1), jnp.int32)
    for _ in range(5):
        eng.step(n_new, pay)
    eng.block_until_ready()
    path = str(tmp_path / "lanes.npz")
    eng.save(path)

    # rewrite the archive exactly as the pre-telemetry save wrote it:
    # index-flattened a{i} keys (the pre-ISSUE-15 positional format),
    # with the telem leaves dropped and the index gap closed
    n_tel = len(LaneTelemetry._fields)
    tel_at = len(jax.tree.flatten(
        tuple(eng.state[:LaneState._fields.index("telem")]))[0])
    with np.load(path) as z:
        meta = z["__meta__"]
        arrays = []
        for name in LaneState._fields:
            n_leaves = len(jax.tree.flatten(
                getattr(eng.state, name))[0])
            arrays += [z[f"{name}:{j}"] for j in range(n_leaves)]
    legacy = arrays[:tel_at] + arrays[tel_at + n_tel:]
    np.savez(path, __meta__=meta,
             **{f"a{i}": a for i, a in enumerate(legacy)})

    eng2 = LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                          max_step_cmds=K, donate=False)
    eng2.restore(path)
    assert eng2.committed_total() == eng.committed_total()
    assert (np.asarray(eng2.state.mac) == np.asarray(eng.state.mac)).all()
    # telemetry restarts from zero and keeps accumulating
    assert int(np.asarray(eng2.state.telem.steps).sum()) == 0
    eng2.step(n_new, pay)
    eng2.block_until_ready()
    assert int(np.asarray(eng2.state.telem.steps).sum()) == N


def test_engine_restore_schema_defaults_cover_missing_fields(tmp_path):
    """ISSUE 15: the schema-named checkpoint format restores a field
    the archive predates through its CHECKPOINT_FIELD_DEFAULTS entry —
    the PR 6 pre-telemetry special case generalized, so the NEXT
    pytree field addition is covered automatically (rule RA15 pins
    registry parity with LaneState._fields).  A missing REQUIRED field
    and an unknown (newer-schema) field both refuse: consensus state
    is never silently dropped."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.engine.lockstep import (CHECKPOINT_FIELD_DEFAULTS,
                                        LaneState)
    from ra_tpu.models import CounterMachine

    # the static half of the contract, pinned at runtime too: every
    # field has a declared default mode
    assert set(CHECKPOINT_FIELD_DEFAULTS) == set(LaneState._fields)

    N, K = 8, 4
    eng = LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                         max_step_cmds=K, donate=False)
    n_new = jnp.full((N,), K, jnp.int32)
    pay = jnp.ones((N, K, 1), jnp.int32)
    for _ in range(5):
        eng.step(n_new, pay)
    eng.block_until_ready()
    path = str(tmp_path / "lanes.npz")
    eng.save(path)

    def rewrite(drop_prefix=None, add=None):
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files}
        if drop_prefix is not None:
            arrays = {k: v for k, v in arrays.items()
                      if not k.startswith(drop_prefix + ":")}
        if add is not None:
            arrays.update(add)
        out = str(tmp_path / "rewritten.npz")
        np.savez(out, **arrays)
        return out

    def fresh():
        return LockstepEngine(CounterMachine(), N, 3, ring_capacity=64,
                              max_step_cmds=K, donate=False)

    # a "zeros"-defaulted field missing from the archive zero-fills,
    # everything else restores exactly (the old-format-checkpoint
    # shape for ANY future defaultable field, not just telem)
    assert CHECKPOINT_FIELD_DEFAULTS["telem"] == "zeros"
    e2 = fresh()
    e2.restore(rewrite(drop_prefix="telem"))
    assert e2.committed_total() == eng.committed_total()
    assert int(np.asarray(e2.state.telem.steps).sum()) == 0
    e2.step(n_new, pay)
    e2.block_until_ready()
    assert int(np.asarray(e2.state.telem.steps).sum()) == N

    # a required field missing is a corrupt archive: refuse loudly
    with pytest.raises(ValueError, match="required field"):
        fresh().restore(rewrite(drop_prefix="commit"))

    # an archive from a NEWER schema (unknown field) refuses too —
    # silently dropping state is not this layer's call
    with pytest.raises(ValueError, match="unknown schema field"):
        fresh().restore(rewrite(
            add={"lease_ms:0": np.zeros((N,), np.int32)}))


def test_checkpoint_roundtrip_with_zero_leaf_field(tmp_path):
    """Review regression pin (ISSUE 15): a LaneState field whose
    pytree flattens to ZERO leaves (a stateless machine's empty mac)
    writes no archive keys — restore() must treat it as trivially
    satisfied, not as a missing 'require' field refusing a checkpoint
    the very same engine just wrote."""
    import jax.numpy as jnp
    from ra_tpu.core.machine import JitMachine
    from ra_tpu.engine import LockstepEngine

    class StatelessMachine(JitMachine):
        command_spec = ("int32", ())
        reply_spec = ("int32", ())

        def jit_init(self, n_lanes):
            return {}

        def jit_apply(self, meta, command, state):
            return state, jnp.int32(0)

    eng = LockstepEngine(StatelessMachine(), 4, 3, ring_capacity=64,
                         max_step_cmds=4, donate=False)
    path = str(tmp_path / "stateless.npz")
    eng.save(path)
    eng2 = LockstepEngine(StatelessMachine(), 4, 3, ring_capacity=64,
                          max_step_cmds=4, donate=False)
    eng2.restore(path)  # must not raise "missing required field 'mac'"
    assert eng2.committed_total() == 0


def test_committed_lanes_async_readback():
    """Non-blocking readback path used by the bench frontier: the async
    copy must survive buffer donation by subsequent steps and match the
    blocking readback."""
    import numpy as np
    from ra_tpu.models import CounterMachine
    from ra_tpu.engine import LockstepEngine

    eng = LockstepEngine(CounterMachine(), 8, 3, ring_capacity=64,
                         max_step_cmds=4)
    n_new = np.full((8,), 2, np.int32)
    payloads = np.ones((8, 4, 1), np.int32)
    handles = []
    for _ in range(6):
        eng.step(n_new, payloads)
        handles.append(eng.committed_lanes_async())
    eng.block_until_ready()
    assert all(h.is_ready() for h in handles)
    vals = [int(np.asarray(h).astype(np.int64).sum()) for h in handles]
    assert vals == sorted(vals)  # cumulative, monotone
    assert vals[-1] == eng.committed_total()


def test_ring_io_onehot_matches_gather():
    """The MXU one-hot ring IO (split16 exact matmul) must be bit-exact
    vs the along-axis gather path, including negative payloads, noop
    columns, and ring wraparound."""
    import numpy as np
    import jax.numpy as jnp
    from ra_tpu.engine.lockstep import _ring_write, _ring_read_window

    rng = np.random.default_rng(7)
    N, R, K, C = 16, 12, 4, 3
    ring0 = jnp.asarray(rng.integers(-2**31, 2**31 - 1, (N, R, C),
                                     dtype=np.int64).astype(np.int32))
    pay = jnp.asarray(rng.integers(-2**31, 2**31 - 1, (N, K, C),
                                   dtype=np.int64).astype(np.int32))
    leader_last = jnp.asarray(rng.integers(0, 50, N).astype(np.int32))
    n_acc = jnp.asarray(rng.integers(0, K + 1, N).astype(np.int32))
    elect = jnp.asarray(rng.integers(0, 2, N).astype(bool))
    a = _ring_write(ring0, pay, leader_last, n_acc, elect, impl="gather")
    b = _ring_write(ring0, pay, leader_last, n_acc, elect, impl="onehot")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    idx = jnp.asarray(rng.integers(1, 100, (N, 6)).astype(np.int32))
    ra = _ring_read_window(a, idx, impl="gather")
    rb = _ring_read_window(a, idx, impl="onehot")
    np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))


def test_engine_runs_with_onehot_ring_io():
    """Full engine correctness under the MXU ring-IO path (forced on
    CPU): commits and replica convergence match the gather path."""
    import numpy as np
    from ra_tpu.models import CounterMachine
    from ra_tpu.engine import LockstepEngine

    res = {}
    for impl in ("gather", "onehot"):
        eng = LockstepEngine(CounterMachine(), 8, 3, ring_capacity=64,
                             max_step_cmds=4, write_delay=1, ring_io=impl)
        n_new = np.full((8,), 3, np.int32)
        pay = np.ones((8, 4, 1), np.int32)
        for _ in range(10):
            eng.step(n_new, pay)
        eng.fail_member(2, 0)
        eng.trigger_election([2])
        for _ in range(6):
            eng.step(n_new, pay)
        res[impl] = (eng.committed_total(),
                     np.asarray(eng.state.mac).copy())
    assert res["gather"][0] == res["onehot"][0]
    np.testing.assert_array_equal(res["gather"][1], res["onehot"][1])


def test_scan_machine_float_state_exact():
    """The lane-scan trajectory select must be exact for float machine
    state (gather path — a matmul select would 0*Inf-poison)."""
    import numpy as np
    import jax.numpy as jnp
    from ra_tpu.core.machine import JitMachine
    from ra_tpu.engine import LockstepEngine

    class FloatAcc(JitMachine):
        command_spec = ("int32", (1,))
        supports_batch_apply = False

        def jit_init(self, n_lanes):
            return jnp.zeros((n_lanes,), jnp.float32)

        def jit_apply(self, meta, command, state):
            new = state + command[..., 0].astype(jnp.float32) * 0.5
            return new, new

    eng = LockstepEngine(FloatAcc(), 4, 3, ring_capacity=64,
                         max_step_cmds=4, write_delay=1)
    n_new = np.full((4,), 3, np.int32)
    pay = np.ones((4, 4, 1), np.int32)
    for _ in range(8):
        eng.step(n_new, pay)
    st = eng.state
    lane = np.arange(4)
    applied = np.asarray(st.applied)
    mac = np.asarray(st.mac)
    act = np.asarray(st.active)
    for i in range(4):
        for p in range(3):
            if act[i, p]:
                # counter noop entries contribute 0; commands 0.5 each
                assert abs(mac[i, p] - 0.5 * applied[i, p]) < 1e-5, \
                    (i, p, mac[i, p], applied[i, p])
