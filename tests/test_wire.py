"""Wire plane tests (ISSUE 12): framing round-trips, the machine-level
dedup fold, the zero-per-command listener sweep, the at-least-once
client contract (refusal re-key, ascending-id replay, reconnect-storm
recovery, resolve_suspects), the FifoClient verdict unification, and
the connection-ladder acceptance rung — ≥100k concurrent connections
through a durable engine at ≥10x the classic-TCP baseline, with an
exactly-once-observable oracle (the full C1M rung rides ``-m slow``).
"""
import time

import numpy as np
import pytest

from ra_tpu.blackbox import RECORDER
from ra_tpu.engine import LockstepEngine
from ra_tpu.ingress import IngressPlane
from ra_tpu.wire import (DEFER, DUP, OK, REJECT, SHED, SLOW,
                         DedupCounterMachine, LoopbackFleet, WireClient,
                         WireListener)
from ra_tpu.wire import framing
from ra_tpu.wire.soak import run_wire_soak

#: the classic-TCP 3-member cluster baseline (BENCH_CLASSIC_r05); the
#: ISSUE 12 bar is 10x it, end to end through a durable engine
CLASSIC_TCP_BASELINE = 2934.0


def mk_engine(lanes=32, cmds=8, ring=128, slots=64, **kw):
    kw.setdefault("donate", False)
    return LockstepEngine(DedupCounterMachine(slots=slots), lanes, 3,
                          ring_capacity=ring, max_step_cmds=cmds, **kw)


def mk_plane(eng, **kw):
    kw.setdefault("superstep_k", 2)
    kw.setdefault("window_s", 0.0)
    kw.setdefault("soft_credit", 1 << 20)
    kw.setdefault("hard_credit", 1 << 20)
    return IngressPlane(eng, **kw)


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def test_framing_round_trips():
    f = framing.encode_hello("acme/alice", 3, tenants=2,
                             payload_width=3)
    t, body, off = framing.read_frame(f)
    assert t == framing.T_HELLO and off == len(f)
    h = framing.decode_hello(body)
    assert h == {"version": framing.WIRE_VERSION, "tenants": 2,
                 "key": "acme/alice", "n_sessions": 3,
                 "payload_width": 3}
    a = framing.encode_hello_ack(7, 1234, slots=[4, 5, 6],
                                 payload_width=3)
    _t, body, _ = framing.read_frame(a)
    d = framing.decode_hello_ack(body)
    assert d["epoch"] == 7 and d["handle_base"] == 1234
    assert d["payload_width"] == 3
    assert d["slots"].tolist() == [4, 5, 6]
    # ERR: the refusal frame round-trips its code + reason
    e = framing.encode_error(framing.E_PAYLOAD_WIDTH, "width 4 != 3")
    _t, body, _ = framing.read_frame(e)
    err = framing.decode_error(body)
    assert err == {"code": framing.E_PAYLOAD_WIDTH,
                   "message": "width 4 != 3"}
    # data: fixed stride, vectorized both ways
    pay = np.arange(6, dtype=np.int32).reshape(2, 3)
    blob = framing.encode_data([0, 1], [10, 11], pay)
    assert len(blob) == 2 * framing.data_stride(3)
    rec = framing.decode_data(blob, 3)
    assert rec["sess"].tolist() == [0, 1]
    assert rec["seqno"].tolist() == [10, 11]
    assert rec["pay"].tolist() == pay.tolist()
    assert (rec["len"] == framing.data_stride(3) - 4).all()
    # credit: ONE encoder for the verdict surface
    c = framing.encode_credit(1, [0, 2], [5, 6], [OK, SHED])
    _t, body, _ = framing.read_frame(c)
    level, crec = framing.decode_credit(body)
    assert level == 1
    assert crec["sess"].tolist() == [0, 2]
    assert crec["status"].tolist() == [OK, SHED]
    k = framing.encode_ack([1], [99])
    _t, body, _ = framing.read_frame(k)
    arec = framing.decode_ack(body)
    assert arec["acked"].tolist() == [99]
    # partial frames: no complete frame -> None
    assert framing.read_frame(c[:3]) is None
    assert framing.read_frame(c[:-1]) is None


# ---------------------------------------------------------------------------
# machine-level dedup
# ---------------------------------------------------------------------------

def test_dedup_machine_batch_fold_matches_sequential():
    """The vectorized window fold must be EXACTLY order-equivalent to
    the sequential masked apply — duplicates, stale replays and
    inversions inside one fused window included."""
    import jax.numpy as jnp
    mac = DedupCounterMachine(slots=8)
    rng = np.random.default_rng(0)
    for _trial in range(8):
        n, a = 4, 12
        state = {"value": jnp.asarray(rng.integers(0, 5, n), jnp.int32),
                 "seq": jnp.asarray(rng.integers(0, 3, (n, 8)),
                                    jnp.int32)}
        cmds = np.zeros((n, a, 3), np.int32)
        cmds[..., 0] = rng.integers(-1, 9, (n, a))    # incl. bad slots
        cmds[..., 1] = rng.integers(0, 6, (n, a))     # dups + stale
        cmds[..., 2] = rng.integers(1, 5, (n, a))
        mask = rng.random((n, a)) < 0.8
        meta = {"index": jnp.zeros((n, a), jnp.int32),
                "term": jnp.zeros((n, 1), jnp.int32)}
        fast = mac.jit_apply_batch(meta, jnp.asarray(cmds),
                                   jnp.asarray(mask), state)
        slow = mac.sequential_window_fold(meta, jnp.asarray(cmds),
                                          jnp.asarray(mask), state)
        np.testing.assert_array_equal(np.asarray(fast["value"]),
                                      np.asarray(slow["value"]))
        np.testing.assert_array_equal(np.asarray(fast["seq"]),
                                      np.asarray(slow["seq"]))


def test_dedup_machine_host_path_dedups():
    mac = DedupCounterMachine(slots=4)
    state = mac.init({})
    from ra_tpu.core.machine import ApplyMeta
    meta = ApplyMeta(index=1, term=1)
    state, r = mac.apply(meta, (0, 1, 10), state)
    assert r == 10
    state, r = mac.apply(meta, (0, 1, 10), state)   # dup: skipped
    assert r == 10
    state, r = mac.apply(meta, (1, 1, 5), state)    # other slot
    assert r == 15
    state, r = mac.apply(meta, (0, 3, 1), state)    # fresh op
    assert r == 16


# ---------------------------------------------------------------------------
# listener: sweep, rings, protocol errors
# ---------------------------------------------------------------------------

def test_sweep_decodes_rings_into_one_ingress_batch():
    eng = mk_engine(lanes=16, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=None, max_conns=32, ring_bytes=2048)
    fleet = LoopbackFleet(lst, 8, sessions_per_conn=4, key="mux",
                          seed=0)
    assert fleet.n_sessions == 32
    fleet.new_ops(np.arange(32), np.ones(32, np.int32))
    fed = fleet.send_queued()
    assert fed == 32
    swept = lst.sweep()
    assert swept == 32
    fleet.collect()
    assert int((fleet.op_state[:32] == 2).sum()) == 32  # all PLACED
    assert plane.counters["accepted"] == 32
    assert lst.counters["credit_ok"] == 32
    assert lst.counters["sweeps"] == 1
    # drive to commit; acks release the replay window
    plane.pump(force=True)
    plane.settle()
    fleet.collect()
    assert fleet.acked_mask().all()
    assert lst.counters["ack_rows"] > 0
    eng.close()


def test_loopback_feed_backpressure_keeps_tail_queued():
    eng = mk_engine(lanes=4, cmds=4)
    plane = mk_plane(eng)
    stride = framing.data_stride(eng.payload_width)
    lst = WireListener(plane, port=None, max_conns=4,
                       ring_bytes=4 * stride)
    fleet = LoopbackFleet(lst, 1, key="tiny", seed=0)
    fleet.new_ops(np.zeros(10, np.int64), np.ones(10, np.int32))
    fed = fleet.send_queued()
    assert fed == 4                      # bounded ring: 4 records max
    assert len(fleet.queued_ops()) == 6  # tail stays queued (no loss)
    lst.sweep()
    fleet.collect()
    fed2 = fleet.send_queued()
    assert fed2 == 4
    eng.close()


def test_sweep_closes_conns_on_protocol_garbage():
    eng = mk_engine(lanes=4, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=None, max_conns=4, ring_bytes=2048)
    fleet = LoopbackFleet(lst, 2, key="bad", seed=0)
    stride = lst.stride
    garbage = bytes(range(stride))       # wrong len/type columns
    lst.loopback_feed(fleet.conns[:1], garbage, np.array([1]))
    base = len([e for e in RECORDER.events("wire")
                if e[1] == "wire.error"])
    swept = lst.sweep()
    assert swept == 0
    assert lst.counters["protocol_errors"] == 1
    assert lst.counters["conns_closed"] == 1
    assert int(lst.cstate[fleet.conns[0]]) == 0    # slot freed
    # garbage rows are protocol errors, NOT shed verdicts — they must
    # not pollute the credit histogram the bench keys derive from
    assert lst.counters["credit_shed"] == 0
    # the freed slot's ring accounting is CLEAN for its next tenant
    # (a negative rfill here would over-size the reused ring)
    assert int(lst.rfill[fleet.conns[0]]) == 0
    assert (lst.rfill >= 0).all()
    errs = [e for e in RECORDER.events("wire") if e[1] == "wire.error"]
    assert len(errs) >= base + 1
    # a fresh connection REUSING the freed slot works end to end
    fleet2 = LoopbackFleet(lst, 1, key="fresh", seed=1)
    assert int(fleet2.conns[0]) == int(fleet.conns[0])  # slot reused
    fleet2.new_ops(np.zeros(1, np.int64), np.full(1, 7, np.int32))
    assert fleet2.send_queued() == 1
    assert lst.sweep() == 1
    fleet2.collect()
    assert (fleet2.op_state[:1] == 2).all()
    eng.close()


def test_slot_reuse_does_not_cross_close_connections():
    """A disconnected client's key binding dies with its slot: after
    the slot is reused, the old key's reconnect must bind a NEW slot,
    not close the unrelated connection now living in the old one."""
    eng = mk_engine(lanes=8, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=0, max_conns=8, ring_bytes=4096)
    a = WireClient(lst.address, key="a")
    a.close()                          # EOF frees A's slot
    deadline = time.monotonic() + 10.0
    while lst.counters["conns_closed"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    b = WireClient(lst.address, key="b")   # LIFO: reuses A's slot
    a2 = WireClient(lst.address, key="a")  # A reconnects
    assert a2.epoch == 2
    # B is still alive and functional end to end
    b.enqueue(5)
    b.flush()
    _drive(lst, plane, b, want_acked=1)
    assert lst.counters["protocol_errors"] == 0
    lst.close()
    a2.close()
    b.close()
    eng.close()


# ---------------------------------------------------------------------------
# the socket path
# ---------------------------------------------------------------------------

def _drive(lst, plane, cli, *, want_acked, timeout=30.0):
    deadline = time.monotonic() + timeout
    while cli.acked_count() < want_acked:
        cli.flush()
        lst.sweep()
        plane.pump(force=True)
        plane.settle()
        cli.poll()
        assert time.monotonic() < deadline, \
            (cli.acked_count(), want_acked)


def test_socket_client_end_to_end_with_mux_and_reconnect():
    eng = mk_engine(lanes=16, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=0, max_conns=16, ring_bytes=4096)
    cli = WireClient(lst.address, key="acme/alice", n_sessions=3)
    assert cli.epoch == 1 and cli.slots is not None
    for i in range(12):
        cli.enqueue(i + 1, sess=i % 3)
    cli.flush()
    _drive(lst, plane, cli, want_acked=12)
    # reconnect: same key, bumped epoch, unacked window replays (empty
    # here), dedup slots stable
    old_slots = cli.slots.copy()
    cli.reconnect()
    assert cli.epoch == 2
    assert cli.slots.tolist() == old_slots.tolist()
    cli.enqueue(100, sess=0)
    cli.flush()
    _drive(lst, plane, cli, want_acked=13)
    total = int(np.asarray(
        eng.consistent_read(np.arange(16))["value"]).sum())
    assert total == sum(range(1, 13)) + 100
    assert lst.counters["hello_reconnects"] == 1
    lst.close()
    cli.close()
    eng.close()


def test_version_mismatch_refuses_connection():
    import socket
    import struct
    eng = mk_engine(lanes=4, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=0, max_conns=4, ring_bytes=2048)
    sock = socket.create_connection(lst.address, timeout=5.0)
    bad = bytearray(framing.encode_hello("v2-client", 1))
    bad[5] = framing.WIRE_VERSION + 1      # version byte inside HELLO
    sock.sendall(bytes(bad))
    sock.settimeout(5.0)
    # the refusal is LOUD: an ERR frame names the reason, then close
    buf, fr = b"", None
    deadline = time.monotonic() + 5.0
    while fr is None:
        assert time.monotonic() < deadline
        chunk = sock.recv(64)
        if not chunk:
            break
        buf += chunk
        fr = framing.read_frame(buf)
    assert fr is not None and fr[0] == framing.T_ERR
    err = framing.decode_error(fr[1])
    assert err["code"] == framing.E_VERSION
    assert sock.recv(64) == b""            # then the server closed it
    while lst.counters["protocol_errors"] == 0:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    sock.close()
    lst.close()
    eng.close()
    _ = struct  # (layout documented by the slice above)


def test_payload_width_mismatch_refused_with_protocol_error():
    """A client declaring a different DATA column count C must be
    refused at HELLO with a protocol error — NOT accepted and misparsed
    at the first data frame (the mixed-machine listener hazard)."""
    eng = mk_engine(lanes=4, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=0, max_conns=4, ring_bytes=2048)
    assert lst.payload_width == 3
    with pytest.raises(ConnectionError, match="payload_width"):
        WireClient(lst.address, key="wide/c1", payload_width=4)
    deadline = time.monotonic() + 5.0
    while lst.counters["protocol_errors"] == 0:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    # a correctly-declared client on the same listener still connects
    ok = WireClient(lst.address, key="wide/c2",
                    payload_width=lst.payload_width)
    assert ok.epoch == 1
    ok.close()
    lst.close()
    eng.close()


def test_refused_op_rekeys_and_is_not_lost():
    """The at-least-once correctness core: a shed op replayed under a
    stale id would be watermark-skipped; the client re-keys it.  Tiny
    coalescer ring forces the shed."""
    eng = mk_engine(lanes=2, cmds=2, ring=64, slots=8)
    plane = mk_plane(eng, superstep_k=1, capacity=2)
    lst = WireListener(plane, port=None, max_conns=4, ring_bytes=4096)
    fleet = LoopbackFleet(lst, 1, key="shed", seed=0)
    # burst far past the per-lane window capacity: most rows shed
    fleet.new_ops(np.zeros(32, np.int64), np.ones(32, np.int32))
    deadline = time.monotonic() + 30.0
    while fleet.unplaced_count() > 0:
        fleet.send_queued()
        lst.sweep()
        fleet.collect()
        plane.pump(force=True)
        fleet.collect()
        assert time.monotonic() < deadline
    plane.settle()
    fleet.collect()
    assert lst.counters["credit_shed"] > 0          # sheds DID happen
    lane = int(plane.directory.lane[fleet.handles[0]])
    val = int(np.asarray(eng.consistent_read([lane])["value"])[0])
    assert val == 32                                # exactly once each
    assert fleet.acked_mask().all()
    eng.close()


def test_crash_reconnect_replays_exactly_once():
    """A client that crashes WITHOUT draining verdicts or acks:
    reconnect bumps the epoch, the server replays the authoritative
    committed watermarks in the handshake, and the unacked window
    replays under its original ids — the machine dedup absorbs every
    duplicate, so each op applies exactly once."""
    eng = mk_engine(lanes=8, cmds=4, slots=8)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=0, max_conns=8, ring_bytes=4096)
    cli = WireClient(lst.address, key="crash/c1")
    for i in range(6):
        cli.enqueue(i + 1)
    cli.flush()
    deadline = time.monotonic() + 30.0
    while lst.counters["swept_rows"] < 6:
        lst.sweep()
        assert time.monotonic() < deadline
        time.sleep(0.005)
    plane.pump(force=True)
    plane.settle()
    # crash: verdicts + acks never read; redial under the same key
    cli._rx = b""
    cli.close(keep_state=True)
    cli._connect()
    assert cli.epoch == 2
    assert len(cli._queued) == 6      # the whole unacked window replays
    cli.poll()                        # handshake watermark replay
    assert int(cli.watermark[0]) == 6
    _drive(lst, plane, cli, want_acked=6)
    total = int(np.asarray(
        eng.consistent_read(np.arange(8))["value"]).sum())
    assert total == sum(range(1, 7))     # dedup'd: exactly once each
    lst.close()
    cli.close()
    eng.close()


def test_lost_verdict_window_replays_gap_free():
    """The one-batch-per-session flush gate: with verdicts LOST, the
    client refuses to layer new ops above the in-flight window — so a
    crash replay under original ids is a send-order suffix, and even
    shed ops inside the lost window apply exactly once."""
    eng = mk_engine(lanes=2, cmds=2, ring=64, slots=8)
    plane = mk_plane(eng, superstep_k=1, capacity=2)
    lst = WireListener(plane, port=0, max_conns=4, ring_bytes=4096)
    cli = WireClient(lst.address, key="lostv/c1")
    # overload the 2-deep lane window in one burst: the tail SHEDS
    for i in range(8):
        cli.enqueue(i + 1)
    assert cli.flush() == 8
    deadline = time.monotonic() + 30.0
    while lst.counters["swept_rows"] < 8:
        lst.sweep()
        assert time.monotonic() < deadline
        time.sleep(0.005)
    assert lst.counters["credit_shed"] > 0
    plane.pump(force=True)
    plane.settle()
    # the verdicts are LOST (never read).  The gate: new ops must NOT
    # be sent past the in-flight window, or a later commit would make
    # the shed ops' old-id replay watermark-skippable
    cli._rx = b""
    cli.enqueue(100)
    assert cli.flush() == 0          # session busy: held, not sent
    assert cli.pending_count() == 9  # 8 in flight + 1 held
    # crash-reconnect: epoch bump replays the WHOLE unacked window
    # under original ids (a gap-free suffix), watermarks replayed in
    # the handshake
    cli.reconnect()
    _drive(lst, plane, cli, want_acked=9)
    total = int(np.asarray(
        eng.consistent_read(np.arange(2))["value"]).sum())
    assert total == sum(range(1, 9)) + 100   # every op exactly once
    lst.close()
    cli.close()
    eng.close()


# ---------------------------------------------------------------------------
# FifoClient unification (one verdict enum, one encoder)
# ---------------------------------------------------------------------------

def test_fifo_client_speaks_the_shared_verdict_enum():
    """ISSUE 12 satellite: FifoClient's ok→slow→StopSending ladder is
    the wire credit protocol — same enum values, same encoder, and
    the pinned ``blocked_since``/``ingress_rejections`` semantics are
    untouched (their behavior pins live in test_fifo_machine)."""
    from ra_tpu.models import StopSending
    from ra_tpu.models.fifo_client import FifoClient
    assert StopSending.VERDICT == REJECT
    cli = FifoClient.__new__(FifoClient)       # no cluster needed
    cli.pending = {}
    cli.next_seqno = 5
    cli.soft_limit = 2
    cli.max_pending = 4
    cli._applied = type("M", (), {"drain": staticmethod(lambda: [])})()
    assert cli.current_verdict() == OK
    cli.pending = {1: "a", 2: "b"}
    assert cli.current_verdict() == SLOW
    cli.pending = {1: "a", 2: "b", 3: "c", 4: "d"}
    assert cli.current_verdict() == REJECT
    # ONE encoder: the client's episode decodes as a wire credit frame
    t, body, _ = framing.read_frame(cli.credit_frame())
    assert t == framing.T_CREDIT
    _level, rec = framing.decode_credit(body)
    assert rec["status"].tolist() == [REJECT]
    assert rec["seqno"].tolist() == [4]
    # enum names are the single source of the documented strings
    assert framing.STATUS_NAMES[OK] == "ok"
    assert framing.STATUS_NAMES[SLOW] == "slow"
    assert framing.STATUS_NAMES[:6] == ("ok", "slow", "defer",
                                        "reject", "dup", "shed")
    assert (OK, SLOW, DEFER, REJECT, DUP, SHED) == (0, 1, 2, 3, 4, 5)


# ---------------------------------------------------------------------------
# reconnect-storm dedup (single-device AND mesh)
# ---------------------------------------------------------------------------

def _storm_scenario(shard_mesh: bool) -> None:
    from ra_tpu.transport.rpc import FaultPlan, FaultSpec
    eng = mk_engine(lanes=32, cmds=8, ring=256, slots=128)
    if shard_mesh:
        import jax

        from ra_tpu.parallel.mesh import shard_engine_state
        if len(jax.devices()) < 2:
            pytest.skip("single-device backend")
        shard_engine_state(eng)
    plane = mk_plane(eng, superstep_k=2)
    lst = WireListener(plane, port=None, max_conns=512,
                       ring_bytes=4096)
    fleet = LoopbackFleet(lst, 400, sessions_per_conn=2, key="storm",
                          tenants=4, seed=3, max_ops=1 << 16)
    plan = FaultPlan(seed=3, default=FaultSpec(drop=0.1))
    rng = np.random.default_rng(3)
    try:
        requeued = None
        for w in range(8):
            fleet.new_ops(rng.integers(0, fleet.n_sessions, 2000),
                          rng.integers(1, 8, 2000).astype(np.int32))
            fleet.send_queued()
            lst.sweep()
            fleet.collect()
            plane.pump(force=True)
            fleet.collect()
            if w == 4:
                # kill 40% of connections MID-FLIGHT: unswept ring
                # bytes lost, epochs bump, unacked window replays
                # under fresh seqnos
                requeued = fleet.storm(0.4)
        assert requeued is not None and len(requeued) > 0
        deadline = time.monotonic() + 60.0
        while fleet.unplaced_count() > 0:
            fleet.send_queued()
            lst.sweep()
            fleet.collect()
            plane.pump(force=True)
            fleet.collect()
            assert time.monotonic() < deadline
        plane.settle()
        fleet.collect()
        # the oracle: no duplicate machine apply, no lost acked op
        expected = fleet.expected_lane_sums(32)
        got = np.asarray(
            eng.consistent_read(np.arange(32))["value"]).astype(np.int64)
        np.testing.assert_array_equal(got, expected)
        ranked = fleet.op_rank[:fleet.n_ops] >= 0
        assert fleet.acked_mask()[ranked].all()
        # duplicates WERE created and absorbed (the storm replayed
        # placed-but-unacked rows)
        assert lst.counters["swept_rows"] > fleet.n_ops
        assert plane.counters["reconnects"] > 0
    finally:
        plan.unregister()
        eng.close()


def test_reconnect_storm_dedup_single_device():
    _storm_scenario(shard_mesh=False)


def test_reconnect_storm_dedup_sharded_mesh():
    _storm_scenario(shard_mesh=True)


# ---------------------------------------------------------------------------
# the ladder acceptance rung (tier-1 twin; full C1M behind -m slow)
# ---------------------------------------------------------------------------

def test_wire_ladder_100k_conns_durable_beats_10x_classic(tmp_path):
    """The ISSUE 12 acceptance bar, tier-1 scaled: ≥100k concurrent
    connections through a DURABLE engine sustaining ≥10x the
    classic-TCP baseline end to end, bounded per-connection buffers,
    shed fairness, reconnect-storm recovery, exactly-once-observable
    oracle.  One retry absorbs shared-CI weather (the bench tests'
    pattern)."""
    bar = 10 * CLASSIC_TCP_BASELINE
    try:
        res = run_wire_soak(0, conns=100_000, lanes=512, waves=6,
                            wave_ops=50_000, cmds=16, superstep_k=4,
                            durable_dir=str(tmp_path / "w"),
                            wal_shards=2, throughput_bar=bar)
    except AssertionError:  # pragma: no cover — CI load
        res = run_wire_soak(0, conns=100_000, lanes=512, waves=6,
                            wave_ops=50_000, cmds=16, superstep_k=4,
                            durable_dir=str(tmp_path / "w2"),
                            wal_shards=2, throughput_bar=bar)
    assert res["conns"] >= 100_000 and res["durable"]
    assert res["wire_cmds_per_s"] >= bar
    assert res["storm_requeued"] > 0
    assert res["wire_reconnect_recovery_s"] >= 0
    if res["wire_shed_fairness"] >= 0:
        assert res["wire_shed_fairness"] < 3.0


def test_wire_soak_cpu_scaled_with_sockets_and_disk_faults(tmp_path):
    """The C10k-shaped rung, CPU-scaled for tier-1: loopback fleet +
    real-socket side-car, durable with a seeded DiskFaultPlan, storm,
    oracle exact (tools/soak.py --wire runs the full ladder)."""
    res = run_wire_soak(1, conns=4_000, lanes=128, waves=6,
                        wave_ops=8_000, cmds=8, superstep_k=2,
                        socket_conns=4, socket_ops=8,
                        durable_dir=str(tmp_path / "w"),
                        disk_faults=True, wal_shards=2)
    assert res["durable"] and res["socket_conns"] == 4
    assert res["dup_rows_absorbed"] >= 0
    assert res["wire_swept_rows"] > res["ops"] > 0


@pytest.mark.slow
def test_wire_ladder_full_c1m(tmp_path):
    """The full C1M rung: a million concurrent wire connections into
    the coalescer, durable, reconnect storm, exactly-once-observable
    (tools/soak.py --wire --c1m runs the same entry)."""
    res = run_wire_soak(0, conns=1_000_000, lanes=1024, waves=12,
                        wave_ops=500_000, cmds=16, superstep_k=4,
                        ring_records=16,
                        durable_dir=str(tmp_path / "w"), wal_shards=2,
                        throughput_bar=10 * CLASSIC_TCP_BASELINE)
    assert res["conns"] == 1_000_000


def test_recovery_reseeds_dedup_slots_across_generations(tmp_path):
    """Machine state is durable, the session/slot directory is not: a
    listener over a RECOVERED engine must skip the dead generation's
    per-lane dedup slots, or a fresh client's early ops would be
    falsely deduped against a dead client's watermark (found by the
    verify probe, not the soak — the soak never reopens)."""
    from ra_tpu.engine import open_engine
    mac = DedupCounterMachine(slots=64)
    d = str(tmp_path / "w")
    eng = open_engine(mac, d, 16, wal_shards=2, ring_capacity=256,
                      max_step_cmds=8, donate=False)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=None, max_conns=64, ring_bytes=2048)
    f = LoopbackFleet(lst, 32, key="gen1", seed=0)
    f.new_ops(np.arange(32), np.full(32, 3, np.int32))
    f.send_queued()
    lst.sweep()
    f.collect()
    plane.pump(force=True)
    plane.settle()
    expected = f.expected_lane_sums(16)
    eng._dur.flush_all()
    lst.close()
    eng.checkpoint()
    eng.close()
    # reopen under a DIFFERENT shard layout: dedup watermarks recover
    eng2 = open_engine(mac, d, 16, wal_shards=4, ring_capacity=256,
                       max_step_cmds=8, donate=False)
    got = np.asarray(
        eng2.consistent_read(np.arange(16))["value"]).astype(np.int64)
    np.testing.assert_array_equal(got, expected)
    plane2 = mk_plane(eng2)
    lst2 = WireListener(plane2, port=None, max_conns=64,
                        ring_bytes=2048)
    assert (lst2._lane_next > 0).any()   # recovered cursor seeded
    f2 = LoopbackFleet(lst2, 32, key="gen2", seed=1)
    for i in range(32):  # no fresh slot collides with a dead watermark
        lane = int(plane2.directory.lane[f2.handles[i]])
        wm = int(np.asarray(eng2.consistent_read([lane])["seq"])
                 [0][int(f2.slots[i])])
        assert wm == 0, (i, wm)
    f2.new_ops(np.arange(32), np.full(32, 5, np.int32))
    f2.send_queued()
    lst2.sweep()
    f2.collect()
    plane2.pump(force=True)
    plane2.settle()
    f2.collect()
    got2 = np.asarray(
        eng2.consistent_read(np.arange(16))["value"]).astype(np.int64)
    np.testing.assert_array_equal(got2,
                                  expected + f2.expected_lane_sums(16))
    assert f2.acked_mask().all()
    lst2.close()
    eng2.close()


# ---------------------------------------------------------------------------
# observability wiring
# ---------------------------------------------------------------------------

def test_wire_fields_ride_the_observatory():
    from ra_tpu.telemetry import Observatory, parse_prometheus
    eng = mk_engine(lanes=16, cmds=4)
    plane = mk_plane(eng)
    lst = WireListener(plane, port=None, max_conns=32, ring_bytes=2048)
    fleet = LoopbackFleet(lst, 8, key="obs", seed=0)
    fleet.new_ops(np.arange(8), np.ones(8, np.int32))
    fleet.send_queued()
    lst.sweep()
    fleet.collect()
    plane.pump(force=True)
    plane.settle()
    obs = Observatory.for_engine(eng)
    lst.attach(obs)
    try:
        snap = obs.snapshot()
        assert snap["wire"]["swept_rows"] == 8
        assert snap["wire"]["conns"] == 8
        flat = parse_prometheus(obs.prometheus())
        assert flat[("ra_tpu_wire_swept_rows", "")] == 8
        assert ("ra_tpu_wire_credit_ok", "") in flat
        obs.snapshot()
        rates = obs.window_rates()
        assert any(k.startswith("wire_") for k in rates)
    finally:
        obs.close()
    eng.close()


def test_ra_top_renders_wire_panel(tmp_path):
    """ra_top shows the wire tier: record rate over the window, conn
    pool, and the credit-level histogram."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = {"conns": 100_000, "socket_conns": 64, "paused_conns": 2,
            "swept_rows": 1_000, "protocol_errors": 1,
            "credit_ok": 900, "credit_slow": 50, "credit_defer": 0,
            "credit_reject": 10, "credit_dup": 20, "credit_shed": 20}
    t0 = time.time()
    snap0 = {"seq": 1, "ts": t0 - 1.0,
             "engine": {"lanes": 16, "members": 3}, "wire": base}
    snap1 = {"seq": 2, "ts": t0,
             "engine": {"lanes": 16, "members": 3},
             "wire": {**base, "swept_rows": 51_000,
                      "credit_ok": 50_000, "credit_shed": 420}}
    path = str(tmp_path / "obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(snap0) + "\n")
        f.write(json.dumps(snap1) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "wire" in out and "conns=100000" in out
    assert "sock=64" in out and "paused=2" in out
    assert "ok=49100" in out        # window delta, not lifetime total
    assert "shed=400" in out
    assert "errs=1" in out
    assert "rec/s" in out


def test_wire_bench_row_carries_diff_keys():
    """The tail keys feed tools/bench_diff.py: throughput higher-is-
    better; shed rate AND reconnect recovery lower-is-better with 0 a
    healthy baseline (a recovery time APPEARING flags); -1 recovery =
    no storm ran, skipped like the latency sentinels."""
    import tools.bench_diff as bd
    row = {"value": 90_000.0, "wire_cmds_per_s": 90_000.0,
           "wire_shed_rate": 0.0, "wire_reconnect_recovery_s": 0.0}
    worse = {"value": 40_000.0, "wire_cmds_per_s": 40_000.0,
             "wire_shed_rate": 0.4, "wire_reconnect_recovery_s": 2.5}
    res = bd.diff(row, worse, noise_pct=10.0)
    metrics = {f["metric"]: f for f in res["rows"]["headline"]}
    assert metrics["wire_cmds_per_s"]["regression"]
    assert metrics["wire_shed_rate"]["regression"]
    assert metrics["wire_reconnect_recovery_s"]["regression"]
    assert res["regressions"] >= 4
    assert bd.diff(row, row, noise_pct=10.0)["regressions"] == 0
    # -1 sentinel (no storm in that round) is skipped, not compared
    nostorm = {**row, "wire_reconnect_recovery_s": -1.0}
    res = bd.diff(nostorm, worse, noise_pct=10.0)
    metrics = {f["metric"]: f for f in res["rows"]["headline"]}
    assert "wire_reconnect_recovery_s" not in metrics
