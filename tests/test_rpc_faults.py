"""Reliable control-plane RPC under seeded transport faults (ISSUE 2).

Drives node_call through FaultPlans — drop / delay / duplicate /
partition / forced reconnect — over REAL sockets (in-process TcpRouter
pairs), proving:

* retries + receiver-side dedup give every lifecycle verb at-most-once
  execution no matter how many attempts the wire forced (the
  rpc:call-over-distribution contract, ra_server_sup_sup.erl:42-130)
* failures surface as the typed triad (Unreachable / RpcTimeout /
  RemoteError) instead of a silent hang
* the SAME FaultPlan seed replays the same fault schedule, and Raft
  data traffic keeps committing through seeded message drops (the
  wire counterpart of tests/test_engine_chaos.py)
"""
import time

import pytest

import ra_tpu
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerId
from ra_tpu.machines import machine_spec, register_machine
from ra_tpu.node import RaNode
from ra_tpu.transport.rpc import (
    FaultPlan,
    FaultSpec,
    RpcTimeout,
    Unreachable,
)
from ra_tpu.transport.tcp import TcpRouter

register_machine("rpcfaults",
                 lambda: SimpleMachine(lambda c, s: s + c, 0))


@pytest.fixture
def pair():
    """A server router hosting one RaNode + a member-less client router
    that reaches it over real sockets."""
    server = TcpRouter(("127.0.0.1", 0), {})
    node = RaNode("fn1", router=server)
    client = TcpRouter(("127.0.0.1", 0),
                       {"fn1": server.listen_addr})
    yield client, server, node
    node.stop()
    client.stop()
    server.stop()


def test_fault_plan_is_deterministic():
    """Two plans with one seed replay identical decisions per stream;
    a different seed diverges; streams are isolated (draws on one never
    shift another)."""
    spec = FaultSpec(drop=0.3, delay=0.2, duplicate=0.2, reorder=0.1)
    a = FaultPlan(42, default=spec)
    b = FaultPlan(42, default=spec)
    seq_a = [a.decide("p1", "msg") for _ in range(50)]
    # interleave a second stream on plan b only: per-stream RNGs mean
    # p1's schedule must not move
    seq_b = []
    for _ in range(50):
        b.decide("p2", "rpc_req")
        seq_b.append(b.decide("p1", "msg"))
    assert seq_a == seq_b
    c = FaultPlan(43, default=spec)
    assert seq_a != [c.decide("p1", "msg") for _ in range(50)]


def test_fault_spec_limit_bounds_injections():
    plan = FaultPlan(1, by_class={"rpc_resp": FaultSpec(drop=1.0,
                                                        limit=2)})
    acts = [plan.decide("p", "rpc_resp").action for _ in range(5)]
    assert acts == ["drop", "drop", "deliver", "deliver", "deliver"]
    # other classes untouched
    assert plan.decide("p", "msg").action == "deliver"


def test_node_call_completes_under_mixed_chaos(pair):
    """20%% drop + delay + duplicate on every stream: every call still
    completes, and the plan's injection counters prove faults fired."""
    client, server, _node = pair
    plan = FaultPlan(7, default=FaultSpec(drop=0.2, delay=0.1,
                                          duplicate=0.1,
                                          delay_ms=(1, 10)))
    client.set_fault_plan(plan)
    for _ in range(10):
        assert ra_tpu.node_call("fn1", "ping", {}, router=client,
                                timeout=30) == ("pong", "fn1")
    assert client.rpc_counters["rpc_calls"] == 10
    assert sum(plan.counters.values()) > 0, plan.overview()
    # retries happened iff the schedule hit an rpc frame; with seed 7
    # it does (verified: 2 retries, 3 drops) — pin that it recovered
    assert client.rpc_counters["rpc_retries"] >= 1
    assert client.rpc_counters["rpc_timeouts"] == 0


def test_lifecycle_verbs_exactly_once_under_drop_and_reconnect(pair):
    """ISSUE 2 acceptance: a seeded 20%% drop plan + one forced peer
    reconnect + a guaranteed first-response loss; every lifecycle verb
    completes and the receiver's executed/dedup counters prove no verb
    ran twice."""
    client, server, node = pair
    sid = ServerId("m1", "fn1")
    plan = FaultPlan(
        11,
        default=FaultSpec(drop=0.2),
        # force at least one retry/dedup cycle: the first response
        # frame the client sees is dropped, so the sender MUST retry
        # and the receiver MUST answer from its dedup cache
        by_class={"rpc_resp": FaultSpec(drop=1.0, limit=1)})
    client.set_fault_plan(plan)
    executed0 = server.rpc_counters["rpc_requests_executed"]

    started = ra_tpu.start_server("fc", machine_spec("rpcfaults"),
                                  sid, [sid], router=client)
    assert tuple(started) == tuple(sid)
    assert ra_tpu.restart_server(sid, router=client) is not None
    # forced reconnect: the cached connection dies mid-sequence (the
    # peer-restart shape); the next verb must redial and continue
    peer = client.peers.get("fn1")
    assert peer is not None
    client._close_peer(peer)
    ra_tpu.stop_server(sid, router=client)
    assert node.shells.get("m1") is None
    assert ra_tpu.restart_server(sid, router=client) is not None
    assert node.shells.get("m1") is not None
    ra_tpu.force_delete_server(sid, router=client)
    assert node.shells.get("m1") is None
    with pytest.raises(RuntimeError, match="not_found"):
        ra_tpu.restart_server(sid, router=client)

    # exactly-once: 6 verbs arrived at the executor exactly 6 times,
    # however many wire attempts the drops forced
    executed = server.rpc_counters["rpc_requests_executed"] - executed0
    assert executed == 6, server.rpc_counters
    # the forced response loss produced a retry answered from cache
    assert client.rpc_counters["rpc_retries"] >= 1
    assert server.rpc_counters["rpc_dedup_hits"] >= 1
    assert server.rpc_counters["rpc_responses_resent"] >= 1


def test_duplicate_requests_execute_once(pair):
    """Every request frame duplicated on the wire: the dedup cache maps
    the twin onto the original — executions == calls, dedup hits count
    the twins."""
    client, server, _node = pair
    client.set_fault_plan(FaultPlan(
        5, by_class={"rpc_req": FaultSpec(duplicate=1.0)}))
    executed0 = server.rpc_counters["rpc_requests_executed"]
    dedup0 = server.rpc_counters["rpc_dedup_hits"]
    for _ in range(5):
        assert ra_tpu.node_call("fn1", "ping", {}, router=client,
                                timeout=30) == ("pong", "fn1")
    assert server.rpc_counters["rpc_requests_executed"] - executed0 == 5
    # settle-based: the sender returns when the ORIGINAL's response
    # lands, so the last call's duplicate twin may still be in flight —
    # on a loaded box the twin can trail by whole scheduler quanta
    deadline = time.monotonic() + 5.0
    while server.rpc_counters["rpc_dedup_hits"] - dedup0 < 5 and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert server.rpc_counters["rpc_dedup_hits"] - dedup0 >= 5
    # execution stayed at-most-once even after every twin arrived
    assert server.rpc_counters["rpc_requests_executed"] - executed0 == 5


def test_partition_unreachable_then_heal(pair):
    """A plan-level partition goes dark both ways: the detector rules
    the peer down and node_call surfaces Unreachable (not a 60s hang);
    healing restores service on the SAME router."""
    client, server, _node = pair
    assert ra_tpu.node_call("fn1", "ping", {}, router=client,
                            timeout=10) == ("pong", "fn1")
    plan = FaultPlan(3)
    client.set_fault_plan(plan)
    plan.partition("fn1")
    t0 = time.monotonic()
    with pytest.raises(Unreachable):
        ra_tpu.node_call("fn1", "ping", {}, router=client, timeout=4)
    assert time.monotonic() - t0 < 6
    assert client.rpc_counters["rpc_unreachable"] == 1
    plan.heal()
    assert ra_tpu.node_call("fn1", "ping", {}, router=client,
                            timeout=15) == ("pong", "fn1")


def test_timeout_when_peer_alive_but_unresponsive(pair):
    """The server's recv path eats every request while the connection
    stays healthy: the deadline surfaces RpcTimeout (reachable but
    unanswered), not Unreachable."""
    client, server, _node = pair
    server.set_fault_plan(FaultPlan(
        9, by_class={"rpc_req": FaultSpec(drop=1.0)}))
    with pytest.raises(RpcTimeout):
        ra_tpu.node_call("fn1", "ping", {}, router=client, timeout=0.6)
    assert client.rpc_counters["rpc_timeouts"] == 1
    assert client.rpc_counters["rpc_retries"] >= 1


def test_unknown_node_is_unreachable_immediately(pair):
    client, _server, _node = pair
    t0 = time.monotonic()
    with pytest.raises(Unreachable, match="address book"):
        ra_tpu.node_call("ghost", "ping", {}, router=client, timeout=30)
    assert time.monotonic() - t0 < 1.0


def test_local_router_has_no_remote_reach():
    from ra_tpu.node import LocalRouter
    with pytest.raises(Unreachable, match="no RPC transport"):
        ra_tpu.node_call("nowhere", "ping", {}, router=LocalRouter(),
                         timeout=5)


def test_raft_traffic_survives_seeded_message_drops(tmp_path):
    """The data plane under the same FaultPlan machinery: a 3-member
    cluster across three in-process TcpRouters (formed OVER the
    reliable control plane) keeps committing through seeded 10%% drops
    of Raft msg frames on every router — pipeline catch-up recovers
    what the plan eats, exactly the drop-tolerance contract the
    reliable layer does NOT need for data traffic."""
    names = ["fr1", "fr2", "fr3"]
    routers: dict = {}
    nodes: dict = {}
    try:
        for n in names:
            routers[n] = TcpRouter(("127.0.0.1", 0), {})
        books = {n: {m: routers[m].listen_addr for m in names if m != n}
                 for n in names}
        for n in names:
            routers[n].address_book.update(books[n])
            nodes[n] = RaNode(n, router=routers[n])
        sids = [ServerId(f"m_{n}", n) for n in names]
        # start_cluster from fr1's router: fr2/fr3 members start over
        # the reliable RPC control plane (machine specs resolve there)
        started = ra_tpu.start_cluster(
            "fchaos", machine_spec("rpcfaults"), sids,
            router=routers["fr1"], election_timeout_ms=200,
            tick_interval_ms=100)
        assert set(started) == set(sids)
        for n in names:
            routers[n].set_fault_plan(FaultPlan(
                17, by_class={"msg": FaultSpec(drop=0.1)}))
        total = 0
        deadline = time.monotonic() + 90
        sent = 0
        while sent < 15 and time.monotonic() < deadline:
            try:
                r = ra_tpu.process_command(sids[0], 1,
                                           router=routers["fr1"],
                                           timeout=15)
            except (TimeoutError, RuntimeError):
                continue
            total = r.reply
            sent += 1
        assert sent == 15, (sent, total)
        assert total == 15
        # every plan injected something — the run really was degraded
        assert any(routers[n].fault_plan.counters.get("drop", 0) > 0
                   for n in names)
    finally:
        for n in names:
            if n in nodes:
                nodes[n].stop()
            if n in routers:
                routers[n].stop()


def test_batched_replication_at_most_once_under_dup_reorder():
    """ISSUE 13: cumulative-ack batches keep at-most-once apply.  A
    3-member TCP cluster replicates multi-entry AppendEntries frames
    while every MEMBER router's FaultPlan duplicates and reorders msg
    frames — duplicated batch frames re-deliver whole AER batches and
    reordered ones arrive out of order, so the follower's
    drop-existing/catch-up machinery and the leader's cumulative
    match-index acks are both exercised.  The counter total must equal
    EXACTLY the number of commands sent: a double-applied batch would
    overshoot, a lost one undershoot."""
    import threading

    names = ["bd1", "bd2", "bd3"]
    routers: dict = {}
    nodes: dict = {}
    client = None
    try:
        for n in names:
            routers[n] = TcpRouter(("127.0.0.1", 0), {})
        books = {n: {m: routers[m].listen_addr for m in names if m != n}
                 for n in names}
        for n in names:
            routers[n].address_book.update(books[n])
            nodes[n] = RaNode(n, router=routers[n])
        sids = [ServerId(f"m_{n}", n) for n in names]
        started = ra_tpu.start_cluster(
            "bdchaos", machine_spec("rpcfaults"), sids,
            router=routers["bd1"], election_timeout_ms=300,
            tick_interval_ms=100)
        assert set(started) == set(sids)
        # the client stays clean: the chaos targets REPLICATION frames
        # (AER batches + replies between members), not command ingress
        client = TcpRouter(("127.0.0.1", 0),
                           {n: routers[n].listen_addr for n in names})
        res = None
        deadline = time.monotonic() + 60
        while res is None and time.monotonic() < deadline:
            try:
                res = ra_tpu.process_command(sids[0], 0, router=client,
                                             timeout=10)
            except TimeoutError:
                pass
        assert res is not None, "no leader over TCP"
        leader = res.leader
        for n in names:
            routers[n].set_fault_plan(FaultPlan(
                23, by_class={"msg": FaultSpec(duplicate=0.3,
                                               reorder=0.3)}))
        notified = []
        nlock = threading.Lock()

        def on_notify(batch):
            with nlock:
                notified.extend(c for c, _r in batch)

        N = 400
        for i in range(N):
            ra_tpu.pipeline_command(leader, 1, correlation=("bd", i),
                                    notify_to=on_notify, router=client,
                                    trace_ctx=False)
        # settle: all N acked (the chaos only delays/duplicates frames,
        # it drops nothing, so every command eventually applies)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with nlock:
                if len(notified) >= N:
                    break
            time.sleep(0.05)
        with nlock:
            acked = len(set(notified))
            n_noti = len(notified)
        assert acked == N, (acked, n_noti)
        # no correlation notified twice (cumulative acks never re-apply)
        assert n_noti == N, n_noti
        # exactly-once apply: the counter saw each command's +1 ONCE,
        # despite duplicated/reordered AER batch frames on the wire
        for n in names:
            r = ra_tpu.local_query(ServerId(f"m_{n}", n),
                                   lambda s: s, router=routers[n],
                                   timeout=10)
            assert r.reply == N, (n, r.reply)
        # the plans really injected (the run was degraded)
        assert any(
            routers[n].fault_plan.counters.get("duplicate", 0) +
            routers[n].fault_plan.counters.get("reorder", 0) > 0
            for n in names), {
                n: dict(routers[n].fault_plan.counters) for n in names}
    finally:
        if client is not None:
            client.stop()
        for n in names:
            if n in nodes:
                nodes[n].stop()
            if n in routers:
                routers[n].stop()
