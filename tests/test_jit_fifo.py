"""JitFifoMachine — device-path FIFO semantics, differential-tested against
the host FifoMachine oracle (models/fifo.py) and a plain-Python fold, and
run under the lane engine and the classic replicated path."""
import jax
import jax.numpy as jnp
import numpy as np

import ra_tpu
from ra_tpu.core.machine import ApplyMeta
from ra_tpu.core.types import ServerId
from ra_tpu.engine import LockstepEngine
from ra_tpu.models import FifoMachine, JitFifoMachine
from ra_tpu.models.jit_fifo import query_depth
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader

META = {"index": jnp.int32(1), "term": jnp.int32(1)}


def apply_seq(m, state, cmds):
    replies = []
    for cmd in cmds:
        cmd = list(cmd) + [0] * (3 - len(cmd))   # pad to [op, a, b]
        state, r = m.jit_apply(META, jnp.asarray(cmd, jnp.int32), state)
        replies.append(int(r))
    return state, replies


def ready_window(state):
    """(value, delivery_count) list in FIFO order from the device state."""
    head, tail = int(state["head"]), int(state["tail"])
    Q = np.asarray(state["buf"]).shape[-1]
    buf, dc = np.asarray(state["buf"]), np.asarray(state["dc"])
    return [(int(buf[i % Q]), int(dc[i % Q])) for i in range(head, tail)]


def checked_out(state):
    """Multiset of (value, delivery_count) currently unsettled."""
    ids = np.asarray(state["co_id"])
    vals, dcs = np.asarray(state["co_val"]), np.asarray(state["co_dc"])
    return sorted((int(v), int(d))
                  for i, v, d in zip(ids, vals, dcs) if i >= 0)


def test_scripted_semantics():
    m = JitFifoMachine(capacity=4, checkout_slots=2)
    st = {k: v[0] for k, v in m.jit_init(1).items()}

    # enqueue 3, dequeue settled pops in order
    st, r = apply_seq(m, st, [[1, 10], [1, 11], [1, 12], [2, 0]])
    assert r == [1, 1, 1, 10]
    assert ready_window(st) == [(11, 0), (12, 0)]

    # unsettled dequeue hands out msg ids 0,1; table then full
    st, r = apply_seq(m, st, [[3, 0], [3, 0]])
    assert r == [0, 1]
    st, _ = apply_seq(m, st, [[1, 13]])
    st, r = apply_seq(m, st, [[3, 0]])
    assert r == [-3]  # checkout table full
    assert checked_out(st) == [(11, 0), (12, 0)]

    # settle one, return the other (redelivery count bumps, goes to front)
    st, r = apply_seq(m, st, [[4, 0], [5, 1], [4, 1]])
    assert r == [1, 1, 0]  # settle ok, return ok, settle of returned id fails
    assert ready_window(st) == [(12, 1), (13, 0)]
    assert checked_out(st) == []

    # unknown ids are rejected; empty dequeue replies -1
    st, r = apply_seq(m, st, [[4, 99], [5, 99], [6, 0], [2, 0], [3, 0]])
    assert r == [0, 0, 2, -1, -1]

    # queue-full enqueue rejected
    st, r = apply_seq(m, st, [[1, 1], [1, 2], [1, 3], [1, 4], [1, 5]])
    assert r == [1, 1, 1, 1, -2]

    # noop leaves state untouched
    st2, r = apply_seq(m, st, [[0, 0]])
    assert r == [0]
    for k in st:
        assert np.array_equal(np.asarray(st[k]), np.asarray(st2[k])), k


def fifo_fold(cmds, Q, K):
    """Plain-Python oracle of the encoded op semantics.  Ready entries are
    (mid, val, dc); returns re-insert sorted by enqueue ticket.  Capacity
    bounds LIVE messages (ready + checked out) so requeues never
    overflow — the machine's documented contract."""
    ready: list = []
    co: dict = {}
    next_id = next_mid = 0
    for op, arg in cmds:
        if op == 1 and len(ready) + len(co) < Q:
            ready.append((next_mid, arg, 0))
            next_mid += 1
        elif op == 2 and ready:
            ready.pop(0)
        elif op == 3 and ready and len(co) < K:
            co[next_id] = ready.pop(0)
            next_id += 1
        elif op == 4:
            co.pop(arg, None)
        elif op == 5 and arg in co:
            m, v, d = co.pop(arg)
            ready.append((m, v, d + 1))
            ready.sort()
        elif op == 6:
            ready.clear()
    return ([(v, d) for (_m, v, d) in ready],
            sorted((v, d) for (_m, v, d) in co.values()))


def test_randomized_vs_python_oracle():
    rng = np.random.default_rng(7)
    m = JitFifoMachine(capacity=8, checkout_slots=3)
    st = {k: v[0] for k, v in m.jit_init(1).items()}
    cmds = []
    outstanding = []
    for i in range(400):
        roll = rng.integers(0, 10)
        if roll < 4:
            cmd = (1, int(rng.integers(0, 1000)))
        elif roll < 6:
            cmd = (2, 0)
        elif roll < 8:
            cmd = (3, 0)
        elif outstanding and roll == 8:
            cmd = (4, outstanding[rng.integers(0, len(outstanding))])
        elif outstanding:
            cmd = (5, outstanding[rng.integers(0, len(outstanding))])
        else:
            cmd = (6, 0) if rng.integers(0, 20) == 0 else (1, i)
        st, r = apply_seq(m, st, [list(cmd)])
        if cmd[0] == 3 and r[0] >= 0:
            outstanding.append(r[0])
        elif cmd[0] in (4, 5) and r[0] == 1:
            outstanding.remove(cmd[1])
        cmds.append(cmd)
    want_ready, want_co = fifo_fold(cmds, 8, 3)
    assert ready_window(st) == want_ready
    assert checked_out(st) == want_co


def test_differential_vs_host_fifo_machine():
    """The device machine's observable queue state tracks the host
    FifoMachine oracle on a shared random workload.

    Alignment notes: host unsettled dequeues go through a one-shot "once"
    consumer; a host "return" auto-redelivers the returned message to that
    consumer (ra_fifo checkout loop), so the harness issues a matching
    device unsettled dequeue after every return."""
    rng = np.random.default_rng(11)
    host = FifoMachine()
    hstate = host.init({})
    dev = JitFifoMachine(capacity=64, checkout_slots=16)
    dstate = {k: v[0] for k, v in dev.jit_init(1).items()}
    cid = ("tag", "pid1")
    idx = 0

    def h_apply(cmd):
        nonlocal hstate, idx
        idx += 1
        hstate, reply, _eff = host.apply(
            ApplyMeta(index=idx, term=1), cmd, hstate)
        return reply

    def d_apply(cmd):
        nonlocal dstate
        dstate, r = dev.jit_apply(META, dev.encode_command(cmd), dstate)
        return r

    # outstanding: list of (host_msg_id, dev_msg_id) pairs
    outstanding = []
    for i in range(300):
        roll = rng.integers(0, 12)
        if roll < 5:
            v = int(rng.integers(0, 10_000))
            h_apply(("enqueue", None, None, v))
            assert int(d_apply(("enqueue", v))) == 1
        elif roll < 7:
            hr = h_apply(("checkout", ("dequeue", "settled"), cid))
            dr = int(d_apply(("dequeue", "settled")))
            if hr == ("dequeue", "empty"):
                assert dr == -1
            else:
                assert dr == hr[1][1]  # same value in FIFO order
        elif roll < 9 and len(outstanding) < 12:
            hr = h_apply(("checkout", ("dequeue", "unsettled"), cid))
            dr = int(d_apply(("dequeue", "unsettled")))
            if hr == ("dequeue", "empty"):
                assert dr == -1
            else:
                outstanding.append((hr[1][0], dr))
        elif roll == 9 and outstanding:
            hid, did = outstanding.pop(rng.integers(0, len(outstanding)))
            h_apply(("settle", (hid,), cid))
            assert int(d_apply(("settle", did))) == 1
        elif roll == 10 and outstanding:
            hid, did = outstanding.pop(rng.integers(0, len(outstanding)))
            con = hstate.consumers.get(cid)
            ids_before = set(con.checked_out) if con else set()
            ids_before.discard(hid)
            h_apply(("return", (hid,), cid))
            assert int(d_apply(("return", did))) == 1
            # the host auto-redelivers the front message iff the consumer
            # regained credit (ra_fifo checkout loop); mirror any actual
            # redelivery with an explicit device unsettled dequeue
            con = hstate.consumers.get(cid)
            new_ids = (set(con.checked_out) - ids_before) if con else set()
            if new_ids:
                new_hid = new_ids.pop()
                new_did = int(d_apply(("dequeue", "unsettled")))
                assert new_did >= 0
                outstanding.append((new_hid, new_did))
        elif rng.integers(0, 30) == 0 and not outstanding:
            h_apply(("purge",))
            d_apply(("purge",))

        # continuous alignment: ready window (values + delivery counts)
        hready = [(raw, h["delivery_count"])
                  for (_i, h, raw) in hstate.messages.values()]
        assert ready_window(dstate) == hready
        hco = sorted(
            (raw, h["delivery_count"])
            for con in hstate.consumers.values()
            for (_mid, _idx, h, raw) in con.checked_out.values())
        assert checked_out(dstate) == hco


def test_engine_replicas_match_oracle():
    """Under the lane engine every member of every lane folds the same
    command order (FIFO ops do not commute — exercises the scan path)."""
    rng = np.random.default_rng(5)
    N, K, STEPS = 8, 4, 8
    m = JitFifoMachine(capacity=32, checkout_slots=4)
    eng = LockstepEngine(m, N, 3, ring_capacity=128, max_step_cmds=K,
                         donate=False)
    lane_cmds = [[] for _ in range(N)]
    for _ in range(STEPS):
        payloads = np.zeros((N, K, 3), np.int32)
        for lane in range(N):
            for k in range(K):
                op = int(rng.integers(1, 4))  # enqueue / deq-s / deq-u
                arg = int(rng.integers(0, 100)) if op == 1 else 0
                payloads[lane, k] = (op, arg, 0)
                lane_cmds[lane].append((op, arg))
        eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(payloads))
    for _ in range(4):
        eng.step(jnp.zeros((N,), jnp.int32), jnp.zeros((N, K, 3), jnp.int32))
    eng.block_until_ready()
    mac = {k: np.asarray(v) for k, v in eng.state.mac.items()}  # [N,P,...]
    for lane in range(N):
        want_ready, want_co = fifo_fold(lane_cmds[lane], 32, 4)
        for member in range(3):
            st = {k: v[lane, member] for k, v in mac.items()}
            assert ready_window(st) == want_ready, (lane, member)
            assert checked_out(st) == want_co, (lane, member)


def test_same_machine_on_classic_path():
    router = LocalRouter()
    nodes = [RaNode(f"jfn{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"jf{i}", f"jfn{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("jfifo", lambda: JitFifoMachine(capacity=16),
                             sids, router=router)
        leader = await_leader(router, sids)
        assert ra_tpu.process_command(
            leader, ("enqueue", 41), router=router).reply == 1
        assert ra_tpu.process_command(
            leader, ("enqueue", 42), router=router).reply == 1
        mid = ra_tpu.process_command(
            leader, ("dequeue", "unsettled"), router=router).reply
        assert mid >= 0
        assert ra_tpu.process_command(
            leader, ("settle", mid), router=router).reply == 1
        assert ra_tpu.process_command(
            leader, ("dequeue", "settled"), router=router).reply == 42
        res = ra_tpu.consistent_query(leader, query_depth, router=router)
        assert res.reply == 0
    finally:
        for n in nodes:
            n.stop()


# -- consumer semantics (round-5 depth: credit / cancel / down) -------------

def test_scripted_consumer_credit_cancel_down():
    """attach/checkout/credit/cancel/down against ra_fifo's consumer
    model (ra_fifo.erl:254-368): per-consumer credit caps unsettled
    checkouts; cancel and down requeue owned messages at their original
    ticket position with delivery_count+1."""
    m = JitFifoMachine(capacity=8, checkout_slots=4, consumer_slots=2)
    st = {k: v[0] for k, v in m.jit_init(1).items()}

    st, r = apply_seq(m, st, [[1, 10], [1, 11], [1, 12], [1, 13]])
    assert r == [1, 1, 1, 1]
    # unknown consumer cannot check out
    st, r = apply_seq(m, st, [[10, 7, 0]])
    assert r == [-4]
    # attach pid 7 with credit 2; pid 9 with credit 1; table then full
    st, r = apply_seq(m, st, [[7, 7, 2], [7, 9, 1], [7, 8, 1]])
    assert r == [1, 1, -4]
    # pid 7 checks out two (ids 0,1), third refused on credit
    st, r = apply_seq(m, st, [[10, 7, 0], [10, 7, 0], [10, 7, 0]])
    assert r == [0, 1, -5]
    # pid 9 takes one; its second refused on credit
    st, r = apply_seq(m, st, [[10, 9, 0], [10, 9, 0]])
    assert r == [2, -5]
    assert ready_window(st) == [(13, 0)]
    # raising pid 9's credit unlocks another checkout
    st, r = apply_seq(m, st, [[11, 9, 2], [10, 9, 0]])
    assert r == [1, 3]
    # settle frees credit: pid 7 settles id 0, can check out again (empty)
    st, r = apply_seq(m, st, [[4, 0, 0], [10, 7, 0]])
    assert r == [1, -1]
    # cancel pid 7: its one remaining checkout (11) requeues at rank
    st, r = apply_seq(m, st, [[8, 7, 0]])
    assert r == [1]
    assert ready_window(st) == [(11, 1)]
    # canceled consumer is gone; re-attach claims a slot again
    st, r = apply_seq(m, st, [[10, 7, 0], [7, 7, 1]])
    assert r == [-4, 1]
    # down pid 9: both its checkouts (12, 13) requeue in ticket order
    st, r = apply_seq(m, st, [[9, 9, 0]])
    assert r == [2]
    assert ready_window(st) == [(11, 1), (12, 1), (13, 1)]
    # down of an unknown pid is a no-op reply 0
    st, r = apply_seq(m, st, [[9, 99, 0]])
    assert r == [0]


def test_interleaved_return_and_cancel_ordering():
    """A canceled consumer's messages merge into a ready window that
    already contains returned (low-ticket) messages — the rank insert
    must interleave, not prepend (the host's sorted rebuild)."""
    m = JitFifoMachine(capacity=8, checkout_slots=4, consumer_slots=2)
    st = {k: v[0] for k, v in m.jit_init(1).items()}
    st, r = apply_seq(m, st, [[1, 20], [1, 21], [1, 22],
                              [7, 5, 3], [10, 5, 0], [10, 5, 0],
                              [3, 0, 0]])
    assert r == [1, 1, 1, 1, 0, 1, 2]
    # anon row holds 22 (id 2); pid 5 holds 20 (id 0) and 21 (id 1).
    # Return 21, then cancel pid 5: 20 must land BEFORE 21.
    st, r = apply_seq(m, st, [[5, 1, 0], [8, 5, 0]])
    assert r == [1, 1]
    assert ready_window(st) == [(20, 1), (21, 1)]
    # the anonymous checkout (22) is untouched by the cancel
    assert checked_out(st) == [(22, 0)]


def test_drop_head_overflow_policy():
    """overflow="drop_head": a full queue admits the new message by
    discarding the oldest ready one (quorum-queue max-length drop-head);
    n_dropped counts the losses; reject stays the default."""
    m = JitFifoMachine(capacity=3, checkout_slots=2, overflow="drop_head")
    st = {k: v[0] for k, v in m.jit_init(1).items()}
    st, r = apply_seq(m, st, [[1, 10], [1, 11], [1, 12], [1, 13], [1, 14]])
    assert r == [1, 1, 1, 1, 1]
    assert ready_window(st) == [(12, 0), (13, 0), (14, 0)]
    assert int(st["n_dropped"]) == 2
    # full via checkouts with a ready message: drop-head still admits
    st, r = apply_seq(m, st, [[3, 0, 0], [3, 0, 0], [2, 0, 0], [1, 15]])
    assert r == [0, 1, 14, 1]
    st, r = apply_seq(m, st, [[1, 16]])   # live = 2 co + 1 ready = full
    assert r == [1]                        # drops ready 15
    assert ready_window(st) == [(16, 0)]
    assert int(st["n_dropped"]) == 3
    # capacity entirely consumed by checkouts: nothing ready to drop ->
    # reject even under drop_head
    m2 = JitFifoMachine(capacity=2, checkout_slots=2, overflow="drop_head")
    st2 = {k: v[0] for k, v in m2.jit_init(1).items()}
    st2, r = apply_seq(m2, st2, [[1, 10], [1, 11], [3, 0], [3, 0], [1, 12]])
    assert r == [1, 1, 0, 1, -2]
    with np.testing.assert_raises(Exception):
        JitFifoMachine(overflow="bogus")


import pytest


@pytest.mark.parametrize("seed", [23, 101, 404, 777])
def test_differential_consumers_vs_host_fifo_machine(seed):
    """Two registered consumers with distinct credits, random
    settle/return/cancel/down/credit traffic: the device machine tracks
    the host FifoMachine oracle exactly.  Host auto-consumers are PUSH
    (delivery effects); the device is PULL — each host delivery is
    mirrored as a device checkout(pid) in host pop order (ascending
    msg_in_id, the order _deliver_ready drains the window)."""
    rng = np.random.default_rng(seed)
    host = FifoMachine()
    hstate = host.init({})
    dev = JitFifoMachine(capacity=64, checkout_slots=16, consumer_slots=4)
    dstate = {k: v[0] for k, v in dev.jit_init(1).items()}
    idx = 0
    PIDS = (1, 2)
    cids = {p: ("t", p) for p in PIDS}
    # host msg_id -> device msg_id per consumer, kept in sync
    id_map: dict = {p: {} for p in PIDS}
    attached: dict = {p: False for p in PIDS}

    def h_apply(cmd):
        nonlocal hstate, idx
        idx += 1
        hstate, reply, _eff = host.apply(
            ApplyMeta(index=idx, term=1), cmd, hstate)
        return reply

    def d_apply(cmd):
        nonlocal dstate
        dstate, r = dev.jit_apply(META, dev.encode_command(cmd), dstate)
        return int(r)

    def snapshot_checked():
        return {p: dict(hstate.consumers[cids[p]].checked_out)
                if cids[p] in hstate.consumers else {} for p in PIDS}

    def mirror_new_deliveries(before):
        """Issue a device checkout(pid) for every message the host just
        pushed, in ascending msg_in_id order."""
        new = []
        for p in PIDS:
            now = snapshot_checked()[p]
            for hid, entry in now.items():
                if hid not in before[p]:
                    new.append((entry[0], p, hid))   # (msg_in_id, pid, hid)
        for _mid, p, hid in sorted(new):
            did = d_apply(("checkout", p))
            assert did >= 0, (p, hid, did)
            id_map[p][hid] = did

    for i in range(350):
        before = snapshot_checked()
        roll = rng.integers(0, 14)
        if roll < 5:
            v = int(rng.integers(0, 10_000))
            h_apply(("enqueue", None, None, v))
            assert d_apply(("enqueue", v)) == 1
        elif roll < 7:
            p = int(rng.choice(PIDS))
            credit = int(rng.integers(1, 4))
            h_apply(("checkout", ("auto", credit), cids[p]))
            if not attached[p]:
                assert d_apply(("attach", p, credit)) == 1
                attached[p] = True
            else:
                assert d_apply(("credit", p, credit)) == 1
        elif roll < 9:
            p = int(rng.choice(PIDS))
            if id_map[p]:
                hid = int(rng.choice(list(id_map[p])))
                h_apply(("settle", (hid,), cids[p]))
                assert d_apply(("settle", id_map[p].pop(hid))) == 1
        elif roll < 11:
            p = int(rng.choice(PIDS))
            if id_map[p]:
                hid = int(rng.choice(list(id_map[p])))
                h_apply(("return", (hid,), cids[p]))
                assert d_apply(("return", id_map[p].pop(hid))) == 1
        elif roll == 11:
            p = int(rng.choice(PIDS))
            if attached[p]:
                h_apply(("checkout", "cancel", cids[p]))
                assert d_apply(("cancel", p)) == len(before[p])
                id_map[p].clear()
                attached[p] = False
        elif roll == 12:
            p = int(rng.choice(PIDS))
            if attached[p]:
                h_apply(("down", p, "died"))
                assert d_apply(("down", p)) == len(before[p])
                id_map[p].clear()
                attached[p] = False
        else:
            h_apply(("purge",))
            d_apply(("purge",))
        mirror_new_deliveries(before)

        hready = [(raw, h["delivery_count"])
                  for (_i, h, raw) in hstate.messages.values()]
        assert ready_window(dstate) == hready, i
        hco = sorted(
            (raw, h["delivery_count"])
            for con in hstate.consumers.values()
            for (_mid, _idx, h, raw) in con.checked_out.values())
        assert checked_out(dstate) == hco, i


@pytest.mark.parametrize("seed,overflow", [
    (5, "reject"), (17, "reject"), (29, "drop_head"), (31, "drop_head")])
def test_batch_apply_matches_sequential_fold(seed, overflow):
    """jit_apply_batch == an in-order masked jit_apply fold, over random
    windows, states, and masks, on BOTH of its internal paths: the
    vectorized noop/enqueue/dequeue fast path (clamped-add
    associative_scan + scatter) and the lax.cond fallback scan for
    windows carrying consumer ops.  Initial states are produced by a
    random warmup through jit_apply so checked-out rows shrink the
    effective capacity (the fast path's Qeff) in some lanes."""
    rng = np.random.default_rng(seed)
    Q, K, A, N = 8, 4, 6, 5
    m = JitFifoMachine(capacity=Q, checkout_slots=K, consumer_slots=2,
                       overflow=overflow)
    state = m.jit_init(N)

    # warmup: random traffic incl. unsettled checkouts, attach, credit
    for i in range(12):
        cmd = jnp.asarray(
            rng.integers(0, 5, size=(N, 3)).astype(np.int32))
        state, _ = m.jit_apply({"index": i, "term": 1}, cmd, state)

    for window_kind in ("fast", "mixed"):
        hi_op = 3 if window_kind == "fast" else 12
        cmds = np.zeros((N, A, 3), np.int32)
        cmds[..., 0] = rng.integers(0, hi_op, size=(N, A))
        cmds[..., 1] = rng.integers(0, 6, size=(N, A))
        cmds[..., 2] = rng.integers(0, 4, size=(N, A))
        mask = rng.random((N, A)) < 0.8
        mask[0, :] = True
        mask[1, :] = False
        cmds_j = jnp.asarray(cmds)
        mask_j = jnp.asarray(mask)
        idx = jnp.broadcast_to(jnp.arange(A, dtype=jnp.int32), (N, A))
        meta = {"index": idx, "term": jnp.int32(1)}

        got = m.jit_apply_batch(meta, cmds_j, mask_j, state)

        want = state
        for i in range(A):
            new, _ = m.jit_apply({"index": idx[:, i], "term": 1},
                                 cmds_j[:, i], want)
            want = jax.tree.map(
                lambda n, o: jnp.where(
                    mask_j[:, i].reshape((N,) + (1,) * (n.ndim - 1)), n, o),
                new, want)

        for key in want:
            np.testing.assert_array_equal(
                np.asarray(got[key]), np.asarray(want[key]),
                err_msg=f"{window_kind}:{key}")
        state = want  # chain: next window starts from evolved state


@pytest.mark.parametrize("overflow", ["reject", "drop_head"])
def test_batch_apply_window_wider_than_queue(overflow):
    """A window wider than the queue capacity aliases ring slots mod Q
    inside one window; the vectorized fast path must resolve each slot
    to its LAST aliasing enqueue (rank_win selection) and stay exact
    against the sequential fold — under BOTH overflow policies, since
    drop_head admissions advance head AND participate in the aliasing
    (a drop-admitted enqueue can overwrite the very slot it freed)."""
    rng = np.random.default_rng(3)
    Q, A, N = 4, 9, 3
    m = JitFifoMachine(capacity=Q, checkout_slots=2, overflow=overflow)
    state = m.jit_init(N)
    cmds = np.zeros((N, A, 3), np.int32)
    cmds[..., 0] = rng.integers(0, 3, size=(N, A))
    cmds[..., 1] = rng.integers(0, 6, size=(N, A))
    cmds_j = jnp.asarray(cmds)
    mask_j = jnp.ones((N, A), bool)
    idx = jnp.broadcast_to(jnp.arange(A, dtype=jnp.int32), (N, A))
    got = m.jit_apply_batch({"index": idx, "term": jnp.int32(1)},
                            cmds_j, mask_j, state)
    want = state
    for i in range(A):
        want, _ = m.jit_apply({"index": idx[:, i], "term": 1},
                              cmds_j[:, i], want)
    for key in want:
        np.testing.assert_array_equal(np.asarray(got[key]),
                                      np.asarray(want[key]), err_msg=key)
