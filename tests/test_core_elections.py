"""Election conformance tests — scenarios modeled on the election cases of
/root/reference/test/ra_server_SUITE.erl (pre-vote, vote counting, higher
term stepping, §5.4.1 up-to-date checks)."""
from harness import SimCluster

from ra_tpu.core.server import RaServer
from ra_tpu.core.types import (
    AppendEntriesRpc,
    ElectionTimeout,
    PreVoteResult,
    PreVoteRpc,
    RequestVoteRpc,
    RequestVoteResult,
)


def test_pre_vote_then_election_elects_leader():
    c = SimCluster(3)
    s1 = c.ids[0]
    assert all(st == "follower" for st in c.states().values())
    c.elect(s1)
    assert c.servers[s1].raft_state.value == "leader"
    assert c.servers[s1].current_term == 1
    # noop committed -> cluster changes permitted
    assert c.servers[s1].cluster_change_permitted
    # followers learned the leader
    for sid in c.ids[1:]:
        assert c.servers[sid].leader_id == s1
        assert c.servers[sid].raft_state.value == "follower"


def test_election_requires_quorum():
    c = SimCluster(5)
    s1 = c.ids[0]
    # isolate s1 with only one peer reachable: 2 < quorum(3)
    c.partition(s1, c.ids[2])
    c.partition(s1, c.ids[3])
    c.partition(s1, c.ids[4])
    c.elect(s1)
    assert c.servers[s1].raft_state.value in ("pre_vote", "candidate")
    assert c.leader() is None


def test_higher_term_aer_steps_leader_down():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    # a new leader in a higher term appears
    c.handle(s1, AppendEntriesRpc(term=99, leader_id=s2, prev_log_index=0,
                                  prev_log_term=0, leader_commit=0))
    assert leader.raft_state.value == "follower"
    assert leader.current_term == 99


def test_vote_denied_for_stale_log():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.command(s1, 5)
    # s3's log is now behind; candidate with empty log must be denied
    srv2 = c.servers[s2]
    effs = srv2.handle(RequestVoteRpc(term=srv2.current_term + 1,
                                      candidate_id=s3,
                                      last_log_index=0, last_log_term=0))
    results = [e.msg for e in effs if hasattr(e, "msg")
               and isinstance(e.msg, RequestVoteResult)]
    assert results and not results[0].vote_granted


def test_vote_granted_once_per_term():
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv1 = c.servers[s1]
    effs = srv1.handle(RequestVoteRpc(term=5, candidate_id=s2,
                                      last_log_index=0, last_log_term=0))
    granted = [e.msg for e in effs if hasattr(e, "msg")
               and isinstance(e.msg, RequestVoteResult)]
    assert granted[0].vote_granted
    # second candidate in the same term is denied
    effs = srv1.handle(RequestVoteRpc(term=5, candidate_id=s3,
                                      last_log_index=10, last_log_term=5))
    denied = [e.msg for e in effs if hasattr(e, "msg")
              and isinstance(e.msg, RequestVoteResult)]
    assert not denied[0].vote_granted


def test_pre_vote_does_not_bump_term():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    srv1 = c.servers[s1]
    term0 = srv1.current_term
    srv1.handle(PreVoteRpc(term=term0, token=object(), candidate_id=s2,
                           version=1, machine_version=0,
                           last_log_index=0, last_log_term=0))
    assert srv1.current_term == term0


def test_pre_vote_result_stale_token_ignored():
    c = SimCluster(3)
    s1 = c.ids[0]
    srv1 = c.servers[s1]
    srv1.handle(ElectionTimeout())  # -> pre_vote, effects not routed
    assert srv1.raft_state.value == "pre_vote"
    votes0 = srv1.votes
    srv1.handle(PreVoteResult(term=srv1.current_term, token=object(),
                              vote_granted=True, from_=c.ids[1]))
    assert srv1.votes == votes0  # stale token did not count


def test_non_voter_ignores_election_timeout():
    from ra_tpu.core.types import Membership
    c = SimCluster(3)
    s1 = c.ids[0]
    srv1 = c.servers[s1]
    srv1.cluster[s1].membership = Membership.NON_VOTER
    srv1.membership = Membership.NON_VOTER
    assert srv1.handle(ElectionTimeout()) == []
    assert srv1.raft_state.value == "follower"


def test_candidate_steps_down_on_higher_term_vote_result():
    from ra_tpu.core.types import NextEvent
    c = SimCluster(3)
    s1 = c.ids[0]
    srv1 = c.servers[s1]
    effs = srv1.handle(ElectionTimeout())
    for e in effs:  # process the self pre-vote
        if isinstance(e, NextEvent):
            srv1.handle(e.event)
    # one peer grant reaches quorum -> candidate
    srv1.handle(PreVoteResult(term=srv1.current_term,
                              token=srv1.pre_vote_token,
                              vote_granted=True, from_=c.ids[1]))
    assert srv1.raft_state.value == "candidate"
    srv1.handle(RequestVoteResult(term=100, vote_granted=False,
                                  from_=c.ids[1]))
    assert srv1.raft_state.value == "follower"
    assert srv1.current_term == 100


def test_agreed_commit_median():
    # the scalar oracle the XLA kernel must match (ra_server.erl:2989-2993)
    assert RaServer.agreed_commit([5]) == 5
    assert RaServer.agreed_commit([5, 3]) == 3
    assert RaServer.agreed_commit([5, 3, 1]) == 3
    assert RaServer.agreed_commit([7, 5, 3, 1]) == 3
    assert RaServer.agreed_commit([9, 7, 5, 3, 1]) == 5
    assert RaServer.agreed_commit([0, 0, 9]) == 0


def test_leadership_transfer():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    c.transfer_leadership(s1, s2)
    assert c.servers[s2].raft_state.value == "leader"
    # old leader followed the new leader
    assert c.servers[s1].raft_state.value == "follower"
    assert c.servers[s1].leader_id == s2
