"""Device-plane runtime observatory tests (ISSUE 16).

The jit-plane static gates (RA04/RA13/RA14/RA15) are proof-only; this
file pins their runtime mirror: every steady-state dispatch loop —
single-step, superstep K=8 through the dispatch-ahead driver, the
sharded-mesh driver, and the ingress pump — runs at ZERO new compiles
and a FIXED per-window transfer budget over a warm measured window; a
deliberate shape drift IS caught and the sentinel names the drifting
argument; the instruments' overhead on the bench dispatch path stays
under 3% (interleaved A/B, the same discipline as the telemetry
overhead pin); and the DEVICE_FIELDS round-trip Observatory ->
Prometheus -> time-series ring -> ra_top.

Deltas, not absolutes: ``WATCH`` is process-wide on purpose (compiles
and live buffers are process facts), so every pin snapshots counters
around its own measured window instead of resetting the singleton out
from under other tests.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from ra_tpu.blackbox import RECORDER
from ra_tpu.devicewatch import WATCH, bench_tail_keys
from ra_tpu.engine import DispatchAheadDriver, LockstepEngine
from ra_tpu.metrics import DEVICE_FIELDS, FIELD_REGISTRY
from ra_tpu.models import CounterMachine
from ra_tpu.telemetry import (Observatory, TelemetrySampler,
                              parse_prometheus)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, P, KC = 16, 3, 4


def mk_engine(lanes=N, cmds=KC, ring=64, **kw):
    kw.setdefault("donate", False)
    return LockstepEngine(CounterMachine(), lanes, P,
                          ring_capacity=ring, max_step_cmds=cmds, **kw)


def compile_snap():
    return (WATCH.counters["compiles"], WATCH.counters["recompiles"])


def site_snap(site):
    return dict(WATCH.sites[site])


def site_delta(site, before):
    now = WATCH.sites[site]
    return {k: now[k] - before[k] for k in before}


# ---------------------------------------------------------------------------
# registry + surface shape
# ---------------------------------------------------------------------------

def test_device_fields_registered_and_covered_by_overview():
    assert FIELD_REGISTRY["device"] is DEVICE_FIELDS
    snap = WATCH.overview()
    for f in DEVICE_FIELDS:
        assert f in snap, f
    assert "per_fn" in snap and "sites" in snap


def test_bench_tail_keys_shape():
    """The ONE definition of the bench-tail device stamp: the keys
    tools/bench_diff.py compares, derived from the live counters."""
    tail = bench_tail_keys()
    assert set(tail) == {"n_compiles", "n_recompiles", "compile_time_s",
                         "transfer_bytes", "peak_live_bytes"}
    assert tail["transfer_bytes"] == \
        WATCH.counters["h2d_bytes"] + WATCH.counters["d2h_bytes"]
    with_cmds = bench_tail_keys(commands=1000)
    assert with_cmds["transfer_bytes_per_cmd"] == \
        round(with_cmds["transfer_bytes"] / 1000, 4)


# ---------------------------------------------------------------------------
# steady-state zero-recompile pins (the acceptance loops)
# ---------------------------------------------------------------------------

def test_single_step_loop_steady_state():
    """Warm single-step dispatch: zero new compiles over the measured
    window, and the per-window transfer budget is FIXED — two equal
    windows of the bench loop body produce identical lanes_async d2h
    deltas (events and bytes)."""
    eng = mk_engine()
    n_new = np.full((N,), 2, np.int32)
    pay = np.ones((N, KC, 1), np.int32)
    for _ in range(3):                       # warm-up: compiles happen here
        eng.step(n_new, pay)
        eng.committed_lanes_async()
    eng.block_until_ready()

    def window():
        c0 = compile_snap()
        s0 = site_snap("lanes_async")
        for _ in range(20):
            eng.step(n_new, pay)
            eng.committed_lanes_async()
        eng.block_until_ready()
        assert compile_snap() == c0, "steady-state loop compiled"
        return site_delta("lanes_async", s0)

    w1, w2 = window(), window()
    assert w1["d2h_events"] == 20
    assert w1 == w2, (w1, w2)               # fixed per-window budget
    assert w1["d2h_bytes"] > 0


def test_superstep_k8_driver_loop_steady_state():
    """Warm K=8 fused dispatch through the dispatch-ahead driver: zero
    new compiles, and the budget is exactly 2 staged h2d events + 1
    watermark d2h event per submit, identical across windows."""
    eng = mk_engine()
    drv = DispatchAheadDriver(eng, max_in_flight=2)
    nb = np.full((8, N), 2, np.int32)
    pb = np.ones((8, N, KC, 1), np.int32)
    for _ in range(3):
        drv.submit(nb, pb)
    drv.drain()

    def window():
        c0 = compile_snap()
        h0 = site_snap("driver_stage")
        d0 = site_snap("driver_watermark")
        for _ in range(10):
            drv.submit(nb, pb)
        drv.drain()
        assert compile_snap() == c0, "steady-state superstep compiled"
        return (site_delta("driver_stage", h0),
                site_delta("driver_watermark", d0))

    (h1, d1), (h2, d2) = window(), window()
    assert h1["h2d_events"] == 2 * 10 and d1["d2h_events"] == 10
    assert (h1, d1) == (h2, d2)
    assert h1["h2d_bytes"] == 10 * (nb.nbytes + pb.nbytes)


def test_mesh_driver_loop_steady_state():
    """The sharded-mesh dispatch loop (drive_uniform_window over a
    mesh_superstep_driver): the one-time state reshard lands in the
    mesh_shard h2d site, then the measured window adds ZERO compiles
    and only the per-dispatch staging/watermark budget."""
    import jax

    from ra_tpu.parallel.mesh import (drive_uniform_window,
                                      mesh_superstep_driver,
                                      shard_engine_state)
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend")
    eng = mk_engine(lanes=64)
    m0 = site_snap("mesh_shard")
    mesh = shard_engine_state(eng)
    ms = site_delta("mesh_shard", m0)
    assert ms["h2d_events"] > 0 and ms["h2d_bytes"] > 0
    drv = mesh_superstep_driver(eng, mesh, max_in_flight=2)
    nb = np.full((8, 64), 2, np.int32)
    pb = np.ones((8, 64, KC, 1), np.int32)
    for _ in range(3):
        drv.submit(nb, pb)
    drv.drain()
    c0 = compile_snap()
    m0 = site_snap("mesh_shard")
    h0 = site_snap("driver_stage")
    dispatches, inner, _el = drive_uniform_window(drv, nb, pb, 0.3)
    drv.drain()
    assert dispatches > 0 and inner == 8 * dispatches
    assert compile_snap() == c0, "mesh driver loop compiled"
    # the reshard is one-time: ZERO mesh_shard h2d inside the window
    # (a per-window delta here is the repartition bug RA15 guards)
    assert site_delta("mesh_shard", m0)["h2d_events"] == 0
    assert site_delta("driver_stage", h0)["h2d_events"] == \
        2 * dispatches


def test_ingress_pump_loop_steady_state():
    """Warm ingress pump waves (dedup -> admission -> coalesce ->
    fused dispatch): zero new compiles across the measured waves."""
    from ra_tpu.ingress import IngressPlane
    eng = mk_engine(lanes=32, cmds=4)
    plane = IngressPlane(eng, superstep_k=2, window_s=0.0,
                         soft_credit=64, hard_credit=256)
    h = plane.connect_bulk(100, tenants=4, key="dw")
    rng = np.random.default_rng(9)

    def wave():
        sess = h[rng.integers(0, len(h), 48)]
        seq = plane.directory.next_seqnos(sess)
        pay = rng.integers(1, 5, (48, 1)).astype(np.int32)
        plane.submit(sess, seq, pay)
        plane.pump(force=True)

    for _ in range(3):                      # warm-up waves
        wave()
    plane.settle()
    c0 = compile_snap()
    for _ in range(6):
        wave()
    plane.settle()
    assert compile_snap() == c0, "steady-state ingress pump compiled"


# ---------------------------------------------------------------------------
# drift attribution: the sentinel names the drifting argument
# ---------------------------------------------------------------------------

def test_shape_drift_recompile_is_detected_and_attributed():
    """A K=8 -> K=4 superstep block drift is a retrace: the sentinel
    counts a recompile, names the drifting argument (shape of the
    n_new/payload block leaves) in per_fn last_drift, and emits the
    registered device.recompile flight-recorder event."""
    # a config no other test uses, so the superstep proxy is fresh
    eng = LockstepEngine(CounterMachine(), 6, 3, ring_capacity=32,
                         max_step_cmds=3, donate=False)
    nb8 = np.full((8, 6), 2, np.int32)
    pb8 = np.ones((8, 6, 3, 1), np.int32)
    eng.superstep(nb8, pb8)                 # first compile (legit)
    eng.superstep(nb8, pb8)                 # warm: no compile
    c0 = compile_snap()
    base_events = len(RECORDER.events("device"))
    eng.superstep(nb8[:4], pb8[:4])         # K drift -> retrace
    c1 = compile_snap()
    assert c1[0] == c0[0] + 1               # one compile...
    assert c1[1] == c0[1] + 1               # ...counted as a RECOMPILE
    drift = WATCH.per_fn["superstep"]["last_drift"]
    assert "shape" in drift, drift
    assert "(8, 6" in drift and "(4, 6" in drift, drift
    evs = RECORDER.events("device")
    assert len(evs) > base_events
    ts, etype, fields = evs[-1]
    assert etype == "device.recompile"
    assert fields["fn"] == "superstep" and "shape" in fields["drift"]


def test_first_compile_of_new_config_is_not_a_recompile():
    """A different engine config compiles fresh jit variants: compiles
    grow, recompiles must NOT — warm-up is not a storm and not drift."""
    c0 = compile_snap()
    eng = LockstepEngine(CounterMachine(), 5, 3, ring_capacity=32,
                         max_step_cmds=2, donate=False)
    eng.step(np.full((5,), 1, np.int32), np.ones((5, 2, 1), np.int32))
    c1 = compile_snap()
    assert c1[0] > c0[0]
    assert c1[1] == c0[1]


# ---------------------------------------------------------------------------
# memory watermarks ride the harvest tick
# ---------------------------------------------------------------------------

def test_watermarks_sampled_on_harvest_cadence():
    """The sampler's harvest tick drives the live-buffer census: no
    sampler, no samples (zero new syncs by construction — the census
    rides the tick the loop already pays for)."""
    eng = mk_engine(lanes=8)
    w0 = WATCH.counters["watermark_samples"]
    for _ in range(4):
        eng.uniform_step(2)
    assert WATCH.counters["watermark_samples"] == w0  # no sampler yet
    s = TelemetrySampler(eng, cadence_steps=4)
    for _ in range(8):
        eng.uniform_step(2)
    s.drain()
    c = WATCH.counters
    assert c["watermark_samples"] > w0
    assert c["live_buffers"] > 0 and c["live_bytes"] > 0
    assert c["peak_live_bytes"] >= c["live_bytes"]


def test_donation_keeps_live_set_flat():
    """RA14's runtime twin: with donation ON, dispatches grow while the
    live-buffer census stays flat — the window's live_buffers delta is
    bounded (a monotonically growing live set here is the donation
    regression the watermarks exist to catch)."""
    eng = LockstepEngine(CounterMachine(), N, P, ring_capacity=64,
                         max_step_cmds=KC, donate=False,
                         superstep_donate=True)
    nb = np.full((4, N), 2, np.int32)
    pb = np.ones((4, N, KC, 1), np.int32)
    for _ in range(3):
        eng.superstep(nb, pb)
    eng.block_until_ready()
    WATCH.sample_watermarks()
    before = WATCH.counters["live_buffers"]
    for _ in range(25):
        eng.superstep(nb, pb)
    eng.block_until_ready()
    WATCH.sample_watermarks()
    after = WATCH.counters["live_buffers"]
    # donated steady-state: no per-dispatch buffer accumulation (slack
    # covers allocator jitter, not a 25-dispatch leak)
    assert after - before < 25, (before, after)


# ---------------------------------------------------------------------------
# overhead: instruments on vs off, interleaved A/B, < 3%
# ---------------------------------------------------------------------------

def test_devicewatch_overhead_under_3pct():
    """Interleaved A/B rounds of the bench dispatch pattern with the
    WATCH master switch on vs off.  Steady-state per-dispatch cost is
    one monotonic read + two cache-size reads + dict increments, so the
    3% bar (the PR 6 telemetry discipline) must hold; in-test retries
    absorb noisy attempts on an oversubscribed box."""
    import collections
    import time

    eng = LockstepEngine(CounterMachine(), 64, 3, ring_capacity=64,
                         max_step_cmds=8, donate=False)
    n_new = np.full((64,), 8, np.int32)
    pay = np.ones((64, 8, 1), np.int32)
    for _ in range(10):
        eng.step(n_new, pay)
    eng.block_until_ready()

    def measure(seconds):
        rb: collections.deque = collections.deque()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            eng.step(n_new, pay)
            rb.append(eng.committed_lanes_async())
            while len(rb) > 8:
                np.asarray(rb.popleft())
            n += 1
        eng.block_until_ready()
        return n / (time.perf_counter() - t0)

    assert WATCH.enabled
    overhead = 1.0
    try:
        for _attempt in range(3):
            rates = {False: [], True: []}
            for _round in range(4):
                for flag in (False, True):
                    WATCH.enabled = flag
                    rates[flag].append(measure(0.3))
            off = sorted(rates[False])[len(rates[False]) // 2]
            on = sorted(rates[True])[len(rates[True]) // 2]
            overhead = (off - on) / off
            if overhead < 0.03:
                break
    finally:
        WATCH.enabled = True
    assert overhead < 0.03, f"devicewatch overhead {overhead:.1%} >= 3%"


# ---------------------------------------------------------------------------
# round trip: Observatory -> Prometheus -> ring -> ra_top
# ---------------------------------------------------------------------------

def test_device_source_round_trips_observatory_prometheus_ring(tmp_path):
    eng = mk_engine(lanes=8)
    s = TelemetrySampler(eng, cadence_steps=4)
    for _ in range(8):
        eng.uniform_step(2)
    s.drain()
    obs = Observatory.for_engine(eng, sampler=s)
    try:
        snap = obs.snapshot()
        dev = snap["device"]
        for f in DEVICE_FIELDS:
            assert f in dev, f
        assert dev["compiles"] == WATCH.counters["compiles"]
        # Prometheus exposition
        flat = parse_prometheus(obs.prometheus())
        assert ("ra_tpu_device_compiles", "") in flat
        assert ("ra_tpu_device_peak_live_bytes", "") in flat
        assert flat[("ra_tpu_device_recompiles", "")] == \
            WATCH.counters["recompiles"]
        # time-series ring: flattened device_* keys, nested per-site
        obs.snapshot()
        _ts, flat_ring = obs.ring()[-1]
        dev_keys = [k for k in flat_ring if k.startswith("device_")]
        for f in DEVICE_FIELDS:
            assert f"device_{f}" in dev_keys
        assert any(k.startswith("device_sites_") for k in dev_keys)
        # ra_top renders the device panel from the JSONL ring
        path = str(tmp_path / "obs.jsonl")
        obs.to_jsonl(path)
        obs.to_jsonl(path)
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "ra_top.py"),
             path, "--once"], capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        assert "device  compiles=" in r.stdout, r.stdout
        assert "peak=" in r.stdout and "h2d=" in r.stdout
    finally:
        obs.close()


def test_slo_steady_state_recompiles_objective():
    """The default SLO set carries steady_state_recompiles <= 0 as a
    rate objective: device-plane rings evaluate it (ok at zero), and a
    classic-plane deployment without the device key stays no_data —
    never a false breach."""
    from ra_tpu.slo import SloEngine, default_objectives
    objs = default_objectives()
    assert any(o.name == "steady_state_recompiles" for o in objs)
    eng = mk_engine(lanes=8)
    s = TelemetrySampler(eng, cadence_steps=4)
    obs = Observatory.for_engine(eng, sampler=s)
    try:
        slo = SloEngine(obs, objs)
        for _ in range(8):
            eng.uniform_step(2)
        s.drain()
        obs.snapshot()
        obs.snapshot()
        res = slo.evaluate()["objectives"]["steady_state_recompiles"]
        assert res["verdict"] == "ok", res
        assert res["value"] == 0.0
        # a ring without device keys -> no_data, not a breach
        bare = Observatory()
        try:
            bare.add_source("engine", lambda: {"telemetry": {}})
            slo2 = SloEngine(bare, objs)
            bare.snapshot()
            bare.snapshot()
            res2 = slo2.evaluate()["objectives"][
                "steady_state_recompiles"]
            assert res2["verdict"] == "no_data"
        finally:
            bare.close()
    finally:
        obs.close()


# ---------------------------------------------------------------------------
# soak family: tools/soak.py --device-obs
# ---------------------------------------------------------------------------

def run_device_obs_chaos(seed, data_dir):
    """One seeded episode of the device-observatory chaos family
    (driven over fresh seed ranges by ``tools/soak.py --device-obs``):
    a DURABLE engine takes fixed-shape superstep traffic through
    election churn and a seeded DiskFaultPlan on its WAL — the
    recompile sentinel must stay QUIET over the measured window (zero
    compiles of any kind once every code path is warm; host-plane
    chaos is not shape drift) — then a deliberate mixed-shape probe
    (K=8 -> K=4 block) MUST be detected within ONE Observatory window
    and attributed to the drifting block shape.  Raises on any
    violation; returns a summary dict for the soak tail.

    The engine config is seed-varied (lanes/cmds) so every episode in
    a multi-seed soak run gets FRESH jit variants — otherwise the
    process-global jit cache would hide the probe from episode 2 on.
    """
    import random as _random

    from ra_tpu.engine import open_engine
    from ra_tpu.log import faults

    rng = _random.Random(seed)
    lanes = 6 + seed % 5
    cmds = 2 + seed % 3
    plan = faults.DiskFaultPlan(seed=seed, by_class={
        "wal": faults.DiskFaultSpec(
            fsync_eio=rng.uniform(0.0, 0.15),
            limit=rng.randint(1, 4))})
    # default sync_mode=1: commits are fsync-gated, so the WAL
    # fault plan has real fsyncs to hit
    eng = open_engine(CounterMachine(), data_dir, lanes, P,
                      ring_capacity=48, max_step_cmds=cmds, donate=False)
    obs = Observatory.for_engine(eng)
    nb = np.full((8, lanes), 1, np.int32)
    pb = np.ones((8, lanes, cmds, 1), np.int32)
    faults.install_plan(plan)
    try:
        # warm every code path the chaos rounds exercise BEFORE the
        # measured window: fused dispatch, election, async readback
        eng.superstep(nb, pb)
        eng.trigger_election(list(range(lanes)))
        eng.superstep(nb, pb)
        np.asarray(eng.committed_lanes_async())
        eng.block_until_ready()
        c0 = compile_snap()
        rounds = 24
        for _ in range(rounds):
            roll = rng.random()
            if roll < 0.6:
                eng.superstep(nb, pb)
            elif roll < 0.8:
                eng.trigger_election(list(range(lanes)))
            else:
                np.asarray(eng.committed_lanes_async())
        eng.block_until_ready()
        c1 = compile_snap()
        assert c1 == c0, \
            f"sentinel fired under election/disk chaos: {c0} -> {c1}"
        # the deliberate mixed-shape probe: detected within ONE window
        obs.snapshot()
        pre = obs.ring()[-1][1]["device_recompiles"]
        eng.superstep(nb[:4], pb[:4])       # K=8 -> K=4 drift
        obs.snapshot()
        post = obs.ring()[-1][1]["device_recompiles"]
        assert post >= pre + 1, \
            f"mixed-shape probe NOT detected: {pre} -> {post}"
        drift = WATCH.per_fn["superstep"]["last_drift"]
        assert "shape" in drift, drift
        return {"rounds": rounds,
                "injected_faults": sum(plan.counters.values()),
                "probe_recompiles": int(post - pre), "drift": drift}
    finally:
        faults.clear_plan()
        obs.close()
        eng.close()


def test_device_obs_chaos_pinned_seed(tmp_path):
    run_device_obs_chaos(0, str(tmp_path / "s0"))


def test_autotuner_freezes_on_compile_storm():
    """A compile observed between autotuner ticks freezes tuning
    (reason compile_storm) for compile_freeze_s; quiet ticks thaw."""
    import time as _time

    from ra_tpu.autotune import AutoTuner
    eng = mk_engine(lanes=8)
    obs = Observatory.for_engine(eng)
    try:
        from ra_tpu.slo import SloEngine, default_objectives
        slo = SloEngine(obs, default_objectives())
        tun = AutoTuner(slo, compile_freeze_s=0.2)
        assert tun._compile_storm_reason() is None  # baseline tick
        WATCH.counters["compiles"] += 1             # a storm arrives
        assert tun._compile_storm_reason() == "compile_storm"
        assert tun._compile_storm_reason() == "compile_storm"  # quiet win
        _time.sleep(0.25)
        assert tun._compile_storm_reason() is None  # thawed
    finally:
        obs.close()
