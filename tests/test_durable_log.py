"""Durable log subsystem tests — WAL batching/rollover/recovery, segment
flush, snapshots/checkpoints, corruption tolerance.  Scenario shapes follow
the reference's ra_log_2_SUITE / ra_log_wal_SUITE / ra_checkpoint_SUITE."""
import os
import pickle
import time


from ra_tpu.core.types import Entry, UserCommand
from ra_tpu.log.segment import SegmentFile
from ra_tpu.system import RaSystem


def drain(log, timeout=5.0):
    """Wait for WAL confirms and apply them."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        evts = log.take_events()
        for e in evts:
            log.handle_written(e)
        if log.last_written().index >= log.last_index_term().index:
            return
        time.sleep(0.005)
    raise TimeoutError("log never confirmed")


def mk_system(tmp_path, **kw):
    return RaSystem(str(tmp_path), **kw)


def mk_log(system, uid="u1"):
    from ra_tpu.core.types import ServerConfig, ServerId
    cfg = ServerConfig(server_id=None, uid=uid, cluster_name="c",
                       initial_members=(), machine=None)
    return system.log_factory(cfg)


def test_append_and_written_confirm(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 101):
        log.append(Entry(i, 1, UserCommand(i)))
    assert log.last_index_term().index == 100
    drain(log)
    assert log.last_written().index == 100
    assert log.fetch(50).command.data == 50
    sys_.close()


def test_rollover_flushes_to_segments_and_deletes_wal(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 201):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    assert log.overview()["num_segments"] >= 1
    assert log.overview()["num_mem_entries"] == 0
    # reads served from segments, with crc verification
    assert log.fetch(123).command.data == 123
    assert log.fetch_term(200) == 1
    # the rolled WAL file is gone; only the fresh one remains
    wal_files = os.listdir(os.path.join(str(tmp_path), "wal"))
    assert len(wal_files) == 1
    sys_.close()


def test_recovery_from_wal_only(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 51):
        log.append(Entry(i, 3, UserCommand(i * 2)))
    drain(log)
    log.store_meta(current_term=3, voted_for=None, last_applied=50)
    sys_.close()  # "crash": entries only in WAL files
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term().index == 50
    assert log2.last_written().index == 50
    assert log2.fetch(25).command.data == 50
    assert log2.fetch_meta("current_term") == 3
    # recovered WAL files are retired once their entries reach segments —
    # no unbounded *.wal accumulation across restarts
    deadline = time.monotonic() + 5
    waldir = os.path.join(str(tmp_path), "wal")
    while time.monotonic() < deadline:
        if len(os.listdir(waldir)) == 1:  # only the fresh live file
            break
        time.sleep(0.02)
    assert len(os.listdir(waldir)) == 1
    assert log2.fetch(25).command.data == 50  # now served from segments
    sys2.close()


def test_recovery_from_segments_plus_wal(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 101):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    for i in range(101, 131):
        log.append(Entry(i, 2, UserCommand(i)))
    drain(log)
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term() == (130, 2)
    assert log2.fetch(42).command.data == 42     # from segment
    assert log2.fetch(120).command.data == 120   # from recovered WAL
    sys2.close()


def test_overwrite_invalidates_tail_across_recovery(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 11):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    # a new leader overwrites from index 5 in term 2
    log.write([Entry(5, 2, UserCommand(500))])
    drain(log)
    assert log.last_index_term() == (5, 2)
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term() == (5, 2)
    assert log2.fetch(5).command.data == 500
    assert log2.fetch(6) is None
    sys2.close()


def test_snapshot_truncates_and_recovers(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 101):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    log.update_release_cursor(80, (), 0, {"acc": 4080})
    assert log.snapshot_index_term() == (80, 1)
    assert log.first_index() == 81
    assert log.fetch(80) is None
    assert log.fetch(90).command.data == 90
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    meta, state = log2.recover_snapshot_state()
    assert meta.index == 80 and state == {"acc": 4080}
    assert log2.last_index_term().index == 100
    sys2.close()


def test_checkpoint_promote(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 31):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    log.checkpoint(10, (), 0, {"acc": 55})
    log.checkpoint(20, (), 0, {"acc": 210})
    assert log.overview()["num_checkpoints"] == 2
    assert log.promote_checkpoint(15)  # promotes cp@10
    assert log.snapshot_index_term().index == 10
    meta, state = log.recover_snapshot_state()
    assert state == {"acc": 55}
    # checkpoint retention cap
    for i in range(12):
        log.checkpoint(20 + i // 2, (), 0, {"i": i})
    assert log.overview()["num_checkpoints"] <= 10
    sys_.close()


def test_corrupt_wal_tail_is_tolerated(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 21):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.close()
    # corrupt the tail of the wal file (torn write)
    waldir = os.path.join(str(tmp_path), "wal")
    fname = sorted(os.listdir(waldir))[0]
    path = os.path.join(waldir, fname)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    # last entry lost, the rest intact
    assert 0 < log2.last_index_term().index < 20
    sys2.close()


def test_corrupt_snapshot_falls_back_to_older(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 31):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    log.update_release_cursor(10, (), 0, {"acc": 55})
    # write a newer snapshot then corrupt it on disk
    log.update_release_cursor(20, (), 0, {"acc": 210})
    sys_.close()
    snapdir = os.path.join(str(tmp_path), "u1", "snapshot")
    snaps = sorted(os.listdir(snapdir))
    newest = os.path.join(snapdir, snaps[-1])
    with open(newest, "r+b") as f:
        f.seek(20)
        f.write(b"\xff\xff\xff")
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    got = log2.recover_snapshot_state()
    # newest is invalid; recovery must not produce garbage. The older
    # snapshot was deleted when the newer one landed, so None is also
    # acceptable — but never a corrupt load.
    if got is not None:
        assert got[1] == {"acc": 55}
    sys2.close()


def test_segment_file_roundtrip(tmp_path):
    path = str(tmp_path / "t.segment")
    seg = SegmentFile(path, max_count=8, create=True)
    for i in range(1, 9):
        assert seg.append(i, 1, pickle.dumps(i * 11))
    assert not seg.append(9, 1, b"x")  # full
    seg.flush()
    seg.close()
    seg2 = SegmentFile(path)
    assert seg2.range() == (1, 8)
    assert pickle.loads(seg2.read(5)[1]) == 55
    seg2.close()


def test_wal_gap_triggers_resend(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    log.append(Entry(1, 1, UserCommand(1)))
    drain(log)
    # bypass the log and inject an out-of-sequence WAL write
    sys_.wal.write("u1", 5, 1, pickle.dumps(UserCommand(5)))
    sys_.wal.flush()
    # the WAL rejected it; log state unchanged and a fresh append works
    log.append(Entry(2, 1, UserCommand(2)))
    drain(log)
    assert log.last_written().index == 2
    sys_.close()


def test_external_reader_survives_snapshot_truncation(tmp_path):
    """ra_2_SUITE's external-reader scenario: a registered reader keeps
    segment-flushed entries readable across a snapshot truncation; the
    pinned files are deleted once the last reader closes."""
    import os as _os

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 41):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    reader = log.register_reader("stream-1")
    seg_paths = [s.path for s in log._segments]
    assert seg_paths, "entries should be segment-flushed"
    # snapshot far past the flushed entries truncates the live log
    log.update_release_cursor(35, (), 0, {"v": 35})
    assert log.first_index() == 36
    assert log.fetch(10) is None            # live reads: truncated
    got = reader.sparse_read([1, 10, 35])   # reader: still visible
    assert [e.command.data for e in got] == [1, 10, 35]
    total = reader.fold(1, 35, lambda e, a: a + e.command.data, 0)
    assert total == sum(range(1, 36))
    # pinned files still on disk until the reader closes
    assert any(_os.path.exists(p) for p in seg_paths)
    reader.close()
    assert not any(_os.path.exists(p) for p in seg_paths
                   if p not in [s.path for s in log._segments])
    sys_.close()


def test_two_readers_pin_until_last_closes(tmp_path):
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 21):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    r1 = log.register_reader("r1")
    r2 = log.register_reader("r2")
    log.update_release_cursor(20, (), 0, {})
    assert r1.fetch(5) is not None
    r1.close()
    assert r2.fetch(5) is not None          # r2 still pins
    r2.close()
    assert log._pinned_segments == []
    sys_.close()


def test_same_name_readers_refcount(tmp_path):
    """Two consumers under one reader name: pins hold until the LAST
    close (a set would collapse them and unpin early)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 21):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    r1 = log.register_reader("stream")
    r2 = log.register_reader("stream")
    log.update_release_cursor(20, (), 0, {})
    r1.close()
    assert r2.fetch(5) is not None, "second reader lost its pins"
    r2.close()
    assert log._pinned_segments == []
    sys_.close()


def test_recovery_reclaims_orphaned_pinned_segments(tmp_path):
    """Shutdown with an open reader leaves pinned (fully-truncated)
    segment files on disk; recovery must reclaim them instead of
    re-adopting dead weight below first_index."""
    import os as _os

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 21):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    log.register_reader("leaky")       # never closed
    log.update_release_cursor(20, (), 0, {"v": 20})
    pinned = [s.path for s in log._pinned_segments]
    assert pinned
    sys_.close()                       # reader still open: files survive
    assert all(_os.path.exists(p) for p in pinned)
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert not any(_os.path.exists(p) for p in pinned)
    assert log2.snapshot_index_term().index == 20
    sys2.close()


def test_parallel_segment_flush_concurrency():
    """The segment writer flushes a job's per-uid ranges on a worker
    pool (the partition_parallel role, ra_log_segment_writer.erl:
    129-147): a barrier that only releases when 4 flushes are in flight
    simultaneously proves the parallelism (a serial writer deadlocks it
    and the WAL file is kept)."""
    import threading

    from ra_tpu.log.segment import SegmentWriter

    barrier = threading.Barrier(4, timeout=8)

    class FakeLog:
        def flush_mem_to_segments(self, hi):
            barrier.wait()
            return (5, 50, 1)

    logs = {f"u{i}": FakeLog() for i in range(4)}
    sw = SegmentWriter(resolve=logs.get, flush_workers=4)
    try:
        sw.accept_ranges({u: (1, 5) for u in logs}, "/nonexistent/x.wal")
        sw.await_idle(timeout=20)
        assert sw.counters["mem_tables"] == 4, sw.counters
        assert sw.counters["entries"] == 20
    finally:
        sw.close()


def test_multi_server_rollover_parallel_flush(tmp_path):
    """Co-hosted servers sharing one WAL: a rollover flushes every
    server's memtable (concurrently) and then deletes the file."""
    sys_ = mk_system(tmp_path)
    logs = [mk_log(sys_, uid=f"u{i}") for i in range(6)]
    t0 = time.monotonic()
    for i in range(1, 101):
        for log in logs:
            log.append(Entry(i, 1, UserCommand(i)))
    for log in logs:
        drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    elapsed = time.monotonic() - t0
    for log in logs:
        assert log.overview()["num_segments"] >= 1
        assert log.overview()["num_mem_entries"] == 0
        assert log.fetch(57).command.data == 57
    wal_files = os.listdir(os.path.join(str(tmp_path), "wal"))
    assert len(wal_files) == 1, wal_files
    # timing note (informational): 6 servers x 100 entries flushed in
    # one rollover; with the 4-worker pool this runs in ~1/4 the serial
    # wall time at scale (disk-bound flushes overlap)
    assert elapsed < 30
    sys_.close()


def test_lock_order_fix_paths_keep_semantics(tmp_path):
    """ISSUE 14 / RA11 regression: three sites used to resolve terms via
    fetch_term while HOLDING the log lock — a segment-read fallthrough
    there takes _io_lock and inverts the documented io-then-log order
    (ABBA vs flush_mem_to_segments).  The fix pre-reads outside the
    lock (set_last_index, _wal_notify) and short-circuits stale
    confirms to a memtable-only lookup (handle_written).  Pin the
    observable semantics on the exact shape that exercised the old
    fallthrough: entries flushed to segments and pruned from the
    memtable."""
    from ra_tpu.core.types import WrittenEvent

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 201):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    # move everything to segments: term lookups below last_written now
    # REQUIRE the segment path (the memtable is empty)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    assert log.overview()["num_mem_entries"] == 0
    # (1) handle_written: a duplicate/stale confirm at/below
    # last_written is a no-op — and never touches the segment path
    # under the log lock
    before = log.last_written()
    log.handle_written(WrittenEvent(1, 150, 1))
    assert log.last_written() == before
    # a stale confirm with a WRONG term is equally a no-op
    log.handle_written(WrittenEvent(100, 180, 7))
    assert log.last_written() == before
    # (2) set_last_index: truncation whose boundary term lives in a
    # segment resolves through the pre-read and still rewinds both
    # last_index and last_written
    log.set_last_index(150)
    assert log.last_index_term().index == 150
    assert log.last_index_term().term == 1
    assert log.last_written().index == 150
    assert log.last_written().term == 1
    # reads above the truncation are gone; below still served
    assert log.fetch(151) is None
    assert log.fetch(150).command.data == 150
    sys_.close()


def test_confirm_for_flushed_ahead_entries_still_advances(tmp_path):
    """Review regression pin (ISSUE 14): the segment writer flushes up
    to the WAL FILE's range, which can run AHEAD of the log's processed
    confirm watermark — a confirm arriving AFTER its entries were
    flushed+pruned must still advance last_written (resolved via an
    out-of-lock segment read, never _io_lock-under-_lock)."""
    from ra_tpu.core.types import WrittenEvent

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 101):
        log.append(Entry(i, 1, UserCommand(i)))
    # make everything WAL-durable, then STEAL the queued confirms so
    # the log never processes them
    sys_.wal.flush()
    held = [e for e in log.take_events()
            if isinstance(e, WrittenEvent)]
    assert held, "expected queued WAL confirms"
    assert log.last_written().index == 0
    # roll + flush: the segment writer prunes the whole memtable even
    # though the log's confirm watermark still sits at 0
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    held += [e for e in log.take_events()
             if isinstance(e, WrittenEvent)]
    assert log.overview()["num_mem_entries"] == 0
    # the late confirms now resolve their terms from segments and the
    # watermark catches up
    for e in held:
        log.handle_written(e)
    assert log.last_written().index == 100, log.last_written()
    assert log.last_written().term == 1
    sys_.close()


def test_poison_rewind_skips_snapshot_subsumed_range(tmp_path):
    """Review regression pin (ISSUE 14, round 3): the poison-rewind
    pre-read in _wal_notify races a concurrent snapshot install — if
    the install prunes <= meta.index between the out-of-lock
    fetch_term and the locked rewind, the pre-read term is stale and
    the rewind would drag last_written BELOW the installed snapshot.
    The rewind branch now re-resolves under the lock and, for a
    snapshot-subsumed range, CLAMPS last_written to the snapshot —
    never below it (stale term under durable state), never leaving it
    above (memtable entries between the snapshot and the old watermark
    rode the failed syscall and MUST be resent; a first-cut skip left
    them only in the poisoned file)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 101):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    # snapshot at 80 WITHOUT a segment flush: entries 81..100 stay
    # memtable-resident — the shape where a skipped rewind loses data
    log.update_release_cursor(80, (), 0, {"acc": 1})
    assert log.first_index() == 81
    assert log.overview()["num_mem_entries"] == 20
    assert log.last_written().index == 100
    resends_before = log.counters["write_resends"]
    # a late poison notify for a range the snapshot subsumed: the exact
    # interleaving is pre-read -> install -> locked rewind; calling
    # after the install drives the same locked branch (the under-lock
    # re-resolve returns None for a pruned index either way)
    log._wal_notify(log.uid, None, 50, -2)
    # clamped to the snapshot, not rewound to hi=50
    assert log.last_written() == (80, 1), log.last_written()
    # and the memtable suffix above the snapshot was re-submitted
    assert log.counters["write_resends"] - resends_before == 20
    drain(log)
    assert log.last_written().index == 100
    # the log still confirms fresh appends normally afterwards
    log.append(Entry(101, 1, UserCommand(101)))
    drain(log)
    assert log.last_written().index == 101
    sys_.close()
