"""Deterministic in-process simulation harness for core conformance tests.

Plays the role the mocked log + scripted events play in the reference's
ra_server_SUITE (/root/reference/test/ra_server_SUITE.erl): drives pure
RaServer cores directly, routing effect data between them with no real
timers, threads, or I/O, so every interleaving is scriptable and
assertions are data-in/data-out.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Callable, Optional

from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.server import RaServer
from ra_tpu.core.types import (
    CancelElectionTimeout,
    Checkpoint,
    CommandEvent,
    ConsistentQueryEvent,
    ElectionTimeout,
    InstallSnapshotRpc,
    NextEvent,
    Notify,
    PromoteCheckpoint,
    ReleaseCursor,
    Reply,
    SendRpc,
    SendSnapshot,
    SendVoteRequests,
    ServerConfig,
    ServerId,
    StartElectionTimeout,
    TransferLeadershipEvent,
    UserCommand,
)
from ra_tpu.log.memory import MemoryLog


def mk_ids(n: int) -> list:
    return [ServerId(f"s{i+1}", f"node{i+1}") for i in range(n)]


class SimCluster:
    """Synchronous router between N RaServer cores."""

    def __init__(self, n: int = 3, machine_factory: Optional[Callable] = None,
                 auto_written: bool = True,
                 snapshot_chunk_size: int = 64,
                 log_factory: Optional[Callable] = None,
                 initial_count: Optional[int] = None) -> None:
        """``log_factory(cfg) -> log`` swaps the in-memory mock for a
        real log (e.g. RaSystem.log_factory) so core scenarios can run
        against durable storage; default stays MemoryLog.
        ``initial_count`` starts only the first K ids as cluster members
        — the rest run as standby servers awaiting a '$ra_join' (the
        start_server-then-add_member flow)."""
        self.ids = mk_ids(n)
        if machine_factory is None:
            machine_factory = lambda: SimpleMachine(  # noqa: E731
                lambda cmd, st: st + cmd, 0)
        self.servers: dict[ServerId, RaServer] = {}
        self.queues: dict[ServerId, deque] = {sid: deque() for sid in self.ids}
        self.replies: list = []         # (server_id, Reply)
        self.notifies: list = []        # (server_id, Notify)
        self.timer_kinds: dict[ServerId, Optional[str]] = {}
        self.dropped: set = set()       # partitioned links (src, dst)
        self.snapshot_chunk_size = snapshot_chunk_size
        self._log_factory = log_factory
        initial = tuple(self.ids[:initial_count]
                        if initial_count else self.ids)
        for sid in self.ids:
            cfg = ServerConfig(server_id=sid, uid=f"uid_{sid.name}",
                               cluster_name="simcluster",
                               initial_members=initial,
                               machine=machine_factory())
            log = (self._log_factory(cfg) if self._log_factory
                   else MemoryLog(auto_written=auto_written))
            srv = RaServer(cfg, log)
            srv.recover()
            self.servers[sid] = srv
            self.timer_kinds[sid] = None

    # -- driving -----------------------------------------------------------

    def handle(self, sid: ServerId, event: Any) -> None:
        """Feed one event to a server and process its effects."""
        srv = self.servers[sid]
        effects = srv.handle(event)
        self._process_effects(sid, effects)
        self._drain_log_events(sid)

    def _drain_log_events(self, sid: ServerId) -> None:
        srv = self.servers[sid]
        for evt in srv.log.take_events():
            effects = srv.handle(evt)
            self._process_effects(sid, effects)

    def _process_effects(self, sid: ServerId, effects: list) -> None:
        srv = self.servers[sid]
        for eff in effects:
            if isinstance(eff, SendRpc):
                self._send(sid, eff.to, eff.msg)
            elif isinstance(eff, SendVoteRequests):
                for to, msg in eff.requests:
                    self._send(sid, to, msg)
            elif isinstance(eff, NextEvent):
                inner = srv.handle(eff.event)
                self._process_effects(sid, inner)
            elif isinstance(eff, Reply):
                self.replies.append((sid, eff))
            elif isinstance(eff, Notify):
                self.notifies.append((sid, eff))
            elif isinstance(eff, StartElectionTimeout):
                self.timer_kinds[sid] = eff.kind
            elif isinstance(eff, CancelElectionTimeout):
                self.timer_kinds[sid] = None
            elif isinstance(eff, (ReleaseCursor, Checkpoint,
                                  PromoteCheckpoint)):
                self._process_effects(sid, srv.handle_machine_effect(eff))
            elif isinstance(eff, SendSnapshot):
                self._send_snapshot(sid, eff)
            # other effects (aux, metrics, monitors...) are inert here

    def _send(self, src: ServerId, dst: ServerId, msg: Any) -> None:
        if (src, dst) in self.dropped:
            return
        self.queues[dst].append(msg)

    def _send_snapshot(self, src: ServerId, eff: SendSnapshot) -> None:
        """Chunked snapshot transfer, modeled synchronously."""
        srv = self.servers[src]
        snap = srv.log.snapshot()
        if snap is None:
            return
        meta, data = snap
        leader_id, term = eff.id_term
        chunks = list(srv.log.snapshot_module.chunks(
            data, self.snapshot_chunk_size)) or [b""]
        for i, chunk in enumerate(chunks):
            flag = "last" if i == len(chunks) - 1 else "next"
            self._send(src, eff.to,
                       InstallSnapshotRpc(term=term, leader_id=leader_id,
                                          meta=meta, chunk_number=i + 1,
                                          chunk_flag=flag, data=chunk,
                                          token=eff.token))

    def step(self) -> bool:
        """Deliver one pending message (round-robin across servers)."""
        for sid in self.ids:
            if self.queues[sid]:
                msg = self.queues[sid].popleft()
                self.handle(sid, msg)
                return True
        return False

    def run(self, max_steps: int = 10_000) -> int:
        n = 0
        while self.step():
            n += 1
            if n >= max_steps:
                raise RuntimeError("simulation did not quiesce")
        return n

    # -- convenience -------------------------------------------------------

    def elect(self, sid: ServerId) -> None:
        """Trigger an election timeout at sid and run to quiescence."""
        self.handle(sid, ElectionTimeout())
        self.run()

    def leader(self) -> Optional[ServerId]:
        for sid, srv in self.servers.items():
            if srv.raft_state.value == "leader":
                return sid
        return None

    def command(self, sid: ServerId, data: Any, from_: Any = None,
                **kw: Any) -> None:
        self.handle(sid, CommandEvent(UserCommand(data, **kw), from_=from_))
        self.run()

    def consistent_query(self, sid: ServerId, fn: Callable,
                         from_: Any = "qclient") -> None:
        self.handle(sid, ConsistentQueryEvent(fn, from_=from_))
        self.run()

    def transfer_leadership(self, sid: ServerId, target: ServerId,
                            from_: Any = "tclient") -> None:
        self.handle(sid, TransferLeadershipEvent(target, from_=from_))
        self.run()

    def partition(self, a: ServerId, b: ServerId) -> None:
        self.dropped.add((a, b))
        self.dropped.add((b, a))

    def heal(self) -> None:
        self.dropped.clear()

    def isolate(self, sid: ServerId) -> None:
        for other in self.ids:
            if other != sid:
                self.partition(sid, other)

    def machine_states(self) -> dict:
        return {sid: srv.machine_state for sid, srv in self.servers.items()}

    def states(self) -> dict:
        return {sid: srv.raft_state.value
                for sid, srv in self.servers.items()}
