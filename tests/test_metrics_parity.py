"""Counter/metrics parity with the reference's observability surface.

The reference keeps ~47 flat counter fields per server (ra.hrl:236-390)
plus node-wide WAL / segment-writer counters (ra_log_wal.erl:32-43,
ra_log_segment_writer.erl:37-52) and samples them via ra:key_metrics
(ra.erl:1229-1257).  These tests pin the field names and prove the
counters actually move under a real durable workload."""
import time

import ra_tpu
from ra_tpu import LocalRouter, RaNode, RaSystem
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerConfig, ServerId

from nemesis import await_leader

# RA_LOG_COUNTER_FIELDS (ra.hrl:236-268), minus documented N/A:
#   reserved_1 (placeholder), read_open_mem_tbl / read_closed_mem_tbl
#   (the open/closed WAL ETS tables are merged into the DurableLog
#   memtable; those hits count as read_cache — wal.py:15-21)
REF_LOG_FIELDS = {
    "write_ops", "write_resends", "read_ops", "read_cache",
    "read_segment", "fetch_term", "snapshots_written",
    "snapshot_installed", "snapshot_bytes_written", "open_segments",
    "checkpoints_written", "checkpoint_bytes_written",
    "checkpoints_promoted",
}

# RA_SRV_COUNTER_FIELDS (ra.hrl:311-357), minus reserved_2 (placeholder)
REF_SRV_FIELDS = {
    "aer_received_follower", "aer_replies_success", "aer_replies_fail",
    "commands", "command_flushes", "aux_commands", "consistent_queries",
    "rpcs_sent", "msgs_sent", "dropped_sends", "send_msg_effects_sent",
    "pre_vote_elections", "elections", "forced_gcs", "snapshots_sent",
    "release_cursors", "aer_received_follower_empty",
    "term_and_voted_for_updates", "local_queries",
    "invalid_reply_mode_commands", "checkpoints",
}

# RA_SRV_METRICS_COUNTER_FIELDS gauges (ra.hrl:359-383), surfaced as
# top-level key_metrics entries (the reference reads them from the same
# counter array; ra.erl:1229-1240 samples the first seven)
REF_METRIC_FIELDS = {
    "last_applied", "commit_index", "snapshot_index", "last_index",
    "last_written_index", "commit_latency", "term", "checkpoint_index",
    "effective_machine_version",
}

REF_WAL_FIELDS = {"wal_files", "batches", "writes", "bytes_written"}
REF_SEGWRITER_FIELDS = {"mem_tables", "segments", "entries",
                        "bytes_written"}


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def mk_cfg(sid, sids):
    return ServerConfig(server_id=sid, uid=f"uid_{sid.name}",
                        cluster_name="metrics",
                        initial_members=tuple(sids), machine=counter(),
                        election_timeout_ms=80, tick_interval_ms=30)


def test_key_metrics_field_parity_and_movement(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"k{i}", f"kn{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)

    for v in range(1, 31):
        ra_tpu.process_command(leader, v, router=router)
    ra_tpu.consistent_query(leader, lambda s: s, router=router)
    ra_tpu.local_query(leader, lambda s: s, router=router)
    # idle ticks produce empty AERs on the followers
    time.sleep(0.2)

    m = ra_tpu.key_metrics(leader, router=router)
    # field parity: every reference field name present
    missing_metric = REF_METRIC_FIELDS - set(m)
    assert not missing_metric, missing_metric
    c = m["counters"]
    missing = (REF_LOG_FIELDS | REF_SRV_FIELDS) - set(c)
    assert not missing, missing

    # ...and the counters actually count
    assert c["commands"] >= 30
    assert c["write_ops"] >= 30
    assert c["rpcs_sent"] > 0
    assert c["msgs_sent"] >= c["rpcs_sent"]
    assert c["consistent_queries"] >= 1
    assert c["local_queries"] >= 1
    assert c["fetch_term"] > 0
    assert m["last_index"] >= 30 and m["commit_index"] >= 30

    follower = next(s for s in sids if s != leader)
    fm = ra_tpu.key_metrics(follower, router=router)
    assert fm["counters"]["aer_received_follower"] > 0
    assert fm["counters"]["aer_received_follower_empty"] > 0
    assert fm["counters"]["write_ops"] >= 30
    # somebody voted: term/voted_for hit disk at least once
    assert any(
        ra_tpu.key_metrics(s, router=router)["counters"]
        ["term_and_voted_for_updates"] > 0 for s in sids)

    # node-wide infra counters
    sysc = systems[leader.node].counters()
    assert REF_WAL_FIELDS <= set(sysc["wal"])
    assert REF_SEGWRITER_FIELDS <= set(sysc["segment_writer"])
    assert sysc["wal"]["writes"] >= 30
    assert sysc["wal"]["batches"] >= 1
    assert sysc["wal"]["bytes_written"] > 0
    assert sysc["wal"]["syncs"] >= 1
    assert sysc["wal"]["wal_files"] >= 1

    # a rollover drains memtables to segments through the segment writer
    systems[leader.node].wal.rollover()
    systems[leader.node].wal.flush()
    systems[leader.node].segment_writer.await_idle()
    sysc = systems[leader.node].counters()
    assert sysc["segment_writer"]["mem_tables"] >= 1
    assert sysc["segment_writer"]["entries"] >= 1
    assert sysc["segment_writer"]["segments"] >= 1
    assert sysc["segment_writer"]["bytes_written"] > 0
    m = ra_tpu.key_metrics(leader, router=router)
    assert m["counters"]["read_segment"] >= 0  # present post-flush

    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()


def test_snapshot_and_checkpoint_counters(tmp_path):
    router = LocalRouter()
    sid = ServerId("mc", "mcn1")
    system = RaSystem(str(tmp_path / "mcn1"))
    node = RaNode("mcn1", router=router, log_factory=system.log_factory)
    node.start_server(mk_cfg(sid, [sid]))
    ra_tpu.trigger_election(sid, router)
    await_leader(router, [sid])
    for v in range(1, 11):
        ra_tpu.process_command(sid, v, router=router)
    # force a snapshot through the machine-effect path
    shell = node.shells[sid.name]
    srv = shell.server
    from ra_tpu.core.types import Checkpoint, ReleaseCursor
    node._execute(shell, [Checkpoint(index=srv.last_applied,
                                     machine_state=srv.machine_state)])
    node._execute(shell, [ReleaseCursor(index=srv.last_applied,
                                        machine_state=srv.machine_state)])
    m = ra_tpu.key_metrics(sid, router=router)
    c = m["counters"]
    assert c["checkpoints_written"] >= 1
    assert c["checkpoint_bytes_written"] > 0
    assert c["snapshots_written"] >= 1
    assert c["snapshot_bytes_written"] > 0
    assert c["release_cursors"] >= 1
    assert c["checkpoints"] >= 1
    assert m["snapshot_index"] >= 1
    node.stop()
    system.close()
