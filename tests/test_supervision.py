"""Supervised server restart — the ra_server_sup transient-child role:
a crashed member restarts over its surviving DURABLE log (bounded by
restart intensity); in-memory members stay down (restarting them over
an empty log would forget term/voted_for — the amnesia double-vote
hazard); peers get the DOWN signal for the dead incarnation either way.
"""
import time

import pytest

import ra_tpu
from ra_tpu import RaSystem
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerId
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def ids(n=3):
    return [ServerId(f"v{i+1}", f"vn{i+1}") for i in range(n)]


@pytest.fixture
def fabric(tmp_path):
    router = LocalRouter()
    systems = {f"vn{i}": RaSystem(str(tmp_path / f"vn{i}"))
               for i in (1, 2, 3)}
    nodes = {n: RaNode(n, router=router, log_factory=systems[n].log_factory)
             for n in systems}
    yield router, nodes
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()


def _poison_once(shell):
    """Instance-level poison on the shell's (shared, durable) log that
    removes itself after firing once — the restarted incarnation reuses
    the same DurableLog object, so a sticky patch would crash-loop."""
    log = shell.server.log

    def boom(*a, **k):
        try:
            del log.write
        except AttributeError:
            pass
        raise RuntimeError("injected write crash")

    log.write = boom


def test_crashed_server_is_restarted_over_durable_log(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("sup1", counter, sids, router=router)
    leader = await_leader(router, sids)
    for v in (1, 2, 3):
        ra_tpu.process_command(leader, v, router=router)
    victim = [s for s in sids if s != leader][0]
    vnode = nodes[victim.node]
    sh = vnode.shells[victim.name]
    _poison_once(sh)
    # traffic drives an AER into the poisoned log -> crash -> restart
    ra_tpu.process_command(leader, 10, router=router)
    deadline = time.monotonic() + 10
    restarted = None
    while time.monotonic() < deadline:
        cur = vnode.shells.get(victim.name)
        if cur is not None and cur is not sh and not cur.stopped:
            restarted = cur
            break
        time.sleep(0.05)
    assert restarted is not None, "supervisor did not restart the member"
    # the restarted incarnation kept its durable identity and catches up
    assert restarted.server.current_term >= sh.server.current_term
    ra_tpu.process_command(leader, 100, router=router)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if restarted.server.machine_state == 116:
            break
        time.sleep(0.05)
    assert restarted.server.machine_state == 116


def test_restart_intensity_gives_up(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("sup2", counter, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 1, router=router)
    victim = [s for s in sids if s != leader][0]
    vnode = nodes[victim.node]
    sh = vnode.shells[victim.name]
    # sticky poison on the shared durable log: every incarnation crashes
    sh.server.log.write = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("sticky crash"))
    ra_tpu.process_command(leader, 2, router=router)
    deadline = time.monotonic() + 15
    gone = False
    while time.monotonic() < deadline:
        if vnode.shells.get(victim.name) is None:
            # no further restart within the window => gave up
            time.sleep(0.6)
            gone = vnode.shells.get(victim.name) is None
            if gone:
                break
        time.sleep(0.1)
    assert gone, "crash loop was not stopped by restart intensity"
    # the rest of the cluster keeps operating
    r = ra_tpu.process_command(leader, 5, router=router)
    assert r.reply == 8


def test_memory_log_member_is_not_auto_restarted():
    """Without durable identity there is no safe restart: the member
    stays down and peers see it as such."""
    router = LocalRouter()
    nodes = {f"mn{i}": RaNode(f"mn{i}", router=router) for i in (1, 2, 3)}
    try:
        sids = [ServerId(f"w{i}", f"mn{i}") for i in (1, 2, 3)]
        ra_tpu.start_cluster("sup3", counter, sids, router=router)
        leader = await_leader(router, sids)
        ra_tpu.process_command(leader, 1, router=router)
        victim = [s for s in sids if s != leader][0]
        vnode = nodes[victim.node]
        sh = vnode.shells[victim.name]
        sh.server.log.write = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("memory crash"))
        ra_tpu.process_command(leader, 2, router=router)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if vnode.shells.get(victim.name) is None:
                break
            time.sleep(0.05)
        time.sleep(0.5)  # would-be restart window
        assert vnode.shells.get(victim.name) is None
        # majority continues
        r = ra_tpu.process_command(leader, 5, router=router)
        assert r.reply == 8
    finally:
        for n in nodes.values():
            n.stop()
