"""Adversarial election scenarios on the lane engine — elections under
inflight traffic, repeated failovers, concurrent failures in the same
round, and a fuzzed multi-step failure schedule under the 2-D device
mesh (VERDICT r3 weak items 4-5).

The properties asserted are the reference's: committed entries survive
any sequence of leader failures (ra_server.erl §5.4 safety via
increment_commit_index, :2955-2964), an uncommitted suffix of a deposed
leader is truncated and never resurrects (AER consistency repair,
ra_server.erl:1032-1156), and a minority can never commit or elect
(:986-1002).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import CounterMachine, RegisterMachine

from test_register_machine import host_fold

N, P, K = 4, 5, 4


def zeros_step(eng):
    eng.step(jnp.zeros((eng.n_lanes,), jnp.int32),
             jnp.zeros((eng.n_lanes, eng.max_step_cmds,
                        eng.payload_width), eng.payload_dtype))


def drain_committed(eng, limit=32):
    """Drive empty rounds until every lane's leader log is fully
    committed and applied on every active member."""
    lane = np.arange(eng.n_lanes)
    for _ in range(limit):
        st = eng.state
        leads = np.asarray(st.leader_slot)
        tail = np.asarray(st.last_index)[lane, leads]
        com = np.asarray(st.commit)[lane, leads]
        act = np.asarray(st.active)
        app = np.where(act, np.asarray(st.applied),
                       np.iinfo(np.int32).max).min(axis=1)
        if (com >= tail).all() and (app >= com).all():
            return
        zeros_step(eng)
    raise AssertionError("drain_committed did not converge")


def reg_payload(cmds):
    pay = np.zeros((N, K, 4), np.int32)
    for k, c in enumerate(cmds[:K]):
        pay[:, k] = c
    return pay


def test_committed_state_survives_repeated_failovers():
    """Six successive leader kills + elections; every command committed
    in any term survives to the end on every member."""
    rng = np.random.default_rng(7)
    eng = LockstepEngine(RegisterMachine(n_slots=8), N, P,
                         ring_capacity=256, max_step_cmds=K,
                         write_delay=1, donate=False)
    committed = []
    dead = {lane: set() for lane in range(N)}
    for _round in range(6):
        cmds = [(1, int(rng.integers(0, 8)), int(rng.integers(1, 100)), 0)
                for _ in range(K)]
        committed += cmds
        eng.step(jnp.full((N,), K, jnp.int32),
                 jnp.asarray(reg_payload(cmds)))
        drain_committed(eng)
        # revive previously-dead members so the next kill still leaves a
        # 3/5 quorum, then kill each lane's current leader
        leads = np.asarray(eng.state.leader_slot)
        for lane in range(N):
            for slot in list(dead[lane]):
                eng.recover_member(lane, slot)
                dead[lane].discard(slot)
            eng.fail_member(lane, int(leads[lane]))
            dead[lane].add(int(leads[lane]))
        term0 = np.asarray(eng.state.term).copy()
        eng.trigger_election(list(range(N)))
        term1 = np.asarray(eng.state.term)
        assert (term1 == term0 + 1).all(), (term0, term1)
        leads1 = np.asarray(eng.state.leader_slot)
        for lane in range(N):
            assert int(leads1[lane]) not in dead[lane]
    for lane in range(N):
        for slot in list(dead[lane]):
            eng.recover_member(lane, slot)
    drain_committed(eng)
    want = host_fold(committed)
    mac = np.asarray(eng.state.mac)
    for lane in range(N):
        for member in range(P):
            assert mac[lane, member].tolist() == want, \
                (lane, member, mac[lane, member].tolist(), want)


def test_uncommitted_suffix_never_resurrects():
    """A deposed leader's unreplicated suffix (accepted while cut off
    from its majority) must never reach any machine, even after the old
    leader rejoins — while every previously committed write survives."""
    rng = np.random.default_rng(11)
    eng = LockstepEngine(RegisterMachine(n_slots=8), N, P,
                         ring_capacity=256, max_step_cmds=K,
                         write_delay=1, donate=False)
    committed = [(1, int(rng.integers(0, 4)), int(rng.integers(1, 100)), 0)
                 for _ in range(K)]
    eng.step(jnp.full((N,), K, jnp.int32),
             jnp.asarray(reg_payload(committed)))
    drain_committed(eng)

    # cut the leader (slot with current leadership) off from everyone:
    # fail all four followers, then push a doomed write to slot 7
    leads = np.asarray(eng.state.leader_slot)
    for lane in range(N):
        for slot in range(P):
            if slot != int(leads[lane]):
                eng.fail_member(lane, slot)
    doomed = [(1, 7, 777, 0)] * K
    for _ in range(2):
        eng.step(jnp.full((N,), K, jnp.int32),
                 jnp.asarray(reg_payload(doomed)))
    base = eng.committed_total()
    zeros_step(eng)
    assert eng.committed_total() == base, "minority leader committed"

    # majority side comes back without the old leader and elects
    for lane in range(N):
        eng.fail_member(lane, int(leads[lane]))
        for slot in range(P):
            if slot != int(leads[lane]):
                eng.recover_member(lane, slot)
    eng.trigger_election(list(range(N)))
    more = [(1, int(rng.integers(0, 4)), int(rng.integers(1, 100)), 0)
            for _ in range(K)]
    committed += more
    eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(reg_payload(more)))
    drain_committed(eng)

    # deposed leader rejoins; its slot-7 write must be gone everywhere
    for lane in range(N):
        eng.recover_member(lane, int(leads[lane]))
    drain_committed(eng)
    want = host_fold(committed)
    assert want[7] == 0
    mac = np.asarray(eng.state.mac)
    for lane in range(N):
        for member in range(P):
            got = mac[lane, member].tolist()
            assert got[7] == 0, (lane, member, got)
            assert got == want, (lane, member, got, want)


def test_election_with_concurrent_follower_failure_and_traffic():
    """One round carrying everything at once: the leader AND a follower
    fail, an election is requested, and fresh commands arrive.  The new
    leader must seat (3/5 still up), accept the batch in the same round,
    and commit it."""
    eng = LockstepEngine(CounterMachine(), N, P, ring_capacity=128,
                         max_step_cmds=K, donate=False)
    eng.step(jnp.full((N,), K, jnp.int32), jnp.ones((N, K, 1), jnp.int32))
    drain_committed(eng)
    before = eng.committed_total()
    term0 = np.asarray(eng.state.term).copy()
    for lane in range(N):
        eng.fail_member(lane, 0)   # the leader (fresh engine: slot 0)
        eng.fail_member(lane, 1)   # plus one follower
    elect = np.ones((N,), bool)
    eng.step(jnp.full((N,), K, jnp.int32), jnp.ones((N, K, 1), jnp.int32),
             elect_mask=jnp.asarray(elect))
    st = eng.state
    assert (np.asarray(st.term) == term0 + 1).all()
    assert (np.asarray(st.leader_slot) >= 2).all()
    drain_committed(eng)
    # the same-round batch landed on the new leader and committed
    # (+N: each lane's term-opening noop commits too)
    assert eng.committed_total() - before == N * K + N


def test_mesh_sharded_election_fuzz():
    """Fuzzed failure/election schedule under the 2-D (members, lanes)
    mesh: per-step invariants (terms and commits never regress, commit
    bounded by the leader log) and final convergence of all replicas.
    This is the sharded, multi-step version of the dryrun's election
    phase — elections race fresh traffic and follower failures across
    many rounds with the member axis laid out over devices."""
    from ra_tpu.parallel import lane_mesh, state_shardings
    from ra_tpu.engine.lockstep import _step
    from ra_tpu.ops.quorum import evaluate_quorum

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = lane_mesh(devices[:8], member_axis=2)
    n_lanes, n_members, k = 16, 4, 4

    machine = CounterMachine()
    eng = LockstepEngine(machine, n_lanes, n_members, ring_capacity=128,
                         max_step_cmds=k, donate=False)
    shardings = state_shardings(mesh, eng.state)
    state = jax.device_put(eng.state, shardings)
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    lane_sh = NamedSharding(mesh, Pspec("lanes"))
    step = jax.jit(
        functools.partial(_step, machine=machine,
                          ring_capacity=128, apply_window=k + 2,
                          pipeline_window=4096, max_append_batch=128,
                          write_delay=1, ring_io="gather",
                          quorum_fn=evaluate_quorum),
        in_shardings=(shardings, lane_sh, lane_sh,
                      NamedSharding(mesh, Pspec("lanes", "members")),
                      lane_sh, lane_sh, lane_sh, lane_sh, lane_sh),
        out_shardings=(shardings,
                       {"appended_hi": lane_sh, "n_acc": lane_sh,
                        "n_app": lane_sh,
                        # the read-plane aux block (ISSUE 20) is
                        # lane-major like everything else
                        "read_done": lane_sh, "read_shed": lane_sh,
                        "read_stale": lane_sh, "read_replies": lane_sh,
                        "read_watermark": lane_sh,
                        "read_served_lanes": lane_sh,
                        "read_shed_lanes": lane_sh,
                        "read_stale_lanes": lane_sh}))

    rng = np.random.default_rng(3)
    n_new = jnp.full((n_lanes,), k, jnp.int32)
    payloads = jnp.ones((n_lanes, k, 1), jnp.int32)
    confirm = jnp.zeros((n_lanes,), jnp.int32)
    query = jnp.zeros((n_lanes,), bool)
    n_read = jnp.zeros((n_lanes,), jnp.int32)
    read_q = jnp.zeros((n_lanes, eng.read_window, eng.query_width),
                       eng.query_dtype)
    fail_host = np.zeros((n_lanes, n_members), bool)

    prev = jax.device_get(
        {"term": state.term, "commit": state.commit,
         "total": state.total_committed})
    for step_i in range(15):
        # fail at most one member per lane (always a 3/4 quorum left);
        # heal with probability 1/2; elect lanes whose leader is down,
        # plus an occasional gratuitous leadership transfer
        leads = np.asarray(state.leader_slot)
        for lane in range(n_lanes):
            if fail_host[lane].any() and rng.random() < 0.5:
                fail_host[lane] = False
            elif not fail_host[lane].any() and rng.random() < 0.4:
                fail_host[lane, rng.integers(0, n_members)] = True
        elect = fail_host[np.arange(n_lanes), leads].copy()
        elect |= rng.random(n_lanes) < 0.1
        # revived members must be re-seeded before stepping (the host
        # snapshot-install contract of recover_member) — here members
        # only fail transiently within the mask, so active stays
        # governed by the mask itself
        state, _aux = step(state, n_new, payloads,
                           jnp.asarray(fail_host), jnp.asarray(elect),
                           confirm, query, n_read, read_q)
        cur = jax.device_get(
            {"term": state.term, "commit": state.commit,
             "total": state.total_committed})
        assert (cur["term"] >= prev["term"]).all(), step_i
        assert (cur["commit"] >= prev["commit"]).all(), step_i
        assert (cur["total"] >= prev["total"]).all(), step_i
        tails = np.asarray(state.last_index)
        leads = np.asarray(state.leader_slot)
        lane_idx = np.arange(n_lanes)
        assert (cur["commit"][lane_idx, leads] <=
                tails[lane_idx, leads]).all(), step_i
        prev = cur

    # heal in the only loss-free order (the recover_member contract):
    # 1) revive dead NON-leader members (snapshot install from the
    #    leader replica, live or frozen), 2) elect lanes whose leader is
    #    still down — the longest durable log wins, exactly what a
    #    restarting reference leader's log comparison gives — and only
    #    then 3) revive the deposed ex-leader slots from the new leader.
    eng.state = jax.device_get(state)
    eng.state = jax.tree.map(jnp.asarray, eng.state)
    was_down = np.asarray(~eng.state.active)
    leads = np.asarray(eng.state.leader_slot)
    for lane in range(n_lanes):
        for slot in range(n_members):
            if was_down[lane, slot] and slot != leads[lane]:
                eng.recover_member(lane, slot)
    act = np.asarray(eng.state.active)
    stalled = [lane for lane in range(n_lanes)
               if not act[lane, leads[lane]]]
    if stalled:
        eng.trigger_election(stalled)
    leads2 = np.asarray(eng.state.leader_slot)
    act2 = np.asarray(eng.state.active)
    for lane in stalled:
        assert act2[lane, leads2[lane]], (lane, "election failed")
        if not act2[lane, leads[lane]]:
            eng.recover_member(lane, int(leads[lane]))
    drain_committed(eng)
    st = eng.state
    mac = np.asarray(st.mac)
    app = np.asarray(st.applied)
    assert (np.asarray(st.total_committed) > 0).all()
    for lane in range(n_lanes):
        assert (mac[lane] == mac[lane, 0]).all(), (lane, mac[lane])
        assert (app[lane] == app[lane, 0]).all(), (lane, app[lane])
