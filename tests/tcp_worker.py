"""Shared OS-process worker for the TCP fabric tests (importable so the
spawn context can pickle the entrypoint).  One process = one RaNode
behind a TcpRouter = one cluster member — the erlang_node_helpers /
inet_tcp_proxy role of the reference's coordination/partitions suites.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_machine(kind: str):
    from ra_tpu.core.machine import Machine, SimpleMachine
    from ra_tpu.core.types import ReleaseCursor

    if kind == "counter":
        return SimpleMachine(lambda c, s: s + c, 0)
    if kind == "list":
        # append-only list: no-loss/no-dup is directly assertable
        return SimpleMachine(lambda c, s: s + [c], [])
    if kind == "snapcounter":
        class SnapCounter(Machine):
            """Counter that releases its cursor every 32 applies (the
            ra_bench release_cursor pattern, ra_bench.erl:43-49) so the
            log truncates and laggards need a snapshot."""

            def init(self, config):
                return 0

            def apply(self, meta, command, state):
                new = state + command
                if meta.index % 32 == 0:
                    return new, new, [ReleaseCursor(meta.index, new)]
                return new, new
        return SnapCounter()
    raise ValueError(kind)


def worker_main(node_name, port_map, cmd_q, res_q, machine_kind="counter",
                data_dir=None, election_timeout_ms=500,
                extra_members=()):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ra_tpu
    from ra_tpu.core.types import Membership, ServerConfig, ServerId
    from ra_tpu.node import RaNode
    from ra_tpu.transport.tcp import TcpRouter

    from ra_tpu.machines import machine_spec, register_machine, \
        resolve_machine
    register_machine("tcpw", make_machine)

    my_addr = ("127.0.0.1", port_map[node_name])
    book = {n: ("127.0.0.1", p) for n, p in port_map.items()
            if n != node_name}
    router = TcpRouter(my_addr, book)
    system = None
    if data_dir:
        from ra_tpu.system import RaSystem
        system = RaSystem(data_dir)
        node = RaNode(node_name, router=router, system=system)
    else:
        node = RaNode(node_name, router=router)
    member_names = sorted(set(port_map) - set(extra_members)
                          - {"client"})
    sids = [ServerId(f"m_{n}", n) for n in member_names]
    me = ServerId(f"m_{node_name}", node_name)
    log_args = {"data_dir": data_dir} if data_dir else {}
    # spec-built machine: the config snapshot then persists the recipe,
    # so the control plane can restart this member from disk alone
    cfg = ServerConfig(
        server_id=me, uid=f"uid_{node_name}", cluster_name="tcp",
        initial_members=tuple(sids),
        machine=resolve_machine(machine_spec("tcpw", kind=machine_kind)),
        election_timeout_ms=election_timeout_ms, tick_interval_ms=200,
        log_init_args=log_args)
    if node_name not in extra_members:
        node.start_server(cfg)

    # readiness handshake: jax import + router bind + server recovery can
    # take tens of seconds on a loaded single-core box — the driver must
    # not start asking (or electing) until every worker is actually up
    res_q.put(("ready", node_name))

    while True:
        cmd = cmd_q.get()
        op = cmd[0]
        try:
            if op == "stop":
                node.stop()
                router.stop()
                res_q.put(("stopped", node_name))
                return
            elif op == "elect":
                ra_tpu.trigger_election(me, router)
                res_q.put(("ok",))
            elif op == "command":
                r = ra_tpu.process_command(me, cmd[1], router=router,
                                           timeout=cmd[2] if len(cmd) > 2
                                           else 15.0)
                res_q.put(("ok", r.reply, str(r.leader)))
            elif op == "state":
                sh = node.shells.get(me.name)
                if sh is None:
                    res_q.put(("ok", "noproc", None, 0))
                else:
                    res_q.put(("ok", sh.server.raft_state.value,
                               sh.server.machine_state,
                               sh.server.current_term))
            elif op == "members":
                sh = node.shells.get(me.name)
                res_q.put(("ok", sorted(s.name for s in
                                        sh.server.cluster)))
            elif op == "metrics":
                res_q.put(("ok", ra_tpu.key_metrics(me, router=router)))
            elif op == "overview":
                res_q.put(("ok", router.overview()))
            elif op == "partition":
                for n in cmd[1]:
                    router.block_node(n)
                res_q.put(("ok",))
            elif op == "heal":
                for n in list(router.blocked_nodes):
                    router.unblock_node(n)
                res_q.put(("ok",))
            elif op == "start_member":
                # start this node's member late (join flow)
                node.start_server(cfg)
                res_q.put(("ok",))
            elif op == "add_member":
                target = ServerId(f"m_{cmd[1]}", cmd[1])
                r = ra_tpu.add_member(me, target, router=router,
                                      membership=Membership.PROMOTABLE)
                res_q.put(("ok", str(r)))
            elif op == "remove_member":
                target = ServerId(f"m_{cmd[1]}", cmd[1])
                r = ra_tpu.remove_member(me, target, router=router)
                res_q.put(("ok", str(r)))
            elif op == "restart_server":
                ra_tpu.restart_server(me, router=router)
                res_q.put(("ok",))
            elif op == "kill_wal":
                # fault injection: crash this node's fan-in WAL thread
                # (the coordination_SUITE segment_writer_or_wal_crash_*
                # scenarios); the system supervisor restarts it
                system.wal.kill()
                res_q.put(("ok",))
            elif op == "wal_alive":
                res_q.put(("ok", bool(system.wal.alive)))
            else:
                res_q.put(("err", f"unknown op {op}"))
        except Exception as e:  # noqa: BLE001 — report to the test
            res_q.put(("err", repr(e)))
