"""JitKvMachine — device-path KV semantics, differential-tested against
the host KvMachine oracle (models/kv.py), and run under the lane engine
and the classic replicated path."""
import jax.numpy as jnp
import numpy as np

import ra_tpu
from ra_tpu.core.machine import ApplyMeta
from ra_tpu.core.types import ServerId
from ra_tpu.engine import LockstepEngine
from ra_tpu.models import JitKvMachine, KvMachine
from ra_tpu.models.jit_kv import query_kv
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader
import pytest

META = {"index": jnp.int32(1), "term": jnp.int32(1)}


def test_scripted_semantics():
    m = JitKvMachine(n_keys=4)
    st = m.jit_init(1)[0]

    st, r = m.jit_apply(META, m.encode_command(("put", 1, 10)), st)
    assert r.tolist() == [1, -1]  # old value absent
    st, r = m.jit_apply(META, m.encode_command(("get", 1)), st)
    assert r.tolist() == [1, 10]
    st, r = m.jit_apply(META, m.encode_command(("get", 2)), st)
    assert r.tolist() == [0, -1]
    st, r = m.jit_apply(META, m.encode_command(("cas", 1, 10, 20)), st)
    assert r.tolist() == [1, 10] and int(st[1]) == 20
    st, r = m.jit_apply(META, m.encode_command(("cas", 1, 10, 30)), st)
    assert r.tolist() == [0, 20] and int(st[1]) == 20
    # cas expecting absence; cas deleting on success (None -> -1)
    st, r = m.jit_apply(META, m.encode_command(("cas", 2, None, 7)), st)
    assert r.tolist() == [1, -1] and int(st[2]) == 7
    st, r = m.jit_apply(META, m.encode_command(("cas", 2, 7, None)), st)
    assert r.tolist() == [1, 7] and int(st[2]) == -1
    st, r = m.jit_apply(META, m.encode_command(("delete", 1)), st)
    assert r.tolist() == [1, 20] and int(st[1]) == -1
    st, r = m.jit_apply(META, m.encode_command(("delete", 1)), st)
    assert r.tolist() == [0, -1]
    # noop untouched
    st2, r = m.jit_apply(META, jnp.zeros((4,), jnp.int32), st)
    assert np.array_equal(np.asarray(st), np.asarray(st2))
    # out-of-range keys: rejected with -2, no aliasing onto boundary cells
    for bad_key in (-1, 4, 1000):
        st3, r = m.jit_apply(META, m.encode_command(("put", bad_key, 5)), st)
        assert r.tolist() == [-2, -1]
        assert np.array_equal(np.asarray(st), np.asarray(st3))
    # a negative put value must not store the absent sentinel: rejected
    # with -2 like the bad-key path (stored-values >= 0 contract)
    st, _ = m.jit_apply(META, m.encode_command(("put", 3, 9)), st)
    for bad_put in (("put", 3, None), ("put", 3, -5)):
        st4, r = m.jit_apply(META, m.encode_command(bad_put), st)
        assert r.tolist() == [-2, -1]
        assert int(st4[3]) == 9  # untouched
    # cas with a new value below -1 is malformed (only -1 = delete)
    st5, r = m.jit_apply(META, m.encode_command(("cas", 3, 9, -7)), st)
    assert r.tolist() == [-2, -1] and int(st5[3]) == 9


def test_differential_vs_host_kv_machine():
    rng = np.random.default_rng(13)
    host = KvMachine()
    hstate = host.init({})
    dev = JitKvMachine(n_keys=16)
    dstate = dev.jit_init(1)[0]
    idx = 0

    for _ in range(500):
        key = int(rng.integers(0, 16))
        roll = rng.integers(0, 10)
        if roll < 4:
            cmd = ("put", key, int(rng.integers(0, 50)))
        elif roll < 6:
            cmd = ("delete", key)
        else:
            expect = (None if rng.integers(0, 3) == 0
                      else int(rng.integers(0, 50)))
            new = (None if rng.integers(0, 5) == 0
                   else int(rng.integers(0, 50)))
            cmd = ("cas", key, expect, new)
        idx += 1
        hstate, hreply, _ = host.apply(ApplyMeta(index=idx, term=1),
                                       cmd, hstate)
        dstate, dreply = dev.jit_apply(META, dev.encode_command(cmd),
                                       dstate)
        code, val = int(dreply[0]), int(dreply[1])
        if cmd[0] == "put":
            assert (None if val < 0 else val) == hreply
        elif cmd[0] == "delete":
            assert (None if val < 0 else val) == hreply
        elif cmd[0] == "cas":
            assert ("ok" if code else "failed") == hreply[0]
            assert (None if val < 0 else val) == hreply[1]
        # full-state alignment
        want = {k: v for k, v in hstate.data.items()}
        got = {k: int(v) for k, v in enumerate(np.asarray(dstate))
               if v >= 0}
        assert got == want


def test_engine_replicas_match_oracle():
    rng = np.random.default_rng(17)
    N, K, STEPS, S = 16, 8, 6, 8
    m = JitKvMachine(n_keys=S)
    eng = LockstepEngine(m, N, 5, ring_capacity=256, max_step_cmds=K,
                         donate=False)
    lane_cmds = [[] for _ in range(N)]
    for _ in range(STEPS):
        payloads = np.zeros((N, K, 4), np.int32)
        for lane in range(N):
            for k in range(K):
                op = int(rng.integers(1, 5))
                key = int(rng.integers(0, S))
                value = int(rng.integers(0, 30))
                expected = int(rng.integers(-1, 30))
                payloads[lane, k] = (op, key, value, expected)
                lane_cmds[lane].append((op, key, value, expected))
        eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(payloads))
    for _ in range(4):
        eng.step(jnp.zeros((N,), jnp.int32), jnp.zeros((N, K, 4), jnp.int32))
    eng.block_until_ready()

    def fold(cmds):
        vals = [-1] * S
        for op, key, value, expected in cmds:
            if op == 1:
                vals[key] = value
            elif op == 3:
                vals[key] = -1
            elif op == 4 and vals[key] == expected:
                vals[key] = value
        return vals

    mac = np.asarray(eng.state.mac)  # [N, P, S]
    for lane in range(N):
        want = fold(lane_cmds[lane])
        for member in range(5):
            assert mac[lane, member].tolist() == want, (lane, member)


def test_same_machine_on_classic_path():
    router = LocalRouter()
    nodes = [RaNode(f"jkn{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"jk{i}", f"jkn{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("jkv", lambda: JitKvMachine(n_keys=8),
                             sids, router=router)
        leader = await_leader(router, sids)
        assert ra_tpu.process_command(
            leader, ("put", 3, 9), router=router).reply == (1, None)
        assert ra_tpu.process_command(
            leader, ("cas", 3, 9, 11), router=router).reply == (1, 9)
        assert ra_tpu.process_command(
            leader, ("get", 3), router=router).reply == (1, 11)
        res = ra_tpu.consistent_query(leader, query_kv, router=router)
        assert res.reply == {3: 11}
    finally:
        for n in nodes:
            n.stop()


@pytest.mark.parametrize("seed", [7, 19, 43])
def test_batch_apply_matches_sequential_fold(seed):
    """jit_apply_batch == an in-order masked jit_apply fold on BOTH
    internal paths: the last-writer-wins fast path (cas-free windows:
    put/get/delete incl. out-of-range keys and negative put values) and
    the lax.cond fallback scan once a cas appears in the window."""
    rng = np.random.default_rng(seed)
    S, A, N = 8, 6, 4
    m = JitKvMachine(n_keys=S)
    state = m.jit_init(N)
    for i in range(5):   # warmup so cells hold values
        cmd = np.zeros((N, 4), np.int32)
        cmd[:, 0] = 1
        cmd[:, 1] = rng.integers(0, S, N)
        cmd[:, 2] = rng.integers(0, 50, N)
        state, _ = m.jit_apply({"index": i, "term": 1},
                               jnp.asarray(cmd), state)

    for hi_op, label in ((4, "fast"), (5, "with-cas")):
        cmds = np.zeros((N, A, 4), np.int32)
        cmds[..., 0] = rng.integers(0, hi_op, size=(N, A))
        cmds[..., 1] = rng.integers(-1, S + 1, size=(N, A))  # incl. bad keys
        cmds[..., 2] = rng.integers(-2, 50, size=(N, A))     # incl. bad vals
        cmds[..., 3] = rng.integers(-1, 50, size=(N, A))
        mask = rng.random((N, A)) < 0.8
        mask[0, :] = True
        cmds_j = jnp.asarray(cmds)
        mask_j = jnp.asarray(mask)
        idx = jnp.broadcast_to(jnp.arange(A, dtype=jnp.int32), (N, A))
        got = m.jit_apply_batch({"index": idx, "term": jnp.int32(1)},
                                cmds_j, mask_j, state)
        want = state
        for i in range(A):
            new, _ = m.jit_apply({"index": idx[:, i], "term": 1},
                                 cmds_j[:, i], want)
            want = jnp.where(mask_j[:, i][..., None], new, want)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=label)
        state = want
