"""Sharded WAL plane tests: per-shard group commit, compacted device->
host readback accounting, ragged crash coverage across shards, and
shard-count migration.

Reference behaviour being extended: the single fan-in WAL writer of
ra_log_wal.erl (one batch, one fdatasync for every co-hosted server)
multiplied across lane shards — each shard keeps the same confirm-
before-commit contract over its lane slice, and the merged per-lane
confirm vector feeds the engine's quorum gate exactly as before.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ra_tpu.engine import open_engine
from ra_tpu.log import faults
from ra_tpu.log.faults import DiskFaultPlan, DiskFaultSpec
from ra_tpu.log.wal import Wal
from ra_tpu.models import CounterMachine

N, P, K = 16, 3, 8

# the poison->escalate ladder may legitimately kill a shard's batch
# thread under injected faults; the shard supervisor restarts it
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")


def make(tmp_path, shards, **kw):
    kw.setdefault("sync_mode", 0)
    kw.setdefault("ring_capacity", 256)
    kw.setdefault("max_step_cmds", K)
    return open_engine(CounterMachine(), str(tmp_path), N, P,
                       wal_shards=shards, **kw)


def drive(eng, n_steps, cmds=4):
    n_new = np.full((N,), cmds, np.int32)
    payloads = np.ones((N, eng.max_step_cmds, 1), np.int32)
    for _ in range(n_steps):
        eng.step(n_new, payloads)


def settle(eng, max_steps=30):
    zero_n = np.zeros((N,), np.int32)
    zero_p = np.zeros((N, eng.max_step_cmds, 1), np.int32)
    for _ in range(max_steps):
        eng.step(zero_n, zero_p)
        eng._dur.drain_all()
        eng._dur.flush_all()


def leader_view(eng, field):
    st = eng.state
    lane = np.arange(N)
    return np.asarray(getattr(st, field))[lane,
                                          np.asarray(st.leader_slot)]


@pytest.mark.parametrize("shards", [2, 4])
def test_sharded_commit_and_recover(tmp_path, shards):
    """Commits gate on the merged per-shard confirms, and recovery from
    the sharded layout restores everything ever reported committed with
    oracle-exact machine state (pure +1 workload: counter == applied)."""
    eng = make(tmp_path, shards)
    assert len(eng._dur.wals) == shards
    drive(eng, 10)
    settle(eng)
    com = leader_view(eng, "commit").copy()
    assert com.sum() > 0
    assert (com <= eng._dur.confirm_upto).all()
    # every shard wrote into its own file sequence
    for i, sh in enumerate(eng._dur._shards):
        assert sh.wal.counters["writes"] > 0, i
    eng.close()

    eng2 = make(tmp_path, shards)
    com2 = leader_view(eng2, "commit")
    assert (com2 >= com).all()
    mac = np.asarray(eng2.state.mac)
    app = np.asarray(eng2.state.applied)
    act = np.asarray(eng2.state.active)
    assert (mac[act] == app[act]).all()
    eng2.close()


def test_shard_count_change_recovers(tmp_path):
    """Blocks self-describe their lane slice (RTB1/RTB2), so reopening
    with a different wal_shards needs no migration: 1 -> 4 -> 1."""
    eng = make(tmp_path, 1)
    drive(eng, 6)
    settle(eng)
    com = leader_view(eng, "commit").copy()
    eng.close()

    eng2 = make(tmp_path, 4)
    com2 = leader_view(eng2, "commit")
    assert (com2 >= com).all()
    drive(eng2, 6)
    settle(eng2)
    com2 = leader_view(eng2, "commit").copy()
    eng2.close()

    eng3 = make(tmp_path, 1)
    com3 = leader_view(eng3, "commit")
    assert (com3 >= com2).all()
    mac = np.asarray(eng3.state.mac)
    app = np.asarray(eng3.state.applied)
    act = np.asarray(eng3.state.active)
    assert (mac[act] == app[act]).all()
    eng3.close()
    # the legacy single-shard layout is pruned at the first checkpoint
    eng4 = make(tmp_path, 4)
    drive(eng4, 2)
    eng4.checkpoint()
    assert not os.path.isdir(os.path.join(str(tmp_path), "wal")) or \
        not os.listdir(os.path.join(str(tmp_path), "wal"))
    eng4.close()


def test_torn_shard_tail_recovery(tmp_path):
    """Crash mid-write on ONE shard (torn tail): recovery merges the
    ragged per-shard coverage — the torn shard's lanes replay their
    surviving prefix and carry forward, every other lane keeps its full
    log, and the merged state stays oracle-consistent."""
    eng = make(tmp_path, 4)
    drive(eng, 8)
    settle(eng)
    com = leader_view(eng, "commit").copy()
    torn = eng._dur._shards[2]
    lo, hi = torn.lo, torn.hi
    wal_dir = torn.wal.dir
    eng.close()

    # tear the newest wal file of shard 2 mid-record
    files = sorted(f for f in os.listdir(wal_dir) if f.endswith(".wal"))
    assert files
    path = os.path.join(wal_dir, files[-1])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(4, size - 11))

    eng2 = make(tmp_path, 4)
    com2 = leader_view(eng2, "commit")
    outside = np.ones((N,), bool)
    outside[lo:hi] = False
    # untouched shards lose nothing
    assert (com2[outside] >= com[outside]).all()
    # the torn shard's lanes recover a (possibly shorter) prefix, and
    # the whole merged state is still the oracle at its apply frontier
    mac = np.asarray(eng2.state.mac)
    app = np.asarray(eng2.state.applied)
    act = np.asarray(eng2.state.active)
    assert (mac[act] == app[act]).all()
    # the lane engine keeps working after the ragged recovery
    drive(eng2, 4)
    settle(eng2)
    com3 = leader_view(eng2, "commit")
    assert (com3 > com2).all()
    eng2.close()


def test_group_commit_amortizes_fsyncs(tmp_path):
    """With a nonzero batch interval the writer holds the group open and
    one fdatasync covers the burst (flush on max_batch_bytes OR
    max_batch_interval_ms — ra_log_wal.erl:193-214 extended with an
    explicit wait budget)."""
    confirmed = []
    done = threading.Event()

    def notify(uid, lo, hi, term):
        confirmed.append((lo, hi))
        if hi >= 20:
            done.set()

    wal = Wal(str(tmp_path), sync_mode=1, max_batch_interval_ms=150.0)
    try:
        wal.register("u", notify)
        for i in range(1, 21):
            wal.write("u", i, 1, b"x" * 64)
        assert done.wait(5.0)
        wal.flush()
        assert wal.counters["writes"] == 20
        # the burst lands in very few groups => few durability syscalls
        assert wal.counters["syncs"] <= 3, wal.counters
        st = wal.stats()
        assert st["records_per_fsync"] >= 5
        assert st["fsync_p50_ms"] >= 0
    finally:
        wal.close()


def test_group_commit_byte_cap_closes_group(tmp_path):
    """max_batch_bytes closes a group early even inside the interval."""
    wal = Wal(str(tmp_path), sync_mode=0, max_batch_interval_ms=500.0,
              max_batch_bytes=256)
    try:
        wal.register("u", lambda *a: None)
        t0 = time.monotonic()
        for i in range(1, 9):
            wal.write("u", i, 1, b"y" * 128)
        wal.flush()
        # 8 * 128B at a 256B cap: the writer must not sit out the full
        # 500ms interval per group
        assert time.monotonic() - t0 < 2.0
        assert wal.counters["writes"] == 8
        assert wal.counters["batches"] >= 2
    finally:
        wal.close()


def test_compacted_readback_counters(tmp_path):
    """The device-side payload compaction shrinks the per-step host
    readback by the occupancy factor: at 2 accepted commands of a
    16-wide batch the compacted bytes must be >= 2x below what the
    full-ring readback would have moved (the ISSUE 3 CI criterion)."""
    eng = make(tmp_path, 1, max_step_cmds=16)
    n_new = np.full((N,), 2, np.int32)   # 2 of 16 slots occupied
    payloads = np.ones((N, 16, 1), np.int32)
    for _ in range(8):
        eng.step(n_new, payloads)
    eng._dur.drain_all()
    ctr = eng._dur.counters
    assert ctr["encoded_blocks"] >= 8
    assert ctr["readback_bytes"] * 2 <= ctr["readback_bytes_full"], ctr
    eng.close()


def test_superstep_block_submit_feeds_every_shard(tmp_path):
    """A K-fused dispatch's stacked aux lands on the sharded WAL plane
    as K consecutive per-inner-step jobs on EVERY shard (ISSUE 5:
    submit_block slices the [K, ...] leaves; record format, per-shard
    file sequences and the merged confirm vector are unchanged), and
    recovery from a superstep-driven sharded layout is oracle-exact."""
    eng = make(tmp_path, 4, max_pending=32)
    SK = 4
    seq0 = eng._dur.step_seq
    n_new = np.full((SK, N), 4, np.int32)
    pay = np.ones((SK, N, eng.max_step_cmds, 1), np.int32)
    for _ in range(5):
        eng.superstep(n_new, pay)
    # step_seq advances one per INNER step — K per fused dispatch
    assert eng._dur.step_seq - seq0 == 5 * SK
    settle(eng)
    com = leader_view(eng, "commit").copy()
    assert com.sum() > 0
    assert (com <= eng._dur.confirm_upto).all()
    for i, sh in enumerate(eng._dur._shards):
        assert sh.wal.counters["writes"] > 0, i
    eng.close()

    eng2 = make(tmp_path, 4)
    com2 = leader_view(eng2, "commit")
    assert (com2 >= com).all()
    mac = np.asarray(eng2.state.mac)
    app = np.asarray(eng2.state.applied)
    act = np.asarray(eng2.state.active)
    assert (mac[act] == app[act]).all()
    eng2.close()


def test_wal_overview_reports_shard_health(tmp_path):
    """engine.overview() merges ENGINE_WAL_FIELDS and per-shard WAL
    stats (batch bytes, records/fsync, fsync p50/p99, confirm lag) —
    the RPC_FIELDS observability pattern on the durability plane."""
    eng = make(tmp_path, 2, sync_mode=1)
    drive(eng, 4)
    settle(eng, 6)
    ov = eng.overview()
    w = ov["wal"]
    for f in ("readback_bytes", "readback_bytes_full", "encoded_blocks",
              "encoded_bytes", "confirm_lag_steps"):
        assert f in w["engine"], f
    assert len(w["shards"]) == 2
    for st in w["shards"]:
        for f in ("bytes_written", "records_per_fsync", "fsync_p50_ms",
                  "fsync_p99_ms", "confirm_lag_steps", "lanes"):
            assert f in st, st
        assert st["bytes_written"] > 0
        assert st["syncs"] > 0
    assert w["engine"]["confirm_lag_steps"] == 0  # settled
    eng.close()


def test_poisoned_shard_holds_back_confirms(tmp_path):
    """fsync-EIO on ONE shard (shard03): its confirm slice freezes at
    the durable horizon, so the merged confirm vector — and therefore
    the fsync-gated commit — provably never advances past unfsynced
    entries; once the fault clears, the poison/rollover resend path
    catches the shard back up and recovery is oracle-exact (the
    per-shard confirm hold-back of ISSUE 4)."""
    faults.reset_disk_fault_counters()
    eng = make(tmp_path, 4, sync_mode=1)
    try:
        drive(eng, 4)
        settle(eng, 6)
        torn = eng._dur._shards[3]
        faults.install_plan(DiskFaultPlan(seed=31, rules=[
            ("wal", DiskFaultSpec(fsync_eio=1.0, limit=3,
                                  path_match="shard03"))]))
        n_new = np.full((N,), 2, np.int32)
        payloads = np.ones((N, K, 1), np.int32)
        from ra_tpu.log.wal import WalDown
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                eng.step(n_new, payloads)
            except WalDown:
                pass  # supervisor races the ladder's rung 3
            # the acceptance invariant, sampled every step: commit is
            # gated on the MERGED confirm vector
            lane = np.arange(N)
            st = eng.state
            com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
            assert (com <= eng._dur.confirm_upto).all(), \
                (com, eng._dur.confirm_upto)
            time.sleep(0.05)  # let the batch thread reach its fsync
            if faults.disk_fault_counters()["poisoned_files"] >= 1:
                break
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["faults_injected"] >= 1, ctr
        assert ctr["poisoned_files"] >= 1, ctr
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        # fault cleared: the shard catches up and commits resume
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                (not torn.wal.alive or
                 torn.confirmed_step < eng._dur.step_seq):
            try:
                settle(eng, 2)
            except (WalDown, TimeoutError):
                time.sleep(0.05)
        assert torn.wal.alive
        com = leader_view(eng, "commit").copy()
        assert (com > 0).all()
        assert (com <= eng._dur.confirm_upto).all()
    finally:
        faults.clear_plan()
        eng.close()
    # cold reopen: oracle-exact at the apply frontier
    eng2 = make(tmp_path, 4, sync_mode=1)
    com2 = leader_view(eng2, "commit")
    assert (com2 >= com).all()
    mac = np.asarray(eng2.state.mac)
    app = np.asarray(eng2.state.applied)
    act = np.asarray(eng2.state.active)
    assert (mac[act] == app[act]).all()
    eng2.close()


_FAULT_CHILD = r"""
import os, sys, json
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from ra_tpu.utils import force_platform_from_env
force_platform_from_env()
from ra_tpu.engine import open_engine
from ra_tpu.log import faults
from ra_tpu.log.faults import DiskFaultPlan, DiskFaultSpec
from ra_tpu.models import CounterMachine

# the ISSUE 4 kill-9 matrix plan: torn writes on shard 0, fsync-EIO on
# shard 3 — active for the child's WHOLE life, including its recovery
faults.install_plan(DiskFaultPlan(seed=97, rules=[
    ("wal", DiskFaultSpec(short_write=0.10, limit=6,
                          path_match="shard00")),
    ("wal", DiskFaultSpec(fsync_eio=0.15, limit=6,
                          path_match="shard03")),
]))

N, P, K = 16, 3, 8
eng = open_engine(CounterMachine(), sys.argv[1], N, P,
                  sync_mode=1, ring_capacity=256, max_step_cmds=K,
                  wal_shards=4)
report = sys.argv[2]
n_new = np.full((N,), 4, np.int32)
payloads = np.ones((N, K, 1), np.int32)
lane = np.arange(N)
from ra_tpu.log.wal import WalDown
import time as _time
for i in range(10_000):
    try:
        eng.step(n_new, payloads)
    except WalDown:
        _time.sleep(0.05)  # shard supervisor races the escalation rung
        continue
    if i % 5 == 4:
        # report the fsync-confirmed commit frontier crash-safely; the
        # min() with confirm_upto is the fsynced-watermark clamp
        st = eng.state
        com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
        com = np.minimum(com, eng._dur.confirm_upto)
        tmp = report + ".tmp"
        with open(tmp, "w") as f:
            json.dump([int(x) for x in com], f)
            f.flush(); os.fsync(f.fileno())
        os.replace(tmp, report)
        print("REPORTED", i, flush=True)
"""


def test_kill9_with_active_disk_faults_recovers_reported(tmp_path):
    """The kill-9 matrix under an ACTIVE DiskFaultPlan (torn write on
    shard 0, fsync-EIO on shard 3): SIGKILL mid-bench while the
    degradation ladder is live, then recover with NO faults — every
    commit the child ever reported (clamped to the fsynced watermark)
    survives, and the replayed state is oracle-exact."""
    import json

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data = str(tmp_path / "data")
    report = str(tmp_path / "report.json")
    child = subprocess.Popen(
        [sys.executable, "-c", _FAULT_CHILD.format(repo=repo), data,
         report],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
             "PYTHONPATH": ""})
    import select
    deadline = time.time() + 360
    reports = 0
    fd = child.stdout.fileno()
    buf = b""
    while time.time() < deadline and reports < 4:
        ready, _, _ = select.select([fd], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(fd, 65536)
        if not chunk:
            break
        buf += chunk
        reports = sum(1 for line in buf.split(b"\n")[:-1]
                      if line.startswith(b"REPORTED"))
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=30)
    assert reports >= 4, child.stderr.read()

    with open(report) as f:
        reported = np.array(json.load(f), np.int32)
    assert reported.sum() > 0

    eng = make(tmp_path / "data", 4, sync_mode=1)
    lane = np.arange(N)
    st = eng.state
    com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
    assert (com >= reported).all(), (com, reported)
    # oracle equivalence at the recovered apply frontier (+1 workload)
    mac = np.asarray(st.mac)
    app = np.asarray(st.applied)
    act = np.asarray(st.active)
    assert (mac[act] == app[act]).all(), (mac, app)
    assert (mac[lane, np.asarray(st.leader_slot)] >= reported).all()
    eng.close()


def test_checkpoint_prunes_every_shard(tmp_path):
    eng = make(tmp_path, 4)
    drive(eng, 6)
    eng.checkpoint()
    for sh in eng._dur._shards:
        files = [f for f in os.listdir(sh.wal.dir)
                 if f.endswith(".wal")]
        assert len(files) == 1, (sh.idx, files)  # only the fresh file
    eng.close()
