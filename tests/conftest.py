import os
import sys

# Tests run sharding on a virtual multi-device CPU mesh; the real chip is
# only exercised by bench.py.  Export RA_TPU_TEST_PLATFORM to override.
# This must OVERRIDE (not setdefault): images with a TPU tunnel export
# JAX_PLATFORMS=<plugin> globally, which would silently point the whole
# suite at the tunnel and hang every test when the tunnel is down.
# (If the tunnel's site hook already registered a plugin whose discovery
# blocks on a dead endpoint, additionally launch pytest with PYTHONPATH=
# so the hook never runs.)
os.environ["JAX_PLATFORMS"] = os.environ.get("RA_TPU_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from ra_tpu.utils import force_platform_from_env  # noqa: E402

force_platform_from_env()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _scoped_fault_plans():
    """Scope fault-plan registration to the test that created it.

    Both plan registries are process-global: transport FaultPlans land
    in a weakly-held live set (rpc._LIVE_PLANS) and DiskFaultPlans in a
    module slot (log.faults).  A test that leaks a plan — a lossy spec
    pinned by a router a leaked node keeps alive — used to poison every
    later guard probe (the tier-1 quiet-plan probe self-skipped).  This
    finalizer unregisters plans REGISTERED during the test and restores
    the installed disk plan, so the probes run unconditionally; the
    leaked objects themselves stay wired wherever they are (only the
    registry listing is scoped)."""
    from ra_tpu.log import faults
    from ra_tpu.transport import rpc
    # hold STRONG refs to the pre-existing plans: an id()-only snapshot
    # could alias a plan that dies mid-test with a test-created one
    # allocated at the recycled address, letting the new plan escape
    pre_net = list(rpc.live_fault_plans())
    pre_disk = faults.current_plan()
    yield
    for p in rpc.live_fault_plans():
        if p not in pre_net:
            p.unregister()
    if faults.current_plan() is not pre_disk:
        if pre_disk is None:
            faults.clear_plan()
        else:
            faults.install_plan(pre_disk)
