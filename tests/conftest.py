import os
import sys

# Tests run sharding on a virtual multi-device CPU mesh; the real chip is
# only exercised by bench.py.  Export RA_TPU_TEST_PLATFORM to override.
# This must OVERRIDE (not setdefault): images with a TPU tunnel export
# JAX_PLATFORMS=<plugin> globally, which would silently point the whole
# suite at the tunnel and hang every test when the tunnel is down.
# (If the tunnel's site hook already registered a plugin whose discovery
# blocks on a dead endpoint, additionally launch pytest with PYTHONPATH=
# so the hook never runs.)
os.environ["JAX_PLATFORMS"] = os.environ.get("RA_TPU_TEST_PLATFORM", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from ra_tpu.utils import force_platform_from_env  # noqa: E402

force_platform_from_env()
