import os
import sys

# Tests run sharding on a virtual multi-device CPU mesh; the real chip is
# only exercised by bench.py.  Export JAX_PLATFORMS=tpu to override.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from ra_tpu.utils import force_platform_from_env  # noqa: E402

force_platform_from_env()
