"""Durable lane-engine tests: fsync-gated commits, WAL crash/restart
survival, checkpoint pruning, election truncation across the WAL
boundary, and a kill -9 recovery test.

Reference behaviour being matched: an entry counts toward the commit
median only after write(2)+fsync (/root/reference/src/ra_log_wal.erl:
753-800), WAL crash -> resend above the durable horizon
(/root/reference/src/ra_log.erl:778-793), and recovery = snapshot + WAL
re-read with overwrite dedup (/root/reference/src/ra_log_wal.erl:871-955).
"""
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ra_tpu.engine import LockstepEngine, open_engine
from ra_tpu.engine.durable import (decode_block, encode_block,
                                   _final_logs)
from ra_tpu.models import CounterMachine



N, P, K = 16, 3, 8


def make_engine(tmp_path, **kw):
    kw.setdefault("sync_mode", 0)  # tests: no fsync, same protocol
    kw.setdefault("ring_capacity", 256)
    kw.setdefault("max_step_cmds", K)
    return open_engine(CounterMachine(), str(tmp_path), N, P, **kw)


def drive(eng, n_steps, cmds=4, value=1):
    n_new = np.full((N,), cmds, np.int32)
    payloads = np.full((N, K, 1), value, np.int32)
    for _ in range(n_steps):
        eng.step(n_new, payloads)


def settle(eng, max_steps=50):
    zero_n = np.zeros((N,), np.int32)
    zero_p = np.zeros((N, K, 1), np.int32)
    for _ in range(max_steps):
        eng.step(zero_n, zero_p)
        eng._dur.drain_all()
        eng._dur.wal.flush()
    return eng


# -- block codec ------------------------------------------------------------

def test_block_roundtrip():
    rng = np.random.default_rng(0)
    hi = rng.integers(1, 100, N).astype(np.int32)
    n_acc = rng.integers(0, K, N).astype(np.int32)
    n_app = n_acc + rng.integers(0, 2, N).astype(np.int32)
    ph = rng.integers(0, 1000, (N, K, 1)).astype(np.int32)
    blk = encode_block(hi, n_app, n_acc, ph)
    lane_lo, hi2, n_app2, n_acc2, rows = decode_block(blk)
    assert lane_lo == 0
    np.testing.assert_array_equal(hi, hi2)
    np.testing.assert_array_equal(n_app, n_app2)
    np.testing.assert_array_equal(n_acc, n_acc2)
    for i in range(N):
        np.testing.assert_array_equal(rows[i, :n_acc[i]], ph[i, :n_acc[i]])
        assert (rows[i, n_acc[i]:] == 0).all()  # noop rows zero-filled


def test_flat_encode_matches_legacy_bytes():
    """The device-compaction encode path (flat accepted rows in) must be
    byte-identical to the legacy host-mask path — the wal_shards=1
    format-compat guarantee."""
    from ra_tpu.engine.durable import encode_block_flat
    rng = np.random.default_rng(1)
    hi = rng.integers(1, 100, N).astype(np.int32)
    n_acc = rng.integers(0, K, N).astype(np.int32)
    n_app = n_acc + rng.integers(0, 2, N).astype(np.int32)
    ph = rng.integers(0, 1000, (N, K, 1)).astype(np.int32)
    mask = np.arange(K)[None, :] < n_acc[:, None]
    flat = ph[mask]
    assert encode_block_flat(hi, n_app, n_acc, flat) == \
        encode_block(hi, n_app, n_acc, ph)


def test_sharded_block_carries_lane_offset():
    from ra_tpu.engine.durable import encode_block_flat
    hi = np.array([7, 9], np.int32)
    n_app = np.array([2, 1], np.int32)
    n_acc = np.array([2, 1], np.int32)
    flat = np.array([[1], [2], [3]], np.int32)
    blk = encode_block_flat(hi, n_app, n_acc, flat, lane_lo=8)
    lane_lo, hi2, n_app2, n_acc2, rows = decode_block(blk)
    assert lane_lo == 8
    np.testing.assert_array_equal(hi2, hi)
    np.testing.assert_array_equal(rows[0, :2, 0], [1, 2])
    np.testing.assert_array_equal(rows[1, :1, 0], [3])


def test_final_logs_truncation():
    # two blocks then an election block that truncates below block 2
    tail = np.zeros((2,), np.int32)
    b1 = (1, np.array([4, 4], np.int32), np.array([4, 4], np.int32),
          np.array([4, 4], np.int32), np.ones((2, 4, 1), np.int32))
    b2 = (2, np.array([8, 8], np.int32), np.array([4, 4], np.int32),
          np.array([4, 4], np.int32), np.ones((2, 4, 1), np.int32))
    # election on lane 0: truncate to 5, append noop -> hi 6
    b3 = (3, np.array([6, 12], np.int32), np.array([1, 4], np.int32),
          np.array([0, 4], np.int32), np.ones((2, 4, 1), np.int32))
    surv, trimmed, final = _final_logs([b1, b2, b3], tail)
    np.testing.assert_array_equal(surv[0], [4, 4])
    np.testing.assert_array_equal(surv[1], [1, 4])  # entries 6..8 die
    np.testing.assert_array_equal(surv[2], [1, 4])
    np.testing.assert_array_equal(final, [6, 12])


# -- commit gating ----------------------------------------------------------

def test_commits_gate_on_wal_confirm(tmp_path):
    eng = make_engine(tmp_path)
    drive(eng, 10)
    # confirm path is asynchronous; drain + flush then step to fold
    settle(eng, 5)
    total = eng.committed_total()
    assert total > 0
    # every committed entry is <= the WAL-confirmed horizon
    st = eng.state
    lane = np.arange(N)
    leader = np.asarray(st.leader_slot)
    com = np.asarray(st.commit)[lane, leader]
    assert (com <= eng._dur.confirm_upto).all()
    eng.close()


# Wal.kill() below makes the batch thread die by an uncaught
# exception on purpose — that IS the scenario under test
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_commits_freeze_when_wal_dies(tmp_path):
    # wal_supervise=False: this test asserts the RAW frozen state and
    # restarts by hand — the default supervisor would race the asserts
    eng = make_engine(tmp_path, wal_supervise=False)
    drive(eng, 6)
    settle(eng, 5)
    before = eng.committed_total()
    eng._dur.wal.kill()
    # steps keep running (appends continue) but commits freeze at the
    # confirmed horizon; submits hit WalDown and blocks stay pending
    from ra_tpu.log.wal import WalDown
    n_new = np.full((N,), 4, np.int32)
    payloads = np.ones((N, K, 1), np.int32)
    frozen = None
    for _ in range(6):
        try:
            eng.step(n_new, payloads)
        except WalDown:
            pass
        frozen = eng.committed_total()
    # nothing beyond the last confirm may commit
    assert frozen is not None
    confirmed_hi = int(eng._dur.confirm_upto.sum())
    lane = np.arange(N)
    st = eng.state
    com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
    assert int(com.sum()) <= confirmed_hi
    # supervised restart: resend above the durable horizon, commits resume
    eng._dur.wal.restart()
    for _ in range(10):
        try:
            eng.step(n_new, payloads)
        except WalDown:
            time.sleep(0.05)
    settle(eng, 10)
    assert eng.committed_total() > before
    eng.close()


def test_checkpoint_prunes_wal_files(tmp_path):
    eng = make_engine(tmp_path)
    drive(eng, 8)
    eng.checkpoint()
    wal_dir = os.path.join(str(tmp_path), "wal")
    files = [f for f in os.listdir(wal_dir) if f.endswith(".wal")]
    # only the fresh post-rollover file remains
    assert len(files) == 1
    assert os.path.exists(os.path.join(str(tmp_path), "ckpt.npz"))
    eng.close()


# -- recovery ---------------------------------------------------------------

def test_recover_from_wal_only(tmp_path):
    eng = make_engine(tmp_path)
    drive(eng, 10, cmds=4)
    settle(eng, 5)
    st = eng.state
    lane = np.arange(N)
    leader = np.asarray(st.leader_slot)
    commits = np.asarray(st.commit)[lane, leader].copy()
    counters = np.asarray(st.mac)[lane, leader].copy()
    eng.close()

    eng2 = make_engine(tmp_path)
    st2 = eng2.state
    leader2 = np.asarray(st2.leader_slot)
    com2 = np.asarray(st2.commit)[lane, leader2]
    mac2 = np.asarray(st2.mac)[lane, leader2]
    assert (com2 >= commits).all()
    assert (mac2 >= counters).all()
    # replicas converge: every active member has the leader's state
    mac_all = np.asarray(st2.mac)
    act = np.asarray(st2.active)
    for i in range(N):
        vals = mac_all[i][act[i]]
        assert (vals == vals[0]).all()
    eng2.close()


def test_recover_from_checkpoint_plus_wal(tmp_path):
    eng = make_engine(tmp_path)
    drive(eng, 6, cmds=4)
    eng.checkpoint()
    drive(eng, 6, cmds=4)  # post-checkpoint tail lives only in the WAL
    settle(eng, 5)
    lane = np.arange(N)
    st = eng.state
    commits = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)].copy()
    eng.close()

    eng2 = make_engine(tmp_path)
    st2 = eng2.state
    com2 = np.asarray(st2.commit)[lane, np.asarray(st2.leader_slot)]
    assert (com2 >= commits).all()
    eng2.close()


def test_durable_dir_with_old_format_checkpoint_reopens(tmp_path):
    """ISSUE 15 forward-compat at the DURABLE-DIR level (the PR 6
    verify probe promoted into tier-1 and generalized): a dir whose
    ckpt.npz was written by an OLD engine — positional a<i> keys,
    telemetry plane absent — reopens through restore()'s legacy branch
    + the RA15 schema defaults, recovers every committed command, and
    keeps committing.  A checkpoint format bump never strands a
    durable dir."""
    import jax

    from ra_tpu.engine.lockstep import LaneState, LaneTelemetry

    eng = make_engine(tmp_path)
    drive(eng, 6, cmds=4)
    eng.checkpoint()
    drive(eng, 3, cmds=4)
    settle(eng, 5)
    committed = eng.committed_total()
    state = eng.state
    eng.close()

    # rewrite ckpt.npz exactly as the pre-telemetry positional save
    # wrote it: index-flattened keys, telem leaves dropped
    ckpt = tmp_path / "ckpt.npz"
    n_tel = len(LaneTelemetry._fields)
    tel_at = len(jax.tree.flatten(
        tuple(state[:LaneState._fields.index("telem")]))[0])
    with np.load(str(ckpt)) as z:
        meta = z["__meta__"]
        arrays = []
        for name in LaneState._fields:
            n_leaves = len(jax.tree.flatten(getattr(state, name))[0])
            arrays += [z[f"{name}:{j}"] for j in range(n_leaves)]
    legacy = arrays[:tel_at] + arrays[tel_at + n_tel:]
    np.savez(str(ckpt), __meta__=meta,
             **{f"a{i}": a for i, a in enumerate(legacy)})

    eng2 = make_engine(tmp_path)
    settle(eng2, 5)
    assert eng2.committed_total() >= committed
    # telemetry zero-fills and accumulates from the reopen
    drive(eng2, 2, cmds=4)
    eng2.block_until_ready()
    assert int(np.asarray(eng2.state.telem.steps).max()) > 0
    eng2.close()


def test_recover_with_election_truncation(tmp_path):
    eng = make_engine(tmp_path)
    drive(eng, 6)
    settle(eng, 5)
    # fail the leader of lane 0 and elect a replacement: the dead
    # leader's unreplicated tail (if any) is truncated, indexes reused
    st = eng.state
    leader0 = int(np.asarray(st.leader_slot)[0])
    eng.fail_member(0, leader0)
    eng.trigger_election([0])
    drive(eng, 6)
    settle(eng, 8)
    lane = np.arange(N)
    st = eng.state
    commits = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)].copy()
    eng.close()

    eng2 = make_engine(tmp_path)
    st2 = eng2.state
    com2 = np.asarray(st2.commit)[lane, np.asarray(st2.leader_slot)]
    assert (com2 >= commits).all()
    # converged replicas on the failed lane too
    mac = np.asarray(st2.mac)[0]
    act = np.asarray(st2.active)[0]
    vals = mac[act]
    assert (vals == vals[0]).all()
    eng2.close()


# -- superstep durable contracts (ISSUE 5) ----------------------------------

def test_superstep_durable_parity(tmp_path):
    """A durable run driven in K-fused supersteps converges to the SAME
    state as a single-step durable run over the same schedule: identical
    WAL records per inner step, identical commits/applies/machine state
    once both settle (the stacked-aux submit_block path feeds the shard
    workers exactly what K step() calls would)."""
    a = make_engine(tmp_path / "a", wal_shards=2, max_pending=32)
    b = make_engine(tmp_path / "b", wal_shards=2, max_pending=32)
    rng = np.random.default_rng(42)
    SK = 4
    for _ in range(3):
        n_new = rng.integers(0, K + 1, (SK, N)).astype(np.int32)
        pay = rng.integers(1, 5, (SK, N, K, 1)).astype(np.int32)
        for j in range(SK):
            a.step(n_new[j], pay[j])
        b.superstep(n_new, pay)
    settle(a, 20)
    settle(b, 20)
    for f in ("commit", "applied", "total_committed"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.state, f)),
            np.asarray(getattr(b.state, f)), err_msg=f)
    np.testing.assert_array_equal(np.asarray(a.state.mac),
                                  np.asarray(b.state.mac))
    # both runs recover to equal durable state too
    a.close()
    b.close()
    a2 = make_engine(tmp_path / "a", wal_shards=2)
    b2 = make_engine(tmp_path / "b", wal_shards=2)
    np.testing.assert_array_equal(np.asarray(a2.state.mac),
                                  np.asarray(b2.state.mac))
    a2.close()
    b2.close()


def test_superstep_confirms_only_lag_fsync(tmp_path):
    """The confirm horizon is sampled ONCE per fused dispatch: no entry
    may commit inside a superstep beyond what was already WAL-confirmed
    when the dispatch launched (write_delay semantics — confirms lag,
    never lead).  Checked against the horizon captured BEFORE each
    dispatch, which is strictly stronger than the settled-state gate."""
    eng = make_engine(tmp_path, max_pending=64)
    lane = np.arange(N)
    rng = np.random.default_rng(7)
    for _ in range(6):
        confirm_before = eng._dur.confirm_upto.copy()
        n_new = rng.integers(0, K + 1, (4, N)).astype(np.int32)
        pay = rng.integers(1, 5, (4, N, K, 1)).astype(np.int32)
        eng.superstep(n_new, pay)
        st = eng.state
        com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
        assert (com <= confirm_before).all(), (com, confirm_before)
    # ...and the horizon does advance once the WAL drains, so the gate
    # above is hold-back, not a frozen pipeline
    settle(eng, 20)
    assert eng.committed_total() > 0
    eng.close()


_CHILD = r"""
import os, sys, json
import numpy as np
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
from ra_tpu.utils import force_platform_from_env
force_platform_from_env()  # a hung TPU tunnel must not block jax init
from ra_tpu.engine import open_engine
from ra_tpu.models import CounterMachine

N, P, K = 16, 3, 8
mode = sys.argv[4] if len(sys.argv) > 4 else "step"
eng = open_engine(CounterMachine(), sys.argv[1], N, P,
                  sync_mode=1, ring_capacity=256, max_step_cmds=K,
                  wal_shards=int(sys.argv[3]),
                  # superstep: step_seq advances SK per dispatch, so the
                  # unconfirmed window must cover a few fused dispatches
                  max_pending=32 if mode == "superstep" else 8)
report = sys.argv[2]
n_new = np.full((N,), 4, np.int32)
payloads = np.ones((N, K, 1), np.int32)
SK = 4
n_new_blk = np.broadcast_to(n_new, (SK, N)).copy()
pay_blk = np.broadcast_to(payloads, (SK, N, K, 1)).copy()
lane = np.arange(N)
for i in range(10_000):
    if mode == "superstep":
        eng.superstep(n_new_blk, pay_blk)
    else:
        eng.step(n_new, payloads)
    if i % 5 == 4:
        # report the fsync-confirmed commit frontier crash-safely
        st = eng.state
        com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
        com = np.minimum(com, eng._dur.confirm_upto)
        tmp = report + ".tmp"
        with open(tmp, "w") as f:
            json.dump([int(x) for x in com], f)
            f.flush(); os.fsync(f.fileno())
        os.replace(tmp, report)
        print("REPORTED", i, flush=True)
"""


@pytest.mark.parametrize("shards,mode", [(1, "step"), (4, "step"),
                                         (4, "superstep")])
def test_kill9_recovers_all_reported_commits(tmp_path, shards, mode):
    """SIGKILL mid-bench: every entry ever reported committed (which the
    engine only does after its WAL block is fsynced) survives recovery —
    for the single-shard compat layout AND the sharded WAL plane (a
    crash can tear one shard mid-write; recovery merges the ragged
    per-shard coverage), and for a run driven in FUSED SUPERSTEP mode
    (ISSUE 5: the kill lands mid-block — some of a dispatch's K
    per-inner-step WAL records written, some not — and recovery still
    honours every fsync-gated report).  The recovered machine state must
    equal the never-crashed oracle at the recovered apply frontier: with
    no elections every applied entry is a +1 command, so the oracle
    counter at applied index a is exactly a."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    data = str(tmp_path / "data")
    report = str(tmp_path / "report.json")
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=repo), data, report,
         str(shards), mode],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        # PYTHONPATH= : the axon site hook must not register a PJRT
        # plugin whose discovery blocks on a dead tunnel (same guard as
        # bench.py's CPU fallback)
        env={**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
             "PYTHONPATH": ""})
    # wait for a few reports, then SIGKILL with no warning (generous
    # deadline: the child pays a fresh jax import + jit compile, minutes
    # on a loaded single-core box; success path exits long before).
    # Read the RAW fd: readline alone would block past the deadline, and
    # select() on the buffered stream misses lines the BufferedReader
    # already slurped.
    import select
    deadline = time.time() + 360
    reports = 0
    fd = child.stdout.fileno()
    buf = b""
    while time.time() < deadline and reports < 4:
        ready, _, _ = select.select([fd], [], [],
                                    max(0.0, deadline - time.time()))
        if not ready:
            break
        chunk = os.read(fd, 65536)
        if not chunk:  # EOF: child died early — stderr tells why
            break
        buf += chunk
        reports = sum(1 for line in buf.split(b"\n")[:-1]
                      if line.startswith(b"REPORTED"))
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=30)
    assert reports >= 4, child.stderr.read()

    import json
    with open(report) as f:
        reported = np.array(json.load(f), np.int32)
    assert reported.sum() > 0

    eng = make_engine(tmp_path / "data", sync_mode=1, wal_shards=shards)
    lane = np.arange(N)
    st = eng.state
    com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
    assert (com >= reported).all(), (com, reported)
    # oracle equivalence: the replayed lane state equals what a
    # never-crashed run holds at the recovered apply frontier — the
    # workload is pure +1 commands (no elections, no noops), so the
    # oracle counter at applied index a is exactly a, on every member
    mac = np.asarray(st.mac)
    app = np.asarray(st.applied)
    act = np.asarray(st.active)
    assert (mac[act] == app[act]).all(), (mac, app)
    assert (mac[lane, np.asarray(st.leader_slot)] >= reported).all()
    eng.close()


def test_volatile_mode_unchanged(tmp_path):
    """The volatile engine (no durable_dir) still works as before."""
    eng = LockstepEngine(CounterMachine(), N, P, ring_capacity=256,
                        max_step_cmds=K)
    n_new = np.full((N,), 4, np.int32)
    payloads = np.ones((N, K, 1), np.int32)
    for _ in range(6):
        eng.step(n_new, payloads)
    assert eng.committed_total() > 0


def test_recover_revives_failed_member_by_snapshot(tmp_path):
    """Regression (r04 review): recovery must revive a failed member via
    snapshot install from its lane leader — a bare active-flag flip
    leaves a frozen applied cursor that would drag the lane-uniform
    apply window onto recycled ring slots and silently diverge."""
    eng = make_engine(tmp_path, ring_capacity=64)
    drive(eng, 4)
    settle(eng, 5)
    eng.fail_member(0, 1)
    # push far more entries than ring_capacity so the failed member's
    # frozen cursor falls behind the reclaim horizon
    drive(eng, 40)
    settle(eng, 5)
    eng.checkpoint()
    lane = np.arange(N)
    st = eng.state
    leader_mac = np.asarray(st.mac)[lane, np.asarray(st.leader_slot)]
    eng.close()

    eng2 = make_engine(tmp_path, ring_capacity=64)
    st2 = eng2.state
    assert bool(np.asarray(st2.active)[0, 1])  # revived
    # the revived member's state equals its leader's (snapshot), and
    # further traffic keeps every replica converged
    drive(eng2, 4)
    settle(eng2, 10)
    st2 = eng2.state
    mac = np.asarray(st2.mac)
    act = np.asarray(st2.active)
    for i in range(N):
        vals = mac[i][act[i]]
        assert (vals == vals[0]).all(), (i, mac[i], act[i])
    led2 = np.asarray(st2.leader_slot)
    assert (mac[lane, led2] >= leader_mac).all()
    eng2.close()
