"""Ingress plane tests (ISSUE 10): session directory placement +
reconnect epochs, vectorized seqno dedup (at-most-once end-to-end),
dense superstep coalescing, the graduated backpressure ladder, and the
ROADMAP item 2 acceptance scenario — sessions fanning into lanes under
chaos with an exactly-once oracle.

The oracle: every submission the plane answered OK/SLOW (placed) must
be applied EXACTLY once — the final per-lane CounterMachine state
equals the host-side sum of placed increments per lane, despite
duplicate resends (dedup'd), rejected/deferred/shed rows (not marked,
so their resends stay fresh), member failures and elections (noops add
0 to a counter).  Linearizability of reads is checked by monotone
consistent-read probes bounded by the host-side placed watermark —
for a grow-only counter register, a read that is monotone, never ahead
of what was placed at its completion, and exact at the end, is
linearizable.

``run_ingress_soak`` is the soak entry point (tools/soak.py --ingress
runs it at 1M sessions x 10k lanes); the tier-1 variants here are
CPU-scaled, the full-scale one rides ``-m slow``.
"""
import time
from collections import deque

import numpy as np
import pytest

from ra_tpu.blackbox import RECORDER
from ra_tpu.engine import LockstepEngine
from ra_tpu.ingress import (DEFER, DUP, OK, REJECT, SLOW, CoalesceWindow,
                            CreditLadder, IngressPlane, SessionDirectory,
                            batch_rank)
from ra_tpu.models import CounterMachine

#: the classic-TCP 3-member cluster baseline (BENCH_CLASSIC_r05) the
#: ISSUE 10 acceptance bar is phrased against
CLASSIC_TCP_BASELINE = 2934.0


def mk_engine(lanes=64, cmds=8, ring=128, **kw):
    kw.setdefault("donate", False)
    return LockstepEngine(CounterMachine(), lanes, 3,
                          ring_capacity=ring, max_step_cmds=cmds, **kw)


# ---------------------------------------------------------------------------
# directory: placement, reconnect epochs, dedup
# ---------------------------------------------------------------------------

def test_batch_rank_counts_within_batch_occurrences():
    assert batch_rank(np.array([7, 3, 7, 7, 3])).tolist() == \
        [0, 0, 1, 2, 1]
    assert batch_rank(np.array([], np.int64)).tolist() == []
    assert batch_rank(np.array([5])).tolist() == [0]


def test_placement_is_deterministic_and_reconnect_stable():
    d1 = SessionDirectory(256, n_shards=4, seed=5)
    d2 = SessionDirectory(256, n_shards=4, seed=5)
    for eid in ("acme/alice", "acme/bob", "solo-client"):
        assert d1.place(eid) == d2.place(eid)
    tenant, lane, shard = d1.place("acme/alice")
    assert tenant == "acme" and 0 <= lane < 256
    assert shard == lane * 4 // 256
    assert d1.place("solo-client")[0] == "default"
    h, reconnected = d1.connect("acme/alice")
    assert not reconnected and d1.epoch[h] == 1
    h2, reconnected = d1.connect("acme/alice")
    assert h2 == h and reconnected and d1.epoch[h] == 2
    assert int(d1.lane[h]) == lane  # placement survives the reconnect


def test_bulk_connect_spreads_lanes_and_bumps_epochs():
    d = SessionDirectory(128, seed=1)
    h = d.connect_bulk(10_000, tenants=4, key="fleet")
    counts = np.bincount(d.lane[h], minlength=128)
    assert counts.min() > 0  # 78x the mean leaves no lane empty
    assert set(np.unique(d.tenant[h])) == {0, 1, 2, 3}
    same = d.connect_bulk(10_000, tenants=4, key="fleet")
    np.testing.assert_array_equal(h, same)   # same fleet, same handles
    assert (d.epoch[h] == 2).all()           # fleet-wide reconnect


def test_seqno_dedup_is_at_most_once():
    d = SessionDirectory(16)
    a = d.connect("c/a")[0]
    b = d.connect("c/b")[0]
    handles = np.array([a, a, b, a], np.int64)
    seqnos = np.array([1, 1, 1, 2], np.int64)
    fresh = d.fresh(handles, seqnos)
    # within-batch duplicate (a,1) passes once; (b,1) and (a,2) pass
    assert fresh.tolist() == [True, False, True, True]
    d.mark(handles[fresh], seqnos[fresh])
    # cross-batch resend of the whole wave: everything is a duplicate
    assert not d.fresh(handles, seqnos).any()
    # a row that was NOT marked (rejected/shed) stays fresh on resend
    fresh2 = d.fresh(np.array([a]), np.array([3]))
    assert fresh2.all()
    assert d.fresh(np.array([a]), np.array([3])).all()  # still unmarked
    # distinct pairs 2^32 apart must NOT collide in the batch dedup (a
    # packed 32-bit key would silently DUP the second — rows lost)
    far = d.fresh(np.array([a, a], np.int64),
                  np.array([10, 10 + 2 ** 32], np.int64))
    assert far.tolist() == [True, True]


def test_bulk_tenants_do_not_alias_named_tenants():
    """connect_bulk's round-robin must land on the REGISTERED bulk
    tenant ids: with a named tenant already in the table, raw modulo
    values would charge half the fleet to the named tenant's quota."""
    d = SessionDirectory(16)
    a = d.connect("acme/alice")[0]
    h = d.connect_bulk(4, tenants=2, key="fleet")
    bulk_tenants = set(d.tenant[h].tolist())
    assert int(d.tenant[a]) not in bulk_tenants
    assert len(bulk_tenants) == 2


# ---------------------------------------------------------------------------
# coalescer: dense blocks, overflow shed
# ---------------------------------------------------------------------------

def test_coalescer_builds_dense_superstep_blocks():
    w = CoalesceWindow(4, 2, 1, superstep_k=2, capacity=8, window_s=0.0)
    lanes = np.array([0, 0, 0, 1, 2])
    pay = np.arange(1, 6, dtype=np.int32)[:, None]
    placed = w.offer(lanes, pay, np.arange(5))
    assert placed.all() and w.queue_rows() == 5
    n_new, payloads, handles, take = w.pop_block()
    assert n_new.shape == (2, 4) and payloads.shape == (2, 4, 2, 1)
    assert take.tolist() == [3, 1, 1, 0]
    # lane 0: 3 rows split [2, 1] over the two inner steps, in order
    assert n_new[:, 0].tolist() == [2, 1]
    assert payloads[0, 0, :, 0].tolist() == [1, 2]
    assert payloads[1, 0, 0, 0] == 3
    assert n_new[:, 1].tolist() == [1, 0] and payloads[0, 1, 0, 0] == 4
    assert n_new[:, 3].tolist() == [0, 0]
    assert handles[0, :3].tolist() == [0, 1, 2]
    assert w.queue_rows() == 0
    # overflow: the bounded ring places capacity rows, sheds the rest
    lanes = np.zeros(10, np.int64)
    placed = w.offer(lanes, np.ones((10, 1), np.int32), np.arange(10))
    assert placed.sum() == 8 and (~placed).sum() == 2
    # the ring wraps correctly across pops (head moved by the take)
    n_new, payloads, handles, take = w.pop_block()
    assert take[0] == 4 and int(n_new[:, 0].sum()) == 4
    assert w.fill[0] == 4


def test_coalescer_ready_on_fill_or_cadence():
    w = CoalesceWindow(2, 2, 1, superstep_k=1, capacity=8,
                       window_s=10.0, fill_frac=0.5)
    assert not w.ready()  # empty: never ready
    w.offer(np.array([0]), np.ones((1, 1), np.int32), np.array([1]))
    assert not w.ready()          # below fill trigger, cadence far off
    assert w.ready(now=time.monotonic() + 20.0)   # cadence trigger
    w.offer(np.array([0, 1]), np.ones((2, 1), np.int32),
            np.array([2, 3]))
    assert w.ready()              # fill trigger (>= half a full block)


# ---------------------------------------------------------------------------
# backpressure ladder
# ---------------------------------------------------------------------------

def test_credit_ladder_graduates_and_enforces_tenant_fairness():
    d = SessionDirectory(8)
    a = d.connect("t0/a")[0]
    b = d.connect("t0/b")[0]
    c = d.connect("t1/c")[0]
    lad = CreditLadder(d, soft_credit=8, hard_credit=16, tenant_quota=2)
    st = lad.admit(np.full(20, a, np.int64))
    # within-batch multiplicity: ok x8, slow x8, reject past the hard
    # window (the StopSending analogue)
    assert st.tolist() == [OK] * 8 + [SLOW] * 8 + [REJECT] * 4
    assert lad.used[a] == 16
    lad.release(np.full(16, a, np.int64))
    assert lad.used[a] == 0
    # a commit_p99 breach tightens credits BEFORE queues grow
    base = len([e for e in RECORDER.events("ingress")
                if e[1] == "ingress.level"])
    lvl = lad.on_slo({"objectives": {"commit_p99_ms":
                                     {"verdict": "breach"}}})
    assert lvl == 1 and lad.effective_limits() == (4, 8)
    st = lad.admit(np.full(10, a, np.int64))
    assert st.tolist() == [OK] * 4 + [SLOW] * 4 + [REJECT] * 2
    lad.release(np.full(8, a, np.int64))
    # alert escalates to tenant fairness: the over-quota tenant defers,
    # the light tenant stays admitted
    assert lad.on_slo({"objectives": {"commit_p99_ms":
                                      {"verdict": "alert"}}}) == 2
    assert lad.effective_limits() == (2, 4)
    st = lad.admit(np.array([a, b, b, c], np.int64))
    # tenant t0's third row crosses quota=2 -> DEFER; tenant t1 is fine
    assert st.tolist() == [OK, OK, DEFER, OK]
    # recovery decays one level per two clean windows (hysteresis)
    assert lad.on_slo({"objectives": {"commit_p99_ms":
                                      {"verdict": "ok"}}}) == 2
    assert lad.on_slo({"objectives": {"commit_p99_ms":
                                      {"verdict": "ok"}}}) == 1
    # every transition is a registered flight-recorder event
    levels = [e for e in RECORDER.events("ingress")
              if e[1] == "ingress.level"]
    assert len(levels) >= base + 3


def test_within_wave_twin_of_unplaced_row_is_not_dup():
    """DUP means 'already placed — stop resending'.  A within-wave
    duplicate of a row that was REJECTED (never placed) must inherit
    the refusal, not read as DUP — a client trusting status 4 would
    otherwise drop a command the engine never saw."""
    eng = mk_engine(lanes=8, cmds=4, ring=64)
    plane = IngressPlane(eng, superstep_k=1, window_s=0.0,
                         soft_credit=1, hard_credit=1)
    h = plane.connect("t/x")
    # exhaust the hard credit (1): the first row places, rest refuse
    st = plane.submit(np.array([h], np.int64), np.array([1]),
                      np.ones((1, 1), np.int32))
    assert st.tolist() == [OK]
    # one wave with (h,2) twice: both rows hit the exhausted window —
    # first is REJECT, and its twin must be REJECT too, not DUP
    st = plane.submit(np.array([h, h], np.int64), np.array([2, 2]),
                      np.ones((2, 1), np.int32))
    assert st.tolist() == [REJECT, REJECT]
    # twin of a PLACED row is a genuine DUP: release credit, resend
    plane.pump(force=True)
    plane.settle()
    st = plane.submit(np.array([h, h], np.int64), np.array([2, 2]),
                      np.ones((2, 1), np.int32))
    assert st.tolist() == [OK, DUP]
    # and a pure watermark resend stays DUP
    st = plane.submit(np.array([h], np.int64), np.array([2]),
                      np.ones((1, 1), np.int32))
    assert st.tolist() == [DUP]


def test_slo_verdict_accessor_drives_the_ladder():
    """The pump path polls ``SloEngine.verdict("commit_p99_ms")`` (one
    memoized dict hit) and feeds ``on_verdict`` — the same transitions
    as the dict-shaped ``on_slo`` form."""
    from ra_tpu.slo import SloEngine, default_objectives
    from ra_tpu.telemetry import Observatory
    obs = Observatory()
    try:
        slo = SloEngine(obs, default_objectives())
        assert slo.verdict("commit_p99_ms") == "no_data"  # empty ring
        assert slo.verdict("no-such-objective") == "no_data"
        d = SessionDirectory(4)
        lad = CreditLadder(d)
        assert lad.on_verdict(slo.verdict("commit_p99_ms")) == 0  # hold
        assert lad.on_verdict("breach") == 1
        assert lad.on_verdict("alert") == 2
    finally:
        obs.close()


# ---------------------------------------------------------------------------
# end to end: dedup + coalesce + engine, Observatory wiring
# ---------------------------------------------------------------------------

def test_ingress_end_to_end_oracle_and_observatory():
    eng = mk_engine(lanes=32, cmds=4, ring=64)
    plane = IngressPlane(eng, superstep_k=2, window_s=0.0,
                         soft_credit=64, hard_credit=256)
    h = plane.connect_bulk(200, tenants=4, key="e2e")
    rng = np.random.default_rng(3)
    expected = np.zeros(32, np.int64)
    for _wave in range(6):
        sess = h[rng.integers(0, len(h), 64)]
        seq = plane.directory.next_seqnos(sess)
        pay = rng.integers(1, 5, (64, 1)).astype(np.int32)
        st = plane.submit(sess, seq, pay)
        ok = st <= SLOW
        np.add.at(expected, plane.directory.lane[sess[ok]],
                  pay[ok, 0].astype(np.int64))
        # immediate resend of the SAME wave: placed rows all dedup
        st2 = plane.submit(sess, seq, pay)
        assert (st2[ok] == DUP).all()
        ok2 = st2 <= SLOW   # rows admitted only on the retry
        np.add.at(expected, plane.directory.lane[sess[ok2]],
                  pay[ok2, 0].astype(np.int64))
        plane.pump(force=True)
    plane.settle()
    mac = np.asarray(eng.consistent_read(np.arange(32)))
    np.testing.assert_array_equal(mac.astype(np.int64), expected)
    assert plane.counters["accepted"] > 0
    assert plane.counters["dup_dropped"] > 0
    # Observatory.for_engine picks the attached plane up automatically;
    # INGRESS_FIELDS reach the exposition + time-series ring
    from ra_tpu.telemetry import Observatory, parse_prometheus
    obs = Observatory.for_engine(eng)
    try:
        snap = obs.snapshot()
        assert snap["ingress"]["accepted"] == plane.counters["accepted"]
        assert snap["ingress"]["queue_rows"] == 0
        flat = parse_prometheus(obs.prometheus())
        assert flat[("ra_tpu_ingress_accepted", "")] == \
            plane.counters["accepted"]
        assert ("ra_tpu_ingress_shed_rows", "") in flat
        # counters rate as monotone keys over the ring; queue gauge
        # keeps its drift
        obs.snapshot()
        rates = obs.window_rates()
        assert "ingress_accepted" in rates
    finally:
        obs.close()
    # the engine overview stamps the session tier next to its pipeline
    ov = eng.overview()
    assert ov["ingress"]["sessions"] == 200
    assert ov["ingress"]["inflight_blocks"] == 0


def _reconnect_scenario(shard_mesh: bool) -> None:
    """Kill a client mid-flight, reconnect under the SAME external id,
    resend the unacked window: seqno dedup yields no duplicate apply
    (settle-based, fixed seed — the ISSUE 10 reconnect satellite)."""
    eng = mk_engine(lanes=16, cmds=4, ring=64)
    if shard_mesh:
        import jax

        from ra_tpu.parallel.mesh import shard_engine_state
        if len(jax.devices()) < 2:
            pytest.skip("single-device backend")
        shard_engine_state(eng)
    # one session -> one lane: the staging ring must hold the whole
    # 60-command burst (default capacity is sized for spread fan-in)
    plane = IngressPlane(eng, superstep_k=2, window_s=0.0, capacity=64)
    h = plane.connect("acme/alice")
    lane = int(plane.directory.lane[h])
    # 40 in-flight commands; only part of them dispatched before the
    # client dies (the rest staged in the window)
    st = plane.submit(np.full(40, h, np.int64), np.arange(1, 41),
                      np.ones((40, 1), np.int32))
    assert (st <= SLOW).all()
    plane.pump(force=True)
    # reconnect: same id -> same handle, same lane, bumped epoch, and
    # the dedup watermark SURVIVES the reconnect
    h2 = plane.connect("acme/alice")
    assert h2 == h and plane.directory.epoch[h] == 2
    assert int(plane.directory.lane[h2]) == lane
    # client resends its unacked tail 20..40 plus new traffic 41..60
    resend = np.arange(20, 61)
    st2 = plane.submit(np.full(len(resend), h2, np.int64), resend,
                       np.ones((len(resend), 1), np.int32))
    assert (st2[:21] == DUP).all()      # already placed: at-most-once
    assert (st2[21:] <= SLOW).all()     # fresh tail admitted
    plane.settle()
    val = int(np.asarray(eng.consistent_read([lane]))[0])
    assert val == 60                    # 1..60 exactly once
    assert plane.counters["dup_dropped"] == 21
    assert plane.counters["reconnects"] == 1


def test_session_reconnect_no_duplicate_apply_single_device():
    _reconnect_scenario(shard_mesh=False)


def test_session_reconnect_no_duplicate_apply_sharded_mesh():
    _reconnect_scenario(shard_mesh=True)


# ---------------------------------------------------------------------------
# overload: the ladder sheds, the queue stays bounded
# ---------------------------------------------------------------------------

def test_overload_sheds_and_queue_depth_stays_bounded():
    eng = mk_engine(lanes=64, cmds=8, ring=256)
    plane = IngressPlane(eng, superstep_k=4, window_s=0.0, capacity=64,
                         soft_credit=1 << 20, hard_credit=1 << 20)
    h = plane.connect_bulk(1000, tenants=2, key="overload")
    rng = np.random.default_rng(9)
    cap_total = 64 * 64
    block_rows = 4 * 8 * 64
    expected = np.zeros(64, np.int64)
    for _ in range(20):
        # 2x overload: twice a full block offered per drain opportunity
        sess = h[rng.integers(0, len(h), 2 * block_rows)]
        pay = np.ones((len(sess), 1), np.int32)
        st = plane.submit(sess, plane.directory.next_seqnos(sess), pay)
        ok = st <= SLOW
        np.add.at(expected, plane.directory.lane[sess[ok]], 1)
        plane.pump(force=True)
        # bounded: the ring sheds instead of growing
        assert plane.window.queue_rows() <= cap_total
    assert plane.counters["shed_rows"] > 0
    shed_ev = [e for e in RECORDER.events("ingress")
               if e[1] == "ingress.shed"]
    assert shed_ev, "shed episode must be a recorded incident"
    plane.settle()
    # exactly-once holds THROUGH the shed episodes: every placed row
    # applied once, every shed row never
    mac = np.asarray(eng.consistent_read(np.arange(64)))
    np.testing.assert_array_equal(mac.astype(np.int64), expected)


# ---------------------------------------------------------------------------
# throughput: the ISSUE 10 acceptance bar
# ---------------------------------------------------------------------------

def _throughput_run(seconds: float = 1.2) -> float:
    eng = mk_engine(lanes=512, cmds=32, ring=2048)
    plane = IngressPlane(eng, superstep_k=8, max_in_flight=2,
                         window_s=0.0, soft_credit=1 << 20,
                         hard_credit=1 << 20)
    h = plane.connect_bulk(4096, tenants=8, key="tput")
    rng = np.random.default_rng(0)
    # 75% of one full block per pump: lane-level Poisson variance must
    # never outrun the per-pump drain, or the bounded ring (correctly)
    # sheds and the clean-throughput measurement stops being clean
    rows = 512 * 32 * 6
    pay = np.ones((rows, 1), np.int32)
    # warm the fused executable + settle path OUTSIDE the measured
    # window (compile time is a one-off, not ingress throughput)
    plane.submit_auto(h[rng.integers(0, len(h), rows)], pay)
    plane.pump(force=True)
    plane.settle()
    base = plane.counters["accepted"]
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        sess = h[rng.integers(0, len(h), rows)]
        plane.submit_auto(sess, pay)
        plane.pump(force=True)
    plane.settle()
    elapsed = time.perf_counter() - t0
    # hashed placement leaves some lanes structurally hot (2x the mean
    # session count), and their bounded rings shed the excess — that is
    # the design working; the throughput claim counts ACCEPTED rows
    c = plane.counters
    assert c["shed_rows"] < 0.2 * c["submitted"]
    return (c["accepted"] - base) / elapsed


def test_ingress_throughput_beats_classic_tcp_100x():
    """The acceptance bar: the batched ingress path moves >= 100x the
    classic-TCP per-command baseline (2,934 cmds/s, BENCH_CLASSIC_r05)
    END TO END — session dedup + admission + coalescing + fused
    dispatch + settle all inside the measured window.  One retry
    absorbs shared-CI weather (the bench tests' pattern)."""
    rate = _throughput_run()
    if rate < 100 * CLASSIC_TCP_BASELINE:  # pragma: no cover — CI load
        rate = _throughput_run(2.0)
    assert rate >= 100 * CLASSIC_TCP_BASELINE, f"{rate:.0f} cmds/s"


# ---------------------------------------------------------------------------
# the soak scenario (tools/soak.py --ingress; CPU-scaled in tier-1)
# ---------------------------------------------------------------------------

def run_ingress_soak(seed, *, sessions=50_000, lanes=512, waves=12,
                     wave_rows=20_000, durable_dir=None,
                     disk_faults=False, superstep_k=4, cmds=16,
                     wal_shards=2, mesh=False,
                     throughput_bar=None) -> dict:
    """ROADMAP item 2 acceptance: ``sessions`` simulated sessions fan
    into ``lanes`` lanes through the full ingress path with duplicate
    resends, member-failure/election chaos (the lane plane's transport
    events), a live lossy transport FaultPlan standing in the process
    registry, and — on the durable variant — a seeded DiskFaultPlan
    injecting real WAL faults.  Exactly-once oracle + monotone
    consistent-read probes; returns a bench_diff-comparable row.

    ``mesh=True`` (ISSUE 11) runs the SAME scenario end-to-end on
    lane state sharded over every available device: per-device WAL
    shards on the durable variant (fsync parallelism follows the lane
    sharding), blocks staged pre-partitioned via the plane's auto
    shardings, and submission waves pumped through the mesh-side
    ``ingress_submit_wave`` path."""
    from ra_tpu.transport.rpc import FaultPlan, FaultSpec
    rng = np.random.default_rng(seed)
    ring = max(512, superstep_k * cmds * 4)
    device_mesh = None
    _mesh_wave = None
    if mesh:
        import jax

        from ra_tpu.parallel.mesh import (
            ingress_submit_wave as _mesh_wave, lane_mesh,
            per_device_wal_shards)
        if len(jax.devices()) < 2:
            # a plain error, NOT pytest.skip: this is a library entry
            # (tools/soak.py --mesh) and Skipped derives from
            # BaseException, which would blow through soak's per-seed
            # except Exception reporting
            raise RuntimeError(
                "mesh soak needs >=2 devices; run with JAX_PLATFORMS="
                "cpu XLA_FLAGS=--xla_force_host_platform_device_count=8")
        device_mesh = lane_mesh(jax.devices(), member_axis=1)
        if durable_dir is not None:
            # per-device WAL shard layout: one shard per lane-axis
            # device, slice boundaries matching the lane sharding
            wal_shards = per_device_wal_shards(device_mesh)
    if durable_dir is not None:
        from ra_tpu.engine.durable import open_engine
        eng = open_engine(CounterMachine(), durable_dir, lanes,
                          wal_shards=wal_shards, ring_capacity=ring,
                          max_step_cmds=cmds, donate=False)
    else:
        eng = mk_engine(lanes=lanes, cmds=cmds, ring=ring)
    if device_mesh is not None:
        from ra_tpu.parallel.mesh import shard_engine_state
        shard_engine_state(eng, device_mesh)
    disk_plan = None
    net_plan = FaultPlan(seed=seed, default=FaultSpec(drop=0.1))
    if disk_faults:
        from ra_tpu.log import faults
        disk_plan = faults.DiskFaultPlan(
            seed=seed, by_class={"wal": faults.DiskFaultSpec(
                fsync_eio=0.05, short_write=0.02, limit=4)})
        faults.install_plan(disk_plan)
    plane = IngressPlane(eng, superstep_k=superstep_k, window_s=0.001,
                         soft_credit=1 << 20, hard_credit=1 << 20)
    try:
        h = plane.connect_bulk(sessions, tenants=16, key="soak")
        # warm the fused/settle/read executables outside the measured
        # window: zero-increment payloads leave the oracle untouched
        plane.submit_auto(h[:min(1024, sessions)],
                          np.zeros((min(1024, sessions), 1), np.int32))
        plane.pump(force=True)
        plane.settle()
        eng.consistent_read([0])
        expected = np.zeros(lanes, np.int64)
        placed_waves: deque = deque(maxlen=4)
        failed_member = None
        probe_lane = int(rng.integers(lanes))
        probe_floor = 0
        placed_total = 0
        resent_rows = 0
        # work_s times the INGRESS PATH (submission, dedup, admission,
        # coalescing, dispatch, final drain); chaos barriers, probe
        # reads and fault-recovery stalls are scenario scaffolding, not
        # path cost — the acceptance bar is about the path
        work_s = 0.0
        t0 = time.perf_counter()
        for w in range(waves):
            tw = time.perf_counter()
            sess = h[rng.integers(0, sessions, wave_rows)]
            seq = plane.directory.next_seqnos(sess)
            pay = rng.integers(1, 8, (wave_rows, 1)).astype(np.int32)
            if device_mesh is not None:
                # the mesh-side pump path (vectorized end to end;
                # lint RA08 gates its module closure)
                st = _mesh_wave(plane, sess, seq, pay)
            else:
                st = plane.submit(sess, seq, pay)
            ok = st <= SLOW
            np.add.at(expected, plane.directory.lane[sess[ok]],
                      pay[ok, 0].astype(np.int64))
            placed_total += int(ok.sum())
            placed_waves.append((sess[ok], seq[ok], pay[ok]))
            if device_mesh is None:
                plane.pump(force=True)
            work_s += time.perf_counter() - tw
            # duplicate resends of an earlier placed wave: the dedup
            # gate must answer DUP for every row (at-most-once)
            if w >= 1 and rng.random() < 0.8:
                ps, pq, pp = placed_waves[int(rng.integers(
                    len(placed_waves)))]
                cut = int(rng.integers(1, len(ps) + 1))
                st2 = plane.submit(ps[:cut], pq[:cut], pp[:cut])
                assert (st2 == DUP).all(), "resend applied twice"
                resent_rows += cut
            # chaos: recover last wave's victim, fail a fresh leader
            # and elect around it (the in-process lane plane's
            # transport-fault analogue)
            if w % 4 == 2:
                if durable_dir is not None:
                    # durability barrier before the leader kill: a
                    # dispatched-but-unfsynced tail is Raft-legally
                    # truncated by the election (it was never acked
                    # committed — docs/INGRESS.md "Delivery
                    # guarantees"); the soak's oracle demands zero
                    # loss, so chaos strikes on a settled plane
                    plane.settle(timeout=60.0)
                if failed_member is not None:
                    lane_c, slot = failed_member
                    if int(np.asarray(
                            eng.state.leader_slot)[lane_c]) != slot:
                        eng.recover_member(lane_c, slot)
                    failed_member = None
                lane_c = int(rng.integers(lanes))
                slot = int(np.asarray(eng.state.leader_slot)[lane_c])
                eng.fail_member(lane_c, slot)
                eng.trigger_election([lane_c])
                failed_member = (lane_c, slot)
            # monotone linearizable-read probe: never below the last
            # read, never above what was placed by its completion
            if w % 5 == 4:
                val = int(np.asarray(
                    eng.consistent_read([probe_lane]))[0])
                assert probe_floor <= val <= expected[probe_lane], \
                    (probe_floor, val, int(expected[probe_lane]))
                probe_floor = val
        if disk_plan is not None:
            from ra_tpu.log import faults
            faults.clear_plan()  # heal so the durable tail converges
        ts = time.perf_counter()
        plane.settle(timeout=120.0)
        work_s += time.perf_counter() - ts  # the final drain is path
        elapsed = time.perf_counter() - t0
        gauges = plane.gauges()
        if durable_dir is not None:
            # the durability half of the backlog gauge is wired
            assert gauges["wal_pending_steps"] >= 0
        assert gauges["queue_rows"] == 0 and \
            gauges["inflight_blocks"] == 0
        mac = np.asarray(eng.consistent_read(np.arange(lanes)))
        np.testing.assert_array_equal(mac.astype(np.int64), expected)
        assert plane.counters["dup_dropped"] >= resent_rows
        throughput = placed_total / work_s
        if throughput_bar is not None:
            assert throughput >= throughput_bar, \
                f"{throughput:.0f} < bar {throughput_bar:.0f} cmds/s"
        c = plane.counters
        return {
            "value": throughput,
            "ingress_cmds_per_s": throughput,
            "ingress_shed_rate": c["shed_rows"] / max(1, c["submitted"]),
            "sessions": sessions, "lanes": lanes,
            "placed": placed_total, "dup_dropped": c["dup_dropped"],
            "blocks_built": c["blocks_built"], "elapsed_s": elapsed,
            "work_s": work_s,
            "durable": durable_dir is not None,
            # mesh stamps (ISSUE 11): the sharding + WAL layout the
            # oracle ran against, bench_diff-attributable like the
            # engine_pipeline stamps in the multichip tail
            "mesh": eng.mesh_shape(),
            "wal_shards": wal_shards if durable_dir is not None else 0,
            "wal_shard_layout": eng._dur.shard_layout()
            if durable_dir is not None else [],
            "disk_faults_injected":
                dict(disk_plan.counters) if disk_plan else {},
        }
    finally:
        net_plan.unregister()
        if disk_faults:
            from ra_tpu.log import faults
            faults.clear_plan()
        eng.close()


def test_ingress_soak_cpu_scaled_volatile():
    """Tier-1 CPU-scaled acceptance run: 50k sessions -> 512 lanes,
    resends + election chaos, exactly-once oracle."""
    res = run_ingress_soak(0)
    assert res["placed"] > 100_000
    assert res["dup_dropped"] > 0


def test_ingress_soak_cpu_scaled_durable_with_disk_faults(tmp_path):
    """Tier-1 durable variant: commits gate on real fsyncs while a
    seeded DiskFaultPlan injects EIO/torn writes into the WAL shards —
    the exactly-once oracle must hold through poison/rollover/resend."""
    res = run_ingress_soak(1, sessions=5_000, lanes=64, waves=8,
                           wave_rows=4_000, superstep_k=2, cmds=8,
                           durable_dir=str(tmp_path / "ing"),
                           disk_faults=True, wal_shards=2)
    assert res["durable"] and res["placed"] > 10_000


def _require_multidevice():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend")


def test_ingress_soak_cpu_scaled_mesh_durable(tmp_path):
    """Tier-1 mesh variant (ISSUE 11): the same exactly-once scenario
    end-to-end on lane state SHARDED over the 8 forced-host devices —
    per-device WAL shards (one per lane-axis device, fsync parallelism
    following the lane sharding), blocks staged pre-partitioned via
    the plane's auto shardings, disk-fault + election chaos."""
    _require_multidevice()
    res = run_ingress_soak(3, sessions=4_000, lanes=64, waves=6,
                           wave_rows=2_500, superstep_k=2, cmds=8,
                           durable_dir=str(tmp_path / "ing"),
                           disk_faults=True, mesh=True)
    assert res["durable"] and res["mesh"] == "1x8"
    assert res["wal_shards"] == 8
    # per-device layout: 8 equal contiguous lane slices
    assert res["wal_shard_layout"] == [[i * 8, (i + 1) * 8]
                                       for i in range(8)]
    assert res["placed"] > 5_000
    assert res["dup_dropped"] > 0


@pytest.mark.slow
def test_ingress_soak_full_scale_mesh(tmp_path):
    """The ISSUE 11 acceptance scenario at full scale: 1M sessions
    into >= 100k lanes sharded across the 8 forced-host devices,
    durable with per-device WAL shards, under disk-fault + election
    chaos, exactly-once oracle exact (tools/soak.py --ingress --mesh
    runs the same entry)."""
    _require_multidevice()
    res = run_ingress_soak(0, sessions=1_000_000, lanes=102_400,
                           waves=24, wave_rows=200_000,
                           durable_dir=str(tmp_path / "ing"),
                           disk_faults=True, mesh=True)
    assert res["sessions"] == 1_000_000 and res["lanes"] >= 100_000
    assert res["mesh"] == "1x8" and res["wal_shards"] == 8


@pytest.mark.slow
def test_ingress_soak_full_scale(tmp_path):
    """The full ISSUE 10 acceptance scenario: ~1M sessions into 10k
    lanes, durable, under disk faults, with the >=100x classic-TCP
    throughput bar.  Behind ``-m slow`` (tools/soak.py --ingress runs
    the same entry)."""
    res = run_ingress_soak(0, sessions=1_000_000, lanes=10_000,
                           waves=24, wave_rows=200_000,
                           durable_dir=str(tmp_path / "ing"),
                           disk_faults=True,
                           throughput_bar=100 * CLASSIC_TCP_BASELINE)
    assert res["sessions"] == 1_000_000


def test_ingress_bench_row_carries_diff_keys():
    """The soak tail keys feed tools/bench_diff.py: throughput is
    higher-is-better, shed rate lower-is-better (0 is a healthy
    baseline, so a shed rate APPEARING flags)."""
    import tools.bench_diff as bd
    row = {"value": 400_000.0, "ingress_cmds_per_s": 400_000.0,
           "ingress_shed_rate": 0.0}
    worse = {"value": 150_000.0, "ingress_cmds_per_s": 150_000.0,
             "ingress_shed_rate": 0.3}
    res = bd.diff(row, worse, noise_pct=10.0)
    metrics = {f["metric"]: f for f in res["rows"]["headline"]}
    assert metrics["ingress_cmds_per_s"]["regression"]
    assert metrics["ingress_shed_rate"]["regression"]
    assert res["regressions"] >= 3  # value + both ingress keys
    assert bd.diff(row, row, noise_pct=10.0)["regressions"] == 0


def test_ra_top_renders_ingress_panel(tmp_path):
    """ra_top shows the session tier: accept rate over the snapshot
    window, queue depth, ladder level, dup/shed counters, and the
    SHEDDING flag when shed_rows grew between frames."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_ing = {"sessions": 1_000_000, "queue_rows": 512,
                "accepted": 10_000, "dup_dropped": 37, "shed_rows": 0,
                "rejected": 5, "wal_pending_steps": 3,
                "ladder": {"level_name": "tight", "level": 1}}
    t0 = time.time()
    snap0 = {"seq": 1, "ts": t0 - 1.0,
             "engine": {"lanes": 16, "members": 3},
             "ingress": base_ing}
    snap1 = {"seq": 2, "ts": t0,
             "engine": {"lanes": 16, "members": 3},
             "ingress": {**base_ing, "accepted": 60_000,
                         "shed_rows": 40}}
    path = str(tmp_path / "obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(snap0) + "\n")
        f.write(json.dumps(snap1) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "ingress" in out and "sessions=1000000" in out
    assert "q=512" in out and "level=tight" in out
    assert "dup=37" in out and "shed=40" in out
    # the durability half of the backlog renders under durable/mesh
    # runs (ISSUE 11 satellite)
    assert "wal_pending=3" in out
    assert "SHEDDING" in out
    assert "50.0K acc/s" in out or "acc/s" in out
