"""Engine-level partition semantics: on-device vote counting, divergent-log
truncation repair, and pipeline-credit-governed replication
(VERDICT round-1 item 3; reference ra_server.erl:986-1002, 1032-1156,
1862-1918, 2260-2319)."""
import jax.numpy as jnp
import numpy as np

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import RegisterMachine

from test_register_machine import host_fold

N, P, K = 4, 5, 4


def puts(rng, n_cmds):
    """Random put commands, identical across lanes for oracle simplicity."""
    cmds = [(1, int(rng.integers(0, 8)), int(rng.integers(1, 100)), 0)
            for _ in range(n_cmds)]
    pay = np.zeros((N, K, 4), np.int32)
    for k, c in enumerate(cmds[:K]):
        pay[:, k] = c
    return cmds, pay


def drain(eng, steps=4):
    for _ in range(steps):
        eng.step(jnp.zeros((N,), jnp.int32), jnp.zeros((N, K, 4), jnp.int32))
    eng.block_until_ready()


def test_minority_partition_appends_discarded_on_heal():
    rng = np.random.default_rng(23)
    m = RegisterMachine(n_slots=8)
    eng = LockstepEngine(m, N, P, ring_capacity=128, max_step_cmds=K,
                         write_delay=1, donate=False)
    committed_cmds = []

    # 1. healthy commits
    cmds, pay = puts(rng, K)
    committed_cmds += cmds
    eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(pay))
    drain(eng)
    base_committed = eng.committed_total()
    assert base_committed > 0

    # 2. partition: leader (slot 0) isolated with slot 1 — a minority.
    # The leader keeps accepting appends but can never commit them.
    for slot in (2, 3, 4):
        for lane in range(N):
            eng.fail_member(lane, slot)
    minority_cmds, mpay = puts(rng, K)  # never committed: NOT in the oracle
    for _ in range(3):
        eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(mpay))
    drain(eng, 3)
    assert eng.committed_total() == base_committed, \
        "a minority must not commit"
    old_leader_tail = int(eng.state.last_index[0, 0])

    # 3. the majority side elects: old leader's side goes dark, the three
    # others come back and run a vote round (3/5 grants = quorum)
    for lane in range(N):
        eng.fail_member(lane, 0)
        eng.fail_member(lane, 1)
        eng.recover_member(lane, 2)
        eng.recover_member(lane, 3)
        eng.recover_member(lane, 4)
    term_before = int(eng.state.term[0])
    eng.trigger_election(list(range(N)))
    assert int(eng.state.term[0]) == term_before + 1
    new_leader = int(eng.state.leader_slot[0])
    assert new_leader in (2, 3, 4)

    # 4. new-term commits
    cmds, pay = puts(rng, K)
    committed_cmds += cmds
    eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(pay))
    drain(eng)

    # 5. heal: the deposed leader and its peer rejoin; their divergent
    # tails must be truncated and overwritten, never applied
    for lane in range(N):
        eng.recover_member(lane, 0)
        eng.recover_member(lane, 1)
    drain(eng, 6)

    want = host_fold(committed_cmds)
    mac = np.asarray(eng.state.mac)          # [N, P, S]
    li = np.asarray(eng.state.last_index)
    for lane in range(N):
        for member in range(P):
            assert mac[lane, member].tolist() == want, \
                (lane, member, mac[lane, member].tolist(), want)
        # the healed ex-leader's tail equals the lane tail: divergent
        # entries are gone, replication credit reopened after repair
        assert li[lane, 0] == li[lane, new_leader]
        assert li[lane, 1] == li[lane, new_leader]
    # the minority's inflated tail was actually longer than the final log
    # only if new-term appends didn't overtake it; either way it is gone
    assert int(li[0, 0]) != old_leader_tail or \
        int(eng.state.commit[0, 0]) >= int(eng.state.term_start[0])


def test_minority_election_fails():
    """A partition with only 2 of 5 voters cannot seat a leader: term,
    leader, and log are all unchanged (pre-vote style: no term bump)."""
    m = RegisterMachine(n_slots=8)
    eng = LockstepEngine(m, N, P, ring_capacity=64, max_step_cmds=K,
                         donate=False)
    cmds, pay = puts(np.random.default_rng(1), K)
    eng.step(jnp.full((N,), K, jnp.int32), jnp.asarray(pay))
    drain(eng)
    for slot in (0, 1, 2):
        for lane in range(N):
            eng.fail_member(lane, slot)
    term0 = int(eng.state.term[0])
    leader0 = int(eng.state.leader_slot[0])
    tail0 = int(eng.state.last_index[0, 3])
    eng.trigger_election(list(range(N)))
    drain(eng, 2)
    assert int(eng.state.term[0]) == term0
    assert int(eng.state.leader_slot[0]) == leader0
    assert int(eng.state.last_index[0, 3]) == tail0  # no noop appended


def test_election_quorum_counts_only_voters():
    """Nonvoters neither grant nor count toward the needed quorum
    ('$ra_join' catch-up members, ra_server.erl:3218-3293)."""
    m = RegisterMachine(n_slots=8)
    eng = LockstepEngine(m, N, P, ring_capacity=64, max_step_cmds=K,
                         donate=False)
    # demote slots 3,4 to nonvoters: voters = {0,1,2}
    eng.state = eng.state._replace(
        voter=eng.state.voter.at[:, 3:].set(False))
    # fail one voter: remaining voters {1,2} of 3 -> still a quorum (2/3)
    for lane in range(N):
        eng.fail_member(lane, 0)
    term0 = int(eng.state.term[0])
    eng.trigger_election(list(range(N)))
    assert int(eng.state.term[0]) == term0 + 1
    assert int(eng.state.leader_slot[0]) in (1, 2)
    # now fail another voter: {2} of 3 is a minority even with both
    # nonvoters reachable
    for lane in range(N):
        eng.fail_member(lane, 1)
    term1 = int(eng.state.term[0])
    eng.trigger_election(list(range(N)))
    assert int(eng.state.term[0]) == term1


def test_pipeline_credit_bounds_catchup():
    """A burst append larger than the AER batch bound reaches followers at
    most max_append_batch entries per round (ra_server.hrl:8)."""
    from ra_tpu.models import CounterMachine
    BATCH = 8
    eng = LockstepEngine(CounterMachine(), 2, 3, ring_capacity=512,
                         max_step_cmds=32, max_append_batch=BATCH,
                         donate=False)
    # one burst: the leader's tail jumps 32 in a single round
    eng.step(jnp.full((2,), 32, jnp.int32), jnp.ones((2, 32, 1), jnp.int32))
    leader_tail = int(eng.state.last_index[0, 0])
    follower = int(eng.state.last_index[0, 1])
    assert leader_tail - follower >= 32 - BATCH
    # followers drain the gap at <= BATCH per round
    steps = 0
    while int(eng.state.last_index[0, 1]) < leader_tail:
        before = int(eng.state.last_index[0, 1])
        eng.step(jnp.zeros((2,), jnp.int32), jnp.zeros((2, 32, 1),
                                                       jnp.int32))
        after = int(eng.state.last_index[0, 1])
        assert 0 < after - before <= BATCH
        steps += 1
        assert steps < 16
    assert steps >= (32 // BATCH) - 1


def test_election_caps_follower_tails_same_round():
    """write_delay=1: member tails can exceed the new leader's durable log
    at election time; the elect round itself must cap them so no phantom
    match entry ever enters the commit median (§5.4 safety)."""
    from ra_tpu.models import CounterMachine
    eng = LockstepEngine(CounterMachine(), 2, 3, ring_capacity=128,
                         max_step_cmds=32, write_delay=1, donate=False)
    # one burst: leader tail 32, leader written still 0
    eng.step(jnp.full((2,), 32, jnp.int32), jnp.ones((2, 32, 1), jnp.int32))
    eng.trigger_election([0, 1])
    st = eng.state
    tails = np.asarray(st.last_index)
    leads = np.asarray(st.leader_slot)
    match = np.asarray(st.match)
    for lane in range(2):
        leader_tail = tails[lane, leads[lane]]
        assert (tails[lane] <= leader_tail).all(), (lane, tails[lane])
        assert (match[lane] <= leader_tail).all(), (lane, match[lane])
