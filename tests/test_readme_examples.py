"""The README quickstart blocks must actually run — extracted verbatim
from README.md and executed (with only filesystem paths and sizes
patched), so the documented first-contact API can never rot."""
import os
import re

import pytest


def _patch(src, old, new):
    """Replace that REFUSES to no-op: README drift must fail the test,
    not silently run the unpatched block (full-size configs, shared
    /tmp paths, files written into the CWD)."""
    assert old in src, f"README drift: {old!r} not found"
    return src.replace(old, new)


def _blocks():
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        text = f.read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)

def test_readme_has_nine_python_blocks():
    assert len(_blocks()) == 9

def test_classic_quickstart_block(tmp_path):
    src = _blocks()[0]
    assert "start_server" in src and "consistent_query" in src
    # patch only the data dir; everything else runs as documented
    src = _patch(src, 'f"/tmp/ra/{s.node}"', 'str(tmp_path / s.node)')
    ns: dict = {"tmp_path": tmp_path}
    try:
        exec(compile(src, "README.md[classic]", "exec"), ns)  # noqa: S102
        # the block printed the linearizable read; re-check it here
        import ra_tpu
        from ra_tpu.models.kv import query_get
        res = ra_tpu.consistent_query(ns["sids"][0], query_get("greeting"),
                                      router=ns["router"])
        assert res.reply == "hello"
    finally:
        for n in ns.get("nodes", {}).values():
            n.stop()
        for s in ns.get("systems", {}).values():
            s.close()

def test_engine_quickstart_block():
    src = _blocks()[1]
    assert "LockstepEngine" in src
    # shrink the documented 10k-lane config for suite runtime; the
    # structure (shapes, calls) runs exactly as written
    src = _patch(src, "10_000", "64")
    ns = {}
    exec(compile(src, "README.md[engine]", "exec"), ns)  # noqa: S102
    assert ns["eng"].committed_total() > 0

def test_trace_quickstart_block():
    src = _blocks()[3]
    lines = [ln for ln in src.splitlines()
             if not ln.strip().startswith("...")]
    src = "\n".join(lines)
    src = _patch(src, 't.dump_chrome_trace("ra_trace.json")', 'pass')
    from ra_tpu import trace
    ns = {}
    try:
        exec(compile(src, "README.md[trace]", "exec"), ns)  # noqa: S102
        assert isinstance(ns["t"].summary(), dict)
    finally:
        trace.set_tracer(None)


def test_slo_autotune_quickstart_block(tmp_path):
    """The ISSUE 9 closed-loop block: SLO verdicts + phase attribution
    + an autotuner ticking a real durable engine, as documented."""
    src = _blocks()[5]
    assert "SloEngine" in src and "AutoTuner" in src
    # patch only path + size; the loop runs exactly as documented
    src = _patch(src, '"/tmp/ra_slo_demo", 1024', "demo_dir, 64")
    ns: dict = {"demo_dir": str(tmp_path / "slo_demo")}
    try:
        exec(compile(src, "README.md[slo]", "exec"), ns)  # noqa: S102
        verdicts = ns["slo"].evaluate()["objectives"]
        assert set(verdicts) == {"commit_p99_ms", "fsync_p99_ms",
                                 "cmds_per_s", "read_p99_ms",
                                 "steady_state_recompiles"}
        ns["eng"]._dur.flush_all()  # settle async confirms -> e2e samples
        snap = ns["obs"].snapshot()
        assert snap["engine"]["phases"]["commit_e2e"]["count"] > 0
        assert "autotune" in snap and "slo" in snap
    finally:
        if "obs" in ns:
            ns["obs"].close()
        if "eng" in ns:
            ns["eng"].close()


def test_ingress_quickstart_block():
    """The ISSUE 10 session-tier block: connect a bulk fleet, submit
    with auto-minted seqnos, pump, settle — exactly once."""
    src = _blocks()[6]
    assert "IngressPlane" in src and "connect_bulk" in src
    # shrink lanes + fleet for suite runtime; structure runs as written
    src = _patch(src, "10_000", "128")
    src = _patch(src, "50_000", "2_000")
    ns: dict = {}
    try:
        exec(compile(src, "README.md[ingress]", "exec"), ns)  # noqa: S102
        plane = ns["plane"]
        assert plane.counters["accepted"] > 0
        assert plane.window.queue_rows() == 0   # settled
        assert ns["eng"].committed_total() >= plane.counters["accepted"]
    finally:
        if "eng" in ns:
            ns["eng"].close()


def test_wire_quickstart_block():
    """The ISSUE 12 wire block: real TCP listener + at-least-once
    client + machine-level dedup — exactly-once-observable through a
    reconnect."""
    import time as _time
    src = _blocks()[7]
    assert "WireListener" in src and "WireClient" in src
    assert "DedupCounterMachine" in src
    # shrink lanes for suite runtime; structure runs as written
    src = _patch(src, "256, 3", "32, 3")
    # the documented busy-wait is fine interactively; bound it for CI
    src = _patch(src, "while lst.sweep() == 0:                      "
                      "# reader ring -> numpy batch\n    pass",
                 "deadline = __import__('time').monotonic() + 30\n"
                 "while lst.sweep() == 0:\n"
                 "    assert __import__('time').monotonic() < deadline")
    ns: dict = {}
    try:
        exec(compile(src, "README.md[wire]", "exec"), ns)  # noqa: S102
        cli = ns["cli"]
        deadline = _time.monotonic() + 30
        while cli.acked_count() < 3:
            cli.flush()
            ns["lst"].sweep()
            ns["plane"].pump(force=True)
            ns["plane"].settle()
            cli.poll()
            assert _time.monotonic() < deadline
        import numpy as np
        total = int(np.asarray(
            ns["eng"].consistent_read(np.arange(32))["value"]).sum())
        assert total == 42  # 5 + 7 + 30, each exactly once
    finally:
        if "lst" in ns:
            ns["lst"].close()
        if "cli" in ns:
            ns["cli"].close()
        if "eng" in ns:
            ns["eng"].close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_failover_quickstart_block(tmp_path):
    """The ISSUE 17 failover block: one small-geometry failover soak
    runs as written and the exactly-once oracle closes (the kill-9
    dies loudly in the victim's WAL thread by design)."""
    src = _blocks()[8]
    assert "run_failover_soak" in src
    # route the soak's durable dirs into the test sandbox
    src = _patch(src, "kill_wave=2)",
                 "kill_wave=2, data_dir=str(tmp_path))")
    ns: dict = {"tmp_path": tmp_path}
    exec(compile(src, "README.md[failover]", "exec"), ns)  # noqa: S102
    row = ns["row"]
    assert row["failover_lost_acked"] == 0
    assert row["failover_double_applied"] == 0
    assert row["failover_recovery_s"] > 0
    assert row["migrations"] >= 1


def test_telemetry_quickstart_block(tmp_path):
    src = _blocks()[4]
    assert "TelemetrySampler" in src and "Observatory" in src
    ring = str(tmp_path / "obs.jsonl")
    src = _patch(src, '"obs.jsonl"', "ring")
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    eng = LockstepEngine(CounterMachine(), 8, 3, ring_capacity=64,
                         max_step_cmds=4, donate=False)
    ns: dict = {"engine": eng, "ring": ring}
    exec(compile(src, "README.md[telemetry]", "exec"), ns)  # noqa: S102
    for _ in range(4):
        eng.uniform_step(2)
    ns["sampler"].drain()
    snap = ns["obs"].snapshot()
    assert snap["engine"]["telemetry"]["steps"] == 4
    import os
    assert os.path.exists(ring)

def test_read_quickstart_block():
    src = _blocks()[2]
    assert "read_lanes" in src and "TtlKvMachine" in src
    # shrink the documented 1024-lane config for suite runtime; the
    # structure (shapes, calls, assertions) runs exactly as written
    src = _patch(src, "1024", "64")
    ns: dict = {}
    exec(compile(src, "README.md[reads]", "exec"), ns)  # noqa: S102
    assert ns["ok"].all() and (ns["replies"][:, 1] == 42).all()
    assert (ns["watermark"] >= 0).all()
