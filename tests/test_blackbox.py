"""Causal tracing plane + crash flight recorder (ISSUE 7).

Covers: per-subsystem ring discipline and the RA06 runtime mirror;
trace-context propagation client→submit→append→WAL→commit→apply on the
classic path; trace ids riding reliable-RPC frames under a seeded
transport FaultPlan (duplicate deliveries VISIBLE as ``rpc.dup`` under
one id while execution stays at-most-once); post-mortem bundle dumps
on WAL kill / poison-streak escalation with the active DiskFaultPlan
named inside; recovery stamping a join-able report; ra_trace timeline
reconstruction + --explain; the RPC_FIELDS→Observatory round trip; the
ra_top incident footer; and the <3% recorder overhead pin on the bench
dispatch path.

``run_blackbox_chaos`` is the seeded chaos family ``tools/soak.py
--blackbox`` drives: kill-9 a WAL under an active DiskFaultPlan and
prove the bundle explains a faulted command end to end.
"""
import glob
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ra_tpu.api as A
from ra_tpu import trace
from ra_tpu.blackbox import EVENT_REGISTRY, FlightRecorder, RECORDER, \
    load_bundle
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerId
from ra_tpu.engine import LockstepEngine
from ra_tpu.log import faults
from ra_tpu.models import CounterMachine
from ra_tpu.node import LocalRouter, RaNode
from ra_tpu.system import RaSystem
from ra_tpu.telemetry import parse_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import ra_trace  # noqa: E402


ADD = SimpleMachine(lambda c, s: s + c, 0)

#: the complete classic-path lifecycle ra_trace must reconstruct
CORE_HOPS = {"cmd.ingress", "cmd.submit", "cmd.append", "wal.write",
             "wal.confirm", "cmd.commit", "cmd.apply"}


@pytest.fixture(autouse=True)
def _fresh_recorder():
    RECORDER.clear()
    yield
    RECORDER.clear()
    faults.clear_plan()


def _mk_cluster(root, router, n=3, prefix="bx"):
    sys_ = RaSystem(str(root), wal_supervise=False)
    node = RaNode(f"{prefix}-n1", router=router, system=sys_)
    sids = [ServerId(f"{prefix}-s{i}", f"{prefix}-n1") for i in range(n)]
    A.start_cluster(f"{prefix}-c", lambda: ADD, sids, router=router)
    return sys_, node, sids


# ---------------------------------------------------------------------------
# recorder unit behaviour
# ---------------------------------------------------------------------------

def test_rings_are_per_subsystem_and_bounded():
    r = FlightRecorder(ring_capacity=8)
    for i in range(50):
        r.record("wal.fsync", ms=i)
    r.record("sup.giveup", plane="wal")
    assert len(r.events("wal")) == 8          # bounded
    assert len(r.events("sup")) == 1          # noisy plane can't evict
    assert [e[2]["ms"] for e in r.events("wal")] == list(range(42, 50))
    merged = r.events()
    assert merged == sorted(merged, key=lambda e: e[0])


def test_unregistered_event_counted_not_lost():
    """The RA06 runtime mirror: a typo'd type is still recorded
    (evidence beats purity at a crash site) but self-counted."""
    r = FlightRecorder()
    r.record("zz.not_a_real_event", x=1)
    assert r.counters["unregistered_events"] == 1
    assert len(r.events("zz")) == 1
    r.record("wal.fsync", ms=1)
    assert r.counters["unregistered_events"] == 1


def test_disabled_recorder_records_nothing():
    r = FlightRecorder()
    r.enabled = False
    r.record("wal.fsync", ms=1)
    assert r.events() == [] and r.counters["events"] == 0


def test_dump_isolates_failing_sources(tmp_path):
    r = FlightRecorder()
    r.add_source("good", lambda: {"x": 1})
    r.add_source("bad", lambda: 1 / 0)
    r.record("bb.dump", reason="seed")  # some ring content
    path = r.dump("unit_test", what="w", where="here",
                  data_dir=str(tmp_path))
    doc = load_bundle(path)
    assert doc["sources"]["good"] == {"x": 1}
    assert "error" in doc["sources"]["bad"]
    assert doc["reason"] == "unit_test"
    assert r.last_incident()["path"] == path
    # a second dump lists the first as a prior incident
    path2 = r.dump("unit_test_2", data_dir=str(tmp_path))
    assert load_bundle(path2)["incidents"][-1]["reason"] == "unit_test"


def test_every_registry_key_has_a_doc_line():
    with open(os.path.join(REPO, "docs", "OBSERVABILITY.md")) as f:
        doc = f.read()
    missing = [k for k in EVENT_REGISTRY if f"`{k}`" not in doc]
    assert not missing, missing


# ---------------------------------------------------------------------------
# trace-context propagation: classic path
# ---------------------------------------------------------------------------

def test_classic_command_full_lifecycle_traced(tmp_path):
    router = LocalRouter()
    trace.set_trace_origin("tlc")
    sys_, node, sids = _mk_cluster(tmp_path, router)
    try:
        res = A.process_command(sids[0], 7, router=router, timeout=10)
        assert res.reply == 7
        evs = RECORDER.events()
        mine = [e for e in evs if e[2].get("trace") == "tlc-1"]
        kinds = [e[1] for e in mine]
        assert kinds[0] == "cmd.ingress"
        assert "cmd.submit" in kinds and "cmd.append" in kinds
        assert kinds.count("cmd.apply") == 3  # every member applies
        # the idx-keyed WAL/commit joins complete the timeline
        traces = ra_trace.index_traces(
            [(*e, "local") for e in evs])
        tl = traces["tlc-1"]
        assert CORE_HOPS <= {e[1] for e in tl["hops"]}, \
            sorted({e[1] for e in tl["hops"]})
        text = ra_trace.explain("tlc-1", tl)
        assert "breakdown:" in text and "wal write+fsync wait" in text
    finally:
        node.stop()
        sys_.close()


def test_pipeline_command_and_fifo_seqno_ctx(tmp_path):
    """pipeline_command mints a ctx too, and FifoClient's derived
    ``<mailbox>/<seqno>`` id is stable across resends by design."""
    from ra_tpu.models.fifo_client import FifoClient
    from ra_tpu.models.fifo import FifoMachine

    router = LocalRouter()
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    node = RaNode("fx-n1", router=router, system=sys_)
    sids = [ServerId(f"fx-s{i}", "fx-n1") for i in range(3)]
    A.start_cluster("fx-c", FifoMachine, sids, router=router)
    try:
        cli = FifoClient(sids, router=router, tag="fxc")
        cli.enqueue(b"one")
        cli.flush()
        want = f"{cli.mailbox.name}/1"
        evs = [e for e in RECORDER.events("cmd")
               if e[2].get("trace") == want]
        assert any(e[1] == "cmd.ingress" for e in evs)
        assert any(e[1] == "cmd.append" for e in evs)
        # a resend reuses the SAME id: one timeline, not two
        cli.pending[1] = b"one"
        cli.resend()
        ing = [e for e in RECORDER.events("cmd")
               if e[1] == "cmd.ingress" and e[2].get("trace") == want]
        # >= 2: flush() may add its own stall-driven resend, also
        # under the same id — still one timeline
        assert len(ing) >= 2
    finally:
        node.stop()
        sys_.close()


# ---------------------------------------------------------------------------
# trace context over reliable RPC under a transport FaultPlan
# ---------------------------------------------------------------------------

def test_rpc_trace_ctx_survives_duplicates_and_partition():
    """Satellite: duplicate/partition frames keep at-most-once
    execution while the dup delivery is VISIBLE in the trace under the
    same id (fixed seed, asserted timeline shape)."""
    from ra_tpu.transport.rpc import FaultPlan, FaultSpec, Unreachable
    from ra_tpu.transport.tcp import TcpRouter

    server = TcpRouter(("127.0.0.1", 0), {})
    node = RaNode("bz1", router=server)
    client = TcpRouter(("127.0.0.1", 0), {"bz1": server.listen_addr})
    try:
        plan = FaultPlan(11, by_class={
            "rpc_req": FaultSpec(duplicate=1.0, limit=3)})
        client.set_fault_plan(plan)
        trace.set_trace_origin("rpx")
        assert A.node_call("bz1", "ping", {}, router=client,
                           timeout=20) == ("pong", "bz1")
        evs = RECORDER.events("rpc")
        sends = [e for e in evs if e[1] == "rpc.send"]
        assert sends, "sender never recorded rpc.send"
        ctx = sends[0][2]["trace"]
        assert ctx.startswith("rpx-")
        recvs = [e for e in evs
                 if e[1] == "rpc.recv" and e[2]["trace"] == ctx]
        dups = [e for e in evs
                if e[1] == "rpc.dup" and e[2]["trace"] == ctx]
        # at-most-once: executed exactly once; every duplicate dedup'd
        # under the SAME trace id
        assert len(recvs) == 1
        assert len(dups) >= 1
        assert server.rpc_counters["rpc_dedup_hits"] >= 1
        # the injected duplicates themselves are events too
        assert any(e[1] == "net.fault"
                   and e[2]["kind"] == "duplicate"
                   for e in RECORDER.events("net"))
        # reorder: frames shuffle behind the batch; the rid+ctx keep
        # execution at-most-once and the call still completes
        plan2 = FaultPlan(12, by_class={
            "rpc_req": FaultSpec(reorder=1.0, limit=2)})
        client.set_fault_plan(plan2)
        executed0 = server.rpc_counters["rpc_requests_executed"]
        assert A.node_call("bz1", "ping", {}, router=client,
                           timeout=20) == ("pong", "bz1")
        assert server.rpc_counters["rpc_requests_executed"] \
            - executed0 == 1
        # partition: unreachable surfaces, with the partition visible
        plan2.partition("bz1")
        with pytest.raises(Unreachable):
            A.node_call("bz1", "ping", {}, router=client, timeout=2)
        assert any(e[2]["kind"] == "partition"
                   for e in RECORDER.events("net"))
        plan2.heal()
    finally:
        node.stop()
        client.stop()
        server.stop()


# ---------------------------------------------------------------------------
# dump triggers + recovery stamp
# ---------------------------------------------------------------------------

def test_wal_kill_dumps_bundle_with_active_plan_named(tmp_path):
    router = LocalRouter()
    sys_, node, sids = _mk_cluster(tmp_path, router, prefix="bk")
    try:
        A.process_command(sids[0], 1, router=router, timeout=10)
        faults.install_plan(faults.DiskFaultPlan(5, by_class={
            "wal": faults.DiskFaultSpec(fsync_eio=1.0, limit=1)}))
        A.process_command(sids[0], 2, router=router, timeout=10)
        sys_.wal.kill()
        bundles = glob.glob(str(tmp_path / "blackbox" / "bundle-*"))
        assert len(bundles) == 1
        doc = load_bundle(bundles[0])
        assert doc["reason"] == "wal_kill"
        plan_src = doc["sources"]["disk_fault_plan"]
        assert plan_src["plan"] is not None
        assert "fsync_eio" in json.dumps(plan_src["plan"])
        kinds = {e[1] for evs in doc["events"].values() for e in evs}
        assert {"wal.kill", "wal.poison", "disk.fault"} <= kinds
    finally:
        faults.clear_plan()
        node.stop()
        sys_.close()


def test_poison_streak_escalation_dumps_bundle(tmp_path):
    """MAX_POISON_STREAK consecutive faulted batches -> thread death is
    a dump trigger (the ladder giving up is exactly when you want the
    black box)."""
    from ra_tpu.log.wal import MAX_POISON_STREAK, Wal

    wal = Wal(str(tmp_path))
    try:
        wal.register("u1", lambda *a: None)
        faults.install_plan(faults.DiskFaultPlan(1, by_class={
            "wal": faults.DiskFaultSpec(fsync_eio=1.0)}))
        # the no-op notify never resends, so drive a fresh faulted
        # batch per write until the streak escalates
        deadline = time.monotonic() + 10
        idx = 0
        while wal.alive and time.monotonic() < deadline:
            idx += 1
            try:
                wal.write("u1", idx, 1, b"x")
            except Exception:  # noqa: BLE001 — WalDown once it dies
                break
            time.sleep(0.05)
        assert not wal.alive
        bundles = glob.glob(str(tmp_path / "blackbox" / "bundle-*"))
        assert bundles, "escalation did not dump"
        doc = load_bundle(bundles[0])
        assert doc["reason"] == "wal_escalation"
        esc = [e for e in doc["events"]["wal"]
               if e[1] == "wal.escalate"]
        assert esc and esc[0][2]["streak"] == MAX_POISON_STREAK
    finally:
        faults.clear_plan()
        wal.close()


def test_recovery_stamp_joins_newest_bundle(tmp_path):
    router = LocalRouter()
    sys_, node, sids = _mk_cluster(tmp_path, router, prefix="br")
    A.process_command(sids[0], 3, router=router, timeout=10)
    sys_.wal.kill()          # bundle
    node.stop()
    sys_.close()
    RECORDER.clear()
    sys2 = RaSystem(str(tmp_path), wal_supervise=False)  # reopen
    try:
        recs = sorted(glob.glob(str(tmp_path / "blackbox"
                                    / "recovery-*")))
        assert recs, "reopen did not stamp a recovery report"
        with open(recs[-1]) as f:
            rep = json.load(f)
        assert rep["plane"] == "classic_wal"
        assert rep["joins"] and rep["joins"].startswith("bundle-")
        assert any(e[1] == "bb.recover"
                   for e in RECORDER.events("bb"))
    finally:
        sys2.close()


# ---------------------------------------------------------------------------
# RPC_FIELDS -> Observatory exposition/ring (satellite, round-trip)
# ---------------------------------------------------------------------------

def test_rpc_counters_reach_exposition_and_ring(tmp_path):
    class _Router:
        rpc_counters = {"rpc_calls": 3, "rpc_retries": 1,
                        "rpc_dedup_hits": 2}

    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        obs = sys_.observatory(router=_Router())
        text = obs.prometheus()
        parsed = parse_prometheus(text)
        assert parsed[("ra_tpu_rpc_rpc_calls", "")] == 3.0
        assert parsed[("ra_tpu_rpc_rpc_dedup_hits", "")] == 2.0
        # and the time-series ring rates them like any counter
        _Router.rpc_counters["rpc_calls"] = 13
        obs.snapshot()
        rates = obs.window_rates()
        assert rates.get("rpc_rpc_calls", 0) > 0
    finally:
        sys_.close()


def test_observatory_embeds_blackbox_incident(tmp_path):
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        obs = sys_.observatory()
        RECORDER.dump("unit_incident", what="w", where="x",
                      data_dir=str(tmp_path))
        snap = obs.snapshot()
        inc = snap["blackbox"]["last_incident"]
        assert inc["reason"] == "unit_incident"
        # bundles embed a fresh Observatory snapshot while it is wired
        path = RECORDER.dump("unit_incident_2", data_dir=str(tmp_path))
        assert "observatory" in load_bundle(path)["sources"]
        # close() unhooks the bundle source (identity-guarded: a NEWER
        # observatory's registration would survive a stale close)
        obs.close()
        path = RECORDER.dump("unit_incident_3", data_dir=str(tmp_path))
        assert "observatory" not in load_bundle(path)["sources"]
    finally:
        sys_.close()


def test_ra_top_once_renders_incident_footer(tmp_path):
    path = str(tmp_path / "obs.jsonl")
    snap = {"seq": 1, "ts": time.time(),
            "engine": {"lanes": 4, "members": 3},
            "blackbox": {"last_incident": {
                "ts": time.time() - 5, "reason": "wal_escalation",
                "what": "poison streak 3 -> thread death",
                "where": "/x/00000001.wal",
                "path": "/x/blackbox/bundle-1-2-003-wal_escalation"
                        ".json"}}}
    with open(path, "w") as f:
        f.write(json.dumps(snap) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "incident wal_escalation" in r.stdout
    assert "bundle-1-2-003-wal_escalation.json" in r.stdout


# ---------------------------------------------------------------------------
# overhead: recorder enabled vs disabled on the bench dispatch path
# ---------------------------------------------------------------------------

def test_volatile_dispatch_path_emits_no_recorder_events():
    """Structural half of the overhead pin: the volatile engine
    dispatch path emits ZERO per-dispatch recorder events (boundary
    events exist only on the durable submit path and on rare host
    transitions)."""
    eng = LockstepEngine(CounterMachine(), 8, 3, ring_capacity=64,
                         max_step_cmds=4, donate=False)
    base = RECORDER.counters["events"]
    for _ in range(20):
        eng.uniform_step(2)
    eng.block_until_ready()
    assert RECORDER.counters["events"] == base


def test_recorder_overhead_under_3pct_on_bench_path():
    """Interleaved A/B of the bench dispatch pattern, recorder enabled
    (default, tracing off -> the disabled-tracing contract) vs hard
    disabled.  Same shape as the telemetry overhead pin: medians over
    interleaved rounds, retries absorb CI noise."""
    import collections

    eng = LockstepEngine(CounterMachine(), 64, 3, ring_capacity=64,
                         max_step_cmds=8, donate=False)
    n_new = np.full((64,), 8, np.int32)
    pay = np.ones((64, 8, 1), np.int32)
    for _ in range(10):
        eng.step(n_new, pay)
    eng.block_until_ready()

    def measure(seconds):
        rb: collections.deque = collections.deque()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            eng.step(n_new, pay)
            rb.append(eng.committed_lanes_async())
            while len(rb) > 8:
                np.asarray(rb.popleft())
            n += 1
        eng.block_until_ready()
        return n / (time.perf_counter() - t0)

    overhead = 1.0
    for _attempt in range(3):
        rates = {False: [], True: []}
        for _round in range(4):
            for enabled in (False, True):
                RECORDER.enabled = enabled
                rates[enabled].append(measure(0.25))
        RECORDER.enabled = True
        off = sorted(rates[False])[len(rates[False]) // 2]
        on = sorted(rates[True])[len(rates[True]) // 2]
        overhead = (off - on) / off
        if overhead < 0.03:
            break
    assert overhead < 0.03, f"recorder overhead {overhead:.1%} >= 3%"


# ---------------------------------------------------------------------------
# the seeded chaos family (tools/soak.py --blackbox)
# ---------------------------------------------------------------------------

def run_blackbox_chaos(seed: int, root: str) -> dict:
    """One episode: classic durable cluster, traced traffic through a
    seeded DiskFaultPlan, then kill-9 the WAL under the ACTIVE plan.
    Asserts the bundle exists, parses, names the injected fault, and
    that ra_trace reconstructs a complete faulted-command lifecycle.
    Returns summary facts for the soak driver."""
    import random

    rng = random.Random(seed)
    RECORDER.clear()
    trace.set_trace_origin(f"bb{seed}")
    router = LocalRouter()
    # supervised, like production: a fault schedule that happens to
    # kill the batch thread mid-rollover (a torn write hitting the
    # fresh file's magic) must heal via restart+resend, not stall the
    # episode — the let-it-crash shape PR 4 pinned
    sys_ = RaSystem(os.path.join(root, "sys"), wal_supervise=True)
    node = RaNode("cb-n1", router=router, system=sys_)
    sids = [ServerId(f"cb-s{i}", "cb-n1") for i in range(3)]
    A.start_cluster("cb-c", lambda: ADD, sids, router=router)
    kind = rng.choice(["fsync_eio", "short_write"])
    try:
        for i in range(rng.randint(2, 5)):
            A.process_command(sids[0], i, router=router, timeout=10)
        spec = faults.DiskFaultSpec(**{kind: 1.0},
                                    limit=rng.randint(1, 2))
        faults.install_plan(faults.DiskFaultPlan(
            seed, by_class={"wal": spec}))
        # traced traffic THROUGH the fault: poison -> rollover ->
        # resend -> confirm, so the faulted command still completes
        # its lifecycle (that is the point: explain a command the
        # fault delayed, not one it killed)
        for i in range(4):
            A.process_command(sids[0], 100 + i, router=router,
                              timeout=15)
        sys_.wal.kill()      # kill-9 under the active plan
        bdir = os.path.join(sys_.data_dir, "blackbox")
        bundles = sorted(glob.glob(os.path.join(bdir, "bundle-*")))
        assert bundles, "wal kill did not dump a bundle"
        doc = load_bundle(bundles[-1])          # parses
        plan_named = doc["sources"]["disk_fault_plan"]["plan"]
        assert plan_named is not None and kind in json.dumps(plan_named)
        kinds = {e[1] for evs in doc["events"].values() for e in evs}
        assert "disk.fault" in kinds, "injected fault not in rings"
        # -- reconstruction through the public tool surface ------------
        events = ra_trace.load_events([bundles[-1]])
        traces = ra_trace.index_traces(events)
        auto = ra_trace.pick_auto(traces)
        tl = traces[auto]
        hops = {e[1] for e in tl["hops"]}
        assert CORE_HOPS <= hops, (auto, sorted(hops))
        assert tl["faults"], "picked trace has no fault in window"
        text = ra_trace.explain(auto, tl)
        assert "FAULT" in text and "breakdown:" in text
        return {"bundle": bundles[-1], "trace": auto, "kind": kind,
                "n_traces": len(traces),
                "fault_events": sum(1 for e in events
                                    if e[1] == "disk.fault")}
    finally:
        faults.clear_plan()
        node.stop()
        sys_.close()
        RECORDER.clear()


def test_blackbox_chaos_family_seed0(tmp_path):
    res = run_blackbox_chaos(0, str(tmp_path))
    assert res["n_traces"] >= 4 and res["fault_events"] >= 1
    # the acceptance surface is the CLI: a bundle + --explain auto
    # prints the full lifecycle with the injected fault inline
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ra_trace.py"),
         res["bundle"], "--explain", "auto"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    for frag in ("cmd.ingress", "cmd.submit", "cmd.append",
                 "wal.confirm", "cmd.commit", "cmd.apply",
                 "FAULT", "breakdown:"):
        assert frag in r.stdout, (frag, r.stdout)


def test_blackbox_chaos_family_seed3(tmp_path):
    res = run_blackbox_chaos(3, str(tmp_path))
    assert res["kind"] in ("fsync_eio", "short_write")


def test_chrome_export_is_loadable(tmp_path):
    router = LocalRouter()
    trace.set_trace_origin("ce")
    sys_, node, sids = _mk_cluster(tmp_path, router, prefix="ce")
    try:
        A.process_command(sids[0], 1, router=router, timeout=10)
        events = [(*e, "procA") for e in RECORDER.events()]
        traces = ra_trace.index_traces(events)
        out = str(tmp_path / "trace.json")
        ra_trace.to_chrome(events, traces, out)
        with open(out) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        assert any(e["ph"] == "X" for e in evs)      # hop spans
        assert any(e.get("name") == "process_name" for e in evs)
        assert all("ts" in e for e in evs if e["ph"] != "M")
    finally:
        node.stop()
        sys_.close()
