"""Pluggable snapshot modules (ra_snapshot behaviour, ra_snapshot.erl:
98-168 + the Machine.snapshot_module/0 override, ra_machine.erl:435-437):
a machine-selected format must round-trip release_cursor -> restart
recovery AND the chunked follower install, with the default (pickle)
unchanged."""
import os
import struct


from harness import SimCluster
from ra_tpu.core.machine import Machine
from ra_tpu.core.types import (CommandEvent, ElectionTimeout,                                ReleaseCursor, ServerConfig, ServerId,
                               UserCommand)
from ra_tpu.log.snapshot import SnapshotModule
from ra_tpu.system import RaSystem

MAGIC = b"CNT1"


class CounterSnapshotModule(SnapshotModule):
    """Custom fixed-width binary format for an int-counter machine —
    the 'machine with huge state streams a custom format' case."""

    name = "cnt1"

    def encode(self, machine_state):
        return MAGIC + struct.pack("<q", int(machine_state))

    def decode(self, data):
        assert data[:4] == MAGIC, data[:8]
        return struct.unpack_from("<q", data, 4)[0]

    def validate(self, data):
        return data[:4] == MAGIC and len(data) == 12


class SnapCounter(Machine):
    """Counter releasing its cursor every 16 applies, with the custom
    snapshot format."""

    def init(self, config):
        return 0

    def apply(self, meta, command, state):
        new = state + command
        if meta.index % 16 == 0:
            return new, new, [ReleaseCursor(meta.index, new)]
        return new, new

    def snapshot_module(self):
        return CounterSnapshotModule()


def pump(c, rounds=12):
    for _ in range(rounds):
        for sid in c.ids:
            while c.queues[sid]:
                c.handle(sid, c.queues[sid].popleft())


def test_custom_module_snapshot_and_recovery(tmp_path):
    """release_cursor writes the custom format; a restarted server over
    the same dir recovers through the custom decode."""
    sys_ = RaSystem(str(tmp_path))
    sid = ServerId("s1", "n1")
    cfg = ServerConfig(server_id=sid, uid="u_s1", cluster_name="c",
                       initial_members=(sid,), machine=SnapCounter())
    from ra_tpu.core.server import RaServer
    log = sys_.log_factory(cfg)
    srv = RaServer(cfg, log)
    srv.recover()

    from ra_tpu.core.types import Checkpoint, PromoteCheckpoint

    def execute(effects):
        # minimal shell: snapshot-lifecycle machine effects only
        for eff in effects:
            if isinstance(eff, (ReleaseCursor, Checkpoint,
                                PromoteCheckpoint)):
                execute(srv.handle_machine_effect(eff))

    def drain():
        for _ in range(20):
            evts = log.take_events()
            if not evts:
                import time as _t
                _t.sleep(0.01)
                evts = log.take_events()
                if not evts:
                    break
            for evt in evts:
                execute(srv.handle(evt))

    execute(srv.handle(ElectionTimeout()))
    drain()
    assert srv.raft_state.value == "leader", srv.raft_state
    for _ in range(40):
        execute(srv.handle(CommandEvent(UserCommand(2))))
        drain()
    # settle before asserting: WAL confirms are async, so keep draining
    # until every appended entry is written AND applied — the release
    # cursor only fires on APPLYING index 16/32, and state_now must not
    # be a racy partial value
    import time as _t
    deadline = _t.monotonic() + 10.0
    while _t.monotonic() < deadline and (
            log.last_written().index < log.last_index_term().index or
            srv.last_applied < log.last_index_term().index):
        drain()
    assert srv.last_applied == log.last_index_term().index, \
        (srv.last_applied, log.last_index_term())
    assert srv.machine_state > 0
    snap = log.snapshot_index_term()
    assert snap.index >= 16, snap
    # on disk: the data section is OUR format, not a pickle
    snapdir = [f for f in os.listdir(str(tmp_path / "u_s1" / "snapshot"))]
    assert snapdir, "no snapshot file written"
    state_now = srv.machine_state
    sys_.close()

    sys2 = RaSystem(str(tmp_path))
    log2 = sys2.log_factory(cfg)
    srv2 = RaServer(cfg, log2)
    srv2.recover()
    # recovery applies through the persisted last_applied; the custom
    # decode must have seeded at least the snapshot state
    assert srv2.machine_state >= snap.index * 2 - 2, srv2.machine_state
    assert srv2.log.snapshot_index_term().index == snap.index
    # after re-election the tail re-commits and state fully catches up
    def drain2():
        for _ in range(20):
            evts = log2.take_events()
            if not evts:
                import time as _t
                _t.sleep(0.01)
                evts = log2.take_events()
                if not evts:
                    break
            for evt in evts:
                srv2.handle(evt)
    srv2.handle(ElectionTimeout())
    deadline = _t.monotonic() + 10.0
    while _t.monotonic() < deadline and srv2.machine_state != state_now:
        drain2()
    assert srv2.machine_state == state_now, (srv2.machine_state, state_now)
    sys2.close()


def test_custom_module_chunked_install():
    """A lagging follower receives the snapshot in chunks and recovers
    the machine state through the custom decode (SURVEY §3.3)."""
    c = SimCluster(3, machine_factory=SnapCounter, snapshot_chunk_size=5)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)
    # partition s3, drive past a release point, heal -> snapshot install
    victim = c.ids[2]
    for other in (c.ids[0], c.ids[1]):
        c.dropped.add((other, victim))
        c.dropped.add((victim, other))
    for _ in range(40):
        c.handle(c.ids[0], CommandEvent(UserCommand(3)))
        pump(c, 2)
    leader_srv = c.servers[c.ids[0]]
    assert leader_srv.log.snapshot_index_term().index > 0
    c.dropped.clear()
    # no real timers in the sim: a tick makes the leader re-probe the
    # healed peer, whose rewind forces the snapshot fallback
    from ra_tpu.core.types import TickEvent
    for _ in range(6):
        c.handle(c.ids[0], TickEvent())
        pump(c, 6)
    v = c.servers[victim]
    assert v.machine_state == leader_srv.machine_state
    assert v.log.snapshot_index_term().index > 0
    assert v.log.counters["snapshot_installed"] >= 1


def test_default_module_unchanged():
    """Machines without an override keep the pickle default."""
    from ra_tpu.log.memory import MemoryLog
    from ra_tpu.log.snapshot import DEFAULT_SNAPSHOT_MODULE
    log = MemoryLog()
    assert log.snapshot_module is DEFAULT_SNAPSHOT_MODULE
    st = {"a": [1, 2, 3]}
    assert log.snapshot_module.decode(log.snapshot_module.encode(st)) == st


def test_module_chunks_roundtrip():
    m = CounterSnapshotModule()
    data = m.encode(12345)
    parts = list(m.chunks(data, 4))
    assert b"".join(parts) == data
    assert m.decode(b"".join(parts)) == 12345
