"""Ops-surface tests: aux commands, counters/key_metrics, overview,
offline debug replay (ra_dbg role)."""
import time

import pytest

import ra_tpu
from ra_tpu import LocalRouter, RaNode, RaSystem
from ra_tpu.core.machine import Machine, SimpleMachine
from ra_tpu.core.types import ServerConfig, ServerId


class AuxCounter(Machine):
    """Machine with aux state: counts aux evals, answers aux queries."""

    def init(self, config):
        return 0

    def apply(self, meta, command, state):
        return state + command, state + command

    def init_aux(self, name):
        return {"evals": 0}

    def handle_aux(self, raft_state, kind, msg, aux_state, internal):
        aux = dict(aux_state or {"evals": 0})
        if msg == "eval":
            aux["evals"] += 1
            return aux, []
        if msg == "get_stats":
            return aux, [], {"evals": aux["evals"],
                             "machine": internal.machine_state,
                             "commit": internal.commit_index}
        return aux, []


@pytest.fixture
def fabric():
    router = LocalRouter()
    nodes = [RaNode(f"o{i}", router=router) for i in (1, 2, 3)]
    yield router, nodes
    for n in nodes:
        n.stop()


def ids():
    return [ServerId(f"a{i+1}", f"o{i+1}") for i in range(3)]


def test_aux_command_and_eval(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("aux1", AuxCounter, sids, router=router)
    leader = None
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and leader is None:
        for s in sids:
            if ra_tpu.key_metrics(s, router=router)["state"] == "leader":
                leader = s
        time.sleep(0.01)
    ra_tpu.process_command(leader, 4, router=router)
    res = ra_tpu.aux_command(leader, "get_stats", router=router)
    assert res["machine"] == 4
    assert res["commit"] >= 2
    assert res["evals"] >= 1  # {aux, eval} fired on commit advance


def test_counters_and_overview(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("aux2", lambda: SimpleMachine(
        lambda c, s: s + c, 0), sids, router=router)
    leader = None
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and leader is None:
        for s in sids:
            if ra_tpu.key_metrics(s, router=router)["state"] == "leader":
                leader = s
        time.sleep(0.01)
    for _ in range(5):
        ra_tpu.process_command(leader, 1, router=router)
    m = ra_tpu.key_metrics(leader, router=router)
    assert m["counters"]["commands"] >= 5
    assert m["counters"]["msgs_processed"] > 5
    ov = ra_tpu.overview(router=router)
    assert set(ov["nodes"]) == {"o1", "o2", "o3"}
    assert "writes" in ov["io"]
    mo = ra_tpu.member_overview(leader, router=router)
    assert mo["raft_state"] == "leader"
    # leaderboard lock-free lookup
    node = router.nodes[leader.node]
    assert node.leaderboard_tab.lookup_leader("aux2") == leader


def test_dbg_replay(tmp_path):
    from ra_tpu.dbg import replay_log
    router = LocalRouter()
    system = RaSystem(str(tmp_path))
    node = RaNode("dbg1", router=router, log_factory=system.log_factory)
    sid = ServerId("d1", "dbg1")
    node.start_server(ServerConfig(
        server_id=sid, uid="uid_dbg", cluster_name="dbgc",
        initial_members=(sid,),
        machine=SimpleMachine(lambda c, s: s + c, 0),
        election_timeout_ms=50, tick_interval_ms=50))
    ra_tpu.trigger_election(sid, router)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if ra_tpu.key_metrics(sid, router=router)["state"] == "leader":
            break
        time.sleep(0.01)
    for v in range(1, 21):
        ra_tpu.process_command(sid, v, router=router)
    time.sleep(0.2)
    node.stop()
    system.close()
    # offline: fold the persisted log through a fresh machine
    final = replay_log(str(tmp_path), "uid_dbg",
                       SimpleMachine(lambda c, s: s + c, 0))
    assert final == 210


def test_dbg_replay_dedups_overwritten_indexes(tmp_path):
    """filter_entry_duplicate (ra_dbg_SUITE): a WAL holding both the
    original and the overwriting records for an index must replay only
    the surviving values — the offline fold sees each index once, at
    its final term."""
    from ra_tpu.core.types import Entry, UserCommand
    from ra_tpu.dbg import read_log, replay_log

    from test_durable_log import drain, mk_log, mk_system

    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    for i in range(1, 11):
        log.append(Entry(i, 1, UserCommand(i)))
    drain(log)
    # follower-path overwrite: 6..8 replaced at term 2 (truncates 9-10)
    log.write([Entry(i, 2, UserCommand(i * 100)) for i in (6, 7, 8)])
    drain(log)
    sys_.close()

    snapshot, entries = read_log(str(tmp_path), "u1")
    assert snapshot is None
    by_idx = {}
    for e in entries:
        assert e.index not in by_idx, f"duplicate index {e.index}"
        by_idx[e.index] = e
    assert {i: e.term for i, e in by_idx.items()} == {
        1: 1, 2: 1, 3: 1, 4: 1, 5: 1, 6: 2, 7: 2, 8: 2}
    final = replay_log(str(tmp_path), "u1",
                       SimpleMachine(lambda c, s: s + c, 0))
    assert final == 1 + 2 + 3 + 4 + 5 + 600 + 700 + 800
