"""Bench child-mode contract tests — the driver runs bench.py unattended
on real hardware at round end, so every measurement mode must be
exercised continuously off-hardware: a mode that crashes or prints a
malformed line would silently cost the round its benchmark evidence.

Each child runs in a subprocess exactly as the bench parent launches it
(PYTHONPATH stripped so a dead TPU tunnel's site hook cannot hang jax
init), at tiny configs sized for a loaded single-core box.
"""
import json
import os
import subprocess
import sys


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

BASE_ENV = {
    **os.environ,
    "RA_TPU_BENCH_CHILD": "1",
    "PYTHONPATH": "",
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "",
    "RA_TPU_BENCH_LANES": "64",
    "RA_TPU_BENCH_MEMBERS": "3",
    "RA_TPU_BENCH_CMDS": "8",
    "RA_TPU_BENCH_SECONDS": "0.5",
}


def run_child(extra, timeout=240):
    r = subprocess.run([sys.executable, BENCH], capture_output=True,
                       text=True, timeout=timeout,
                       env={**BASE_ENV, **extra}, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, r.stdout
    return json.loads(lines[-1])


def test_child_throughput_mode_contract():
    doc = run_child({})
    assert doc["value"] > 0
    assert doc["p50_commit_latency_ms"] > 0
    assert doc["machine"] == "counter" and doc["durable"] is False
    assert doc["latency_samples"] > 0


def test_child_durable_mode_contract():
    doc = run_child({"RA_TPU_BENCH_DURABLE": "1"})
    assert doc["value"] > 0
    assert doc["durable"] is True
    assert "sync_mode" in doc and "wal_strategy" in doc


def test_child_fifo_machine_contract():
    doc = run_child({"RA_TPU_BENCH_MACHINE": "fifo"})
    assert doc["value"] > 0
    assert doc["machine"] == "fifo"


def test_child_superstep_mode_contract():
    """The fused-dispatch throughput row (ISSUE 5): K engine rounds per
    XLA dispatch through the dispatch-ahead driver.  Exercised in CI so
    the superstep path can't silently rot while only the classic path
    is benchmarked — the contract pins the pipeline stamps (realized
    fusion factor, driver sync counts) and the single-step reference +
    speedup fields the acceptance criterion reads."""
    doc = run_child({"RA_TPU_BENCH_SUPERSTEP": "4",
                     "RA_TPU_BENCH_DISPATCH_AHEAD": "2"})
    assert doc["value"] > 0
    assert doc["superstep_k"] == 4 and doc["dispatch_ahead"] == 2
    pipe = doc["pipeline"]
    assert pipe["superstep_dispatches"] > 0
    # realized fusion: the fused phase adds K inner steps per dispatch
    assert pipe["inner_steps"] >= 4 * pipe["superstep_dispatches"]
    assert pipe["blocks_staged"] > 0
    # dispatch-ahead ran ahead: window syncs are a small fraction of
    # dispatches (the in-flight cap, not a per-dispatch block)
    assert pipe["window_syncs"] <= pipe["superstep_dispatches"] + 2
    ref = doc["single_step_ref"]
    assert ref["value"] > 0 and ref["steps"] > 0
    assert doc["speedup_vs_single_step"] > 0
    assert doc["latency_mode"] == "step_stamped"
    assert doc["p50_commit_latency_ms"] > 0


def test_child_superstep_durable_mode_contract():
    """Fused dispatches over the durable engine: confirms stay
    fsync-gated (the WAL stats ride along) and the mode completes with
    a sane latency distribution.  Autotune opt-in rides along (ISSUE
    9): knobs the loop cannot apply are FROZEN via bounds — the tail's
    knob stamps must describe the measured dispatches — and any K the
    controller picked is restaged live by the fused loop."""
    doc = run_child({"RA_TPU_BENCH_SUPERSTEP": "4",
                     "RA_TPU_BENCH_DURABLE": "1",
                     "RA_TPU_BENCH_AUTOTUNE": "1"})
    assert doc["value"] > 0
    assert doc["durable"] is True and doc["superstep_k"] == 4
    assert doc["pipeline"]["superstep_dispatches"] > 0
    assert "wal" in doc
    tun = doc["autotune"]
    assert tun["knobs"]["cmds_per_step"] == 8  # frozen to the env cmds
    assert tun["knobs"]["superstep_k"] >= 1
    # inner_steps must agree with whatever K sequence really ran (a
    # decision the loop did not apply would break this bookkeeping)
    assert doc["pipeline"]["inner_steps"] >= doc["steps"]


def test_child_multichip_mode_contract():
    """The sharded-mesh frontier sweep (ISSUE 11): per mesh shape x
    lane rung, the superstep+dispatch-ahead pipeline over sharded
    state vs the single-step reference, with the autotuner's chosen
    knobs and the engine_pipeline config stamped per row.  Exercised
    off-hardware at a tiny ladder on the 8 forced-host devices so the
    sweep cannot rot while only single-device modes are benchmarked."""
    doc = run_child({"RA_TPU_BENCH_MODE": "multichip",
                     "RA_TPU_BENCH_MESH_LANES": "64",
                     "RA_TPU_BENCH_SECONDS": "0.4",
                     "XLA_FLAGS":
                     "--xla_force_host_platform_device_count=8"},
                    timeout=420)
    assert doc["value"] > 0 and doc["n_devices"] == 8
    rows = doc["multichip"]
    assert {r["mesh"] for r in rows} == {"1x8", "2x4"}
    # the shared rung clamp (ladder_rungs): >= 16 lanes per lane-axis
    # device, so the 64-lane override clamps to 128 on the 1x8 shape
    expect_lanes = {"1x8": 128, "2x4": 64}
    for r in rows:
        assert r["value"] > 0 and r["lanes"] == expect_lanes[r["mesh"]]
        assert r["single_step_ref"]["value"] > 0
        assert r["speedup_vs_single_step"] > 0
        assert r["latency_mode"] == "step_stamped"
        assert r["p50_commit_latency_ms"] > 0
        # the cross-round attribution stamp (ISSUE 11 satellite)
        ep = r["engine_pipeline"]
        assert ep["mesh_shape"] == r["mesh"]
        assert ep["superstep_k"] >= 1 and ep["dispatch_ahead"] >= 1
        assert "donation" in ep and "wal_shard_layout" in ep
        # pipeline counters rode the sweep (fused dispatches happened)
        assert r["pipeline"]["superstep_dispatches"] > 0
        assert r["pipeline"]["mesh_shape"] == r["mesh"]
        # the autotuner drove the walk and its knobs are stamped
        assert r["autotune"]["knobs"]["superstep_k"] == \
            ep["superstep_k"]
        assert r["tune_k_rates"]
    assert doc["best_point"]["mesh"] in ("1x8", "2x4")


def test_bench_diff_compares_multichip_tails(tmp_path):
    """ISSUE 11 satellite: bench_diff pairs multichip rows per mesh
    shape x lane rung (cmds_per_s higher-is-better) alongside the
    existing keys, and the dryrun-format rows (cmds_per_s, no value)
    compare too."""
    import tools.bench_diff as bd
    old = {"value": 2e6, "multichip": [
        {"mesh": "1x8", "lanes": 1024, "value": 1.5e6,
         "p99_commit_latency_ms": 20.0},
        {"mesh": "2x4", "lanes": 1024, "cmds_per_s": 1.6e6},
        {"mesh": "2x4", "lanes": 8192, "value": 2.0e6}]}
    new = {"value": 2e6, "multichip": [
        {"mesh": "1x8", "lanes": 1024, "value": 1.6e6,
         "p99_commit_latency_ms": 90.0},
        {"mesh": "2x4", "lanes": 1024, "cmds_per_s": 0.5e6},
        {"mesh": "2x4", "lanes": 8192, "value": 2.1e6}]}
    res = bd.diff(old, new, noise_pct=10.0)
    rows = res["rows"]
    assert "multichip/1x8/lanes1024" in rows
    assert "multichip/2x4/lanes1024" in rows
    assert "multichip/2x4/lanes8192" in rows
    by = {(n, f["metric"]): f for n, fs in rows.items() for f in fs}
    # per-shape throughput regression flagged (higher-is-better)...
    assert by[("multichip/2x4/lanes1024", "value")]["regression"]
    # ...latency rise flagged, healthy rows clean
    assert by[("multichip/1x8/lanes1024",
               "p99_commit_latency_ms")]["regression"]
    assert not by[("multichip/2x4/lanes8192", "value")]["regression"]
    assert res["regressions"] >= 2
    assert bd.diff(old, old, noise_pct=10.0)["regressions"] == 0


def test_superstep_flag_sets_env():
    """`bench.py --superstep [K]` resolves to the child env contract
    ("auto" = the system-level superstep_k tunable)."""
    import bench
    env = {}
    try:
        os.environ.pop("RA_TPU_BENCH_SUPERSTEP", None)
        bench._parse_flags(["--superstep", "4"])
        env["explicit"] = os.environ.get("RA_TPU_BENCH_SUPERSTEP")
        os.environ.pop("RA_TPU_BENCH_SUPERSTEP", None)
        bench._parse_flags(["--superstep"])
        env["auto"] = os.environ.get("RA_TPU_BENCH_SUPERSTEP")
    finally:
        os.environ.pop("RA_TPU_BENCH_SUPERSTEP", None)
    assert env == {"explicit": "4", "auto": "auto"}


def test_child_frontier_mode_contract():
    doc = run_child({"RA_TPU_BENCH_MODE": "frontier",
                     "RA_TPU_BENCH_SIZES": "1,8",
                     "RA_TPU_BENCH_WINDOW": "2",
                     "RA_TPU_BENCH_SECONDS": "0.5"})
    assert doc["value"] > 0
    assert len(doc["points"]) == 2
    for p in doc["points"]:
        assert p["cmds_per_step"] in (1, 8)
        assert p["value"] > 0
        assert p["batches_measured"] > 0
    assert doc["sync_rtt_ms"] > 0
    assert doc["best_point"] in doc["points"]


def test_frontier_default_operating_point_holds_p99_bar():
    """The documented default operating point (32 cmds/step, window 4 —
    docs/BENCHMARKS.md) must be reported by the frontier sweep, meet
    the p99 bar, and sustain the north-star line scaled to the lane
    count (1M cmds/s at 10k lanes == 100 cmds/s/lane).

    Retries (p99 on a shared/sandboxed CPU box is scheduler-jitter
    bound; real hardware passes first try), and the p99 bar is the
    sweep's EFFECTIVE bar — lifted
    per point to the backend's own pipeline floor (window * solo step
    p99, measured unpipelined so a pipelining/readback regression
    cannot hide in it).  On real hardware steps are sub-ms and the
    effective bar equals the 25ms/RTT bar; on a shared CPU box it
    reflects what the backend can execute at all.  The p50 pin stays
    against the HARD bar — a systematic latency regression moves the
    median, not just the tail."""
    doc = None
    for _attempt in range(4):
        doc = run_child({"RA_TPU_BENCH_MODE": "frontier",
                         "RA_TPU_BENCH_SIZES": "8,32",
                         "RA_TPU_BENCH_WINDOW": "4",
                         "RA_TPU_BENCH_LANES": "256",
                         "RA_TPU_BENCH_SECONDS": "1.0"})
        dp = doc["default_point"]
        assert dp is not None and dp["cmds_per_step"] == 32
        if dp["meets_p99_bar"] and dp["value"] >= 100.0 * 256:
            break
    assert 0 < dp["p50_commit_latency_ms"] < doc["p99_bar_ms"], dp
    assert dp["meets_p99_bar"], (dp, doc["p99_bar_ms"])
    assert dp["value"] >= 100.0 * 256, dp
    assert doc["p99_bar_ms"] >= 25.0


def test_classic_bench_contract():
    """bench_classic.py (the ra_bench-parity run over the full node
    path, ra_bench.erl:84-129) must emit one JSON line with both phase
    rows, host metadata, and nonzero throughput at a tiny config."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_classic.py")],
        capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
             "RA_TPU_CLASSIC_SECONDS": "1.5",
             "RA_TPU_CLASSIC_DEGREE": "2",
             "RA_TPU_CLASSIC_PIPE": "50"},
        cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, r.stdout
    doc = json.loads(lines[0])
    assert doc["metric"] == "classic_node_committed_cmds_per_sec"
    assert doc["unit"] == "cmds/s"
    detail = doc["detail"]
    assert detail["errors"] == {}, detail["errors"]
    assert "cpu_count" in detail["host"]
    for phase in ("local", "tcp"):
        row = detail[phase]
        assert row["value"] > 0, (phase, row)
        assert row["durable"] is True
        assert row["p50_applied_latency_ms"] > 0
    # ISSUE 6 satellite: the local phase stamps the leader system's
    # Observatory snapshot (WAL fsync p50/p99 + queue depth)
    wal = detail["local"]["observatory"]["system"]["counters"]["wal"]
    assert "fsync_p50_ms" in wal and "queue_depth" in wal
    # ISSUE 7 satellite: the tcp phase's client-side Observatory
    # carries the reliable-RPC counters (RPC_FIELDS reach the
    # snapshot/exposition like the WAL stats do)
    rpc = detail["tcp"]["observatory"]["rpc"]
    assert "rpc_calls" in rpc and "rpc_dedup_hits" in rpc
    # ISSUE 13 satellites: the host envelope carries the fd cap next
    # to cpu_count (cross-host drift attribution) and both phases
    # stamp the CLASSIC_FIELDS batching-health shape — AER batches
    # actually multi-entry, and the local (shared-WAL) phase shows the
    # group-commit fan-in factor
    assert detail["host"]["rlimit_nofile"] > 0
    from ra_tpu.metrics import CLASSIC_FIELDS
    for phase in ("local", "tcp"):
        cb = detail[phase]["classic_batch"]
        assert cb["aer_batches_sent"] > 0, (phase, cb)
        assert cb["aer_batch_entries"] > cb["aer_batches_sent"], \
            (phase, cb)  # batching really happened (entries/batch > 1)
    local_cb = detail["local"]["classic_batch"]
    assert set(CLASSIC_FIELDS) <= set(local_cb)
    assert local_cb["records_per_fsync"] != 0
    # ...and the classic stats ride the local Observatory snapshot
    assert detail["local"]["observatory"]["classic"][
        "aer_batches_sent"] > 0
    # ISSUE 16: the classic tail stamps the device keys as ZEROS — the
    # classic plane is host-only; a nonzero compile count here means
    # jit dispatch leaked into the classic path
    assert doc["n_compiles"] == 0 and doc["n_recompiles"] == 0
    assert doc["transfer_bytes"] == 0


def test_bench_diff_compares_classic_captures(tmp_path):
    """ISSUE 13 satellite: bench_diff pairs classic captures per phase
    (classic/local + classic/tcp): throughput drops (higher-better,
    the classic_node_committed_cmds_per_sec sub-values) and
    p99_applied_latency_ms rises (lower-better) are flagged; the r05
    on-disk capture shape itself produces the rows."""
    import tools.bench_diff as bd
    r05 = bd._load(os.path.join(REPO, "BENCH_CLASSIC_r05.json"))
    rows = bd.extract_rows(r05)
    assert "classic/local" in rows and "classic/tcp" in rows
    new = {"metric": "classic_node_committed_cmds_per_sec",
           "value": 1000.0,
           "detail": {
               "local": {"value": 8000.0,
                         "p99_applied_latency_ms": 500.0},
               "tcp": {"value": 1000.0,
                       "p99_applied_latency_ms": 1100.0}}}
    res = bd.diff(r05, new, noise_pct=10.0)
    by = {(n, f["metric"]): f for n, fs in res["rows"].items()
          for f in fs}
    # local throughput halved + latency doubled: both flagged
    assert by[("classic/local", "value")]["regression"]
    assert by[("classic/local",
               "p99_applied_latency_ms")]["regression"]
    # tcp p99 improved: clean
    assert not by[("classic/tcp",
                   "p99_applied_latency_ms")]["regression"]
    assert res["regressions"] >= 3  # local value+p99, tcp value
    # self-compare is clean
    assert bd.diff(r05, r05, noise_pct=10.0)["regressions"] == 0


def test_bench_tail_carries_observatory_snapshot():
    """ISSUE 6 satellite: the throughput tail stamps the final
    Observatory snapshot — telemetry summary, sampler health, and the
    per-shard WAL fsync p50/p99 + queue depths — so cross-round
    comparisons stop hand-collecting fields."""
    doc = run_child({"RA_TPU_BENCH_DURABLE": "1",
                     "RA_TPU_BENCH_WAL_SHARDS": "2"})
    eng = doc["observatory"]["engine"]
    tel = eng["telemetry"]
    assert tel["steps"] > 0
    assert tel["committed_total"] > 0
    assert tel["stall_threshold"] > 0
    assert eng["sampler"]["samples_harvested"] >= 1
    assert eng["sampler"]["samples_started"] >= 1
    shards = eng["wal"]["shards"]
    assert len(shards) == 2
    for sh in shards:
        assert "fsync_p50_ms" in sh and "fsync_p99_ms" in sh
        assert "queue_depth" in sh and "jobs_pending" in sh
    # pipeline counters ride in the snapshot too (the SLO-autotuner
    # substrate: rate fields next to the knobs that move them)
    assert eng["pipeline"]["dispatches"] > 0


def test_bench_tail_carries_slo_and_phase_attribution():
    """ISSUE 9: the durable tail stamps the SLO verdicts (evaluated
    over the run's own ring windows) and the phase attribution rides
    the Observatory snapshot — budget decomposition + objective health
    land in the same artifact the rounds compare."""
    doc = run_child({"RA_TPU_BENCH_DURABLE": "1",
                     "RA_TPU_BENCH_WAL_SHARDS": "2",
                     "RA_TPU_BENCH_SECONDS": "1.0"})
    objs = doc["slo"]["objectives"]
    for name in ("commit_p99_ms", "fsync_p99_ms", "cmds_per_s"):
        assert name in objs
        assert objs[name]["verdict"] in ("ok", "breach", "alert",
                                         "no_data")
        assert "burn_fast" in objs[name]
    # the run produced real windows and real verdicts (a 1s durable
    # run commits plenty; commit_e2e always samples on this path)
    assert doc["slo"]["windows"] >= 2
    assert objs["commit_p99_ms"]["value"] is not None
    ph = doc["observatory"]["engine"]["phases"]
    for p in ("queue_wait", "wal_encode", "fsync_wait",
              "confirm_publish", "commit_e2e"):
        assert ph[p]["count"] > 0, p
    assert ph["dropped"] == 0
    # the tunable knobs are stamped next to the rates they move (RA07)
    pipe = doc["observatory"]["engine"]["pipeline"]
    assert pipe["cmds_per_step"] == 8
    assert pipe["wal_max_batch_interval_ms"] >= 0.0


def test_bench_diff_smoke_flags_regressions(tmp_path):
    """tools/bench_diff.py consumes the live tail format (pinned here
    so the format cannot drift out from under it): same-doc compare is
    clean/exit 0; a degraded doc flags value + p99 regressions and
    exits 1."""
    doc = run_child({})
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(doc))
    b.write_text(json.dumps(doc))
    diff_tool = os.path.join(REPO, "tools", "bench_diff.py")
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b),
                        "--json"], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    res = json.loads(r.stdout)
    assert res["rows_compared"] == 1 and res["regressions"] == 0
    worse = dict(doc)
    worse["value"] = doc["value"] * 0.5
    worse["p99_commit_latency_ms"] = \
        doc["p99_commit_latency_ms"] * 3 + 10
    b.write_text(json.dumps(worse))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout
    assert r.stdout.count("REGRESSION") == 2, r.stdout
    # history capture records (BENCH_r*.json wrappers) unwrap too
    wrapped = tmp_path / "hist.json"
    wrapped.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "", "parsed": doc}))
    r = subprocess.run([sys.executable, diff_tool, str(wrapped),
                        str(a)], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_telemetry_opt_out():
    """RA_TPU_BENCH_TELEMETRY=0 runs the legacy tail (no sampler, no
    observatory key) — the A side of the overhead comparison."""
    doc = run_child({"RA_TPU_BENCH_TELEMETRY": "0"})
    assert doc["value"] > 0
    assert "observatory" not in doc


def test_child_wire_mode_contract(tmp_path):
    """ISSUE 12: the ``bench.py --wire`` child prints one JSON tail
    carrying the wire frontier keys (format pinned — bench_diff and
    the round captures parse this shape).  Tiny CPU-scaled config."""
    doc = run_child({
        "RA_TPU_BENCH_MODE": "wire",
        "RA_TPU_BENCH_WIRE_CONNS": "512",
        "RA_TPU_BENCH_WIRE_LANES": "64",
        "RA_TPU_BENCH_WIRE_WAVES": "4",
        "RA_TPU_BENCH_WIRE_DURABLE": "0",
    })
    assert doc["value"] > 0
    assert doc["wire_cmds_per_s"] == doc["value"]
    assert 0 <= doc["wire_shed_rate"] <= 1
    assert "wire_reconnect_recovery_s" in doc
    assert doc["conns"] == 512 and doc["metric"] == \
        "wire_committed_cmds_per_sec"
    assert doc["storm_requeued"] > 0       # the storm actually ran
    assert "host" in doc


def test_wire_flag_sets_env():
    """--wire routes the parent into the wire-mode child (the flag
    twin of --multichip)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location("bench_flags", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    old = os.environ.pop("RA_TPU_BENCH_MODE", None)
    try:
        mod._parse_flags(["--wire"])
        assert os.environ["RA_TPU_BENCH_MODE"] == "wire"
    finally:
        if old is None:
            os.environ.pop("RA_TPU_BENCH_MODE", None)
        else:
            os.environ["RA_TPU_BENCH_MODE"] = old


def test_bench_diff_compares_wire_keys(tmp_path):
    """ISSUE 12 satellite: when both tails carry the wire keys,
    bench_diff flags throughput drops, shed-rate rises AND reconnect-
    recovery regressions (0 is a healthy baseline for both; a -1
    recovery sentinel = no storm ran, skipped)."""
    diff_tool = os.path.join(REPO, "tools", "bench_diff.py")
    base = {"value": 90_000.0, "wire_cmds_per_s": 90_000.0,
            "wire_shed_rate": 0.0, "wire_reconnect_recovery_s": 0.1}
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(base))
    worse = {"value": 40_000.0, "wire_cmds_per_s": 40_000.0,
             "wire_shed_rate": 0.3, "wire_reconnect_recovery_s": 3.0}
    b.write_text(json.dumps(worse))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout
    # value + wire_cmds_per_s + shed rate + recovery
    assert r.stdout.count("REGRESSION") == 4, r.stdout
    b.write_text(json.dumps(base))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_tail_stamps_device_keys():
    """ISSUE 16: the throughput tail stamps the device-plane keys
    (format pinned — bench_diff compares them), and the real bench
    dispatch path itself runs recompile-free: warm-up compiles are
    counted, steady state adds none."""
    doc = run_child({})
    for k in ("n_compiles", "n_recompiles", "compile_time_s",
              "transfer_bytes", "transfer_bytes_per_cmd",
              "peak_live_bytes"):
        assert k in doc, k
    assert doc["n_compiles"] > 0          # warm-up compiles counted
    assert doc["n_recompiles"] == 0       # the zero-retrace pin, live
    assert doc["transfer_bytes"] > 0
    assert doc["transfer_bytes_per_cmd"] > 0
    assert doc["peak_live_bytes"] > 0     # watermarks rode the harvest


def test_bench_parent_promotes_device_keys():
    """The parent headline line carries the measuring CHILD's device
    stamp (counters are per-process; the parent never dispatches), so
    bench_diff can compare headline rows across rounds."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    row = {"value": 1.0, "n_compiles": 3, "n_recompiles": 0,
           "transfer_bytes": 10, "unrelated": 7}
    out = bench._promote_device_keys(row)
    assert out == {"n_compiles": 3, "n_recompiles": 0,
                   "transfer_bytes": 10}


def test_bench_diff_compares_device_keys(tmp_path):
    """ISSUE 16 satellite: n_compiles/n_recompiles compare ABSOLUTELY
    (any growth flags — a one-per-round retrace hides inside a 10%
    noise bar), the cost keys lower-is-better with 0 a healthy
    baseline (classic tails stamp zeros)."""
    diff_tool = os.path.join(REPO, "tools", "bench_diff.py")
    base = {"value": 1000.0, "n_compiles": 6, "n_recompiles": 0,
            "compile_time_s": 1.5, "transfer_bytes_per_cmd": 84.0,
            "peak_live_bytes": 50_000}
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(base))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    # +1 compile is only ~17% of 6 but must flag regardless of bar;
    # a recompile appearing from 0 must flag too
    worse = {"value": 1000.0, "n_compiles": 7, "n_recompiles": 1,
             "compile_time_s": 3.0, "transfer_bytes_per_cmd": 120.0,
             "peak_live_bytes": 50_000}
    b.write_text(json.dumps(worse))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b),
                        "--noise-pct", "25"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout
    # n_compiles + n_recompiles (absolute) + compile_time_s +
    # transfer_bytes_per_cmd (both past the 25% bar); peak unchanged
    assert r.stdout.count("REGRESSION") == 4, r.stdout
    # improvements are never regressions: dropping compiles is clean
    b.write_text(json.dumps(dict(base, n_compiles=3)))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_diff_compares_ingress_keys(tmp_path):
    """ISSUE 10 satellite: when both tails carry the ingress keys,
    bench_diff flags throughput drops (higher-is-better) and shed-rate
    rises — including a shed rate APPEARING from a healthy 0, which the
    latency-style o>0 guard would have skipped; tails without the keys
    keep comparing exactly as before."""
    diff_tool = os.path.join(REPO, "tools", "bench_diff.py")
    base = {"value": 1000.0, "ingress_cmds_per_s": 400_000.0,
            "ingress_shed_rate": 0.0}
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(base))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b),
                        "--json"], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    res = json.loads(r.stdout)
    metrics = [f["metric"] for f in res["rows"]["headline"]]
    assert "ingress_cmds_per_s" in metrics
    assert "ingress_shed_rate" in metrics
    worse = {"value": 1000.0, "ingress_cmds_per_s": 300_000.0,
             "ingress_shed_rate": 0.25}
    b.write_text(json.dumps(worse))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout
    assert r.stdout.count("REGRESSION") == 2, r.stdout
    # a tail without the ingress keys is compared on what it has
    b.write_text(json.dumps({"value": 1000.0}))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_bench_diff_compares_read_keys(tmp_path):
    """ISSUE 20 satellite: when both tails carry the read-frontier keys
    (the `bench.py --reads` capture format, pinned here), bench_diff
    flags read-throughput drops, read_p99 rises, shed-rate rises AND
    stale refusals appearing from a healthy 0; the -1 "no reads ran"
    latency sentinel is skipped; tails without the keys keep comparing
    exactly as before."""
    diff_tool = os.path.join(REPO, "tools", "bench_diff.py")
    base = {"value": 25_000.0, "read_cmds_per_s": 25_000.0,
            "read_p99_ms": 4.0, "read_shed_rate": 0.0,
            "read_stale_refused": 0.0}
    a = tmp_path / "old.json"
    b = tmp_path / "new.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(base))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b),
                        "--json"], capture_output=True, text=True,
                       timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    res = json.loads(r.stdout)
    metrics = [f["metric"] for f in res["rows"]["headline"]]
    assert "read_cmds_per_s" in metrics
    assert "read_p99_ms" in metrics
    assert "read_shed_rate" in metrics
    assert "read_stale_refused" in metrics
    worse = {"value": 25_000.0, "read_cmds_per_s": 15_000.0,
             "read_p99_ms": 9.0, "read_shed_rate": 0.3,
             "read_stale_refused": 12.0}
    b.write_text(json.dumps(worse))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1, r.stdout
    assert r.stdout.count("REGRESSION") == 4, r.stdout
    # a write-only tail (read_p99_ms -1 sentinel, no read keys) still
    # compares on what it has
    b.write_text(json.dumps({"value": 25_000.0, "read_p99_ms": -1.0}))
    r = subprocess.run([sys.executable, diff_tool, str(a), str(b)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
