"""Engine-path consistent queries (linearizable reads) — VERDICT r03
item 3.  Matches the heartbeat-quorum machinery of
/root/reference/src/ra_server.erl:3032-3190: a read is served only after
a majority of voters confirm the leader's query token (leadership
certified after registration) and the leader has applied its commit
index as of registration.
"""
import numpy as np
import pytest

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import CounterMachine

N, P, K = 8, 5, 4


def mk(**kw):
    kw.setdefault("ring_capacity", 128)
    kw.setdefault("max_step_cmds", K)
    kw.setdefault("write_delay", 1)
    return LockstepEngine(CounterMachine(), N, P, **kw)


def write(eng, cmds=K, steps=1):
    n_new = np.full((N,), cmds, np.int32)
    pay = np.ones((N, K, 1), np.int32)
    for _ in range(steps):
        eng.step(n_new, pay)


def settle(eng, steps=4):
    zero_n = np.zeros((N,), np.int32)
    zero_p = np.zeros((N, K, 1), np.int32)
    for _ in range(steps):
        eng.step(zero_n, zero_p)


def test_consistent_read_sees_completed_writes():
    eng = mk()
    write(eng, steps=3)
    settle(eng)
    lane = np.arange(N)
    st = eng.state
    counts = np.asarray(st.mac)[lane, np.asarray(st.leader_slot)]
    got = eng.consistent_read(list(range(N)))
    assert (np.asarray(got) >= counts).all()


def test_consistent_read_monotone_across_writes():
    """Read-your-writes: after each completed write batch, a consistent
    read must reflect at least everything read before plus the batch."""
    eng = mk()
    prev = np.zeros((N,), np.int32)
    for _ in range(5):
        write(eng)
        settle(eng)
        got = np.asarray(eng.consistent_read(list(range(N))))
        assert (got >= prev + K).all(), (got, prev)
        prev = got


def test_consistent_read_blocks_without_majority():
    """A leader cut off from its majority must NOT serve reads — the
    exact stale-read scenario consistent_query exists to prevent."""
    eng = mk()
    write(eng, steps=2)
    settle(eng)
    leader0 = int(np.asarray(eng.state.leader_slot)[0])
    others = [s for s in range(P) if s != leader0]
    for s in others[:P - 2]:  # leave leader + 1: a 2/5 minority
        eng.fail_member(0, s)
    for s in others[P - 2:]:
        eng.fail_member(0, s)
    with pytest.raises(TimeoutError):
        eng.consistent_read([0], timeout_steps=12)


def test_consistent_read_across_election():
    """A read issued after an election must wait for the new leader to
    certify leadership (fresh heartbeat quorum) and commit its noop,
    then reflect every write completed before the election."""
    eng = mk()
    write(eng, steps=3)
    settle(eng)
    lane = np.arange(N)
    st = eng.state
    counts = np.asarray(st.mac)[lane, np.asarray(st.leader_slot)]
    # kill every lane's leader, elect replacements
    leads = np.asarray(st.leader_slot)
    for i in range(N):
        eng.fail_member(i, int(leads[i]))
    eng.trigger_election(list(range(N)))
    got = np.asarray(eng.consistent_read(list(range(N))))
    assert (got >= counts).all(), (got, counts)
    # and the read is served by the NEW leaders
    assert (np.asarray(eng.state.leader_slot) != leads).any()


def test_query_tokens_monotone():
    eng = mk()
    write(eng)
    settle(eng)
    eng.consistent_read([0, 1])
    q1 = np.asarray(eng.state.query_index).copy()
    eng.consistent_read([0])
    q2 = np.asarray(eng.state.query_index)
    assert q2[0] == q1[0] + 1
    assert q2[1] == q1[1]


def test_consistent_read_durable_mode(tmp_path):
    """Reads on the durable path wait for fsync-gated applies."""
    from ra_tpu.engine import open_engine
    eng = open_engine(CounterMachine(), str(tmp_path), N, P, sync_mode=0,
                      ring_capacity=128, max_step_cmds=K)
    write(eng, steps=3)
    got = np.asarray(eng.consistent_read(list(range(N)),
                                         timeout_steps=512))
    assert (got >= 0).all()
    lane = np.arange(N)
    st = eng.state
    com = np.asarray(st.commit)[lane, np.asarray(st.leader_slot)]
    assert (com <= eng._dur.confirm_upto).all()
    eng.close()
