"""Full-node durability: clusters over RaSystem-backed logs survive node
restart (the ra_2_SUITE restart/recovery lifecycles)."""
import time


import ra_tpu
from ra_tpu import LocalRouter, RaNode, RaSystem
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import ServerConfig, ServerId


def counter():
    return SimpleMachine(lambda c, s: s + c, 0)


def mk_cfg(sid, sids, uid=None):
    return ServerConfig(server_id=sid, uid=uid or f"uid_{sid.name}",
                        cluster_name="dur", initial_members=tuple(sids),
                        machine=counter(), election_timeout_ms=80,
                        tick_interval_ms=100)


def await_leader(router, sids, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for sid in sids:
            node = router.nodes.get(sid.node)
            shell = node.shells.get(sid.name) if node else None
            if shell and shell.server.raft_state.value == "leader":
                return sid
        time.sleep(0.01)
    raise TimeoutError("no leader")


def test_cluster_survives_full_node_restart(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"d{i}", f"dn{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)
    for v in range(1, 51):
        ra_tpu.process_command(leader, v, router=router)
    res = ra_tpu.consistent_query(leader, lambda s: s, router=router)
    assert res.reply == 1275
    # hard-stop everything
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()

    # restart: fresh systems/nodes over the same data dirs and uids
    router2 = LocalRouter()
    systems2 = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes2 = {s.node: RaNode(s.node, router=router2,
                             log_factory=systems2[s.node].log_factory)
              for s in sids}
    for sid in sids:
        nodes2[sid.node].start_server(mk_cfg(sid, sids))
    leader2 = await_leader(router2, sids)
    # recovered state: all previous commands replayed
    res = ra_tpu.consistent_query(leader2, lambda s: s, router=router2)
    assert res.reply == 1275
    # and the cluster still makes progress
    res = ra_tpu.process_command(leader2, 25, router=router2)
    assert res.reply == 1300
    for n in nodes2.values():
        n.stop()
    for s in systems2.values():
        s.close()


def test_single_member_restart_preserves_term_and_vote(tmp_path):
    router = LocalRouter()
    sids = [ServerId(f"e{i}", f"en{i}") for i in (1, 2, 3)]
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(mk_cfg(sid, sids))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 5, router=router)
    follower = next(s for s in sids if s != leader)
    fnode = nodes[follower.node]
    term_before = fnode.shells[follower.name].server.current_term
    time.sleep(0.3)  # let a tick persist last_applied (lazy, like dets)
    fnode.kill_server(follower.name)
    # recreate over the same dir/uid
    fnode.start_server(mk_cfg(follower, sids))
    srv = fnode.shells[follower.name].server
    assert srv.current_term >= term_before
    assert srv.last_applied >= 1  # recovered apply progress
    # it rejoins replication
    ra_tpu.process_command(leader, 7, router=router)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        st = ra_tpu.local_query(follower, lambda s: s, router=router)
        if st.reply == 12:
            break
        time.sleep(0.02)
    assert st.reply == 12
    for n in nodes.values():
        n.stop()
    for s in systems.values():
        s.close()


def test_restart_does_not_reissue_side_effects(tmp_path):
    """restarted_server_does_not_reissue_side_effects (ra_2_SUITE):
    machine effects for entries at or below the persisted apply
    watermark are suppressed during recovery replay — a subscriber must
    not see duplicate notifications after a restart."""
    from ra_tpu.core.machine import Machine
    from ra_tpu.core.types import SendMsg

    class Notifier(Machine):
        def __init__(self, sink):
            self.sink = sink

        def init(self, config):
            return 0

        def apply(self, meta, command, state):
            new = state + command
            return new, new, [SendMsg(self.sink, ("applied", command))]

    router = LocalRouter()
    sid = ServerId("fx1", "fxn1")
    system = RaSystem(str(tmp_path))
    node = RaNode(sid.node, router=router, log_factory=system.log_factory)
    seen: list = []
    node.start_server(ServerConfig(
        server_id=sid, uid="uid_fx", cluster_name="fx",
        initial_members=(sid,), machine=Notifier(seen.append),
        election_timeout_ms=80, tick_interval_ms=50))
    ra_tpu.trigger_election(sid, router)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(seen) < 3:
        try:
            for v in (1, 2, 3)[len(seen):]:
                ra_tpu.process_command(sid, v, router=router)
        except Exception:
            time.sleep(0.05)
    assert [m for m in seen] == [("applied", 1), ("applied", 2),
                                 ("applied", 3)]
    # let a tick persist the apply watermark (lazy last_applied)
    time.sleep(0.3)
    node.stop()
    system.close()

    seen2: list = []
    system2 = RaSystem(str(tmp_path))
    node2 = RaNode(sid.node, router=LocalRouter(),
                   log_factory=system2.log_factory)
    node2.start_server(ServerConfig(
        server_id=sid, uid="uid_fx", cluster_name="fx",
        initial_members=(sid,), machine=Notifier(seen2.append),
        election_timeout_ms=80, tick_interval_ms=50))
    sh = node2.shells[sid.name]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and sh.server.machine_state != 6:
        time.sleep(0.05)
    assert sh.server.machine_state == 6
    assert seen2 == [], seen2   # recovery replay suppressed every effect
    node2.stop()
    system2.close()


def test_config_modification_at_restart(tmp_path):
    """config_modification_at_restart (ra_2_SUITE): restarting a member
    over its durable log with modified tunables (election timeout, tick)
    honors the new values while preserving the recovered state."""
    router = LocalRouter()
    sid = ServerId("cm1", "cmn1")
    system = RaSystem(str(tmp_path))
    node = RaNode(sid.node, router=router, log_factory=system.log_factory)
    node.start_server(ServerConfig(
        server_id=sid, uid="uid_cm", cluster_name="cm",
        initial_members=(sid,), machine=counter(),
        election_timeout_ms=80, tick_interval_ms=50))
    ra_tpu.trigger_election(sid, router)
    deadline = time.monotonic() + 10
    ok = False
    while time.monotonic() < deadline and not ok:
        try:
            ok = ra_tpu.process_command(sid, 5, router=router).reply == 5
        except Exception:
            time.sleep(0.05)
    assert ok
    node.stop()
    system.close()

    system2 = RaSystem(str(tmp_path))
    node2 = RaNode(sid.node, router=LocalRouter(),
                   log_factory=system2.log_factory)
    node2.start_server(ServerConfig(
        server_id=sid, uid="uid_cm", cluster_name="cm",
        initial_members=(sid,), machine=counter(),
        election_timeout_ms=555, tick_interval_ms=200))
    sh = node2.shells[sid.name]
    assert sh.server.cfg.election_timeout_ms == 555
    assert sh.server.cfg.tick_interval_ms == 200
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and sh.server.machine_state != 5:
        time.sleep(0.05)
    assert sh.server.machine_state == 5    # durable state preserved
    node2.stop()
    system2.close()


def test_cohosted_follower_restart_resumes_replication(tmp_path):
    """ISSUE 13 (found by the verify probe): co-hosted members share a
    node, so a kill broadcasts DownEvent and the leader marks the peer
    DISCONNECTED — but a RESTART had no up edge, so a restarted
    follower whose log was behind the tail wedged forever: it cannot
    win pre-votes (shorter log) and the leader skips DISCONNECTED
    peers.  start_server now broadcasts the UpEvent twin and the
    leader resumes catch-up immediately."""
    router = LocalRouter()
    system = RaSystem(str(tmp_path))
    node = RaNode("ch", router=router, log_factory=system.log_factory)
    sids = [ServerId(f"ch{i}", "ch") for i in (1, 2, 3)]

    def cfg(sid):
        return ServerConfig(server_id=sid, uid=f"uid_{sid.name}",
                            cluster_name="cohosted",
                            initial_members=tuple(sids),
                            machine=counter(),
                            election_timeout_ms=120,
                            tick_interval_ms=50)

    try:
        for sid in sids:
            node.start_server(cfg(sid))
        ra_tpu.trigger_election(sids[0], router)
        leader = await_leader(router, sids)
        for v in range(1, 11):
            ra_tpu.process_command(leader, v, router=router)
        follower = next(s for s in sids if s != leader)
        node.kill_server(follower.name)
        # the log moves PAST the killed member: on restart it is
        # behind the tail, so only leader-driven catch-up can save it
        r = ra_tpu.process_command(leader, 100, router=router)
        final = r.reply
        node.start_server(cfg(follower))
        deadline = time.monotonic() + 10
        got = None
        while time.monotonic() < deadline:
            got = ra_tpu.local_query(follower, lambda s: s,
                                     router=router).reply
            if got == final:
                break
            time.sleep(0.02)
        assert got == final, (got, final)
    finally:
        node.stop()
        system.close()
