"""Node runtime + public API integration tests — the single-node and
multi-node lifecycles of the reference's ra_SUITE/ra_2_SUITE/
coordination_SUITE, with RaNodes standing in for Erlang VMs (real timers,
real event loops, in-process router)."""
import time

import pytest

import ra_tpu
from ra_tpu.core.types import ServerId
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.node import LocalRouter, RaNode


@pytest.fixture
def fabric():
    router = LocalRouter()
    nodes = [RaNode(f"n{i}", router=router) for i in (1, 2, 3)]
    yield router, nodes
    for n in nodes:
        n.stop()


def counter_factory():
    return SimpleMachine(lambda cmd, st: st + cmd, 0)


def ids(n=3):
    return [ServerId(f"m{i+1}", f"n{i+1}") for i in range(n)]


from nemesis import await_leader  # noqa: E402  (shared helper)


def test_start_cluster_and_commands(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t1", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    for v in (1, 2, 3):
        res = ra_tpu.process_command(leader, v, router=router)
    assert res.reply == 6
    assert res.leader == leader


def test_redirect_from_follower(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t2", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    follower = next(s for s in sids if s != leader)
    res = ra_tpu.process_command(follower, 10, router=router)
    assert res.reply == 10


def test_queries(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t3", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 5, router=router)
    res = ra_tpu.consistent_query(leader, lambda st: st * 2, router=router)
    assert res.reply == 10
    res = ra_tpu.leader_query(sids[0], lambda st: st, router=router)
    assert res.reply == 5
    # local query on a follower may lag but must answer
    follower = next(s for s in sids if s != leader)
    res = ra_tpu.local_query(follower, lambda st: st, router=router)
    assert res.reply in (0, 5)


def test_leader_failover(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("t4", counter_factory, sids, router=router,
                         election_timeout_ms=80)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 1, router=router)
    # kill the leader's node process
    router.nodes[leader.node].kill_server(leader.name)
    rest = [s for s in sids if s != leader]
    new_leader = await_leader(router, rest, timeout=10.0)
    assert new_leader != leader
    res = ra_tpu.process_command(new_leader, 2, router=router)
    assert res.reply == 3


def test_pipeline_command_notifications(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t5", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    got = []
    for i in range(40):
        ra_tpu.pipeline_command(leader, 1, correlation=i,
                                notify_to=got.extend, router=router)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(got) < 40:
        time.sleep(0.01)
    assert len(got) == 40
    assert sorted(c for c, _ in got) == list(range(40))


def test_membership_add_remove(fabric):
    router, nodes = fabric
    sids = ids(2)  # start with 2 of the 3 nodes
    ra_tpu.start_cluster("t6", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 7, router=router)
    # start member 3 and join it
    new = ServerId("m3", "n3")
    ra_tpu.start_server("t6", counter_factory, new, sids + [new],
                        router=router)
    res = ra_tpu.add_member(leader, new, router=router)
    assert not isinstance(res, ra_tpu.core.types.ErrorResult), res
    assert set(ra_tpu.members(leader, router=router)) == set(sids + [new])
    # the new member catches up
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        st = ra_tpu.local_query(new, lambda s: s, router=router)
        if st.reply == 7:
            break
        time.sleep(0.02)
    assert st.reply == 7
    # remove it again
    res = ra_tpu.remove_member(leader, new, router=router)
    assert set(ra_tpu.members(leader, router=router)) == set(sids)


def test_transfer_leadership_api(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t7", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    target = next(s for s in sids if s != leader)
    ra_tpu.transfer_leadership(leader, target, router=router)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        m = ra_tpu.key_metrics(target, router=router)
        if m["state"] == "leader":
            break
        time.sleep(0.02)
    assert m["state"] == "leader"


def test_key_metrics(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t8", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 1, router=router)
    m = ra_tpu.key_metrics(leader, router=router)
    assert m["state"] == "leader"
    assert m["commit_index"] >= 2  # noop + command
    assert m["last_applied"] == m["commit_index"]


def test_restart_server_recovers_state(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("t9", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 42, router=router)
    follower = next(s for s in sids if s != leader)
    node = router.nodes[follower.node]
    # memory log does not survive restart; this exercises re-join + catch-up
    node.restart_server(follower.name)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        st = ra_tpu.local_query(follower, lambda s: s, router=router)
        if st.reply == 42:
            break
        time.sleep(0.02)
    assert st.reply == 42


def test_delete_cluster(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t11", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    res = ra_tpu.delete_cluster(leader, router=router)
    assert res.reply == "ok"
    # every member eventually tears down
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        alive = [s for s in sids
                 if router.nodes[s.node].shells.get(s.name) is not None]
        if not alive:
            break
        time.sleep(0.02)
    assert not alive


def test_partition_and_heal(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("t10", counter_factory, sids, router=router,
                         election_timeout_ms=80)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 1, router=router)
    # partition the leader away from both followers
    others = [s for s in sids if s != leader]
    for o in others:
        router.block(leader.node, o.node)
    new_leader = await_leader(router, others, timeout=10.0)
    res = ra_tpu.process_command(new_leader, 2, router=router)
    assert res.reply == 3
    router.heal()
    # old leader rejoins and converges
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        st = ra_tpu.local_query(leader, lambda s: s, router=router)
        if st.reply == 3:
            break
        time.sleep(0.02)
    assert st.reply == 3
    assert ra_tpu.key_metrics(leader, router=router)["state"] == "follower"


def test_stuck_snapshot_send_retries_after_timeout(fabric):
    """A lost install_snapshot result must not wedge the peer in
    SENDING_SNAPSHOT forever: the leader's tick resets stale transfers
    (the snapshot_sender DOWN recovery, ra_server.erl handle_down)."""
    import time as _t

    from ra_tpu.core.server import RaServer
    from ra_tpu.core.types import PeerStatus

    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("snapstuck", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    lnode = router.nodes[leader.node]
    srv = lnode.shells[leader.name].server
    victim = [s for s in sids if s != leader][0]
    peer = srv.cluster[victim]
    # wedge the peer as if a snapshot send's ack was lost long ago
    peer.status = PeerStatus.SENDING_SNAPSHOT
    peer.snapshot_started = _t.monotonic() - RaServer.SNAPSHOT_SEND_TIMEOUT_S - 1
    before = ra_tpu.process_command(leader, 4, router=router)
    deadline = _t.monotonic() + 10
    while _t.monotonic() < deadline:
        if peer.status != PeerStatus.SENDING_SNAPSHOT:
            break
        _t.sleep(0.05)
    assert peer.status != PeerStatus.SENDING_SNAPSHOT
    # and the previously wedged member converges again
    vshell = router.nodes[victim.node].shells[victim.name]
    deadline = _t.monotonic() + 10
    while _t.monotonic() < deadline:
        if vshell.server.machine_state == before.reply:
            break
        _t.sleep(0.05)
    assert vshell.server.machine_state == before.reply


def test_aux_monitor_down_routes_to_handle_aux(fabric):
    """ra_monitors component multiplexing: an aux-component monitor's
    DOWN goes to handle_aux, not the machine command path."""
    from ra_tpu.core.machine import Machine
    from ra_tpu.core.types import Monitor

    downs = []

    class AuxMon(Machine):
        def init(self, config):
            return 0

        def apply(self, meta, command, state):
            if command == "watch":
                return state, "ok", [Monitor("process", "extproc",
                                             component="aux")]
            return state + command, state + command

        def handle_aux(self, raft_state, kind, msg, aux, internal):
            if isinstance(msg, tuple) and msg and msg[0] == "down":
                downs.append(msg)
            return aux, []

    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("auxmon", AuxMon, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, "watch", router=router)
    lnode = router.nodes[leader.node]
    import time as _t
    deadline = _t.monotonic() + 5
    while _t.monotonic() < deadline:
        if "extproc" in lnode.shells[leader.name].aux_monitors:
            break
        _t.sleep(0.02)
    lnode.process_down("extproc", "killed")
    deadline = _t.monotonic() + 5
    while _t.monotonic() < deadline and not downs:
        _t.sleep(0.02)
    assert downs and downs[0] == ("down", "extproc", "killed"), downs


def test_ping(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("png", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    assert ra_tpu.ping(leader, router=router) == ("pong", "leader")
    follower = next(s for s in sids if s != leader)
    assert ra_tpu.ping(follower, router=router)[0] == "pong"
    with pytest.raises(RuntimeError):
        ra_tpu.ping(ServerId("ghost", sids[0].node), router=router)


def test_start_cluster_majority_formation(fabric):
    """ra.erl:397-409 formation semantics: the cluster forms when more
    than half the members start (stragglers retried later); when it
    cannot form, the members that DID start are force-deleted."""
    router, nodes = fabric
    # 2 of 3 nodes exist: majority forms, the missing member reported
    sids = ids() [:2] + [ServerId("mX", "no_such_node")]
    from ra_tpu.machines import machine_spec
    started = ra_tpu.start_cluster("tmaj", machine_spec("counter"), sids,
                                   router=router)
    assert started == sids[:2]
    leader = await_leader(router, started)
    assert ra_tpu.process_command(leader, 4, router=router).reply == 4
    # 1 of 3: cluster_not_formed, and the one started member is deleted
    sids2 = [ServerId("q1", "n1"), ServerId("q2", "ghost2"),
             ServerId("q3", "ghost3")]
    with pytest.raises(RuntimeError, match="cluster_not_formed"):
        ra_tpu.start_cluster("tfail", machine_spec("counter"), sids2,
                             router=router)
    assert nodes[0].shells.get("q1") is None


def test_config_modification_at_restart(fabric):
    """config_modification_at_restart (ra_2_SUITE): a restart merges
    whitelisted mutable keys into the recovered config
    (?MUTABLE_CONFIG_KEYS, ra_server_sup_sup.erl:12-20); identity and
    consensus-bearing keys are silently refused."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("tmut", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, 5, router=router)
    victim = [s for s in sids if s != leader][0]
    node = router.nodes[victim.node]
    old_uid = node.shells[victim.name].server.cfg.uid
    ra_tpu.restart_server(victim, router=router, mutable_config={
        "tick_interval_ms": 12345,
        "friendly_name": "renamed",
        "uid": "evil_uid",              # NOT mutable: ignored
        "election_timeout_ms": 1,       # NOT mutable: ignored
    })
    cfg = node.shells[victim.name].server.cfg
    assert cfg.tick_interval_ms == 12345
    assert cfg.friendly_name == "renamed"
    assert cfg.uid == old_uid
    assert cfg.election_timeout_ms != 1


def test_reply_from_member(fabric):
    """The reply_from command option (ra.erl:786-823,
    process_command_reply_from_member): the NAMED member answers an
    await_consensus call instead of the leader — and exactly once."""
    from ra_tpu.core.types import ReplyMode, UserCommand

    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("trf", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    follower = [s for s in sids if s != leader][0]
    got = []
    cmd = UserCommand(5, reply_mode=ReplyMode.AWAIT_CONSENSUS,
                      reply_from=("member", follower))
    router.nodes[leader.node].submit_command(leader.name, cmd, got.append)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not got:
        time.sleep(0.02)
    assert got and got[0].reply == 5, got
    time.sleep(0.5)     # a second (duplicate) reply must never arrive
    assert len(got) == 1, got
    # api surface: explicit member and client-side "local" resolution
    res = ra_tpu.process_command(leader, 3, router=router,
                                 reply_from=("member", follower))
    assert res.reply == 8
    res = ra_tpu.process_command(leader, 1, router=router,
                                 reply_from="local")
    assert res.reply == 9


def test_members_info_and_local_query_condition(fabric):
    """members_info (ra:members_info, state_query(members_info)) and
    local_query's {applied, IdxTerm} condition (query_condition,
    ra.erl:115-131): read-your-writes on a follower."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("tmi", counter_factory, sids, router=router)
    leader = await_leader(router, sids)
    res = ra_tpu.process_command(leader, 5, router=router)
    follower = [s for s in sids if s != leader][0]
    # condition blocks until the follower applied the commit, then the
    # read observes it — no retry loop needed
    got = ra_tpu.local_query(follower, lambda s: s, router=router,
                             condition=("applied", (res.index, res.term)))
    assert got.reply == 5
    assert got.index >= res.index
    # a mismatched term reports the overwrite instead of lying
    from ra_tpu.core.types import ErrorResult
    bad = ra_tpu.local_query(follower, lambda s: s, router=router,
                             condition=("applied", (res.index,
                                                    res.term + 9)))
    assert isinstance(bad, ErrorResult)
    assert bad.reason == "condition_term_mismatch"
    # an index that never applies times out rather than hanging
    with pytest.raises(TimeoutError):
        ra_tpu.local_query(follower, lambda s: s, router=router,
                           condition=("applied", (10_000, 1)),
                           timeout=0.3)
    info = ra_tpu.members_info(follower, router=router)  # redirects
    assert set(info) == set(sids)
    for sid, row in info.items():
        assert row["membership"] == "voter"
        assert row["match_index"] >= res.index, (sid, row)
    assert info[leader]["status"] == "normal"
