"""Second durable-log depth suite — the ra_log_2_SUITE scenarios not yet
covered by test_durable_log.py (/root/reference/test/ra_log_2_SUITE.erl):
sparse reads incl. out-of-range, overlapped-write read validation,
last_index resets before/after written confirms, writes below the
snapshot index, reads across segment updates, WAL-down read
availability, recovery with missing auxiliary directories, and snapshot
metadata round-trips (machine_version through release_cursor).
"""
import os
import pickle
import shutil


import pytest

from ra_tpu.core.types import Entry, SnapshotMeta, UserCommand

from test_durable_log import drain, mk_log, mk_system



def put(log, lo, hi, term, val=None):
    for i in range(lo, hi + 1):
        log.append(Entry(i, term, UserCommand(val if val is not None
                                              else i)))


def overwrite(log, lo, hi, term, val=None):
    """Truncating write — the follower AER path (ra_log:write)."""
    log.write([Entry(i, term, UserCommand(val if val is not None else i))
               for i in range(lo, hi + 1)])


# -- sparse reads -----------------------------------------------------------

def test_sparse_read_across_tiers(tmp_path):
    """sparse_read resolves each index through memtable/segments alike
    (ra_log_2_SUITE:sparse_read), preserving request order."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 100, 1)
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()          # 1..100 now in segments
    put(log, 101, 120, 1)
    drain(log)                                 # 101..120 in the memtable
    idxs = [3, 115, 42, 101, 100, 7]
    got = log.sparse_read(idxs)
    assert [e.index for e in got] == idxs
    assert [e.command.data for e in got] == idxs
    sys_.close()


def test_sparse_read_out_of_range(tmp_path):
    """Out-of-range indexes are skipped, in-range ones still returned
    (sparse_read_out_of_range, sparse_read_out_of_range_2)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 20, 1)
    drain(log)
    got = log.sparse_read([0, 5, 21, 10, 9999])
    assert [e.index for e in got] == [5, 10]
    # truncate the front behind a snapshot, then ask for dropped indexes
    meta = SnapshotMeta(index=10, term=1, cluster=(), machine_version=0)
    log.install_snapshot(meta, pickle.dumps({"s": 10}))
    got = log.sparse_read([5, 10, 15])
    assert [e.index for e in got] == [15]
    sys_.close()


# -- overwrite / reset semantics -------------------------------------------

def test_reads_for_overlapped_writes(tmp_path):
    """Write 1..10@t1, overwrite 5..8@t2, extend 9..12@t2: reads and
    terms must reflect the final log, memtable and recovery alike
    (validate_reads_for_overlapped_writes)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 10, 1)
    drain(log)
    overwrite(log, 5, 8, 2, val=1000)
    put(log, 9, 12, 2, val=2000)
    drain(log)
    assert log.last_index_term() == (12, 2)
    for i in range(1, 5):
        assert log.fetch(i).term == 1
        assert log.fetch(i).command.data == i
    for i in range(5, 9):
        assert (log.fetch(i).term, log.fetch(i).command.data) == (2, 1000)
    for i in range(9, 13):
        assert (log.fetch(i).term, log.fetch(i).command.data) == (2, 2000)
    sys_.close()
    # identical view after recovery (WAL overwrite rule)
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term() == (12, 2)
    assert log2.fetch(4).term == 1
    assert log2.fetch(5).command.data == 1000
    assert log2.fetch(12).command.data == 2000
    sys2.close()


def test_last_index_reset_after_written(tmp_path):
    """set_last_index truncates confirmed tail state: last_written falls
    with it and the next append reuses the indexes (last_index_reset)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 10, 1)
    drain(log)
    log.set_last_index(6)
    assert log.last_index_term() == (6, 1)
    assert log.last_written().index == 6
    put(log, 7, 9, 2)
    drain(log)
    assert log.last_index_term() == (9, 2)
    assert log.fetch(8).term == 2
    sys_.close()


def test_last_index_reset_before_written(tmp_path):
    """Resetting below a not-yet-confirmed tail must not let the stale
    confirm resurrect it (last_index_reset_before_written)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 10, 1)
    drain(log)
    put(log, 11, 20, 1)          # in flight, possibly unconfirmed
    log.set_last_index(10)       # follower-style revert before confirm
    for e in log.take_events():
        log.handle_written(e)    # late confirms for 11..20 arrive now
    assert log.last_index_term().index == 10
    assert log.last_written().index <= 10
    put(log, 11, 12, 3)
    drain(log)
    assert log.last_index_term() == (12, 3)
    assert log.fetch(11).term == 3
    sys_.close()


# -- snapshot interactions --------------------------------------------------

def test_writes_below_snapshot_index_dropped(tmp_path):
    """After a snapshot install, writes at or below the snapshot index
    are obsolete — they must not resurface in reads or after recovery
    (writes_lower_than_snapshot_index_are_dropped)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 30, 1)
    drain(log)
    meta = SnapshotMeta(index=20, term=1, cluster=(), machine_version=0)
    log.install_snapshot(meta, pickle.dumps({"s": 20}))
    assert log.first_index() == 21
    # a straggler AER delivers pre-snapshot entries again
    overwrite(log, 21, 25, 1, val=5555)  # legitimate: above the snapshot
    drain(log)
    assert log.fetch(25).command.data == 5555
    assert log.fetch(20) is None
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.first_index() == 21
    assert log2.fetch(15) is None
    assert log2.snapshot_index_term() == (20, 1)
    sys2.close()


def test_release_cursor_roundtrips_machine_version(tmp_path):
    """update_release_cursor persists cluster + machine_version in the
    snapshot meta; recovery hands both back
    (update_release_cursor_with_machine_version)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 50, 1)
    drain(log)
    cluster = (("s1", "n1"), ("s2", "n2"))
    log.update_release_cursor(40, cluster, 3, {"acc": 40})
    assert log.snapshot_index_term() == (40, 1)
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    rec = log2.recover_snapshot_state()
    assert rec is not None
    meta, state = rec
    assert meta.index == 40 and meta.term == 1
    assert meta.machine_version == 3
    assert tuple(meta.cluster) == cluster
    assert state == {"acc": 40}
    sys2.close()


# -- WAL-down availability --------------------------------------------------

# Wal.kill() below makes the batch thread die by an uncaught
# exception on purpose — that IS the scenario under test
@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_wal_down_reads_still_serve(tmp_path):
    """A dead WAL blocks writes, not reads: everything already written
    stays readable from memtable and segments
    (wal_down_read_availability)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 60, 1)
    drain(log)
    sys_.wal.kill()
    assert not log.wal_is_up()
    assert log.fetch(30).command.data == 30
    assert [e.index for e in log.sparse_read([1, 59])] == [1, 59]
    assert log.fold(1, 60, lambda e, acc: acc + 1, 0) == 60
    sys_.close()


# -- recovery robustness ----------------------------------------------------

def test_recovery_with_missing_checkpoints_directory(tmp_path):
    """Deleting the checkpoints dir offline must not break recovery
    (recovery_with_missing_checkpoints_directory)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 40, 1)
    drain(log)
    log.checkpoint(30, (), 0, {"c": 30})
    assert log.checkpoint_index() == 30
    sys_.close()
    ckpt_dir = None
    for root, dirs, _files in os.walk(str(tmp_path)):
        for d in dirs:
            if d == "checkpoints":
                ckpt_dir = os.path.join(root, d)
    assert ckpt_dir is not None
    shutil.rmtree(ckpt_dir)
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term().index == 40
    assert log2.checkpoint_index() == 0
    assert log2.fetch(40).command.data == 40
    sys2.close()


def test_recovery_with_missing_wal_directory(tmp_path):
    """Once every entry reached segments, the WAL directory itself is
    disposable: recovery from segments alone serves the full log
    (recovery_with_missing_* family — a registered dir may vanish
    without breaking boot)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 25, 1)
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    sys_.close()
    shutil.rmtree(os.path.join(str(tmp_path), "wal"))
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    assert log2.last_index_term().index == 25
    assert log2.fetch(25).command.data == 25
    assert log2.fetch(1).command.data == 1
    sys2.close()


# -- checkpoint recovery (ra_checkpoint_SUITE) ------------------------------

def test_recover_from_checkpoint_only(tmp_path):
    """With no snapshot, the newest checkpoint is the machine-state base
    (recover_from_checkpoint_only) — the log below it stays intact."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 40, 1)
    drain(log)
    log.checkpoint(25, (), 0, {"acc": 25})
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    base = log2.recover_machine_base()
    assert base is not None
    meta, state = base
    assert meta.index == 25 and state == {"acc": 25}
    assert log2.recover_snapshot_state() is None   # no snapshot exists
    assert log2.first_index() == 1                 # no truncation
    sys2.close()


def test_recover_from_checkpoint_and_snapshot(tmp_path):
    """A checkpoint newer than the snapshot wins as the base
    (recover_from_checkpoint_and_snapshot)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 60, 1)
    drain(log)
    log.update_release_cursor(20, (), 0, {"acc": 20})
    log.checkpoint(45, (), 0, {"acc": 45})
    sys_.close()
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    meta, state = log2.recover_machine_base()
    assert meta.index == 45 and state == {"acc": 45}
    # and the snapshot alone still answers with 20 (install path)
    smeta, _ = log2.recover_snapshot_state()
    assert smeta.index == 20
    sys2.close()


def test_newer_snapshot_deletes_older_checkpoints(tmp_path):
    """A release_cursor drops checkpoints at or below its index
    (newer_snapshot_deletes_older_checkpoints)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 60, 1)
    drain(log)
    log.checkpoint(10, (), 0, {"acc": 10})
    log.checkpoint(30, (), 0, {"acc": 30})
    log.checkpoint(50, (), 0, {"acc": 50})
    log.update_release_cursor(40, (), 0, {"acc": 40})
    assert log.checkpoint_index() == 50            # the survivor
    assert log.overview()["num_checkpoints"] == 1
    meta, state = log.recover_machine_base()
    assert meta.index == 50
    sys_.close()


def test_corrupt_checkpoint_falls_back_to_older(tmp_path):
    """init_recover_corrupt: a torn newest checkpoint is skipped in
    favor of the next older one."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 40, 1)
    drain(log)
    log.checkpoint(20, (), 0, {"acc": 20})
    log.checkpoint(35, (), 0, {"acc": 35})
    sys_.close()
    cpdir = os.path.join(str(tmp_path), "u1", "checkpoints")
    newest = sorted(os.listdir(cpdir))[-1]
    with open(os.path.join(cpdir, newest), "r+b") as f:
        f.seek(18)
        f.write(b"\xde\xad\xbe\xef")
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    meta, state = log2.recover_machine_base()
    assert meta.index == 20 and state == {"acc": 20}
    sys2.close()


def test_multi_corrupt_checkpoints_fall_back_to_snapshot(tmp_path):
    """init_recover_multi_corrupt: every checkpoint torn -> the snapshot
    is the base; no garbage load."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 40, 1)
    drain(log)
    log.update_release_cursor(10, (), 0, {"acc": 10})
    log.checkpoint(20, (), 0, {"acc": 20})
    log.checkpoint(35, (), 0, {"acc": 35})
    sys_.close()
    cpdir = os.path.join(str(tmp_path), "u1", "checkpoints")
    for fname in os.listdir(cpdir):
        with open(os.path.join(cpdir, fname), "r+b") as f:
            f.seek(18)
            f.write(b"\xde\xad\xbe\xef")
    sys2 = mk_system(tmp_path)
    log2 = mk_log(sys2)
    meta, state = log2.recover_machine_base()
    assert meta.index == 10 and state == {"acc": 10}
    sys2.close()


def test_server_restart_resumes_from_checkpoint_base(tmp_path):
    """End-to-end: a node restart recovers machine state from the
    checkpoint and replays only the tail above it."""
    import ra_tpu
    from ra_tpu.core.machine import SimpleMachine
    from ra_tpu.core.types import ServerConfig, ServerId
    from ra_tpu.node import LocalRouter, RaNode

    router = LocalRouter()
    sys_ = mk_system(tmp_path)
    node = RaNode("ck1", router=router, log_factory=sys_.log_factory)
    sid = ServerId("c1", "ck1")
    applied = []

    def mk_machine():
        def fn(cmd, st):
            applied.append(cmd)
            return st + cmd
        return SimpleMachine(fn, 0)

    node.start_server(ServerConfig(
        server_id=sid, uid="uid_ck", cluster_name="ck",
        initial_members=(sid,), machine=mk_machine(),
        election_timeout_ms=200, tick_interval_ms=100))
    ra_tpu.trigger_election(sid, router)
    total = 0
    for v in range(1, 31):
        ra_tpu.process_command(sid, v, router=router)
        total += v
    # checkpoint at the current applied index via the machine-effect path
    sh = node.shells[sid.name]
    sh.server.log.checkpoint(sh.server.last_applied, (), 0,
                             sh.server.machine_state)
    node.stop()
    sys_.close()

    applied.clear()
    sys2 = mk_system(tmp_path)
    node2 = RaNode("ck1", router=LocalRouter(),
                   log_factory=sys2.log_factory)
    node2.start_server(ServerConfig(
        server_id=sid, uid="uid_ck", cluster_name="ck",
        initial_members=(sid,), machine=mk_machine(),
        election_timeout_ms=200, tick_interval_ms=100))
    sh2 = node2.shells[sid.name]
    assert sh2.server.machine_state == total
    assert applied == []  # nothing re-applied: the checkpoint was the base
    node2.stop()
    sys2.close()


def test_updated_segment_can_be_read(tmp_path):
    """Append, flush, append more into the SAME segment file, flush
    again: both flush generations stay readable
    (updated_segment_can_be_read)."""
    sys_ = mk_system(tmp_path)
    log = mk_log(sys_)
    put(log, 1, 10, 1)
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    n_seg1 = log.overview()["num_segments"]
    put(log, 11, 20, 1)
    drain(log)
    sys_.wal.rollover()
    sys_.wal.flush()
    sys_.segment_writer.await_idle()
    assert log.overview()["num_mem_entries"] == 0
    for i in (1, 10, 11, 20):
        assert log.fetch(i).command.data == i
    # both flushes may share a segment file (append-optimized format)
    assert log.overview()["num_segments"] >= n_seg1
    sys_.close()
