"""Machine registry — picklable machine specs for cross-node lifecycle
(the module-name-over-rpc role of ra_server_sup_sup.erl:42-130)."""
import pickle

import pytest

from ra_tpu.core.machine import SimpleMachine
from ra_tpu.machines import (is_machine_spec, machine_spec,
                             register_machine, resolve_machine, spec_of)


def test_spec_roundtrip_and_resolution():
    spec = machine_spec("jit_fifo", capacity=32, checkout_slots=4)
    assert is_machine_spec(spec)
    assert pickle.loads(pickle.dumps(spec)) == spec
    m = resolve_machine(spec)
    assert m.capacity == 32 and m.checkout_slots == 4
    assert spec_of(m) == spec


def test_builtin_counter_and_custom_registration():
    m = resolve_machine(machine_spec("counter", initial=7))
    assert m.apply(None, 3, 7)[0] == 10

    register_machine("t_custom", lambda n=1: SimpleMachine(
        lambda c, s: s + c * n, 0))
    m2 = resolve_machine(machine_spec("t_custom", n=5))
    assert m2.apply(None, 2, 0)[0] == 10
    assert spec_of(m2) == ("$machine", "t_custom", {"n": 5})


def test_resolution_errors_and_idempotence():
    with pytest.raises(KeyError, match="not registered"):
        resolve_machine(machine_spec("no_such_machine"))
    with pytest.raises(ValueError, match="not a machine spec"):
        resolve_machine(("bogus",))
    live = SimpleMachine(lambda c, s: s, 0)
    assert resolve_machine(live) is live     # idempotent on instances
    assert spec_of(live) is None
