"""Read-plane tests + the linearizable-read oracle (ISSUE 20).

Two layers:

* unit pins — lease grant/expiry, stale refusal under a partitioned
  leader, ``read_lanes`` round trips over the query machine library,
  checkpoint restore of the read counters;
* :func:`run_read_oracle` — the chaos family ``tools/soak.py --reads``
  drives: a host-side model machine folds the SAME committed command
  history the engine applies, and every consistent read served across
  election churn, leader kills, majority partitions and (optionally)
  disk faults must equal the model's answer over the FULL committed
  prefix — "a read at watermark W reflects every write committed
  <= W".  A reply matching only an OLDER prefix is a stale serve and
  the oracle's stale counter is pinned 0 (the device refusing a read
  is always safe; serving stale never is).  Runs single-device and on
  the sharded 8-way lane mesh.
"""
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import (CounterMachine, JitKvMachine, StreamMachine,
                           TtlKvMachine)

N = 8      # lanes
P = 3      # members
K = 4      # cmds per traffic round


def _zeros_step(eng, **kw):
    zn = np.zeros((eng.n_lanes,), np.int32)
    zp = np.zeros((eng.n_lanes, eng.max_step_cmds, eng.payload_width),
                  np.dtype(eng.payload_dtype))
    return eng.step(zn, zp, **kw)


def _drain(eng, limit=64):
    """Empty rounds until every lane's leader log is committed and
    applied on every ACTIVE member (the drain_committed pattern)."""
    lane = np.arange(eng.n_lanes)
    for _ in range(limit):
        st = eng.state
        leads = np.asarray(st.leader_slot)
        tail = np.asarray(st.last_index)[lane, leads]
        com = np.asarray(st.commit)[lane, leads]
        act = np.asarray(st.active)
        app = np.where(act, np.asarray(st.applied),
                       np.iinfo(np.int32).max).min(axis=1)
        if (com >= tail).all() and (app >= com).all():
            return
        _zeros_step(eng)
    raise AssertionError("read-plane drain did not converge")


# ---------------------------------------------------------------------------
# host model machines: exact folds of the committed command history
# ---------------------------------------------------------------------------

class _TtlModel:
    """TtlKvMachine fold for ttl=0 command streams (put/delete/watch are
    raft-index-independent then, so the model needs no logical clock)."""

    def __init__(self, n_keys=8):
        self.n_keys = n_keys
        self.vals: dict = {}
        self.watch: dict = {}

    def apply(self, cmd) -> None:
        op, key, val, _ttl = (int(x) for x in cmd)
        if not (0 <= key < self.n_keys):
            return
        if op == 1 and val >= 0:
            self.vals[key] = val
        elif op == 3:
            self.vals.pop(key, None)
        elif op == 4:
            self.watch[key] = self.watch.get(key, 0) + 1

    def query(self, q) -> tuple:
        op, key = int(q[0]), int(q[1])
        ok = 0 <= key < self.n_keys
        if op == 2:  # watchers(key)
            return ((1, self.watch.get(key, 0)) if ok else (0, -1))
        # get(key)
        if ok and key in self.vals:
            return (1, self.vals[key])
        return (0, -1)


class _StreamModel:
    """StreamMachine fold: ring retention + monotone group cursors."""

    def __init__(self, capacity=16, groups=4):
        self.q, self.g = capacity, groups
        self.buf: dict = {}
        self.tail = 0
        self.base = 0
        self.cursors = [0] * groups

    def apply(self, cmd) -> None:
        op, a, b = (int(x) for x in cmd)
        if op == 1 and a >= 0:
            self.buf[self.tail] = a
            self.tail += 1
        elif op == 2 and 0 <= a < self.g:
            self.cursors[a] = min(max(self.cursors[a], b, 0), self.tail)
        elif op == 3:
            self.base = min(max(self.base, a, 0), self.tail)
        self.base = max(self.base, self.tail - self.q)

    def query(self, q) -> tuple:
        op, a = int(q[0]), int(q[1])
        if op == 0:  # bounds()
            return (self.tail, self.base)
        if op == 1:  # read(offset)
            if self.base <= a < self.tail:
                return (1, self.buf[a])
            return (0, -1)
        if 0 <= a < self.g:  # cursor(g)
            return (1, self.cursors[a])
        return (0, -1)


def _ttl_cmds(rng):
    out = []
    for _ in range(K):
        r = rng.random()
        key = rng.randrange(8)
        if r < 0.5:
            out.append((1, key, rng.randrange(100), 0))      # put, no ttl
        elif r < 0.7:
            out.append((3, key, 0, 0))                       # delete
        else:
            out.append((4, key, 0, 0))                       # watch
    return out


def _ttl_query(rng):
    return (rng.choice([1, 2]), rng.randrange(-1, 9))


def _stream_cmds(rng, tail):
    out = []
    for _ in range(K):
        r = rng.random()
        if r < 0.7:
            out.append((1, rng.randrange(1, 100), 0))        # append
        elif r < 0.9:
            out.append((2, rng.randrange(4), rng.randrange(tail + 2)))
        else:
            out.append((3, rng.randrange(tail + 2), 0))      # truncate
    return out


def _stream_query(rng, tail):
    r = rng.random()
    if r < 0.3:
        return (0, 0)                                        # bounds
    if r < 0.8:
        return (1, rng.randrange(-1, tail + 2))              # read(off)
    return (2, rng.randrange(-1, 5))                         # cursor(g)


_KINDS = {
    "ttl_kv": (lambda: TtlKvMachine(n_keys=8), lambda: _TtlModel(8),
               _ttl_cmds, _ttl_query, 4),
    "stream": (lambda: StreamMachine(capacity=16, groups=4),
               lambda: _StreamModel(16, 4),
               _stream_cmds, _stream_query, 3),
}


# ---------------------------------------------------------------------------
# the oracle
# ---------------------------------------------------------------------------

def run_read_oracle(seed, machine_kind="ttl_kv", *, mesh=False,
                    durable_dir=None, disk_faults=False,
                    rounds=16) -> dict:
    """Chaos schedule with a consistent read wave every round.  Traffic
    rounds fully drain before any nemesis fires, so the committed state
    at every read is EXACTLY the model fold of the whole history — a
    served reply must match it; matching only an older prefix counts as
    a stale serve (pinned 0); refusing is always legal.  The final
    healed wave must SERVE on every lane (liveness)."""
    import random

    rng = random.Random(seed)
    make_machine, make_model, make_cmds, make_query, width = \
        _KINDS[machine_kind]

    if durable_dir is not None:
        from ra_tpu.engine.durable import open_engine
        eng = open_engine(make_machine(), durable_dir, N, P,
                          wal_shards=2, ring_capacity=64,
                          max_step_cmds=K, max_step_reads=4,
                          lease_ttl=4, donate=False)
    else:
        eng = LockstepEngine(make_machine(), N, P, ring_capacity=64,
                             max_step_cmds=K, max_step_reads=4,
                             lease_ttl=4, donate=False)
    if mesh:
        import jax

        from ra_tpu.parallel.mesh import lane_mesh, shard_engine_state
        shard_engine_state(eng, lane_mesh(jax.devices(), member_axis=1))
    plan = None
    if disk_faults:
        from ra_tpu.log import faults
        plan = faults.DiskFaultPlan(
            seed=seed, by_class={"wal": faults.DiskFaultSpec(
                fsync_eio=0.05, short_write=0.02, limit=3)})
        faults.install_plan(plan)

    model = make_model()
    snaps: list = [model]           # model state after each prefix
    history: list = []
    down: dict = {lane: set() for lane in range(N)}
    last_wm = np.full((N,), -1, np.int32)
    stats = {"served": 0, "refused": 0, "stale_serves": 0}

    def snapshot_model():
        import copy
        return copy.deepcopy(snaps[-1])

    def submit(cmds) -> None:
        pay = np.zeros((N, K, width), np.int32)
        for k, c in enumerate(cmds):
            pay[:, k] = c
        eng.step(np.full((N,), K, np.int32), jnp.asarray(pay))
        _drain(eng)
        for c in cmds:
            history.append(c)
            m = snapshot_model()
            m.apply(c)
            snaps.append(m)

    def read_wave(must_refuse=None) -> None:
        tail = snaps[-1].tail if machine_kind == "stream" else 0
        qs = [(make_query(rng, tail) if machine_kind == "stream"
               else make_query(rng)) for _ in range(N)]
        replies, wm, ok = eng.read_lanes(
            np.arange(N), np.asarray(qs, np.int32))
        if must_refuse is not None:
            assert not ok[must_refuse], (
                f"lane {must_refuse} served a read past its lease while "
                f"partitioned from quorum (seed={seed})")
        for lane in range(N):
            if not ok[lane]:
                stats["refused"] += 1
                continue
            stats["served"] += 1
            want = snaps[-1].query(qs[lane])
            got = (int(replies[lane][0]), int(replies[lane][1]))
            if got != want:
                # distinguish stale serve from corruption for the
                # failure message, then fail either way
                if any(s.query(qs[lane]) == got for s in snaps[:-1]):
                    stats["stale_serves"] += 1
                assert got == want, (
                    f"lane {lane} read {qs[lane]} -> {got}, model says "
                    f"{want} (stale_serves={stats['stale_serves']}, "
                    f"seed={seed}, kind={machine_kind})")
            assert wm[lane] >= last_wm[lane], \
                f"lane {lane} served watermark regressed"
            last_wm[lane] = wm[lane]

    try:
        for _ in range(rounds):
            roll = rng.random()
            if roll < 0.45:
                tail = snaps[-1].tail if machine_kind == "stream" else 0
                submit(make_cmds(rng, tail) if machine_kind == "stream"
                       else make_cmds(rng))
            elif roll < 0.6:
                # quorum-preserving member kill (leader kill included)
                leads = np.asarray(eng.state.leader_slot)
                for lane in range(N):
                    if len(down[lane]) >= (P - 1) // 2:
                        continue
                    victim = rng.choice(
                        [s for s in range(P) if s not in down[lane]])
                    eng.fail_member(lane, victim)
                    down[lane].add(victim)
                    if victim == int(leads[lane]):
                        eng.trigger_election([lane])
            elif roll < 0.75:
                # majority partition on ONE lane: its leader loses
                # quorum entirely.  Burn past the lease horizon, then a
                # read on that lane must REFUSE (a lease read never
                # outlives lease expiry) while healthy lanes still
                # serve.  Heal before the round ends so the next
                # traffic round can commit everywhere.
                lane = rng.randrange(N)
                lead = int(np.asarray(eng.state.leader_slot)[lane])
                cut = [s for s in range(P)
                       if s != lead and s not in down[lane]]
                for s in cut:
                    eng.fail_member(lane, s)
                for _ in range(3 * eng.lease_ttl):
                    _zeros_step(eng)
                read_wave(must_refuse=lane)
                for s in cut:
                    eng.recover_member(lane, s)
                st = eng.state
                if not np.asarray(st.active)[
                        lane, int(np.asarray(st.leader_slot)[lane])]:
                    eng.trigger_election([lane])
                _drain(eng, limit=96)
                continue
            elif roll < 0.9:
                leads = np.asarray(eng.state.leader_slot)
                for lane in range(N):
                    if down[lane]:
                        slot = rng.choice(sorted(down[lane]))
                        if slot != int(leads[lane]):
                            eng.recover_member(lane, slot)
                            down[lane].discard(slot)
                _drain(eng, limit=96)
            else:
                healthy = [lane for lane in range(N) if not down[lane]]
                if healthy:
                    eng.trigger_election(healthy)
            read_wave()

        # heal everything, converge, and require liveness: every lane
        # serves the final wave at the full model state
        for _ in range(3):
            leads = np.asarray(eng.state.leader_slot)
            for lane in range(N):
                for slot in sorted(down[lane]):
                    if slot != int(leads[lane]):
                        eng.recover_member(lane, slot)
                        down[lane].discard(slot)
            broken = [lane for lane in range(N) if down[lane]]
            if broken:
                eng.trigger_election(broken)
        assert not any(down.values()), down
        _drain(eng, limit=128)
        qs = [(make_query(rng, snaps[-1].tail)
               if machine_kind == "stream" else make_query(rng))
              for _ in range(N)]
        replies, _wm, ok = eng.read_lanes(
            np.arange(N), np.asarray(qs, np.int32))
        assert ok.all(), f"healed lanes refused reads: {np.where(~ok)[0]}"
        for lane in range(N):
            want = snaps[-1].query(qs[lane])
            got = (int(replies[lane][0]), int(replies[lane][1]))
            assert got == want, (lane, qs[lane], got, want)
    finally:
        if plan is not None:
            from ra_tpu.log import faults
            faults.clear_plan()
    assert stats["stale_serves"] == 0, stats
    return stats


# ---------------------------------------------------------------------------
# unit pins
# ---------------------------------------------------------------------------

def test_read_lanes_round_trip_query_machines():
    """Every query machine serves exact consistent reads post-commit."""
    cases = [
        (CounterMachine(), [(1,)] * 3, np.zeros((N, 1), np.int32),
         lambda rep: (rep[:, 0] == 3).all()),
        (JitKvMachine(n_keys=8),
         [(1, 3, 77, 0)],                       # put(3, 77)
         np.tile(np.asarray([[1, 3]], np.int32), (N, 1)),
         lambda rep: (rep[:, 0] == 1).all() and (rep[:, 1] == 77).all()),
        (TtlKvMachine(n_keys=8),
         [(1, 2, 55, 0), (4, 2, 0, 0)],          # put + watch
         np.tile(np.asarray([[2, 2]], np.int32), (N, 1)),
         lambda rep: (rep[:, 0] == 1).all() and (rep[:, 1] == 1).all()),
        (StreamMachine(capacity=8, groups=2),
         [(1, 42, 0), (1, 43, 0)],               # append x2
         np.tile(np.asarray([[1, 1]], np.int32), (N, 1)),
         lambda rep: (rep[:, 0] == 1).all() and (rep[:, 1] == 43).all()),
    ]
    for machine, cmds, queries, check in cases:
        eng = LockstepEngine(machine, N, P, ring_capacity=32,
                             max_step_cmds=4, max_step_reads=4,
                             lease_ttl=4, donate=False)
        w = eng.payload_width
        pay = np.zeros((N, 4, w), np.int32)
        for k, c in enumerate(cmds):
            pay[:, k, :len(c)] = c
        eng.step(np.full((N,), len(cmds), np.int32), jnp.asarray(pay))
        _drain(eng)
        replies, wm, ok = eng.read_lanes(np.arange(N), queries)
        assert ok.all(), type(machine).__name__
        assert (wm >= 0).all()
        assert check(replies), (type(machine).__name__, replies[:2])


def test_partitioned_leader_refuses_after_lease_expiry():
    """A leader cut from its majority must stop serving once the lease
    horizon passes: the pending read settles as a STALE REFUSAL (the
    device's read_stale counter advances), never a stale serve."""
    eng = LockstepEngine(TtlKvMachine(n_keys=8), N, P, ring_capacity=32,
                         max_step_cmds=4, max_step_reads=4,
                         lease_ttl=4, donate=False)
    pay = np.zeros((N, 4, 4), np.int32)
    pay[:, 0] = (1, 1, 9, 0)
    eng.step(np.full((N,), 1, np.int32), jnp.asarray(pay))
    _drain(eng)
    # partition lane 0's leader from both followers
    lead = int(np.asarray(eng.state.leader_slot)[0])
    for s in range(P):
        if s != lead:
            eng.fail_member(0, s)
    # burn well past the lease so no grant survives registration
    for _ in range(3 * eng.lease_ttl):
        _zeros_step(eng)
    stale0 = int(np.asarray(eng.state.read_stale)[0])
    shed0 = int(np.asarray(eng.state.read_shed)[0])
    replies, wm, ok = eng.read_lanes(
        [0], np.asarray([[1, 1]], np.int32))
    assert not ok[0], "partitioned leader served past its lease"
    assert wm[0] == -1
    stale1 = int(np.asarray(eng.state.read_stale)[0])
    shed1 = int(np.asarray(eng.state.read_shed)[0])
    assert stale1 + shed1 > stale0 + shed0
    # heal: recover followers, re-elect, and the lane serves again
    for s in range(P):
        if s != lead:
            eng.recover_member(0, s)
    _drain(eng, limit=96)
    replies, wm, ok = eng.read_lanes([0], np.asarray([[1, 1]], np.int32))
    assert ok[0] and replies[0][0] == 1 and replies[0][1] == 9


def test_checkpoint_roundtrip_preserves_read_counters():
    """save/restore carries the read-plane counters (CHECKPOINT
    defaults are "zeros" — an old archive restores cleanly, pinned by
    the schema tests; here: a NEW archive round-trips exactly)."""
    import os
    eng = LockstepEngine(JitKvMachine(n_keys=8), N, P, ring_capacity=32,
                         max_step_cmds=4, max_step_reads=4,
                         lease_ttl=4, donate=False)
    pay = np.zeros((N, 4, 4), np.int32)
    pay[:, 0] = (1, 2, 5, 0)
    eng.step(np.full((N,), 1, np.int32), jnp.asarray(pay))
    _drain(eng)
    eng.read_lanes(np.arange(N), np.tile(
        np.asarray([[1, 2]], np.int32), (N, 1)))
    served = np.asarray(eng.state.read_served).copy()
    assert served.sum() > 0
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.npz")
        eng.save(path)
        eng2 = LockstepEngine(JitKvMachine(n_keys=8), N, P,
                              ring_capacity=32, max_step_cmds=4,
                              max_step_reads=4, lease_ttl=4,
                              donate=False)
        eng2.restore(path)
        np.testing.assert_array_equal(
            np.asarray(eng2.state.read_served), served)
        replies, _wm, ok = eng2.read_lanes(np.arange(N), np.tile(
            np.asarray([[1, 2]], np.int32), (N, 1)))
        assert ok.all() and (replies[:, 1] == 5).all()


def test_read_oracle_ttl_kv():
    run_read_oracle(0, "ttl_kv", rounds=12)


def test_read_oracle_stream():
    run_read_oracle(1, "stream", rounds=12)


def test_read_oracle_sharded_mesh():
    run_read_oracle(2, "ttl_kv", mesh=True, rounds=8)


@pytest.mark.slow
def test_read_oracle_durable_disk_faults():
    with tempfile.TemporaryDirectory() as d:
        run_read_oracle(3, "stream", durable_dir=d, disk_faults=True,
                        rounds=10)


def test_ra_top_renders_read_panel(tmp_path):
    """ra_top shows the read plane: serve rate over the snapshot
    window, read_e2e p99 from the phase attribution, lease coverage,
    shed/stale counters, and the REFUSING flag when stale_refused grew
    between frames."""
    import json
    import os
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_rd = {"served": 10_000, "shed": 12, "stale_refused": 3,
               "queue_rows": 64, "lease_coverage_pct": 96.5}
    eng = {"lanes": 16, "members": 3,
           "phases": {"read_e2e": {"count": 9, "p99_ms": 4.2}}}
    t0 = time.time()
    snap0 = {"seq": 1, "ts": t0 - 1.0, "engine": eng, "read": base_rd}
    snap1 = {"seq": 2, "ts": t0, "engine": eng,
             "read": {**base_rd, "served": 60_000, "stale_refused": 7}}
    path = str(tmp_path / "obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(snap0) + "\n")
        f.write(json.dumps(snap1) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "reads" in out and "srv/s" in out
    assert "p99=4.2ms" in out
    assert "lease=96%" in out or "lease=97%" in out
    assert "q=64" in out and "shed=12" in out
    assert "stale_refused=7" in out
    assert "REFUSING" in out
