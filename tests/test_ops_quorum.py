"""Kernel-vs-oracle equivalence: the batched XLA quorum kernels must agree
with the scalar pure core on randomized inputs (the TPU analogue of driving
ra_server's quorum functions directly in ra_server_SUITE)."""
import numpy as np

import jax.numpy as jnp

from ra_tpu.core.server import RaServer
from ra_tpu.ops import (
    agreed_commit,
    election_quorum,
    evaluate_quorum,
    update_match_next,
)

rng = np.random.default_rng(42)


def test_agreed_commit_matches_oracle_randomized():
    N, P = 257, 7
    match = rng.integers(0, 1000, size=(N, P)).astype(np.int32)
    # random voter masks with at least 1 voter
    mask = rng.random((N, P)) < 0.7
    mask[:, 0] = True
    got = np.asarray(agreed_commit(jnp.asarray(match), jnp.asarray(mask)))
    for i in range(N):
        voters = [int(match[i, p]) for p in range(P) if mask[i, p]]
        assert got[i] == RaServer.agreed_commit(voters), (i, voters, got[i])


def test_agreed_commit_known_cases():
    cases = [
        ([5], [True], 5),
        ([5, 3], [True, True], 3),
        ([5, 3, 1], [True, True, True], 3),
        ([7, 5, 3, 1], [True] * 4, 3),
        ([9, 7, 5, 3, 1], [True] * 5, 5),
        ([9, 7, 5, 3, 1], [True, True, True, False, False], 7),  # non-voters
        ([0, 0, 9], [True] * 3, 0),
    ]
    for vals, mask, want in cases:
        P = len(vals)
        got = int(agreed_commit(jnp.asarray([vals], jnp.int32),
                                jnp.asarray([mask]))[0])
        assert got == want, (vals, mask, got, want)


def test_evaluate_quorum_term_gate():
    # agreed=5 but term_start=6 -> not committable (§5.4.2)
    match = jnp.asarray([[5, 5, 5], [5, 5, 5]], jnp.int32)
    mask = jnp.ones((2, 3), bool)
    commit = jnp.asarray([2, 2], jnp.int32)
    term_start = jnp.asarray([6, 3], jnp.int32)
    out = np.asarray(evaluate_quorum(commit, match, mask, term_start))
    assert out.tolist() == [2, 5]


def test_evaluate_quorum_never_regresses():
    N, P = 128, 5
    match = rng.integers(0, 50, size=(N, P)).astype(np.int32)
    mask = np.ones((N, P), bool)
    commit = rng.integers(0, 60, size=N).astype(np.int32)
    ts = rng.integers(0, 60, size=N).astype(np.int32)
    out = np.asarray(evaluate_quorum(jnp.asarray(commit), jnp.asarray(match),
                                     jnp.asarray(mask), jnp.asarray(ts)))
    assert (out >= commit).all()


def test_election_quorum():
    granted = jnp.asarray([
        [True, True, False, False, False],   # 2/5 -> no
        [True, True, True, False, False],    # 3/5 -> yes
        [True, False, False, False, False],  # 1/1 voter -> yes
        [True, True, False, False, False],   # 2/3 voters -> yes
    ])
    mask = jnp.asarray([
        [True] * 5,
        [True] * 5,
        [True, False, False, False, False],
        [True, True, True, False, False],
    ])
    out = np.asarray(election_quorum(granted, mask))
    assert out.tolist() == [False, True, True, True]


def test_update_match_next_fold():
    match = jnp.asarray([[3, 0, 7]], jnp.int32)
    nxt = jnp.asarray([[4, 1, 8]], jnp.int32)
    success = jnp.asarray([[True, False, True]])
    r_last = jnp.asarray([[6, 9, 5]], jnp.int32)
    r_next = jnp.asarray([[7, 10, 6]], jnp.int32)
    m, n = update_match_next(match, nxt, success, r_last, r_next)
    assert np.asarray(m).tolist() == [[6, 0, 7]]   # only replied slots move
    assert np.asarray(n).tolist() == [[7, 1, 8]]   # max() never regresses


def test_kernels_jit_and_vmap():
    import jax
    f = jax.jit(evaluate_quorum)
    out = f(jnp.zeros((16,), jnp.int32),
            jnp.ones((16, 5), jnp.int32) * 3,
            jnp.ones((16, 5), bool),
            jnp.ones((16,), jnp.int32))
    assert np.asarray(out).tolist() == [3] * 16
