"""RegisterMachine — the order-dependent jittable machine family: CAS
semantics, the lane engine's sequential scan apply path, and the same
machine running unchanged on the classic host path."""
import jax.numpy as jnp
import numpy as np

import ra_tpu
from ra_tpu.core.types import ServerId
from ra_tpu.engine import LockstepEngine
from ra_tpu.models import RegisterMachine
from ra_tpu.models.registers import query_registers
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader
import pytest


def host_fold(cmds, n_slots=8):
    """Python oracle for the encoded command semantics."""
    regs = [0] * n_slots
    for op, slot, value, expected in cmds:
        slot = max(0, min(slot, n_slots - 1))
        if op == 1:
            regs[slot] = value
        elif op == 2:
            regs[slot] += value
        elif op == 3 and regs[slot] == expected:
            regs[slot] = value
    return regs


def test_jit_apply_semantics():
    m = RegisterMachine(n_slots=4)
    state = m.jit_init(1)[0]
    meta = {"index": jnp.int32(1), "term": jnp.int32(1)}
    state, old = m.jit_apply(meta, m.encode_command(("put", 2, 7)), state)
    assert int(old) == 0 and int(state[2]) == 7
    state, new = m.jit_apply(meta, m.encode_command(("add", 2, 3)), state)
    assert int(new) == 10 and int(state[2]) == 10
    state, ok = m.jit_apply(meta, m.encode_command(("cas", 2, 10, 99)),
                            state)
    assert int(ok) == 1 and int(state[2]) == 99
    state, ok = m.jit_apply(meta, m.encode_command(("cas", 2, 10, 1)),
                            state)
    assert int(ok) == 0 and int(state[2]) == 99
    # noop leaves everything untouched
    state2, _ = m.jit_apply(meta, jnp.zeros((4,), jnp.int32), state)
    assert (np.asarray(state2) == np.asarray(state)).all()


def test_lane_engine_scan_order_matches_oracle():
    """CAS does not commute: the engine's sequential apply must reproduce
    the exact per-lane command order."""
    rng = np.random.default_rng(3)
    N, K, STEPS = 16, 8, 6
    m = RegisterMachine(n_slots=8)
    eng = LockstepEngine(m, N, 3, ring_capacity=256, max_step_cmds=K,
                        donate=False)
    lane_cmds = [[] for _ in range(N)]
    for _ in range(STEPS):
        payloads = np.zeros((N, K, 4), np.int32)
        n_new = np.full((N,), K, np.int32)
        for lane in range(N):
            for k in range(K):
                op = rng.integers(1, 4)
                slot = rng.integers(0, 8)
                value = int(rng.integers(0, 100))
                expected = int(rng.integers(0, 100)) if op == 3 else 0
                payloads[lane, k] = (op, slot, value, expected)
                lane_cmds[lane].append((op, slot, value, expected))
        eng.step(jnp.asarray(n_new), jnp.asarray(payloads))
    # drain the pipeline (no new commands; commit/apply catch up)
    for _ in range(4):
        eng.step(jnp.zeros((N,), jnp.int32),
                 jnp.zeros((N, K, 4), jnp.int32))
    eng.block_until_ready()
    mac = np.asarray(eng.state.mac)          # [N, P, S]
    for lane in range(N):
        want = host_fold(lane_cmds[lane])
        for member in range(3):
            got = mac[lane, member].tolist()
            assert got == want, (lane, member, got, want)


def test_same_machine_on_classic_path():
    router = LocalRouter()
    nodes = [RaNode(f"gn{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"g{i}", f"gn{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("regs", lambda: RegisterMachine(n_slots=4),
                             sids, router=router)
        leader = await_leader(router, sids)
        assert ra_tpu.process_command(
            leader, ("put", 1, 5), router=router).reply == 0
        assert ra_tpu.process_command(
            leader, ("add", 1, 2), router=router).reply == 7
        assert ra_tpu.process_command(
            leader, ("cas", 1, 7, 42), router=router).reply == 1
        res = ra_tpu.consistent_query(leader, query_registers,
                                      router=router)
        assert res.reply == [0, 42, 0, 0]
    finally:
        for n in nodes:
            n.stop()


def test_malformed_commands_encode_as_noop():
    """Bad client input must not crash the replicated apply fold: wrong
    arity or non-int fields encode as noop."""
    m = RegisterMachine(n_slots=4)
    for bad in (("cas", 1, 5), ("put", "a", 1), ("add",), ("put", 0, 1, 2),
                "put", 7, None, ("frobnicate", 1, 2), (),
                ("put", 0, 2**31), ("add", 0, -2**40)):
        enc = np.asarray(m.encode_command(bad))
        assert enc.tolist() == [0, 0, 0, 0], bad


@pytest.mark.parametrize("seed", [3, 11, 59])
def test_batch_apply_matches_sequential_fold(seed):
    """jit_apply_batch == an in-order masked jit_apply fold on BOTH
    internal paths: the cas-free fast path (last-put + subsequent adds
    per slot, incl. out-of-range slots that clip and int32 wrap) and
    the lax.cond fallback scan once a cas appears in the window."""
    rng = np.random.default_rng(seed)
    S, A, N = 4, 7, 5
    m = RegisterMachine(n_slots=S)
    state = m.jit_init(N)
    for i in range(4):   # warmup so slots hold values
        cmd = np.zeros((N, 4), np.int32)
        cmd[:, 0] = rng.integers(1, 3, N)
        cmd[:, 1] = rng.integers(0, S, N)
        cmd[:, 2] = rng.integers(-5, 50, N)
        state, _ = m.jit_apply({"index": i, "term": 1},
                               jnp.asarray(cmd), state)

    for hi_op, label in ((3, "fast"), (4, "with-cas")):
        cmds = np.zeros((N, A, 4), np.int32)
        cmds[..., 0] = rng.integers(0, hi_op, size=(N, A))
        cmds[..., 1] = rng.integers(-1, S + 1, size=(N, A))  # clips
        cmds[..., 2] = rng.integers(-10, 50, size=(N, A))
        cmds[..., 3] = rng.integers(0, 50, size=(N, A))
        # wrap coverage: one giant add per lane in the fast window
        if hi_op == 3:
            cmds[:, 2, 0] = 2
            cmds[:, 2, 2] = 2**31 - 3
        mask = rng.random((N, A)) < 0.8
        mask[0, :] = True
        cmds_j = jnp.asarray(cmds)
        mask_j = jnp.asarray(mask)
        idx = jnp.broadcast_to(jnp.arange(A, dtype=jnp.int32), (N, A))
        got = m.jit_apply_batch({"index": idx, "term": jnp.int32(1)},
                                cmds_j, mask_j, state)
        want = state
        for i in range(A):
            new, _ = m.jit_apply({"index": idx[:, i], "term": 1},
                                 cmds_j[:, i], want)
            want = jnp.where(mask_j[:, i][..., None], new, want)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=label)
        state = want
