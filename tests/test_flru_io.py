"""Flru, the open-segment fd cap, and io metrics (the reference's
ra_flru.erl, ra_log_reader open_segments, and ra_file_handle roles)."""

from ra_tpu.core.types import Entry, ServerConfig, ServerId
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.native import IO
from ra_tpu.utils.flru import Flru


def test_flru_eviction_order_and_handler():
    evicted = []
    lru = Flru(3, on_evict=lambda k, v: evicted.append(k))
    for k in "abc":
        lru.touch(k, k.upper())
    lru.touch("a", "A")          # refresh: a is now MRU
    lru.touch("d", "D")          # evicts b (the LRU)
    assert evicted == ["b"]
    assert "a" in lru and "b" not in lru
    lru.touch("e", "E")          # evicts c
    assert evicted == ["b", "c"]
    assert len(lru) == 3


def test_flru_pop_skips_handler_and_evict_all():
    evicted = []
    lru = Flru(4, on_evict=lambda k, v: evicted.append(k))
    for k in "abcd":
        lru.touch(k, k)
    assert lru.pop("b") == "b"
    assert evicted == []
    lru.evict_all()
    assert sorted(evicted) == ["a", "c", "d"]
    assert len(lru) == 0


def _mk_log(system, uid):
    cfg = ServerConfig(server_id=ServerId(uid, "n1"), uid=uid,
                       cluster_name="flru",
                       initial_members=(ServerId(uid, "n1"),),
                       machine=SimpleMachine(lambda c, s: s, 0))
    return system.log_factory(cfg)


def _settle(system, log):
    system.wal.flush()
    system.segment_writer.await_idle()
    for evt in log.take_events():
        log.handle_written(evt)


def test_open_segment_fds_are_capped(tmp_path):
    from ra_tpu import RaSystem
    from ra_tpu.log.durable import MAX_OPEN_SEGMENTS

    system = RaSystem(str(tmp_path / "d"), segment_max_count=8)
    log = _mk_log(system, "uid_cap")
    try:
        # 96 entries over 8-entry segments -> 12 segment files
        for i in range(1, 97):
            log.write([Entry(i, 1, f"e{i}")])
            if i % 8 == 0:
                system.wal.rollover()
                _settle(system, log)
        _settle(system, log)
        assert len(log._segments) >= 10
        open_fds = sum(1 for s in log._segments if s.fd is not None)
        assert open_fds <= MAX_OPEN_SEGMENTS
        # reads across ALL segments still work (evicted ones reopen),
        # and the cap holds afterwards
        for i in range(1, 97):
            ent = log.fetch(i)
            assert ent is not None and ent.command == f"e{i}"
        open_fds = sum(1 for s in log._segments if s.fd is not None)
        assert open_fds <= MAX_OPEN_SEGMENTS
    finally:
        system.close()


def test_reopen_after_restart_respects_cap(tmp_path):
    from ra_tpu import RaSystem
    from ra_tpu.log.durable import MAX_OPEN_SEGMENTS

    data = str(tmp_path / "d2")
    system = RaSystem(data, segment_max_count=8)
    log = _mk_log(system, "uid_cap2")
    for i in range(1, 81):
        log.write([Entry(i, 1, f"e{i}")])
        if i % 8 == 0:
            system.wal.rollover()
            _settle(system, log)
    _settle(system, log)
    system.close()
    system2 = RaSystem(data, segment_max_count=8)
    log2 = _mk_log(system2, "uid_cap2")
    try:
        assert log2.last_index_term().index == 80
        open_fds = sum(1 for s in log2._segments if s.fd is not None)
        assert open_fds <= MAX_OPEN_SEGMENTS
        assert log2.fetch(1).command == "e1"
    finally:
        system2.close()


def test_io_stats_observe_traffic(tmp_path):
    from ra_tpu import RaSystem

    before = IO.stats()
    system = RaSystem(str(tmp_path / "d3"))
    log = _mk_log(system, "uid_io")
    try:
        log.write([Entry(i, 1, b"x" * 64) for i in range(1, 33)])
        _settle(system, log)
        after = IO.stats()
        assert after["writes"] > before["writes"]
        assert after["write_bytes"] > before["write_bytes"]
        assert after["syncs"] > before["syncs"]
        assert set(after) == {"reads", "read_bytes", "writes",
                              "write_bytes", "syncs", "opens"}
    finally:
        system.close()


def test_overview_exposes_io(tmp_path):
    import ra_tpu
    from ra_tpu.node import LocalRouter

    router = LocalRouter()
    ov = ra_tpu.overview(router=router)
    assert "writes" in ov["io"]
    assert ov["nodes"] == {}
