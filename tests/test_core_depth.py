"""Core-server depth suite — the ra_server_SUITE cases not yet covered
by test_core_elections / test_core_replication
(/root/reference/test/ra_server_SUITE.erl): unknown-peer hygiene, stale
reply handling, candidate/leader RPC edge cases, snapshot-install
interruptions and stale installs, membership (leave/rejoin/promote,
leader removal), recovery of cluster changes, and the heartbeat state
matrix across raft states.
"""
import pickle
import zlib

from harness import SimCluster

from ra_tpu.core.server import RaServer
from ra_tpu.core.types import (
    AppendEntriesReply,
    AppendEntriesRpc,
    ClusterChangeCommand,
    CommandEvent,
    ElectionTimeout,
    Entry,
    HeartbeatReply,
    HeartbeatRpc,
    IdxTerm,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    JoinCommand,
    LeaveCommand,
    Membership,
    PreVoteRpc,
    RequestVoteRpc,
    RequestVoteResult,
    SendRpc,
    ServerConfig,
    ServerId,
    SnapshotMeta,
    UserCommand,
)

UNKNOWN = ServerId("ghost", "nodeX")


# -- unknown-peer hygiene ---------------------------------------------------

def test_aer_reply_from_unknown_peer_ignored():
    """append_entries_reply_no_success_from_unknown_peer: replies from
    peers outside the cluster must not touch any state."""
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    leader = c.servers[s1]
    commit0 = leader.commit_index
    matches0 = {pid: p.match_index for pid, p in leader.cluster.items()}
    for success in (True, False):
        effs = leader.handle(AppendEntriesReply(
            term=leader.current_term, success=success, next_index=99,
            last_index=98, last_term=leader.current_term, from_=UNKNOWN))
        assert effs == []
    assert leader.commit_index == commit0
    assert {pid: p.match_index
            for pid, p in leader.cluster.items()} == matches0


def test_leader_does_not_abdicate_to_unknown_peer():
    """A higher-term vote request from outside the cluster is dropped:
    the leader neither adopts the term nor steps down."""
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    leader = c.servers[s1]
    term0 = leader.current_term
    effs = leader.handle(RequestVoteRpc(
        term=term0 + 5, candidate_id=UNKNOWN,
        last_log_index=100, last_log_term=term0 + 5))
    assert effs == []
    assert leader.raft_state.value == "leader"
    assert leader.current_term == term0
    effs = leader.handle(PreVoteRpc(
        term=term0 + 5, token=object(), candidate_id=UNKNOWN, version=1,
        machine_version=0, last_log_index=100, last_log_term=term0 + 5))
    assert effs == []
    assert leader.raft_state.value == "leader"


def test_leader_denies_same_term_vote_and_reasserts_on_pre_vote():
    """request_vote_rpc_with_lower_term + leader_receives_pre_vote: a
    known peer's same-term vote request is denied; a same-term pre-vote
    makes the leader re-assert leadership with fresh AERs."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    effs = leader.handle(RequestVoteRpc(
        term=leader.current_term, candidate_id=s2,
        last_log_index=0, last_log_term=0))
    denies = [e.msg for e in effs if isinstance(e, SendRpc)]
    assert denies and isinstance(denies[0], RequestVoteResult)
    assert not denies[0].vote_granted
    effs = leader.handle(PreVoteRpc(
        term=leader.current_term, token=object(), candidate_id=s2,
        version=1, machine_version=0, last_log_index=0, last_log_term=0))
    aers = [e.msg for e in effs if isinstance(e, SendRpc)
            and isinstance(e.msg, AppendEntriesRpc)]
    assert len(aers) == 2  # leadership enforced toward both peers
    assert leader.raft_state.value == "leader"


# -- stale replies ----------------------------------------------------------

def test_stale_success_reply_does_not_regress_match():
    """leader_received_append_entries_reply_with_stale_last_index: a
    success reply older than the peer's recorded match is a no-regress
    max() merge."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    for v in (1, 2, 3):
        c.command(s1, v)
    leader = c.servers[s1]
    match0 = leader.cluster[s2].match_index
    assert match0 >= 4
    leader.handle(AppendEntriesReply(
        term=leader.current_term, success=True, next_index=2,
        last_index=1, last_term=1, from_=s2))
    assert leader.cluster[s2].match_index == match0
    assert leader.cluster[s2].next_index >= match0 + 1


def test_candidate_steps_down_on_current_term_aer():
    """candidate_handles_append_entries_rpc: an AER at the candidate's
    own term proves a leader exists — revert to follower, process it."""
    from ra_tpu.core.types import RaftState
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    srv2 = c.servers[s2]
    term = leader.current_term
    srv2.current_term = term
    srv2.raft_state = RaftState.CANDIDATE
    effs = srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=0, prev_log_term=0,
        leader_commit=0, entries=()))
    assert srv2.raft_state.value in ("follower", "await_condition")
    assert srv2.current_term == term


# -- snapshot installs ------------------------------------------------------

def snap_meta(idx, term, cluster_ids, mv=0):
    return SnapshotMeta(index=idx, term=term,
                        cluster=tuple((sid, Membership.VOTER)
                                      for sid in cluster_ids),
                        machine_version=mv)


def test_follower_stale_snapshot_confirms_progress():
    """follower_receives_stale_snapshot: an install at or below the
    follower's applied index is answered with its own progress, no state
    change."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    for v in (1, 2, 3):
        c.command(s1, v)
    srv2 = c.servers[s2]
    last = srv2.log.last_index_term()
    effs = srv2.handle(InstallSnapshotRpc(
        term=srv2.current_term, leader_id=s1,
        meta=snap_meta(1, 1, c.ids), chunk_number=1, chunk_flag="last",
        data=b"", token="tkn"))
    results = [e.msg for e in effs if isinstance(e, SendRpc)]
    assert results and isinstance(results[0], InstallSnapshotResult)
    assert results[0].last_index == last.index
    assert results[0].token == "tkn"
    assert srv2.raft_state.value == "follower"
    assert srv2.log.last_index_term() == last


def test_receive_snapshot_interrupted_by_aer():
    """receive_snapshot_new_leader_aer: an AER at >= term aborts the
    in-flight chunk stream and the entries are processed as follower."""
    c = SimCluster(3, snapshot_chunk_size=4)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    meta = snap_meta(10, 1, c.ids)
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=meta, chunk_number=1,
        chunk_flag="next", data=b"abcd", token="t1"))
    # NextEvent(rpc) re-enters in receive_snapshot and acks the chunk
    assert srv3.raft_state.value == "receive_snapshot" or any(
        hasattr(e, "event") for e in effs)
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "receive_snapshot"
    entries = tuple(Entry(i, 2, UserCommand(i)) for i in range(1, 4))
    effs = srv3.handle(AppendEntriesRpc(
        term=2, leader_id=s2, prev_log_index=0, prev_log_term=0,
        leader_commit=3, entries=entries))
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "follower"
    assert srv3.log.last_index_term().index == 3
    assert srv3._accepting_snapshot is None


def test_snapshotted_follower_accepts_following_appends():
    """snapshotted_follower_received_append_entries: after a completed
    install, an AER whose prev point is the snapshot index appends."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    meta = snap_meta(10, 1, c.ids)
    data = srv3.log.snapshot_module.encode(55)
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=meta, chunk_number=1,
        chunk_flag="last", data=data, token="t2"))
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "follower"
    assert srv3.last_applied == 10
    assert srv3.machine_state == 55
    effs = srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s1, prev_log_index=10, prev_log_term=1,
        leader_commit=10, entries=(Entry(11, 1, UserCommand(7)),)))
    assert srv3.log.last_index_term().index == 11


def test_written_event_never_applies_stale_suffix():
    """Apply safety (found by the interleaving fuzzer): commit_index is
    optimistically set to leader_commit BEFORE the AER consistency check
    (ra_server.erl:1047-1048), so after a FAILED check it can cover a
    stale uncommitted suffix of an older term.  A later WAL confirm for
    that suffix must not trigger an apply — applying is only safe from
    the validated AER path (the reference's follower written-event
    clause only replies, :1183-1192)."""
    from ra_tpu.core.types import WrittenEvent

    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    # term-1 leader s2 replicates 1..3 but only 1..2 commit
    srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s2, prev_log_index=0, prev_log_term=0,
        leader_commit=2,
        entries=(Entry(1, 1, UserCommand(10)),
                 Entry(2, 1, UserCommand(20)),
                 Entry(3, 1, UserCommand(999)))))
    assert srv3.last_applied == 2
    assert srv3.machine_state == 30
    # new term-2 leader s1 (its own log: 1..2@t1 then 3..4@t2, commit 4)
    # sends an AER whose prev point exposes the conflict: the check
    # fails, but commit_index has already been bumped to 4
    srv3.handle(AppendEntriesRpc(
        term=2, leader_id=s1, prev_log_index=3, prev_log_term=2,
        leader_commit=4, entries=()))
    assert srv3.raft_state.value == "await_condition"
    assert srv3.commit_index == 4          # the optimistic bump
    # the catch-up condition times out; back to follower, stale tail
    # still in place (repair has not arrived yet)
    srv3.handle(ElectionTimeout())
    assert srv3.raft_state.value == "follower"
    assert srv3.log.last_index_term() == (3, 1)
    # a late WAL confirm for the stale suffix arrives: it must NOT be
    # applied — entry 3@t1 was never committed by anyone
    srv3.handle(WrittenEvent(1, 3, 1))
    assert srv3.last_applied == 2, "stale uncommitted suffix applied!"
    assert srv3.machine_state == 30
    # the repair AER overwrites the suffix; only then does apply resume
    srv3.handle(AppendEntriesRpc(
        term=2, leader_id=s1, prev_log_index=2, prev_log_term=1,
        leader_commit=4,
        entries=(Entry(3, 2, UserCommand(300)),
                 Entry(4, 2, UserCommand(400)))))
    assert srv3.last_applied == 4
    assert srv3.machine_state == 30 + 300 + 400


def test_leader_ignores_success_reply_with_mismatched_term():
    """Companion to the stale-suffix apply fix: a success reply whose
    confirmed (last_index, last_term) is NOT the leader's own entry —
    the written-event reply of a follower still holding a deposed
    leader's suffix — must never advance match, or a divergent entry
    enters the commit median."""
    c = SimCluster(3)
    s1, s2, _s3 = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    term = leader.current_term
    c.isolate(s2)
    c.command(s1, 5)                       # entry 2@term, s2 cut off
    match0 = leader.cluster[s2].match_index
    assert match0 < 2
    # forged/stale confirm: s2 claims a durable entry 2 at a WRONG term
    leader.handle(AppendEntriesReply(
        term=term, success=True, next_index=3, last_index=2,
        last_term=term + 7, from_=s2))
    assert leader.cluster[s2].match_index == match0, \
        "unverified tail entered the match fold"
    # a truthful confirm for the same index advances normally
    leader.handle(AppendEntriesReply(
        term=term, success=True, next_index=3, last_index=2,
        last_term=term, from_=s2))
    assert leader.cluster[s2].match_index == 2


def test_corrupt_chunk_aborts_accept(tmp_path):
    """abort_accept: a chunk failing its crc aborts the stream — back to
    follower, own progress confirmed, partial state discarded."""
    import zlib

    c = SimCluster(3, snapshot_chunk_size=4)
    s1, _s2, s3 = c.ids
    srv3 = c.servers[s3]
    meta = snap_meta(10, 1, c.ids)
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=meta, chunk_number=1,
        chunk_flag="next", data=b"abcd",
        chunk_crc=zlib.crc32(b"abcd"), token="t9"))
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "receive_snapshot"
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=meta, chunk_number=2,
        chunk_flag="last", data=b"efgh",
        chunk_crc=zlib.crc32(b"CORRUPT"), token="t9"))
    assert srv3.raft_state.value == "follower"
    assert srv3._accepting_snapshot is None
    assert srv3.log.snapshot_index_term().index == 0
    results = [e.msg for e in effs if isinstance(e, SendRpc)
               and isinstance(e.msg, InstallSnapshotResult)]
    assert results and results[0].last_index == \
        srv3.log.last_index_term().index


def test_snapshot_install_recovers_voter_status():
    """init_recover_voter_status: the installed snapshot's cluster
    carries membership — a member listed as nonvoter must behave as one
    (no election timeouts granted to itself)."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    srv3 = c.servers[s3]
    cluster = ((s1, Membership.VOTER), (s2, Membership.VOTER),
               (s3, Membership.NON_VOTER))
    meta = SnapshotMeta(index=10, term=1, cluster=cluster,
                        machine_version=0)
    data = srv3.log.snapshot_module.encode(99)
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=meta, chunk_number=1,
        chunk_flag="last", data=data, token="tv"))
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "follower"
    assert not srv3.is_voter()
    # a nonvoter ignores its election timeout (ra_server.erl:1307-1315)
    effs = srv3.handle(ElectionTimeout())
    assert effs == []
    assert srv3.raft_state.value == "follower"


def test_force_shrink_aborts_inflight_snapshot_accept():
    """ForceMemberChange out of RECEIVE_SNAPSHOT must run the state's
    teardown: the partial accept stream is aborted before the shrink."""
    from ra_tpu.core.types import ForceMemberChangeEvent

    c = SimCluster(3, snapshot_chunk_size=4)
    s1, _s2, s3 = c.ids
    srv3 = c.servers[s3]
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=snap_meta(10, 1, c.ids),
        chunk_number=1, chunk_flag="next", data=b"abcd", token="tf"))
    c._process_effects(s3, effs)
    assert srv3.raft_state.value == "receive_snapshot"
    c.handle(s3, ForceMemberChangeEvent())
    c.run()
    assert srv3._accepting_snapshot is None
    assert srv3.raft_state.value == "leader"        # cluster of one
    assert set(srv3.cluster) == {s3}


def test_force_shrink_on_leader_tears_down_leader_state():
    """ForceMemberChange on a LEADER drops leader-only bookkeeping
    before the shrink (the reference re-dispatches through
    leader->follower, ra_server.erl:830-831): a consistent query
    waiting on heartbeats is answered not_leader instead of leaking,
    and an in-flight snapshot-send token is invalidated."""
    from ra_tpu.core.types import (ConsistentQueryEvent, ErrorResult,
                                   ForceMemberChangeEvent, PeerStatus,
                                   Reply)

    c = SimCluster(3)
    s1, s2, _s3 = c.ids
    c.elect(s1)
    c.run()
    srv1 = c.servers[s1]
    assert srv1.raft_state.value == "leader"
    # park a consistent query: handle directly (heartbeats unanswered)
    srv1.handle(ConsistentQueryEvent(lambda st: st, from_="q1"))
    assert srv1.queries_waiting_heartbeats or \
        srv1.pending_consistent_queries
    srv1.cluster[s2].snapshot_sender = "tok"
    srv1.cluster[s2].status = PeerStatus.SENDING_SNAPSHOT
    effs = srv1.handle(ForceMemberChangeEvent(from_="op"))
    not_leader = [e for e in effs if isinstance(e, Reply) and
                  isinstance(e.msg, ErrorResult) and
                  e.msg.reason == "not_leader"]
    assert [e.to for e in not_leader] == ["q1"]
    assert srv1.queries_waiting_heartbeats == []
    assert srv1.pending_consistent_queries == []
    assert all(p.snapshot_sender is None
               for p in srv1.cluster.values())
    # quorum of one: the shrink self-elects straight back to leader
    assert srv1.raft_state.value == "leader"
    assert set(srv1.cluster) == {s1}


def test_deposed_leader_answers_parked_queries_not_leader():
    """A leader deposed by a higher-term AER (the normal involuntary
    step-down) must not leak its parked consistent queries or keep
    stale snapshot-send tokens (_become_follower teardown)."""
    from ra_tpu.core.types import (ConsistentQueryEvent, ErrorResult,
                                   Reply)

    c = SimCluster(3)
    s1, s2, _s3 = c.ids
    c.elect(s1)
    c.run()
    srv1 = c.servers[s1]
    assert srv1.raft_state.value == "leader"
    srv1.handle(ConsistentQueryEvent(lambda st: st, from_="q1"))
    assert srv1.queries_waiting_heartbeats or \
        srv1.pending_consistent_queries
    srv1.cluster[s2].snapshot_sender = "tok"
    effs = srv1.handle(AppendEntriesRpc(
        term=srv1.current_term + 5, leader_id=s2, prev_log_index=0,
        prev_log_term=0, leader_commit=0, entries=()))
    not_leader = [e for e in effs if isinstance(e, Reply) and
                  isinstance(e.msg, ErrorResult) and
                  e.msg.reason == "not_leader"]
    assert [e.to for e in not_leader] == ["q1"]
    assert srv1.raft_state.value == "follower"
    assert srv1.queries_waiting_heartbeats == []
    assert srv1.pending_consistent_queries == []
    assert all(p.snapshot_sender is None for p in srv1.cluster.values())


def test_parked_leader_gates_stale_and_foreign_vote_requests():
    """A leader parked in await_condition (transfer/wal_down) applies
    the active leader's vote-request gates: same/lower-term requests
    are denied in place, non-member candidates are ignored, and only a
    genuine higher-term member candidacy deposes it (with teardown)."""
    from ra_tpu.core.types import (ConsistentQueryEvent, ErrorResult,
                                   Reply, TransferLeadershipEvent)

    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.run()
    srv1 = c.servers[s1]
    srv1.handle(ConsistentQueryEvent(lambda st: st, from_="q1"))
    srv1.handle(TransferLeadershipEvent(s2))
    assert srv1.raft_state.value == "await_condition"
    term = srv1.current_term
    # stale same-term candidacy from a member: denied, still parked
    effs = srv1.handle(RequestVoteRpc(term=term, candidate_id=s3,
                                      last_log_index=99, last_log_term=99))
    assert srv1.raft_state.value == "await_condition"
    assert any(isinstance(e, SendRpc) and
               not e.msg.vote_granted for e in effs)
    # higher-term candidacy from a NON-member: ignored entirely
    stranger = ServerId("sX", "nX")
    effs = srv1.handle(RequestVoteRpc(term=term + 1, candidate_id=stranger,
                                      last_log_index=99, last_log_term=99))
    assert effs == []
    assert srv1.raft_state.value == "await_condition"
    assert srv1.queries_waiting_heartbeats or \
        srv1.pending_consistent_queries
    # higher-term member candidacy: genuine step-down with teardown
    effs = srv1.handle(RequestVoteRpc(term=term + 2, candidate_id=s3,
                                      last_log_index=99, last_log_term=99))
    assert srv1.raft_state.value == "follower"
    not_leader = [e for e in effs if isinstance(e, Reply) and
                  isinstance(e.msg, ErrorResult) and
                  e.msg.reason == "not_leader"]
    assert [e.to for e in not_leader] == ["q1"]


def test_cluster_spec_at_cache_matches_uncached_scan():
    """_cluster_spec_at's scan memo is an optimization only: with two
    membership changes in flight above the queried index (forcing the
    downward log scan), a cache-warm answer must equal a cold one for
    every index in the log."""
    c = SimCluster(4, initial_count=3)
    s1, _s2, _s3, s4 = c.ids
    c.elect(s1)
    c.run()
    srv1 = c.servers[s1]
    for i in range(6):
        c.command(s1, i)
        c.run()
    c.handle(s1, CommandEvent(JoinCommand(s4)))
    c.run()
    for i in range(4):
        c.command(s1, i)
        c.run()
    c.handle(s1, CommandEvent(LeaveCommand(s4)))
    c.run()
    last = srv1.last_idx_term().index
    for idx in range(srv1.log.first_index(), last + 1):
        srv1._spec_cache = None
        cold = srv1._cluster_spec_at(idx)
        warm = srv1._cluster_spec_at(idx)       # memo from the cold call
        assert warm == cold, idx
    # ascending queries with a warm memo (the release-cursor pattern)
    srv1._spec_cache = None
    for idx in range(srv1.log.first_index(), last + 1):
        got = srv1._cluster_spec_at(idx)
        srv1._spec_cache, saved = None, srv1._spec_cache
        assert got == srv1._cluster_spec_at(idx), idx
        srv1._spec_cache = saved


def test_force_shrink_refused_while_parked_in_await_condition():
    """ForceMemberChange in AWAIT_CONDITION is refused (the reference
    has no clause for it there): exiting a park would race the parked
    condition — under wal_down the forced append itself would fail
    mid-mutation — so the caller gets unsupported_call and state is
    untouched."""
    from ra_tpu.core.types import ErrorResult, ForceMemberChangeEvent, Reply

    c = SimCluster(3)
    s1, _s2, s3 = c.ids
    srv3 = c.servers[s3]
    # gap AER parks the follower in await_condition
    srv3.handle(AppendEntriesRpc(
        term=1, leader_id=s1, prev_log_index=10, prev_log_term=1,
        leader_commit=10, entries=(Entry(11, 1, UserCommand(1)),)))
    assert srv3.raft_state.value == "await_condition"
    effs = srv3.handle(ForceMemberChangeEvent(from_="op1"))
    replies = [e for e in effs if isinstance(e, Reply)]
    assert replies and isinstance(replies[0].msg, ErrorResult)
    assert replies[0].msg.reason == "unsupported_call"
    assert srv3.raft_state.value == "await_condition"
    assert set(srv3.cluster) == {s1, _s2, s3}


# -- membership -------------------------------------------------------------

def test_leader_steps_down_when_removed():
    """leader_is_removed: committing its own '$ra_leave' terminates the
    leader once the rest of the cluster has the change."""
    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    leader = c.servers[s1]
    c.handle(s1, CommandEvent(LeaveCommand(s1)))
    c.run()
    assert s1 not in leader.cluster
    assert leader.raft_state.value in ("stop", "terminating_leader")


def test_rejoined_promotable_member_is_auto_promoted():
    """append_entries_reply_success_promotes_nonvoter +
    leader_server_join_nonvoter: a promotable nonvoter counts toward no
    quorum until its match reaches the promote target, then the leader
    appends the promotion cluster change."""
    c = SimCluster(4)
    s1, s2, s3, s4 = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    c.handle(s1, CommandEvent(LeaveCommand(s4)))
    c.run()
    assert s4 not in leader.cluster
    for v in (1, 2):
        c.command(s1, v)
    # feed the join directly (no pump yet): the cluster change takes
    # effect on append, so the nonvoter state is observable here
    effs = leader.handle(CommandEvent(JoinCommand(
        s4, membership=Membership.PROMOTABLE)))
    assert leader.cluster[s4].membership == Membership.PROMOTABLE
    assert leader.cluster[s4].promote_target > 0
    c._process_effects(s1, effs)
    c._drain_log_events(s1)
    c.run()   # deliveries catch s4 up; the auto-promotion change lands
    for v in (3, 4):
        c.command(s1, v)
    c.run()
    assert leader.cluster[s4].membership == Membership.VOTER
    states = c.machine_states()
    assert states[s4] == states[s1] == 1 + 2 + 3 + 4


def test_recover_restores_cluster_changes():
    """recover_restores_cluster_changes: a restarted server replays the
    log and ends with the changed membership, not the seed config."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.handle(s1, CommandEvent(LeaveCommand(s3)))
    c.run()
    leader = c.servers[s1]
    assert set(leader.cluster) == {s1, s2}
    # rebuild the leader's server over the SAME log object
    cfg = ServerConfig(server_id=s1, uid="uid_s1_rebuilt",
                      cluster_name="simcluster",
                      initial_members=tuple(c.ids),
                      machine=leader.cfg.machine)
    srv = RaServer(cfg, leader.log)
    srv.recover()
    assert set(srv.cluster) == {s1, s2}, \
        "recovery must re-apply the committed '$ra_leave'"


# -- heartbeat state matrix -------------------------------------------------

def test_follower_heartbeat_updates_query_index_and_replies():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    srv2 = c.servers[s2]
    term = srv2.current_term
    effs = srv2.handle(HeartbeatRpc(query_index=7, term=term,
                                    leader_id=s1))
    assert srv2.query_index >= 7
    replies = [e.msg for e in effs if isinstance(e, SendRpc)]
    assert replies and isinstance(replies[0], HeartbeatReply)
    assert replies[0].query_index >= 7
    assert replies[0].term == term


def test_follower_heartbeat_lower_term_still_replies_current():
    """A stale leader's heartbeat gets a reply carrying OUR term so it
    steps down (leader_heartbeat_reply_higher_term on its side)."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    srv2 = c.servers[s2]
    term = srv2.current_term
    qi0 = srv2.query_index
    effs = srv2.handle(HeartbeatRpc(query_index=99, term=term - 1,
                                    leader_id=s1))
    assert srv2.query_index == qi0          # stale rpc: no qidx adoption
    replies = [e.msg for e in effs if isinstance(e, SendRpc)]
    assert replies and replies[0].term == term


def test_leader_steps_down_on_higher_term_heartbeat_reply():
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    term = leader.current_term
    leader.handle(HeartbeatReply(query_index=0, term=term + 3, from_=s2))
    assert leader.raft_state.value == "follower"
    assert leader.current_term == term + 3


def test_candidate_heartbeat_same_term_steps_down():
    """candidate_heartbeat: a heartbeat at the candidate's term proves a
    live leader; the candidate reverts and answers it."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    srv2 = c.servers[s2]
    srv2.current_term = leader.current_term
    srv2.raft_state = type(srv2.raft_state).CANDIDATE
    effs = srv2.handle(HeartbeatRpc(query_index=3,
                                    term=leader.current_term,
                                    leader_id=s1))
    c._process_effects(s2, effs)
    assert srv2.raft_state.value == "follower"
    assert srv2.query_index >= 3


def test_pre_vote_state_heartbeat_steps_back_to_follower():
    """pre_vote_heartbeat: same-or-higher-term heartbeat during a
    pre-vote round cancels the candidacy."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    leader = c.servers[s1]
    srv2 = c.servers[s2]
    c.isolate(s2)
    srv2.handle(ElectionTimeout())      # enters pre_vote
    assert srv2.raft_state.value == "pre_vote"
    c.heal()
    effs = srv2.handle(HeartbeatRpc(query_index=1,
                                    term=leader.current_term,
                                    leader_id=s1))
    c._process_effects(s2, effs)
    assert srv2.raft_state.value == "follower"


# -- consistent queries (ra_SUITE consistent_query_* family) ----------------

def test_consistent_query_blocked_in_minority():
    """consistent_query_minority: a leader cut off from its majority
    must never answer a consistent query — the heartbeat quorum cannot
    certify its authority."""
    from ra_tpu.core.types import ConsistentQueryEvent, TickEvent

    c = SimCluster(3)
    s1 = c.ids[0]
    c.elect(s1)
    c.command(s1, 5)
    c.isolate(s1)
    c.handle(s1, ConsistentQueryEvent(lambda st: st, from_="qminor"))
    for _ in range(4):
        c.handle(s1, TickEvent())
        c.run()
    assert not any(r.to == "qminor" for _sid, r in c.replies), \
        "a minority leader answered a linearizable read"


def test_consistent_query_waits_for_new_leader_noop():
    """consistent_query_leader_change: a query registered with a brand
    new leader is held until its term-opening noop commits
    (pending_consistent_queries, ra_server.erl:3174-3190)."""
    from ra_tpu.core.types import (ConsistentQueryEvent, ElectionTimeout,
                                   TickEvent)

    c = SimCluster(3, auto_written=False)
    s1 = c.ids[0]
    c.handle(s1, ElectionTimeout())
    c.run()
    srv = c.servers[s1]
    assert srv.raft_state.value == "leader"
    assert not srv.cluster_change_permitted   # noop not yet committed
    c.handle(s1, ConsistentQueryEvent(lambda st: st, from_="qnoop"))
    c.run()
    assert not any(r.to == "qnoop" for _sid, r in c.replies)
    # the noop commits once WALs confirm; the pending query then fires
    for sid in c.ids:
        log = c.servers[sid].log
        last = log.last_index_term()
        log.release_written(1, last.index, last.term)
        c._drain_log_events(sid)
    c.run()
    for _ in range(3):
        c.handle(s1, TickEvent())
        c.run()
    got = [r for _sid, r in c.replies if r.to == "qnoop"]
    assert got, "query never answered after the noop committed"
    assert got[0].msg.reply == 0


def test_empty_aer_reset_never_truncates_committed_entries():
    """Found by the snapshot fuzz: a stale/pipelined empty AER can carry
    a prev point below the follower's commit index; the 'leader's log is
    shorter' reset must clamp at commit — committed entries are
    immutable."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    for v in (1, 2, 3, 4):
        c.command(s1, v)
    srv2 = c.servers[s2]
    assert srv2.commit_index >= 5
    tail0 = srv2.log.last_index_term().index
    srv2.handle(AppendEntriesRpc(
        term=srv2.current_term, leader_id=s1, prev_log_index=2,
        prev_log_term=srv2.log.fetch_term(2), leader_commit=5,
        entries=()))
    assert srv2.log.last_index_term().index >= srv2.commit_index
    assert srv2.log.last_index_term().index >= min(tail0,
                                                   srv2.commit_index)
    # entries at/below commit still present
    for i in range(1, srv2.commit_index + 1):
        assert srv2.log.fetch(i) is not None, i


def test_restorative_snapshot_install_accepted_at_applied_index():
    """A member whose durable tail fell behind its own applied index
    (crash-reverted log, surviving apply watermark) must accept an
    install AT its applied index instead of refusing it as stale —
    otherwise it wedges forever once the leader compacted the range."""
    c = SimCluster(3)
    s1, s2, _ = c.ids
    c.elect(s1)
    for v in (1, 2, 3, 4):
        c.command(s1, v)
    srv2 = c.servers[s2]
    la = srv2.last_applied
    assert la >= 5
    # crash-revert the durable tail below the applied watermark
    srv2.log._last_index = 2
    srv2.log._last_term = 1
    for k in [k for k in srv2.log._entries if k > 2]:
        del srv2.log._entries[k]
    assert srv2.log.last_index_term().index < la
    meta = snap_meta(la, srv2.current_term, c.ids)
    data = srv2.log.snapshot_module.encode(srv2.machine_state)
    effs = srv2.handle(InstallSnapshotRpc(
        term=srv2.current_term, leader_id=s1, meta=meta,
        chunk_number=1, chunk_flag="last", data=data, token="tr"))
    c._process_effects(s2, effs)
    assert srv2.raft_state.value == "follower"
    assert srv2.log.snapshot_index_term().index == la
    assert srv2.log.last_index_term().index == la   # tail restored
    assert srv2.last_applied == la
    # replication resumes above the snapshot
    nxt = la + 1
    effs = srv2.handle(AppendEntriesRpc(
        term=srv2.current_term, leader_id=s1, prev_log_index=la,
        prev_log_term=srv2.current_term, leader_commit=la,
        entries=(Entry(nxt, srv2.current_term, UserCommand(9)),)))
    assert srv2.log.last_index_term().index == nxt


def test_leader_install_rpc_higher_term_abdicates_known_peer_only():
    """leader_receives_install_snapshot_rpc (+ the unknown-peer guard,
    ra_server.erl:662-671): a higher-term install from a KNOWN member
    abdicates and re-dispatches; one from an unknown sender is ignored
    — abdicating to a stranger would let any forged packet depose a
    leader."""
    c = SimCluster(3)
    s1, s2, _s3 = c.ids
    c.elect(s1)
    c.run()
    srv1 = c.servers[s1]
    assert srv1.raft_state.value == "leader"
    term = srv1.current_term
    stranger = ServerId("zz", "zz")
    effs = srv1.handle(InstallSnapshotRpc(
        term=term + 5, leader_id=stranger, meta=snap_meta(9, term, c.ids),
        chunk_number=1, chunk_flag="last", data=b"", token="tu"))
    assert effs == []
    assert srv1.raft_state.value == "leader"
    assert srv1.current_term == term
    effs = srv1.handle(InstallSnapshotRpc(
        term=term + 5, leader_id=s2, meta=snap_meta(9, term, c.ids),
        chunk_number=1, chunk_flag="next", data=b"xx", token="tk"))
    assert srv1.raft_state.value != "leader"
    assert srv1.current_term == term + 5


def test_leader_ignores_lower_term_install_rpc():
    """'leader ignores lower term' (leader_receives_install_snapshot_rpc
    tail): no reply, no state change — unlike stale AERs, which are
    nacked."""
    c = SimCluster(3)
    s1, s2, _s3 = c.ids
    c.elect(s1)
    c.run()
    c.command(s1, 1)
    c.run()
    srv1 = c.servers[s1]
    term = srv1.current_term
    effs = srv1.handle(InstallSnapshotRpc(
        term=term - 1 if term > 1 else 0, leader_id=s2,
        meta=snap_meta(1, 0, c.ids),
        chunk_number=1, chunk_flag="last", data=b"", token="tl"))
    assert effs == []
    assert srv1.raft_state.value == "leader"
    assert srv1.current_term == term


def test_follower_refuses_snapshot_with_higher_machine_version():
    """follower_ignores_installs_snapshot_with_higher_machine_version:
    a snapshot whose machine version exceeds what this member can run
    is refused (it could not apply entries above it); the refusal
    reports the applied frontier so the leader resumes replication
    there instead of looping the install."""
    from ra_tpu.core.types import InstallSnapshotResult, SendRpc

    c = SimCluster(3)
    s1, _s2, s3 = c.ids
    srv3 = c.servers[s3]
    effs = srv3.handle(InstallSnapshotRpc(
        term=1, leader_id=s1, meta=snap_meta(10, 1, c.ids, mv=99),
        chunk_number=1, chunk_flag="last", data=b"", token="tv"))
    assert srv3.raft_state.value == "follower"      # never entered accept
    results = [e for e in effs if isinstance(e, SendRpc) and
               isinstance(e.msg, InstallSnapshotResult)]
    assert len(results) == 1
    assert results[0].msg.last_index == srv3.last_applied


def test_truncation_reverts_adopted_config_to_surviving_prefix():
    """The empty-AER shorter-log reset must revert the effective
    configuration when it truncates the change entry it came from —
    at truncation time, through every fallback level: previous_cluster,
    a rescan of the surviving prefix, and (with neither) the bootstrap
    config (soak seed 161122 + review's no-snapshot base case)."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.command(s1, 1)                       # idx 2 committed everywhere
    c.run()
    leader = c.servers[s1]
    srv2 = c.servers[s2]
    term = leader.current_term
    base_cit = srv2.cluster_index_term
    # feed s2 two uncommitted config changes above its applied frontier
    spec_a = tuple((sid, Membership.VOTER) for sid in (s1, s2))
    spec_b = tuple((sid, Membership.VOTER) for sid in (s1, s2, s3))
    tail = srv2.log.last_index_term()
    e_a = Entry(tail.index + 1, term, ClusterChangeCommand(spec_a))
    e_b = Entry(tail.index + 2, term, ClusterChangeCommand(spec_b))
    srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=tail.index,
        prev_log_term=tail.term, entries=(e_a, e_b),
        leader_commit=srv2.commit_index))
    assert srv2.cluster_index_term.index == e_b.index
    assert set(srv2.cluster) == {s1, s2, s3}
    # shorter-log reset truncates BOTH changes; no snapshot exists and
    # the surviving prefix (noop + user cmd) carries no change -> the
    # view must fall all the way back instead of keeping B's phantom
    srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=tail.index,
        prev_log_term=tail.term, entries=(),
        leader_commit=srv2.commit_index))
    assert srv2.log.last_index_term() == tail
    assert srv2.cluster_index_term.index <= tail.index
    assert srv2.cluster_index_term == base_cit or \
        srv2.cluster_index_term == IdxTerm(0, 0)
    assert set(srv2.cluster) == {s1, s2, s3}  # bootstrap = initial members
    assert srv2.previous_cluster is None
    # and a one-change rewind uses previous_cluster: adopt A then B,
    # truncate only B
    tail2 = srv2.log.last_index_term()
    e_a2 = Entry(tail2.index + 1, term, ClusterChangeCommand(spec_a))
    e_b2 = Entry(tail2.index + 2, term, ClusterChangeCommand(spec_b))
    srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=tail2.index,
        prev_log_term=tail2.term, entries=(e_a2, e_b2),
        leader_commit=srv2.commit_index))
    srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=e_a2.index,
        prev_log_term=term, entries=(),
        leader_commit=srv2.commit_index))
    assert srv2.cluster_index_term == IdxTerm(e_a2.index, term)
    assert set(srv2.cluster) == {s1, s2}


def test_snapshot_install_keeps_retained_newer_config():
    """A catch-up snapshot install (meta.index > last_applied) pins the
    config to the meta, but the log RETAINS its suffix above the
    snapshot — config changes there are NEWER than the meta and must
    stay in force (soak seed 181279: the pin regressed a server's view
    two changes back, and it later elected itself under the stale
    larger membership)."""
    c = SimCluster(3)
    s1, s2, s3 = c.ids
    c.elect(s1)
    c.run()
    srv2 = c.servers[s2]
    term = srv2.current_term
    tail = srv2.log.last_index_term()
    la0 = srv2.last_applied
    # feed s2 an UNCOMMITTED suffix carrying a config change
    spec_new = tuple((sid, Membership.VOTER) for sid in (s1, s2))
    e_cmd = Entry(tail.index + 1, term, UserCommand(7))
    e_chg = Entry(tail.index + 2, term, ClusterChangeCommand(spec_new))
    e_cmd2 = Entry(tail.index + 3, term, UserCommand(8))
    srv2.handle(AppendEntriesRpc(
        term=term, leader_id=s1, prev_log_index=tail.index,
        prev_log_term=tail.term, entries=(e_cmd, e_chg, e_cmd2),
        leader_commit=srv2.commit_index))
    assert set(srv2.cluster) == {s1, s2}
    assert srv2.last_applied == la0            # suffix uncommitted
    # catch-up install: snapshot lands between the applied frontier and
    # the change; the meta carries the OLD three-member config
    spec_old = tuple((sid, Membership.VOTER) for sid in (s1, s2, s3))
    meta = SnapshotMeta(index=e_cmd.index, term=term,
                        cluster=spec_old, machine_version=0)
    data = pickle.dumps(c.servers[s1].machine_state)
    srv2.handle(InstallSnapshotRpc(
        term=term, leader_id=s1, meta=meta, chunk_number=1,
        chunk_flag="last", data=data, chunk_crc=zlib.crc32(data)))
    # the install genuinely happened (not refused as stale)...
    assert srv2.log.snapshot_index_term().index == meta.index
    # ...the suffix above it is retained...
    assert srv2.log.last_index_term().index >= e_chg.index
    # ...and the retained change stays in force over the meta's config
    assert srv2.cluster_index_term == IdxTerm(e_chg.index, term)
    assert set(srv2.cluster) == {s1, s2}, \
        "install pinned the meta config over a retained newer change"
