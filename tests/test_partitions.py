"""Partition/nemesis suite — the reference's partitions_SUITE.erl run
against the in-process fabric: a 5-member fifo cluster under scripted
faults (partitions, heals, server restarts) with a continuous enqueuer
workload, asserting **no message loss and no duplicate applies** once the
cluster heals (partitions_SUITE.erl:29-57 + nemesis scripts).

The failure model matches the reference's inet_tcp_proxy carrier: links
silently drop in both directions while blocked; processes keep running.
"""
import threading
import time

import pytest

import ra_tpu
from ra_tpu.core.types import ServerId
from ra_tpu.models import FifoClient, FifoMachine
from ra_tpu.node import LocalRouter, RaNode

from nemesis import Nemesis, await_leader

N_MEMBERS = 5


@pytest.fixture
def fabric():
    router = LocalRouter()
    nodes = [RaNode(f"pn{i}", router=router) for i in range(1, N_MEMBERS + 1)]
    yield router, nodes
    router.heal()
    for n in nodes:
        n.stop()


def ids():
    return [ServerId(f"p{i}", f"pn{i}") for i in range(1, N_MEMBERS + 1)]


class Enqueuer:
    """Continuous pipelined-enqueue workload (test/enqueuer.erl): keeps
    enqueueing unique payloads until stopped; never gives up on a message
    — the client resends unacknowledged seqnos after leader changes."""

    def __init__(self, sids, router, tag="enq"):
        self.client = FifoClient(sids, router=router, tag=tag)
        self.sent: list = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _run(self):
        from ra_tpu.models import StopSending
        i = 0
        while not self._stop.is_set():
            payload = f"{self.client.tag}-{i}"
            try:
                self.client.enqueue(payload)
            except StopSending:
                # window full during a long partition: back off and
                # retry THE SAME payload — dying here would silently
                # shrink the workload the assertions cover
                self.client.resend()
                self._stop.wait(0.05)
                continue
            self.sent.append(payload)
            i += 1
            # periodic resend keeps progress through leader changes
            if i % 25 == 0:
                self.client.resend()
            time.sleep(0.005)

    def stop_and_flush(self, timeout=60.0):
        self._stop.set()
        self._thread.join(timeout=10.0)
        self.client.flush(timeout=timeout)
        return self.sent


def drain_all(sids, router, expect, timeout=30.0):
    """Dequeue until the queue is empty; returns the list of raw payloads
    (settled dequeues, so every message is consumed exactly once)."""
    client = FifoClient(sids, router=router, tag="drain")
    got = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            res = client.dequeue(settled=True)
        except TimeoutError:
            # a just-bounced cluster can be mid-election: retry within
            # the drain deadline instead of failing the no-loss check
            continue
        if res == ("dequeue", "empty"):
            if len(got) >= expect:
                break
            time.sleep(0.1)
            continue
        kind, (_header, raw) = res
        assert kind == "dequeue"
        got.append(raw)
    return got


def test_enq_drain_minority_partitioned_leader(fabric):
    """Partition the leader into a minority mid-stream: a new leader must
    emerge in the majority, the enqueuer must keep committing against it
    while the partition holds, and after heal every message must be
    present exactly once."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("part-q1", lambda: FifoMachine(), sids,
                         router=router, election_timeout_ms=100)
    leader = await_leader(router, sids)
    # steer the enqueuer at the majority side: a client pinned to the
    # minority leader would just stall for the partition's duration
    majority = [s for s in sids if s.node != leader.node]
    enq = Enqueuer(majority, router)
    enq.start()
    time.sleep(0.5)
    for other in majority:
        router.block(leader.node, other.node)
    # a majority-side leader must take over while the partition holds
    new_leader = await_leader(router, majority, timeout=10.0)
    assert new_leader != leader
    acked_at_takeover = len(enq.sent) - enq.client.pending_count()
    time.sleep(1.5)
    acked_later = len(enq.sent) - enq.client.pending_count()
    assert acked_later > acked_at_takeover, \
        "no commits landed under the majority leader during the partition"
    router.heal()
    time.sleep(1.0)
    sent = enq.stop_and_flush()
    got = drain_all(sids, router, expect=len(sent))
    assert sorted(got) == sorted(sent)          # no loss, no duplicates


def test_random_partition_schedule(fabric):
    """Several random partitions back to back (the reference's scripted
    nemesis): convergence + exactly-once delivery at the end."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("part-q2", lambda: FifoMachine(), sids,
                         router=router, election_timeout_ms=100)
    await_leader(router, sids)
    nem = Nemesis(router, nodes, seed=42)
    enq = Enqueuer(sids, router)
    enq.start()
    nem.run([
        ("part_random", 1.5),
        ("wait", 0.5),
        ("part_random", 1.5),
        ("wait", 0.5),
        ("part_random", 1.5),
        ("heal",),
        ("wait", 1.0),
    ])
    sent = enq.stop_and_flush()
    got = drain_all(sids, router, expect=len(sent))
    assert sorted(got) == sorted(sent)


def test_app_restart_under_load(tmp_path):
    """Restart servers (including the leader) while enqueuing
    ({app_restart, Servers}): restarted members rejoin, catch up, and the
    queue converges with no loss.  Servers run over durable RaSystem logs
    — a restart must come back with its log and term/voted_for intact, or
    acked-entry durability doesn't hold and the no-loss assertion is
    meaningless with 3 of 5 members bouncing."""
    from ra_tpu import RaSystem
    from ra_tpu.core.types import ServerConfig

    router = LocalRouter()
    sids = ids()
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = [RaNode(s.node, router=router,
                    log_factory=systems[s.node].log_factory) for s in sids]
    for sid in sids:
        router.nodes[sid.node].start_server(ServerConfig(
            server_id=sid, uid=f"uid_{sid.name}", cluster_name="part-q3",
            initial_members=tuple(sids), machine=FifoMachine(),
            election_timeout_ms=100))
    ra_tpu.trigger_election(sids[0], router)
    leader = await_leader(router, sids)
    nem = Nemesis(router, nodes, seed=7)
    enq = Enqueuer(sids, router)
    enq.start()
    time.sleep(0.5)
    followers = [s for s in sids if s != leader]
    try:
        nem.run([
            ("app_restart", followers[:2]),
            ("wait", 1.0),
            ("app_restart", [leader]),
            ("wait", 1.5),
        ])
        sent = enq.stop_and_flush()
        got = drain_all(sids, router, expect=len(sent))
        assert sorted(got) == sorted(sent)
    finally:
        for n in nodes:
            n.stop()
        for s in systems.values():
            s.close()


def test_two_enqueuers_through_partitions(fabric):
    """Two competing enqueuers through a partition round: per-enqueuer
    FIFO order must hold in the delivered stream and nothing is lost
    (partitions_SUITE's multi-publisher variant)."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("part-q4", lambda: FifoMachine(), sids,
                         router=router, election_timeout_ms=100)
    await_leader(router, sids)
    nem = Nemesis(router, nodes, seed=9)
    e1 = Enqueuer(sids, router, tag="alpha")
    e2 = Enqueuer(sids, router, tag="beta")
    e1.start()
    e2.start()
    nem.run([
        ("part_random", 1.5),
        ("wait", 1.0),
    ])
    sent1 = e1.stop_and_flush()
    sent2 = e2.stop_and_flush()
    got = drain_all(sids, router, expect=len(sent1) + len(sent2))
    assert sorted(got) == sorted(sent1 + sent2)
    # per-enqueuer order is preserved in the drain stream
    for tag, sent in (("alpha", sent1), ("beta", sent2)):
        stream = [g for g in got if g.startswith(tag)]
        assert stream == sent


def test_leader_in_minority_cannot_commit(fabric):
    """While the old leader sits in a minority island, commands sent to it
    must not be lost-and-acked: anything it acked before the partition is
    preserved; anything during must either fail or commit after heal."""
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("part-q5", lambda: FifoMachine(), sids,
                         router=router, election_timeout_ms=100)
    leader = await_leader(router, sids)
    client = FifoClient(sids, router=router, tag="strict")
    for i in range(10):
        client.enqueue_sync(f"pre-{i}")
    # cut the leader off
    others = [s.node for s in sids if s.node != leader.node]
    for o in others:
        router.block(leader.node, o)
    # the majority elects a new leader
    majority = [s for s in sids if s.node != leader.node]
    new_leader = await_leader(router, majority, timeout=10.0)
    assert new_leader != leader
    # a sync command to the minority leader must time out, not falsely ack
    with pytest.raises((TimeoutError, RuntimeError)):
        ra_tpu.process_command(leader, ("enqueue", None, None, "ghost"),
                               router=router, timeout=1.0)
    router.heal()
    time.sleep(1.0)
    got = drain_all(sids, router, expect=10)
    assert [g for g in got if g.startswith("pre-")] == \
        [f"pre-{i}" for i in range(10)]
