"""Static-analysis gate — the dialyzer/xref/elvis role of the
reference's CI (/root/reference/rebar.config:30-44).  The image ships
no ruff/mypy, so tools/lint.py implements the checks over stdlib ast;
this test keeps the tree clean and the checker honest."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint.py")


def run_lint(*args):
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, timeout=120)


def test_repo_is_lint_clean():
    r = run_lint()
    assert r.returncode == 0, r.stdout + r.stderr


def test_checker_detects_each_rule(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import os
        import sys

        print(sys.argv)

        def f(x=[]):
            try:
                pass
            except:
                pass
            assert (x, "oops")
            if x is "lit":
                return f"nothing"
            return {1: "a", 1: "b"}
            print("unreachable")

        def f():
            pass
    """))
    r = run_lint(str(bad))
    out = r.stdout
    assert r.returncode == 1
    for code in ("F401", "B006", "E722", "F631", "F632", "F541",
                 "F601", "F811", "W101"):
        assert code in out, (code, out)
    # 'sys' is used; only 'os' may be flagged unused
    assert "'sys' imported but unused" not in out


def test_checker_false_positive_guards(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""\
        from __future__ import annotations
        import json  # noqa: F401

        @property
        def x(self):
            return 1

        @x.setter
        def x(self, v):
            pass

        def g(i):
            return f"{i:03d}"
    """))
    r = run_lint(str(ok))
    assert r.returncode == 0, r.stdout
