"""Static-analysis gate — the dialyzer/xref/elvis role of the
reference's CI (/root/reference/rebar.config:30-44).  The image ships
no ruff/mypy, so tools/lint.py implements the checks over stdlib ast;
this test keeps the tree clean and the checker honest."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint.py")


def run_lint(*args):
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, timeout=120)


def test_repo_is_lint_clean():
    r = run_lint()
    assert r.returncode == 0, r.stdout + r.stderr


def test_checker_detects_each_rule(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import os
        import sys

        print(sys.argv)

        def f(x=[]):
            try:
                pass
            except:
                pass
            assert (x, "oops")
            if x is "lit":
                return f"nothing"
            return {1: "a", 1: "b"}
            print("unreachable")

        def f():
            pass
    """))
    r = run_lint(str(bad))
    out = r.stdout
    assert r.returncode == 1
    for code in ("F401", "B006", "E722", "F631", "F632", "F541",
                 "F601", "F811", "W101"):
        assert code in out, (code, out)
    # 'sys' is used; only 'os' may be flagged unused
    assert "'sys' imported but unused" not in out


def test_checker_forbids_one_shot_sends_in_lifecycle_verbs(tmp_path):
    """RA01: api-layer lifecycle verbs must ride the reliable RPC layer
    (transport/rpc.py) — a direct router.send/remote_call from one is
    the silent-loss race ISSUE 2 removed.  Applies to files named
    api.py only; non-lifecycle functions keep their one-shot sends."""
    bad = tmp_path / "api.py"
    bad.write_text(textwrap.dedent("""\
        def stop_server(server_id, router):
            router.send("?", server_id, object())

        def restart_server(server_id, router):
            return router.remote_call(server_id, object())

        def trigger_election(server_id, router):
            router.send("?", server_id, object())  # not a lifecycle verb
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA01") == 2, r.stdout
    assert "stop_server" in r.stdout and "restart_server" in r.stdout
    assert "trigger_election" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA01" not in r.stdout


def test_api_module_is_ra01_clean():
    """The real api.py passes the lifecycle-RPC gate (covered by the
    repo-wide run too; pinned separately so a regression names the
    rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "api.py"))
    assert "RA01" not in r.stdout, r.stdout


def test_checker_forbids_host_syncs_in_engine_hot_loop(tmp_path):
    """RA02: np.asarray/.item() inside the engine step hot-loop
    functions force a device->host sync that serializes the XLA
    pipeline.  Applies to files named lockstep.py/durable.py only;
    `# ra02-ok:` allowlists a documented readback point."""
    bad = tmp_path / "lockstep.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        def step(self, n_new):
            host = np.asarray(n_new)
            flag = self.state.term[0].item()
            return host, flag

        def _step(state, n_new):
            ok = np.asarray(n_new)  # ra02-ok: host-provided mask
            return ok

        def overview(self):
            return np.asarray(self.state.commit)  # not a hot-loop fn
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA02") == 2, r.stdout
    assert "np.asarray" in r.stdout and ".item()" in r.stdout
    # the same content under a non-engine module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA02" not in r.stdout


def test_engine_modules_are_ra02_clean():
    """The real engine hot loop passes the host-sync gate (covered by
    the repo-wide run too; pinned separately so a regression names the
    rule)."""
    for mod in ("lockstep.py", "durable.py"):
        r = run_lint(os.path.join(REPO, "ra_tpu", "engine", mod))
        assert "RA02" not in r.stdout, (mod, r.stdout)


def test_checker_forbids_swallowed_io_errors_in_log_layer(tmp_path):
    """RA03: pass-only except OSError/Exception around durability I/O
    (fsync/pwrite/write/sync) in log/ files is the silent-loss bug
    class ISSUE 4 removed; `# ra03-ok:` allowlists an audited site.
    Applies to files inside a directory named log/ only."""
    logdir = tmp_path / "log"
    logdir.mkdir()
    bad = logdir / "wal.py"
    bad.write_text(textwrap.dedent("""\
        import os

        def flush(fd, buf):
            try:
                os.write(fd, buf)
                os.fsync(fd)
            except OSError:
                pass

        def sync2(io, fd):
            try:
                io.sync(fd, 2)
            except Exception:  # ra03-ok: audited, counter bumped in caller
                pass

        def close_quiet(fd):
            try:
                os.close(fd)       # not durability-bearing: no finding
            except OSError:
                pass

        def handled(fd, buf):
            try:
                os.pwrite(fd, buf, 0)
            except OSError:
                raise RuntimeError("escalate")  # routed, not swallowed
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA03") == 1, r.stdout
    assert ":7:" in r.stdout, r.stdout  # the except line of flush()
    # the same content outside a log/ directory is not gated
    other = tmp_path / "wal.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA03" not in r.stdout


def test_log_layer_is_ra03_clean():
    """The real log layer passes the swallowed-IO-error gate (covered
    by the repo-wide run too; pinned separately so a regression names
    the rule)."""
    for mod in ("wal.py", "segment.py", "durable.py", "snapshot.py",
                "faults.py", "memory.py"):
        r = run_lint(os.path.join(REPO, "ra_tpu", "log", mod))
        assert "RA03" not in r.stdout, (mod, r.stdout)


def test_checker_forbids_host_syncs_in_bench_dispatch_loops(tmp_path):
    """RA04: block_until_ready/.item()/np.asarray/committed_total inside
    a bench/soak dispatch loop serializes the measured pipeline (ISSUE
    5).  Applies to files named bench.py/bench_classic.py/soak.py only;
    `# ra04-ok:` allowlists window-boundary syncs; loops that dispatch
    nothing are not gated."""
    bad = tmp_path / "bench.py"
    bad.write_text(textwrap.dedent("""\
        import time
        import numpy as np

        def run(eng, n_new, payloads):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 1.0:
                eng.step(n_new, payloads)
                eng.block_until_ready()
                total = eng.committed_total()
                flag = eng.state.term[0].item()
                host = np.asarray(eng.state.commit)
            return total, flag, host

        def run_windowed(eng, n_new, payloads, rb):
            for _ in range(100):
                eng.superstep(n_new, payloads)
                while len(rb) > 4:
                    np.asarray(rb.popleft())  # ra04-ok: window boundary
            eng.block_until_ready()

        def postprocess(rows):
            # no dispatch in this loop: host-side math is not gated
            out = []
            for r in rows:
                out.append(np.asarray(r).sum().item())
            return out
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 4, r.stdout
    for frag in (".block_until_ready()", ".committed_total()",
                 ".item()", "np.asarray()"):
        assert frag in r.stdout, (frag, r.stdout)
    # the same content under a non-bench module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_bench_files_are_ra04_clean():
    """The real bench/soak measured loops pass the dispatch-loop sync
    gate (covered by the repo-wide run too; pinned separately so a
    regression names the rule)."""
    for mod in ("bench.py", "bench_classic.py",
                os.path.join("tools", "soak.py")):
        r = run_lint(os.path.join(REPO, mod))
        assert "RA04" not in r.stdout, (mod, r.stdout)


def test_checker_false_positive_guards(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""\
        from __future__ import annotations
        import json  # noqa: F401

        @property
        def x(self):
            return 1

        @x.setter
        def x(self, v):
            pass

        def g(i):
            return f"{i:03d}"
    """))
    r = run_lint(str(ok))
    assert r.returncode == 0, r.stdout


def test_checker_enforces_field_registry(tmp_path):
    """RA05: a counter-field tuple missing from FIELD_REGISTRY, or with
    fields undocumented in docs/OBSERVABILITY.md, is flagged at the
    definition site.  Applies to files named metrics.py only."""
    bad = tmp_path / "metrics.py"
    bad.write_text(textwrap.dedent("""\
        WAL_FIELDS = ("syncs", "batches")

        ORPHAN_FIELDS = ("zz_not_documented_anywhere",)

        FIELD_REGISTRY = {"wal": WAL_FIELDS}
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA05") == 2, r.stdout
    assert "ORPHAN_FIELDS is not listed" in r.stdout
    assert "zz_not_documented_anywhere" in r.stdout
    # WAL_FIELDS is registered and its fields are documented: clean
    assert "WAL_FIELDS" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA05" not in r.stdout


def test_metrics_module_is_ra05_clean():
    """The real registry passes the parity gate: every *_FIELDS tuple
    is in FIELD_REGISTRY and documented in docs/OBSERVABILITY.md."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "metrics.py"))
    assert "RA05" not in r.stdout, r.stdout


def test_checker_gates_telemetry_sampler_path(tmp_path):
    """RA04 (sampler extension): blocking syncs inside the telemetry
    sampler's tick-path functions (tick/_start_sample/_harvest) are
    flagged — the sampler rides the dispatch loop, so its tick path
    obeys the same no-host-sync contract as the bench loops.  Applies
    to files named telemetry.py only."""
    bad = tmp_path / "telemetry.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class S:
            def tick(self):
                self.engine.block_until_ready()
                v = self.handle.item()
                return v

            def _harvest(self):
                host = np.asarray(self.handle)  # ra04-ok: ready-gated
                return host

            def drain(self):
                return np.asarray(self.handle)  # not a tick-path fn
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert ".block_until_ready()" in r.stdout and ".item()" in r.stdout
    assert "drain" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "other.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_telemetry_module_is_ra04_clean():
    """The real sampler tick path passes the no-host-sync gate."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "telemetry.py"))
    assert "RA04" not in r.stdout, r.stdout


def test_checker_enforces_event_registry(tmp_path):
    """RA06: an event type emitted via record()/blackbox.record/
    RECORDER.record or a module-level trace.span/trace.instant that is
    not a key of blackbox.EVENT_REGISTRY is flagged; Tracer OBJECT
    spans (t.span) and non-constant types are exempt; tests are exempt
    by path."""
    bb = tmp_path / "blackbox.py"
    bb.write_text('EVENT_REGISTRY = {"wal.fsync": "doc"}\n')
    bad = tmp_path / "instrumented.py"
    bad.write_text(textwrap.dedent("""\
        from blackbox import RECORDER, record
        import trace

        def f(t, name):
            record("wal.fsync", ms=1)        # registered: clean
            record("zz.bogus", x=1)          # RA06
            RECORDER.record("zz.worse")      # RA06
            record(name)                     # non-constant: exempt
            with trace.span("zz.span"):      # RA06
                pass
            with t.span("anything"):         # Tracer object: exempt
                pass
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA06") == 3, r.stdout
    assert "zz.bogus" in r.stdout and "zz.worse" in r.stdout
    assert "zz.span" in r.stdout
    # the same content under a tests/ path is exempt
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "helper.py").write_text(bad.read_text())
    (tmp_path / "tests" / "blackbox.py").write_text(bb.read_text())
    r = run_lint(str(tdir / "helper.py"))
    assert "RA06" not in r.stdout, r.stdout


def test_checker_enforces_event_registry_doc_half(tmp_path):
    """RA06 (doc half): blackbox.py's EVENT_REGISTRY keys must be
    backticked in docs/OBSERVABILITY.md (resolved next to the file
    first, like RA05's doc resolution)."""
    d = tmp_path / "docs"
    d.mkdir()
    (d / "OBSERVABILITY.md").write_text("only `wal.fsync` is here\n")
    bb = tmp_path / "blackbox.py"
    bb.write_text('EVENT_REGISTRY = {"wal.fsync": "d", '
                  '"zz.undocumented": "d"}\n')
    r = run_lint(str(bb))
    assert r.returncode == 1
    assert "RA06" in r.stdout and "zz.undocumented" in r.stdout


def test_checker_gates_recorder_emit_path(tmp_path):
    """RA04 extension: host syncs inside blackbox.py's record()
    closure are flagged — the recorder rides dispatch loops."""
    bad = tmp_path / "blackbox.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        EVENT_REGISTRY = {"wal.fsync": "d"}

        class R:
            def record(self, etype, **fields):
                self._stash(fields)
                self.handle.block_until_ready()
                return np.asarray(fields["x"])

            def _stash(self, fields):
                return fields["x"].item()

            def dump(self):
                return np.asarray(self.rings)  # not on the emit path
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 3, r.stdout
    assert "dump" not in r.stdout


def test_checker_enforces_autotune_contract(tmp_path):
    """RA07 (ISSUE 9): TUNABLE_KNOBS must be stamped in the
    engine_pipeline overview (telemetry.py next to the file) and
    documented in docs/OBSERVABILITY.md; a knob-mutating function
    without a registered record(...) event is a silent knob turn.
    Applies to files named autotune.py only."""
    (tmp_path / "telemetry.py").write_text(
        'PIPE = {"superstep_k": 1}\n')
    d = tmp_path / "docs"
    d.mkdir()
    (d / "OBSERVABILITY.md").write_text("`superstep_k` is documented\n")
    (tmp_path / "blackbox.py").write_text(
        'EVENT_REGISTRY = {"tune.decision": "d"}\n')
    bad = tmp_path / "autotune.py"
    bad.write_text(textwrap.dedent("""\
        from blackbox import record

        TUNABLE_KNOBS = ("superstep_k", "zz_ghost_knob")

        class T:
            def good_set(self, v):
                self.knobs["superstep_k"] = v
                record("tune.decision", new=v)

            def silent_set(self, v):
                self.knobs["superstep_k"] = v      # RA07: no event

            def unregistered_set(self, v):
                self.superstep_k = v
                record("zz.not.registered", new=v)  # RA07: bogus type
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    # ghost knob: not stamped in telemetry.py AND not documented
    assert out.count("zz_ghost_knob") == 2, out
    assert "not stamped in the" in out and "undocumented" in out
    assert "silent_set" in out and "unregistered_set" in out
    assert "good_set" not in out
    assert out.count("RA07") == 4, out
    # the same content under another module name is not gated
    other = tmp_path / "controller.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA07" not in r.stdout


def test_checker_gates_autotune_tick_path(tmp_path):
    """RA04 extension: host syncs reachable from the controller's
    tick() closure are flagged — the tuner runs between dispatches."""
    (tmp_path / "telemetry.py").write_text("PIPE = {}\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text("nothing\n")
    bad = tmp_path / "autotune.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class T:
            def tick(self):
                self._decide()
                return self.handle.item()

            def _decide(self):
                return np.asarray(self.state.commit)

            def overview(self):
                return np.asarray(self.rings)  # not on the tick path
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert ".item()" in r.stdout and "np.asarray" in r.stdout
    assert "overview" not in r.stdout


def test_autotune_module_is_ra07_and_ra04_clean():
    """The real controller passes both gates (covered by the repo-wide
    run too; pinned separately so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "autotune.py"))
    assert "RA07" not in r.stdout and "RA04" not in r.stdout, r.stdout


def test_blackbox_module_is_ra06_and_ra04_clean():
    """The real recorder and every instrumented module pass the gates
    (covered by the repo-wide run too; pinned so a regression names
    the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "blackbox.py"))
    assert "RA06" not in r.stdout and "RA04" not in r.stdout, r.stdout
    for mod in ("ra_tpu/api.py", "ra_tpu/core/server.py",
                "ra_tpu/log/wal.py", "ra_tpu/transport/rpc.py",
                "ra_tpu/engine/durable.py", "ra_tpu/engine/lockstep.py"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA06" not in r.stdout, (mod, r.stdout)


def test_checker_enforces_coalescer_hot_path(tmp_path):
    """RA08 (ISSUE 10): Python loops and dict allocation inside the
    ingress coalescer's block-build hot path (offer/pop_block + the
    same-module helpers they reach) are flagged; `# ra08-ok:` lines
    and non-hot functions are exempt; other filenames are not gated."""
    import textwrap
    bad = tmp_path / "coalesce.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class W:
            def offer(self, lanes, payloads, handles):
                for ln in lanes:                      # RA08: loop
                    self.fill[ln] += 1
                meta = {"rows": len(lanes)}           # RA08: dict
                return self._scatter(lanes), meta

            def _scatter(self, lanes):
                return dict(enumerate(lanes))         # RA08: via helper

            def pop_block(self):
                takes = [int(t) for t in self.fill]   # RA08: comp loop
                return takes

            def ready(self):
                # NOT hot: loops here are control-plane work
                return any(f > 0 for f in [1, 2])
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA08") == 4, out
    assert "offer()" in out and "pop_block()" in out \
        and "_scatter()" in out
    assert "ready()" not in out
    # allowlisted lines pass
    fixed = bad.read_text() \
        .replace("for ln in lanes:", "for ln in lanes:  # ra08-ok: tiny") \
        .replace('meta = {"rows": len(lanes)}',
                 'meta = {"rows": len(lanes)}  # ra08-ok: once') \
        .replace("return dict(enumerate(lanes))",
                 "return dict(enumerate(lanes))  # ra08-ok: cold") \
        .replace("takes = [int(t) for t in self.fill]",
                 "takes = [int(t) for t in self.fill]  # ra08-ok: k")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA08" not in r.stdout, r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "window.py"
    other.write_text(textwrap.dedent("""\
        class W:
            def offer(self, lanes):
                return {ln: 1 for ln in lanes}
    """))
    r = run_lint(str(other))
    assert "RA08" not in r.stdout


def test_ingress_coalescer_is_ra08_clean():
    """The real coalescer's hot path is loop- and dict-free (covered by
    the repo-wide run too; pinned so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "ingress", "coalesce.py"))
    assert "RA08" not in r.stdout, r.stdout


def test_checker_gates_mesh_driver_dispatch_loop(tmp_path):
    """RA04 (mesh extension, ISSUE 11): host syncs reachable from the
    mesh driver's dispatch loop (drive_uniform_window + same-module
    closure) are flagged — the sharded frontier's measured loop obeys
    the same no-sync contract as the bench loops.  Applies to files
    named mesh.py only."""
    bad = tmp_path / "mesh.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        def drive_uniform_window(driver, nb, pb, seconds):
            n = 0
            while n < 100:
                driver.submit(nb, pb)
                _peek(driver)
                n += 1
            return n

        def _peek(driver):
            driver.engine.block_until_ready()
            return np.asarray(driver.last_committed)

        def shard_engine_state(engine):
            # not on the dispatch loop: conversions here are fine
            return np.asarray(engine.state.commit)
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert "_peek" in r.stdout
    assert "shard_engine_state" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "driver.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_checker_gates_mesh_ingress_pump_path(tmp_path):
    """RA08 (mesh extension, ISSUE 11): per-session Python loops/dict
    allocation in the mesh-side ingress pump path (ingress_submit_wave
    + closure) are flagged; non-pump functions are exempt."""
    bad = tmp_path / "mesh.py"
    bad.write_text(textwrap.dedent("""\
        def ingress_submit_wave(plane, handles, seqnos, payloads):
            for h in handles:                     # RA08: per-session
                plane.touch(h)
            return _meta(handles)

        def _meta(handles):
            return {"rows": len(handles)}         # RA08: via helper

        def lane_mesh(devices):
            # control-plane setup: loops here are fine
            return [d for d in devices]
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA08") == 2, r.stdout
    assert "ingress_submit_wave" in r.stdout and "_meta" in r.stdout
    assert "lane_mesh" not in r.stdout
    other = tmp_path / "pump.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA08" not in r.stdout


def test_checker_enforces_wire_sweep_path(tmp_path):
    """RA09 (ISSUE 12): Python loops and dict allocation inside the
    wire reader sweep path (sweep + the same-module helpers it
    reaches) are flagged — per-frame Python there is the RA08 bug
    class extended to the socket path.  `# ra09-ok:` allowlists
    per-CONNECTION work; non-sweep functions and other directories
    are not gated."""
    wdir = tmp_path / "wire"
    wdir.mkdir()
    bad = wdir / "server.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class L:
            def sweep(self):
                rows = [r for r in self.rbuf]         # RA09: loop
                meta = {"rows": len(rows)}            # RA09: dict
                return self._fanout(rows), meta

            def _fanout(self, rows):
                for r in rows:                        # RA09: via helper
                    self.send(r)

            def overview(self):
                # NOT on the sweep path: control-plane loops are fine
                return {k: v for k, v in self.counters.items()}
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA09") == 3, out
    assert "sweep()" in out and "_fanout()" in out
    assert "overview" not in out
    # allowlisted per-connection lines pass
    fixed = bad.read_text() \
        .replace("rows = [r for r in self.rbuf]",
                 "rows = [r for r in self.rbuf]  # ra09-ok: test") \
        .replace('meta = {"rows": len(rows)}',
                 'meta = {"rows": len(rows)}  # ra09-ok: once') \
        .replace("for r in rows:",
                 "for r in rows:  # ra09-ok: per-connection write")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA09" not in r.stdout, r.stdout
    # the same content OUTSIDE a wire/ directory is not gated
    other = tmp_path / "server.py"
    other.write_text(textwrap.dedent("""\
        class L:
            def sweep(self):
                return [r for r in self.rbuf]
    """))
    r = run_lint(str(other))
    assert "RA09" not in r.stdout


def test_wire_package_is_ra09_clean():
    """The real wire sweep path is loop- and dict-free outside its
    allowlisted per-connection sites (covered by the repo-wide run
    too; pinned so a regression names the rule)."""
    import os as _os
    wdir = os.path.join(REPO, "ra_tpu", "wire")
    for name in sorted(_os.listdir(wdir)):
        if name.endswith(".py"):
            r = run_lint(os.path.join(wdir, name))
            assert "RA09" not in r.stdout, (name, r.stdout)


def test_checker_enforces_classic_hot_path(tmp_path):
    """RA10 (ISSUE 13): per-entry pickle.dumps/encode_command and
    per-entry WAL submits inside loops in the classic replication hot
    paths are flagged — including a pickle moved into a same-module
    helper called from the loop; `# ra10-ok:` allowlists deliberate
    per-item sites; unscoped filenames are not gated."""
    bad = tmp_path / "tcp.py"
    bad.write_text(textwrap.dedent("""\
        import pickle

        class R:
            def _send_items(self, peer, items):
                buf = bytearray()
                for item in items:
                    buf += pickle.dumps(item)       # RA10: per-item
                    buf += self._encode_item(item)  # RA10: via helper
                return bytes(buf)

            def _encode_item(self, item):
                return pickle.dumps(item)

            def overview(self):
                # not on the sender path: per-item work is fine here
                return [pickle.dumps(x) for x in (1, 2)]
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA10") == 2, r.stdout
    assert "_send_items" in r.stdout
    assert "overview" not in r.stdout
    # allowlisted lines pass
    fixed = bad.read_text() \
        .replace("buf += pickle.dumps(item)       # RA10: per-item",
                 "buf += pickle.dumps(item)  # ra10-ok: singles") \
        .replace("buf += self._encode_item(item)  # RA10: via helper",
                 "buf += self._encode_item(item)  # ra10-ok: fallback")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA10" not in r.stdout, r.stdout
    # log/durable.py: per-entry WAL submits in the batch-append path
    logdir = tmp_path / "log"
    logdir.mkdir()
    dlog = logdir / "durable.py"
    dlog.write_text(textwrap.dedent("""\
        def encode_command(cmd):
            import pickle
            return pickle.dumps(cmd)

        class D:
            def write(self, entries):
                for e in entries:
                    payload = encode_command(e)     # RA10: per-entry
                    self.wal.write(self.uid, e, payload)  # RA10: WAL
    """))
    r = run_lint(str(dlog))
    assert r.returncode == 1
    assert r.stdout.count("RA10") == 2, r.stdout
    assert "per-entry WAL submit" in r.stdout
    # the same content under another parent dir is not gated
    other = tmp_path / "durable.py"
    other.write_text(dlog.read_text())
    r = run_lint(str(other))
    assert "RA10" not in r.stdout
    # an unscoped filename with the same sender content is not gated
    free = tmp_path / "sender.py"
    free.write_text(textwrap.dedent("""\
        import pickle

        class R:
            def _send_items(self, peer, items):
                return [pickle.dumps(i) for i in items]
    """))
    r = run_lint(str(free))
    assert "RA10" not in r.stdout


def test_classic_hot_paths_are_ra10_clean():
    """The real sender loop, batch-append, and commit-advance closures
    pass the per-entry gate (covered by the repo-wide run too; pinned
    separately so a regression names the rule)."""
    for mod in ("ra_tpu/transport/tcp.py", "ra_tpu/log/durable.py",
                "ra_tpu/core/server.py"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA10" not in r.stdout, (mod, r.stdout)


def test_mesh_module_is_ra04_and_ra08_clean():
    """The real mesh driver passes both gates (covered by the repo-wide
    run too; pinned separately so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "parallel", "mesh.py"))
    assert "RA04" not in r.stdout and "RA08" not in r.stdout, r.stdout
