"""Static-analysis gate — the dialyzer/xref/elvis role of the
reference's CI (/root/reference/rebar.config:30-44).  The image ships
no ruff/mypy, so tools/lint.py implements the checks over stdlib ast;
this test keeps the tree clean and the checker honest."""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(REPO, "tools", "lint.py")


def run_lint(*args):
    return subprocess.run([sys.executable, LINT, *args],
                          capture_output=True, text=True, timeout=120)


def test_repo_is_lint_clean():
    r = run_lint()
    assert r.returncode == 0, r.stdout + r.stderr


def test_checker_detects_each_rule(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""\
        import os
        import sys

        print(sys.argv)

        def f(x=[]):
            try:
                pass
            except:
                pass
            assert (x, "oops")
            if x is "lit":
                return f"nothing"
            return {1: "a", 1: "b"}
            print("unreachable")

        def f():
            pass
    """))
    r = run_lint(str(bad))
    out = r.stdout
    assert r.returncode == 1
    for code in ("F401", "B006", "E722", "F631", "F632", "F541",
                 "F601", "F811", "W101"):
        assert code in out, (code, out)
    # 'sys' is used; only 'os' may be flagged unused
    assert "'sys' imported but unused" not in out


def test_checker_forbids_one_shot_sends_in_lifecycle_verbs(tmp_path):
    """RA01: api-layer lifecycle verbs must ride the reliable RPC layer
    (transport/rpc.py) — a direct router.send/remote_call from one is
    the silent-loss race ISSUE 2 removed.  Applies to files named
    api.py only; non-lifecycle functions keep their one-shot sends."""
    bad = tmp_path / "api.py"
    bad.write_text(textwrap.dedent("""\
        def stop_server(server_id, router):
            router.send("?", server_id, object())

        def restart_server(server_id, router):
            return router.remote_call(server_id, object())

        def trigger_election(server_id, router):
            router.send("?", server_id, object())  # not a lifecycle verb
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA01") == 2, r.stdout
    assert "stop_server" in r.stdout and "restart_server" in r.stdout
    assert "trigger_election" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA01" not in r.stdout


def test_api_module_is_ra01_clean():
    """The real api.py passes the lifecycle-RPC gate (covered by the
    repo-wide run too; pinned separately so a regression names the
    rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "api.py"))
    assert "RA01" not in r.stdout, r.stdout


def test_checker_forbids_host_syncs_in_engine_hot_loop(tmp_path):
    """RA02: np.asarray/.item() inside the engine step hot-loop
    functions force a device->host sync that serializes the XLA
    pipeline.  Applies to files named lockstep.py/durable.py only;
    `# ra02-ok:` allowlists a documented readback point."""
    bad = tmp_path / "lockstep.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        def step(self, n_new):
            host = np.asarray(n_new)
            flag = self.state.term[0].item()
            return host, flag

        def _step(state, n_new):
            ok = np.asarray(n_new)  # ra02-ok: host-provided mask
            return ok

        def overview(self):
            return np.asarray(self.state.commit)  # not a hot-loop fn
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA02") == 2, r.stdout
    assert "np.asarray" in r.stdout and ".item()" in r.stdout
    # the same content under a non-engine module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA02" not in r.stdout


def test_engine_modules_are_ra02_clean():
    """The real engine hot loop passes the host-sync gate (covered by
    the repo-wide run too; pinned separately so a regression names the
    rule)."""
    for mod in ("lockstep.py", "durable.py"):
        r = run_lint(os.path.join(REPO, "ra_tpu", "engine", mod))
        assert "RA02" not in r.stdout, (mod, r.stdout)


def test_checker_forbids_swallowed_io_errors_in_log_layer(tmp_path):
    """RA03: pass-only except OSError/Exception around durability I/O
    (fsync/pwrite/write/sync) in log/ files is the silent-loss bug
    class ISSUE 4 removed; `# ra03-ok:` allowlists an audited site.
    Applies to files inside a directory named log/ only."""
    logdir = tmp_path / "log"
    logdir.mkdir()
    bad = logdir / "wal.py"
    bad.write_text(textwrap.dedent("""\
        import os

        def flush(fd, buf):
            try:
                os.write(fd, buf)
                os.fsync(fd)
            except OSError:
                pass

        def sync2(io, fd):
            try:
                io.sync(fd, 2)
            except Exception:  # ra03-ok: audited, counter bumped in caller
                pass

        def close_quiet(fd):
            try:
                os.close(fd)       # not durability-bearing: no finding
            except OSError:
                pass

        def handled(fd, buf):
            try:
                os.pwrite(fd, buf, 0)
            except OSError:
                raise RuntimeError("escalate")  # routed, not swallowed
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA03") == 1, r.stdout
    assert ":7:" in r.stdout, r.stdout  # the except line of flush()
    # the same content outside a log/ directory is not gated
    other = tmp_path / "wal.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA03" not in r.stdout


def test_log_layer_is_ra03_clean():
    """The real log layer passes the swallowed-IO-error gate (covered
    by the repo-wide run too; pinned separately so a regression names
    the rule)."""
    for mod in ("wal.py", "segment.py", "durable.py", "snapshot.py",
                "faults.py", "memory.py"):
        r = run_lint(os.path.join(REPO, "ra_tpu", "log", mod))
        assert "RA03" not in r.stdout, (mod, r.stdout)


def test_checker_forbids_host_syncs_in_bench_dispatch_loops(tmp_path):
    """RA04: block_until_ready/.item()/np.asarray/committed_total inside
    a bench/soak dispatch loop serializes the measured pipeline (ISSUE
    5).  Applies to files named bench.py/bench_classic.py/soak.py only;
    `# ra04-ok:` allowlists window-boundary syncs; loops that dispatch
    nothing are not gated."""
    bad = tmp_path / "bench.py"
    bad.write_text(textwrap.dedent("""\
        import time
        import numpy as np

        def run(eng, n_new, payloads):
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < 1.0:
                eng.step(n_new, payloads)
                eng.block_until_ready()
                total = eng.committed_total()
                flag = eng.state.term[0].item()
                host = np.asarray(eng.state.commit)
            return total, flag, host

        def run_windowed(eng, n_new, payloads, rb):
            for _ in range(100):
                eng.superstep(n_new, payloads)
                while len(rb) > 4:
                    np.asarray(rb.popleft())  # ra04-ok: window boundary
            eng.block_until_ready()

        def postprocess(rows):
            # no dispatch in this loop: host-side math is not gated
            out = []
            for r in rows:
                out.append(np.asarray(r).sum().item())
            return out
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 4, r.stdout
    for frag in (".block_until_ready()", ".committed_total()",
                 ".item()", "np.asarray()"):
        assert frag in r.stdout, (frag, r.stdout)
    # the same content under a non-bench module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_bench_files_are_ra04_clean():
    """The real bench/soak measured loops pass the dispatch-loop sync
    gate (covered by the repo-wide run too; pinned separately so a
    regression names the rule)."""
    for mod in ("bench.py", "bench_classic.py",
                os.path.join("tools", "soak.py")):
        r = run_lint(os.path.join(REPO, mod))
        assert "RA04" not in r.stdout, (mod, r.stdout)


def test_checker_false_positive_guards(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text(textwrap.dedent("""\
        from __future__ import annotations
        import json  # noqa: F401

        @property
        def x(self):
            return 1

        @x.setter
        def x(self, v):
            pass

        def g(i):
            return f"{i:03d}"
    """))
    r = run_lint(str(ok))
    assert r.returncode == 0, r.stdout


def test_checker_enforces_field_registry(tmp_path):
    """RA05: a counter-field tuple missing from FIELD_REGISTRY, or with
    fields undocumented in docs/OBSERVABILITY.md, is flagged at the
    definition site.  Applies to files named metrics.py only."""
    bad = tmp_path / "metrics.py"
    bad.write_text(textwrap.dedent("""\
        WAL_FIELDS = ("syncs", "batches")

        ORPHAN_FIELDS = ("zz_not_documented_anywhere",)

        FIELD_REGISTRY = {"wal": WAL_FIELDS}
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA05") == 2, r.stdout
    assert "ORPHAN_FIELDS is not listed" in r.stdout
    assert "zz_not_documented_anywhere" in r.stdout
    # WAL_FIELDS is registered and its fields are documented: clean
    assert "WAL_FIELDS" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "helpers.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA05" not in r.stdout


def test_metrics_module_is_ra05_clean():
    """The real registry passes the parity gate: every *_FIELDS tuple
    is in FIELD_REGISTRY and documented in docs/OBSERVABILITY.md."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "metrics.py"))
    assert "RA05" not in r.stdout, r.stdout


def test_checker_gates_telemetry_sampler_path(tmp_path):
    """RA04 (sampler extension): blocking syncs inside the telemetry
    sampler's tick-path functions (tick/_start_sample/_harvest) are
    flagged — the sampler rides the dispatch loop, so its tick path
    obeys the same no-host-sync contract as the bench loops.  Applies
    to files named telemetry.py only."""
    bad = tmp_path / "telemetry.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class S:
            def tick(self):
                self.engine.block_until_ready()
                v = self.handle.item()
                return v

            def _harvest(self):
                host = np.asarray(self.handle)  # ra04-ok: ready-gated
                return host

            def drain(self):
                return np.asarray(self.handle)  # not a tick-path fn
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert ".block_until_ready()" in r.stdout and ".item()" in r.stdout
    assert "drain" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "other.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_telemetry_module_is_ra04_clean():
    """The real sampler tick path passes the no-host-sync gate."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "telemetry.py"))
    assert "RA04" not in r.stdout, r.stdout


def test_checker_enforces_event_registry(tmp_path):
    """RA06: an event type emitted via record()/blackbox.record/
    RECORDER.record or a module-level trace.span/trace.instant that is
    not a key of blackbox.EVENT_REGISTRY is flagged; Tracer OBJECT
    spans (t.span) and non-constant types are exempt; tests are exempt
    by path."""
    bb = tmp_path / "blackbox.py"
    bb.write_text('EVENT_REGISTRY = {"wal.fsync": "doc"}\n')
    bad = tmp_path / "instrumented.py"
    bad.write_text(textwrap.dedent("""\
        from blackbox import RECORDER, record
        import trace

        def f(t, name):
            record("wal.fsync", ms=1)        # registered: clean
            record("zz.bogus", x=1)          # RA06
            RECORDER.record("zz.worse")      # RA06
            record(name)                     # non-constant: exempt
            with trace.span("zz.span"):      # RA06
                pass
            with t.span("anything"):         # Tracer object: exempt
                pass
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA06") == 3, r.stdout
    assert "zz.bogus" in r.stdout and "zz.worse" in r.stdout
    assert "zz.span" in r.stdout
    # the same content under a tests/ path is exempt
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "helper.py").write_text(bad.read_text())
    (tmp_path / "tests" / "blackbox.py").write_text(bb.read_text())
    r = run_lint(str(tdir / "helper.py"))
    assert "RA06" not in r.stdout, r.stdout


def test_checker_enforces_event_registry_doc_half(tmp_path):
    """RA06 (doc half): blackbox.py's EVENT_REGISTRY keys must be
    backticked in docs/OBSERVABILITY.md (resolved next to the file
    first, like RA05's doc resolution)."""
    d = tmp_path / "docs"
    d.mkdir()
    (d / "OBSERVABILITY.md").write_text("only `wal.fsync` is here\n")
    bb = tmp_path / "blackbox.py"
    bb.write_text('EVENT_REGISTRY = {"wal.fsync": "d", '
                  '"zz.undocumented": "d"}\n')
    r = run_lint(str(bb))
    assert r.returncode == 1
    assert "RA06" in r.stdout and "zz.undocumented" in r.stdout


def test_checker_gates_recorder_emit_path(tmp_path):
    """RA04 extension: host syncs inside blackbox.py's record()
    closure are flagged — the recorder rides dispatch loops."""
    bad = tmp_path / "blackbox.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        EVENT_REGISTRY = {"wal.fsync": "d"}

        class R:
            def record(self, etype, **fields):
                self._stash(fields)
                self.handle.block_until_ready()
                return np.asarray(fields["x"])

            def _stash(self, fields):
                return fields["x"].item()

            def dump(self):
                return np.asarray(self.rings)  # not on the emit path
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 3, r.stdout
    assert "dump" not in r.stdout


def test_checker_enforces_autotune_contract(tmp_path):
    """RA07 (ISSUE 9): TUNABLE_KNOBS must be stamped in the
    engine_pipeline overview (telemetry.py next to the file) and
    documented in docs/OBSERVABILITY.md; a knob-mutating function
    without a registered record(...) event is a silent knob turn.
    Applies to files named autotune.py only."""
    (tmp_path / "telemetry.py").write_text(
        'PIPE = {"superstep_k": 1}\n')
    d = tmp_path / "docs"
    d.mkdir()
    (d / "OBSERVABILITY.md").write_text("`superstep_k` is documented\n")
    (tmp_path / "blackbox.py").write_text(
        'EVENT_REGISTRY = {"tune.decision": "d"}\n')
    bad = tmp_path / "autotune.py"
    bad.write_text(textwrap.dedent("""\
        from blackbox import record

        TUNABLE_KNOBS = ("superstep_k", "zz_ghost_knob")

        class T:
            def good_set(self, v):
                self.knobs["superstep_k"] = v
                record("tune.decision", new=v)

            def silent_set(self, v):
                self.knobs["superstep_k"] = v      # RA07: no event

            def unregistered_set(self, v):
                self.superstep_k = v
                record("zz.not.registered", new=v)  # RA07: bogus type
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    # ghost knob: not stamped in telemetry.py AND not documented
    assert out.count("zz_ghost_knob") == 2, out
    assert "not stamped in the" in out and "undocumented" in out
    assert "silent_set" in out and "unregistered_set" in out
    assert "good_set" not in out
    assert out.count("RA07") == 4, out
    # the same content under another module name is not gated
    other = tmp_path / "controller.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA07" not in r.stdout


def test_checker_gates_autotune_tick_path(tmp_path):
    """RA04 extension: host syncs reachable from the controller's
    tick() closure are flagged — the tuner runs between dispatches."""
    (tmp_path / "telemetry.py").write_text("PIPE = {}\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text("nothing\n")
    bad = tmp_path / "autotune.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class T:
            def tick(self):
                self._decide()
                return self.handle.item()

            def _decide(self):
                return np.asarray(self.state.commit)

            def overview(self):
                return np.asarray(self.rings)  # not on the tick path
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert ".item()" in r.stdout and "np.asarray" in r.stdout
    assert "overview" not in r.stdout


def test_autotune_module_is_ra07_and_ra04_clean():
    """The real controller passes both gates (covered by the repo-wide
    run too; pinned separately so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "autotune.py"))
    assert "RA07" not in r.stdout and "RA04" not in r.stdout, r.stdout


def test_blackbox_module_is_ra06_and_ra04_clean():
    """The real recorder and every instrumented module pass the gates
    (covered by the repo-wide run too; pinned so a regression names
    the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "blackbox.py"))
    assert "RA06" not in r.stdout and "RA04" not in r.stdout, r.stdout
    for mod in ("ra_tpu/api.py", "ra_tpu/core/server.py",
                "ra_tpu/log/wal.py", "ra_tpu/transport/rpc.py",
                "ra_tpu/engine/durable.py", "ra_tpu/engine/lockstep.py"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA06" not in r.stdout, (mod, r.stdout)


def test_checker_enforces_coalescer_hot_path(tmp_path):
    """RA08 (ISSUE 10): Python loops and dict allocation inside the
    ingress coalescer's block-build hot path (offer/pop_block + the
    same-module helpers they reach) are flagged; `# ra08-ok:` lines
    and non-hot functions are exempt; other filenames are not gated."""
    import textwrap
    bad = tmp_path / "coalesce.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class W:
            def offer(self, lanes, payloads, handles):
                for ln in lanes:                      # RA08: loop
                    self.fill[ln] += 1
                meta = {"rows": len(lanes)}           # RA08: dict
                return self._scatter(lanes), meta

            def _scatter(self, lanes):
                return dict(enumerate(lanes))         # RA08: via helper

            def pop_block(self):
                takes = [int(t) for t in self.fill]   # RA08: comp loop
                return takes

            def ready(self):
                # NOT hot: loops here are control-plane work
                return any(f > 0 for f in [1, 2])
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA08") == 4, out
    assert "offer()" in out and "pop_block()" in out \
        and "_scatter()" in out
    assert "ready()" not in out
    # allowlisted lines pass
    fixed = bad.read_text() \
        .replace("for ln in lanes:", "for ln in lanes:  # ra08-ok: tiny") \
        .replace('meta = {"rows": len(lanes)}',
                 'meta = {"rows": len(lanes)}  # ra08-ok: once') \
        .replace("return dict(enumerate(lanes))",
                 "return dict(enumerate(lanes))  # ra08-ok: cold") \
        .replace("takes = [int(t) for t in self.fill]",
                 "takes = [int(t) for t in self.fill]  # ra08-ok: k")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA08" not in r.stdout, r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "window.py"
    other.write_text(textwrap.dedent("""\
        class W:
            def offer(self, lanes):
                return {ln: 1 for ln in lanes}
    """))
    r = run_lint(str(other))
    assert "RA08" not in r.stdout


def test_ingress_coalescer_is_ra08_clean():
    """The real coalescer's hot path is loop- and dict-free (covered by
    the repo-wide run too; pinned so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "ingress", "coalesce.py"))
    assert "RA08" not in r.stdout, r.stdout


def test_checker_gates_mesh_driver_dispatch_loop(tmp_path):
    """RA04 (mesh extension, ISSUE 11): host syncs reachable from the
    mesh driver's dispatch loop (drive_uniform_window + same-module
    closure) are flagged — the sharded frontier's measured loop obeys
    the same no-sync contract as the bench loops.  Applies to files
    named mesh.py only."""
    bad = tmp_path / "mesh.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        def drive_uniform_window(driver, nb, pb, seconds):
            n = 0
            while n < 100:
                driver.submit(nb, pb)
                _peek(driver)
                n += 1
            return n

        def _peek(driver):
            driver.engine.block_until_ready()
            return np.asarray(driver.last_committed)

        def shard_engine_state(engine):
            # not on the dispatch loop: conversions here are fine
            return np.asarray(engine.state.commit)
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert "_peek" in r.stdout
    assert "shard_engine_state" not in r.stdout
    # the same content under another module name is not gated
    other = tmp_path / "driver.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA04" not in r.stdout


def test_checker_gates_mesh_ingress_pump_path(tmp_path):
    """RA08 (mesh extension, ISSUE 11): per-session Python loops/dict
    allocation in the mesh-side ingress pump path (ingress_submit_wave
    + closure) are flagged; non-pump functions are exempt."""
    bad = tmp_path / "mesh.py"
    bad.write_text(textwrap.dedent("""\
        def ingress_submit_wave(plane, handles, seqnos, payloads):
            for h in handles:                     # RA08: per-session
                plane.touch(h)
            return _meta(handles)

        def _meta(handles):
            return {"rows": len(handles)}         # RA08: via helper

        def lane_mesh(devices):
            # control-plane setup: loops here are fine
            return [d for d in devices]
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA08") == 2, r.stdout
    assert "ingress_submit_wave" in r.stdout and "_meta" in r.stdout
    assert "lane_mesh" not in r.stdout
    other = tmp_path / "pump.py"
    other.write_text(bad.read_text())
    r = run_lint(str(other))
    assert "RA08" not in r.stdout


def test_checker_enforces_wire_sweep_path(tmp_path):
    """RA09 (ISSUE 12): Python loops and dict allocation inside the
    wire reader sweep path (sweep + the same-module helpers it
    reaches) are flagged — per-frame Python there is the RA08 bug
    class extended to the socket path.  `# ra09-ok:` allowlists
    per-CONNECTION work; non-sweep functions and other directories
    are not gated."""
    wdir = tmp_path / "wire"
    wdir.mkdir()
    bad = wdir / "server.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        class L:
            def sweep(self):
                rows = [r for r in self.rbuf]         # RA09: loop
                meta = {"rows": len(rows)}            # RA09: dict
                return self._fanout(rows), meta

            def _fanout(self, rows):
                for r in rows:                        # RA09: via helper
                    self.send(r)

            def overview(self):
                # NOT on the sweep path: control-plane loops are fine
                return {k: v for k, v in self.counters.items()}
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA09") == 3, out
    assert "sweep()" in out and "_fanout()" in out
    assert "overview" not in out
    # allowlisted per-connection lines pass
    fixed = bad.read_text() \
        .replace("rows = [r for r in self.rbuf]",
                 "rows = [r for r in self.rbuf]  # ra09-ok: test") \
        .replace('meta = {"rows": len(rows)}',
                 'meta = {"rows": len(rows)}  # ra09-ok: once') \
        .replace("for r in rows:",
                 "for r in rows:  # ra09-ok: per-connection write")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA09" not in r.stdout, r.stdout
    # the same content OUTSIDE a wire/ directory is not gated
    other = tmp_path / "server.py"
    other.write_text(textwrap.dedent("""\
        class L:
            def sweep(self):
                return [r for r in self.rbuf]
    """))
    r = run_lint(str(other))
    assert "RA09" not in r.stdout


def test_wire_package_is_ra09_clean():
    """The real wire sweep path is loop- and dict-free outside its
    allowlisted per-connection sites (covered by the repo-wide run
    too; pinned so a regression names the rule)."""
    import os as _os
    wdir = os.path.join(REPO, "ra_tpu", "wire")
    for name in sorted(_os.listdir(wdir)):
        if name.endswith(".py"):
            r = run_lint(os.path.join(wdir, name))
            assert "RA09" not in r.stdout, (name, r.stdout)


def test_checker_enforces_classic_hot_path(tmp_path):
    """RA10 (ISSUE 13 + the ISSUE 18 codec family): per-entry
    pickle.dumps/encode_command and per-entry WAL submits inside loops
    in the classic replication hot paths are flagged — including a
    pickle moved into a same-module helper called from the loop — AND
    any raw pickle.dumps anywhere in the closure (loop or not) that
    bypasses the codec's tagged fallback; `# ra10-ok:` allowlists
    deliberate sites; unscoped filenames are not gated."""
    bad = tmp_path / "tcp.py"
    bad.write_text(textwrap.dedent("""\
        import pickle

        class R:
            def _send_items(self, peer, items):
                buf = bytearray()
                for item in items:
                    buf += pickle.dumps(item)       # RA10: per-item
                    buf += self._encode_item(item)  # RA10: via helper
                return bytes(buf)

            def _encode_item(self, item):
                return pickle.dumps(item)           # RA10: raw pickle

            def _wire_form(self, to, msg, src):
                # the codec family: no loop, still a hot closure
                return pickle.dumps(msg)            # RA10: raw pickle

            def overview(self):
                # not on the sender path: per-item work is fine here
                return [pickle.dumps(x) for x in (1, 2)]
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA10") == 4, r.stdout
    assert "_send_items" in r.stdout
    assert "encode_fallback" in r.stdout    # the codec-family message
    assert "overview" not in r.stdout
    # allowlisted lines pass
    fixed = bad.read_text() \
        .replace("buf += pickle.dumps(item)       # RA10: per-item",
                 "buf += pickle.dumps(item)  # ra10-ok: singles") \
        .replace("buf += self._encode_item(item)  # RA10: via helper",
                 "buf += self._encode_item(item)  # ra10-ok: fallback") \
        .replace("return pickle.dumps(item)           # RA10: raw pickle",
                 "return pickle.dumps(item)  # ra10-ok: envelope") \
        .replace("return pickle.dumps(msg)            # RA10: raw pickle",
                 "return pickle.dumps(msg)  # ra10-ok: envelope")
    bad.write_text(fixed)
    r = run_lint(str(bad))
    assert "RA10" not in r.stdout, r.stdout
    # log/durable.py: per-entry WAL submits in the batch-append path,
    # plus the helper encoder's own raw pickle (the codec family)
    logdir = tmp_path / "log"
    logdir.mkdir()
    dlog = logdir / "durable.py"
    dlog.write_text(textwrap.dedent("""\
        def encode_command(cmd):
            import pickle
            return pickle.dumps(cmd)

        class D:
            def write(self, entries):
                for e in entries:
                    payload = encode_command(e)     # RA10: per-entry
                    self.wal.write(self.uid, e, payload)  # RA10: WAL
    """))
    r = run_lint(str(dlog))
    assert r.returncode == 1
    assert r.stdout.count("RA10") == 3, r.stdout
    assert "per-entry WAL submit" in r.stdout
    assert "raw pickle.dumps" in r.stdout
    # the same content under another parent dir is not gated
    other = tmp_path / "durable.py"
    other.write_text(dlog.read_text())
    r = run_lint(str(other))
    assert "RA10" not in r.stdout
    # an unscoped filename with the same sender content is not gated
    free = tmp_path / "sender.py"
    free.write_text(textwrap.dedent("""\
        import pickle

        class R:
            def _send_items(self, peer, items):
                return [pickle.dumps(i) for i in items]
    """))
    r = run_lint(str(free))
    assert "RA10" not in r.stdout


def test_classic_hot_paths_are_ra10_clean():
    """The real sender loop, batch-append, WAL batch-writer, segment
    flush, codec, and commit-advance closures pass the per-entry +
    raw-pickle gate (covered by the repo-wide run too; pinned
    separately so a regression names the rule)."""
    for mod in ("ra_tpu/transport/tcp.py", "ra_tpu/log/durable.py",
                "ra_tpu/log/wal.py", "ra_tpu/log/segment.py",
                "ra_tpu/codec.py", "ra_tpu/core/server.py",
                "ra_tpu/wire/server.py"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA10" not in r.stdout, (mod, r.stdout)


def test_mesh_module_is_ra04_and_ra08_clean():
    """The real mesh driver passes both gates (covered by the repo-wide
    run too; pinned separately so a regression names the rule)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "parallel", "mesh.py"))
    assert "RA04" not in r.stdout and "RA08" not in r.stdout, r.stdout


# ---------------------------------------------------------------------------
# ISSUE 14 — the whole-program analyzer (tools/analyzer/): cross-module
# closures, RA11 lock-order cycles, RA12 thread roles, the suppression
# audit, and the CLI additions (--changed/--json/--report).
# ---------------------------------------------------------------------------

def test_checker_catches_cross_module_escape(tmp_path):
    """The tentpole regression: a host sync moved into a helper ONE
    MODULE AWAY is flagged.  The pre-ISSUE-14 gate walked only the
    same-module call closure, so this exact shape escaped every rule —
    the finding below lands in helpers.py, a file the old checker
    could never attribute a sampler-path finding to."""
    pkg = tmp_path / "plane"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(textwrap.dedent("""\
        import numpy as np

        def pull(handle):
            return np.asarray(handle)
    """))
    (pkg / "telemetry.py").write_text(textwrap.dedent("""\
        from .helpers import pull

        class S:
            def tick(self):
                return pull(self.handle)
    """))
    r = run_lint(str(pkg / "telemetry.py"))
    assert r.returncode == 1
    assert "RA04" in r.stdout, r.stdout
    assert "helpers.py" in r.stdout and "pull" in r.stdout, r.stdout


def test_checker_resolves_ra_type_annotation_seams(tmp_path):
    """`# ra-type: Class` on an attribute assignment types the seam, so
    the closure walks through dynamically passed collaborators (the
    light-annotation half of ISSUE 14 — lockstep's `_dur` bridge and
    the WAL shard's `bridge` use exactly this)."""
    bad = tmp_path / "lockstep.py"
    bad.write_text(textwrap.dedent("""\
        class Bridge:
            def work(self):
                return self.h.item()

        class Eng:
            def __init__(self, bridge):
                self.bridge = bridge  # ra-type: Bridge

            def step(self):
                self.bridge.work()
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert "RA02" in r.stdout and "work" in r.stdout, r.stdout
    # without the annotation the seam is opaque: no finding (the
    # analyzer only follows provable edges)
    bad.write_text(bad.read_text().replace("  # ra-type: Bridge", ""))
    r = run_lint(str(bad))
    assert "RA02" not in r.stdout, r.stdout


def test_checker_detects_lock_order_cycle(tmp_path):
    """RA11: an ABBA pair — a-then-b on one path, b-then-a (through a
    helper call) on another — is a lock-order cycle; both directions
    are named.  A consistent hierarchy passes clean."""
    pkg = tmp_path / "store"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "store.py"
    mod.write_text(textwrap.dedent("""\
        import threading


        class Store:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def put(self):
                with self._a:
                    with self._b:
                        pass

            def flush(self):
                with self._b:
                    self._refresh()

            def _refresh(self):
                with self._a:
                    pass
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA11") == 2, r.stdout
    assert "Store._a" in r.stdout and "Store._b" in r.stdout
    # consistent a-then-b everywhere: clean
    mod.write_text(textwrap.dedent("""\
        import threading


        class Store:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def put(self):
                with self._a:
                    with self._b:
                        pass

            def flush(self):
                with self._a:
                    self._refresh()

            def _refresh(self):
                with self._b:
                    pass
    """))
    r = run_lint(str(pkg))
    assert "RA11" not in r.stdout, r.stdout


def test_checker_pins_the_fetch_term_abba_shape(tmp_path):
    """The exact shape RA11 caught LIVE in log/durable.py (ISSUE 14):
    a term lookup whose tail falls through to the io lock, called while
    the log lock is held, against a flush path that holds io-then-log.
    The PR 13 review fixed this class on the append path by hand; the
    analyzer found three surviving sites (_wal_notify/set_last_index/
    handle_written) — fixed in this PR and pinned clean below."""
    pkg = tmp_path / "logpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "durlog.py"
    mod.write_text(textwrap.dedent("""\
        import threading


        class Log:
            def __init__(self):
                self._lock = threading.RLock()
                self._io_lock = threading.Lock()

            def fetch_term(self, idx):
                with self._lock:
                    got = idx
                return self._segment_read(got)

            def _segment_read(self, idx):
                with self._io_lock:
                    return idx

            def handle_written(self, evt):
                with self._lock:
                    return self.fetch_term(evt)

            def flush(self):
                with self._io_lock:
                    with self._lock:
                        pass
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert "RA11" in r.stdout, r.stdout
    assert "Log._io_lock" in r.stdout and "Log._lock" in r.stdout
    # `# ra11-ok:` allowlists reviewed edges (both directions tagged)
    fixed = mod.read_text() \
        .replace("return self.fetch_term(evt)",
                 "return self.fetch_term(evt)  # ra11-ok: reviewed") \
        .replace("with self._lock:\n                pass",
                 "with self._lock:  # ra11-ok: reviewed\n"
                 "                pass")
    mod.write_text(fixed)
    r = run_lint(str(pkg))
    assert "RA11" not in r.stdout, r.stdout


def test_log_layer_is_ra11_clean():
    """The real log layer holds the documented io-then-log order with
    no cycle — the PR 13 ABBA class cannot reland (ISSUE 14
    acceptance pin; the three fixed sites live in durable.py)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "log"))
    assert "RA11" not in r.stdout, r.stdout
    r = run_lint(os.path.join(REPO, "ra_tpu", "log", "durable.py"))
    assert "RA11" not in r.stdout, r.stdout


def test_checker_ra11_lock_annotation_names_dynamic_locks(tmp_path):
    """`# ra11-lock: Name` names a dynamically passed lock so its
    acquisitions join the order graph (the small annotation ISSUE 14
    specifies for locks the resolver cannot type)."""
    pkg = tmp_path / "w"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "worker.py").write_text(textwrap.dedent("""\
        import threading


        class W:
            def __init__(self, shared):
                self._own = threading.Lock()
                self._shared = shared

            def a(self):
                with self._own:
                    with self._shared:  # ra11-lock: Pool.biglock
                        pass

            def b(self):
                with self._shared:  # ra11-lock: Pool.biglock
                    with self._own:
                        pass
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA11") == 2, r.stdout
    assert "Pool.biglock" in r.stdout and "W._own" in r.stdout


def test_checker_detects_worker_thread_device_ops(tmp_path):
    """RA12: jax.*/jnp.* calls, device_put and block_until_ready in the
    transitive closure of a threading.Thread target are flagged — the
    PR 11 mesh deadlock (an encode worker enqueuing device work against
    an in-flight pjit), as a lint.  Non-worker functions and
    non-package files are exempt."""
    pkg = tmp_path / "eng"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "shard.py"
    mod.write_text(textwrap.dedent("""\
        import threading

        import jax
        import jax.numpy as jnp


        class Shard:
            def start(self):
                self._t = threading.Thread(target=self._run, daemon=True)
                self._t.start()

            def _run(self):
                self._work()

            def _work(self):
                a = jnp.ones(3)
                jax.device_put(a)
                a.block_until_ready()

            def overview(self):
                return jnp.zeros(1)
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA12") == 3, r.stdout
    assert "_work" in r.stdout and "overview" not in r.stdout
    assert "jnp.ones" in r.stdout and "jax.device_put" in r.stdout
    assert ".block_until_ready()" in r.stdout
    # tagged host-materialization sites pass (and stay audit-live)
    fixed = mod.read_text() \
        .replace("a = jnp.ones(3)",
                 "a = jnp.ones(3)  # ra12-ok: pre-spawn smoke") \
        .replace("jax.device_put(a)",
                 "jax.device_put(a)  # ra12-ok: staged pre-spawn") \
        .replace("a.block_until_ready()",
                 "a.block_until_ready()  # ra12-ok: joined after stop")
    mod.write_text(fixed)
    r = run_lint(str(pkg))
    assert "RA12" not in r.stdout and "AUDIT" not in r.stdout, r.stdout
    # the same content OUTSIDE a package (no __init__.py) is not gated:
    # test harnesses and CLI tools own their whole process
    loose = tmp_path / "shard.py"
    loose.write_text(textwrap.dedent("""\
        import threading

        import jax.numpy as jnp


        class Shard:
            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                return jnp.ones(3)
    """))
    r = run_lint(str(loose))
    assert "RA12" not in r.stdout, r.stdout


def test_engine_and_parallel_are_ra12_clean():
    """ISSUE 14 acceptance pin: the real worker closures (WAL shard
    encode workers, supervisors, TCP/wire reader loops) are free of
    device ops — the sharded path materializes host-side ONCE via the
    annotated `bridge` seam (`EngineDurability._host_aux`, pure d2h),
    so the PR 11 deadlock class cannot reland."""
    for mod in ("ra_tpu/engine", "ra_tpu/parallel", "ra_tpu/log",
                "ra_tpu/wire", "ra_tpu/transport"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA12" not in r.stdout, (mod, r.stdout)


def test_engine_pipeline_closure_is_ra02_ra04_clean():
    """ISSUE 14: the cross-module closure walks step/superstep through
    the annotated seams (DispatchAheadDriver staging, the durability
    bridge, the sampler).  The syncs it surfaced — _host_mask's host
    coercion, _stage's staging encodes, _dispatch's window-boundary
    readback — are documented ra02-ok points; an UNtagged sync reached
    through any of these seams now fails the gate."""
    for mod in ("ra_tpu/engine/lockstep.py", "ra_tpu/engine/durable.py",
                "ra_tpu/parallel/mesh.py"):
        r = run_lint(os.path.join(REPO, *mod.split("/")))
        assert "RA02" not in r.stdout and "RA04" not in r.stdout, \
            (mod, r.stdout)


def test_checker_flags_drain_inside_bench_dispatch_loop(tmp_path):
    """`.drain()` is a full pipeline barrier — the strongest sync of
    all — and the pre-ISSUE-14 gate missed it inside measured loops."""
    bad = tmp_path / "bench.py"
    bad.write_text(textwrap.dedent("""\
        def run(driver, n, p):
            for _ in range(8):
                driver.submit(n, p)
                driver.drain()
            driver.drain()
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 1, r.stdout
    assert ".drain()" in r.stdout


def test_audit_flags_stale_suppressions(tmp_path):
    """The allowlist-rot gate: a raNN-ok tag on a line its rule family
    no longer flags is itself an error; live tags, tags inside string
    literals, and tests-dir files are exempt."""
    bad = tmp_path / "lockstep.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np


        def step(x):
            host = np.asarray(x)  # ra02-ok: documented readback
            y = 1 + 1  # ra02-ok: stale - nothing flagged here
            return host, y
    """))
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("AUDIT") == 1, r.stdout
    assert "stale suppression" in r.stdout and ":6:" in r.stdout
    # a tag inside a string literal is NOT a suppression comment
    strings = tmp_path / "strings.py"
    strings.write_text(
        'S = "np.asarray(x)  # ra02-ok: not a comment"\n')
    r = run_lint(str(strings))
    assert "AUDIT" not in r.stdout, r.stdout
    # tests-dir files are exempt (their tags live inside fixtures)
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "helper.py").write_text("y = 1  # ra02-ok: fixture text\n")
    r = run_lint(str(tdir / "helper.py"))
    assert "AUDIT" not in r.stdout, r.stdout


def test_suppression_tag_families_cover_shared_closures(tmp_path):
    """RA02/RA04 police the same host-sync class from different roots;
    one line reached by both carries ONE documented tag and either
    code's tag suppresses both (and stays audit-live)."""
    bad = tmp_path / "lockstep.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np


        def step(x):
            return tick(x)


        def tick(x):
            return np.asarray(x)  # ra02-ok: one tag for both closures
    """))
    r = run_lint(str(bad))
    # tick is reached from step's RA02 closure; under telemetry.py's
    # name it would ALSO be an RA04 root — the single ra02-ok tag
    # suppresses the family either way
    assert "RA02" not in r.stdout and "RA04" not in r.stdout, r.stdout
    assert "AUDIT" not in r.stdout, r.stdout


def test_analyzer_runtime_budget():
    """Satellite (ISSUE 14, re-measured for ISSUE 15): the whole-repo
    pass stays well inside a tier-1 budget — the gate must never
    become the slow step.  With the three jit-plane rule families
    (RA13/RA14/RA15) and the migrated FILE_RULES the measured full
    pass is ~7.6s on the builder box (~4s at PR 14); 60s absorbs
    shared-CI noise with a wide margin."""
    import time as _time
    t0 = _time.monotonic()
    r = run_lint()
    elapsed = _time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert elapsed < 60.0, f"analyzer too slow for tier-1: {elapsed:.1f}s"


def test_lint_changed_mode_runs():
    """`--changed` lints only files differing from HEAD (fast local
    loop).  Content depends on the working tree, so pin the contract:
    it runs, keeps the output format, and never scans MORE files than
    the default target set."""
    r = run_lint("--changed")
    assert r.returncode in (0, 1), r.stderr
    tail = r.stdout.strip().splitlines()[-1]
    assert tail.startswith("lint: ") and "files" in tail, r.stdout
    full = run_lint()
    n_changed = int(tail.split()[1])
    n_full = int(full.stdout.strip().splitlines()[-1].split()[1])
    assert n_changed <= n_full


def test_lint_json_output():
    """`--json` emits the machine-readable finding pool (findings +
    suppressed + file count) for CI tooling."""
    import json as _json
    r = run_lint("--json", os.path.join(REPO, "ra_tpu", "telemetry.py"))
    data = _json.loads(r.stdout)
    assert data["files"] == 1
    assert data["findings"] == []
    assert any(s["code"] in ("RA02", "RA04") for s in data["suppressed"])


def test_lint_report_output():
    """`--report` renders the grouped human view over the same pool."""
    r = run_lint("--report", os.path.join(REPO, "ra_tpu", "telemetry.py"))
    assert "static analysis report" in r.stdout
    assert "suppressed" in r.stdout


def test_ra11_mutual_recursion_is_order_independent(tmp_path):
    """Review regression pin: mutually recursive lock-takers must
    contribute their FULL transitive lock sets regardless of traversal
    order.  The first cut memoized a cycle-truncated DFS result, so an
    early caller could poison the memo and a genuine ABBA pair went
    unreported; the analyzer now SCC-collapses the call graph."""
    pkg = tmp_path / "rec"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(textwrap.dedent("""\
        import threading


        class R:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._c = threading.Lock()

            def early(self):
                # traversal bait: computes f's set before h needs g's
                self.f(3)

            def f(self, n):
                with self._a:
                    pass
                if n:
                    self.g(n - 1)

            def g(self, n):
                with self._b:
                    pass
                if n:
                    self.f(n - 1)

            def h(self):
                with self._c:
                    self.g(1)

            def inv(self):
                with self._a:
                    with self._c:
                        pass
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1, r.stdout
    assert "RA11" in r.stdout, r.stdout
    assert "R._c" in r.stdout and "R._a" in r.stdout, r.stdout


def test_lint_missing_target_fails_loudly():
    """Review regression pin: a typo'd explicit target must not report
    green having linted nothing."""
    r = run_lint("ra_tpu/enigne_typo.py")
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "no such target" in r.stderr, r.stderr


def test_ra12_gates_positional_thread_spawns(tmp_path):
    """Review regression pin: threading.Thread's FIRST positional
    parameter is `group` — `Thread(None, self._run)` must still harvest
    `_run` as a worker root."""
    pkg = tmp_path / "pos"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "w.py").write_text(textwrap.dedent("""\
        import threading

        import jax.numpy as jnp


        class W:
            def start(self):
                self._t = threading.Thread(None, self._run)
                self._t.start()

            def _run(self):
                return jnp.ones(3)
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert "RA12" in r.stdout and "_run" in r.stdout, r.stdout


def test_ra11_ignores_locks_in_deferred_callbacks(tmp_path):
    """Review regression pin: a `with self._a:` body that merely
    DEFINES a callback taking `self._b` does not hold a while taking b
    — deferred execution must not create acquisition-order edges (the
    first cut walked nested defs and reported a bogus ABBA)."""
    pkg = tmp_path / "cb"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(textwrap.dedent("""\
        import threading


        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._cbs = []

            def register(self):
                with self._a:
                    def cb():
                        with self._b:
                            pass
                    self._cbs.append(cb)

            def other(self):
                with self._b:
                    with self._a:
                        pass
    """))
    r = run_lint(str(pkg))
    assert "RA11" not in r.stdout, r.stdout


def test_ra11_flags_plain_lock_self_deadlock(tmp_path):
    """Review regression pin: re-acquiring a held plain threading.Lock
    is a GUARANTEED self-deadlock, not a benign reentry — the first cut
    dropped every same-lock edge, so `outer()` holding `_lock` and
    calling `inner()` (which takes `_lock` again) linted clean while
    hanging the process unconditionally.  RLock (and the RLock-backed
    default Condition) stay edge-free."""
    pkg = tmp_path / "sd"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    src = textwrap.dedent("""\
        import threading


        class Eng:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    return 1
    """)
    (pkg / "eng.py").write_text(src)
    r = run_lint(str(pkg))
    assert r.returncode == 1, r.stdout
    assert "RA11" in r.stdout and "self-deadlock" in r.stdout, r.stdout
    assert "Eng._lock" in r.stdout, r.stdout
    # reentrant ctors are exempt: the same shape over an RLock is fine
    (pkg / "eng.py").write_text(src.replace("threading.Lock()",
                                            "threading.RLock()"))
    r = run_lint(str(pkg))
    assert "RA11" not in r.stdout, r.stdout
    assert r.returncode == 0, r.stdout


def test_scoped_lint_keeps_cross_module_tags_live(tmp_path):
    """Review regression pin: rule roots are harvested from every
    indexed source module, not just the lint TARGETS — the first cut
    seeded roots from targets only, so linting a tagged helper alone
    (exactly what --changed does after editing it) lost the root one
    file away, read the tag as stale, and the fast loop false-failed
    on code the full run passes."""
    pkg = tmp_path / "scoped"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(textwrap.dedent("""\
        import numpy as np


        def pull(handle):
            return np.asarray(handle)  # ra04-ok: window boundary
    """))
    (pkg / "telemetry.py").write_text(textwrap.dedent("""\
        from .helpers import pull


        class S:
            def tick(self):
                return pull(self.handle)
    """))
    full = run_lint(str(pkg))
    assert full.returncode == 0, full.stdout
    scoped = run_lint(str(pkg / "helpers.py"))
    assert scoped.returncode == 0, scoped.stdout
    assert "AUDIT" not in scoped.stdout, scoped.stdout
    # and the gate itself still bites in the scoped run: untag the
    # helper and linting it ALONE must flag the cross-module sync
    (pkg / "helpers.py").write_text(textwrap.dedent("""\
        import numpy as np


        def pull(handle):
            return np.asarray(handle)
    """))
    scoped = run_lint(str(pkg / "helpers.py"))
    assert scoped.returncode == 1, scoped.stdout
    assert "RA04" in scoped.stdout, scoped.stdout


def test_lint_changed_rejects_explicit_paths():
    """Review regression pin: `--changed` with explicit targets used to
    silently lint the git-changed set and ignore the paths — now a loud
    usage error, like unknown flags."""
    r = run_lint("--changed", "ra_tpu")
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "no explicit targets" in r.stderr, r.stderr


def test_lint_syntax_prefix_contract(tmp_path):
    """Review regression pin: syntax findings keep the historical
    'path:N: syntax: msg' rendering (the colon after `syntax`) that CI
    greps key on."""
    bad = tmp_path / "syn.py"
    bad.write_text("def broken(:\n")
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert ": syntax: " in r.stdout, r.stdout


def test_scoped_lint_attributes_findings_to_reaching_roots(tmp_path):
    """Review regression pin (round 3): a finding carries exactly the
    root modules whose closure REACHES it — stamping the whole rule's
    root set made linting one root file report escapes only reachable
    from a different root (editing telemetry.py then `--changed` would
    false-fail on a pre-existing mesh-only escape)."""
    pkg = tmp_path / "attr"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(textwrap.dedent("""\
        import numpy as np


        def pull(handle):
            return np.asarray(handle)
    """))
    (pkg / "mesh.py").write_text(textwrap.dedent("""\
        from .helper import pull


        def drive_uniform_window(h):
            return pull(h)
    """))
    (pkg / "telemetry.py").write_text(textwrap.dedent("""\
        class S:
            def tick(self):
                return 1
    """))
    r = run_lint(str(pkg / "telemetry.py"))
    assert r.returncode == 0, r.stdout
    assert "helper.py" not in r.stdout, r.stdout
    r = run_lint(str(pkg / "mesh.py"))
    assert r.returncode == 1, r.stdout
    assert "RA04" in r.stdout and "helper.py" in r.stdout, r.stdout


def test_ra11_annotated_locks_never_claim_unproven_self_deadlock(
        tmp_path):
    """Review regression pin (round 3): `# ra11-lock:` is the escape
    hatch for locks the resolver cannot type — forcing ctor 'Lock' on
    it false-positived a self-deadlock on annotated RLocks/Conditions.
    Unknown ctor orders ABBA edges but never claims self-deadlock; an
    explicit `# ra11-lock: Name Ctor` token or the named class's
    indexed lock attr proves one."""
    pkg = tmp_path / "ann"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    body = textwrap.dedent("""\
        class W:
            def outer(self):
                with self._shared:  # ra11-lock: Pool.biglock{tok}
                    self.inner()

            def inner(self):
                with self._shared:  # ra11-lock: Pool.biglock{tok}
                    return 1
    """)
    (pkg / "m.py").write_text(body.format(tok=""))
    r = run_lint(str(pkg))
    assert "self-deadlock" not in r.stdout, r.stdout
    assert r.returncode == 0, r.stdout
    # pinning the ctor in the annotation proves the deadlock
    (pkg / "m.py").write_text(body.format(tok=" Lock"))
    r = run_lint(str(pkg))
    assert r.returncode == 1, r.stdout
    assert "self-deadlock" in r.stdout, r.stdout
    # the named class's indexed lock attr resolves the ctor too: an
    # RLock-typed Pool.biglock stays clean without any extra token
    (pkg / "m.py").write_text(
        "import threading\n\n\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self.biglock = threading.RLock()\n\n\n"
        + body.format(tok=""))
    r = run_lint(str(pkg))
    assert "self-deadlock" not in r.stdout, r.stdout


def test_lint_changed_fails_loudly_when_git_unavailable():
    """Review regression pin (round 3): `--changed` must not silently
    widen to the full default target set when git fails — that hands
    the user findings for files they never touched."""
    env = dict(os.environ, PATH="/nonexistent")
    r = subprocess.run([sys.executable, LINT, "--changed"],
                       capture_output=True, text=True, timeout=120,
                       env=env)
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "could not read the git diff" in r.stderr, r.stderr


# ---------------------------------------------------------------------------
# ISSUE 15 — the jit-plane analyzer (tools/analyzer/jitplane.py): traced-
# closure harvest, RA13 trace hazards, RA14 donation lifetime, RA15
# pytree/sharding/checkpoint schema, and the RA05/06/07 migration onto
# the engine's declarative FILE_RULES.
# ---------------------------------------------------------------------------

def test_checker_detects_trace_hazards(tmp_path):
    """RA13: inside a traced closure (here rooted by a module-level
    jax.jit), Python control flow on tracer-typed values, host-world
    calls, and concretizing casts are flagged; keyword-only params are
    static config (the repo's partial-bound idiom) and functions the
    traced world never reaches are exempt."""
    pkg = tmp_path / "plane"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "kernels.py"
    mod.write_text(textwrap.dedent("""\
        import time

        import jax
        import numpy as np


        def _step(state, n_new, *, window):
            if window:
                n_new = n_new + 0
            if state.sum() > 0:
                n_new = n_new + 1
            assert n_new.sum() >= 0
            flag = bool(state[0])
            t0 = time.time()
            host = np.asarray(n_new)
            v = state[0].item()
            return state + n_new, (flag, t0, host, v)


        STEP = jax.jit(_step)


        def overview(state):
            if state is None:
                return 0
            return state
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA13") == 6, out
    for frag in ("Python `if` on a traced value", "`assert` on a traced",
                 "bool() cast", "time.time()", "np.asarray() over a",
                 ".item() on a traced"):
        assert frag in out, (frag, out)
    # the static-config branch and the untraced function stay clean
    assert "overview" not in out, out
    assert ":8:" not in out, out  # `if window:` — keyword-only = static
    # tagged sites pass and stay audit-live
    fixed = mod.read_text()
    for line in ("if state.sum() > 0:", "assert n_new.sum() >= 0",
                 "flag = bool(state[0])", "t0 = time.time()",
                 "host = np.asarray(n_new)", "v = state[0].item()"):
        fixed = fixed.replace(line, line + "  # ra13-ok: fixture why")
    mod.write_text(fixed)
    r = run_lint(str(pkg))
    assert "RA13" not in r.stdout and "AUDIT" not in r.stdout, r.stdout
    # the same content OUTSIDE a package is not gated (CLI tools and
    # harnesses own their whole process, same boundary as RA12)
    loose = tmp_path / "kernels.py"
    loose.write_text(textwrap.dedent("""\
        import jax


        def _step(state):
            if state.sum() > 0:
                return state
            return state + 1


        STEP = jax.jit(_step)
    """))
    r = run_lint(str(loose))
    assert "RA13" not in r.stdout, r.stdout


def test_checker_traces_through_jit_wrapper_param(tmp_path):
    """The tentpole resolution shape: the repo jits through a wrapper
    (`_build_jit(fn, ...)` builds functools.partial(fn) and jits it),
    so the traced callable is a PARAMETER — the harvest must chase the
    wrapper's call sites and root the argument passed there."""
    pkg = tmp_path / "eng"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "lockjit.py").write_text(textwrap.dedent("""\
        import functools

        import jax


        def _step(state, n):
            while state.sum() > 0:
                state = state - n
            return state


        class Eng:
            def _build_jit(self, fn, donate):
                partial = functools.partial(fn, n=1)
                return jax.jit(partial,
                               donate_argnums=(0,) if donate else ())

            def compile(self):
                self._step = self._build_jit(_step, True)
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA13") == 1, r.stdout
    assert "Python `while` on a traced value" in r.stdout, r.stdout
    assert "_step" in r.stdout, r.stdout


def test_checker_traces_scan_and_cond_bodies(tmp_path):
    """lax.scan/cond body callables are traced roots even with no
    jax.jit in sight — scan bodies run under trace wherever the scan
    itself ends up."""
    pkg = tmp_path / "fold"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "folds.py").write_text(textwrap.dedent("""\
        from jax import lax


        def fold(xs, init):
            def body(carry, x):
                if x > 0:
                    carry = carry + x
                return carry, x
            return lax.scan(body, init, xs)


        def pick(pred, a, b):
            return lax.cond(pred,
                            lambda t: int(t[0]),
                            lambda t: 0,
                            (a, b))


        def route(i, x):
            def br0(t):
                return float(t)
            def br1(t):
                return t + 1
            return lax.switch(i, [br0, br1], x)
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA13") == 3, r.stdout
    assert "Python `if` on a traced value" in r.stdout
    assert "int() cast of a traced value" in r.stdout
    # switch branches ride ONE sequence argument — the harvest must
    # unpack the list, and operands must NOT be chased as callables
    # (review finding: positional slots 1-6 missed every real switch)
    assert "float() cast of a traced value" in r.stdout, r.stdout


def test_checker_detects_donated_buffer_read_after_call(tmp_path):
    """RA14 (lifetime half): reading the donated argument after the
    donating call is poison on backends where donation is real; the
    rebind-the-result shape (`self.state, aux = self._step(self.state,
    ...)`) is the sanctioned idiom and passes."""
    pkg = tmp_path / "don"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "eng.py").write_text(textwrap.dedent("""\
        import jax


        class Eng:
            def __init__(self, fn, state):
                self._step = jax.jit(fn, donate_argnums=(0,))
                self.state = state

            def bad(self, n):
                out, aux = self._step(self.state, n)
                return self.state.sum()

            def masked(self, n):
                out, aux = self._step(self.state, n)
                pre = self.state.sum()
                self.state = out
                return pre + self.state.sum()

            def good(self, n):
                self.state, aux = self._step(self.state, n)
                return self.state.sum()
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    # bad() reads with no rebind; masked() reads BEFORE a later rebind
    # (a post-rebind read must not mask it); good()'s rebind-at-call
    # is the sanctioned shape
    assert r.stdout.count("RA14") == 2, r.stdout
    assert "after it was DONATED" in r.stdout, r.stdout
    assert "self.state" in r.stdout, r.stdout
    assert ":15:" in r.stdout, r.stdout  # masked()'s pre-rebind read


def test_checker_detects_loop_carried_donation(tmp_path):
    """Review regression pin: a donating call inside a loop that never
    rebinds the donated key hands the invalidated buffer back in on
    the next iteration — a read the linear before/after scan cannot
    see.  A rebind in the loop body protects it, and a rebind inside a
    nested def (deferred execution) does NOT."""
    pkg = tmp_path / "loopdon"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "eng.py").write_text(textwrap.dedent("""\
        import jax


        class Eng:
            def __init__(self, fn, state):
                self._step = jax.jit(fn, donate_argnums=(0,))
                self.state = state

            def bad_loop(self, blocks):
                for b in blocks:
                    out, aux = self._step(self.state, b)
                return out

            def masked_by_nested_def(self, blocks):
                for b in blocks:
                    out, aux = self._step(self.state, b)

                    def cb():
                        self.state = out
                    self._cbs.append(cb)
                return out

            def good_loop(self, blocks):
                for b in blocks:
                    self.state, aux = self._step(self.state, b)
                return aux
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA14") == 2, r.stdout
    assert "inside a loop that never rebinds it" in r.stdout, r.stdout
    assert "good_loop" not in r.stdout


def test_checker_detects_aliased_pytree_leaves(tmp_path):
    """RA14 (aliasing half): the exact PR 6 shape as a fixture — ONE
    buffer binding passed as two NamedTuple leaves (or splatted across
    all of them) aliases one device buffer and trips the donating
    path's 'donate same buffer twice'; one constructor per leaf is the
    fix shape and passes."""
    pkg = tmp_path / "tel"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "telem.py"
    mod.write_text(textwrap.dedent("""\
        from typing import NamedTuple

        import jax.numpy as jnp


        class Telem(NamedTuple):
            a: object
            b: object


        def init_bad(n):
            z = jnp.zeros((n,), jnp.int32)
            return Telem(z, z)


        def init_splat(n):
            z = jnp.zeros((n,), jnp.int32)
            return Telem(*(z for _ in range(2)))


        def init_good(n):
            return Telem(*(jnp.zeros((n,), jnp.int32)
                           for _ in range(2)))
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA14") == 2, r.stdout
    assert "as two leaves" in r.stdout, r.stdout
    assert "splats ONE buffer binding" in r.stdout, r.stdout
    assert "init_good" not in r.stdout
    # tagged sites pass and stay audit-live
    fixed = mod.read_text() \
        .replace("return Telem(z, z)",
                 "return Telem(z, z)  # ra14-ok: fixture why") \
        .replace("return Telem(*(z for _ in range(2)))",
                 "return Telem(*(z for _ in range(2)))"
                 "  # ra14-ok: fixture why")
    mod.write_text(fixed)
    r = run_lint(str(pkg))
    assert "RA14" not in r.stdout and "AUDIT" not in r.stdout, r.stdout


def test_checker_enforces_state_shardings_coverage(tmp_path):
    """RA15(a): every schema field must be covered by the shardings
    dispatch — the fixture reproduces the PR 6 uncovered-telemetry
    shape (explicit per-field dict that forgot `telem`); generic
    `._fields` iteration is full coverage, but a by-name special case
    naming a NON-field is a stale dispatch arm."""
    pkg = tmp_path / "mesh"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "shards.py"
    mod.write_text(textwrap.dedent("""\
        from typing import NamedTuple


        class LaneState(NamedTuple):
            term: object
            ring: object
            telem: object


        def state_shardings(mesh, state: LaneState):
            return {"term": mesh, "ring": mesh}
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA15") == 1, r.stdout
    assert "does not cover" in r.stdout and "telem" in r.stdout
    # covering the field passes
    mod.write_text(mod.read_text().replace(
        'return {"term": mesh, "ring": mesh}',
        'return {"term": mesh, "ring": mesh, "telem": mesh}'))
    r = run_lint(str(pkg))
    assert "RA15" not in r.stdout, r.stdout
    # generic _fields iteration is full coverage; a special-case arm
    # naming a non-field is stale
    mod.write_text(textwrap.dedent("""\
        from typing import NamedTuple


        class LaneState(NamedTuple):
            term: object
            ring: object
            telem: object


        def state_shardings(mesh, state: LaneState):
            specs = {}
            for name in LaneState._fields:
                if name == "mac":
                    continue
                specs[name] = mesh
            return specs
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA15") == 1, r.stdout
    assert "special-cases 'mac'" in r.stdout, r.stdout
    mod.write_text(mod.read_text().replace('"mac"', '"ring"'))
    r = run_lint(str(pkg))
    assert "RA15" not in r.stdout, r.stdout


def test_checker_enforces_checkpoint_defaults_registry(tmp_path):
    """RA15(b): the schema module must declare a per-field
    CHECKPOINT_FIELD_DEFAULTS registry (parity with the schema, no
    stale keys) and restore() must consult it — the PR 6 pre-telemetry
    restore() KeyError, closed for every FUTURE field addition."""
    pkg = tmp_path / "ckpt"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "lanes.py"
    base = textwrap.dedent("""\
        from typing import NamedTuple


        class LaneState(NamedTuple):
            term: object
            telem: object


        def state_shardings(mesh, state: LaneState):
            return {"term": mesh, "telem": mesh}


        @REGISTRY@

        class Eng:
            def restore(self, path):
                @RESTORE@
    """)

    def build(registry, restore_body):
        return base.replace("@REGISTRY@", registry) \
                   .replace("@RESTORE@", restore_body)

    # no registry at all
    mod.write_text(build("", "return path"))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA15") == 1, r.stdout
    assert "no CHECKPOINT_FIELD_DEFAULTS registry" in r.stdout
    # registry missing a field + stale key + restore not consulting it
    mod.write_text(build(
        'CHECKPOINT_FIELD_DEFAULTS = {"term": "require", '
        '"mac": "zeros"}', "return path"))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    out = r.stdout
    assert out.count("RA15") == 3, out
    assert "missing" in out and "telem" in out
    assert "names ['mac']" in out, out
    assert "does not consult" in out, out
    # complete registry + consulting restore passes
    mod.write_text(build(
        'CHECKPOINT_FIELD_DEFAULTS = {"term": "require", '
        '"telem": "zeros"}',
        "return CHECKPOINT_FIELD_DEFAULTS.get(path)"))
    r = run_lint(str(pkg))
    assert "RA15" not in r.stdout, r.stdout


def test_checker_enforces_block_staging_coverage(tmp_path):
    """RA15(c): a staged superstep-block key with no entry in
    superstep_block_shardings repartitions the staged block on every
    dispatch (or rejects on a mesh) — the staging path's `.get` keys
    must all be covered."""
    pkg = tmp_path / "stage"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    mod = pkg / "driver.py"
    mod.write_text(textwrap.dedent("""\
        def superstep_block_shardings(mesh):
            return {"n_new": mesh, "payloads": mesh}


        class Driver:
            def _stage(self, blk):
                a = self.shardings.get("n_new")
                b = self.shardings.get("query")
                return a, b, blk
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("RA15") == 1, r.stdout
    assert "'query' has no entry" in r.stdout, r.stdout
    # a documented `# ra15-ok` tag suppresses and stays audit-live
    tagged = mod.read_text().replace(
        'b = self.shardings.get("query")',
        'b = self.shardings.get("query")  # ra15-ok: fixture why')
    mod.write_text(tagged)
    r = run_lint(str(pkg))
    assert "RA15" not in r.stdout and "AUDIT" not in r.stdout, r.stdout
    mod.write_text(tagged.replace("  # ra15-ok: fixture why", "")
                   .replace('{"n_new": mesh, "payloads": mesh}',
                            '{"n_new": mesh, "payloads": mesh, '
                            '"query": mesh}'))
    r = run_lint(str(pkg))
    assert "RA15" not in r.stdout, r.stdout


def test_jit_plane_modules_are_clean():
    """ISSUE 15 acceptance pin: the engine, mesh, ingress, machine and
    ops trees carry zero untagged RA13/RA14/RA15 findings — the jitted
    arithmetic stays trace-pure, donation lifetimes hold, and the
    schema contracts (shardings coverage, checkpoint defaults, block
    staging) are satisfied on main."""
    # one invocation, six targets: each full run rebuilds the whole-
    # program index (~8s), so per-target subprocesses would pay that
    # six times for the identical check (review finding)
    r = run_lint(*(os.path.join(REPO, *m.split("/"))
                   for m in ("ra_tpu/engine", "ra_tpu/parallel",
                             "ra_tpu/ingress", "ra_tpu/models",
                             "ra_tpu/core", "ra_tpu/ops")))
    for code in ("RA13", "RA14", "RA15"):
        assert code not in r.stdout, (code, r.stdout)


def test_cond_concrete_probe_is_tagged_and_audit_live():
    """The sanctioned concreteness probe (core/machine.py
    cond_concrete's bool(pred)) is a SUPPRESSED RA13 finding, not an
    absent one — the tag is live, so deleting the probe without
    removing the tag trips the audit."""
    import json as _json
    r = run_lint("--json",
                 os.path.join(REPO, "ra_tpu", "core", "machine.py"))
    data = _json.loads(r.stdout)
    assert data["findings"] == [], data["findings"]
    assert any(s["code"] == "RA13" and "bool()" in s["msg"]
               for s in data["suppressed"]), data["suppressed"]


def test_audit_covers_jitplane_tags(tmp_path):
    """The allowlist-rot audit extends to the new tag families: a
    ra13/ra14/ra15-ok tag on a line its rule no longer flags is an
    AUDIT error."""
    pkg = tmp_path / "rot"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(textwrap.dedent("""\
        X = 1  # ra13-ok: stale - nothing traced here
        Y = 2  # ra14-ok: stale
        Z = 3  # ra15-ok: stale
    """))
    r = run_lint(str(pkg))
    assert r.returncode == 1
    assert r.stdout.count("AUDIT") == 3, r.stdout


def test_file_rules_ride_the_engine(tmp_path):
    """ISSUE 15 satellite: RA05/RA06/RA07 are declarative FILE_RULES
    evaluated by the analyzer engine (one engine owns every rule).
    The behavioural contract is pinned by the per-rule tests above;
    this pins the MIGRATION — the specs live in the engine's rule
    table and the old lint-side walkers are gone."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from analyzer.rules import FILE_RULES
    finally:
        sys.path.pop(0)
    codes = {r.code for r in FILE_RULES}
    assert {"RA05", "RA06", "RA07", "RA16"} <= codes, codes
    import ast as _ast
    lint_src = open(LINT, encoding="utf-8").read()
    tree = _ast.parse(lint_src)
    defs = {n.name for n in _ast.walk(tree)
            if isinstance(n, (_ast.FunctionDef, _ast.AsyncFunctionDef))}
    for gone in ("_check_field_registry", "_check_event_registry_use",
                 "_check_autotune_contract"):
        assert gone not in defs, gone


# -- RA16: placement retry bounds (ISSUE 17) ------------------------------

_RA16_BB = 'EVENT_REGISTRY = {"placement.giveup": "doc"}\n'


def _ra16_fixture(tmp_path, body):
    """A fixture module inside a `placement/` dir (the rule's scope)
    with a local blackbox.py registering the give-up event."""
    pdir = tmp_path / "placement"
    pdir.mkdir(exist_ok=True)
    (pdir / "blackbox.py").write_text(_RA16_BB)
    mod = pdir / "sup.py"
    mod.write_text(body)
    return mod


def test_ra16_flags_unbounded_and_silent_retry_loops(tmp_path):
    """RA16: an unbounded escalation loop is flagged, and a bounded
    loop whose function never emits a registered give-up event is
    flagged too (exhaustion must be visible to the flight recorder)."""
    mod = _ra16_fixture(tmp_path, textwrap.dedent("""\
        import time
        from blackbox import record


        def unbounded(sid, cmd, router):
            while True:                     # RA16: no bound evidence
                res = process_command(sid, cmd, router)
                if res:
                    return res
                time.sleep(0.1)


        def bounded_but_silent(sid, cmd, router, clock):
            deadline = clock() + 5.0
            while clock() < deadline:       # RA16: bounded, no giveup
                res = process_command(sid, cmd, router)
                if res:
                    return res
            return None
    """))
    r = run_lint(str(mod))
    assert r.returncode == 1
    assert r.stdout.count("RA16") == 2, r.stdout
    assert "no deadline/bounded-attempt evidence" in r.stdout
    assert "never emits a registered record" in r.stdout


def test_ra16_full_shape_is_clean(tmp_path):
    """The supervisor's canonical shape passes: deadline in the loop
    test + a registered give-up record on exhaustion.  A bound-guarded
    break inside the body is accepted as bound evidence too."""
    mod = _ra16_fixture(tmp_path, textwrap.dedent("""\
        from blackbox import record


        def commit(attempt_fn, clock, timeout):
            deadline = clock() + timeout * 3
            attempts = 0
            while clock() < deadline:
                attempts += 1
                res = attempt_fn()
                if res is not None:
                    return res
            record("placement.giveup", what="commit",
                   attempts=attempts)
            raise RuntimeError("gave up")


        def poll(attempt_fn, max_tries):
            tries = 0
            while True:
                res = attempt_fn()
                if res is not None:
                    return res
                tries += 1
                if tries >= max_tries:      # bound-guarded raise
                    record("placement.giveup", what="poll",
                           attempts=tries)
                    raise RuntimeError("gave up")
    """))
    r = run_lint(str(mod))
    assert "RA16" not in r.stdout, r.stdout
    assert r.returncode == 0, r.stdout + r.stderr


def test_ra16_scope_and_suppression(tmp_path):
    """RA16 only gates files inside a `placement/` directory; inside
    the scope `# ra16-ok: <why>` allowlists a site and the audit
    flags the tag once the loop stops being a finding."""
    body = textwrap.dedent("""\
        import time


        def unbounded(sid, cmd, router):
            while True:
                res = process_command(sid, cmd, router)
                if res:
                    return res
                time.sleep(0.1)
    """)
    # same content OUTSIDE a placement/ dir: out of scope, clean
    other = tmp_path / "elsewhere.py"
    other.write_text(body)
    r = run_lint(str(other))
    assert "RA16" not in r.stdout, r.stdout
    # inside the scope, the tag suppresses (and stays audit-live)
    mod = _ra16_fixture(tmp_path, body.replace(
        "while True:",
        "while True:  # ra16-ok: fixture, externally watchdogged"))
    r = run_lint(str(mod))
    assert "RA16" not in r.stdout and "AUDIT" not in r.stdout, r.stdout
    # a tag on a line the rule no longer flags is itself an error
    stale = _ra16_fixture(tmp_path, textwrap.dedent("""\
        def fine():  # ra16-ok: stale
            return 1
    """))
    r = run_lint(str(stale))
    assert "stale suppression" in r.stdout, r.stdout


def test_placement_package_is_ra16_clean():
    """The live pin: every retry loop the real placement package ships
    satisfies its own rule (the supervisor's _commit deadline loop and
    the soak's recovery/drain loops carry bounds + give-up events)."""
    pkg = os.path.join(REPO, "ra_tpu", "placement")
    mods = [os.path.join(pkg, f) for f in sorted(os.listdir(pkg))
            if f.endswith(".py")]
    r = run_lint(*mods)
    assert "RA16" not in r.stdout, r.stdout


# -- ISSUE 20: read-plane closure gates ------------------------------------

def test_checker_gates_read_admission_lane(tmp_path):
    """RA08 (read extension, ISSUE 20): per-session Python loops and
    dict allocation in the ingress read lane (submit_reads /
    _pop_read_block / _harvest_reads / _emit_read_replies + their
    same-module closure) are flagged; scoped to ingress/__init__.py
    only; `# ra08-ok:` allowlists survive."""
    pkg = tmp_path / "ingress"
    pkg.mkdir()
    bad = pkg / "__init__.py"
    body = textwrap.dedent("""\
        import numpy as np

        class Plane:
            def submit_reads(self, handles, seqnos, queries):
                for h in handles:                     # RA08: loop
                    self.pending[h] = 1
                return np.asarray(handles)

            def _emit_read_replies(self, blk, mask, status, wms, reps):
                out = {"rows": len(blk)}              # RA08: dict
                return out

            def read_overview(self):
                # NOT hot: overview is control-plane reporting
                return {k: 1 for k in ["a", "b"]}
    """)
    bad.write_text(body)
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA08") == 2, r.stdout
    assert "submit_reads()" in r.stdout
    assert "_emit_read_replies()" in r.stdout
    assert "read_overview()" not in r.stdout
    # allowlisted lines pass
    bad.write_text(body
                   .replace("for h in handles:",
                            "for h in handles:  # ra08-ok: tiny")
                   .replace('out = {"rows": len(blk)}',
                            'out = {"rows": len(blk)}  # ra08-ok: once'))
    r = run_lint(str(bad))
    assert "RA08" not in r.stdout, r.stdout
    # same content outside an ingress/ package: out of scope
    other = tmp_path / "plane.py"
    other.write_text(body)
    r = run_lint(str(other))
    assert "RA08" not in r.stdout, r.stdout


def test_checker_gates_read_reply_egress(tmp_path):
    """RA09 (read extension, ISSUE 20): per-read Python in the wire
    server's READ_REPLY egress (_on_reads_served /
    collect_read_replies + closure) is flagged; scoped to
    wire/server.py only."""
    pkg = tmp_path / "wire"
    pkg.mkdir()
    bad = pkg / "server.py"
    body = textwrap.dedent("""\
        import numpy as np

        class Server:
            def _on_reads_served(self, handles, seqnos, sts, wms, reps):
                frames = [bytes(r) for r in reps]     # RA09: per-read
                meta = {"n": len(handles)}            # RA09: dict
                return frames, meta

            def overview(self):
                # NOT hot
                return [i for i in range(3)]
    """)
    bad.write_text(body)
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA09") == 2, r.stdout
    assert "_on_reads_served()" in r.stdout
    assert "overview()" not in r.stdout
    # same content outside a wire/ dir: out of scope
    other = tmp_path / "server.py"
    other.write_text(body)
    r = run_lint(str(other))
    assert "RA09" not in r.stdout, r.stdout


def test_checker_gates_driver_read_observer(tmp_path):
    """RA04 (read extension, ISSUE 20): a blocking device sync inside
    the driver's read observer (_observe_reads + closure in
    lockstep.py) is flagged — the observer may only touch COMPLETED
    async read-aux copies."""
    bad = tmp_path / "lockstep.py"
    body = textwrap.dedent("""\
        import numpy as np

        class Driver:
            def _observe_reads(self, t_sub, robs):
                robs["read_done"].block_until_ready()  # RA04: sync
                return self._decode(robs)

            def _decode(self, robs):
                return np.asarray(robs["read_replies"])  # RA04: sync

            def read_overview(self):
                # not on the observer path
                return np.asarray([1, 2]).item()
    """)
    bad.write_text(body)
    r = run_lint(str(bad))
    assert r.returncode == 1
    assert r.stdout.count("RA04") == 2, r.stdout
    assert "_observe_reads" in r.stdout or "_decode" in r.stdout
    # other module names are not gated by this scope
    other = tmp_path / "driver.py"
    other.write_text(body)
    r = run_lint(str(other))
    assert "RA04" not in r.stdout, r.stdout


def test_read_plane_modules_are_read_gate_clean():
    """Live pins: the real read lane satisfies its own gates — the
    ingress admission/reply lane (RA08), the wire READ_REPLY egress
    (RA09), and the driver read observer (RA04)."""
    r = run_lint(os.path.join(REPO, "ra_tpu", "ingress", "__init__.py"))
    assert "RA08" not in r.stdout, r.stdout
    r = run_lint(os.path.join(REPO, "ra_tpu", "wire", "server.py"))
    assert "RA09" not in r.stdout, r.stdout
    r = run_lint(os.path.join(REPO, "ra_tpu", "engine", "lockstep.py"))
    assert "RA04" not in r.stdout, r.stdout
