"""Storage-plane fault injection + crash-degradation policy (ISSUE 4).

The recovery matrix the acceptance criteria pin: for each fault class
(fsync-EIO, ENOSPC, short/torn write, read-side bit corruption) x each
storage plane (WAL, segment, snapshot), the degradation ladder
(poison -> rollover -> resend; retry -> escalate; pending-dir skip)
keeps the system live, recovery replays to oracle-exact state, and no
acknowledged index ever exceeds what a cold restart can recover — the
fsynced watermark (asserted via DISK_FAULT_FIELDS + the confirm-vector
checks).  The fsyncgate discipline is pinned throughout:
``fsync_retries_after_failure`` must stay 0.

Plus: plan determinism, the WAL escalation ladder, the segment-flush
escalation hook, and the combined transport+disk+crash nemesis run
checked by the linearizability checker under a fixed seed.
"""
import os
import threading
import time

import pytest

import ra_tpu
from ra_tpu import LocalRouter, RaNode, RaSystem
from ra_tpu.core.machine import SimpleMachine
from ra_tpu.core.types import Entry, ServerConfig, ServerId, \
    UserCommand, WrittenEvent
from ra_tpu.log import faults
from ra_tpu.log.faults import DiskFaultPlan, DiskFaultSpec

from nemesis import Nemesis, await_leader

# injected faults legitimately kill the WAL batch thread on escalation —
# that is the ladder's last rung, not a test failure
pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults.clear_plan()
    faults.reset_disk_fault_counters()
    yield
    faults.clear_plan()
    faults.reset_disk_fault_counters()


def mk_log(system, uid="u1"):
    cfg = ServerConfig(server_id=None, uid=uid, cluster_name="c",
                       initial_members=(), machine=None)
    return system.log_factory(cfg)


def drain(log, upto, timeout=10.0):
    """Pump written events until last_written reaches ``upto`` (faulted
    batches confirm late: resends ride the fresh post-rollover file)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for e in log.take_events():
            if isinstance(e, WrittenEvent):
                log.handle_written(e)
        if log.last_written().index >= upto:
            return
        time.sleep(0.005)
    raise TimeoutError(
        f"log never confirmed up to {upto} "
        f"(at {log.last_written().index}); {faults.disk_fault_counters()}")


def append_range(log, lo, hi):
    for i in range(lo, hi + 1):
        log.append(Entry(i, 1, UserCommand(i)))


def verify_oracle(tmp_path, uid, hi, snap_idx=0):
    """Cold restart: every entry above the snapshot floor is present
    with its oracle value — the recovery-replays-to-oracle-exact check."""
    sys2 = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log2 = mk_log(sys2, uid)
        assert log2.last_index_term().index >= hi
        for i in range(max(1, snap_idx + 1), hi + 1):
            ent = log2.fetch(i)
            assert ent is not None, i
            assert ent.command.data == i, (i, ent.command.data)
    finally:
        sys2.close()


WRITE_FAULTS = {
    "fsync_eio": dict(fsync_eio=1.0, limit=2),
    "enospc": dict(enospc=1.0, limit=2),
    "short_write": dict(short_write=1.0, limit=2),
}


# ---------------------------------------------------------------------------
# plan determinism + shim basics
# ---------------------------------------------------------------------------

def test_plan_streams_are_deterministic():
    def draws(plan):
        return [plan.decide("wal", "fsync", "/d/00000001.wal")[0]
                for _ in range(32)]

    spec = DiskFaultSpec(fsync_eio=0.5)
    a = draws(DiskFaultPlan(seed=7, by_class={"wal": spec}))
    b = draws(DiskFaultPlan(seed=7, by_class={"wal": spec}))
    assert a == b
    assert "fsync_eio" in a
    c = draws(DiskFaultPlan(seed=8, by_class={"wal": spec}))
    assert a != c  # a different seed is a different schedule
    # streams are independent: draining another stream first must not
    # perturb this one
    p = DiskFaultPlan(seed=7, by_class={"wal": spec,
                                        "segment": spec})
    for _ in range(100):
        p.decide("segment", "fsync", "/d/x.segment")
    p2 = DiskFaultPlan(seed=7, by_class={"wal": spec})
    assert draws(p) == draws(p2)


def test_plan_limit_and_rules_resolution():
    spec = DiskFaultSpec(enospc=1.0, limit=3)
    plan = DiskFaultPlan(seed=1, rules=[
        ("wal", DiskFaultSpec(enospc=1.0, limit=1,
                              path_match="shard03")),
        ("wal", spec),
    ])
    # the shard03 rule wins for matching paths and spends only ITS limit
    assert plan.decide("wal", "write", "/d/shard03/wal/1.wal")[0] == \
        "enospc"
    assert plan.decide("wal", "write", "/d/shard03/wal/1.wal")[0] == "ok"
    # other wal paths resolve to the broad rule (its own 3-fault budget)
    kinds = [plan.decide("wal", "write", "/d/wal/1.wal")[0]
             for _ in range(5)]
    assert kinds == ["enospc"] * 3 + ["ok", "ok"]
    # unmatched classes fall through to the quiet default
    assert plan.decide("segment", "write", "/d/s.segment")[0] == "ok"


def test_classify_path():
    cp = faults.classify_path
    assert cp("/d/wal/00000001.wal") == "wal"
    assert cp("/d/u1/00000003.segment") == "segment"
    assert cp("/d/u1/00000003.segment.trunc") == "segment"
    assert cp("/d/u1/snapshot/snap_1_1.rtsn") == "snapshot"
    assert cp("/d/u1/snapshot/snap_1_1.rtsn.partial") == "snapshot"
    assert cp("/d/u1/snapshot/accept.partial") == "snapshot"
    assert cp("/d/u1/checkpoints/cp_1_1.rtsn") == "snapshot"
    assert cp("/d/u1/meta") == "meta"
    assert cp("/d/u1/meta.partial") == "meta"
    assert cp("/d/whatever.bin") == "other"


# ---------------------------------------------------------------------------
# WAL plane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fault", sorted(WRITE_FAULTS))
def test_wal_write_fault_matrix(tmp_path, fault):
    """A failed WAL batch write/fsync poisons the file, rolls over, and
    resends — confirmation is withheld until the entries are really
    durable, nothing acknowledged is lost across a cold restart, and
    the fsyncgate discipline holds (no fsync retried on a failed fd)."""
    sys_ = RaSystem(str(tmp_path), wal_supervise=True)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 10)
        drain(log, 10)

        faults.install_plan(DiskFaultPlan(
            seed=3, by_class={"wal": DiskFaultSpec(**WRITE_FAULTS[fault])}))
        append_range(log, 11, 30)
        drain(log, 30)
        faults.clear_plan()

        ctr = faults.disk_fault_counters()
        assert ctr["faults_injected"] >= 1, ctr
        assert ctr["faults_hit"] >= 1, ctr
        assert ctr["poisoned_files"] >= 1, ctr
        # the ladder rolled over (or escalated to a supervised restart)
        assert ctr["fault_rollovers"] + ctr["wal_escalations"] >= 1, ctr
        # fsyncgate: the policy NEVER re-syncs a failed fd
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        observed_lw = log.last_written().index
        assert observed_lw == 30
    finally:
        faults.clear_plan()
        sys_.close()
    # the fsynced-watermark check: everything ever confirmed must be
    # recoverable from disk alone
    verify_oracle(tmp_path, "u1", 30)


def test_wal_recovery_read_corruption_caught_by_crc(tmp_path):
    """Read-side bit rot during WAL recovery: the record crc catches it
    (crc_catches), the scan retries with a fresh read, and recovery is
    oracle-exact."""
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    log = mk_log(sys_)
    append_range(log, 1, 30)
    drain(log, 30)
    sys_.close()

    faults.install_plan(DiskFaultPlan(
        seed=5, by_class={"wal": DiskFaultSpec(corrupt_read=1.0,
                                               limit=1)}))
    try:
        verify_oracle(tmp_path, "u1", 30)
    finally:
        faults.clear_plan()
    ctr = faults.disk_fault_counters()
    assert ctr["faults_injected"] >= 1, ctr
    assert ctr["crc_catches"] >= 1, ctr


def test_wal_escalation_ladder_hands_off_to_supervisor(tmp_path):
    """MAX_POISON_STREAK consecutive faulted batches escalate to thread
    death; the system supervisor restarts the WAL and the writers
    resend — the last two rungs of the ladder compose."""
    from ra_tpu.log.wal import MAX_POISON_STREAK

    sys_ = RaSystem(str(tmp_path), wal_supervise=True)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 5)
        drain(log, 5)
        # unbounded fsync failure: rollover cannot outrun it, so the
        # ladder must escalate within MAX_POISON_STREAK batches
        faults.install_plan(DiskFaultPlan(
            seed=11, by_class={"wal": DiskFaultSpec(
                fsync_eio=1.0, limit=2 * MAX_POISON_STREAK)}))
        append_range(log, 6, 20)
        drain(log, 20, timeout=15.0)
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["wal_escalations"] >= 1, ctr
        assert ctr["fsync_retries_after_failure"] == 0, ctr
    finally:
        faults.clear_plan()
        sys_.close()
    verify_oracle(tmp_path, "u1", 20)


def test_sync_after_notify_fault_rewrites_confirmed_suffix(tmp_path):
    """sync_after_notify's documented weaker window: a batch is
    confirmed BEFORE its durability syscall.  When that syscall fails,
    the poison path must pull the resend floor below the already-
    confirmed suffix so it is re-written into the fresh file — on disk
    the full log survives even though the poisoned file's tail never
    fsynced."""
    from ra_tpu.log.wal import Wal, scan_wal_file

    sent: dict = {}
    confirmed: list = []

    wal = Wal(str(tmp_path), sync_mode=1,
              write_strategy="sync_after_notify")
    try:
        def notify(uid, lo, hi, term):
            if lo is None:
                # resend_from protocol: the writer re-submits above hi
                for i in sorted(sent):
                    if i > hi:
                        wal.write(uid, i, 1, sent[i])
            else:
                confirmed.append((lo, hi))

        wal.register("u1", notify)
        faults.install_plan(DiskFaultPlan(
            seed=27, by_class={"wal": DiskFaultSpec(fsync_eio=1.0,
                                                    limit=1)}))
        for i in range(1, 21):
            sent[i] = f"v-{i}".encode()
            wal.write("u1", i, 1, sent[i])
        wal.flush(timeout=10.0)
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["poisoned_files"] >= 1, ctr
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        assert confirmed and max(hi for _lo, hi in confirmed) == 20
    finally:
        faults.clear_plan()
        wal.close()
    tables: dict = {}
    wdir = os.path.join(str(tmp_path), "wal")
    for f in sorted(os.listdir(wdir)):
        if f.endswith(".wal"):
            scan_wal_file(os.path.join(wdir, f), tables)
    got = tables.get("u1", {})
    assert sorted(got) == list(range(1, 21)), sorted(got)
    assert got[20][1] == b"v-20"


def test_sync_after_notify_rewind_reaches_durable_log(tmp_path):
    """Contract pin for the term=-2 resend signal (the sync_after_notify
    poison path): a DurableLog floor-clamps plain resends (term=-1) to
    its last_written, so a confirm processed BEFORE the failed
    durability syscall would leave the confirmed suffix only in the
    poisoned (never-fsynced) file.  The -2 signal must pull last_written
    back to the floor and re-write the memtable-resident suffix into
    the current (fresh) file."""
    from ra_tpu.log.durable import DurableLog
    from ra_tpu.log.wal import Wal, scan_wal_file

    wal = Wal(str(tmp_path), sync_mode=1)
    try:
        log = DurableLog("u1", str(tmp_path), wal)
        append_range(log, 1, 25)
        drain(log, 25)
        assert log.last_written().index == 25
        base_resends = log.counters["write_resends"]

        # a PLAIN resend_from(10) is floor-clamped: everything <= 25 is
        # (as far as this writer knows) durable, nothing is re-written
        log._wal_notify("u1", None, 10, -1)
        assert log.counters["write_resends"] == base_resends
        assert log.last_written().index == 25

        # the rewind signal: confirms above 10 rode a failed syscall.
        # last_written pulls back and [11..25] re-enter the WAL queue.
        wal.rollover()   # fresh file, as the poison path produces
        log._wal_notify("u1", None, 10, -2)
        assert log.last_written().index == 10
        assert log.counters["write_resends"] == base_resends + 15
        drain(log, 25)   # the resends confirm again
        wal.flush()
    finally:
        wal.close()
    # the LAST file alone re-covers the rewound suffix
    wdir = os.path.join(str(tmp_path), "wal")
    last = sorted(f for f in os.listdir(wdir) if f.endswith(".wal"))[-1]
    tables: dict = {}
    scan_wal_file(os.path.join(wdir, last), tables)
    got = tables.get("u1", {})
    assert set(range(11, 26)) <= set(got), sorted(got)
    # and cold recovery over all files is oracle-exact
    verify_oracle(tmp_path, "u1", 25)


# ---------------------------------------------------------------------------
# segment plane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fault", sorted(WRITE_FAULTS))
def test_segment_flush_fault_matrix(tmp_path, fault):
    """Segment-flush I/O errors ride the retry-with-backoff rung
    (flush() bookkeeping is retry-shaped: identical pwrites, re-dirtied
    pages) and the memtable keeps every entry until the flush really
    lands — reads and a cold restart stay oracle-exact."""
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 40)
        drain(log, 40)
        faults.install_plan(DiskFaultPlan(
            seed=9, by_class={
                "segment": DiskFaultSpec(**WRITE_FAULTS[fault])}))
        sys_.wal.rollover()
        sys_.wal.flush()   # barrier: ranges handed to the segment writer
        sys_.segment_writer.await_idle()
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["faults_injected"] >= 1, ctr
        assert ctr["flush_retries"] >= 1, ctr
        assert ctr["flush_escalations"] == 0, ctr  # budget was enough
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        # flushed out of the memtable and readable from segments
        for i in (1, 20, 40):
            assert log.fetch(i).command.data == i
    finally:
        faults.clear_plan()
        sys_.close()
    verify_oracle(tmp_path, "u1", 40)


def test_segment_read_corruption_caught_by_crc(tmp_path):
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 40)
        drain(log, 40)
        sys_.wal.rollover()
        sys_.wal.flush()   # barrier: ranges handed to the segment writer
        sys_.segment_writer.await_idle()
        assert log.overview()["num_mem_entries"] == 0  # segment-resident
        faults.install_plan(DiskFaultPlan(
            seed=13, by_class={"segment": DiskFaultSpec(
                corrupt_read=1.0, limit=1)}))
        # the corrupt pread is caught by the entry crc and retried
        for i in range(1, 41):
            assert log.fetch(i).command.data == i
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["crc_catches"] >= 1, ctr
    finally:
        faults.clear_plan()
        sys_.close()


def test_segment_flush_escalation_hook_fires(tmp_path):
    """Retry budget exhausted -> flush_escalations + the system hook;
    the WAL file is KEPT, so a cold restart still recovers everything
    acknowledged (degraded means 'WAL files accumulate', never loss)."""
    escalated = []
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        sys_.on_flush_escalation = lambda uid, exc: escalated.append(uid)
        log = mk_log(sys_)
        append_range(log, 1, 20)
        drain(log, 20)
        wal_dir = sys_.wal.dir
        # enough budget to outlast every retry attempt
        faults.install_plan(DiskFaultPlan(
            seed=17, by_class={"segment": DiskFaultSpec(fsync_eio=1.0)}))
        sys_.wal.rollover()
        sys_.wal.flush()   # barrier: ranges handed to the segment writer
        sys_.segment_writer.await_idle(timeout=30.0)
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["flush_escalations"] >= 1, ctr
        assert escalated == ["u1"], escalated
        # the rolled WAL file survived the failed flush
        rolled = [f for f in os.listdir(wal_dir) if f.endswith(".wal")]
        assert len(rolled) >= 2, rolled
    finally:
        faults.clear_plan()
        sys_.close()
    verify_oracle(tmp_path, "u1", 20)


def test_flush_skips_already_segment_durable_duplicates(tmp_path):
    """Regression pin (found by the poison/rollover chaos): a memtable
    duplicate of an entry already durable in a segment (same term) must
    NOT be re-appended at its lower index — the segment's overwrite-
    invalidation would wipe every durable entry above it.  A term
    MISMATCH is a genuine overwrite and must still invalidate."""
    from ra_tpu.core.types import UserCommand as UC
    from ra_tpu.log.durable import encode_command

    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 20)
        drain(log, 20)
        sys_.wal.rollover()
        sys_.wal.flush()
        sys_.segment_writer.await_idle()
        assert log.overview()["num_mem_entries"] == 0
        # a recovered duplicate re-enters the memtable (same term/value)
        with log._lock:
            log._memtable[5] = Entry(5, 1, UC(5))
            log._mem_bytes[5] = encode_command(UC(5))
        log.flush_mem_to_segments(20)
        # nothing above 5 was wiped; the duplicate pruned (it IS durable)
        assert log.overview()["num_mem_entries"] == 0
        for i in range(1, 21):
            assert log.fetch(i).command.data == i, i
        # term mismatch = real overwrite: the stale tail must go
        with log._lock:
            log._memtable[5] = Entry(5, 2, UC(500))
            log._mem_bytes[5] = encode_command(UC(500))
            log._last_index, log._last_term = 5, 2
            log._last_written = type(log._last_written)(4, 1)
        log.flush_mem_to_segments(5)
        assert log.fetch(5).command.data == 500
        assert log._segment_read(6) is None  # invalidated with the tail
    finally:
        sys_.close()


def test_recovery_contiguity_clamp_on_holed_disk(tmp_path):
    """Regression pin: a disk state whose WAL files cover [1..10] and
    [15..20] (a lost middle from a crashed unconfirmed batch) must
    recover as the honest contiguous prefix [1..10] — never a
    last_index of 20 over a hole, which could win elections it must
    lose."""
    from ra_tpu.log.durable import encode_command
    from ra_tpu.core.types import UserCommand as UC
    from ra_tpu.log.wal import Wal

    # file 1: entries 1..10, clean
    wal = Wal(str(tmp_path), sync_mode=1)
    wal.register("u1", lambda *a: None)
    for i in range(1, 11):
        wal.write("u1", i, 1, encode_command(UC(i)))
    wal.flush()
    wal.close()
    # file 2: a fresh incarnation accepts 15.. (no gap check on a fresh
    # writer) — the crash-window disk shape the live path now prevents
    wal2 = Wal(str(tmp_path), sync_mode=1)
    wal2.register("u1", lambda *a: None)
    for i in range(15, 21):
        wal2.write("u1", i, 1, encode_command(UC(i)))
    wal2.flush()
    wal2.close()

    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log = mk_log(sys_)
        assert log.last_index_term().index == 10
        assert log.last_written().index == 10
        for i in range(1, 11):
            assert log.fetch(i).command.data == i
        assert log.fetch(15) is None
    finally:
        sys_.close()


# ---------------------------------------------------------------------------
# snapshot plane
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fault", sorted(WRITE_FAULTS))
def test_snapshot_write_fault_matrix(tmp_path, fault):
    """Pending-dir discipline: a torn/failed container write can never
    shadow a good snapshot — the release cursor simply does not
    advance, the log stays intact, and a clean retry succeeds."""
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log = mk_log(sys_)
        append_range(log, 1, 20)
        drain(log, 20)
        faults.install_plan(DiskFaultPlan(
            seed=21, by_class={
                "snapshot": DiskFaultSpec(**WRITE_FAULTS[fault])}))
        log.update_release_cursor(10, (), 0, {"count": 10})
        ctr = faults.disk_fault_counters()
        assert ctr["snapshot_write_failures"] >= 1, ctr
        # no torn container reached the slot; the full log is intact
        assert log.snapshot_index_term().index == 0
        for i in (1, 10, 20):
            assert log.fetch(i).command.data == i
        faults.clear_plan()
        # clean retry truncates below the snapshot as usual
        log.update_release_cursor(10, (), 0, {"count": 10})
        assert log.snapshot_index_term().index == 10
        assert log.first_index() == 11
    finally:
        faults.clear_plan()
        sys_.close()
    verify_oracle(tmp_path, "u1", 20, snap_idx=10)


def test_snapshot_read_corruption_caught_by_crc(tmp_path):
    sys_ = RaSystem(str(tmp_path), wal_supervise=False)
    log = mk_log(sys_)
    append_range(log, 1, 20)
    drain(log, 20)
    log.update_release_cursor(12, (), 0, {"count": 12})
    sys_.close()

    faults.install_plan(DiskFaultPlan(
        seed=23, by_class={"snapshot": DiskFaultSpec(corrupt_read=1.0,
                                                     limit=1)}))
    sys2 = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        log2 = mk_log(sys2)
        # the container crc caught the flipped bit; the fresh re-read
        # recovered the good bytes instead of rewinding machine state
        assert log2.snapshot_index_term().index == 12
        got = log2.recover_snapshot_state()
        assert got is not None and got[1] == {"count": 12}
        for i in range(13, 21):
            assert log2.fetch(i).command.data == i
        ctr = faults.disk_fault_counters()
        assert ctr["crc_catches"] >= 1, ctr
    finally:
        faults.clear_plan()
        sys2.close()


# ---------------------------------------------------------------------------
# combined transport + disk + crash chaos, linearizability-checked
# ---------------------------------------------------------------------------

def _start_durable_cluster(tmp_path, sids, router):
    systems = {s.node: RaSystem(str(tmp_path / s.node)) for s in sids}
    nodes = {s.node: RaNode(s.node, router=router,
                            log_factory=systems[s.node].log_factory)
             for s in sids}
    for sid in sids:
        nodes[sid.node].start_server(ServerConfig(
            server_id=sid, uid=f"uid_{sid.name}", cluster_name="dzchaos",
            initial_members=tuple(sids),
            machine=SimpleMachine(lambda c, s: c, 0),
            election_timeout_ms=120, tick_interval_ms=50))
    return systems, nodes


def test_combined_transport_disk_crash_chaos_linearizable(tmp_path):
    """The acceptance soak: concurrent register writes + linearizable
    reads against a durable 3-node cluster while a FIXED-SEED nemesis
    schedule composes partitions (transport plane), a DiskFaultPlan
    episode (storage plane) and a WAL crash (process plane) — the full
    history passes the Wing & Gong linearizability check."""
    from test_linearizability import check_register_linearizable

    router = LocalRouter()
    sids = [ServerId(f"dz{i}", f"dzn{i}") for i in (1, 2, 3)]
    systems, nodes = _start_durable_cluster(tmp_path, sids, router)
    history: list = []
    hlock = threading.Lock()
    stop = threading.Event()

    def record(op, value, invoke, complete):
        with hlock:
            history.append({"op": op, "value": value,
                            "invoke": invoke, "complete": complete})

    try:
        ra_tpu.trigger_election(sids[0], router)
        await_leader(router, sids)

        def writer(tid):
            v = tid * 1000
            for _ in range(25):
                if stop.is_set():
                    break
                v += 1
                t0 = time.monotonic()
                try:
                    ra_tpu.process_command(sids[tid % 3], v,
                                           router=router, timeout=2)
                    record("write", v, t0, time.monotonic())
                except Exception:
                    record("write", v, t0, None)   # indeterminate
                time.sleep(0.03)

        def reader():
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    r = ra_tpu.consistent_query(sids[1], lambda s: s,
                                                router=router, timeout=2)
                    record("read", r.reply, t0, time.monotonic())
                except Exception:
                    pass                            # failed read: no info
                time.sleep(0.05)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in (1, 2)] + [threading.Thread(target=reader)]
        for th in threads:
            th.start()

        plan = DiskFaultPlan(seed=42, by_class={
            "wal": DiskFaultSpec(fsync_eio=0.25, short_write=0.1,
                                 limit=8),
            "segment": DiskFaultSpec(fsync_eio=0.3, limit=4),
        })
        Nemesis(router, nodes.values(), seed=42,
                systems=systems).run([
            ("wait", 0.4),
            ("disk_faults", plan),
            ("part", (("dzn1", "dzn2"), ("dzn3",)), 0.5),
            ("wal_kill", "dzn2"),
            ("wait", 0.6),
            ("disk_heal",),
            ("part", (("dzn1",), ("dzn2",)), 0.4),
            ("heal",),
            ("wait", 0.5),
        ])
        stop.set()
        for th in threads:
            th.join(timeout=15)
        assert len(history) >= 20, len(history)
        determinate = [h for h in history if h["complete"] is not None]
        assert any(h["op"] == "read" for h in determinate)
        assert check_register_linearizable(history), history
        ctr = faults.disk_fault_counters()
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        # the killed WAL came back under supervision
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                not systems["dzn2"].wal.alive:
            time.sleep(0.02)
        assert systems["dzn2"].wal.alive
    finally:
        stop.set()
        faults.clear_plan()
        for n in nodes.values():
            n.stop()
        for s in systems.values():
            s.close()


# ---------------------------------------------------------------------------
# soak entry point (tools/soak.py --disk-faults SEED)
# ---------------------------------------------------------------------------

def run_disk_chaos(seed: int, data_dir: str) -> None:
    """One seeded disk-chaos episode over the classic storage plane:
    a random DiskFaultPlan + a mid-run WAL kill, then a cold restart
    that must be oracle-exact.  Raises on any violation; driven over
    fresh seed ranges by ``tools/soak.py --disk-faults``."""
    import random as _random

    rng = _random.Random(seed)
    spec = DiskFaultSpec(
        fsync_eio=rng.uniform(0.0, 0.4),
        enospc=rng.uniform(0.0, 0.2),
        short_write=rng.uniform(0.0, 0.2),
        limit=rng.randint(2, 8))
    plan = DiskFaultPlan(seed=seed, by_class={
        "wal": spec,
        "segment": DiskFaultSpec(fsync_eio=rng.uniform(0.0, 0.5),
                                 limit=rng.randint(1, 4)),
    })
    faults.reset_disk_fault_counters()
    sys_ = RaSystem(data_dir, wal_supervise=True)
    try:
        log = mk_log(sys_, "soak")
        append_range(log, 1, 10)
        drain(log, 10)
        faults.install_plan(plan)
        append_range(log, 11, 40)
        if rng.random() < 0.5 and sys_.wal.alive:
            sys_.wal.kill()  # crash plane: supervisor must recover it
        append_done = 40
        try:
            append_range(log, 41, 50)
            append_done = 50
        except Exception:
            # WalDown while the supervisor races us: entries 41+ were
            # never accepted into the log — the oracle ends at 40
            pass
        drain(log, append_done, timeout=20.0)
        faults.clear_plan()
        ctr = faults.disk_fault_counters()
        assert ctr["fsync_retries_after_failure"] == 0, ctr
        observed = log.last_written().index
    finally:
        faults.clear_plan()
        sys_.close()
    sys2 = RaSystem(data_dir, wal_supervise=False)
    try:
        log2 = mk_log(sys2, "soak")
        assert log2.last_index_term().index >= observed
        for i in range(1, observed + 1):
            ent = log2.fetch(i)
            assert ent is not None and ent.command.data == i, i
    finally:
        sys2.close()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_disk_chaos_pinned_seeds(tmp_path, seed):
    run_disk_chaos(seed, str(tmp_path / f"s{seed}"))


def test_batched_replication_kill9_leader_mid_batch_oracle(tmp_path):
    """ISSUE 13 acceptance: the batch-native replication path (deep
    {commands, Batch} flushes -> multi-entry AERs -> write_many group
    commits through ONE shared Wal) under an ACTIVE DiskFaultPlan
    (fsync-EIO + torn write), with the leader kill-9'd MID-BATCH.
    Contract: the survivors elect and keep committing, the killed
    member recovers over its durable state and reconverges, every
    APPLIED-NOTIFIED command survives, and no command ever applies
    twice (every member's counter == the same exactly-once total)."""
    router = LocalRouter()
    # co-hosted members over ONE system: all three feed one group-
    # commit Wal — the shared-WAL fan-in deployment the batching
    # tentpole targets
    system = RaSystem(str(tmp_path))
    node = RaNode("kb", router=router, log_factory=system.log_factory)
    sids = [ServerId(f"kb{i}", "kb") for i in (1, 2, 3)]
    notified: list = []
    nlock = threading.Lock()

    def on_notify(batch):
        with nlock:
            notified.extend(corr for corr, _r in batch)

    try:
        for sid in sids:
            node.start_server(ServerConfig(
                server_id=sid, uid=f"uid_{sid.name}",
                cluster_name="kill9batch", initial_members=tuple(sids),
                machine=SimpleMachine(lambda c, s: s + c, 0),
                election_timeout_ms=120, tick_interval_ms=50))
        ra_tpu.trigger_election(sids[0], router)
        leader = await_leader(router, sids)

        # storm faults while the batched burst is in flight
        faults.install_plan(DiskFaultPlan(seed=29, by_class={
            "wal": DiskFaultSpec(fsync_eio=0.6, short_write=0.4,
                                 limit=6)}))
        sent = 0
        for i in range(1200):
            ra_tpu.pipeline_command(leader, 1, correlation=("k", i),
                                    notify_to=on_notify, router=router,
                                    trace_ctx=False)
            sent += 1
        # kill-9 the leader mid-burst: batches are in every stage —
        # low-queue, in-flight AERs, WAL group, unsent confirms
        time.sleep(0.15)
        node.kill_server(leader.name)
        survivors = [s for s in sids if s != leader]
        new_leader = await_leader(router, survivors, timeout=15.0)
        # progress under the active plan proves the ladder holds with
        # batching on.  Probe writes carry value 0 so a timed-out
        # attempt retried after an election cannot perturb the exact
        # at-most-once accounting below even if both attempts commit.
        for _ in (1, 2):
            deadline = time.monotonic() + 30
            r = None
            while r is None and time.monotonic() < deadline:
                try:
                    r = ra_tpu.process_command(new_leader, 0,
                                               router=router,
                                               timeout=10.0)
                except TimeoutError:
                    continue
            assert r is not None
        faults.clear_plan()
        # the killed member restarts over its surviving durable state
        node.start_server(ServerConfig(
            server_id=leader, uid=f"uid_{leader.name}",
            cluster_name="kill9batch", initial_members=tuple(sids),
            machine=SimpleMachine(lambda c, s: s + c, 0),
            election_timeout_ms=120, tick_interval_ms=50))
        # settle: a final fully-acked write, then all members converge
        r = ra_tpu.process_command(new_leader, 1000, router=router,
                                   timeout=30.0)
        final = r.reply
        deadline = time.monotonic() + 20
        states = {}
        while time.monotonic() < deadline:
            states = {str(s): ra_tpu.local_query(
                s, lambda st: st, router=router).reply for s in sids}
            if len(set(states.values())) == 1 and \
                    list(states.values())[0] == final:
                break
            time.sleep(0.05)
        assert len(set(states.values())) == 1, states
        total = list(states.values())[0]
        assert total == final
        with nlock:
            acked = len(set(notified))
            dup_acks = len(notified) - acked
        # at-most-once apply with cumulative-ack batches: the burst's
        # contribution to the converged counter (value 1 per command)
        # must cover every ACKED command and never exceed what was
        # SENT — nothing acked was lost, nothing applied twice.  acked
        # may trail applied: a leader kill loses the leader-local
        # applied-notifications for entries the successor commits
        # (Raft-legal, the documented at-most-once gate), but never
        # duplicates one.
        assert dup_acks == 0, dup_acks
        burst_applied = total - 1000
        assert acked <= burst_applied <= sent, \
            (acked, burst_applied, sent)
        ctr = faults.disk_fault_counters()
        assert ctr["faults_injected"] >= 1, ctr
        assert ctr["fsync_retries_after_failure"] == 0, ctr
    finally:
        faults.clear_plan()
        node.stop()
        system.close()
