"""Machine effect vocabulary: the {append, Cmd} effect and the
completeness audit against the reference's effect() type
(/root/reference/src/ra_machine.erl:121-142).
"""

from harness import SimCluster
from ra_tpu.core.machine import Machine
from ra_tpu.core.types import (AppendEffect, CommandEvent, ElectionTimeout,
                               ReplyMode, UserCommand)


class ChainMachine(Machine):
    """Counter that, on an ('add_twice', n) command, appends a follow-up
    ('add', n) command from apply/3 — the ra_fifo-class use of the
    append effect (e.g. dead-letter / requeue follow-ups)."""

    def init(self, config):
        return 0

    def apply(self, meta, command, state):
        op = command[0]
        if op == "add":
            return state + command[1], state + command[1]
        if op == "add_twice":
            return (state + command[1], state + command[1],
                    [AppendEffect(("add", command[1]))])
        return state, state


def pump(c: SimCluster, rounds: int = 12):
    for _ in range(rounds):
        for sid in c.ids:
            while c.queues[sid]:
                c.handle(sid, c.queues[sid].popleft())


def test_append_effect_applied_on_all_members():
    c = SimCluster(3, machine_factory=ChainMachine)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)
    leader = c.ids[0]
    assert c.servers[leader].raft_state.value == "leader"
    c.handle(leader, CommandEvent(UserCommand(("add_twice", 5))))
    pump(c)
    # 5 applied twice (the original and the machine-appended follow-up),
    # replicated to every member
    for sid in c.ids:
        assert c.servers[sid].machine_state == 10, \
            (sid, c.servers[sid].machine_state)


def test_append_effect_chain_depth():
    """Chained appends: each follow-up may itself append (bounded)."""

    class Deep(Machine):
        def init(self, config):
            return []

        def apply(self, meta, command, state):
            tag, depth = command
            new_state = state + [depth]
            if depth > 0:
                return new_state, None, [AppendEffect(("c", depth - 1))]
            return new_state, None

    c = SimCluster(3, machine_factory=Deep)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)
    c.handle(c.ids[0], CommandEvent(UserCommand(("c", 3))))
    pump(c)
    for sid in c.ids:
        assert c.servers[sid].machine_state == [3, 2, 1, 0], \
            c.servers[sid].machine_state


def test_append_effect_not_executed_by_followers():
    """Only the leader originates the follow-up append — otherwise every
    member would append a duplicate (filter_follower_effects drops it,
    ra_server.erl:1817-1860)."""
    c = SimCluster(3, machine_factory=ChainMachine)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)
    c.handle(c.ids[0], CommandEvent(UserCommand(("add_twice", 7))))
    pump(c)
    leader_log = c.servers[c.ids[0]].log.last_index_term().index
    for sid in c.ids:
        assert c.servers[sid].log.last_index_term().index == leader_log
        assert c.servers[sid].machine_state == 14


def test_append_effect_with_notify_reply_mode():
    c = SimCluster(3, machine_factory=ChainMachine)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)

    class Chain2(ChainMachine):
        def apply(self, meta, command, state):
            if command[0] == "spawn_notify":
                return (state, state,
                        [AppendEffect(("add", 1),
                                      reply_mode=ReplyMode.NOTIFY,
                                      correlation="c1",
                                      notify_to="client9")])
            return super().apply(meta, command, state)

    for srv in c.servers.values():
        srv.cfg.machine.__class__ = Chain2
    c.handle(c.ids[0], CommandEvent(UserCommand(("spawn_notify", 0))))
    pump(c)
    assert any(n.to == "client9" and ("c1", 1) in tuple(n.correlations)
               for _sid, n in c.notifies), c.notifies


def test_append_effect_from_tick():
    """Appends emitted from machine callbacks OTHER than apply (tick
    here) are executed by the leader too — the conversion lives in the
    effect layer, not one apply call site."""
    from ra_tpu.core.types import TickEvent

    class Ticker(Machine):
        def init(self, config):
            return 0

        def apply(self, meta, command, state):
            return state + command[1], state + command[1]

        def tick(self, time_ms, state):
            return [AppendEffect(("add", 100))]

    c = SimCluster(3, machine_factory=Ticker)
    c.handle(c.ids[0], ElectionTimeout())
    pump(c)
    c.handle(c.ids[0], TickEvent())
    pump(c)
    for sid in c.ids:
        assert c.servers[sid].machine_state == 100, \
            (sid, c.servers[sid].machine_state)


class TimerMachine(Machine):
    """Machine that arms a timer on ('arm', ms), cancels on ('cancel',),
    and counts delivered timeouts — the {timer, Name, T} contract
    (ra_machine.erl:135, executed ra_server_proc.erl:1549-1550 with the
    expiry appended as a '{timeout, Name}' command, :556-560)."""

    def init(self, config):
        return {"timeouts": 0}

    def apply(self, meta, command, state):
        from ra_tpu.core.types import TimerEffect
        op = command[0]
        if op == "arm":
            return state, "armed", [TimerEffect("t1", command[1])]
        if op == "cancel":
            return state, "cancelled", [TimerEffect("t1", None)]
        if op == "timeout":
            new = {"timeouts": state["timeouts"] + 1}
            return new, new
        return state, state


def _timer_cluster(router):
    import ra_tpu
    from ra_tpu.core.types import ServerId
    from nemesis import await_leader
    sids = [ServerId(f"tm{i}", f"n{i}") for i in (1, 2, 3)]
    ra_tpu.start_cluster("timers", TimerMachine, sids, router=router)
    return sids, await_leader(router, sids)


def test_machine_timer_fires_and_replicates():
    import time

    import ra_tpu
    from ra_tpu.node import LocalRouter, RaNode

    router = LocalRouter()
    nodes = [RaNode(f"n{i}", router=router) for i in (1, 2, 3)]
    try:
        sids, leader = _timer_cluster(router)
        ra_tpu.process_command(leader, ("arm", 50), router=router)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            got = ra_tpu.local_query(
                leader, lambda s: s["timeouts"], router=router)
            if got.reply >= 1:
                break
            time.sleep(0.02)
        assert got.reply == 1, got
        # the timeout command went through consensus: every member's
        # machine saw it
        for sid in sids:
            r = ra_tpu.local_query(sid, lambda s: s["timeouts"],
                                   router=router)
            assert r.reply == 1, (sid, r)
    finally:
        for n in nodes:
            n.stop()


def test_machine_timer_cancel_suppresses_fire():
    import time

    import ra_tpu
    from ra_tpu.node import LocalRouter, RaNode

    router = LocalRouter()
    nodes = [RaNode(f"n{i}", router=router) for i in (1, 2, 3)]
    try:
        sids, leader = _timer_cluster(router)
        ra_tpu.process_command(leader, ("arm", 300), router=router)
        ra_tpu.process_command(leader, ("cancel",), router=router)
        time.sleep(0.7)
        got = ra_tpu.local_query(leader, lambda s: s["timeouts"],
                                 router=router)
        assert got.reply == 0, got
    finally:
        for n in nodes:
            n.stop()


def test_effect_vocabulary_parity():
    """Every effect in ra_machine.erl:121-142 has a counterpart class
    (the completeness audit VERDICT r03 item 4 asks for)."""
    import ra_tpu.core.types as T
    vocabulary = {
        "send_msg": "SendMsg",               # :121-125
        "mod_call": "ModCall",               # :126
        "append": "AppendEffect",            # :128-130
        "monitor": "Monitor",                # :131-132 (process|node)
        "demonitor": "Demonitor",            # :133-134
        "timer": "TimerEffect",              # :135
        "log": "LogReadEffect",              # :136-137
        "release_cursor": "ReleaseCursor",   # :138-139
        "checkpoint": "Checkpoint",          # :140
        "aux": "AuxEffect",                  # :141
        "garbage_collection": "GarbageCollection",  # :142
    }
    for ref_name, cls_name in vocabulary.items():
        assert hasattr(T, cls_name), (ref_name, cls_name)
    # monitor/demonitor must support both process and node targets
    import inspect
    assert "component" in inspect.signature(T.Monitor).parameters or \
        hasattr(T.Monitor, "component")


def test_log_read_effect_reads_back_committed_entries():
    """The {log, Indexes, Fun} effect (ra_machine.erl:136-137,
    ra_machine_int_SUITE log_effect): the shell reads the requested
    committed entries back from the log and hands them to the fun."""
    import time as _t

    import ra_tpu
    from ra_tpu.core.types import LogReadEffect, ServerId
    from ra_tpu.node import LocalRouter, RaNode
    from nemesis import await_leader

    got: list = []

    class Reader(Machine):
        def init(self, config):
            return 0

        def apply(self, meta, command, state):
            if isinstance(command, tuple) and command[0] == "readback":
                # {local, Node}: execute on exactly one member
                # (the bare form runs on EVERY member, reference parity)
                return state, "ok", [LogReadEffect(command[1], got.extend,
                                                   local=command[2])]
            return state + command, state + command

    router = LocalRouter()
    nodes = [RaNode(f"lr{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"lrm{i}", f"lr{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("logread", Reader, sids, router=router)
        leader = await_leader(router, sids)
        for v in (7, 8, 9):
            ra_tpu.process_command(leader, v, router=router)
        # read a range wide enough to cover noops from any extra
        # elections; assert on the user entries, in log order
        ra_tpu.process_command(
            leader, ("readback", tuple(range(1, 9)), leader.node),
            router=router)
        deadline = _t.monotonic() + 10
        while _t.monotonic() < deadline and len(got) < 4:
            _t.sleep(0.05)
        vals = [(e.index, e.command.data) for e in got
                if getattr(e.command, "data", None) in (7, 8, 9)]
        assert [v for _i, v in vals] == [7, 8, 9], got
        assert [i for i, _v in vals] == sorted(i for i, _v in vals)
    finally:
        for n in nodes:
            n.stop()


def test_deleted_cluster_emits_eol_to_attached_pids():
    """deleted_cluster_emits_eol_effect (ra_machine_int_SUITE): on
    '$ra_cluster' delete the machine's state_enter('eol') effects run,
    telling every attached process the queue is gone
    (ra_fifo.erl:381)."""
    import time as _t

    import ra_tpu
    from ra_tpu.core.types import ServerId
    from ra_tpu.models import FifoClient, FifoMachine
    from ra_tpu.node import LocalRouter, RaNode
    from nemesis import await_leader

    router = LocalRouter()
    nodes = [RaNode(f"el{i}", router=router) for i in (1, 2, 3)]
    sids = [ServerId(f"elm{i}", f"el{i}") for i in (1, 2, 3)]
    try:
        ra_tpu.start_cluster("eolq", FifoMachine, sids, router=router)
        leader = await_leader(router, sids)
        cli = FifoClient(sids, router=router, tag="eol-consumer")
        con = cli.mailbox
        cli.checkout(credit=2)
        cli.enqueue_sync("m1")
        r = ra_tpu.delete_cluster(leader, router=router)
        assert r.reply == "ok"
        deadline = _t.monotonic() + 10
        eol = None
        while _t.monotonic() < deadline and eol is None:
            for msg in con.drain():
                if isinstance(msg, tuple) and msg[0] == "eol":
                    eol = msg
            _t.sleep(0.05)
        assert eol is not None, "consumer never saw the eol signal"
    finally:
        for n in nodes:
            n.stop()
