"""Scripted fault scheduler for partition/nemesis tests.

The reference drives its partition suite with ``test/nemesis.erl``: a
small interpreter over fault scripts — ``{part, Nodes, Time}`` blocks
traffic between a chosen split for a while, ``heal`` removes all blocks,
``{app_restart, Servers}`` stops and restarts ra servers mid-workload,
``{wait, Time}`` paces the schedule (nemesis.erl:29-35,100-126).  The
transport hook there is the inet_tcp_proxy dist carrier; here it is
LocalRouter.block/heal, which the node runtime consults on every send —
the same "links silently drop" failure model.

The storage plane composes with the wire: ``disk_faults`` installs a
seeded :class:`ra_tpu.log.faults.DiskFaultPlan` (the node-wide storage
I/O shim consults it), ``disk_heal`` clears it, and ``wal_kill``
crashes a named system's WAL batch thread mid-schedule — together with
partitions this is the combined transport+disk+crash chaos the
linearizability suite drives.
"""
from __future__ import annotations

import random
import time
from typing import Iterable, Optional

from ra_tpu.core.types import ServerId
from ra_tpu.log import faults
from ra_tpu.node import LocalRouter, RaNode


class Nemesis:
    """Interprets fault schedules against a router + set of RaNodes
    (plus, optionally, their RaSystems for storage-plane ops)."""

    def __init__(self, router: LocalRouter, nodes: Iterable[RaNode],
                 seed: int = 0, systems: Optional[dict] = None) -> None:
        self.router = router
        self.nodes = {n.name: n for n in nodes}
        #: node name -> RaSystem (for wal_kill); optional
        self.systems = dict(systems or {})
        self.rng = random.Random(seed)
        self.history: list = []

    # -- schedule interpreter ----------------------------------------------

    def run(self, schedule: Iterable[tuple]) -> None:
        from ra_tpu.blackbox import record
        for step in schedule:
            self.history.append(step)
            op, args = step[0], step[1:]
            # the chaos schedule narrates itself into the flight
            # recorder: a post-mortem bundle shows WHICH nemesis op
            # preceded the death, not just that one did
            record("nemesis.op", op=op,
                   args=repr(args)[:120] if args else "")
            getattr(self, f"_op_{op}")(*args)

    def _op_wait(self, seconds: float) -> None:
        time.sleep(seconds)

    def _op_heal(self) -> None:
        self.router.heal()

    def _op_part(self, split: tuple, seconds: float) -> None:
        """Block every link crossing the (group_a, group_b) split for
        ``seconds``, then unblock exactly those links ({part, Nodes,
        Time}) — blocks installed outside this op are left alone so
        partitions compose."""
        group_a, group_b = split
        pairs = [(a, b) for a in group_a for b in group_b]
        for a, b in pairs:
            self.router.block(a, b)
        time.sleep(seconds)
        for a, b in pairs:
            self.router.blocked.discard((a, b))
            self.router.blocked.discard((b, a))

    def _op_part_random(self, seconds: float) -> None:
        """Random minority/majority split (the reference nemesis picks
        random node subsets)."""
        names = list(self.nodes)
        self.rng.shuffle(names)
        cut = self.rng.randint(1, (len(names) - 1) // 2)
        self._op_part((names[:cut], names[cut:]), seconds)

    def _op_part_leader(self, leader_node: str, seconds: float) -> None:
        """Partition the given node into a minority island."""
        others = [n for n in self.nodes if n != leader_node]
        self._op_part(([leader_node], others), seconds)

    def _op_app_restart(self, servers: Iterable[ServerId]) -> None:
        """Stop and restart ra servers in place ({app_restart, Servers})."""
        for sid in servers:
            node = self.nodes.get(sid.node)
            if node is not None and sid.name in node.shells:
                node.restart_server(sid.name)

    def _op_kill(self, servers: Iterable[ServerId]) -> None:
        for sid in servers:
            node = self.nodes.get(sid.node)
            if node is not None and sid.name in node.shells:
                node.kill_server(sid.name)

    # -- storage plane ------------------------------------------------------

    def _op_disk_faults(self, plan: faults.DiskFaultPlan) -> None:
        """Install a seeded DiskFaultPlan on the node-wide storage I/O
        shim (every co-hosted system shares it — the same blast radius
        a sick disk has)."""
        faults.install_plan(plan)

    def _op_disk_heal(self) -> None:
        faults.clear_plan()

    def _op_wal_kill(self, node_name: str) -> None:
        """Crash the named system's WAL batch thread (the supervisor is
        expected to bring it back; servers park in await_condition
        meanwhile)."""
        system = self.systems.get(node_name)
        if system is not None and system.wal.alive:
            system.wal.kill()

    # -- placement plane (ISSUE 17) -----------------------------------------

    def _op_engine_kill(self, host) -> None:
        """Kill-9 a whole lane-engine host (ra_tpu.placement.host
        .LaneEngineHost): WAL shards die abruptly, unfsynced tail
        lost, no shutdown ceremony.  The heal is placement_failover —
        the classic control plane re-homes the lane space, the host
        itself never comes back."""
        host.kill9()

    def _op_placement_failover(self, supervisor, victim: str,
                               survivor: str, trace_ctx=None) -> None:
        """Heal for engine_kill: drive the supervisor's committed
        re-placement of ``victim``'s lane ranges onto ``survivor``
        (generation-gated table commands; the supervisor's on_migrate
        hook performs the adoption + session re-homing)."""
        supervisor.failover(victim, survivor, trace_ctx=trace_ctx)


def current_leader(router: LocalRouter,
                   sids: Iterable[ServerId]) -> Optional[ServerId]:
    for sid in sids:
        node = router.nodes.get(sid.node)
        shell = node.shells.get(sid.name) if node else None
        if shell and shell.server.raft_state.value == "leader":
            return sid
    return None


def await_leader(router: LocalRouter, sids: list,
                 timeout: float = 10.0) -> ServerId:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = current_leader(router, sids)
        if got is not None:
            return got
        time.sleep(0.01)
    raise TimeoutError("no leader elected")
