"""Seeded chaos schedule for the lockstep lane engine — the device-path
counterpart of the core interleaving fuzzers: random member failures
(quorum preserved), recoveries through the snapshot-install contract,
elections for dead-leader lanes, and continuous traffic, with
per-step invariants and a final all-replica convergence check against
the RegisterMachine host oracle.
"""
import random

import jax.numpy as jnp
import numpy as np
import pytest

from ra_tpu.engine import LockstepEngine
from ra_tpu.models import RegisterMachine

from test_engine_elections_adversarial import drain_committed
from test_register_machine import host_fold

N, P, K = 4, 5, 4


def run_chaos(seed, rounds=30, make_engine=None):
    """Drive the chaos schedule; returns (engine, host_fold oracle).
    ``make_engine`` swaps in a different engine construction (e.g. the
    durable open_engine) — the schedule, invariants, and final
    convergence check are identical for both paths."""
    rng = random.Random(seed)
    if make_engine is not None:
        eng = make_engine()
    else:
        eng = LockstepEngine(RegisterMachine(n_slots=8), N, P,
                             ring_capacity=256, max_step_cmds=K,
                             write_delay=1, donate=False)
    committed_cmds: list = []       # acked = fully committed batches
    down: dict = {lane: set() for lane in range(N)}
    prev_total = 0

    def drain_all():
        drain_committed(eng, limit=40)

    for _round in range(rounds):
        roll = rng.random()
        if roll < 0.5:
            # traffic: identical commands across lanes (oracle stays 1-D)
            cmds = [(1, rng.randrange(0, 8), rng.randrange(1, 100), 0)
                    for _ in range(K)]
            pay = np.zeros((N, K, 4), np.int32)
            for k, cmd in enumerate(cmds):
                pay[:, k] = cmd
            eng.step(np.full((N,), K, np.int32), jnp.asarray(pay))
            drain_all()
            committed_cmds.extend(cmds)
        elif roll < 0.7:
            # fail a random member on every lane, quorum preserved
            leads = np.asarray(eng.state.leader_slot)
            for lane in range(N):
                if len(down[lane]) >= (P - 1) // 2:
                    continue
                choices = [s for s in range(P) if s not in down[lane]]
                victim = rng.choice(choices)
                eng.fail_member(lane, victim)
                down[lane].add(victim)
                if victim == int(leads[lane]):
                    eng.trigger_election([lane])
        elif roll < 0.9:
            # recover one dead member per lane (leader-guard respected)
            leads = np.asarray(eng.state.leader_slot)
            for lane in range(N):
                if down[lane]:
                    slot = rng.choice(sorted(down[lane]))
                    if slot != int(leads[lane]):
                        eng.recover_member(lane, slot)
                        down[lane].discard(slot)
        else:
            eng.trigger_election(list(range(N)))  # gratuitous transfer
        total = eng.committed_total()
        assert total >= prev_total, "committed total regressed"
        prev_total = total
        st = eng.state
        lane = np.arange(N)
        leads = np.asarray(st.leader_slot)
        com = np.asarray(st.commit)[lane, leads]
        tail = np.asarray(st.last_index)[lane, leads]
        assert (com <= tail).all(), "commit beyond leader log"

    # heal everything and converge
    leads = np.asarray(eng.state.leader_slot)
    for lane in range(N):
        for slot in sorted(down[lane]):
            if slot != int(leads[lane]):
                eng.recover_member(lane, slot)
                down[lane].discard(slot)
    stalled = [lane for lane in range(N) if down[lane]]
    if stalled:
        eng.trigger_election(stalled)
        leads = np.asarray(eng.state.leader_slot)
        for lane in stalled:
            for slot in sorted(down[lane]):
                if slot != int(leads[lane]):
                    eng.recover_member(lane, slot)
                    down[lane].discard(slot)
    assert not any(down.values()), down
    drain_all()
    want = host_fold(committed_cmds)
    mac = np.asarray(eng.state.mac)
    for lane in range(N):
        for member in range(P):
            assert mac[lane, member].tolist() == want, \
                (lane, member, mac[lane, member].tolist(), want)
    return eng, want


@pytest.mark.parametrize("seed", [1, 9])
def test_engine_chaos_schedule(seed):
    run_chaos(seed)


def test_engine_chaos_durable_mode(tmp_path):
    """The SAME chaos schedule (invariants included) over the DURABLE
    engine: every commit is WAL-confirm-gated while members fail,
    recover, and elections churn — then a checkpoint + reopen must
    recover the converged state."""
    from ra_tpu.engine import open_engine

    def make():
        return open_engine(RegisterMachine(n_slots=8), str(tmp_path),
                           N, P, sync_mode=0, ring_capacity=256,
                           max_step_cmds=K)

    eng, want = run_chaos(3, rounds=14, make_engine=make)
    eng.checkpoint()
    totals = eng.committed_per_lane().copy()
    eng.close()
    eng2 = open_engine(RegisterMachine(n_slots=8), str(tmp_path), N, P,
                       sync_mode=0, ring_capacity=256, max_step_cmds=K)
    mac2 = np.asarray(eng2.state.mac)
    leads2 = np.asarray(eng2.state.leader_slot)
    for lane in range(N):
        assert mac2[lane, leads2[lane]].tolist() == want, lane
    assert (eng2.committed_per_lane() >= totals).all()
    eng2.close()
