"""Phase attribution + SLO engine + closed-loop autotuner (ISSUE 9).

The acceptance pins: on a dispatch-bound workload the controller
raises ``superstep_k`` and CONVERGES within a bounded number of
windows; on an fsync-bound one it backs off the WAL batch interval and
then K instead; decisions freeze under an active DiskFaultPlan (and a
transport FaultPlan, and a fresh incident); every decision is a
registered flight-recorder event; phase attribution and SLO verdicts
reach the Prometheus exposition; and the whole plane's interleaved A/B
overhead on the bench dispatch path stays under 3%.

The closed-loop tests drive a SYNTHETIC workload: an Observatory whose
engine source is a controllable dict in the exact layout the real
engine source emits (same flat ring keys), with a plant model mapping
knob values to the next window's latencies — deterministic, seedless,
and it exercises the controller's real input path (ring -> flat keys
-> window_rates -> verdicts), not a mock of it.
"""
import gc
import time

import numpy as np
import pytest

from ra_tpu.autotune import AutoTuner, TUNABLE_KNOBS
from ra_tpu.blackbox import EVENT_REGISTRY, RECORDER
from ra_tpu.metrics import FIELD_REGISTRY, PHASE_FIELDS
from ra_tpu.slo import Objective, SloEngine, default_objectives
from ra_tpu.telemetry import Observatory, PhaseStats, parse_prometheus


# ---------------------------------------------------------------------------
# PhaseStats: the attribution substrate
# ---------------------------------------------------------------------------

def test_phase_fields_registered():
    assert FIELD_REGISTRY["phase"] is PHASE_FIELDS


def test_phase_stats_accumulates_and_buckets():
    ph = PhaseStats(reservoir=8)
    for ms in (0.5, 1.5, 3.0, 100.0):
        ph.note("fsync_wait", ms / 1000.0)
    ov = ph.overview()
    f = ov["fsync_wait"]
    assert f["count"] == 4
    assert f["total_ms"] == pytest.approx(105.0, rel=1e-3)
    assert f["p50_ms"] > 0 and f["max_ms"] == pytest.approx(100.0, rel=1e-3)
    # log2-ms buckets: 0.5ms -> b0, 1.5 -> b1, 3 -> b2, 100 -> b7
    assert f["hist"][0] == 1 and f["hist"][1] == 1
    assert f["hist"][2] == 1 and f["hist"][7] == 1
    # unknown phases are counted, never silently eaten
    ph.note("zz_bogus", 0.001)
    assert ph.overview()["dropped"] == 1
    # untouched phases report the -1 "never measured" sentinel
    assert ov["queue_wait"]["p50_ms"] == -1.0


# ---------------------------------------------------------------------------
# ring edge cases the SLO engine depends on (satellite)
# ---------------------------------------------------------------------------

def test_percentile_over_empty_and_missing_keys():
    obs = Observatory()
    assert obs.percentile("anything", 0.5) is None   # empty ring
    obs.add_source("s", lambda: {"x": 1})
    obs.snapshot()
    assert obs.percentile("s_x", 0.5) == 1.0
    assert obs.percentile("s_missing", 0.99) is None  # key never seen
    assert obs.window_rates() == {}                   # single entry


def test_window_rates_n_window_span():
    vals = iter(range(0, 500, 10))
    obs = Observatory()
    obs.add_source("s", lambda: {"ctr_count": next(vals)})
    t0 = time.time()
    for _ in range(5):
        obs.snapshot()
    # span=4 rates ring[-5] -> ring[-1]: delta 40 over the elapsed dt
    r = obs.window_rates(span=4)
    (ta, a), (tb, b) = obs.ring()[-5], obs.ring()[-1]
    assert r["s_ctr_count"] == pytest.approx(
        (b["s_ctr_count"] - a["s_ctr_count"]) / max(tb - ta, 1e-9),
        rel=1e-3)
    # an `end` in the past rates an interior pair
    r_mid = obs.window_rates(span=1, end=2)
    assert r_mid["s_ctr_count"] > 0
    # out-of-range spans yield {} rather than indexing garbage
    assert obs.window_rates(span=10) == {}
    del t0


def test_window_rates_keeps_depth_gauge_negative_drift():
    """dispatches_in_flight is a DEPTH gauge, not a counter: its
    negative drift (the pipeline draining) must stay visible — a
    substring monotone hint ('dispatches') must not swallow it."""
    depth = iter([4.0, 1.0])
    disp = iter([100.0, 50.0])  # the true counter resets -> omitted
    obs = Observatory()
    obs.add_source("engine", lambda: {"pipeline": {
        "dispatches_in_flight": next(depth),
        "dispatches": next(disp)}})
    obs.snapshot()
    obs.snapshot()
    rates = obs.window_rates()
    assert rates["engine_pipeline_dispatches_in_flight"] < 0
    assert "engine_pipeline_dispatches" not in rates


def test_window_rates_omits_counter_reset():
    """An engine restart zeroes monotone counters mid-ring: the rate
    must be OMITTED, never negative — a burn-rate evaluator fed a huge
    negative 'rate' across the restart window would mis-verdict."""
    seq = iter([1000.0, 2000.0, 5.0])  # restart before the 3rd snap
    gauge = iter([10.0, 4.0, 2.0])     # gauges may drift down freely
    obs = Observatory()
    obs.add_source("s", lambda: {"committed_total": next(seq),
                                 "lag_depth": next(gauge)})
    obs.snapshot()
    obs.snapshot()
    assert obs.window_rates()["s_committed_total"] > 0
    obs.snapshot()  # 2000 -> 5: backwards-moving monotone counter
    rates = obs.window_rates()
    assert "s_committed_total" not in rates
    assert rates["s_lag_depth"] < 0  # gauge drift still reported


# ---------------------------------------------------------------------------
# SLO engine: per-window verdicts + burn rates
# ---------------------------------------------------------------------------

def mk_obs(state):
    """An Observatory whose engine source mirrors the real layout —
    same flat ring keys the production SloEngine objectives read."""
    obs = Observatory(ring_capacity=64)

    def engine_src():
        return {
            "phases": {
                "device_dispatch": {
                    "total_ms": state["disp_total"]},
                "fsync_wait": {"total_ms": state["fsync_total"]},
                "commit_e2e": {"total_ms": state["e2e_total"],
                               "p99_ms": state["commit_p99"]},
            },
            "wal": {"shards": [{"fsync_p99_ms": state["fsync_p99"]}]},
            "telemetry": {"ts": time.time(),
                          "committed_total": state["committed"]},
            # a plant-controlled throughput GAUGE (value-kind floor
            # objectives): deterministic under scheduler jitter, unlike
            # differentiating committed_total against wall time
            "gauge_cmds_per_s": state["gauge_rate"],
        }

    obs.add_source("engine", engine_src)
    return obs


def base_state():
    return {"disp_total": 0.0, "fsync_total": 0.0, "e2e_total": 0.0,
            "commit_p99": 5.0, "fsync_p99": 5.0, "committed": 0.0,
            "gauge_rate": -1.0}


def test_slo_verdicts_ok_breach_alert_no_data():
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=100.0),
                    fast_windows=2, slow_windows=4,
                    burn_fast=0.5, burn_slow=0.5)
    v = slo.evaluate()
    assert v["objectives"]["commit_p99_ms"]["verdict"] == "no_data"
    for _ in range(3):
        state["committed"] += 1000.0
        time.sleep(0.002)
        obs.snapshot()
    v = slo.evaluate()["objectives"]
    assert v["commit_p99_ms"]["verdict"] == "ok"
    assert v["cmds_per_s"]["verdict"] == "ok"
    assert v["cmds_per_s"]["value"] > 100.0
    # sustained breach: fast then slow windows burn -> breach -> alert
    state["commit_p99"] = 90.0
    seen = []
    for _ in range(4):
        state["committed"] += 1000.0
        time.sleep(0.002)
        obs.snapshot()
        seen.append(slo.evaluate()["objectives"]["commit_p99_ms"])
    assert seen[0]["verdict"] in ("breach", "alert")
    assert seen[-1]["verdict"] == "alert"
    assert seen[-1]["burn_fast"] == 1.0
    assert not seen[-1]["ok"]
    # the verdicts ride the snapshot + exposition via the slo source
    snap = obs.snapshot()
    assert snap["slo"]["objectives"]["commit_p99_ms"]["ok"] is False
    text = obs.prometheus(snap)
    parsed = parse_prometheus(text)
    assert parsed[("ra_tpu_slo_objectives_commit_p99_ms_ok", "")] == 0.0


def test_slo_wildcard_aggregates_shards_and_skips_sentinels():
    shards = [{"fsync_p99_ms": -1.0}, {"fsync_p99_ms": 70.0}]
    obs = Observatory()
    obs.add_source("engine", lambda: {"wal": {"shards": shards}})
    slo = SloEngine(
        obs, (Objective("fsync_p99_ms",
                        "engine_wal_shards_*_fsync_p99_ms", "<=", 50.0),),
        fast_windows=1, slow_windows=2, burn_fast=0.5)
    obs.snapshot()
    v = slo.evaluate()["objectives"]["fsync_p99_ms"]
    # max over shards, -1 "never synced" sentinel excluded (with a
    # 1-window fast AND slow burn both saturate -> alert immediately)
    assert v["value"] == 70.0 and v["verdict"] in ("breach", "alert")
    assert not v["ok"]
    shards[1]["fsync_p99_ms"] = -1.0
    obs.snapshot()
    assert slo.evaluate()["objectives"]["fsync_p99_ms"]["verdict"] \
        == "no_data"


def test_slo_duplicate_objective_names_rejected():
    obs = Observatory()
    objs = (Objective("a", "x", "<=", 1.0), Objective("a", "y", ">=", 1.0))
    with pytest.raises(ValueError):
        SloEngine(obs, objs)


# ---------------------------------------------------------------------------
# the closed loop (acceptance demo, synthetic plants)
# ---------------------------------------------------------------------------

def mk_tuner(slo, obs, **kw):
    kw.setdefault("freeze_guard", lambda: None)  # plants, not chaos
    kw.setdefault("incident_freeze_s", 0.0)  # other tests dump bundles
    kw.setdefault("cooldown_windows", 0)
    kw.setdefault("breach_windows", 2)
    return AutoTuner(slo, obs, **kw)


def drive(obs, tuner, state, plant, windows):
    """Run the loop: plant(knobs) -> next window's metrics -> snapshot
    -> tick.  Returns the decisions made."""
    decisions = []
    for _ in range(windows):
        plant(tuner.knobs, state)
        time.sleep(0.002)
        obs.snapshot()
        d = tuner.tick()
        if d is not None:
            decisions.append(d)
    return decisions


def dispatch_bound_plant(knobs, state):
    """Fixed per-dispatch overhead amortized by K: commit p99 and the
    dispatch phase's budget share fall as superstep_k rises."""
    k = knobs["superstep_k"]
    state["disp_total"] += 100.0 / k
    state["fsync_total"] += 4.0
    state["e2e_total"] += 110.0 / k
    state["commit_p99"] = 100.0 / k + 5.0
    state["committed"] += 10000.0


def test_closed_loop_raises_superstep_k_when_dispatch_bound():
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = mk_tuner(slo, obs, knobs={"superstep_k": 1})
    base_events = len(RECORDER.events("tune"))
    decisions = drive(obs, tuner, state, dispatch_bound_plant,
                      windows=16)
    # k=1: p99 105 -> 2: 55 -> 4: 30 -> 8: 17.5 (under the 25ms SLO):
    # three doublings, all attributed to the dispatch phase, then quiet
    assert [d["knob"] for d in decisions] == ["superstep_k"] * 3
    assert [d["new"] for d in decisions] == [2, 4, 8]
    assert all(d["phase"] == "device_dispatch" for d in decisions)
    assert all(d["objective"] == "commit_p99_ms" for d in decisions)
    assert tuner.knobs["superstep_k"] == 8
    assert tuner.decisions.maxlen == 256  # bounded, like every record
    # CONVERGED: green windows keep the knobs still
    more = drive(obs, tuner, state, dispatch_bound_plant, windows=6)
    assert more == []
    # every decision is a registered flight-recorder event
    evs = RECORDER.events("tune")[base_events:]
    decided = [e for e in evs if e[1] == "tune.decision"]
    assert len(decided) == 3
    assert all(e[1] in EVENT_REGISTRY for e in evs)
    assert RECORDER.counters["unregistered_events"] == 0
    # and the snapshot carries the controller state for ra_top
    snap = obs.snapshot()
    assert snap["autotune"]["knobs"]["superstep_k"] == 8
    assert snap["autotune"]["last_decision"]["new"] == 8


def fsync_bound_plant(knobs, state):
    """A slow disk: the fsync phase owns the budget, and the group
    -commit wait plus the per-dispatch burst (K) both add to the
    syscall tail."""
    k = knobs["superstep_k"]
    interval = knobs["wal_max_batch_interval_ms"]
    state["fsync_total"] += 100.0
    state["disp_total"] += 5.0
    state["e2e_total"] += 120.0
    state["fsync_p99"] = 30.0 + 2.0 * interval + 4.0 * k
    # commit p99 tracks the fsync tail (the path is fsync-gated):
    # both objectives go green together once the disk is relieved
    state["commit_p99"] = state["fsync_p99"] / 2.0
    state["committed"] += 1000.0


def test_closed_loop_backs_off_interval_then_k_when_fsync_bound():
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = mk_tuner(slo, obs,
                     knobs={"superstep_k": 8,
                            "wal_max_batch_interval_ms": 2.0})
    decisions = drive(obs, tuner, state, fsync_bound_plant, windows=16)
    # fsync p99: iv=2,k=8 -> 66; back off iv 2->1 (64), 1->0 (62),
    # THEN halve K 8->4 (46 < 50: green) — never raise K into a slow
    # disk
    assert [(d["knob"], d["new"]) for d in decisions] == [
        ("wal_max_batch_interval_ms", 1.0),
        ("wal_max_batch_interval_ms", 0.0),
        ("superstep_k", 4)]
    assert all(d["objective"] == "fsync_p99_ms" for d in decisions)
    assert all(d["phase"] == "fsync_wait" for d in decisions)
    # converged
    assert drive(obs, tuner, state, fsync_bound_plant, windows=6) == []


def throughput_bound_plant(knobs, state):
    """Latency green, throughput below the floor until fusion/batching
    deepen: the achieved rate scales with k * cmds."""
    k = knobs["superstep_k"]
    c = knobs["cmds_per_step"]
    state["disp_total"] += 10.0
    state["commit_p99"] = 5.0
    state["gauge_rate"] = 100.0 * k * c
    state["e2e_total"] += 10.0


def test_closed_loop_deepens_batching_on_throughput_floor():
    state = base_state()
    obs = mk_obs(state)
    # floor requires k*c >= 512 * 100: k caps at 4 -> cmds must double
    slo = SloEngine(
        obs,
        (Objective("commit_p99_ms", "engine_phases_commit_e2e_p99_ms",
                   "<=", 25.0),
         Objective("cmds_per_s", "engine_gauge_cmds_per_s",
                   ">=", 25_000.0)),
        fast_windows=3, slow_windows=6, burn_fast=0.5, burn_slow=0.25)
    tuner = mk_tuner(slo, obs, bounds={"superstep_k": (1, 4)},
                     knobs={"superstep_k": 1, "cmds_per_step": 32})
    decisions = drive(obs, tuner, state, throughput_bound_plant,
                      windows=20)
    knobs = [(d["knob"], d["new"]) for d in decisions]
    # fusion deepens to its bound first, then the per-lane batch grows
    # (4 * 64 * 100 = 25.6k >= the floor: converged)
    assert knobs == [("superstep_k", 2), ("superstep_k", 4),
                     ("cmds_per_step", 64)]
    assert all(d["objective"] == "cmds_per_s" for d in decisions)
    assert drive(obs, tuner, state, throughput_bound_plant,
                 windows=6) == []


def mk_mesh_obs(state):
    """An Observatory whose engine source mirrors a SHARDED-MESH
    durable engine: mesh_shape stamped in the pipeline overview and
    PER-DEVICE WAL shards (8, one per lane-axis device) feeding the
    wildcard fsync objective — the layout the multichip sweep's tuner
    reads (ISSUE 11)."""
    obs = Observatory(ring_capacity=64)

    def engine_src():
        fp = state["fsync_p99"]
        return {
            "pipeline": {"mesh_shape": "1x8"},
            "phases": {
                "device_dispatch": {"total_ms": state["disp_total"]},
                "fsync_wait": {"total_ms": state["fsync_total"]},
                "commit_e2e": {"total_ms": state["e2e_total"],
                               "p99_ms": state["commit_p99"]},
            },
            # 8 per-device shards; the objective's max-aggregation
            # must read the laggiest device's fsync tail
            "wal": {"shards": [
                {"shard": i, "lanes": [i * 8, (i + 1) * 8],
                 "fsync_p99_ms": fp if fp < 0 else fp + 0.01 * i}
                for i in range(8)]},
            "telemetry": {"ts": time.time(),
                          "committed_total": state["committed"]},
            "gauge_cmds_per_s": state["gauge_rate"],
        }

    obs.add_source("engine", engine_src)
    return obs


def mesh_plant(knobs, state):
    """Synthetic sharded-mesh plant: dispatch-bound while the fixed
    per-dispatch cost dominates (fusion amortizes it across the mesh);
    once the per-device WAL shards saturate (``regime`` flips), the
    fsync tail grows with the group wait AND the per-dispatch burst K
    — fusing deeper into the saturated shards makes it worse."""
    k = knobs["superstep_k"]
    interval = knobs["wal_max_batch_interval_ms"]
    if state["regime"] == "dispatch":
        state["disp_total"] += 100.0 / k
        state["fsync_total"] += 4.0
        state["e2e_total"] += 110.0 / k
        state["commit_p99"] = 100.0 / k + 5.0
        state["fsync_p99"] = 5.0
    else:
        state["fsync_total"] += 100.0
        state["disp_total"] += 5.0
        state["e2e_total"] += 120.0
        state["fsync_p99"] = 30.0 + 2.0 * interval + 4.0 * k
        state["commit_p99"] = state["fsync_p99"] / 2.0
    state["committed"] += 10000.0


def test_closed_loop_converges_on_mesh_plant():
    """ISSUE 11 acceptance: pointing the PR 8 controller at a mesh
    plant is the cheapest frontier search we own — on the
    dispatch-bound mesh K walks up (1->2->4->8, attributed to
    device_dispatch) and converges; when the per-device WAL shards
    go fsync-bound it backs the group wait off 2->1->0 and then
    halves K, never fusing deeper into saturated shards."""
    state = {**base_state(), "regime": "dispatch"}
    obs = mk_mesh_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = mk_tuner(slo, obs,
                     knobs={"superstep_k": 1,
                            "wal_max_batch_interval_ms": 2.0})
    up = drive(obs, tuner, state, mesh_plant, windows=16)
    assert [(d["knob"], d["new"]) for d in up] == [
        ("superstep_k", 2), ("superstep_k", 4), ("superstep_k", 8)]
    assert all(d["phase"] == "device_dispatch" for d in up)
    # converged on the dispatch-bound mesh: green windows stay quiet
    assert drive(obs, tuner, state, mesh_plant, windows=4) == []
    # the per-device shards saturate: fsync owns the budget
    state["regime"] = "fsync"
    down = drive(obs, tuner, state, mesh_plant, windows=18)
    assert [(d["knob"], d["new"]) for d in down] == [
        ("wal_max_batch_interval_ms", 1.0),
        ("wal_max_batch_interval_ms", 0.0),
        ("superstep_k", 4)]
    assert all(d["objective"] == "fsync_p99_ms" for d in down)
    assert all(d["phase"] == "fsync_wait" for d in down)
    assert drive(obs, tuner, state, mesh_plant, windows=6) == []
    # the chosen knobs ride the snapshot the multichip tail stamps
    snap = obs.snapshot()
    assert snap["autotune"]["knobs"]["superstep_k"] == 4
    assert snap["engine"]["pipeline"]["mesh_shape"] == "1x8"


def test_hysteresis_one_noisy_window_never_turns_a_knob():
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=2, slow_windows=4, burn_fast=0.5)
    tuner = mk_tuner(slo, obs, breach_windows=2,
                     knobs={"superstep_k": 1})

    def noisy_plant(knobs, st):
        dispatch_bound_plant(knobs, st)
        # alternate: one breaching window, then a green one
        st["commit_p99"] = 90.0 if st["committed"] % 20000 else 5.0

    decisions = drive(obs, tuner, state, noisy_plant, windows=10)
    assert decisions == []


def test_cooldown_spaces_decisions():
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = mk_tuner(slo, obs, cooldown_windows=3,
                     knobs={"superstep_k": 1})

    def always_slow(knobs, st):
        dispatch_bound_plant(knobs, st)
        st["commit_p99"] = 90.0  # never improves: worst case walk

    ticks = []
    for w in range(12):
        always_slow(tuner.knobs, state)
        time.sleep(0.002)
        obs.snapshot()
        if tuner.tick() is not None:
            ticks.append(w)
    # >= cooldown+1 windows between consecutive decisions
    assert len(ticks) >= 2
    assert all(b - a >= 4 for a, b in zip(ticks, ticks[1:])), ticks


# ---------------------------------------------------------------------------
# freeze guards (acceptance: frozen under an active DiskFaultPlan)
# ---------------------------------------------------------------------------

def breach_forever(knobs, state):
    dispatch_bound_plant(knobs, state)
    state["commit_p99"] = 90.0


def isolated_guard():
    """``default_freeze_guard`` minus plans that PREDATE this test:
    the plan registries are process-global and weakly held, so earlier
    suite tests can leave plans alive (a router pinned by a leaked
    node); the guard logic under test is identical, filtered to plans
    this test creates."""
    from ra_tpu.log import faults
    from ra_tpu.transport.rpc import live_fault_plans
    gc.collect()
    pre_net = {id(p) for p in live_fault_plans()}
    pre_disk = faults.current_plan()

    def guard():
        cur = faults.current_plan()
        if cur is not None and cur is not pre_disk:
            return "disk_fault_plan_active"
        if any(id(p) not in pre_net and not p.quiet()
               for p in live_fault_plans()):
            return "transport_fault_plan_active"
        return None

    return guard


def test_frozen_under_active_disk_fault_plan():
    from ra_tpu.autotune import default_freeze_guard
    from ra_tpu.log import faults
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = AutoTuner(slo, obs, cooldown_windows=0, breach_windows=2,
                      incident_freeze_s=0.0,
                      freeze_guard=isolated_guard(),
                      knobs={"superstep_k": 1})
    # a QUIET plan (no fault probabilities): installed-ness is what
    # freezes; injecting real fsync EIO here would hit OTHER tests'
    # lingering WAL threads through the process-global IO shim
    plan = faults.DiskFaultPlan(seed=7)
    faults.install_plan(plan)
    try:
        # the REAL default guard names it (disk is checked first, so
        # this is deterministic whatever plans earlier tests leaked)
        assert default_freeze_guard() == "disk_fault_plan_active"
        base_f = len([e for e in RECORDER.events("tune")
                      if e[1] == "tune.freeze"])
        decisions = drive(obs, tuner, state, breach_forever, windows=6)
        assert decisions == []  # hard freeze: sustained breach ignored
        ov = tuner.overview()
        assert ov["frozen"] and \
            ov["freeze_reason"] == "disk_fault_plan_active"
        # freeze recorded ON THE TRANSITION, not per frozen tick
        freezes = [e for e in RECORDER.events("tune")
                   if e[1] == "tune.freeze"]
        assert len(freezes) == base_f + 1
    finally:
        faults.clear_plan()
    # thaw: breach streaks were reset, so it takes breach_windows
    # fresh windows of evidence before the first post-fault decision
    decisions = drive(obs, tuner, state, breach_forever, windows=4)
    assert decisions and decisions[0]["knob"] == "superstep_k"
    assert not tuner.overview()["frozen"]


def test_quiet_or_healed_transport_plan_does_not_freeze():
    """Liveness is not activity: routers pin their FaultPlan object
    after a chaos exercise ends, so the default guard must ignore
    plans that can no longer inject (all-zero specs, partitions
    healed) — otherwise one healed plan freezes every tuner in the
    process forever."""
    from ra_tpu.autotune import default_freeze_guard
    from ra_tpu.log import faults
    from ra_tpu.transport.rpc import FaultPlan, FaultSpec
    # plan registration is test-scoped (the conftest autouse fixture
    # unregisters plans leaked by earlier tests and restores the disk
    # slot), so this probe runs UNCONDITIONALLY — tier-1 carries no
    # skips; a failure here means the scoping fixture regressed
    assert faults.current_plan() is None, \
        "conftest plan scoping failed to restore the disk-plan slot"
    quiet = FaultPlan(seed=1)  # all-default specs: nothing to inject
    assert quiet.quiet()
    partitioned = FaultPlan(seed=2)
    partitioned.partition("nodeB")
    assert not partitioned.quiet()
    lossy = FaultPlan(seed=3, default=FaultSpec(drop=0.5))
    assert not lossy.quiet()
    partitioned.heal()
    assert partitioned.quiet()  # healed partition-only plan: quiet
    del lossy
    gc.collect()
    # only quiet plans remain alive: the scoped registry holds nothing
    # non-quiet from earlier tests, and this test's lossy plan is gone
    from ra_tpu.transport.rpc import live_fault_plans
    assert all(p.quiet() for p in live_fault_plans()), \
        "conftest plan scoping failed to unregister a leaked plan"
    assert default_freeze_guard() is None


def test_frozen_under_live_transport_fault_plan():
    from ra_tpu.transport.rpc import (FaultPlan, FaultSpec,
                                      live_fault_plans)
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = AutoTuner(slo, obs, cooldown_windows=0, breach_windows=2,
                      incident_freeze_s=0.0,
                      freeze_guard=isolated_guard(),
                      knobs={"superstep_k": 1})
    # an ACTIVE (non-quiet) plan: a lossy spec, wired to no transport
    plan = FaultPlan(seed=3, default=FaultSpec(drop=0.25))
    try:
        assert plan in live_fault_plans()  # the registry the guard reads
        assert not plan.quiet()
        assert drive(obs, tuner, state, breach_forever, windows=5) == []
        assert tuner.overview()["freeze_reason"] == \
            "transport_fault_plan_active"
    finally:
        del plan
        gc.collect()
    assert drive(obs, tuner, state, breach_forever, windows=4)


def test_frozen_after_fresh_incident(tmp_path):
    state = base_state()
    obs = mk_obs(state)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0),
                    fast_windows=3, slow_windows=6,
                    burn_fast=0.5, burn_slow=0.25)
    tuner = AutoTuner(slo, obs, cooldown_windows=0, breach_windows=2,
                      freeze_guard=lambda: None,  # isolate the incident leg
                      incident_freeze_s=3600.0,
                      knobs={"superstep_k": 1})
    RECORDER.dump("tuner_unit_incident", what="w",
                  data_dir=str(tmp_path))
    try:
        assert drive(obs, tuner, state, breach_forever, windows=5) == []
        assert tuner.overview()["freeze_reason"] == "recent_incident"
    finally:
        RECORDER.incidents.clear()  # do not freeze later tests' tuners
    assert drive(obs, tuner, state, breach_forever, windows=4)


# ---------------------------------------------------------------------------
# real-engine integration: phases flow end to end
# ---------------------------------------------------------------------------

def test_phase_attribution_on_real_durable_engine(tmp_path):
    from ra_tpu.engine import DispatchAheadDriver, open_engine
    from ra_tpu.models import CounterMachine

    eng = open_engine(CounterMachine(), str(tmp_path / "d"), 16, 3,
                      wal_shards=2, max_step_cmds=4, ring_capacity=64)
    try:
        obs = Observatory.for_engine(eng)
        slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0))
        drv = DispatchAheadDriver(eng, max_in_flight=2)
        nb = np.full((4, 16), 4, np.int32)
        pb = np.ones((4, 16, 4, 1), np.int32)
        for i in range(10):
            drv.submit(nb, pb)
            if i % 3 == 0:
                time.sleep(0.01)
                obs.snapshot()
        drv.drain()
        eng._dur.flush_all()
        snap = obs.snapshot()
        ph = snap["engine"]["phases"]
        # every phase of the durable dispatch path collected samples
        for p in ("host_staging", "device_dispatch", "queue_wait",
                  "wal_encode", "fsync_wait", "confirm_publish",
                  "commit_e2e"):
            assert ph[p]["count"] > 0, p
            assert ph[p]["total_ms"] >= 0
        assert ph["dropped"] == 0
        # knob stamps ride the pipeline overview (RA07's runtime half)
        pipe = snap["engine"]["pipeline"]
        for knob in TUNABLE_KNOBS:
            if knob != "cmds_per_step":
                assert knob in pipe
        assert pipe["cmds_per_step"] == 4
        # exposition: flattened phase scalars + the labelled histogram
        text = obs.prometheus(snap)
        parse_prometheus(text)
        assert "ra_tpu_engine_phases_commit_e2e_p99_ms" in text
        assert 'ra_tpu_engine_phase_ms_bucket{phase="fsync_wait"' in text
        assert "ra_tpu_slo_objectives_fsync_p99_ms_ok" in text
        # live batch-interval retarget lands on every shard
        eng._dur.set_batch_interval_ms(3.5)
        assert all(sh.wal.max_batch_interval_ms == 3.5
                   for sh in eng._dur._shards)
        assert eng._dur.batch_interval_ms() == 3.5
        obs.close()
        del slo
    finally:
        eng.close()


def test_volatile_engine_has_phase_plane_too():
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    eng = LockstepEngine(CounterMachine(), 8, 3, ring_capacity=64,
                         max_step_cmds=4)
    for _ in range(4):
        eng.uniform_step(2)
    ov = eng.phases.overview()
    # no driver, no WAL: the plane exists (zero-filled), never crashes
    assert ov["commit_e2e"]["count"] == 0
    assert eng.overview()["pipeline"]["wal_max_batch_interval_ms"] == -1.0


# ---------------------------------------------------------------------------
# ra_top: SLO verdict panel + autotuner footer
# ---------------------------------------------------------------------------

def test_ra_top_renders_slo_panel_and_tuner_footer(tmp_path):
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    snap = {
        "seq": 1, "ts": time.time(),
        "engine": {"lanes": 4, "members": 3},
        "slo": {"objectives": {
            "commit_p99_ms": {"verdict": "ok", "value": 8.2,
                              "op": "<=", "threshold": 25.0,
                              "burn_fast": 0.0, "burn_slow": 0.0},
            "fsync_p99_ms": {"verdict": "breach", "value": 61.0,
                             "op": "<=", "threshold": 50.0,
                             "burn_fast": 0.8, "burn_slow": 0.2}}},
        "autotune": {
            "knobs": {"superstep_k": 16, "cmds_per_step": 32,
                      "wal_max_batch_interval_ms": 0.0},
            "frozen": True, "freeze_reason": "disk_fault_plan_active",
            "decisions": 3, "cooldown_left": 2,
            "last_decision": {"ts": time.time() - 12,
                              "knob": "superstep_k", "old": 8,
                              "new": 16, "phase": "device_dispatch",
                              "objective": "commit_p99_ms"}},
    }
    path = str(tmp_path / "obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(snap) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "commit_p99_ms OK" in out
    assert "fsync_p99_ms BREACH" in out and "burn=0.8/0.2" in out
    assert "superstep_k 8->16 via device_dispatch/commit_p99_ms" in out
    assert "FROZEN(disk_fault_plan_active)" in out
    assert "superstep_k=16" in out and "decisions=3" in out


# ---------------------------------------------------------------------------
# overhead: the whole plane (phases + SLO + tuner) on the bench path
# ---------------------------------------------------------------------------

def test_plane_overhead_under_3pct_on_bench_path():
    """Interleaved A/B of the bench dispatch pattern: the ISSUE 9
    plane (phase stamps + Observatory snapshots + SLO evaluation +
    tuner ticks at the bench's window cadence) ON vs OFF, both sides
    with the PR 6 sampler attached — the sampler-vs-nothing bound is
    test_telemetry_overhead_under_3pct's pin already, so THIS pin
    isolates what ISSUE 9 adds on top.  Medians over interleaved
    rounds, retries absorb CI noise — the same shape as the PR 6/7
    pins."""
    import collections

    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine
    from ra_tpu.telemetry import TelemetrySampler

    eng = LockstepEngine(CounterMachine(), 64, 3, ring_capacity=64,
                         max_step_cmds=8, donate=False)
    n_new = np.full((64,), 8, np.int32)
    pay = np.ones((64, 8, 1), np.int32)
    for _ in range(10):
        eng.step(n_new, pay)
    eng.block_until_ready()
    sampler = TelemetrySampler(eng, cadence_steps=64)
    obs = Observatory.for_engine(eng, sampler=sampler)
    slo = SloEngine(obs, default_objectives(min_cmds_per_s=1.0))
    tuner = mk_tuner(slo, obs)
    sampler.drain()  # compile the jitted summary OUTSIDE the A/B

    def measure(seconds, plane_on):
        rb: collections.deque = collections.deque()
        n = 0
        last_obs = 0.0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            eng.step(n_new, pay)
            rb.append(eng.committed_lanes_async())
            while len(rb) > 8:
                np.asarray(rb.popleft())
            n += 1
            now = time.perf_counter()
            # the bench's own window cadence (bench.py maybe_observe):
            # snapshot + verdict + tick on a TIME basis, not per step
            if plane_on and now - last_obs >= 0.1:
                last_obs = now
                obs.snapshot()
                tuner.tick()
        eng.block_until_ready()
        return n / (time.perf_counter() - t0)

    # four attempts at PR 6's window length: the ~0.3s windows make a
    # 3% bound tight on an oversubscribed 1-2 core box; a REAL
    # regression fails every median
    overhead = 1.0
    for _attempt in range(4):
        rates = {False: [], True: []}
        for _round in range(4):
            for on in (False, True):
                rates[on].append(measure(0.3, on))
        off = sorted(rates[False])[len(rates[False]) // 2]
        on_r = sorted(rates[True])[len(rates[True]) // 2]
        overhead = (off - on_r) / off
        if overhead < 0.03:
            break
    obs.close()
    assert overhead < 0.03, f"plane overhead {overhead:.1%} >= 3%"
