"""Machine-version upgrade rules — the reference's
ra_machine_version_SUITE.erl:29-39 scenarios against the deterministic
harness: version exchange in pre-vote, the noop-carried version bump, the
('machine_version', Old, New) pseudo-command through which_module
dispatch, followers that cannot understand the new version stalling
their apply fold, and snapshot metadata carrying the version across
installs (ra_server.erl:2671-2732, :2260-2319)."""
from harness import SimCluster

from ra_tpu.core.machine import Machine
from ra_tpu.core.types import ElectionTimeout, SnapshotMeta


class CounterV0(Machine):
    """v0: commands add; knows nothing about versions."""

    version = 0

    def init(self, config):
        return 0

    def apply(self, meta, command, state):
        if isinstance(command, tuple) and command[0] == "machine_version":
            raise AssertionError(
                "an unversioned machine must never see a "
                "machine_version command")
        return state + command, state + command, []


class CounterV1(Machine):
    """v1: commands add DOUBLE; upgrade marker recorded in state.

    State becomes (value, upgraded_at_meta_index) after upgrade so tests
    can see the pseudo-command."""

    version = 1

    def __init__(self):
        self._v0 = CounterV0()

    def init(self, config):
        return 0

    def which_module(self, version):
        return self._v0 if version == 0 else self

    def apply(self, meta, command, state):
        if isinstance(command, tuple) and command[0] == "machine_version":
            _tag, old, new = command
            assert (old, new) == (0, 1)
            return ("v1", state, meta.index), None, []
        tag, base, at = state if isinstance(state, tuple) else \
            ("v1", state, None)
        new_val = base + 2 * command
        return (tag, new_val, at), new_val, []


def mixed_cluster(n=3, upgraded=(0, 1)):
    """SimCluster where servers at positions in `upgraded` run the v1
    machine and the rest still run v0 (a rolling upgrade in progress)."""
    calls = iter(range(n))
    return SimCluster(n, machine_factory=lambda: (
        CounterV1() if next(calls) in upgraded else CounterV0()))


def test_upgraded_leader_bumps_effective_version():
    c = mixed_cluster()
    s1, s2, s3 = c.ids
    c.elect(s1)
    assert c.leader() == s1
    srv1 = c.servers[s1]
    assert srv1.effective_machine_version == 1
    # the bump was applied as a pseudo-command through the v1 module
    assert srv1.machine_state[0] == "v1"
    # v1 semantics now in force: +5 adds 10
    c.command(s1, 5)
    assert srv1.machine_state[1] == 10
    # the upgraded follower tracked the bump and the command
    assert c.servers[s2].effective_machine_version == 1
    assert c.servers[s2].machine_state[1] == 10


def test_stale_version_follower_stalls_apply():
    c = mixed_cluster()
    s1, _s2, s3 = c.ids
    c.elect(s1)
    c.command(s1, 5)
    srv3 = c.servers[s3]
    # the v0 member saw the noop, recorded the new effective version, but
    # cannot run it: its apply fold stops (ra_server.erl:2713-2732)
    assert srv3.effective_machine_version == 1
    assert srv3.machine_state == 0
    assert srv3.last_applied < c.servers[s1].last_applied


def test_pre_vote_denies_too_new_candidate():
    c = mixed_cluster(3, upgraded=(0,))
    s1, s2, s3 = c.ids
    # v1 candidate, both peers v0 with effective version 0: they must
    # deny (they could not run a v1 leader's machine), so no quorum
    c.handle(s1, ElectionTimeout())
    c.run()
    assert c.leader() is None
    assert c.servers[s1].raft_state.value in ("pre_vote", "follower",
                                              "candidate")


def test_pre_vote_denies_stale_candidate_after_upgrade():
    c = mixed_cluster()
    s1, s2, s3 = c.ids
    c.elect(s1)          # effective version now 1 on s1, s2
    # the v0 member times out; its pre-vote carries machine_version 0,
    # below the upgraded members' effective version: denied
    c.handle(s3, ElectionTimeout())
    c.run()
    assert c.servers[s3].raft_state.value != "leader"
    assert c.leader() in (s1, None)


def test_unversioned_cluster_sees_no_version_command():
    # all v0: CounterV0.apply raises if it ever sees the pseudo-command
    c = SimCluster(3, machine_factory=CounterV0)
    c.elect(c.ids[0])
    c.command(c.ids[0], 7)
    assert c.servers[c.ids[0]].machine_state == 7
    assert c.servers[c.ids[0]].effective_machine_version == 0


def test_snapshot_meta_carries_machine_version():
    c = mixed_cluster(3, upgraded=(0, 1, 2))
    s1, _, _ = c.ids
    c.elect(s1)
    for v in (1, 2, 3):
        c.command(s1, v)
    srv = c.servers[s1]
    idx = srv.last_applied
    srv.log.update_release_cursor(
        idx, tuple((sid, p.membership) for sid, p in srv.cluster.items()),
        srv.effective_machine_version, srv.machine_state)
    got = srv.log.snapshot()
    assert got is not None
    meta: SnapshotMeta = got[0]
    assert meta.machine_version == 1
    assert meta.index == idx


def test_unversioned_can_change_to_versioned():
    """unversioned_can_change_to_versioned: a cluster born on an
    unversioned (v0) machine restarts onto a versioned one; the new
    leader's noop carries the bump and the upgrade pseudo-command runs
    exactly once."""
    from ra_tpu.core.server import RaServer
    from ra_tpu.core.types import ServerConfig

    c = SimCluster(3, machine_factory=CounterV0)
    s1 = c.ids[0]
    c.elect(s1)
    for v in (3, 4):
        c.command(s1, v)
    assert c.servers[s1].machine_state == 7
    # rolling restart: same logs, versioned machine
    for sid in c.ids:
        old = c.servers[sid]
        cfg = ServerConfig(server_id=sid, uid=old.cfg.uid,
                           cluster_name="simcluster",
                           initial_members=tuple(c.ids),
                           machine=CounterV1())
        srv = RaServer(cfg, old.log)
        srv.recover()
        c.servers[sid] = srv
        c.queues[sid].clear()
    c.elect(s1)
    srv1 = c.servers[s1]
    assert srv1.effective_machine_version == 1
    # recovery replayed the OLD entries through the v0 module (+3, +4),
    # then the bump pseudo-command ran through the v1 module
    assert srv1.machine_state[0] == "v1"
    assert srv1.machine_state[1] == 7
    # v1 semantics in force from here on: +5 adds 10
    c.command(s1, 5)
    assert srv1.machine_state[1] == 17
    for sid in c.ids:
        assert c.servers[sid].machine_state[1] == 17, sid


def test_snapshot_install_rejected_by_stale_member():
    """A follower whose machine cannot run the snapshot's version must
    refuse the install (the version gate on the receive path,
    ra_server.erl:1260-1296) and confirm only its own progress, instead
    of accepting state it cannot interpret.  Driven as a single injected
    RPC: in a live cluster the leader just retries later (the member
    stays behind until it is upgraded), which a synchronous sim cannot
    run to quiescence."""
    from ra_tpu.core.types import InstallSnapshotRpc, SendRpc

    c = mixed_cluster()
    s1, _s2, s3 = c.ids
    c.elect(s1)
    c.command(s1, 5)
    srv1, srv3 = c.servers[s1], c.servers[s3]
    meta = SnapshotMeta(
        index=srv1.last_applied, term=srv1.current_term,
        cluster=tuple((sid, p.membership)
                      for sid, p in srv1.cluster.items()),
        machine_version=1)
    effects = srv3.handle(InstallSnapshotRpc(
        term=srv1.current_term, leader_id=s1, meta=meta,
        chunk_number=1, chunk_flag="last", data=b""))
    # stays a follower, machine state untouched, nothing installed
    assert srv3.raft_state.value == "follower"
    assert srv3.machine_state == 0
    assert srv3.log.snapshot_index_term().index == 0
    # and the reply confirms only its own VALIDATED progress (the
    # applied frontier — advertising the raw tail can loop the leader's
    # repair through re-installs; see _follower_install_snapshot)
    replies = [e for e in effects if isinstance(e, SendRpc)]
    assert replies and replies[0].msg.last_index == srv3.last_applied
