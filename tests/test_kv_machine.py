"""KV machine tests — the ra-kv-store capability proof: linearizable
put/cas/delete semantics, key watchers over the monitor effect
vocabulary, and release-cursor snapshotting.  Part 1 drives apply
directly; part 2 runs a live cluster including a partition round
asserting cas-based counters lose no increments."""
import threading
import time

import pytest

import ra_tpu
from ra_tpu.core.machine import ApplyMeta
from ra_tpu.core.types import Monitor, ReleaseCursor, SendMsg, ServerId
from ra_tpu.models import KvMachine, Mailbox
from ra_tpu.models.kv import query_get, query_keys, query_size
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader


class Driver:
    def __init__(self, machine=None):
        self.m = machine or KvMachine()
        self.state = self.m.init({})
        self.idx = 0
        self.effects = []

    def apply(self, cmd):
        self.idx += 1
        st, reply, effs = self.m.apply(ApplyMeta(self.idx, 1), cmd,
                                       self.state)
        self.state = st
        self.effects.extend(effs)
        return reply


def test_put_get_delete_cas_semantics():
    d = Driver()
    assert d.apply(("put", "a", 1)) is None
    assert d.apply(("put", "a", 2)) == 1          # old value returned
    assert d.apply(("cas", "a", 2, 3)) == ("ok", 2)
    assert d.apply(("cas", "a", 99, 4)) == ("failed", 3)
    assert d.state.data["a"] == 3
    assert d.apply(("cas", "a", 3, None)) == ("ok", 3)   # cas-delete
    assert "a" not in d.state.data
    assert d.apply(("delete", "a")) is None
    d.apply(("put", "b", 9))
    assert d.apply(("delete", "b")) == 9


def test_watchers_notify_and_down_cleans_up():
    d = Driver()
    w = Mailbox("w1")
    d.apply(("watch", "k", w))
    assert any(isinstance(e, Monitor) and e.target is w for e in d.effects)
    d.apply(("put", "k", 5))
    d.apply(("delete", "k"))
    d.apply(("put", "other", 1))       # unwatched key: no event
    events = [e.msg for e in d.effects
              if isinstance(e, SendMsg) and e.to is w]
    assert events == [("kv_event", "k", 5), ("kv_event", "k", None)]
    # watcher death drops its watches (builtin down routed by the shell)
    d.apply(("down", w, "killed"))
    assert d.state.watchers == {}
    d.apply(("put", "k", 6))
    events = [e.msg for e in d.effects
              if isinstance(e, SendMsg) and e.to is w]
    assert len(events) == 2            # nothing new after down


def test_release_cursor_interval():
    d = Driver(KvMachine(snapshot_interval=5))
    for i in range(12):
        d.apply(("put", i, i))
    cursors = [e for e in d.effects if isinstance(e, ReleaseCursor)]
    assert [c.index for c in cursors] == [5, 10]
    # snapshot state is detached from live state
    snap = cursors[-1].machine_state
    before = len(snap.data)
    d.apply(("put", "x", 1))
    assert len(snap.data) == before


def test_queries():
    d = Driver()
    d.apply(("put", "a", 1))
    d.apply(("put", "b", 2))
    assert query_get("a")(d.state) == 1
    assert query_keys(d.state) == ["a", "b"]
    assert query_size(d.state) == 2


# ---------------------------------------------------------------------------
# live cluster
# ---------------------------------------------------------------------------

@pytest.fixture
def fabric():
    router = LocalRouter()
    nodes = [RaNode(f"kn{i}", router=router) for i in (1, 2, 3)]
    yield router, nodes
    router.heal()
    for n in nodes:
        n.stop()


def ids():
    return [ServerId(f"k{i}", f"kn{i}") for i in (1, 2, 3)]


def test_kv_end_to_end_linearizable_reads(fabric):
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("kv1", KvMachine, sids, router=router)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, ("put", "x", 10), router=router)
    res = ra_tpu.consistent_query(leader, query_get("x"), router=router)
    assert res.reply == 10
    res = ra_tpu.process_command(leader, ("cas", "x", 10, 11),
                                 router=router)
    assert res.reply == ("ok", 10)
    res = ra_tpu.consistent_query(leader, query_get("x"), router=router)
    assert res.reply == 11


def test_kv_watch_notifications_across_cluster(fabric):
    router, nodes = fabric
    sids = ids()
    ra_tpu.start_cluster("kv2", KvMachine, sids, router=router)
    leader = await_leader(router, sids)
    w = Mailbox("kvwatch")
    ra_tpu.process_command(leader, ("watch", "cfg", w), router=router)
    ra_tpu.process_command(leader, ("put", "cfg", {"v": 1}),
                           router=router)
    deadline = time.monotonic() + 5
    got = []
    while time.monotonic() < deadline and not got:
        got = [m for m in w.drain() if m[0] == "kv_event"]
        time.sleep(0.01)
    assert got == [("kv_event", "cfg", {"v": 1})]


def test_kv_cas_counters_lose_nothing_through_partition(fabric):
    """Jepsen-style workload: concurrent cas-increment clients through a
    leader partition; the final counter equals the number of successful
    cas acks (no lost or phantom increments)."""
    router, _ = fabric
    sids = ids()
    ra_tpu.start_cluster("kv3", KvMachine, sids, router=router,
                         election_timeout_ms=100)
    leader = await_leader(router, sids)
    ra_tpu.process_command(leader, ("put", "ctr", 0), router=router)
    acked = []
    maybe = []       # command sent but ack lost (e.g. timeout): Jepsen's
    stop = threading.Event()    # "info" result — may or may not have applied

    def worker():
        while not stop.is_set():
            target = None
            try:
                target = await_leader(router, sids, timeout=5.0)
                cur = ra_tpu.consistent_query(
                    target, query_get("ctr"), router=router,
                    timeout=2.0).reply
                res = ra_tpu.process_command(
                    target, ("cas", "ctr", cur, cur + 1), router=router,
                    timeout=2.0)
                if getattr(res, "reply", None) and res.reply[0] == "ok":
                    acked.append(1)
            except TimeoutError:
                if target is not None:
                    maybe.append(1)
                time.sleep(0.05)
            except Exception:
                time.sleep(0.05)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    # partition the current leader away mid-workload
    lead = await_leader(router, sids)
    for other in sids:
        if other.node != lead.node:
            router.block(lead.node, other.node)
    time.sleep(1.5)
    router.heal()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=5.0)
    final_leader = await_leader(router, sids)
    final = ra_tpu.consistent_query(final_leader, query_get("ctr"),
                                    router=router).reply
    # every acked increment landed exactly once; ack-lost attempts may or
    # may not have applied (at-most-once each)
    assert len(acked) <= final <= len(acked) + len(maybe), \
        f"counter {final} outside [{len(acked)}, " \
        f"{len(acked) + len(maybe)}]"
    assert len(acked) > 0, "workload made no progress"


def test_unknown_command_is_rejected():
    d = Driver()
    assert d.apply(("get", "k")) == ("error", "unknown_command")
    assert d.apply(("putt", "k", 1)) == ("error", "unknown_command")
    assert d.state.data == {}


def test_query_funs_cross_pickle_boundaries():
    """Query funs must be picklable: on TCP-transport clusters they ride
    inside query events (a lambda would be silently dropped at the
    frame encoder)."""
    import pickle

    d = Driver()
    d.apply(("put", "a", 41))
    q = pickle.loads(pickle.dumps(query_get("a")))
    assert q(d.state) == 41
    for fn in (query_keys, query_size):
        assert pickle.loads(pickle.dumps(fn))(d.state) is not None
