"""Device-resident telemetry plane + unified Observatory (ISSUE 6).

Covers: the TELEMETRY/TELEMETRY_SUMMARY field-registry parity (rule
RA05's runtime half), the Counters telemetry_dropped self-metric, the
async sampler's no-blocking-tick contract and snapshot correctness,
stall DETECTION under chaos (single-device and sharded-mesh — the
acceptance scenario), Prometheus exposition round-trip, the
time-series ring's rate consistency, the JSONL ring + ra_top renderer,
and the telemetry-on overhead bound on the bench dispatch path.
"""
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

import ra_tpu
from ra_tpu.core.types import ServerId
from ra_tpu.engine import LockstepEngine
from ra_tpu.engine.lockstep import LaneTelemetry
from ra_tpu.metrics import (Counters, FIELD_REGISTRY, TELEMETRY_FIELDS,
                            TELEMETRY_SUMMARY_FIELDS)
from ra_tpu.models import CounterMachine
from ra_tpu.telemetry import (Observatory, TelemetrySampler,
                              append_jsonl_ring, parse_prometheus,
                              read_jsonl_tail)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_engine(n_lanes=16, n_members=3):
    return LockstepEngine(CounterMachine(), n_lanes, n_members,
                          ring_capacity=64, max_step_cmds=4,
                          donate=False)


# ---------------------------------------------------------------------------
# registry parity (rule RA05's runtime half)
# ---------------------------------------------------------------------------

def test_lane_telemetry_matches_registry():
    assert LaneTelemetry._fields == TELEMETRY_FIELDS
    assert FIELD_REGISTRY["telemetry"] is TELEMETRY_FIELDS
    assert FIELD_REGISTRY["telemetry_summary"] is TELEMETRY_SUMMARY_FIELDS


def test_summary_snapshot_covers_registry_fields():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=4)
    for _ in range(4):
        eng.uniform_step(2)
    snap = s.drain()
    for field in TELEMETRY_SUMMARY_FIELDS:
        assert field in snap, field
    # host stamps ride alongside, never shadowing registry fields
    assert snap["stall_threshold"] == s.stall_threshold
    assert snap["inner_steps_at_sample"] == 4


def test_every_registry_group_documented():
    """Every field of every FIELD_REGISTRY group is named (backticked)
    in docs/OBSERVABILITY.md — the doc half of lint rule RA05, pinned
    at runtime too so the lint and the live registry cannot drift."""
    with open(os.path.join(REPO, "docs", "OBSERVABILITY.md")) as f:
        doc = f.read()
    for group, fields in FIELD_REGISTRY.items():
        for field in fields:
            assert f"`{field}`" in doc, (group, field)


# ---------------------------------------------------------------------------
# Counters telemetry_dropped self-metric (satellite 1)
# ---------------------------------------------------------------------------

def test_counters_count_dropped_increments():
    c = Counters()
    c.new("srv", ("a", "b"))
    c.incr("srv", "a")
    c.incr("srv", "b", 3)
    assert c.self_metrics() == {"telemetry_dropped": 0}
    c.incr("srv", "typo_field")       # unknown field
    c.incr("no_such_group", "a")      # unknown group
    assert c.self_metrics() == {"telemetry_dropped": 2}
    assert c.fetch("srv") == {"a": 1, "b": 3}


def test_node_workload_drops_nothing():
    """A real cluster workload must leave telemetry_dropped at 0: a
    nonzero value means an instrumentation site addresses a field the
    registry does not know (the silent-loss class this metric ends)."""
    from nemesis import await_leader
    from ra_tpu.core.machine import SimpleMachine
    from ra_tpu.node import LocalRouter, RaNode

    router = LocalRouter()
    nodes = [RaNode(f"tn{i}", router=router) for i in (1, 2, 3)]
    try:
        sids = [ServerId(f"tm{i}", f"tn{i}") for i in (1, 2, 3)]
        ra_tpu.start_cluster("tel_drop",
                             lambda: SimpleMachine(
                                 lambda cmd, st: st + cmd, 0),
                             sids, router=router)
        leader = await_leader(router, sids)
        for v in (1, 2, 3, 4):
            ra_tpu.process_command(leader, v, router=router)
        for n in nodes:
            assert n.counters.self_metrics()["telemetry_dropped"] == 0
    finally:
        for n in nodes:
            n.stop()


# ---------------------------------------------------------------------------
# sampler: async drain, no blocking ticks, correct values
# ---------------------------------------------------------------------------

def test_sampler_tick_path_never_blocks():
    eng = mk_engine()
    s = TelemetrySampler(eng, cadence_steps=4)
    for _ in range(16):
        eng.uniform_step(2)  # engine ticks the attached sampler itself
    assert s.counters["samples_started"] == 4
    assert s.counters["blocking_waits"] == 0
    s.drain()
    assert s.counters["samples_harvested"] == \
        s.counters["samples_started"] - s.counters["samples_dropped"]


def test_sampler_snapshot_matches_engine():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=8)
    for _ in range(10):
        eng.uniform_step(3)
    snap = s.drain()
    assert snap["steps"] == 10
    assert snap["committed_total"] == eng.committed_total()
    # healthy steady-state: no stalls, no churn, stable leaders
    assert snap["stalled_lanes"] == 0
    assert snap["leader_changes"] == 0
    assert snap["commit_lag_hist"][0] == 8  # all lanes at lag 0
    assert sum(snap["commit_lag_hist"]) == 8


def test_sampler_counts_elections():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=64)
    eng.uniform_step(1)
    eng.trigger_election([0, 3])
    snap = s.drain()
    assert snap["elections_requested"] == 2
    assert snap["elections_won"] == 2
    # the incumbent (longest log) wins the re-election: the leader
    # never MOVED, so stability age keeps counting — leader_age agrees
    # with leader_changes (0), not with elections_won
    assert snap["leader_changes"] == 0
    assert snap["leader_age_min"] == snap["steps"]


def test_sampler_superstep_cadence():
    """The fused path ticks the sampler K rounds per dispatch."""
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=8)
    for _ in range(4):
        eng.uniform_superstep(4, 2)
    assert s.counters["samples_started"] == 2
    snap = s.drain()
    assert snap["steps"] == 16
    assert snap["committed_total"] == eng.committed_total()


def test_sampler_cadence_carries_superstep_overshoot():
    """A superstep K that does not divide the cadence must not stretch
    the effective window: the overshoot carries into the next window
    (48 rounds in ticks of 3 at cadence 8 -> exactly 48//8 samples,
    not the 5 a reset-to-zero cadence would give)."""
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=8)
    for _ in range(16):
        eng.uniform_superstep(3, 1)
    assert s.counters["samples_started"] == 6


def test_sampler_overflow_evicts_oldest_without_blocking():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=1, max_pending=2)
    for _ in range(8):
        eng.uniform_step(1)
    assert s.counters["samples_started"] == 8
    assert s.counters["blocking_waits"] == 0
    assert len(s._pending) <= 2


def test_sampler_observer_fault_isolation():
    """A raising observer (a full JSONL ring's ENOSPC, say) must never
    crash the dispatch loop the harvest path rides: the error is
    counted in ``observer_errors``, later observers still run, and
    harvesting continues."""
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=2)
    seen = []
    s.add_observer(lambda _snap: (_ for _ in ()).throw(OSError("disk full")))
    s.add_observer(seen.append)
    for _ in range(8):
        eng.uniform_step(1)
    s.drain()
    assert s.counters["observer_errors"] >= 1
    assert s.counters["samples_harvested"] >= 2
    assert len(seen) == s.counters["samples_harvested"]


# ---------------------------------------------------------------------------
# stall detection under chaos (the acceptance scenario)
# ---------------------------------------------------------------------------

def run_stall_chaos(seed, obs_path=None, shard=False):
    """One chaos episode: break a random lane's quorum under traffic,
    assert the stall is DETECTED (stalled-lane count + top-K offender
    membership) within one sampling window of crossing the stall
    threshold, then heal and assert the flag clears.  Shared with
    ``tools/soak.py --obs``; ``shard=True`` runs the identical episode
    over a lanes-sharded mesh (virtual CPU devices)."""
    rng = random.Random(seed)
    N, P, cadence, threshold = 16, 3, 8, 4
    eng = mk_engine(N, P)
    if shard:
        from ra_tpu.parallel.mesh import shard_engine_state
        shard_engine_state(eng)
    s = TelemetrySampler(eng, cadence_steps=cadence, top_k=4,
                         stall_threshold=threshold)
    obs = Observatory.for_engine(eng, sampler=s)
    harvested: list = []
    s.add_observer(harvested.append)
    if obs_path:
        s.add_observer(lambda _snap: obs.to_jsonl(obs_path))

    # warmup traffic, everyone healthy
    for _ in range(4):
        eng.uniform_step(2)

    # break the victim's quorum: both non-leader members fail, so its
    # leader keeps accepting commands it can never commit
    victim = rng.randrange(N)
    lead = int(np.asarray(eng.state.leader_slot)[victim])
    for slot in range(P):
        if slot != lead:
            eng.fail_member(victim, slot)
    stall_from = eng.pipeline_counters["inner_steps"]
    for _ in range(2 * cadence):
        eng.uniform_step(2)
    assert s.counters["blocking_waits"] == 0, "tick path blocked"
    snap = s.drain()
    assert snap["stalled_lanes"] >= 1, snap
    assert victim in snap["top_lanes"], (victim, snap)
    rank = snap["top_lanes"].index(victim)
    assert snap["top_stall_steps"][rank] >= threshold
    assert snap["top_commit_lag"][rank] > 0
    assert snap["commit_lag_max"] > 0
    # detection latency: the first flagged PERIODIC sample landed within
    # one sampling window of the lane crossing the stall threshold
    flagged = [h["inner_steps_at_sample"] for h in harvested
               if h["stalled_lanes"] >= 1]
    assert flagged, "no periodic sample flagged the stall"
    assert min(flagged) <= stall_from + threshold + cadence

    # heal: recover the failed members, let the backlog commit
    for slot in range(P):
        if slot != lead:
            eng.recover_member(victim, slot)
    for _ in range(2 * cadence):
        eng.uniform_step(0)
    snap2 = s.drain()
    assert snap2["stalled_lanes"] == 0, snap2
    assert snap2["commit_lag_max"] == 0
    return {"victim": victim, "detected_at": min(flagged),
            "stall_from": stall_from, "snapshots": len(harvested)}


@pytest.mark.parametrize("seed", [0, 7])
def test_stalled_lane_detected_single_device(seed):
    run_stall_chaos(seed)


def test_stalled_lane_detected_sharded_mesh():
    """The same episode over a lanes-sharded mesh: the jitted summary's
    reductions + top_k lower to cross-device collectives, so the
    offender ids stay global lane ids."""
    run_stall_chaos(11, shard=True)


# ---------------------------------------------------------------------------
# Observatory: merge, ring, rates, exposition
# ---------------------------------------------------------------------------

def test_shard_stats_reach_exposition_and_ring():
    """Per-shard WAL stats are a LIST of dicts in wal_overview(): the
    numeric flattening indexes into them so fsync p50/p99 and queue
    depths reach the Prometheus exposition and the time-series ring
    (the SLO-autotuner substrate), not just the raw JSONL view."""
    obs = Observatory()
    obs.add_source("engine", lambda: {
        "wal": {"shards": [{"fsync_p50_ms": 3.0, "queue_depth": 2},
                           {"fsync_p50_ms": 5.5, "queue_depth": 0}]}})
    snap = obs.snapshot()
    parsed = parse_prometheus(obs.prometheus(snap))
    assert parsed[("ra_tpu_engine_wal_shards_0_fsync_p50_ms", "")] == 3.0
    assert parsed[("ra_tpu_engine_wal_shards_1_fsync_p50_ms", "")] == 5.5
    obs.snapshot()
    assert obs.percentile("engine_wal_shards_0_queue_depth", 0.5) == 2.0
    assert obs.window_rates().get("engine_wal_shards_1_queue_depth") == 0.0


def test_per_device_shard_stats_round_trip_under_mesh(tmp_path):
    """ISSUE 11 satellite: a REAL durable engine sharded over the 8
    forced-host devices with PER-DEVICE WAL shards (8, one per
    lane-axis device) — every shard's fsync/queue/confirm stats must
    round-trip through the Prometheus exposition and land in the
    time-series ring as rateable keys (>4 shards: nothing may silently
    truncate), and ra_top must render the per-shard rows."""
    import subprocess
    import sys

    import jax

    from ra_tpu.engine.durable import open_engine
    from ra_tpu.parallel.mesh import (lane_mesh, per_device_wal_shards,
                                      shard_engine_state)
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 forced host devices")
    mesh = lane_mesh(jax.devices(), member_axis=1)
    n_shards = per_device_wal_shards(mesh)
    assert n_shards == 8
    eng = open_engine(CounterMachine(), str(tmp_path / "d"), 64,
                      wal_shards=n_shards, ring_capacity=256,
                      max_step_cmds=8, donate=False)
    try:
        shard_engine_state(eng, mesh)
        obs = Observatory.for_engine(eng)
        n_new = np.full((64,), 8, np.int32)
        pay = np.ones((64, 8, 1), np.int32)
        for _ in range(4):
            eng.step(n_new, pay)
        eng._dur.flush_all()
        obs.snapshot()
        for _ in range(4):
            eng.step(n_new, pay)
        eng._dur.flush_all()
        snap = obs.snapshot()
        assert len(snap["engine"]["wal"]["shards"]) == 8
        parsed = parse_prometheus(obs.prometheus(snap))
        for i in range(8):
            # every per-device shard's latency + depth gauges exposed
            assert ("ra_tpu_engine_wal_shards_%d_fsync_p50_ms" % i,
                    "") in parsed, i
            assert ("ra_tpu_engine_wal_shards_%d_queue_depth" % i,
                    "") in parsed, i
        # monotone per-shard counters rate over the ring (writes
        # happened between the two snapshots on every shard)
        rates = obs.window_rates()
        for i in range(8):
            assert rates.get("engine_wal_shards_%d_writes" % i, 0) > 0, i
        # the mesh stamp rides the pipeline overview
        assert snap["engine"]["pipeline"]["mesh_shape"] == "1x8"
        obs.close()
        # ra_top renders one row per shard with its lane slice
        import json as _json
        path = str(tmp_path / "obs.jsonl")
        with open(path, "w") as f:
            f.write(_json.dumps(snap, default=repr) + "\n")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "ra_top.py"),
             path, "--once"], capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        for i in range(8):
            assert f"wal[{i}]" in r.stdout, r.stdout
        assert "lanes=56..64" in r.stdout  # the last device's slice
    finally:
        eng.close()


def test_prometheus_round_trip():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=4)
    for _ in range(8):
        eng.uniform_step(2)
    s.drain()
    obs = Observatory.for_engine(eng, sampler=s)
    text = obs.prometheus()
    parsed = parse_prometheus(text)
    assert parsed  # every non-comment line parsed or ValueError raised
    names = {n for n, _lbl in parsed}
    assert "ra_tpu_engine_telemetry_committed_total" in names
    assert "ra_tpu_engine_sampler_samples_started" in names
    # histogram family: cumulative, +Inf bucket == lane count == count
    buckets = sorted((lbl, v) for (n, lbl), v in parsed.items()
                     if n == "ra_tpu_engine_commit_lag_bucket")
    assert buckets, text
    inf = [v for lbl, v in buckets if "+Inf" in lbl]
    assert inf == [8.0]
    assert parsed[("ra_tpu_engine_commit_lag_count", "")] == 8.0
    # top-K offender gauges carry lane + rank labels
    assert any(n == "ra_tpu_engine_top_commit_lag" and "lane=" in lbl
               for n, lbl in parsed)


def test_prometheus_parser_rejects_garbage():
    with pytest.raises(ValueError):
        parse_prometheus("ra_tpu_ok 1\nnot a metric line at all\n")
    with pytest.raises(ValueError):
        parse_prometheus("ra_tpu_ok notanumber\n")
    # every value form the exposition format allows must parse —
    # including negative exponents, which _fmt_num emits for tiny
    # floats (review catch: a char-class regex rejected '5e-05')
    got = parse_prometheus(
        "ra_tpu_tiny 5e-05\nra_tpu_neg -1\nra_tpu_inf +Inf\n")
    assert got[("ra_tpu_tiny", "")] == 5e-05
    assert got[("ra_tpu_neg", "")] == -1.0
    assert got[("ra_tpu_inf", "")] == float("inf")


def test_window_rates_consistent_with_counters():
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=4)
    obs = Observatory.for_engine(eng, sampler=s)
    for _ in range(4):
        eng.uniform_step(2)
    s.drain()
    obs.snapshot()
    c0 = eng.committed_total()
    time.sleep(0.05)
    for _ in range(6):
        eng.uniform_step(2)
    s.drain()
    obs.snapshot()
    c1 = eng.committed_total()
    rates = obs.window_rates()
    key = "engine_telemetry_committed_total"
    (t0, a), (t1, b) = obs.ring()[-2:]
    # telemetry keys rate over the SAMPLE's own window, not snapshot ts
    tdt = b["engine_telemetry_ts"] - a["engine_telemetry_ts"]
    assert rates[key] == pytest.approx((c1 - c0) / tdt, rel=1e-4)
    assert rates[key] > 0
    # monotone counters never read negative; seq ticks exactly 1/snap
    # (window_rates rounds to 4 decimals, hence the loose tolerance)
    assert rates["seq"] * (t1 - t0) == pytest.approx(1.0, rel=1e-2)
    assert obs.percentile(key, 0.5) is not None


def test_window_rates_omit_stale_telemetry_sample():
    """Snapshots faster than the harvest cadence re-embed the same
    sample: telemetry keys must be OMITTED (absent beats a misleading
    0 cmds/s on a running engine); other sources still rate."""
    same_sample = {"ts": 1000.0, "committed_total": 512.0}
    obs = Observatory()
    obs.add_source("engine", lambda: {"telemetry": dict(same_sample),
                                      "pipeline": {"dispatches": 7}})
    obs.snapshot()
    obs.snapshot()
    rates = obs.window_rates()
    assert "engine_telemetry_committed_total" not in rates
    assert rates.get("engine_pipeline_dispatches") == 0.0


def test_failing_source_degrades_not_dies():
    obs = Observatory()
    obs.add_source("ok", lambda: {"x": 1})
    obs.add_source("boom", lambda: 1 / 0)
    snap = obs.snapshot()
    assert snap["ok"] == {"x": 1}
    assert "error" in snap["boom"]
    parse_prometheus(obs.prometheus(snap))  # still exports


def test_system_observatory_merges_wal_counters(tmp_path):
    from ra_tpu.system import RaSystem

    sysm = RaSystem(str(tmp_path), wal_supervise=False)
    try:
        obs = sysm.observatory()
        snap = obs.snapshot()
        wal = snap["system"]["counters"]["wal"]
        assert "fsync_p50_ms" in wal and "queue_depth" in wal
        assert "disk_faults" in snap["system"]["counters"]
        parse_prometheus(obs.prometheus(snap))
    finally:
        sysm.close()


# ---------------------------------------------------------------------------
# JSONL ring + ra_top
# ---------------------------------------------------------------------------

def test_jsonl_ring_bounds_and_tail(tmp_path):
    path = str(tmp_path / "obs.jsonl")
    for i in range(70):
        append_jsonl_ring(path, {"seq": i}, max_lines=16)
    with open(path) as f:
        lines = f.readlines()
    assert len(lines) <= 32  # compacts once past 2*max_lines
    tail = read_jsonl_tail(path, 3)
    assert [t["seq"] for t in tail] == [67, 68, 69]


def test_ra_top_renders_observatory_snapshot(tmp_path):
    eng = mk_engine(8)
    s = TelemetrySampler(eng, cadence_steps=4)
    # stall a lane so the offender row renders
    lead = int(np.asarray(eng.state.leader_slot)[2])
    for slot in range(3):
        if slot != lead:
            eng.fail_member(2, slot)
    for _ in range(12):
        eng.uniform_step(2)
    s.drain()
    obs = Observatory.for_engine(eng, sampler=s)
    path = str(tmp_path / "obs.jsonl")
    obs.to_jsonl(path)
    obs.to_jsonl(path)  # two snapshots -> the rate line renders too
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ra_top.py"),
         path, "--once"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "ra_top" in out and "stalled=1" in out
    assert "STALLED" in out and "#2" in out
    assert "cmds/s" in out and "pipe" in out


# ---------------------------------------------------------------------------
# overhead: telemetry on at default cadence stays under 3% (bench path)
# ---------------------------------------------------------------------------

def test_telemetry_overhead_under_3pct():
    """Interleaved A/B rounds of the bench dispatch pattern, sampler on
    vs off, same engine config (shared jitted step).  Interleaving
    cancels host drift; one in-test retry absorbs a noisy first attempt
    on oversubscribed CI before declaring a real regression."""

    def mk(with_sampler):
        eng = LockstepEngine(CounterMachine(), 64, 3, ring_capacity=64,
                             max_step_cmds=8, donate=False)
        if with_sampler:
            TelemetrySampler(eng)  # attaches at default cadence
        return eng

    eng_off, eng_on = mk(False), mk(True)
    n_new = np.full((64,), 8, np.int32)
    pay = np.ones((64, 8, 1), np.int32)
    for eng in (eng_off, eng_on):
        for _ in range(10):
            eng.step(n_new, pay)
        eng.block_until_ready()

    def measure(eng, seconds):
        import collections
        rb: collections.deque = collections.deque()
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            eng.step(n_new, pay)
            rb.append(eng.committed_lanes_async())
            while len(rb) > 8:
                np.asarray(rb.popleft())
            n += 1
        eng.block_until_ready()
        return n / (time.perf_counter() - t0)

    # three attempts: the ~0.3s windows make the 3% bound tight on an
    # oversubscribed 2-core box; a REAL regression fails every median
    overhead = 1.0
    for _attempt in range(3):
        rates = {False: [], True: []}
        for _round in range(4):
            for flag in (False, True):
                rates[flag].append(
                    measure(eng_on if flag else eng_off, 0.3))
        off = sorted(rates[False])[len(rates[False]) // 2]
        on = sorted(rates[True])[len(rates[True]) // 2]
        overhead = (off - on) / off
        if overhead < 0.03:
            break
    assert overhead < 0.03, f"telemetry overhead {overhead:.1%} >= 3%"


def test_sampler_feeds_tracer_counter_track():
    """Harvested samples feed the installed Tracer a `lane_health`
    counter track (ph "C"), so Chrome traces carry lane-health gauges
    alongside the engine spans; no tracer installed = no events."""
    from ra_tpu import trace

    t = trace.Tracer()
    trace.set_tracer(t)
    try:
        eng = mk_engine(8)
        s = TelemetrySampler(eng, cadence_steps=4)
        for _ in range(8):
            eng.uniform_step(2)
        s.drain()
    finally:
        trace.set_tracer(None)
    tracks = [e for e in t.events()
              if e["ph"] == "C" and e["name"] == "lane_health"]
    assert tracks, "no lane_health counter events recorded"
    args = tracks[-1]["args"]
    for key in ("stalled_lanes", "commit_lag_max", "apply_lag_max",
                "leader_changes"):
        assert key in args, args


def test_node_incr_sites_address_server_fields():
    """Every counter increment the node shell issues by field literal
    must name a SERVER_FIELDS member — its groups are created with
    that field set, so anything else is silently dropped (pre-PR) or
    flags telemetry_dropped (now).  Review catch: a snapshot_installed
    incr here targeted a LOG_FIELDS name and was lost for five PRs;
    the log facade owns that field."""
    import ast
    import inspect

    from ra_tpu import node as node_mod
    from ra_tpu.metrics import SERVER_FIELDS

    tree = ast.parse(inspect.getsource(node_mod))
    sites = [(n.lineno, n.args[1].value) for n in ast.walk(tree)
             if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)
             and n.func.attr == "incr" and len(n.args) >= 2
             and isinstance(n.args[1], ast.Constant)
             and isinstance(n.args[1].value, str)]
    assert sites, "expected incr sites in node.py"
    bad = [s for s in sites if s[1] not in SERVER_FIELDS]
    assert not bad, f"incr sites addressing non-SERVER_FIELDS names: {bad}"
