"""Cross-process TCP transport tests — the coordination_SUITE role: real OS
processes as nodes, real sockets, leader kill, failure detection."""
import multiprocessing as mp
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))


def _worker(node_name, port_map, cmd_q, res_q):
    """One OS process hosting one RaNode behind a TcpRouter."""
    import ra_tpu
    from ra_tpu.core.machine import SimpleMachine
    from ra_tpu.core.types import ServerConfig, ServerId
    from ra_tpu.node import RaNode
    from ra_tpu.transport.tcp import TcpRouter

    my_addr = ("127.0.0.1", port_map[node_name])
    book = {n: ("127.0.0.1", p) for n, p in port_map.items()
            if n != node_name}
    router = TcpRouter(my_addr, book)
    node = RaNode(node_name, router=router)
    sids = [ServerId(f"m_{n}", n) for n in sorted(port_map)]
    me = ServerId(f"m_{node_name}", node_name)
    node.start_server(ServerConfig(
        server_id=me, uid=f"uid_{node_name}", cluster_name="tcp",
        initial_members=tuple(sids),
        machine=SimpleMachine(lambda c, s: s + c, 0),
        election_timeout_ms=150, tick_interval_ms=150))
    while True:
        cmd = cmd_q.get()
        if cmd[0] == "stop":
            res_q.put(("stopped", node_name))
            return
        if cmd[0] == "elect":
            ra_tpu.trigger_election(me, router)
            res_q.put(("ok",))
        elif cmd[0] == "command":
            try:
                r = ra_tpu.process_command(me, cmd[1], router=router,
                                           timeout=10.0)
                res_q.put(("ok", r.reply, str(r.leader)))
            except Exception as e:
                res_q.put(("err", repr(e)))
        elif cmd[0] == "state":
            sh = node.shells.get(me.name)
            res_q.put(("ok", sh.server.raft_state.value,
                       sh.server.machine_state,
                       sh.server.current_term))
        elif cmd[0] == "metrics":
            res_q.put(("ok", ra_tpu.key_metrics(me, router=router)))


@pytest.fixture
def procs():
    import socket
    ctx = mp.get_context("spawn")
    names = ["tn1", "tn2", "tn3"]
    ports = {}
    socks = []
    for n in names:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports[n] = s.getsockname()[1]
        socks.append(s)
    for s in socks:
        s.close()
    chans = {}
    workers = {}
    for n in names:
        cq, rq = ctx.Queue(), ctx.Queue()
        p = ctx.Process(target=_worker, args=(n, ports, cq, rq),
                        daemon=True)
        p.start()
        chans[n] = (cq, rq)
        workers[n] = p
    time.sleep(0.5)  # listeners up
    yield names, chans, workers
    for n, p in workers.items():
        if p.is_alive():
            chans[n][0].put(("stop",))
    time.sleep(0.3)
    for p in workers.values():
        if p.is_alive():
            p.terminate()


def _ask(chans, n, *cmd, timeout=15):
    cq, rq = chans[n]
    cq.put(cmd)
    return rq.get(timeout=timeout)


def test_cross_process_cluster(procs):
    names, chans, workers = procs
    _ask(chans, "tn1", "elect")
    # the election is fire-and-forget: wait for a leader FIRST, then send
    # the (non-idempotent) command exactly once — retrying a counter
    # command after a lost reply would double-apply it
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        states = [_ask(chans, n, "state") for n in names]
        if any(s[1] == "leader" for s in states):
            break
        time.sleep(0.2)
    assert any(s[1] == "leader" for s in states), states
    r = _ask(chans, "tn1", "command", 5, timeout=20)
    assert r[0] == "ok" and r[1] == 5, r
    r = _ask(chans, "tn2", "command", 7)  # redirect over TCP
    assert r[0] == "ok" and r[1] == 12, r
    # replicas converge
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        states = [_ask(chans, n, "state") for n in names]
        if all(s[2] == 12 for s in states):
            break
        time.sleep(0.1)
    assert all(s[2] == 12 for s in states), states


def test_leader_process_kill_failover(procs):
    names, chans, workers = procs
    _ask(chans, "tn1", "elect")
    r = _ask(chans, "tn1", "command", 1)
    assert r[0] == "ok"
    leader_node = r[2].split("@")[1]
    # SIGKILL the leader's OS process: detector + election timers recover
    workers[leader_node].terminate()
    rest = [n for n in names if n != leader_node]
    deadline = time.monotonic() + 20
    got = None
    while time.monotonic() < deadline:
        r = _ask(chans, rest[0], "command", 2, timeout=20)
        if r[0] == "ok":
            got = r
            break
        time.sleep(0.2)
    assert got is not None and got[1] == 3, got
    assert got[2].split("@")[1] != leader_node
