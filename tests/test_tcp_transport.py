"""Cross-process TCP fabric tests — the coordination_SUITE +
partitions_SUITE roles over real OS processes and real sockets
(/root/reference/test/coordination_SUITE.erl,
/root/reference/test/partitions_SUITE.erl:29-57): cluster lifecycle,
leader kill, socket-level partitions with no-loss/no-dup assertions,
snapshot install across processes, membership change, node restart over
a durable log, and drop-counter accounting.
"""
import multiprocessing as mp
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
sys.path.insert(0, os.path.dirname(__file__))

from tcp_worker import worker_main  # noqa: E402


def _free_ports(names):
    import socket
    ports = {}
    socks = []
    for n in names:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports[n] = s.getsockname()[1]
        socks.append(s)
    for s in socks:
        s.close()
    return ports


class Fabric:
    """Spawned-process cluster driver."""

    def __init__(self, names, machine="counter", data_root=None,
                 extra_members=()):
        ctx = mp.get_context("spawn")
        self.names = names
        self.ports = _free_ports(names)
        self.chans = {}
        self.workers = {}
        self.machine = machine
        self.data_root = data_root
        self.extra = tuple(extra_members)
        for n in names:
            self._spawn(ctx, n)
        self._await_ready(names)

    def _spawn(self, ctx, n):
        cq, rq = ctx.Queue(), ctx.Queue()
        data_dir = os.path.join(self.data_root, n) if self.data_root else None
        p = ctx.Process(target=worker_main,
                        args=(n, self.ports, cq, rq, self.machine,
                              data_dir, 500, self.extra),
                        daemon=True)
        p.start()
        self.chans[n] = (cq, rq)
        self.workers[n] = p

    def respawn(self, n):
        """Restart a (possibly killed) worker process over its data."""
        ctx = mp.get_context("spawn")
        self._spawn(ctx, n)
        self._await_ready([n])

    def _await_ready(self, names, timeout=240):
        """Block until each named worker reports ready.  Worker startup
        (a fresh jax import per spawned process) can take tens of
        seconds on a loaded box; starting the test before every peer is
        up loses the initial election trigger and blows per-ask
        timeouts (the round-3 test_cross_process_cluster flake)."""
        for n in names:
            r = self.chans[n][1].get(timeout=timeout)
            assert r[0] == "ready", r

    def ask(self, n, *cmd, timeout=60):
        import queue as _q
        cq, rq = self.chans[n]
        cq.put(cmd)
        deadline = time.monotonic() + timeout
        while True:
            try:
                return rq.get(timeout=1.0)
            except _q.Empty:
                if not self.workers[n].is_alive():
                    # the reply may have landed just as the process
                    # exited (e.g. the "stop" ack): drain once before
                    # declaring death
                    try:
                        return rq.get_nowait()
                    except _q.Empty:
                        raise RuntimeError(
                            f"worker {n} died while awaiting "
                            f"{cmd[0]!r}") from None
                if time.monotonic() > deadline:
                    raise

    def await_identical_lists(self, acked, timeout=90):
        """Poll every member until all hold ONE identical list that
        contains every acked value; returns it.  Short per-poll
        timeouts so one unresponsive worker cannot eat the budget, and
        the last error is surfaced instead of a vacuous pass."""
        deadline = time.monotonic() + timeout
        states, last_err = {}, None
        while time.monotonic() < deadline:
            try:
                states = {n: self.ask(n, "state", timeout=5)[2]
                          for n in self.names}
            except Exception as e:  # noqa: BLE001 — retried probe
                last_err = e
                time.sleep(0.5)
                continue
            lists = list(states.values())
            if all(x == lists[0] for x in lists) and \
                    set(acked) <= set(lists[0]):
                break
            time.sleep(0.4)
        assert states, f"no member ever answered: {last_err!r}"
        lists = list(states.values())
        assert all(x == lists[0] for x in lists), states
        final = lists[0]
        assert set(acked) <= set(final), \
            (sorted(set(acked) - set(final)), "acked values lost")
        assert len(final) == len(set(final)), "duplicates applied"
        return final

    def stop(self):
        for n, p in self.workers.items():
            if p.is_alive():
                try:
                    self.chans[n][0].put(("stop",))
                except Exception:
                    pass
        time.sleep(0.3)
        for p in self.workers.values():
            if p.is_alive():
                p.terminate()

    # helpers ------------------------------------------------------------

    def await_leader(self, timeout=60):
        deadline = time.monotonic() + timeout
        states = {}
        while time.monotonic() < deadline:
            for n in self.names:
                if not self.workers[n].is_alive():
                    continue
                r = self.ask(n, "state")
                states[n] = r
                if r[1] == "leader":
                    return n
            time.sleep(0.2)
        raise TimeoutError(f"no leader: {states}")

    def await_converged(self, want, nodes=None, timeout=30):
        nodes = nodes or self.names
        deadline = time.monotonic() + timeout
        states = {}
        while time.monotonic() < deadline:
            states = {n: self.ask(n, "state") for n in nodes}
            if all(s[2] == want for s in states.values()):
                return states
            time.sleep(0.2)
        raise AssertionError(f"no convergence to {want!r}: {states}")


@pytest.fixture
def fabric3():
    f = Fabric(["tn1", "tn2", "tn3"])
    f.ask("tn1", "elect")
    yield f
    f.stop()


def test_cross_process_cluster(fabric3):
    f = fabric3
    f.await_leader()
    r = f.ask("tn1", "command", 5)
    assert r[0] == "ok" and r[1] == 5, r
    r = f.ask("tn2", "command", 7)  # redirect over TCP
    assert r[0] == "ok" and r[1] == 12, r
    f.await_converged(12)


def test_leader_process_kill_failover(fabric3):
    f = fabric3
    f.await_leader()
    r = f.ask("tn1", "command", 1)
    assert r[0] == "ok"
    leader_node = r[2].split("@")[1]
    f.workers[leader_node].terminate()
    rest = [n for n in f.names if n != leader_node]
    deadline = time.monotonic() + 30
    got = None
    while time.monotonic() < deadline:
        r = f.ask(rest[0], "command", 2, timeout=30)
        if r[0] == "ok":
            got = r
            break
        time.sleep(0.3)
    assert got is not None and got[1] == 3, got
    assert got[2].split("@")[1] != leader_node


def test_partition_no_loss_no_dup():
    """Socket-level partition + heal with an append-only list machine:
    every acknowledged value survives exactly once, nothing duplicates
    (the partitions_SUITE no-loss workload over real sockets)."""
    f = Fabric(["tn1", "tn2", "tn3"], machine="list")
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        acked = []
        for v in range(10):
            r = f.ask(leader, "command", v)
            assert r[0] == "ok"
            acked.append(v)
        # partition one follower at the socket level (both directions)
        victim = [n for n in f.names if n != leader][0]
        f.ask(victim, "partition", [n for n in f.names if n != victim])
        for n in f.names:
            if n != victim:
                f.ask(n, "partition", [victim])
        # majority keeps committing
        for v in range(10, 20):
            r = f.ask(leader, "command", v, timeout=30)
            assert r[0] == "ok", r
            acked.append(v)
        # the victim's detector rules the others down meanwhile
        deadline = time.monotonic() + 10
        seen_down = False
        while time.monotonic() < deadline and not seen_down:
            ov = f.ask(victim, "overview")[1]
            seen_down = any(s == "down" for s in ov["node_status"].values())
            time.sleep(0.2)
        assert seen_down, ov
        # heal and converge
        for n in f.names:
            f.ask(n, "heal")
        states = f.await_converged(acked, timeout=40)
        for n, s in states.items():
            assert s[2] == acked, (n, s[2])           # no loss
            assert len(s[2]) == len(set(s[2])), n     # no dup
    finally:
        f.stop()


def test_drop_counters_during_partition():
    f = Fabric(["tn1", "tn2", "tn3"])
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        victim = [n for n in f.names if n != leader][0]
        f.ask(leader, "partition", [victim])
        for v in range(5):
            assert f.ask(leader, "command", v + 1, timeout=30)[0] == "ok"
        time.sleep(1.0)
        ov = f.ask(leader, "overview")[1]
        assert ov["dropped_sends"] > 0, ov  # [noconnect,nosuspend] drops
    finally:
        f.stop()


def test_snapshot_install_over_tcp(tmp_path):
    """A member cut off while the leader truncates its log behind a
    snapshot must catch up via the chunked install_snapshot path over
    real sockets (SURVEY §3.3)."""
    f = Fabric(["tn1", "tn2", "tn3"], machine="snapcounter",
               data_root=str(tmp_path))
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        victim = [n for n in f.names if n != leader][0]
        f.ask(victim, "partition", [n for n in f.names if n != victim])
        for n in f.names:
            if n != victim:
                f.ask(n, "partition", [victim])
        # push far past several release_cursor points (every 32 applies)
        total = 0
        for v in range(120):
            r = f.ask(leader, "command", 1, timeout=30)
            assert r[0] == "ok", r
            total += 1
        # leader must have snapshotted
        deadline = time.monotonic() + 15
        snap_idx = 0
        while time.monotonic() < deadline and snap_idx == 0:
            m = f.ask(leader, "metrics")[1]
            snap_idx = m.get("snapshot_index", 0) or 0
            time.sleep(0.2)
        assert snap_idx > 0, m
        for n in f.names:
            f.ask(n, "heal")
        states = f.await_converged(total, timeout=60)
        # the victim caught up via snapshot: its own snapshot index is
        # at least the leader's truncation point
        m = f.ask(victim, "metrics")[1]
        assert (m.get("snapshot_index", 0) or 0) >= snap_idx, m
    finally:
        f.stop()


def test_membership_change_over_tcp():
    """Join a 4th OS-process member as promotable nonvoter, watch it
    catch up and get promoted, then remove it ('$ra_join'/'$ra_leave'
    over real sockets)."""
    f = Fabric(["tn1", "tn2", "tn3", "tn4"], extra_members=("tn4",))
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        for v in (1, 2, 3):
            assert f.ask(leader, "command", v)[0] == "ok"
        # start the new member's server, then join it
        assert f.ask("tn4", "start_member")[0] == "ok"
        r = f.ask(leader, "add_member", "tn4", timeout=30)
        assert r[0] == "ok", r
        # the new member catches up and (promotable) becomes a voter
        deadline = time.monotonic() + 30
        ok = False
        while time.monotonic() < deadline and not ok:
            s = f.ask("tn4", "state")
            members = f.ask(leader, "members")
            ok = s[2] == 6 and "m_tn4" in members[1]
            time.sleep(0.2)
        assert ok, (s, members)
        # remove it again
        r = f.ask(leader, "remove_member", "tn4", timeout=30)
        assert r[0] == "ok", r
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            members = f.ask(leader, "members")[1]
            if "m_tn4" not in members:
                break
            time.sleep(0.2)
        assert "m_tn4" not in members, members
    finally:
        f.stop()


@pytest.mark.parametrize("victim_role", ["leader", "follower"])
def test_wal_crash_on_node_over_tcp(tmp_path, victim_role):
    """coordination_SUITE segment_writer_or_wal_crash_{leader,follower}:
    crash one node's fan-in WAL mid-traffic across real OS processes.
    The supervisor restarts it, unconfirmed entries are resent, and no
    acknowledged command is lost on any member."""
    f = Fabric(["tn1", "tn2", "tn3"], machine="list",
               data_root=str(tmp_path))
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        victim = leader if victim_role == "leader" else \
            [n for n in f.names if n != leader][0]
        acked = []
        for v in range(5):
            assert f.ask(leader, "command", v)[0] == "ok"
            acked.append(v)
        assert f.ask(victim, "kill_wal")[0] == "ok"
        # traffic continues through the crash + supervised restart.
        # A timed-out command may still commit later (the parked leader
        # postpones it), so every ATTEMPT uses a fresh value: acked is
        # then a subset of the final list and per-value no-dup stays a
        # meaningful assertion even across client-side retries.
        deadline = time.monotonic() + 60
        val, oks = 100, 0
        while oks < 7 and time.monotonic() < deadline:
            r = f.ask(leader, "command", val, timeout=30)
            if r[0] == "ok":
                acked.append(val)
                oks += 1
            else:
                leader = f.await_leader()
            val += 1
        assert oks == 7, (oks, r)
        # WAL supervision brought the victim's WAL back
        r = f.ask(victim, "wal_alive")
        assert r == ("ok", True), r
        # replicas converge to one identical list containing every
        # acked value exactly once (timed-out attempts may or may not
        # appear — but never twice)
        f.await_identical_lists(acked, timeout=60)
    finally:
        f.stop()


def test_randomized_fault_schedule_over_tcp(tmp_path):
    """Seeded random schedule over real OS processes and sockets:
    socket-level partitions and heals, WAL crashes, process kill +
    respawn over the durable log, and client commands with unique
    values — every acked value must survive exactly once on every
    member (the partitions_SUITE nemesis shape, randomized)."""
    import random

    rng = random.Random(7)
    f = Fabric(["tn1", "tn2", "tn3"], machine="list",
               data_root=str(tmp_path))
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        acked = []
        val = 0
        # one fault active at a time (the nemesis discipline): a
        # partition PLUS a kill exceeds quorum and makes unavailability
        # legitimate, which is not what this test asserts
        fault = None          # None | ("part", victim) | ("kill", victim)
        for step in range(26):
            roll = rng.random()
            if roll < 0.5:
                val += 1
                r = f.ask(leader, "command", val, timeout=45)
                if r[0] == "ok":
                    acked.append(val)
                else:
                    leader = f.await_leader(timeout=45)
            elif roll < 0.65 and fault is None:
                victim = rng.choice([n for n in f.names if n != leader])
                f.ask(victim, "partition",
                      [n for n in f.names if n != victim])
                for n in f.names:
                    if n != victim:
                        f.ask(n, "partition", [victim])
                fault = ("part", victim)
            elif roll < 0.8 and fault is not None:
                kind, victim = fault
                if kind == "part":
                    for n in f.names:
                        f.ask(n, "heal")
                else:
                    f.respawn(victim)
                fault = None
            elif roll < 0.9 and fault is None:
                victim = rng.choice(f.names)
                f.workers[victim].terminate()
                f.workers[victim].join(timeout=10)
                fault = ("kill", victim)
                if victim == leader:
                    leader = f.await_leader(timeout=45)
            elif fault is None:
                f.ask(leader, "kill_wal", timeout=45)
        if fault is not None:
            kind, victim = fault
            if kind == "kill":
                f.respawn(victim)
        for n in f.names:
            f.ask(n, "heal")
        # converge: identical lists everywhere, every ack exactly once
        f.await_identical_lists(acked, timeout=90)
    finally:
        f.stop()


def test_node_restart_over_tcp(tmp_path):
    """Stop a member's whole OS process, restart it over its durable
    log directory: it recovers its state and rejoins the cluster
    (coordination_SUITE restart flow over sockets)."""
    f = Fabric(["tn1", "tn2", "tn3"], data_root=str(tmp_path))
    try:
        f.ask("tn1", "elect")
        leader = f.await_leader()
        for v in (10, 20, 30):
            assert f.ask(leader, "command", v)[0] == "ok"
        f.await_converged(60)
        victim = [n for n in f.names if n != leader][0]
        f.ask(victim, "stop")
        time.sleep(0.3)
        if f.workers[victim].is_alive():
            f.workers[victim].terminate()
        # majority continues
        assert f.ask(leader, "command", 5, timeout=30)[0] == "ok"
        # restart the process over the same data dir
        f.respawn(victim)
        states = f.await_converged(65, timeout=40)
        assert states[victim][2] == 65
    finally:
        f.stop()


@pytest.mark.parametrize("rep", [1, 2, 3])
def test_cross_node_lifecycle_control_plane(tmp_path, rep):
    """The ra_server_sup_sup role over the fabric
    (/root/reference/src/ra_server_sup_sup.erl:42-130): a client with NO
    local members brings up a 3-node cluster in ONE start_cluster call
    (machine specs resolve on each target node), then remotely stops,
    restarts — including a restart that recovers config + machine from
    the target node's DISK after a full process kill (recover_config) —
    and force-deletes members over the control plane.

    Runs 3x consecutively (ISSUE 2 acceptance): the kill-respawn-restart
    step used to lose the one-shot control RPC into the dead peer's
    cached socket reproducibly under full-suite load; three green
    repeats prove the reliable RPC layer's retry/reconnect path rather
    than a lucky race."""
    import ra_tpu
    from ra_tpu.core.types import ServerId
    from ra_tpu.machines import machine_spec
    from ra_tpu.transport.tcp import TcpRouter

    names = ["cp1", "cp2", "cp3"]
    # every worker is an "extra member": it hosts a RaNode + RaSystem but
    # starts NO server — the control plane does that remotely
    f = Fabric(names, data_root=str(tmp_path), extra_members=tuple(names))
    client = None
    try:
        client = TcpRouter(("127.0.0.1", 0),
                           {n: ("127.0.0.1", f.ports[n]) for n in names})
        assert ra_tpu.node_call("cp1", "ping", {}, router=client) == \
            ("pong", "cp1")
        sids = [ServerId(f"m_{n}", n) for n in names]
        started = ra_tpu.start_cluster(
            "ctl", machine_spec("tcpw", kind="counter"), sids,
            router=client, election_timeout_ms=500, tick_interval_ms=200)
        assert started == sids
        # double-start is refused like the reference's not_new/
        # already_started
        with pytest.raises(RuntimeError, match="already_started"):
            ra_tpu.start_server("ctl", machine_spec("tcpw", kind="counter"),
                                sids[0], sids, router=client)
        res = None
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                res = ra_tpu.process_command(sids[0], 5, router=client,
                                             timeout=10.0)
                break
            except (TimeoutError, RuntimeError):
                ra_tpu.trigger_election(sids[0], router=client)
        assert res is not None and res.leader is not None
        leader = res.leader
        r = ra_tpu.process_command(leader, 3, router=client, timeout=30.0)
        assert r.reply == 8
        # reply_from over real sockets: the rcall handle survives
        # replication and the NAMED member answers (reply_from option,
        # ra.erl:786-823)
        fol0 = next(s for s in sids if s != leader)
        r = ra_tpu.process_command(leader, 2, router=client, timeout=30.0,
                                   reply_from=("member", fol0))
        assert r.reply == 10
        # remote graceful stop of a follower
        follower = next(s for s in sids if s != leader)
        ra_tpu.stop_server(follower, router=client)
        assert f.ask(follower.node, "state")[1] == "noproc"
        # a STOPPED member with durable state refuses a fresh start
        # (the reference's not_new): recreating it under a new uid
        # would orphan its log and rejoin it with amnesia
        with pytest.raises(RuntimeError, match="not_new"):
            ra_tpu.start_server("ctl", machine_spec("tcpw", kind="counter"),
                                follower, sids, router=client)
        assert ra_tpu.process_command(leader, 10, router=client,
                                      timeout=30.0).reply == 20
        # kill the follower's whole OS process, respawn it with no
        # member, then control-plane restart: config AND machine recover
        # from the target node's persisted snapshot (recover_config)
        f.workers[follower.node].terminate()
        f.workers[follower.node].join(timeout=15)
        f.respawn(follower.node)
        restarted = ra_tpu.restart_server(follower, router=client)
        assert tuple(restarted) == tuple(follower)
        deadline = time.monotonic() + 60
        state = None
        while time.monotonic() < deadline:
            state = f.ask(follower.node, "state")
            if state[1] in ("follower", "leader") and state[2] == 20:
                break
            time.sleep(0.4)
        assert state is not None and state[2] == 20, state
        # remote force-delete wipes the member + its durable footprint
        ra_tpu.force_delete_server(follower, router=client)
        assert f.ask(follower.node, "state")[1] == "noproc"
        member_dirs = [d for d in os.listdir(
            os.path.join(str(tmp_path), follower.node))
            if d.startswith("m_")]
        assert member_dirs == [], member_dirs
        # a deleted member cannot be disk-restarted any more
        with pytest.raises(RuntimeError, match="not_found"):
            ra_tpu.restart_server(follower, router=client)
    finally:
        if client is not None:
            client.stop()
        f.stop()
