"""machine_ets — node-owned side tables survive member restarts (the
ra_machine_ets role, ra_machine_ets.erl:28-33 / ra_sup.erl:33-35)."""
import ra_tpu
from ra_tpu import machine_ets
from ra_tpu.core.machine import Machine
from ra_tpu.core.types import ServerId
from ra_tpu.node import LocalRouter, RaNode

from nemesis import await_leader


class IndexingMachine(Machine):
    """Counts applies into a node-owned side table (the pattern the
    reference service exists for: machine-maintained indexes that
    outlive the server process)."""

    def init(self, config):
        machine_ets.create_table("idx_table")
        return 0

    def apply(self, meta, command, state):
        tab = machine_ets.create_table("idx_table")
        tab[meta.index] = command
        return state + 1, state + 1


def test_registry_is_idempotent_and_deletable():
    t1 = machine_ets.create_table("t_reg")
    t1["k"] = 1
    assert machine_ets.create_table("t_reg") is t1
    assert "t_reg" in machine_ets.which_tables()
    machine_ets.delete_table("t_reg")
    assert "t_reg" not in machine_ets.which_tables()
    machine_ets.delete_table("t_reg")  # no-op


def test_uid_scoped_tables_do_not_alias_across_clusters():
    """Two co-hosted clusters picking the same table NAME get distinct
    tables when scoped by server uid; the bare name stays the shared
    process-global table (compatibility shim)."""
    machine_ets.delete_table("dup_name")
    try:
        a = machine_ets.create_table("dup_name", scope="uid_a")
        b = machine_ets.create_table("dup_name", scope="uid_b")
        shared = machine_ets.create_table("dup_name")
        assert a is not b and a is not shared
        a["k"] = "from_a"
        assert "k" not in b and "k" not in shared
        # idempotent per scope
        assert machine_ets.create_table("dup_name", scope="uid_a") is a
        assert machine_ets.which_tables("uid_a") == ("dup_name",)
        # drop_scope wipes ONLY that scope (the force-delete footprint)
        machine_ets.drop_scope("uid_a")
        assert machine_ets.which_tables("uid_a") == ()
        assert machine_ets.create_table("dup_name", scope="uid_b") is b
    finally:
        machine_ets.delete_table("dup_name")
        machine_ets.drop_scope("uid_a")
        machine_ets.drop_scope("uid_b")


def test_force_delete_drops_uid_scoped_tables():
    """force_delete_server wipes the member's uid-scoped side tables
    with the rest of its footprint; plain stop does not."""

    class ScopedMachine(Machine):
        def init(self, config):
            self._uid = config["uid"]
            machine_ets.create_table("scoped_idx", scope=self._uid)
            return 0

        def apply(self, meta, command, state):
            tab = machine_ets.create_table("scoped_idx",
                                           scope=self._uid)
            tab[meta.index] = command
            return state + 1, state + 1

    router = LocalRouter()
    sids = [ServerId(f"sc{i}", f"scn{i}") for i in (1, 2, 3)]
    nodes = {s.node: RaNode(s.node, router=router) for s in sids}
    try:
        ra_tpu.start_cluster("ets2", ScopedMachine, sids, router=router,
                             election_timeout_ms=300, tick_interval_ms=50)
        leader = await_leader(router, sids)
        for i in range(3):
            ra_tpu.process_command(leader, f"v{i}", router=router)
        victim = next(s for s in sids if s != leader)
        uid = nodes[victim.node].shells[victim.name].server.cfg.uid
        assert machine_ets.which_tables(uid) == ("scoped_idx",)
        # graceful stop keeps the table (the service's whole point)
        ra_tpu.stop_server(victim, router=router)
        assert machine_ets.which_tables(uid) == ("scoped_idx",)
        ra_tpu.restart_server(victim, router=router)
        # force-delete wipes it
        ra_tpu.force_delete_server(victim, router=router)
        assert machine_ets.which_tables(uid) == ()
    finally:
        for n in nodes.values():
            n.stop()


def test_side_table_survives_member_restart():
    machine_ets.delete_table("idx_table")
    router = LocalRouter()
    sids = [ServerId(f"e{i}", f"en{i}") for i in (1, 2, 3)]
    nodes = {s.node: RaNode(s.node, router=router) for s in sids}
    try:
        ra_tpu.start_cluster("ets", IndexingMachine, sids, router=router,
                             election_timeout_ms=300, tick_interval_ms=50)
        leader = await_leader(router, sids)
        for i in range(5):
            ra_tpu.process_command(leader, f"c{i}", router=router)
        tab = machine_ets.create_table("idx_table")
        n_before = len(tab)
        assert n_before >= 5  # every member's apply writes the table
        # kill + restart one member: the node-owned table is untouched
        victim = next(s for s in sids if s != leader)
        ra_tpu.stop_server(victim, router=router)
        assert len(machine_ets.create_table("idx_table")) == n_before
        ra_tpu.restart_server(victim, router=router)
        ra_tpu.process_command(leader, "after", router=router)
        assert len(machine_ets.create_table("idx_table")) > n_before
    finally:
        for n in nodes.values():
            n.stop()
        machine_ets.delete_table("idx_table")
