"""Pallas quorum kernel vs the jnp oracle (interpreter mode off-TPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ra_tpu.ops.pallas_quorum import (evaluate_quorum_pallas,
                                      make_evaluate_quorum)
from ra_tpu.ops.quorum import evaluate_quorum

INTERPRET = jax.default_backend() not in ("tpu", "axon")


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("n,p", [(64, 3), (200, 5), (1024, 7), (513, 2)])
def test_pallas_matches_oracle(seed, n, p):
    rng = np.random.default_rng(seed)
    commit = jnp.asarray(rng.integers(0, 50, size=(n,)), jnp.int32)
    match = jnp.asarray(rng.integers(0, 100, size=(n, p)), jnp.int32)
    voter = jnp.asarray(rng.random((n, p)) < 0.8)
    # guarantee at least one voter per lane (lanes without voters are
    # padding in practice)
    voter = voter.at[:, 0].set(True)
    tstart = jnp.asarray(rng.integers(0, 80, size=(n,)), jnp.int32)
    want = evaluate_quorum(commit, match, voter, tstart)
    got = evaluate_quorum_pallas(commit, match, voter, tstart,
                                 interpret=INTERPRET)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_quorum_properties():
    """Commit never regresses; never advances past the agreed median;
    respects the term gate."""
    rng = np.random.default_rng(7)
    n, p = 256, 5
    commit = jnp.asarray(rng.integers(0, 40, size=(n,)), jnp.int32)
    match = jnp.asarray(rng.integers(0, 90, size=(n, p)), jnp.int32)
    voter = jnp.ones((n, p), bool)
    tstart = jnp.asarray(rng.integers(0, 90, size=(n,)), jnp.int32)
    out = np.asarray(evaluate_quorum_pallas(commit, match, voter, tstart,
                                            interpret=INTERPRET))
    commit_np = np.asarray(commit)
    match_np = np.asarray(match)
    tstart_np = np.asarray(tstart)
    assert (out >= commit_np).all()
    med = np.sort(match_np, axis=1)[:, (p - 1) // 2]  # trunc(5/2)+1-th desc
    advanced = out > commit_np
    assert (out[advanced] == med[advanced]).all()
    assert (out[advanced] >= tstart_np[advanced]).all()
    # gate holds: where the median is below term_start, no advance
    blocked = (med > commit_np) & (med < tstart_np)
    assert (out[blocked] == commit_np[blocked]).all()


def test_make_evaluate_quorum_resolution():
    fn = make_evaluate_quorum("xla")
    assert fn is not None
    fn2 = make_evaluate_quorum("auto")
    commit = jnp.zeros((8,), jnp.int32)
    match = jnp.ones((8, 3), jnp.int32)
    voter = jnp.ones((8, 3), bool)
    tstart = jnp.ones((8,), jnp.int32)
    np.testing.assert_array_equal(np.asarray(fn(commit, match, voter,
                                                tstart)),
                                  np.ones(8, np.int32))
    if jax.default_backend() not in ("tpu", "axon"):
        # auto resolves to the xla path off-TPU and must agree
        np.testing.assert_array_equal(
            np.asarray(fn2(commit, match, voter, tstart)),
            np.ones(8, np.int32))


def test_auto_resolves_to_xla_unless_env_gated(monkeypatch):
    """The kernel is a demoted experiment (VERDICT weak #5: 101.4M vs
    112.4M cmds/s on the same config): 'auto' resolves to the XLA
    oracle on EVERY backend unless RA_TPU_ENABLE_PALLAS_QUORUM opts
    back in."""
    from ra_tpu.ops.quorum import evaluate_quorum as xla_impl

    monkeypatch.delenv("RA_TPU_ENABLE_PALLAS_QUORUM", raising=False)
    assert make_evaluate_quorum("auto") is xla_impl
    monkeypatch.setenv("RA_TPU_ENABLE_PALLAS_QUORUM", "0")
    assert make_evaluate_quorum("auto") is xla_impl
    monkeypatch.setenv("RA_TPU_ENABLE_PALLAS_QUORUM", "1")
    fn = make_evaluate_quorum("auto")
    if jax.default_backend() in ("tpu", "axon"):
        assert fn is not xla_impl     # env gate re-enables the kernel
    else:
        assert fn is xla_impl         # off-TPU auto stays on the oracle
    # an explicit 'pallas' choice always wins, gate or no gate
    monkeypatch.delenv("RA_TPU_ENABLE_PALLAS_QUORUM", raising=False)
    assert make_evaluate_quorum("pallas") is not xla_impl
