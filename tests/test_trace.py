"""Tracing/profiling hooks (SURVEY §5 tracing row): span recording,
Chrome trace-event export, summary rollups, the swappable process-wide
tracer, and the instrumentation sites in the engine and WAL.
"""
import json
import threading
import time

import jax.numpy as jnp

from ra_tpu import trace
from ra_tpu.trace import Tracer


def test_span_and_instant_recorded():
    t = Tracer()
    with t.span("op", "cat", k=1):
        time.sleep(0.002)
    t.instant("mark")
    t.counter("queue_depth", depth=3)
    evts = t.events()
    phases = {e["ph"] for e in evts}
    assert phases == {"X", "i", "C"}
    sp = next(e for e in evts if e["ph"] == "X")
    assert sp["name"] == "op" and sp["dur"] >= 1000  # >= 1ms in us
    assert sp["args"] == {"k": 1}


def test_dump_chrome_trace_is_loadable_json(tmp_path):
    t = Tracer()
    for i in range(5):
        with t.span(f"s{i}"):
            pass
    path = t.dump_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == 5
    assert all("ts" in e and "pid" in e for e in doc["traceEvents"])


def test_ring_capacity_keeps_newest():
    t = Tracer(capacity=10)
    for i in range(25):
        with t.span(f"s{i}"):
            pass
    evts = t.events()
    assert len(evts) == 10
    names = [e["name"] for e in evts]
    assert names == [f"s{i}" for i in range(15, 25)]


def test_summary_rollup():
    t = Tracer()
    for _ in range(3):
        with t.span("hot"):
            pass
    with t.span("cold"):
        pass
    s = t.summary()
    assert s["hot"]["count"] == 3
    assert s["cold"]["count"] == 1
    assert s["hot"]["total_us"] >= s["hot"]["max_us"]


def test_global_tracer_disabled_by_default():
    assert trace.get_tracer() is None
    with trace.span("noop"):
        pass  # must not raise, must not record anywhere
    trace.instant("noop2")


def test_threads_get_distinct_tids():
    t = Tracer()

    def work():
        with t.span("w"):
            pass

    th = threading.Thread(target=work)
    th.start()
    th.join()
    with t.span("main"):
        pass
    tids = {e["tid"] for e in t.events()}
    assert len(tids) == 2


def test_engine_step_instrumented():
    from ra_tpu.engine import LockstepEngine
    from ra_tpu.models import CounterMachine

    t = Tracer()
    trace.set_tracer(t)
    try:
        eng = LockstepEngine(CounterMachine(), 4, 3, ring_capacity=64,
                             max_step_cmds=4, donate=False)
        for _ in range(3):
            eng.step(jnp.full((4,), 2, jnp.int32),
                     jnp.ones((4, 4, 1), jnp.int32))
        eng.block_until_ready()
    finally:
        trace.set_tracer(None)
    s = t.summary()
    assert s.get("engine.step", {}).get("count") == 3


def test_wal_batch_instrumented(tmp_path):
    from ra_tpu.core.types import Entry, UserCommand

    from test_durable_log import drain, mk_log, mk_system

    t = Tracer()
    trace.set_tracer(t)
    try:
        sys_ = mk_system(tmp_path)
        log = mk_log(sys_)
        for i in range(1, 21):
            log.append(Entry(i, 1, UserCommand(i)))
        drain(log)
        sys_.close()
    finally:
        trace.set_tracer(None)
    s = t.summary()
    assert s.get("wal.batch", {}).get("count", 0) >= 1


def test_ring_wrap_preserves_order_and_reports_drops():
    """Satellite (ISSUE 6): after the ring wraps, events() stays in
    oldest->newest order across the wrap seam and the tracer reports
    how many events were overwritten — a truncated trace must not be
    mistaken for a complete one."""
    t = Tracer(capacity=8)
    assert not t.wrapped and t.dropped_events == 0
    for i in range(20):
        t.instant(f"e{i}")
    evts = t.events()
    assert [e["name"] for e in evts] == [f"e{i}" for i in range(12, 20)]
    ts = [e["ts"] for e in evts]
    assert ts == sorted(ts)  # monotone across the seam
    assert t.wrapped and t.dropped_events == 12
    # keep recording after the wrap: the ring keeps sliding
    t.instant("late")
    assert t.events()[-1]["name"] == "late"
    assert t.dropped_events == 13


def test_summary_carries_wrapped_indicator():
    t = Tracer(capacity=4)
    for i in range(3):
        with t.span("a"):
            pass
    s = t.summary()
    assert s["_meta"] == {"wrapped": False, "dropped_events": 0}
    assert s["a"]["count"] == 3
    for _ in range(6):
        with t.span("b"):
            pass
    s = t.summary()
    assert s["_meta"]["wrapped"] is True
    assert s["_meta"]["dropped_events"] == 5
    # post-wrap counts cover only the surviving window — the indicator
    # is what stops them being read as totals
    assert s["b"]["count"] == 4 and "a" not in s


# -- causal trace context (ISSUE 7) -----------------------------------------

def test_trace_ctx_is_deterministic_under_set_origin():
    trace.set_trace_origin("seeded")
    a = [trace.new_trace_ctx() for _ in range(3)]
    trace.set_trace_origin("seeded")
    b = [trace.new_trace_ctx() for _ in range(3)]
    assert a == b == ["seeded-1", "seeded-2", "seeded-3"]
    assert trace.new_trace_ctx("other") == "other-4"


def test_trace_ctx_default_origin_is_process_scoped():
    import os

    trace.set_trace_origin(f"p{os.getpid()}")
    ctx = trace.new_trace_ctx()
    assert ctx.startswith(f"p{os.getpid()}-")
