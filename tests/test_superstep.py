"""Superstep parity tests (ISSUE 5): a K-round fused dispatch
(`LockstepEngine.superstep`, lax.scan over the step body) must be
ORACLE-EXACT against K single steps — same LaneState bit for bit — for
every machine flavour (batch-apply counter/kv AND the sequential-window
fifo), including mid-superstep election masks, member failures and ring
backpressure.  Durable-mode behaviour (confirm hold-back, kill-9
recovery of a superstep-driven run) lives in test_engine_durable.py /
test_wal_shards.py; this file pins the pure state-transition algebra.

Also the soak entry point: ``run_superstep_fuzz`` explores fresh random
schedules (tools/soak.py --superstep).
"""
import numpy as np
import pytest

from ra_tpu.engine import DispatchAheadDriver, LockstepEngine
from ra_tpu.models import CounterMachine, JitFifoMachine, JitKvMachine

N, P, KC = 8, 3, 4  # lanes, members, max cmds/step


def _machine(name):
    if name == "jit_kv":
        return JitKvMachine(n_keys=16)
    if name == "jit_fifo":
        return JitFifoMachine(capacity=16, checkout_slots=4)
    return CounterMachine()


def _payloads(name, rng, k):
    """Random valid [k, N, KC, C] command blocks for the machine."""
    if name == "jit_kv":
        p = np.zeros((k, N, KC, 4), np.int32)
        p[..., 0] = rng.integers(1, 5, (k, N, KC))     # put/get/del/cas
        p[..., 1] = rng.integers(0, 16, (k, N, KC))    # key
        p[..., 2] = rng.integers(0, 100, (k, N, KC))   # value
        p[..., 3] = rng.integers(-1, 5, (k, N, KC))    # cas expected
        return p
    if name == "jit_fifo":
        p = np.zeros((k, N, KC, 3), np.int32)
        p[..., 0] = rng.integers(1, 3, (k, N, KC))     # enqueue/dequeue
        p[..., 1] = rng.integers(1, 9, (k, N, KC))
        return p
    return rng.integers(1, 9, (k, N, KC, 1)).astype(np.int32)


def _mk(name, **kw):
    kw.setdefault("ring_capacity", 64)
    kw.setdefault("max_step_cmds", KC)
    kw.setdefault("write_delay", 1)
    return LockstepEngine(_machine(name), N, P, **kw)


def _assert_state_equal(a, b, ctx=""):
    for f in a.state._fields:
        if f == "mac":
            continue
        xa, xb = np.asarray(getattr(a.state, f)), \
            np.asarray(getattr(b.state, f))
        np.testing.assert_array_equal(xa, xb, err_msg=f"{ctx}: {f}")
    import jax
    for pa, pb in zip(jax.tree.leaves(a.state.mac),
                      jax.tree.leaves(b.state.mac)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb),
                                      err_msg=f"{ctx}: mac")


@pytest.mark.parametrize("machine_name", ["counter", "jit_kv", "jit_fifo"])
@pytest.mark.parametrize("k", [1, 2, 8])
def test_superstep_oracle_exact(machine_name, k):
    """K fused rounds == K single rounds, bit for bit, through normal
    traffic, a member failure and a mid-superstep election (the elect
    schedule fires at an INNER step, so candidate selection, the
    term-opening noop and the same-round follower clamp all run inside
    the scan)."""
    a = _mk(machine_name)
    b = _mk(machine_name)
    rng = np.random.default_rng(100 + k)
    for rnd in range(3):
        n_new = rng.integers(0, KC + 1, (k, N)).astype(np.int32)
        pay = _payloads(machine_name, rng, k)
        elect = np.zeros((k, N), bool)
        if rnd == 1:
            # fail lane 2's leader, then request the election at a
            # mid-superstep inner index
            leader = int(np.asarray(a.state.leader_slot)[2])
            a.fail_member(2, leader)
            b.fail_member(2, leader)
            elect[min(1, k - 1), 2] = True
        for j in range(k):
            a.step(n_new[j], pay[j], elect_mask=elect[j])
        b.superstep(n_new, pay, elect_blk=elect)
        _assert_state_equal(a, b, f"{machine_name} k={k} round={rnd}")


def test_superstep_aux_watermarks_are_per_inner_step():
    """The stacked aux carries the cumulative committed and applied
    watermarks after EACH inner step — monotone, ending exactly at the
    engine's final state (what the dispatch-ahead driver and the bench
    latency stamping read)."""
    eng = _mk("counter")
    rng = np.random.default_rng(0)
    eng.superstep(np.full((4, N), 2, np.int32),
                  _payloads("counter", rng, 4))
    aux = eng.uniform_superstep(4, 2)
    com = np.asarray(aux["committed_lanes"]).astype(np.int64)
    app = np.asarray(aux["applied_lanes"]).astype(np.int64)
    assert com.shape == (4, N) and app.shape == (4, N)
    assert (np.diff(com, axis=0) >= 0).all()
    assert (np.diff(app, axis=0) >= 0).all()
    np.testing.assert_array_equal(
        com[-1], np.asarray(eng.state.total_committed))


def test_superstep_ring_backpressure_parity():
    """Bursts beyond ring headroom inside the fused loop clip exactly
    like the single-step path (n_acc per inner step)."""
    a = _mk("counter", ring_capacity=16, max_step_cmds=8,
            apply_window=4)
    b = _mk("counter", ring_capacity=16, max_step_cmds=8,
            apply_window=4)
    rng = np.random.default_rng(7)
    for _ in range(4):
        n_new = np.full((4, N), 8, np.int32)
        pay = rng.integers(1, 5, (4, N, 8, 1)).astype(np.int32)
        for j in range(4):
            a.step(n_new[j], pay[j])
        b.superstep(n_new, pay)
        _assert_state_equal(a, b, "backpressure")


def test_dispatch_ahead_driver_matches_plain_supersteps():
    """The staging driver is a pure pipelining layer: the final engine
    state equals driving the same blocks through superstep() directly,
    and its in-flight cap is honoured."""
    a = _mk("counter")
    b = _mk("counter")
    rng = np.random.default_rng(3)
    blocks = [(np.full((4, N), 2, np.int32), _payloads("counter", rng, 4))
              for _ in range(6)]
    for nb, pb in blocks:
        a.superstep(nb, pb)
    drv = DispatchAheadDriver(b, max_in_flight=2)
    for nb, pb in blocks:
        drv.submit(nb, pb)
        assert drv.in_flight() <= 2
    final = drv.drain()
    _assert_state_equal(a, b, "driver")
    np.testing.assert_array_equal(final,
                                  np.asarray(b.state.total_committed))
    assert b.pipeline_counters["superstep_dispatches"] == 6
    assert b.pipeline_counters["inner_steps"] == 24
    assert b.overview(0)["pipeline"]["dispatch_ahead"] == 2


def test_driver_stages_blocks_under_mesh_shardings():
    """A sharded engine + a driver built with
    superstep_block_shardings: staged n_new/payloads land lane-sharded
    over the mesh (no resharding copy at dispatch) and the fused run
    stays parity-exact with an unsharded engine.  conftest forces 8
    host devices, so the mesh is real."""
    import jax
    from ra_tpu.parallel.mesh import (shard_engine_state,
                                      superstep_block_shardings)
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend")
    a = _mk("counter")
    b = _mk("counter")
    mesh = shard_engine_state(b)
    sh = superstep_block_shardings(mesh)
    # elect is host data; the read block shards with the write block
    # (ISSUE 20)
    assert set(sh) == {"n_new", "payloads", "query", "n_read", "read_q"}
    drv = DispatchAheadDriver(b, max_in_flight=2, shardings=sh)
    rng = np.random.default_rng(23)
    blocks = [(np.full((4, N), 2, np.int32),
               _payloads("counter", rng, 4)) for _ in range(4)]
    for nb, pb in blocks:
        a.superstep(nb, pb)
        drv.submit(nb, pb)
    assert drv._staged is not None
    for arr, key in ((drv._staged[0], "n_new"),
                     (drv._staged[1], "payloads")):
        assert arr.sharding.is_equivalent_to(sh[key], arr.ndim), key
    drv.drain()
    _assert_state_equal(a, b, "mesh driver")
    assert b.pipeline_counters["blocks_staged"] == 4


def test_window_syncs_count_only_real_waits():
    """window_syncs backs the 'window_syncs << dispatches' health rule,
    so a readback that was already ready when harvested must NOT count:
    on this backend the tiny dispatches complete long before the host
    loops back, so a healthy dispatch-ahead run reports (near-)zero
    syncs while dispatches climb."""
    eng = _mk("counter")
    drv = DispatchAheadDriver(eng, max_in_flight=2)
    nb = np.full((4, N), 2, np.int32)
    pb = np.ones((4, N, KC, 1), np.int32)
    import time
    for _ in range(20):
        drv.submit(nb, pb)
        time.sleep(0.002)  # device finishes: harvests find ready handles
    drv.drain()
    pc = eng.pipeline_counters
    assert pc["superstep_dispatches"] == 20
    assert pc["window_syncs"] <= 2, pc


@pytest.mark.parametrize("machine_name", ["counter", "jit_kv"])
@pytest.mark.parametrize("k", [1, 8])
def test_mesh_superstep_parity(machine_name, k):
    """ISSUE 11: the fused superstep over state SHARDED on the 8
    forced-host devices is bit-exact vs the single-device engine on
    identical schedules — including a mid-superstep election (the vote
    round runs inside the scan over sharded state, with the quorum
    math lowering to collectives) and donation ON (the superstep
    default), driven through the mesh dispatch-ahead driver with
    pre-partitioned staged blocks."""
    import jax

    from ra_tpu.parallel.mesh import (mesh_superstep_driver,
                                      shard_engine_state)
    if len(jax.devices()) < 2:
        pytest.skip("single-device backend")
    a = _mk(machine_name)                       # single-device oracle
    b = _mk(machine_name, superstep_donate=True)
    mesh = shard_engine_state(b)
    drv = mesh_superstep_driver(b, mesh, max_in_flight=2)
    rng = np.random.default_rng(300 + k)
    for rnd in range(3):
        n_new = rng.integers(0, KC + 1, (k, N)).astype(np.int32)
        pay = _payloads(machine_name, rng, k)
        elect = np.zeros((k, N), bool)
        if rnd == 1:
            # fail lane 1's leader, request the election at a
            # mid-superstep inner index: candidate selection, the
            # term-opening noop and the same-round follower clamp all
            # run inside the scan on SHARDED state
            leader = int(np.asarray(a.state.leader_slot)[1])
            a.fail_member(1, leader)
            b.fail_member(1, leader)
            elect[min(1, k - 1), 1] = True
        for j in range(k):
            a.step(n_new[j], pay[j], elect_mask=elect[j])
        b.superstep(n_new, pay, elect_blk=elect)
        _assert_state_equal(a, b, f"mesh {machine_name} k={k} r={rnd}")
    # the driver path too: staged blocks land pre-partitioned and the
    # final state still matches the oracle
    for _ in range(3):
        nb = rng.integers(0, KC + 1, (k, N)).astype(np.int32)
        pb = _payloads(machine_name, rng, k)
        for j in range(k):
            a.step(nb[j], pb[j])
        drv.submit(nb, pb)
    drv.drain()
    _assert_state_equal(a, b, f"mesh driver {machine_name} k={k}")


def test_superstep_donation_parity():
    """Donating the state buffer into the fused dispatch (the superstep
    default) changes nothing observable vs donate-off."""
    a = _mk("counter", superstep_donate=False)
    b = _mk("counter", superstep_donate=True)
    rng = np.random.default_rng(11)
    for _ in range(3):
        nb = rng.integers(0, KC + 1, (8, N)).astype(np.int32)
        pb = _payloads("counter", rng, 8)
        a.superstep(nb, pb)
        b.superstep(nb, pb)
        _assert_state_equal(a, b, "donation")


def test_superstep_consistent_read_still_linearizable():
    """consistent_read interleaves with superstep driving: the
    certified state reflects every committed fused round."""
    eng = _mk("counter")
    eng.uniform_superstep(4, 2)
    eng.uniform_superstep(4, 0)  # settle the write-delay confirms
    mac = eng.consistent_read(range(N))
    per_lane = np.asarray(eng.state.total_committed)
    np.testing.assert_array_equal(np.asarray(mac) >= 2 * 4, True)
    assert (np.asarray(mac) <= per_lane * 2).all()


def run_superstep_fuzz(seed, rounds=4):
    """Soak entry (tools/soak.py --superstep): random K/schedules with
    failures + elections, exact-parity checked every round."""
    rng = np.random.default_rng(seed)
    name = ["counter", "jit_kv", "jit_fifo"][seed % 3]
    a = _mk(name)
    b = _mk(name)
    failed: set = set()
    for rnd in range(rounds):
        k = int(rng.choice([1, 2, 4, 8]))
        n_new = rng.integers(0, KC + 1, (k, N)).astype(np.int32)
        pay = _payloads(name, rng, k)
        elect = np.zeros((k, N), bool)
        if rng.random() < 0.5:
            lane = int(rng.integers(0, N))
            leader = int(np.asarray(a.state.leader_slot)[lane])
            if (lane, leader) not in failed and \
                    sum(1 for (ln, _s) in failed if ln == lane) < P // 2:
                a.fail_member(lane, leader)
                b.fail_member(lane, leader)
                failed.add((lane, leader))
                elect[int(rng.integers(0, k)), lane] = True
        for j in range(k):
            a.step(n_new[j], pay[j], elect_mask=elect[j])
        b.superstep(n_new, pay, elect_blk=elect)
        _assert_state_equal(a, b, f"fuzz seed={seed} round={rnd} k={k}")


@pytest.mark.parametrize("seed", [0, 1, 2, 5])
def test_superstep_fuzz_anchor_seeds(seed):
    run_superstep_fuzz(seed)
